"""Replica-axis execution of the TCP dumbbell (BASELINE config #2).

Lowers a dumbbell object graph — N left leaves bulk-sending TCP through
one bottleneck toward N right leaves (tcp-variants-comparison's shape;
SURVEY.md §2.7/§2.9) — to a device-resident **packet-slot** program: one
``lax.scan`` step per bottleneck serialization time τ (= pkt_bytes·8/C),
per-replica per-flow state in (R, F) arrays, all SEVENTEEN
TcpCongestionOps variants (the full upstream family incl. BBR, DCTCP,
H-TCP, YeAH, LEDBAT and TCP-LP) evaluated as masked vector rules in one
fused step.  A RED root
qdisc on the bottleneck lowers too: EWMA average queue, early
drop/CE-mark (RFC 3168 ECE triggers the variant's loss response; DCTCP
scales its cut by the marked fraction), gentle mode, hard-drop forced
region.

The slot model (each deviation documented, mirrored on replicated.py's
timing-model contract):
- the bottleneck serves exactly one packet per slot when backlogged
  (work-conserving FIFO); *which* flow's head departs is drawn with
  probability proportional to per-flow queue occupancy — FIFO in
  expectation, not in exact order.
- the access links are required to be faster than the bottleneck (the
  lowering rejects otherwise); their delay folds into the base RTT and
  their serialization into a per-slot send-burst cap.
- ACKs ride the uncongested reverse path: ack arrival = departure slot
  + base-lag slots; reverse-direction queueing is not modeled.
- loss detection is dupack-timed: a tail-dropped packet triggers one
  window reduction per RTT (NewReno-style recovery window
  ``recover_until``); every lost packet individually leaves the flight
  so the ACK clock never stalls.  RTO timeouts are not modeled (with a
  clocked recovery window they are unreachable for backlogged flows).
- RTT samples (Vegas/Veno) are base_rtt + queue_wait with queue_wait
  approximated by the instantaneous backlog at departure.

The scalar DES (real TcpSocketBase over PointToPointNetDevice) stays
the per-packet oracle; tests assert statistical parity of per-variant
goodput, not per-packet equality.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from tpudes.fuzz.envelope import FuzzEnvelope

# variant ids (order is the vector-rule dispatch table; the full
# upstream tcp-variants-comparison family, tcp_congestion.TCP_VARIANTS)
VARIANTS = ("TcpNewReno", "TcpCubic", "TcpScalable", "TcpHighSpeed",
            "TcpVegas", "TcpVeno", "TcpLinuxReno", "TcpBic", "TcpWestwood",
            "TcpIllinois", "TcpHybla", "TcpBbr", "TcpDctcp", "TcpHtcp",
            "TcpYeah", "TcpLedbat", "TcpLp")
(V_NEWRENO, V_CUBIC, V_SCALABLE, V_HIGHSPEED, V_VEGAS, V_VENO,
 V_LINUXRENO, V_BIC, V_WESTWOOD, V_ILLINOIS, V_HYBLA, V_BBR,
 V_DCTCP, V_HTCP, V_YEAH, V_LEDBAT, V_LP) = range(17)

INIT_CWND = 10.0          # segments (tcp_congestion.TcpSocketState default)
SSTHRESH0 = 1e9
CUBIC_C = 0.4
CUBIC_BETA = 0.7
SCALABLE_AI = 50.0
SCALABLE_MD = 0.125
HS_LOW_WINDOW = 38.0
VEGAS_ALPHA, VEGAS_BETA, VEGAS_GAMMA = 2.0, 4.0, 1.0
VENO_BETA = 3.0
BIC_BETA, BIC_LOW_WND, BIC_MAX_INCR, BIC_SMIN = 0.8, 14.0, 16.0, 0.01
ILL_ALPHA_MAX, ILL_ALPHA_MIN = 10.0, 0.3
ILL_BETA_MAX, ILL_BETA_MIN = 0.5, 0.125
HYBLA_RRTT = 0.025
BBR_HIGH_GAIN = 2.89
BBR_CYCLE_GAINS = (1.25, 0.75, 1.0, 1.0, 1.0, 1.0, 1.0, 1.0)
BBR_STARTUP, BBR_DRAIN, BBR_PROBE_BW = range(3)
BBR_BW_DECAY = 0.98       # per-round decaying-max ≈ the 10-round window
DCTCP_G = 0.0625
HTCP_DELTA_B = 1.0        # s: low-speed regime boundary
HTCP_DEFAULT_BACKOFF = 0.5
YEAH_ALPHA, YEAH_QMAX, YEAH_RHO = 80.0, 8.0, 0.125
LEDBAT_TARGET_S, LEDBAT_GAIN = 0.1, 1.0
LP_INFERENCE_FRAC = 0.15


#: the documented-faithful fuzz region (see :mod:`tpudes.fuzz`): the
#: tcp-variants-comparison dumbbell shape lower_dumbbell accepts —
#: access faster than the bottleneck, packet-mode droptail queue, one
#: SendSize, all flows left→right — across the full 17-variant family
#: ("mixed" assigns variants round-robin from the drawn one)
FUZZ_ENVELOPE = FuzzEnvelope(
    engine="dumbbell",
    axes={
        "n_flows": ("int", 2, 4),
        "variant": ("choice", VARIANTS),
        "variant_mix": ("choice", ("homogeneous", "mixed")),
        "bottleneck_mbps": ("choice", (3, 5, 10)),
        "bottleneck_delay_ms": ("choice", (5, 10, 20)),
        "queue_pkts": ("choice", (25, 50, 100)),
        "seg_bytes": ("choice", (500, 1000)),
        "sim_ms": ("int", 900, 2500),
        "replicas": ("int", 2, 9),
        "chunk_divisor": ("choice", (2, 3)),
        "key_seed": ("int", 0, 2**16),
        # ISSUE-14 traffic draws (appended): app-limited flows from
        # the drawn workload model; "off" keeps the bulk source
        "traffic": ("choice", ("off", "cbr", "mmpp", "onoff", "trace")),
        "tr_burst": ("float", 0.1, 0.6),
        "tr_phase": ("float", 0.0, 1.0),
    },
    # sim_ms floor 8: even at the fastest slot (500 B @ 10 Mbps,
    # 0.432 ms) the shrunk horizon lands under 32 slots
    floors={"replicas": 1, "n_flows": 1, "sim_ms": 8},
    doc="single-bottleneck dumbbell, bulk TCP left→right, 17 variants",
)


@dataclass(frozen=True)
class DumbbellProgram:
    """Static description of one dumbbell scenario on the replica axis."""

    n_flows: int
    variant_idx: np.ndarray      # (F,) index into VARIANTS
    start_slot: np.ndarray       # (F,) first slot each flow may send
    stop_slot: np.ndarray        # (F,) no new packets at/after this slot
    max_pkts: np.ndarray         # (F,) segment budget (INT32_MAX = unlimited)
    slot_s: float                # τ: bottleneck serialization time
    n_slots: int                 # simulation horizon in slots
    ack_lag: int                 # slots from departure to ack arrival
    queue_cap: int               # bottleneck queue capacity (packets)
    burst_cap: int               # per-flow packets enqueueable per slot
    base_rtt_s: float            # unloaded RTT (for Vegas/Veno diff)
    seg_bytes: int               # application payload per packet
    #: (F,) ECN-capable flows (variant REQUIRES_ECN or UseEcn socket
    #: attribute): the AQM marks their packets instead of early-dropping
    ecn: np.ndarray = None
    #: bottleneck AQM: "fifo" (tail drop) or "red"
    qdisc: str = "fifo"
    red_min_th: float = 5.0
    red_max_th: float = 15.0
    red_max_p: float = 0.02      # 1 / LInterm
    red_qw: float = 0.002
    red_gentle: bool = True
    red_use_ecn: bool = False
    red_use_hard_drop: bool = True
    #: device-resident workload (tpudes.traffic.TrafficProgram over the
    #: F flows): None = the legacy bulk source (infinite application
    #: backlog, bit-identical compile).  With a program, each flow is
    #: APP-LIMITED: it may only keep ``delivered + inflight`` below the
    #: workload's cumulative offered segments (closed-form on device),
    #: so bursts and think-times shape the congestion dynamics.  Model
    #: id + params are traced operands — only ``traffic.shape_key()``
    #: enters the runner cache key.
    traffic: object = None

    @property
    def buf_len(self) -> int:
        return self.ack_lag + 2


class UnliftableDumbbellError(ValueError):
    """The object graph is not a dumbbell this lowering can faithfully
    represent; callers fall back to the scalar DES."""


def lower_dumbbell(sim_end_s: float) -> DumbbellProgram:
    """Lower the live object graph (NodeList) to a DumbbellProgram.

    Discovers the bottleneck as the unique p2p link whose BOTH endpoint
    nodes forward (≥3 interfaces, no applications); flows are
    BulkSendApplications on leaf nodes whose sink lives across the
    bottleneck.  Rejects shapes the slot model cannot represent.
    """
    from tpudes.models.applications import BulkSendApplication, PacketSink
    from tpudes.models.internet.ipv4 import Ipv4L3Protocol
    from tpudes.models.internet.tcp import TcpL4Protocol
    from tpudes.models.p2p import PointToPointNetDevice
    from tpudes.network.node import NodeList

    nodes = [NodeList.GetNode(i) for i in range(NodeList.GetNNodes())]

    def n_ifaces(node):
        ipv4 = node.GetObject(Ipv4L3Protocol)
        return len(ipv4.interfaces) - 1 if ipv4 else 0  # minus loopback

    routers = [n for n in nodes if n_ifaces(n) >= 3 and n.GetNApplications() == 0]
    router_ids = {id(n) for n in routers}
    candidates = []
    for n in routers:
        for d in range(n.GetNDevices()):
            dev = n.GetDevice(d)
            if not isinstance(dev, PointToPointNetDevice):
                continue
            ch = dev.GetChannel()
            peer = ch.GetPeer(dev)
            if id(peer.GetNode()) in router_ids and peer.GetNode() is not n:
                candidates.append((dev, peer, ch))
    # each link appears once from each endpoint; a true dumbbell has
    # exactly one router-router link
    links = {id(c[2]) for c in candidates}
    if not candidates:
        raise UnliftableDumbbellError("no router-router bottleneck link found")
    if len(links) > 1:
        raise UnliftableDumbbellError(
            f"{len(links)} router-router links (multi-path topology); the "
            "slot model represents exactly one bottleneck"
        )
    bdev, bpeer, bchan = candidates[0]
    left_router, right_router = bdev.GetNode(), bpeer.GetNode()
    bn_rate = float(bdev.data_rate.GetBitRate())
    bn_delay_s = bchan.GetDelay().GetSeconds()
    qs = bdev.GetQueue().max_size
    if qs.mode != qs.PACKETS:
        raise UnliftableDumbbellError(
            "slot model counts queue capacity in packets (byte-mode queue)"
        )
    queue_cap = int(qs.value)

    # sinks by (address, port) so each bulk app can be paired; any app
    # kind the slot model does not represent is cross-traffic that would
    # silently vanish from the shared queue — reject, don't drop
    sinks = {}
    for node in nodes:
        for a in range(node.GetNApplications()):
            app = node.GetApplication(a)
            if not isinstance(app, (BulkSendApplication, PacketSink)):
                raise UnliftableDumbbellError(
                    f"unmodeled application {type(app).__name__} on node "
                    f"{node.GetId()} (cross-traffic would be dropped)"
                )
            if isinstance(app, PacketSink):
                port = app.local.GetPort()
                ipv4 = node.GetObject(Ipv4L3Protocol)
                for iface in ipv4.interfaces[1:]:
                    for addr in iface.addresses:
                        sinks[(addr.GetLocal().addr, port)] = node

    def access_router(leaf):
        """The router a leaf's single access link attaches to."""
        acc = leaf.GetDevice(0)
        if not isinstance(acc, PointToPointNetDevice):
            raise UnliftableDumbbellError("leaf access link is not p2p")
        return acc.GetChannel().GetPeer(acc).GetNode()

    flows, variants, starts, stops, budgets, ecns = [], [], [], [], [], []
    seg_sizes, access_rates, access_delays = set(), set(), []
    directions: set[bool] = set()
    for node in nodes:
        for a in range(node.GetNApplications()):
            app = node.GetApplication(a)
            if not isinstance(app, BulkSendApplication):
                continue
            dst = app.remote  # InetSocketAddress
            sink_node = sinks.get((dst.GetIpv4().addr, dst.GetPort()))
            if sink_node is None:
                raise UnliftableDumbbellError(
                    f"bulk sender on node {node.GetId()} has no matching sink"
                )
            if n_ifaces(node) != 1 or n_ifaces(sink_node) != 1:
                raise UnliftableDumbbellError(
                    "bulk flows must run leaf-to-leaf (one access interface)"
                )
            # every flow must cross the bottleneck, all in the SAME
            # direction: a same-side flow never touches the modeled
            # queue, and opposing flows queue on the two different link
            # directions — both would be silent mis-lowerings
            src_r, dst_r = access_router(node), access_router(sink_node)
            if {src_r, dst_r} != {left_router, right_router}:
                raise UnliftableDumbbellError(
                    f"flow node{node.GetId()}→node{sink_node.GetId()} does "
                    "not cross the bottleneck; the slot model represents "
                    "one shared queue"
                )
            directions.add(src_r is left_router)
            acc = node.GetDevice(0)
            access_rates.add(float(acc.data_rate.GetBitRate()))
            access_delays.append(acc.GetChannel().GetDelay().GetSeconds())
            sink_acc = sink_node.GetDevice(0)
            access_delays.append(sink_acc.GetChannel().GetDelay().GetSeconds())
            tcp = node.GetObject(TcpL4Protocol)
            vname = tcp.GetAttribute("SocketType") if tcp else "TcpNewReno"
            if vname not in VARIANTS:
                raise UnliftableDumbbellError(f"unknown TCP variant {vname}")
            seg_sizes.add(int(app.send_size))
            flows.append(app)
            from tpudes.models.internet.tcp_congestion import TCP_VARIANTS

            ecns.append(
                bool(getattr(tcp, "use_ecn", False))
                or bool(getattr(TCP_VARIANTS[vname], "REQUIRES_ECN", False))
            )
            variants.append(VARIANTS.index(vname))
            starts.append(app.start_time.GetSeconds())
            stops.append(
                app.stop_time.GetSeconds()
                if app.stop_time.GetTimeStep() > 0
                else sim_end_s
            )
            budgets.append(int(app.max_bytes) if app.max_bytes else 0)
    if not flows:
        raise UnliftableDumbbellError("no TCP bulk flows found")
    if len(directions) > 1:
        raise UnliftableDumbbellError(
            "flows cross the bottleneck in both directions; the slot "
            "model represents one direction of one shared queue"
        )
    if len(seg_sizes) > 1:
        raise UnliftableDumbbellError(
            f"flows must share one SendSize — the slot is one on-wire "
            f"packet time (got {sorted(seg_sizes)})"
        )
    if len(access_rates) != 1:
        raise UnliftableDumbbellError(
            f"access links must share one rate (got {sorted(access_rates)})"
        )
    access_rate = access_rates.pop()
    if access_rate <= bn_rate:
        raise UnliftableDumbbellError(
            "access links must be faster than the bottleneck for the "
            "slot model (queueing would form at the leaves)"
        )
    seg = max(seg_sizes) if seg_sizes else 536
    pkt_bits = (seg + 40) * 8  # +IPv4/TCP headers on the wire
    slot_s = pkt_bits / bn_rate
    acc_d = float(np.mean(access_delays)) if access_delays else 0.0
    # after leaving the queue: prop + far access (data), then the ack's
    # reverse trip (access + bottleneck prop + access)
    ack_lag_s = 2.0 * bn_delay_s + 4.0 * acc_d
    base_rtt_s = ack_lag_s + slot_s

    # --- bottleneck AQM (traffic-control root qdisc on the tx device
    # of the modeled direction) -----------------------------------------
    from tpudes.models.traffic_control import (
        FifoQueueDisc,
        RedQueueDisc,
        TrafficControlLayer,
    )

    src_is_left = directions.pop()
    tx_dev = bdev if src_is_left else bpeer
    tcl = tx_dev.GetNode().GetObject(TrafficControlLayer)
    qd = tcl.GetRootQueueDisc(tx_dev) if tcl is not None else None
    qdisc_kind, red_kw = "fifo", {}
    if isinstance(qd, RedQueueDisc):
        qdisc_kind = "red"
        queue_cap = int(qd.max_packets)
        red_kw = dict(
            red_min_th=float(qd.min_th),
            red_max_th=float(qd.max_th),
            red_max_p=1.0 / float(qd.l_interm),
            red_qw=float(qd.qw),
            red_gentle=bool(qd.gentle),
            red_use_ecn=bool(qd.use_ecn),
            red_use_hard_drop=bool(qd.use_hard_drop),
        )
    elif isinstance(qd, FifoQueueDisc):
        queue_cap = int(qd.max_packets)
    elif qd is not None:
        raise UnliftableDumbbellError(
            f"bottleneck qdisc {type(qd).__name__} has no slot-model "
            "analog (fifo and RED are modeled)"
        )
    return DumbbellProgram(
        n_flows=len(flows),
        variant_idx=np.asarray(variants, np.int32),
        start_slot=np.asarray(
            [int(s / slot_s) for s in starts], np.int32
        ),
        stop_slot=np.asarray(
            [int(min(s, sim_end_s) / slot_s) for s in stops], np.int32
        ),
        max_pkts=np.asarray(
            [(b + seg - 1) // seg if b else 2**31 - 1 for b in budgets],
            np.int32,
        ),
        slot_s=slot_s,
        n_slots=int(math.ceil(sim_end_s / slot_s)),
        ack_lag=max(1, int(round(ack_lag_s / slot_s))),
        queue_cap=queue_cap,
        burst_cap=max(1, int(access_rate / bn_rate)),
        base_rtt_s=base_rtt_s,
        seg_bytes=seg,
        ecn=np.asarray(ecns, bool),
        qdisc=qdisc_kind,
        **red_kw,
    )


def _cwnd_increase(var, cwnd, ssthresh, acked, t_s, rtt_s, st,
                   acked_raw=None):
    """Vectorized per-ack cwnd growth for all thirteen variants
    (segments).

    ``st`` carries the variant side-state dict; returns (new_cwnd, st').
    Masked-dense: every rule computes, the variant index selects.
    ``acked_raw`` (defaults to ``acked``) feeds the PktsAcked-analog
    estimators (min-RTT, Westwood BWE, Illinois delay, BBR rounds) —
    the host calls PktsAcked on every ack, recovery or not, while
    window growth sees only the recovery-masked count.
    """
    w = jnp.maximum(cwnd, 1.0)
    a = acked.astype(jnp.float32)
    ar = a if acked_raw is None else acked_raw.astype(jnp.float32)
    in_ss = cwnd < ssthresh

    # --- PktsAcked-analog side estimators (raw acks) --------------------
    sampled = ar > 0
    min_rtt = jnp.where(
        sampled, jnp.minimum(st["min_rtt"], rtt_s), st["min_rtt"]
    )
    # Westwood+: EWMA bandwidth once ~a cwnd's worth of acks arrived
    ww_acc = st["ww_acc"] + ar
    ww_done = sampled & (ww_acc >= w)
    ww_sample = ww_acc / jnp.maximum(rtt_s, 1e-6)
    bwe = jnp.where(
        ww_done,
        jnp.where(st["bwe"] == 0.0, ww_sample,
                  0.9 * st["bwe"] + 0.1 * ww_sample),
        st["bwe"],
    )
    ww_acc = jnp.where(ww_done, 0.0, ww_acc)
    # Illinois: delay-modulated alpha/beta
    ill_max = jnp.where(
        sampled, jnp.maximum(st["ill_max_rtt"], rtt_s), st["ill_max_rtt"]
    )
    dm = ill_max - min_rtt
    da = jnp.maximum(rtt_s - min_rtt, 0.0)
    d1 = 0.01 * dm
    k_ill = (ILL_ALPHA_MAX - ILL_ALPHA_MIN) / jnp.maximum(dm - d1, 1e-9)
    alpha_raw = jnp.where(
        da <= d1, ILL_ALPHA_MAX,
        jnp.maximum(ILL_ALPHA_MAX - k_ill * (da - d1), ILL_ALPHA_MIN),
    )
    beta_raw = jnp.clip(
        ILL_BETA_MIN
        + (ILL_BETA_MAX - ILL_BETA_MIN) * da / jnp.maximum(dm, 1e-9),
        ILL_BETA_MIN, ILL_BETA_MAX,
    )
    ill_alpha = jnp.where(
        sampled, jnp.where(dm <= 0.0, ILL_ALPHA_MAX, alpha_raw),
        st["ill_alpha"],
    )
    ill_beta = jnp.where(
        sampled, jnp.where(dm <= 0.0, ILL_BETA_MIN, beta_raw),
        st["ill_beta"],
    )
    # BBR: per-round max-filtered delivery rate + state machine
    bbr_acc = st["bbr_acc"] + ar
    round_done = sampled & (bbr_acc >= w)
    bbr_sample = bbr_acc / jnp.maximum(rtt_s, 1e-6)
    bbr_bw = jnp.where(
        round_done,
        jnp.maximum(st["bbr_bw"] * BBR_BW_DECAY, bbr_sample),
        st["bbr_bw"],
    )
    bbr_acc = jnp.where(round_done, 0.0, bbr_acc)
    grew = bbr_sample > st["bbr_full_bw"] * 1.25
    bbr_full_bw = jnp.where(round_done & grew, bbr_sample, st["bbr_full_bw"])
    bbr_full_cnt = jnp.where(
        round_done,
        jnp.where(grew, 0, st["bbr_full_cnt"] + 1),
        st["bbr_full_cnt"],
    )
    state = st["bbr_state"]
    pipe_full = round_done & (state == BBR_STARTUP) & (bbr_full_cnt >= 3)
    state = jnp.where(pipe_full, BBR_DRAIN, state)
    # one round of DRAIN, then PROBE_BW cycling
    leave_drain = round_done & (st["bbr_state"] == BBR_DRAIN)
    state = jnp.where(leave_drain, BBR_PROBE_BW, state)
    bbr_cycle = jnp.where(
        round_done & (state == BBR_PROBE_BW),
        (st["bbr_cycle"] + 1) % len(BBR_CYCLE_GAINS),
        st["bbr_cycle"],
    )

    # --- congestion avoidance rules (per ack batch) ---------------------
    inc_reno = a / w
    inc_scal = a / jnp.minimum(w, SCALABLE_AI)
    a_hs = jnp.where(
        w <= HS_LOW_WINDOW, 1.0, jnp.maximum(1.0, 0.156 * w**0.8 / 2.0)
    )
    inc_hs = a_hs * a / w

    # cubic: (re)open an epoch on first CA ack after loss
    fresh = (st["epoch_t"] < 0.0) & (a > 0) & ~in_ss
    k = jnp.where(
        st["w_max"] > w,
        jnp.cbrt(jnp.maximum(st["w_max"] - w, 0.0) / CUBIC_C),
        0.0,
    )
    origin = jnp.maximum(st["w_max"], w)
    epoch_t = jnp.where(fresh, t_s, st["epoch_t"])
    k = jnp.where(fresh, k, st["k"])
    origin = jnp.where(fresh, origin, st["origin"])
    w_est = jnp.where(fresh, w, st["w_est"])
    te = t_s - epoch_t + rtt_s
    target = origin + CUBIC_C * (te - k) ** 3
    w_est = w_est + 3.0 * (1 - CUBIC_BETA) / (1 + CUBIC_BETA) * a / w
    target = jnp.maximum(target, w_est)
    inc_cubic = jnp.clip((target - w) / w, 0.0, 0.5) * a

    # vegas / veno backlog estimate from the shared rtt sample
    diff = w * (1.0 - st["base_rtt"] / jnp.maximum(rtt_s, st["base_rtt"]))
    inc_vegas = jnp.where(
        diff < VEGAS_ALPHA, a / w, jnp.where(diff > VEGAS_BETA, -a / w, 0.0)
    )
    inc_veno = jnp.where(diff < VENO_BETA, inc_reno, 0.5 * inc_reno)

    # Linux reno (and DCTCP, which inherits it): whole-cwnd ack counting
    is_lr = (var == V_LINUXRENO) | (var == V_DCTCP)
    cnt = st["cwnd_cnt"] + a
    whole = jnp.floor(cnt / w)
    inc_lr = whole
    new_cnt = jnp.where(
        is_lr & ~in_ss & (a > 0), cnt - whole * w, st["cwnd_cnt"]
    )

    # BIC: binary search toward w_max, max-probe beyond it
    bic_mid = jnp.minimum((st["w_max"] - w) / 2.0, BIC_MAX_INCR)
    bic_probe = jnp.minimum(w - st["w_max"] + 1.0, BIC_MAX_INCR)
    bic_inc = jnp.maximum(
        jnp.where(w < st["w_max"], bic_mid, bic_probe), BIC_SMIN
    )
    inc_bic = jnp.where(
        (w < BIC_LOW_WND) | (st["w_max"] == 0.0),
        inc_reno, a * bic_inc / w,
    )

    inc_ill = ill_alpha * a / w

    # Hybla: growth normalized by rho = RTT / 25 ms
    rho = jnp.maximum(rtt_s / HYBLA_RRTT, 1.0)
    inc_hybla = a * rho * rho / w

    # H-TCP: additive increase grows with time since the last congestion
    # event (quadratic past the 1 s low-speed boundary), scaled by the
    # adaptive backoff beta carried in st["htcp_beta"]
    h_delta = jnp.maximum(t_s - st["htcp_last_cong"] - HTCP_DELTA_B, 0.0)
    h_alpha = jnp.maximum(
        2.0 * (1.0 - st["htcp_beta"])
        * (1.0 + 10.0 * h_delta + 0.25 * h_delta * h_delta),
        1.0,
    )
    inc_htcp = h_alpha * a / w

    # YeAH: STCP fast mode while the backlog estimate (the shared
    # Vegas-style `diff`) stays under Q_max; Reno slow mode past it with
    # the precautionary decongestion shed spread over one cwnd of acks
    inc_yeah = jnp.where(
        diff < YEAH_QMAX,
        a / jnp.minimum(w, YEAH_ALPHA),
        (1.0 - diff * (1.0 - YEAH_RHO)) * a / w,
    )

    # LEDBAT: window tracks the 100 ms queueing-delay target; negative
    # off-target shrinks the window (scavenger behavior)
    qdelay = jnp.maximum(rtt_s - jnp.minimum(st["min_rtt"], rtt_s), 0.0)
    inc_ledbat = (
        LEDBAT_GAIN * (LEDBAT_TARGET_S - qdelay) / LEDBAT_TARGET_S * a / w
    )

    # TCP-LP: Reno growth outside the inference phase (the early-
    # congestion collapse itself is applied after the select below)
    in_infer = t_s < st["lp_until"]
    inc_lp = jnp.where(in_infer, 0.0, inc_reno)

    inc_ca = jnp.select(
        [var == V_NEWRENO, var == V_CUBIC, var == V_SCALABLE,
         var == V_HIGHSPEED, var == V_VEGAS, var == V_VENO,
         is_lr, var == V_BIC, var == V_WESTWOOD,
         var == V_ILLINOIS, var == V_HYBLA, var == V_HTCP,
         var == V_YEAH, var == V_LEDBAT, var == V_LP],
        [inc_reno, inc_cubic, inc_scal, inc_hs, inc_vegas, inc_veno,
         inc_lr, inc_bic, inc_reno, inc_ill, inc_hybla, inc_htcp,
         inc_yeah, inc_ledbat, inc_lp],
    )
    # slow start: +1 per ack (Hybla: 2^rho − 1 per ack); Vegas leaves SS
    # once the backlog passes γ
    vegas_exit = (var == V_VEGAS) & in_ss & (diff > VEGAS_GAMMA) & (a > 0)
    ssthresh = jnp.where(vegas_exit, jnp.maximum(w - 1.0, 2.0), ssthresh)
    inc_ss = jnp.where(var == V_HYBLA, a * (2.0**rho - 1.0), a)
    inc = jnp.where(in_ss & ~vegas_exit, inc_ss, inc_ca)
    # TCP-LP yields completely while inferring congestion: the collapsed
    # 1-segment window must not slow-start straight back up, or the
    # scavenger stops yielding (the host's ack-clocked hold is slower
    # than this slot model's, so the gate covers slow start too)
    inc = jnp.where((var == V_LP) & in_infer, 0.0, inc)
    # TCP-LP's inference collapse holds at ONE segment (host behavior);
    # every other variant keeps the usual 2-segment floor
    floor = jnp.where(
        (var == V_LP) & in_infer, jnp.float32(1.0), jnp.float32(2.0)
    )
    new_cwnd = jnp.maximum(cwnd + jnp.where(a > 0, inc, 0.0), floor)

    # BBR replaces loss-driven AIMD entirely: cwnd tracks gain × BDP
    gain = jnp.select(
        [state == BBR_STARTUP, state == BBR_DRAIN],
        [BBR_HIGH_GAIN, 1.0 / BBR_HIGH_GAIN],
        # dtype pinned: an unpinned float table would ride f64 through
        # the whole BBR lane under ambient x64 (JXL002)
        jnp.asarray(BBR_CYCLE_GAINS, jnp.float32)[bbr_cycle],
    )
    bdp = bbr_bw * min_rtt
    target = jnp.maximum(gain * bdp, 4.0)
    cwnd_bbr = jnp.where(
        bbr_bw == 0.0,
        cwnd + a,                                 # first RTTs
        jnp.where(
            cwnd < target,
            cwnd + jnp.minimum(a, target - cwnd + 1.0),
            jnp.maximum(target, 4.0),
        ),
    )
    new_cwnd = jnp.where(
        var == V_BBR, jnp.where(a > 0, cwnd_bbr, cwnd), new_cwnd
    )

    # TCP-LP early-congestion inference: one-way delay past 15% of the
    # observed delay range collapses the window to one segment and holds
    # the inference phase for one RTT (host PktsAcked hook)
    lp_trigger = (
        (var == V_LP) & sampled & (ill_max > min_rtt)
        & (rtt_s > min_rtt + LP_INFERENCE_FRAC * (ill_max - min_rtt))
        & ~in_infer
    )
    new_cwnd = jnp.where(lp_trigger, 1.0, new_cwnd)
    ssthresh = jnp.where(
        lp_trigger, jnp.maximum(ssthresh / 2.0, 2.0), ssthresh
    )
    lp_until = jnp.where(
        lp_trigger, t_s + rtt_s, st["lp_until"]
    )

    st = dict(st, epoch_t=epoch_t, k=k, origin=origin, w_est=w_est,
              lp_until=lp_until,
              last_diff=jnp.where(a > 0, diff, st["last_diff"]),
              min_rtt=min_rtt, ww_acc=ww_acc, bwe=bwe,
              ill_max_rtt=ill_max, ill_alpha=ill_alpha, ill_beta=ill_beta,
              bbr_acc=bbr_acc, bbr_bw=bbr_bw, bbr_full_bw=bbr_full_bw,
              bbr_full_cnt=bbr_full_cnt, bbr_state=state,
              bbr_cycle=bbr_cycle, cwnd_cnt=new_cnt)
    return new_cwnd, ssthresh, st


def _loss_response(var, cwnd, st, t_s):
    """Vectorized GetSsThresh on a detected loss (segments).

    ``t_s`` stamps H-TCP's last-congestion clock (its additive increase
    grows with the time elapsed since this moment)."""
    w = jnp.maximum(cwnd, 1.0)
    ss_reno = w / 2.0
    # cubic fast convergence: remember a reduced w_max when still climbing
    new_wmax = jnp.where(
        w < st["w_max"], w * (1.0 + CUBIC_BETA) / 2.0, w
    )
    ss_cubic = w * CUBIC_BETA
    ss_scal = w * (1.0 - SCALABLE_MD)
    b_hs = jnp.where(
        w <= HS_LOW_WINDOW,
        0.5,
        jnp.maximum(
            0.5
            - 0.4
            * (jnp.log(w) - math.log(HS_LOW_WINDOW))
            / (math.log(83000.0) - math.log(HS_LOW_WINDOW)),
            0.1,
        ),
    )
    ss_hs = w * (1.0 - b_hs)
    ss_veno = jnp.where(st["last_diff"] < VENO_BETA, w * 0.8, w * 0.5)
    # BIC fast convergence mirrors cubic's w_max bookkeeping at β=0.8
    bic_wmax = jnp.where(w < st["w_max"], w * (1.0 + BIC_BETA) / 2.0, w)
    ss_bic = w * BIC_BETA
    # Westwood+: BWE · RTTmin instead of blind halving
    ss_west = jnp.where(
        (st["bwe"] > 0.0) & jnp.isfinite(st["min_rtt"]),
        st["bwe"] * st["min_rtt"], w / 2.0,
    )
    ss_ill = w * (1.0 - st["ill_beta"])
    # BBR ignores loss beyond the BDP floor
    ss_bbr = jnp.maximum(st["bbr_bw"] * jnp.where(
        jnp.isfinite(st["min_rtt"]), st["min_rtt"], 0.0
    ), 4.0)
    # DCTCP: reduction fraction follows the marked-byte EWMA
    ss_dctcp = w * (1.0 - st["dctcp_alpha"] / 2.0)
    # H-TCP adaptive backoff: beta = RTTmin/RTTmax clamped to [0.5, 0.8]
    # once an RTT spread exists, default 0.5 before
    h_valid = (st["ill_max_rtt"] > 0.0) & jnp.isfinite(st["min_rtt"])
    h_beta = jnp.where(
        h_valid,
        jnp.clip(
            st["min_rtt"] / jnp.maximum(st["ill_max_rtt"], 1e-9), 0.5, 0.8
        ),
        HTCP_DEFAULT_BACKOFF,
    )
    ss_htcp = w * h_beta
    # YeAH: shed the larger of the measured backlog and cwnd/8
    ss_yeah = w - jnp.maximum(st["last_diff"], w / 8.0)
    ssthresh = jnp.select(
        [var == V_NEWRENO, var == V_CUBIC, var == V_SCALABLE,
         var == V_HIGHSPEED, var == V_VEGAS, var == V_VENO,
         var == V_LINUXRENO, var == V_BIC, var == V_WESTWOOD,
         var == V_ILLINOIS, var == V_HYBLA, var == V_BBR,
         var == V_DCTCP, var == V_HTCP, var == V_YEAH,
         var == V_LEDBAT, var == V_LP],
        [ss_reno, ss_cubic, ss_scal, ss_hs, ss_reno, ss_veno,
         ss_reno, ss_bic, ss_west, ss_ill, ss_reno, ss_bbr, ss_dctcp,
         ss_htcp, ss_yeah, ss_reno, ss_reno],
    )
    ssthresh = jnp.maximum(ssthresh, 2.0)
    st = dict(
        st,
        w_max=jnp.select(
            [var == V_CUBIC, var == V_BIC],
            [new_wmax, bic_wmax],
            st["w_max"],
        ),
        epoch_t=jnp.full_like(st["epoch_t"], -1.0),
        htcp_beta=jnp.where(var == V_HTCP, h_beta, st["htcp_beta"]),
        htcp_last_cong=jnp.where(
            var == V_HTCP, t_s, st["htcp_last_cong"]
        ),
    )
    return ssthresh, st


#: queue-occupancy histogram bins for the on-device obs accumulators
OBS_QHIST_BINS = 16


def build_dumbbell_step(prog: DumbbellProgram, replicas: int, obs: bool = False):
    """Return (init_state, step_fn) for the slot-stepped scan.

    ``step_fn(s, (t, key), var, ecn_cap)`` — the per-flow variant ids
    ``var`` (F,) and ECN-capability flags ``ecn_cap`` (F,) are RUNTIME
    operands, not trace-time constants: every variant assignment rides
    one compiled executable, and the config-axis sweep vmaps them
    alongside the replica axis.

    ``obs=True`` (the ``TpudesObs`` knob at run time) threads three
    extra accumulators through the carry — per-lane cwnd-cut events,
    retransmissions (losses consumed by the dupack-timed detector), and
    a bottleneck-occupancy histogram — fetched once at run end.  A
    disabled run compiles the exact pre-obs program.
    """
    R, F, L = replicas, prog.n_flows, prog.buf_len
    if obs:
        from tpudes.obs.flowmon import (
            FLOW_DELAY_BINS,
            VERDICT_DROP,
            VERDICT_RX,
            VERDICT_TX,
            flow_accumulate,
            flow_carry,
            flow_ring_write,
        )
    start = jnp.asarray(prog.start_slot)
    stop = jnp.asarray(prog.stop_slot)
    max_pkts = jnp.asarray(prog.max_pkts)
    # a strong f32 scalar: `t * slot_s` must stay f32 under ambient
    # x64 (an unpinned python float would promote the i32 clock to f64)
    slot_s = jnp.float32(prog.slot_s)
    base_rtt = jnp.float32(prog.base_rtt_s)
    rtt_slots = max(1, int(round(prog.base_rtt_s / prog.slot_s)))
    Q = prog.queue_cap
    burst = prog.burst_cap
    RED = prog.qdisc == "red"
    TRAFFIC = prog.traffic is not None
    if TRAFFIC:
        from tpudes.traffic.device import build_cum_fn

        tr_cum = build_cum_fn(prog.traffic)
        slot_us = max(1, int(round(prog.slot_s * 1e6)))

    def init_state():
        z = lambda *sh, dt=jnp.float32: jnp.zeros(sh, dt)  # noqa: E731
        extra = (
            dict(
                cwnd_cuts=z(R, F, dt=jnp.int32),
                retx_cnt=z(R, F, dt=jnp.int32),
                q_hist=z(R, OBS_QHIST_BINS, dt=jnp.int32),
                # per-flow FlowMonitor columns + the packet-event ring
                **flow_carry(F, lead=(R,)),
            )
            if obs
            else {}
        )
        # every fill dtype pinned f32: an unpinned python-float fill
        # would widen the whole carry under ambient x64 (JXL002)
        return dict(
            **extra,
            cwnd=jnp.full((R, F), INIT_CWND, jnp.float32),
            ssthresh=jnp.full((R, F), SSTHRESH0, jnp.float32),
            inflight=z(R, F, dt=jnp.int32),
            q=z(R, F, dt=jnp.int32),
            q_marked=z(R, F),            # CE-marked packets in the queue
            delivered=z(R, F, dt=jnp.int32),
            drops=z(R, F, dt=jnp.int32),
            recover_until=z(R, F, dt=jnp.int32),
            ack_buf=z(R, L, F, dt=jnp.int32),
            loss_buf=z(R, L, F, dt=jnp.int32),
            mark_buf=z(R, L, F),         # ECE echoes riding the acks
            rtt_buf=jnp.full((R, L), prog.base_rtt_s, jnp.float32),
            qsum=z(R),
            red_avg=z(R),                # RED EWMA average queue
            dctcp_acked=z(R, F),
            dctcp_marked=z(R, F),
            side=dict(
                w_max=z(R, F),
                epoch_t=jnp.full((R, F), -1.0, jnp.float32),
                k=z(R, F),
                origin=z(R, F), w_est=z(R, F),
                base_rtt=jnp.broadcast_to(base_rtt, (R, F)),
                last_diff=z(R, F),
                min_rtt=jnp.full((R, F), jnp.inf, jnp.float32),
                ww_acc=z(R, F), bwe=z(R, F),
                ill_max_rtt=z(R, F),
                ill_alpha=jnp.full((R, F), ILL_ALPHA_MAX, jnp.float32),
                ill_beta=jnp.full((R, F), ILL_BETA_MIN, jnp.float32),
                bbr_acc=z(R, F), bbr_bw=z(R, F), bbr_full_bw=z(R, F),
                bbr_full_cnt=z(R, F),
                bbr_state=z(R, F, dt=jnp.int32),
                bbr_cycle=z(R, F, dt=jnp.int32),
                cwnd_cnt=z(R, F),
                dctcp_alpha=jnp.ones((R, F), jnp.float32),
                htcp_beta=jnp.full(
                    (R, F), HTCP_DEFAULT_BACKOFF, jnp.float32
                ),
                htcp_last_cong=z(R, F),
                lp_until=z(R, F),
            ),
        )

    def step_fn(s, inp, var, ecn_cap, tr=None):
        t, key = inp
        idx = t % L

        # per-replica keying: replica r's draws at slot t are a pure
        # function of (key, t, r) — independent of R — so runtime
        # replica-bucketing (padding R to a power of two) leaves every
        # real replica's stream bit-identical
        rkeys = jax.vmap(lambda i: jax.random.fold_in(key, i))(jnp.arange(R))
        if RED:

            def draw(kk):
                # fixed-arity split of a fold_in-derived key: pure in
                # (key, t, r), so bucketing/chunking stay bit-exact;
                # draw dtypes pinned f32 (ambient x64 must not widen
                # the streams — JXL002)
                k_dep, k_red, k_mark = jax.random.split(kk, 3)
                return (
                    jax.random.uniform(k_dep, (), jnp.float32),
                    jax.random.uniform(k_red, (F,), jnp.float32),
                    jax.random.uniform(k_mark, (), jnp.float32),
                )

            u_dep, u_red, u_mark = jax.vmap(draw)(rkeys)
        else:
            u_dep = jax.vmap(
                lambda kk: jax.random.uniform(kk, (), jnp.float32)
            )(rkeys)

        # 1. consume this slot's ack / loss / ECN-echo arrivals
        acks = s["ack_buf"][:, idx, :]
        losses = s["loss_buf"][:, idx, :]
        marks = s["mark_buf"][:, idx, :]
        rtt = s["rtt_buf"][:, idx][:, None]
        ack_buf = s["ack_buf"].at[:, idx, :].set(0)
        loss_buf = s["loss_buf"].at[:, idx, :].set(0)
        mark_buf = s["mark_buf"].at[:, idx, :].set(0.0)
        inflight = s["inflight"] - acks - losses

        # DCTCP per-window marked-fraction EWMA (PktsAcked/EceReceived)
        d_acked = s["dctcp_acked"] + acks.astype(jnp.float32)
        d_marked = s["dctcp_marked"] + marks
        win_done = d_acked >= s["cwnd"]
        side = dict(
            s["side"],
            dctcp_alpha=jnp.where(
                win_done,
                (1.0 - DCTCP_G) * s["side"]["dctcp_alpha"]
                + DCTCP_G * d_marked / jnp.maximum(d_acked, 1.0),
                s["side"]["dctcp_alpha"],
            ),
        )
        d_acked = jnp.where(win_done, 0.0, d_acked)
        d_marked = jnp.where(win_done, 0.0, d_marked)

        in_recovery = t < s["recover_until"]
        cwnd, ssthresh, side = _cwnd_increase(
            var[None, :], s["cwnd"], s["ssthresh"],
            jnp.where(in_recovery, 0, acks), t * slot_s, rtt, side,
            acked_raw=acks,
        )
        # 2. one reduction per recovery window on loss or ECN echo
        # (RFC 3168: an ECE ack triggers the variant's loss response;
        # DCTCP's response is the alpha-scaled cut via ss_dctcp)
        reduce = ((losses > 0) | ((marks > 0) & ecn_cap[None, :])) & ~in_recovery
        ss_loss, side_loss = _loss_response(
            var[None, :], cwnd, side, t * slot_s
        )
        ssthresh = jnp.where(reduce, ss_loss, ssthresh)
        cwnd = jnp.where(reduce, ssthresh, cwnd)
        side = {
            k: jnp.where(reduce, side_loss[k], side[k]) for k in side
        }
        recover_until = jnp.where(
            reduce, t + rtt_slots, s["recover_until"]
        )

        # 3. departure: serve one packet, flow ∝ queue occupancy
        q = s["q"]
        # int reductions pin dtype=jnp.int32: an unpinned .sum()
        # widens to i64 under ambient x64 (JXL002); bit-exact
        # no-op under the default config
        qtot = q.sum(axis=1, dtype=jnp.int32)
        backlogged = qtot > 0
        cum = jnp.cumsum(q, axis=1, dtype=jnp.int32)
        thresh = (u_dep * qtot.astype(jnp.float32)).astype(jnp.int32)
        dep = jnp.argmax(cum > thresh[:, None], axis=1)  # (R,)
        dep_oh = jax.nn.one_hot(dep, F, dtype=jnp.int32) * backlogged[
            :, None
        ].astype(jnp.int32)
        # the departing packet carries a CE mark with probability equal
        # to the flow's marked share — INTEGER marks only (a fractional
        # residue would keep the `marks > 0` loss response firing for
        # hundreds of RTTs after a marking episode)
        if RED:
            dep_marked = dep_oh.astype(jnp.float32) * (
                u_mark[:, None]
                < s["q_marked"] / jnp.maximum(q, 1).astype(jnp.float32)
            ).astype(jnp.float32)
        else:
            dep_marked = jnp.zeros((R, F), jnp.float32)
        q_marked = jnp.maximum(s["q_marked"] - dep_marked, 0.0)
        q = q - dep_oh
        delivered = s["delivered"] + dep_oh
        aidx = (t + prog.ack_lag) % L
        ack_buf = ack_buf.at[:, aidx, :].add(dep_oh)
        mark_buf = mark_buf.at[:, aidx, :].add(dep_marked)
        rtt_buf = s["rtt_buf"].at[:, aidx].set(
            prog.base_rtt_s + qtot.astype(jnp.float32) * slot_s
        )

        # 4. window-driven arrivals; AQM (RED mark/early-drop) then
        # tail-drop past capacity
        want = jnp.clip(
            cwnd.astype(jnp.int32) - inflight, 0, burst
        )
        live = (t >= start[None, :]) & (t < stop[None, :]) & (
            delivered + inflight < max_pkts[None, :]
        )
        want = jnp.where(live, want, 0)
        if TRAFFIC:
            # app-limited sending: the workload's cumulative offered
            # segments (closed-form, shared across replicas — the
            # realization IS the workload, like the mobility
            # trajectory) caps what may ever have left the
            # application — an EXACT clip, not a gate, so the send
            # burst cannot overshoot the offered count.  Arrivals
            # inside a slot are sendable in that slot (the slot-end
            # evaluation — sub-slot timing is below this model's
            # resolution either way)
            app_cum = jnp.floor(
                tr_cum(tr, (t + 1) * jnp.int32(slot_us))
            ).astype(jnp.int32)                          # (F,)
            want = jnp.minimum(
                want,
                jnp.maximum(
                    app_cum[None, :] - delivered - inflight, 0
                ),
            )
        red_avg = s["red_avg"]
        red_marks = jnp.zeros((R, F), jnp.float32)
        red_drops = jnp.zeros((R, F), jnp.int32)
        if RED:
            # EWMA over this slot's arrivals against the instantaneous
            # queue (per-arrival updates folded into one (1-qw)^n step;
            # idle-time decay not modeled — the bottleneck is backlogged
            # in every regime this engine targets)
            qnow = q.sum(axis=1, dtype=jnp.int32).astype(jnp.float32)
            n_arr = want.sum(axis=1, dtype=jnp.int32)
            red_avg = jnp.where(
                n_arr > 0,
                qnow
                + (red_avg - qnow)
                * jnp.float32(1.0 - prog.red_qw) ** n_arr,
                red_avg,
            )
            p = jnp.where(
                red_avg < prog.red_min_th,
                0.0,
                prog.red_max_p
                * (red_avg - prog.red_min_th)
                / max(prog.red_max_th - prog.red_min_th, 1e-9),
            )
            if prog.red_gentle:
                p = jnp.where(
                    red_avg >= prog.red_max_th,
                    prog.red_max_p
                    + (1.0 - prog.red_max_p)
                    * (red_avg - prog.red_max_th) / prog.red_max_th,
                    p,
                )
                forced = red_avg >= 2.0 * prog.red_max_th
            else:
                forced = red_avg >= prog.red_max_th
            p = jnp.clip(jnp.where(forced, 1.0, p), 0.0, 1.0)
            # ECT packets are marked unless the forced region hard-drops
            ect = ecn_cap[None, :] & prog.red_use_ecn
            n_act = jnp.minimum(
                want,
                jnp.floor(
                    want.astype(jnp.float32) * p[:, None] + u_red
                ).astype(jnp.int32),
            )
            mark_sel = ect & ~(
                forced[:, None] & bool(prog.red_use_hard_drop)
            )
            red_drops = jnp.where(mark_sel, 0, n_act)
            red_marks = jnp.where(mark_sel, n_act, 0).astype(jnp.float32)
            want_q = want - red_drops
        else:
            want_q = want
        wtot = want_q.sum(axis=1, dtype=jnp.int32)
        free = jnp.maximum(Q - q.sum(axis=1, dtype=jnp.int32), 0)
        # proportional admission with largest-remainder rounding
        scale = jnp.minimum(
            free.astype(jnp.float32) / jnp.maximum(wtot, 1).astype(jnp.float32),
            1.0,
        )
        exact = want_q.astype(jnp.float32) * scale[:, None]
        acc = jnp.floor(exact).astype(jnp.int32)
        rem = exact - acc
        leftover = jnp.minimum(
            free - acc.sum(axis=1, dtype=jnp.int32),
            wtot - acc.sum(axis=1, dtype=jnp.int32),
        )
        order = jnp.argsort(-rem, axis=1)
        rank = jnp.argsort(order, axis=1)
        acc = acc + (
            (rank < leftover[:, None]) & (acc < want_q)
        ).astype(jnp.int32)
        acc = jnp.minimum(acc, want_q)
        rej = want_q - acc
        q = q + acc
        # marked packets are among the admitted ones (integer count)
        q_marked = q_marked + jnp.minimum(red_marks, acc.astype(jnp.float32))
        inflight = inflight + want
        drops = s["drops"] + rej + red_drops
        lidx = (t + prog.ack_lag) % L  # dupack-timed detection
        loss_buf = loss_buf.at[:, lidx, :].add(rej + red_drops)

        extra = {}
        if obs:
            # per-lane metric accumulators (no host sync: they ride the
            # carry and are fetched with the outcome arrays at run end)
            bucket = jnp.clip(
                qtot * OBS_QHIST_BINS // max(Q + 1, 1), 0, OBS_QHIST_BINS - 1
            )
            extra = dict(
                cwnd_cuts=s["cwnd_cuts"] + reduce.astype(jnp.int32),
                retx_cnt=s["retx_cnt"] + losses,
                q_hist=s["q_hist"]
                + jax.nn.one_hot(bucket, OBS_QHIST_BINS, dtype=jnp.int32),
            )
            # FlowMonitor columns: a packet is one segment + 40 header
            # bytes (the host monitor counts GetSize()+20 on packets
            # already carrying a 20-byte TCP header); one-way delay =
            # half the base RTT plus the bottleneck residence this
            # slot's departure saw — all dense adds, no sparse ops
            pkt_b = jnp.int32(prog.seg_bytes + 40)
            drop_f = rej + red_drops
            delay = (
                0.5 * base_rtt
                + qtot.astype(jnp.float32)[:, None] * slot_s
            )
            fm = flow_accumulate(
                {k: s[k] for k in s if k.startswith("fm_")},
                t_s=t * slot_s,
                tx=want,
                tx_bytes=want * pkt_b,
                rx=dep_oh,
                rx_bytes=dep_oh * pkt_b,
                delay_s=jnp.broadcast_to(delay, (R, F)),
                lost=drop_f,
                bin_width_s=(
                    0.5 * prog.base_rtt_s + Q * prog.slot_s
                ) / FLOW_DELAY_BINS,
            )
            # packet-event ring: ONE sampled event per (replica, slot)
            # — the delivery if one happened (at most one per replica
            # per slot: dep_oh is one-hot), else a drop, else a send;
            # step column -1 marks an idle slot
            has_drop = drop_f.sum(axis=1, dtype=jnp.int32) > 0
            has_tx = want.sum(axis=1, dtype=jnp.int32) > 0
            ev_flow = jnp.where(
                backlogged,
                dep,
                jnp.where(
                    has_drop,
                    jnp.argmax(drop_f, axis=1),
                    jnp.argmax(want, axis=1),
                ),
            ).astype(jnp.int32)
            ev_verdict = jnp.where(
                backlogged,
                VERDICT_RX,
                jnp.where(has_drop, VERDICT_DROP, VERDICT_TX),
            )
            any_ev = backlogged | has_drop | has_tx
            slot_us_c = jnp.int32(max(1, round(prog.slot_s * 1e6)))
            row = jnp.stack(
                [
                    jnp.where(any_ev, t, -1),
                    jnp.broadcast_to(t * slot_us_c, (R,)),
                    ev_flow,
                    jnp.broadcast_to(pkt_b, (R,)),
                    ev_verdict,
                ],
                axis=-1,
            )
            fm["fm_ring"] = flow_ring_write(s["fm_ring"], t, row)
            extra.update(fm)
        return dict(
            **extra,
            cwnd=cwnd, ssthresh=ssthresh, inflight=inflight, q=q,
            q_marked=q_marked,
            delivered=delivered, drops=drops, recover_until=recover_until,
            ack_buf=ack_buf, loss_buf=loss_buf, mark_buf=mark_buf,
            rtt_buf=rtt_buf,
            qsum=s["qsum"] + qtot.astype(jnp.float32),
            red_avg=red_avg,
            dctcp_acked=d_acked, dctcp_marked=d_marked,
            side=side,
        ), None

    return init_state, step_fn


#: the RED/AQM knobs — cache-key components only when the qdisc is
#: actually "red" (see dumbbell_prog_key)
_RED_FIELDS = (
    "red_min_th", "red_max_th", "red_max_p", "red_qw", "red_gentle",
    "red_use_ecn", "red_use_hard_drop",
)


def dumbbell_prog_key(prog: DumbbellProgram) -> tuple:
    """Hashable identity of the DumbbellProgram fields that shape the
    compiled program.  ``n_slots``, ``variant_idx`` and ``ecn`` are
    deliberately ABSENT: the horizon is a traced while_loop bound and
    the variant/ECN assignment a traced operand, so one executable
    serves every horizon AND every variant assignment.  In fifo mode
    the ``red_*`` parameters are absent too — they never reach the
    fifo program (keying on them was a dead cache-key component
    causing spurious recompiles across RED-parameter sweeps of
    non-RED studies; found by analysis rule JXL004).  ``traffic``
    contributes only its SHAPE key: the workload model id and every
    parameter are traced operands."""
    skip = {"n_slots", "variant_idx", "ecn", "traffic"}
    if prog.qdisc != "red":
        skip.update(_RED_FIELDS)
    return tuple(
        v.tobytes() if isinstance(v, np.ndarray) else v
        for k, v in prog.__dict__.items()
        if k not in skip
    ) + (None if prog.traffic is None else prog.traffic.shape_key(),)


def build_dumbbell_advance(prog: DumbbellProgram, r_pad: int,
                           obs: bool = False, n_cfg: int | None = None,
                           sweep: str = "variant"):
    """``(init_state, fn)`` with ``fn(carry, key, var, ecn, t_end)``
    the UNJITTED advance exactly as :func:`run_tcp_dumbbell` jits it —
    factored out so the trace manifest (:func:`trace_manifest`)
    abstractly traces the same program the runner cache compiles.

    With a config axis (``n_cfg``), ``sweep`` picks which operand
    carries it: ``"variant"`` vmaps the per-flow variant/ECN
    assignment (the PR-5 sweep), ``"traffic"`` vmaps the workload
    operand tables instead (ISSUE-15: the BSS ``traffic_sweep`` seam
    mirrored — var/ecn are shared across points, the (C, …) traffic
    tables fan out)."""
    init_state, step_fn = build_dumbbell_step(prog, r_pad, obs=obs)

    def advance(carry, key, var, ecn, t_end, tr=None):
        # per-slot key = fold_in(key, t): pure in (key, t), so the
        # traced horizon needs no split-keys array shape and a
        # chunked run re-enters at t>0 on the same slot streams
        def body(c):
            t, s = c
            s, _ = step_fn(
                s, (t, jax.random.fold_in(key, t)), var, ecn, tr
            )
            return t + 1, s

        t, s = jax.lax.while_loop(
            lambda c: c[0] < t_end, body, carry
        )
        # chunk summaries only under TpudesObs (obs is in the
        # cache key): a disabled run compiles the pre-obs program
        metrics = (
            dict(
                delivered=jnp.sum(
                    s["delivered"], axis=-1, dtype=jnp.int32
                ),
                drops=jnp.sum(s["drops"], axis=-1, dtype=jnp.int32),
                # the per-chunk packet-ring snapshot must be a FRESH
                # value (drive_chunks donates the carry before the
                # deferred fetch reads the metrics): lax.rev is a real
                # op XLA cannot fold back into an alias, and the
                # decoder orders rows by the step column, so the flip
                # needs no undo
                fm_ring=jnp.flip(s["fm_ring"], axis=-2),
            )
            if obs
            else {}
        )
        return (t, s), metrics

    fn = advance
    if n_cfg is not None:
        if sweep == "traffic":
            fn = jax.vmap(fn, in_axes=(0, None, None, None, None, 0))
        else:
            fn = jax.vmap(fn, in_axes=(0, None, 0, 0, None, None))
    return init_state, fn


def _variant_point(entry) -> np.ndarray:
    """One sweep point → (F,) int32 variant ids (names or ids in)."""
    return np.asarray(
        [VARIANTS.index(v) if isinstance(v, str) else int(v) for v in entry],
        np.int32,
    )


def _variant_ecn(variant_idx: np.ndarray) -> np.ndarray:
    """(F,) ECN capability implied by the variant alone (the
    ``REQUIRES_ECN`` class flag, e.g. DCTCP) — what a sweep point that
    reassigns variants can know without a live socket's UseEcn
    attribute."""
    from tpudes.models.internet.tcp_congestion import TCP_VARIANTS

    return np.asarray(
        [
            bool(getattr(TCP_VARIANTS[VARIANTS[int(i)]], "REQUIRES_ECN", False))
            for i in variant_idx
        ],
        bool,
    )


#: state keys fetched to the host at run end (plus the obs extras)
_TCP_FETCH = ("delivered", "drops", "qsum", "cwnd")


def _tcp_fetch_obs():
    from tpudes.obs.flowmon import FM_KEYS

    return ("cwnd_cuts", "retx_cnt", "q_hist") + FM_KEYS


def _planted_divergence(finalize):
    """``TPUDES_FUZZ_PLANTED_BUG=1``: deliberately corrupt CHUNKED-run
    results (replica 0, flow 0: ``delivered`` += 1) so the fuzz
    harness's planted-bug self-test (tests/test_fuzz.py + the CI step)
    can prove the scalar-vs-chunked oracle detects, shrinks and replays
    a real divergence end to end.  Never on outside that self-test —
    the flag is read per call and gates nothing else."""

    def wrapped(host):
        out = finalize(host)
        for point in out if isinstance(out, list) else [out]:
            d = np.array(point["delivered"], copy=True)
            d[0, 0] += 1
            point["delivered"] = d
        return out

    return wrapped


def _tcp_unpack(host: dict, prog: DumbbellProgram, replicas: int,
                obs: bool) -> dict:
    """Host-side result assembly for ONE config point."""
    sim_s = prog.n_slots * prog.slot_s
    R = replicas
    delivered = np.asarray(host["delivered"])[:R]
    result = dict(
        goodput_mbps=delivered.astype(np.float32) * prog.seg_bytes * 8.0
        / sim_s / 1e6,
        delivered=delivered,
        drops=np.asarray(host["drops"])[:R],
        mean_queue=np.asarray(host["qsum"])[:R] / prog.n_slots,
        cwnd_final=np.asarray(host["cwnd"])[:R],
    )
    if obs:
        from tpudes.obs.flowmon import FM_KEYS

        result.update(
            cwnd_cuts=np.asarray(host["cwnd_cuts"])[:R],
            retx=np.asarray(host["retx_cnt"])[:R],
            queue_hist=np.asarray(host["q_hist"])[:R],
            # per-flow FlowMonitor columns + the packet-event ring,
            # replica-sliced; reduce with tpudes.obs.flowmon
            flow={k: np.asarray(host[k])[:R] for k in FM_KEYS},
        )
    return result


def tcp_study(prog: DumbbellProgram, key, replicas, mesh=None):
    """Serving-layer study descriptor (see :mod:`tpudes.serving`): the
    per-flow variant/ECN assignment is the traced sweep operand, so two
    dumbbell studies coalesce onto one (C, R, F) launch whenever their
    static fields, slot horizon, key, replica count and mesh all match.

    A program whose declared ``ecn`` disagrees with the variants'
    ``REQUIRES_ECN`` flags is marked ``solo``: sweep points derive ECN
    from the variant (the PR-5 equality contract), so such a study can
    only be served bit-faithfully by its own plain launch."""
    import dataclasses

    from tpudes.serving.descriptor import StudyDescriptor, mesh_fingerprint

    ids = np.asarray(prog.variant_idx, np.int32)
    declared = (
        np.asarray(prog.ecn, bool) if prog.ecn is not None
        else np.zeros(prog.n_flows, bool)
    )
    solo = not np.array_equal(declared, _variant_ecn(ids))
    statics = tuple(
        v.tobytes() if isinstance(v, np.ndarray) else v
        for k, v in prog.__dict__.items()
        if k not in ("variant_idx", "ecn", "traffic")
    ) + (
        # workload identity by VALUE: params are traced, but studies
        # with different workloads must not coalesce
        None if prog.traffic is None else prog.traffic.param_key(),
    )  # n_slots stays IN: the batch shares one traced slot bound
    ck = (
        statics, np.asarray(key).tobytes(), int(replicas),
        mesh_fingerprint(mesh),
    )
    point = tuple(int(i) for i in ids)

    def launch(points, block=False):
        if solo or len(points) == 1:
            pt = _variant_point(list(points[0]))
            p1 = prog if solo else dataclasses.replace(
                prog, variant_idx=pt, ecn=_variant_ecn(pt)
            )
            return run_tcp_dumbbell(
                p1, key, replicas=replicas, mesh=mesh, block=block
            )
        return run_tcp_dumbbell(
            prog, key, replicas=replicas, mesh=mesh,
            variants=[list(p) for p in points], block=block,
        )

    def warm(n_points):
        # the slot horizon is a traced operand: a 1-slot run compiles
        # the exact executable every real horizon reuses
        tiny = dataclasses.replace(prog, n_slots=1)
        if n_points == 1:
            run_tcp_dumbbell(tiny, key, replicas=replicas, mesh=mesh)
        else:
            run_tcp_dumbbell(
                tiny, key, replicas=replicas, mesh=mesh,
                variants=[list(point)] * n_points,
            )

    spec = None if (mesh is not None or solo) else dict(
        engine="dumbbell", prog=prog, key=np.asarray(key),
        replicas=replicas,
    )
    return StudyDescriptor(
        "dumbbell", ck, point, launch, warm, solo=solo, spec=spec
    )


def run_tcp_dumbbell(
    prog: DumbbellProgram,
    key,
    replicas: int,
    mesh=None,
    *,
    variants=None,
    traffic_sweep=None,
    chunk_slots: int | None = None,
    checkpoint=None,
    block: bool = True,
):
    """Execute R replicas of the dumbbell program; returns per-replica
    outcome arrays: goodput_mbps (R,F), delivered (R,F), drops (R,F),
    mean_queue (R,), cwnd_final (R,F) — plus, under ``TpudesObs=1``,
    the on-device metric accumulators ``cwnd_cuts`` (R,F), ``retx``
    (R,F) and ``queue_hist`` (R, OBS_QHIST_BINS).  The slot horizon AND
    the per-flow variant/ECN assignments are traced operands and the
    replica axis is runtime-bucketed, so horizon/variant/replica sweeps
    all reuse one executable per replica bucket.

    ``variants=[point, ...]`` (each point an (F,)-sequence of variant
    names or ids) runs a **config-axis sweep**: one launch of a
    (C, R, F) program, returning a list of per-point result dicts equal
    to what ``dataclasses.replace(prog, variant_idx=point,
    ecn=REQUIRES_ECN(point))`` per-point launches (same key) produce.

    ``traffic_sweep=[...]`` (TrafficPrograms sharing one
    ``shape_key``, with ``prog.traffic`` naming the shape class) runs
    a **config-axis workload sweep** instead (ISSUE-15, mirroring the
    BSS seam): the traffic operand tables gain the leading vmapped
    axis while the variant/ECN assignment is shared, so a C-point
    mixed cbr/mmpp/onoff/trace workload study is ONE launch of a
    (C, R, F) program — demuxed bit-equal to per-point launches with
    ``dataclasses.replace(prog, traffic=tp)`` and the same key.

    ``chunk_slots=N`` splits the horizon into N-slot segments with a
    donated carry handoff (bit-identical to single-shot; per-chunk
    metrics stream to ``tpudes.obs``).  ``checkpoint=`` (a path or
    :class:`~tpudes.parallel.checkpoint.CarryCheckpoint`) persists the
    carry after each chunk and resumes a matching run from its last
    completed chunk, bit-equal to uninterrupted.  ``block=False``
    returns an :class:`~tpudes.parallel.runtime.EngineFuture`.
    """
    from tpudes.obs.device import CompileTelemetry, device_metrics_enabled
    from tpudes.parallel.checkpoint import checkpoint_ctx
    from tpudes.parallel.runtime import (
        RUNTIME,
        EngineFuture,
        bucket_replicas,
        chunk_bounds,
        donate_argnums,
        drive_chunks,
        finalize_with_flush,
        shard_replica_axis,
        stack_axis,
        unstack_points,
    )

    if variants is not None and traffic_sweep is not None:
        raise ValueError(
            "one config axis per launch: sweep either the variant "
            "assignment (variants=[...]) or the workload "
            "(traffic_sweep=[...])"
        )
    obs = device_metrics_enabled()
    r_pad = bucket_replicas(replicas, mesh)
    sweep = "traffic" if traffic_sweep is not None else "variant"
    n_cfg = (
        len(variants) if variants is not None
        else (len(traffic_sweep) if traffic_sweep is not None else None)
    )
    # see dumbbell_prog_key for what is (deliberately) absent; the
    # sweep KIND is a cache-key component (the two sweeps vmap
    # different operands — different executables)
    ck = dumbbell_prog_key(prog) + (r_pad, obs, n_cfg, sweep)

    def build():
        init_state, fn = build_dumbbell_advance(
            prog, r_pad, obs=obs, n_cfg=n_cfg, sweep=sweep
        )
        return init_state, jax.jit(fn, donate_argnums=donate_argnums(0))

    (init_state, fn), compiling = RUNTIME.runner("dumbbell", ck, build)

    if variants is None:
        points = [np.asarray(prog.variant_idx, np.int32)]
        ecns = [
            np.asarray(prog.ecn, bool)
            if prog.ecn is not None
            else np.zeros(prog.n_flows, bool)
        ]
    else:
        points = [_variant_point(p) for p in variants]
        ecns = [_variant_ecn(p) for p in points]
        for p in points:
            if p.shape != (prog.n_flows,):
                raise ValueError(
                    f"each sweep point assigns all {prog.n_flows} flows "
                    f"(got shape {p.shape})"
                )
    var = jnp.asarray(
        points[0] if n_cfg is None or sweep == "traffic"
        else np.stack(points)
    )
    ecn = jnp.asarray(
        ecns[0] if n_cfg is None or sweep == "traffic"
        else np.stack(ecns)
    )

    carry = (jnp.int32(0), init_state())
    carry = stack_axis(carry, n_cfg)
    carry = shard_replica_axis(
        carry, mesh, r_pad, 0 if n_cfg is None else 1
    )

    # workload params ride as TRACED operands (None = the bulk path);
    # the runner cache key above carries only the traffic shape key
    if traffic_sweep is not None:
        from tpudes.traffic.device import stack_traffic_operands

        if prog.traffic is None or any(
            tp.shape_key() != prog.traffic.shape_key()
            for tp in traffic_sweep
        ):
            raise ValueError(
                "a workload sweep needs prog.traffic set and every "
                "point sharing its traffic shape key (one executable "
                "serves the sweep; pad tables to a common capacity)"
            )
        tr = stack_traffic_operands(traffic_sweep)
    else:
        tr = None if prog.traffic is None else prog.traffic.operands()
    ckpt = checkpoint_ctx(
        checkpoint, engine="dumbbell", key=key, replicas=replicas,
        r_pad=r_pad, n_cfg=n_cfg, obs=obs,
        axis=0 if n_cfg is None else 1, mesh=mesh,
        extra=dumbbell_prog_key(prog)
        + (tuple(tuple(int(i) for i in p) for p in points),
           None if prog.traffic is None else prog.traffic.param_key(),
           None if traffic_sweep is None
           else tuple(tp.param_key() for tp in traffic_sweep)),
    )
    with CompileTelemetry.timed("dumbbell", compiling):
        carry, flush = drive_chunks(
            "dumbbell",
            chunk_bounds(prog.n_slots, chunk_slots or prog.n_slots),
            carry,
            lambda c, t_end: fn(c, key, var, ecn, jnp.int32(t_end), tr),
            obs,
            checkpoint=ckpt,
        )
        if compiling:
            jax.block_until_ready(carry)

    keys = _TCP_FETCH + (_tcp_fetch_obs() if obs else ())
    fetch = {k: carry[1][k] for k in keys}
    finalize = finalize_with_flush(
        flush,
        unstack_points(
            n_cfg, lambda host: _tcp_unpack(host, prog, replicas, obs)
        ),
    )
    if (
        chunk_slots is not None
        and os.environ.get("TPUDES_FUZZ_PLANTED_BUG") == "1"
    ):
        finalize = _planted_divergence(finalize)
    fut = EngineFuture("dumbbell", fetch, finalize)
    return fut.result() if block else fut


# --- trace manifest (tpudes.analysis.jaxpr) --------------------------------

#: canonical tiny replica count for the abstract traces
_TRACE_R = 2


def _trace_prog(**over):
    """Canonical tiny-shape program: 2 flows, short horizon."""
    import dataclasses

    from tpudes.parallel.programs import toy_dumbbell_program

    prog = toy_dumbbell_program(n_flows=2, n_slots=30)
    return dataclasses.replace(prog, **over) if over else prog


def _trace_entries(
    prog: DumbbellProgram, obs: bool = False, scale: bool = True
):
    """The cached-runner functions exactly as ``run_tcp_dumbbell`` jits
    them, with concrete tiny operands.  ``scale=False`` skips the
    JXL007 axis declarations (the axis builders re-enter here)."""
    from tpudes.analysis.jaxpr.spec import TraceEntry

    init_state, fn = build_dumbbell_advance(prog, _TRACE_R, obs=obs)
    key = jax.random.PRNGKey(0)
    var = jnp.asarray(prog.variant_idx, jnp.int32)
    ecn = jnp.asarray(_variant_ecn(np.asarray(prog.variant_idx)))
    carry = (jnp.int32(0), init_state())
    tr = None if prog.traffic is None else prog.traffic.operands()
    traced = {"var": 2, "ecn": 3, "t_end": 4}
    if tr is not None:
        traced["tr"] = 5
    return [
        TraceEntry("init", init_state, (), kernel=False),
        TraceEntry(
            "advance",
            fn,
            (carry, key, var, ecn, jnp.int32(8), tr),
            donate=(0,),
            carry=(0,),
            traced=traced,
            scale_axes=_scale_axes() if scale else (),
        ),
    ]


def _scale_axes():
    """JXL007 scale axis for the dumbbell advance kernel: per-flow
    cwnd/ring state is (R, F) — linear in the flow count, budget
    1.0 (an all-pairs fairness table would fire it)."""
    from tpudes.analysis.jaxpr.spec import ScaleAxis

    from tpudes.parallel.programs import toy_dumbbell_program

    def at(v):
        prog = toy_dumbbell_program(n_flows=int(v), n_slots=30)
        return _trace_entries(prog, scale=False)[1]

    return (
        ScaleAxis(
            "n_flows", at, points=(2, 8), mem_budget=1.0
        ),
    )


def _trace_flips():
    import dataclasses

    from tpudes.analysis.jaxpr.spec import FlipSpec

    base = _trace_prog()

    def flip(**over):
        prog = dataclasses.replace(base, **over)
        return FlipSpec(
            build=lambda p=prog: _trace_entries(p),
            key_differs=dumbbell_prog_key(prog) != dumbbell_prog_key(base),
        )

    from tpudes.traffic import TrafficProgram

    return {
        # live components: each must change some traced program
        "queue_cap": flip(queue_cap=13),
        "ack_lag": flip(ack_lag=7),
        "qdisc": flip(qdisc="red"),
        # a workload program joins the trace (the app-limit gate) and
        # its SHAPE key joins the cache key
        "traffic": flip(
            traffic=TrafficProgram.onoff(2, 300.0, horizon_us=30_000)
        ),
        "obs": FlipSpec(
            build=lambda: _trace_entries(base, obs=True),
            key_differs=True,
        ),
        # excluded-by-design fields must leave every trace identical:
        # the horizon/variant assignment are traced operands, and in
        # fifo mode the RED knobs never reach the program (the JXL004-
        # found dead components)
        "n_slots": flip(n_slots=60),
        "variant_idx": flip(
            variant_idx=np.asarray([3, 5], np.int32)
        ),
        "red_qw": flip(red_qw=0.5),
    }


def trace_manifest():
    """Per-engine trace manifest (see :mod:`tpudes.analysis.jaxpr`)."""
    from tpudes.analysis.jaxpr.spec import TraceManifest, TraceVariant

    return TraceManifest(
        engine="dumbbell",
        path="tpudes/parallel/tcp_dumbbell.py",
        variants=lambda: [
            TraceVariant(
                "base", lambda: _trace_entries(_trace_prog())
            ),
            # the TpudesObs program (FlowMonitor columns + packet ring)
            # joins the lint surface: its ring dynamic_update_slice
            # must pass the registered SparseSite contract (JXL008)
            TraceVariant(
                "obs", lambda: _trace_entries(_trace_prog(), obs=True)
            ),
        ],
        flips=_trace_flips,
    )
