"""Checkpoint/resume for chunked-horizon engine runs.

The PR-5 chunked-horizon restructuring made every engine's state an
explicit ``advance(carry, …, t_end)`` carry — which means the carry
*is* the complete simulation state, and persisting it after each
completed chunk makes a long-horizon study resumable: a run killed at
hour N restarts from its last completed chunk instead of from zero,
and because every step's randomness is ``fold_in(key, t)`` (pure in t,
indifferent to segment boundaries), the resumed run is **bit-equal**
to an uninterrupted one.

Usage (every device engine's ``run_*`` takes ``checkpoint=``, valid
with its ``chunk_*`` argument)::

    run_lte_sm(prog, key, replicas=64, chunk_ttis=1000,
               checkpoint="study.ckpt")
    # ... killed between chunks ...
    run_lte_sm(prog, key, replicas=64, chunk_ttis=1000,
               checkpoint="study.ckpt")   # resumes, finishes bit-equal

Format: one pickle file (atomic tmp+rename) holding the host-fetched
carry tree verbatim, a per-leaf *replica marker* tree (computed at
save time: which leaves carry the padded replica axis at the engine's
replica position), the save-time bucket size, and a fingerprint of
everything the carry's meaning depends on — engine, key bytes, replica
count, config axis, obs mode, and the engine's static program key.
When the resume run's bucket matches the saved one (the common case,
including every ``TPUDES_INFLIGHT`` flip) the carry is restored
verbatim — no axis heuristics at all.  When the bucket CHANGED
(a ``TPUDES_BUCKETING`` flip), only marker-true leaves are resized:
real replica rows are kept and pad rows reconstructed by edge
replication (any valid state row works: replicas are independent and
pad-row results are sliced off at unpack).  The marker is a size match
at the replica position recorded at save time, so a non-replica leaf
whose axis length coincidentally equals the save-time bucket would be
mis-resized on a cross-bucketing resume — the one residual heuristic,
inherited from ``shard_replica_axis``'s identification rule and only
reachable on a bucket change.
The chunk *schedule* (the bounds list) must match between save and
resume — a changed chunk size changes which carries exist, so it is
refused loudly rather than resumed approximately.

Chaos hook: after each save, the ``checkpoint_save`` injection site
fires (tag = engine name), so a seed-keyed
:class:`~tpudes.chaos.ChaosSchedule` can kill the run *between* chunks
— exactly the crash the resume contract is pinned against.
"""

from __future__ import annotations

import hashlib
import os
import pickle
from dataclasses import dataclass

__all__ = ["CarryCheckpoint", "CheckpointError", "checkpoint_ctx"]

_VERSION = 1


class CheckpointError(RuntimeError):
    """A checkpoint file cannot serve this run: fingerprint mismatch
    (different program/key/replicas/obs), a changed chunk schedule, or
    a corrupt/foreign file.  Delete the file (or pass a fresh path) to
    start over."""


def _key_bytes(key) -> bytes:
    import numpy as np

    try:  # new-style typed PRNG keys
        import jax

        return np.asarray(jax.random.key_data(key)).tobytes()
    except (TypeError, ValueError, AttributeError):
        return np.asarray(key).tobytes()


def _tree_map_np(fn, tree):
    """Map ``fn`` over array leaves of a (tuple/list/dict/None) tree —
    structure-preserving, no jax import needed at restore time."""
    if tree is None:
        return None
    if isinstance(tree, dict):
        return {k: _tree_map_np(fn, v) for k, v in tree.items()}
    if isinstance(tree, tuple):
        return tuple(_tree_map_np(fn, v) for v in tree)
    if isinstance(tree, list):
        return [_tree_map_np(fn, v) for v in tree]
    return fn(tree)


def _tree_map2_np(fn, tree, other):
    """Two-tree variant: ``fn(leaf, other_leaf)`` over matching
    positions (structures are identical by construction — the marker
    tree is derived from the carry tree)."""
    if tree is None:
        return None
    if isinstance(tree, dict):
        return {k: _tree_map2_np(fn, v, other[k]) for k, v in tree.items()}
    if isinstance(tree, tuple):
        return tuple(
            _tree_map2_np(fn, v, o) for v, o in zip(tree, other)
        )
    if isinstance(tree, list):
        return [_tree_map2_np(fn, v, o) for v, o in zip(tree, other)]
    return fn(tree, other)


class CarryCheckpoint:
    """One resumable run's persistent carry slot (one file)."""

    def __init__(self, path):
        self.path = str(path)

    def exists(self) -> bool:
        return os.path.exists(self.path)

    def remove(self) -> None:
        if self.exists():
            os.remove(self.path)

    # --- engine-facing protocol (driven by runtime.drive_chunks) ---------

    def save(self, ctx: "_CkptCtx", bound: int, bounds, carry) -> None:
        """Persist the carry after the chunk ending at ``bound``
        (blocks on the device fetch; atomic on the filesystem).  The
        chaos ``checkpoint_save`` site fires AFTER the file is durable,
        so an injected kill always leaves a resumable state."""
        import jax
        import numpy as np

        host = jax.device_get(carry)
        markers = None
        if ctx.r_pad is not None:
            def is_replica_leaf(v):
                a = np.asarray(v)
                return bool(
                    a.ndim > ctx.axis and a.shape[ctx.axis] == ctx.r_pad
                )

            markers = _tree_map_np(is_replica_leaf, host)
        doc = {
            "version": _VERSION,
            "fingerprint": ctx.fingerprint,
            "engine": ctx.engine,
            "bound": int(bound),
            "bounds": [int(b) for b in bounds],
            "replicas": ctx.replicas,
            "r_pad": ctx.r_pad,
            "replica_leaf": markers,
            "carry": host,
        }
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(doc, f)
        os.replace(tmp, self.path)
        from tpudes.obs.serving import ServingTelemetry

        ServingTelemetry.record_checkpoint("save")
        from tpudes.chaos import maybe_fail

        maybe_fail("checkpoint_save", what="checkpoint",
                   tag=ctx.engine)

    def restore(self, ctx: "_CkptCtx", bounds):
        """Load the saved carry for this run, re-padded to the current
        replica bucket; returns ``(done_bound, carry)`` or None when no
        checkpoint exists.  Refuses (CheckpointError) a file whose
        fingerprint or chunk schedule disagrees with this run."""
        if not self.exists():
            return None
        try:
            with open(self.path, "rb") as f:
                doc = pickle.load(f)
        except Exception as e:  # noqa: BLE001 - corrupt file: loud stop
            raise CheckpointError(
                f"{self.path}: unreadable checkpoint ({e})"
            ) from e
        if doc.get("version") != _VERSION:
            raise CheckpointError(
                f"{self.path}: checkpoint version {doc.get('version')} "
                f"!= {_VERSION}"
            )
        if doc.get("fingerprint") != ctx.fingerprint:
            raise CheckpointError(
                f"{self.path}: fingerprint mismatch — this checkpoint "
                "belongs to a different study (program, key, replicas, "
                "sweep points, or obs mode changed)"
            )
        if doc.get("bounds") != [int(b) for b in bounds]:
            raise CheckpointError(
                f"{self.path}: chunk schedule changed "
                f"({doc.get('bounds')} != {[int(b) for b in bounds]}); "
                "resume with the same chunk size or start fresh"
            )
        carry = self._rebucket(doc, ctx)
        if ctx.mesh is not None:
            from tpudes.parallel.runtime import shard_replica_axis

            carry = shard_replica_axis(
                carry, ctx.mesh, ctx.r_pad, ctx.axis
            )
        from tpudes.obs.serving import ServingTelemetry

        ServingTelemetry.record_checkpoint("restore")
        return int(doc["bound"]), carry

    # --- replica-axis normalization --------------------------------------

    def _rebucket(self, doc: dict, ctx: "_CkptCtx"):
        """The saved carry, resized to the CURRENT replica bucket.
        Same bucket (every resume that didn't flip TPUDES_BUCKETING):
        verbatim, zero heuristics.  Changed bucket: only the leaves the
        save-time marker identified as replica-bearing are resized —
        real rows kept, pad rows rebuilt by edge replication (pad rows
        are independent replicas whose results are sliced off at
        unpack, and their PRNG streams are re-derived per-index, so
        any valid state row serves)."""
        import numpy as np

        host = doc["carry"]
        saved_r_pad = doc.get("r_pad")
        if ctx.r_pad == saved_r_pad:
            return host
        if ctx.r_pad is None or saved_r_pad is None:
            raise CheckpointError(
                f"{self.path}: replica-axis presence changed between "
                "save and resume"
            )
        # indices into the saved axis: the real rows, edge-replicated
        # out to the new bucket
        idx = np.minimum(np.arange(ctx.r_pad), ctx.replicas - 1)

        def resize(v, is_replica):
            if not is_replica:
                return v
            return np.take(np.asarray(v), idx, axis=ctx.axis)

        return _tree_map2_np(resize, host, doc["replica_leaf"])


@dataclass
class _CkptCtx:
    """Everything drive_chunks needs to save/restore one run."""

    ckpt: CarryCheckpoint
    engine: str
    fingerprint: str
    replicas: int | None
    r_pad: int | None
    axis: int
    mesh: object = None


def checkpoint_ctx(
    checkpoint,
    *,
    engine: str,
    key,
    replicas: int | None,
    r_pad: int | None,
    n_cfg: int | None,
    obs: bool,
    axis: int,
    mesh=None,
    extra: tuple = (),
) -> _CkptCtx | None:
    """Build the drive_chunks checkpoint context (None passes through).
    ``extra`` is the engine's static identity (its program cache key +
    sweep points): anything that, if changed, would make the saved
    carry mean a different study."""
    if checkpoint is None:
        return None
    ckpt = (
        checkpoint
        if isinstance(checkpoint, CarryCheckpoint)
        else CarryCheckpoint(checkpoint)
    )
    ident = repr((
        engine,
        _key_bytes(key).hex(),
        None if replicas is None else int(replicas),
        None if n_cfg is None else int(n_cfg),
        bool(obs),
        extra,
    ))
    fp = hashlib.sha256(ident.encode()).hexdigest()
    return _CkptCtx(
        ckpt, engine, fp,
        None if replicas is None else int(replicas),
        None if r_pad is None else int(r_pad),
        int(axis), mesh,
    )
