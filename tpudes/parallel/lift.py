"""Scenario lifting at the SimulatorImplementationType seam.

The north-star contract (BASELINE.json): a scenario script opts into the
TPU engine with ONE GlobalValue flip —

    python examples/wifi-bss.py \
        --SimulatorImplementationType=tpudes::JaxSimulatorImpl \
        --JaxReplicas=512

No per-example plumbing: when ``JaxSimulatorImpl.Run`` sees
``JaxReplicas > 0`` it walks the live object graph (NodeList), finds a
scenario shape a registered lowering can represent, lowers it to a
device program (replicated.py / lte_sm.py), and runs every replica on
the accelerator at once.  Graphs no lowering can faithfully represent
fall back to the windowed scalar engine with a loud warning — never a
silent mis-lowering (the round-2 rule).

Reference parity: upstream has no analog — this is the TPU-native
replacement for running 512 separate ns-3 processes; the seam itself is
simulator-impl.{h,cc}'s ObjectFactory (SURVEY.md §1, §7 step 7).
"""

from __future__ import annotations

from tpudes.parallel.replicated import UnliftableScenarioError


def _iter_nodes():
    from tpudes.network.node import NodeList

    for i in range(NodeList.GetNNodes()):
        yield NodeList.GetNode(i)


def _discover_bss(sim_end_s: float):
    """Find an infrastructure-BSS shape (one AP, N STAs, echo clients)
    in the global object graph and lower it."""
    from tpudes.models.applications import UdpEchoClient
    from tpudes.models.wifi.device import WifiNetDevice
    from tpudes.models.wifi.mac import ApWifiMac, StaWifiMac
    from tpudes.parallel.replicated import lower_bss

    aps, stas, clients, stray_clients = [], [], [], 0
    bss_nodes = set()
    for node in _iter_nodes():
        for d in range(node.GetNDevices()):
            dev = node.GetDevice(d)
            if isinstance(dev, WifiNetDevice):
                mac = dev.GetMac()
                if isinstance(mac, ApWifiMac):
                    aps.append(dev)
                    bss_nodes.add(node)
                elif isinstance(mac, StaWifiMac):
                    stas.append(dev)
                    bss_nodes.add(node)
    for node in _iter_nodes():
        for a in range(node.GetNApplications()):
            app = node.GetApplication(a)
            if isinstance(app, UdpEchoClient):
                if node in bss_nodes:
                    clients.append(app)
                else:
                    stray_clients += 1
    if len(aps) != 1 or not stas:
        raise UnliftableScenarioError(
            f"not an infrastructure BSS (found {len(aps)} APs, "
            f"{len(stas)} STAs)"
        )
    if stray_clients:
        # a client on a non-BSS node (mixed wired/wireless topology)
        # would be silently dropped by the lowering — refuse instead
        raise UnliftableScenarioError(
            f"{stray_clients} echo client(s) live on non-BSS nodes; the "
            "replica axis models only the BSS traffic"
        )
    from tpudes.core.global_value import GlobalValue

    prog = lower_bss(
        stas, aps[0], clients, sim_end_s,
        geom_stride=int(GlobalValue.GetValue("JaxGeomStride")),
    )
    prog = _attach_bss_traffic(prog)
    return "bss", prog, lambda: None


def _attach_bss_traffic(prog):
    """The ISSUE-14 one-flip seam: ``--JaxTrafficModel=<model>`` swaps
    the lowered BSS program's STA arrivals onto the device traffic
    stage at the echo apps' mean rate (the AP's beacon process stays
    cbr); ``off`` returns the program untouched — the bit-identical
    legacy compile."""
    import dataclasses

    import numpy as np

    from tpudes.core.global_value import GlobalValue

    model = str(GlobalValue.GetValue("JaxTrafficModel"))
    if model == "off":
        return prog
    from tpudes.traffic import TrafficProgram

    seed = int(GlobalValue.GetValue("JaxTrafficSeed"))
    n, horizon = prog.n, prog.sim_end_us
    sta_iv = prog.interval_us[1:].astype(np.int64)
    rate = float(
        np.mean(np.where(sta_iv >= 2**29, 0.0, 1e6 / np.maximum(sta_iv, 1)))
    )
    if model == "cbr":
        tp = TrafficProgram.cbr(prog.start_us, prog.interval_us)
    elif model == "mmpp":
        tp = TrafficProgram.mmpp(
            n, rate, horizon_us=horizon, epoch_s=0.05,
            start_us=prog.start_us, tr_seed=seed,
        )
    elif model == "onoff":
        tp = TrafficProgram.onoff(
            n, rate / 0.4, horizon_us=horizon, on=(1.5, 0.05, 0.5),
            off_mean_s=0.15, start_us=prog.start_us, tr_seed=seed,
        )
    elif model == "trace":
        # a deterministic synthetic trace at the apps' mean rate (the
        # stand-in until a pcap/CSV ingester lands — ROADMAP item 4
        # remainder).  The span clamps at 0: an app starting past the
        # horizon gets a constant (never-firing) row, not a descending
        # one trace_replay would reject
        k = max(4, int(rate * (horizon - int(prog.start_us[1:].min()))
                       / 1e6))
        span = np.maximum(
            horizon - prog.start_us[:, None].astype(np.int64), 0
        )
        grid = np.sort(
            (np.linspace(0.02, 0.98, k)[None, :] * span
             + prog.start_us[:, None]).astype(np.int64),
            axis=1,
        )
        tp = TrafficProgram.trace_replay(grid)
    else:
        raise ValueError(
            f"JaxTrafficModel={model!r}: pick off|cbr|mmpp|onoff|trace"
        )
    tp = tp.with_cbr_rows(
        np.arange(n) == 0, int(prog.interval_us[0]),
        int(prog.start_us[0]),
    )
    return dataclasses.replace(prog, traffic=tp)


def _discover_lte_sm(sim_end_s: float):
    """Find a full-buffer LTE shape (eNBs with a TTI controller) and
    lower it to the device-resident SM engine."""
    from types import SimpleNamespace

    from tpudes.models.lte.device import LteEnbNetDevice
    from tpudes.parallel.lte_sm import (
        UnliftableLteScenarioError,
        lower_lte_sm,
    )

    controller = None
    for node in _iter_nodes():
        for d in range(node.GetNDevices()):
            dev = node.GetDevice(d)
            if isinstance(dev, LteEnbNetDevice) and dev.controller is not None:
                controller = dev.controller
                break
        if controller is not None:
            break
    if controller is None:
        raise UnliftableScenarioError("no LTE eNB devices in the graph")
    try:
        from tpudes.core.global_value import GlobalValue

        prog = lower_lte_sm(
            SimpleNamespace(controller=controller), sim_end_s,
            geom_stride=int(GlobalValue.GetValue("JaxGeomStride")),
        )
    except UnliftableLteScenarioError as e:
        raise UnliftableScenarioError(str(e)) from e

    def commit():
        # the controller's own TTI events must not ALSO run the scenario;
        # armed only after the device run succeeds, so a failed run (OOM,
        # backend error) leaves the host path fully functional
        controller.lifted = True

    return "lte_sm", prog, commit


def _discover_dumbbell(sim_end_s: float):
    """Find a TCP dumbbell (bulk flows over one router-router
    bottleneck) and lower it to the packet-slot program."""
    from tpudes.parallel.tcp_dumbbell import (
        UnliftableDumbbellError,
        lower_dumbbell,
    )

    try:
        prog = lower_dumbbell(sim_end_s)
    except UnliftableDumbbellError as e:
        raise UnliftableScenarioError(str(e)) from e
    return "dumbbell", prog, lambda: None


def _discover_as_flows(sim_end_s: float):
    """Find a routed p2p topology carrying sparse CBR UDP flows (the
    config-#5 shape) and lower it to the flow-level device engine."""
    from tpudes.parallel.as_flows import UnliftableAsError, lower_as_flows

    try:
        prog = lower_as_flows(sim_end_s)
    except UnliftableAsError as e:
        raise UnliftableScenarioError(str(e)) from e
    return "as_flows", prog, lambda: None


#: discovery order: most specific first (as_flows last — it accepts the
#: most generic shape, any routed p2p graph with CBR UDP clients)
LOWERINGS = [_discover_lte_sm, _discover_dumbbell, _discover_bss, _discover_as_flows]


def lift(sim_end_s: float):
    """Try every registered lowering; returns ``(kind, program, commit)``
    — ``commit()`` is called by the engine after the device run succeeds
    (it disarms any host-side duplicate of the scenario) — or raises
    UnliftableScenarioError with every reason collected."""
    reasons = []
    for discover in LOWERINGS:
        try:
            return discover(sim_end_s)
        except UnliftableScenarioError as e:
            reasons.append(f"{discover.__name__}: {e}")
    raise UnliftableScenarioError("; ".join(reasons))


def run_lifted(kind: str, prog, replicas: int, key=None, mesh=None):
    """Execute a lifted program on the replica axis.

    ``mesh=None`` auto-selects: a 1-axis replica mesh over all local
    devices when more than one is visible and divides ``replicas``.
    Returns the program's per-replica outcome dict (see
    run_replicated_bss / run_lte_sm).
    """
    import jax

    if key is None:
        from tpudes.core.rng import RngSeedManager

        key = jax.random.PRNGKey(
            (RngSeedManager.GetSeed() * 2654435761 + RngSeedManager.GetRun())
            & 0x7FFFFFFF
        )
    if mesh is None:
        import math

        n_dev = len(jax.devices())
        n_use = math.gcd(replicas, n_dev)
        if n_use > 1:
            from tpudes.parallel.mesh import replica_mesh

            mesh = replica_mesh(n_use)
        if 1 < n_use < n_dev or (n_use == 1 < n_dev and replicas > 1):
            import warnings

            warnings.warn(
                f"JaxReplicas={replicas} is not divisible by the "
                f"{n_dev} visible devices; running on {n_use} — "
                f"pick a multiple of {n_dev} to use the whole mesh",
                RuntimeWarning,
                stacklevel=2,
            )
    if kind == "bss":
        from tpudes.parallel.replicated import run_replicated_bss

        return run_replicated_bss(prog, replicas, key, mesh=mesh)
    if kind == "lte_sm":
        from tpudes.parallel.lte_sm import run_lte_sm

        return run_lte_sm(prog, key, replicas=replicas, mesh=mesh)
    if kind == "dumbbell":
        from tpudes.parallel.tcp_dumbbell import run_tcp_dumbbell

        return run_tcp_dumbbell(prog, key, replicas=replicas, mesh=mesh)
    if kind == "as_flows":
        from tpudes.parallel.as_flows import run_as_flows

        return run_as_flows(prog, key, replicas=replicas, mesh=mesh)
    raise ValueError(f"unknown lifted program kind {kind!r}")
