"""Workload observability: what the traffic stage offered vs delivered.

The traffic subsystem (``tpudes.traffic``) moves the workload INSIDE
the compiled engines; :class:`TrafficTelemetry` is the process-wide
accounting of what each engine's workload offered and what the engine
delivered — so bench rows and interactive sessions can SAY which model
family ran, how bursty it was, and how much of the offered load
survived:

- ``offered`` / ``delivered`` — load accounting per engine (bits for
  the LTE backlog engine, packets for the arrival engines; one unit
  per engine, named in ``unit``);
- ``runs`` / ``models`` — per-model launch counts (the draw-count
  axis: how often each model id was dispatched);
- ``duty`` — mean ON share of launched ON-OFF workloads (burst duty
  cycle).

Follows the :class:`tpudes.obs.geometry.GeomTelemetry` shape:
recording is a dict update, snapshots computed on demand, reset
explicit.  ``python -m tpudes.obs --traffic metrics.json`` is the
schema gate.
"""

from __future__ import annotations

__all__ = ["TrafficTelemetry", "validate_traffic_metrics"]


class TrafficTelemetry:
    """Process-wide workload counters, per engine."""

    _engines: dict[str, dict] = {}

    @classmethod
    def _engine(cls, engine: str) -> dict:
        return cls._engines.setdefault(
            engine,
            {
                "offered": 0.0, "delivered": 0.0, "runs": 0,
                "models": {}, "duty_sum": 0.0, "duty_n": 0,
                "unit": "packets",
            },
        )

    @classmethod
    def record(
        cls, engine: str, model: str, *, offered: float,
        delivered: float, unit: str = "bits", duty: float | None = None,
    ) -> None:
        e = cls._engine(engine)
        e["offered"] += float(offered)
        e["delivered"] += float(delivered)
        e["runs"] += 1
        e["unit"] = unit
        e["models"][model] = e["models"].get(model, 0) + 1
        if duty is not None:
            e["duty_sum"] += float(duty)
            e["duty_n"] += 1

    @classmethod
    def snapshot(cls) -> dict:
        engines = {}
        for name, e in sorted(cls._engines.items()):
            engines[name] = {
                "offered": round(e["offered"], 3),
                "delivered": round(e["delivered"], 3),
                "delivered_frac": (
                    round(min(e["delivered"] / e["offered"], 1.0), 4)
                    if e["offered"] > 0
                    else 0.0
                ),
                "runs": e["runs"],
                "models": dict(e["models"]),
                "burst_duty": (
                    round(e["duty_sum"] / e["duty_n"], 4)
                    if e["duty_n"] > 0
                    else None
                ),
                "unit": e["unit"],
            }
        return {"version": 1, "engines": engines}

    @classmethod
    def engine(cls, engine: str) -> dict:
        return dict(cls._engine(engine))

    @classmethod
    def reset(cls) -> None:
        cls._engines = {}


def validate_traffic_metrics(doc) -> list[str]:
    """Schema check for a :meth:`TrafficTelemetry.snapshot` document
    (dependency-free, mirroring ``validate_geometry_metrics``).
    Returns human-readable problems; empty means valid."""
    from tpudes.obs.schema import make_need

    problems: list[str] = []
    need = make_need(problems)

    if not isinstance(doc, dict):
        return ["top level: not a JSON object"]
    if doc.get("version") != 1:
        problems.append("version: expected 1")
    engines = need(doc, "engines", dict, "top level")
    if engines is not None:
        for name, e in engines.items():
            where = f"engines.{name}"
            offered = need(e, "offered", (int, float), where)
            delivered = need(e, "delivered", (int, float), where)
            frac = need(e, "delivered_frac", (int, float), where)
            runs = need(e, "runs", int, where)
            models = need(e, "models", dict, where)
            need(e, "unit", str, where)
            for k, v in (("offered", offered), ("delivered", delivered)):
                if isinstance(v, (int, float)) and v < 0:
                    problems.append(f"{where}.{k}: negative")
            if isinstance(runs, int) and runs < 0:
                problems.append(f"{where}.runs: negative")
            if isinstance(frac, (int, float)) and not (
                0.0 <= float(frac) <= 1.0
            ):
                problems.append(f"{where}.delivered_frac: outside [0, 1]")
            if isinstance(models, dict):
                total = 0
                for m, c in models.items():
                    if not isinstance(c, int) or c < 0:
                        problems.append(
                            f"{where}.models.{m}: not a count"
                        )
                    else:
                        total += c
                if isinstance(runs, int) and total != runs:
                    problems.append(
                        f"{where}: model counts sum {total} != runs "
                        f"{runs}"
                    )
            duty = e.get("burst_duty")
            if duty is not None and (
                not isinstance(duty, (int, float))
                or not (0.0 <= float(duty) <= 1.0)
            ):
                problems.append(f"{where}.burst_duty: outside [0, 1]")
    return problems
