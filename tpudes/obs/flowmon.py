"""Device-resident FlowMonitor: per-flow KPI columns + packet rings.

The host engines measure flows through
:class:`tpudes.models.flow_monitor.FlowMonitor` riding the Ipv4 trace
sources; the device engines cannot fire per-packet callbacks — their
whole point is that the hot loop never leaves the accelerator.  This
module is the device-side equivalent, split in two:

- **In-kernel accumulators** (:func:`flow_carry`,
  :func:`flow_accumulate`, :func:`flow_ring_write`): per-flow FlowStats
  columns that ride the scan carry — tx/rx packets+bytes, RFC-3550
  delay/jitter sums, loss, a fixed-bin delay histogram — plus a bounded
  packet-event ring ``(step, t_us, flow, size, verdict)`` recycled
  modularly by the engine's step counter.  All updates are DENSE
  (one-hot / where forms); the single sparse op is the ring's
  ``dynamic_update_slice``, registered as a machine-checked
  ``SparseSite`` contract per engine (JXL008) rather than a gate
  exemption.  The columns only exist when ``TpudesObs=1`` — a disabled
  run compiles the exact pre-obs program (pinned in tests/test_obs.py).
- **Host-side reduction** (:func:`decode_packet_rings`,
  :func:`reduce_flow_stats`, :class:`DeviceFlowMonitor`): turn the
  fetched columns/ring snapshots into the same
  :class:`~tpudes.models.flow_monitor.FlowStats` objects the host
  monitor produces, export them through the ONE shared XML serializer
  (:func:`serialize_flow_stats_xml` — ``FlowMonitor.SerializeToXmlFile``
  calls it too), and emit the ring's delivered packets as a classic
  libpcap file in the ``network/trace_helper`` frame format, so
  ``traffic/ingest.read_pcap`` round-trips a device run straight back
  into a trace-replay :class:`~tpudes.traffic.TrafficProgram`.

Accumulation semantics match the host monitor's callbacks
(``_on_send`` / ``_on_deliver``) with one documented coarsening: the
engines are step-synchronous, so when a flow delivers more than one
packet in a single step the step contributes ONE delay observation
(the per-step mean) to the jitter chain instead of one per packet.
The pure-NumPy :func:`host_reference_stats` oracle applies the
identical rule, and tests/test_flowmon.py additionally pins
:func:`flow_accumulate` bit-for-bit against a live
:class:`~tpudes.models.flow_monitor.FlowMonitor` on a shared
one-packet-per-step event sequence, where the rules coincide exactly.

Counters are ``int32`` (JXL002 dtype discipline): byte sums overflow
past ~2.1 GB per flow — far beyond the chunked horizons the engines
run, and the reducer checks for saturation loudly.
"""

from __future__ import annotations

import struct
from typing import NamedTuple

import numpy as np

from tpudes.models.flow_monitor import FiveTuple, FlowStats

__all__ = [
    "FLOW_DELAY_BINS",
    "FLOW_RING_CAP",
    "FM_KEYS",
    "RING_COLS",
    "VERDICT_TX",
    "VERDICT_RX",
    "VERDICT_DROP",
    "DeviceFlowMonitor",
    "PacketEvent",
    "decode_packet_rings",
    "flow_accumulate",
    "flow_carry",
    "flow_ring_write",
    "host_reference_stats",
    "reduce_flow_stats",
    "serialize_flow_stats_xml",
    "validate_flowmon_xml",
    "validate_pcap",
    "write_events_pcap",
]

#: fixed-bin delay histogram width (per-flow column ``fm_hist``)
FLOW_DELAY_BINS = 16
#: packet-event ring capacity — one slot per engine step, recycled
#: modularly; a chunk no longer than this fetches a COMPLETE event log
#: at every chunk boundary (the ChunkStream overlap path)
FLOW_RING_CAP = 512
#: ring row layout: (step, t_us, flow, size, verdict)
RING_COLS = 5

VERDICT_TX = 0
VERDICT_RX = 1
VERDICT_DROP = 2

#: the carry/fetch keys :func:`flow_carry` creates — the engines fetch
#: exactly this set (order is the stable fetch order)
FM_KEYS = (
    "fm_tx", "fm_txb", "fm_rx", "fm_rxb", "fm_lost",
    "fm_dsum", "fm_jsum", "fm_dlast", "fm_t0", "fm_t1",
    "fm_hist", "fm_ring",
)


class PacketEvent(NamedTuple):
    """One decoded ring row (µs timestamp, ns-3 trace verdict)."""

    step: int
    t_us: int
    flow: int
    size: int
    verdict: int


# --- in-kernel accumulators (jax.numpy; imported lazily so the host
# ---  layers can use the reducer without jax present) ----------------


def flow_carry(n_flows: int, lead: tuple = (), ring_cap: int = FLOW_RING_CAP):
    """The obs-only carry extension: per-flow FlowStats columns plus
    the packet-event ring, all zero/sentinel-initialised.

    ``lead`` prefixes every column with batch axes (the engine's
    replica layout, e.g. ``(R,)`` or LTE's ``(1,)`` row convention).
    Sentinels: ``fm_dlast``/``fm_t0``/``fm_t1`` start at ``-1.0`` (no
    observation yet — the host monitor's ``None``), ring rows start at
    step ``-1`` (never written)."""
    import jax.numpy as jnp

    F = int(n_flows)
    z = lambda *s: jnp.zeros(lead + s, jnp.int32)  # noqa: E731
    zf = lambda *s: jnp.zeros(lead + s, jnp.float32)  # noqa: E731
    return dict(
        fm_tx=z(F),
        fm_txb=z(F),
        fm_rx=z(F),
        fm_rxb=z(F),
        fm_lost=z(F),
        fm_dsum=zf(F),
        fm_jsum=zf(F),
        fm_dlast=zf(F) - 1.0,
        fm_t0=zf(F) - 1.0,
        fm_t1=zf(F) - 1.0,
        fm_hist=z(F, FLOW_DELAY_BINS),
        fm_ring=jnp.full(
            lead + (int(FLOW_RING_CAP), RING_COLS), -1, jnp.int32
        ),
    )


def flow_accumulate(
    fm: dict,
    *,
    t_s,
    tx,
    tx_bytes,
    rx,
    rx_bytes,
    delay_s,
    lost,
    bin_width_s: float,
):
    """One step of FlowStats accumulation over the ``fm_*`` columns
    (dense — no gather/scatter; the ring write is separate).

    All operands broadcast against the ``(..., F)`` columns: ``tx``/
    ``rx``/``lost`` are this step's per-flow packet counts, ``*_bytes``
    the matching byte counts, ``delay_s`` the per-flow delay of this
    step's deliveries (ignored where ``rx == 0``), ``t_s`` the current
    sim time in seconds.  Jitter is the RFC-3550 accumulation the host
    monitor runs (|delay - last_delay|), with one observation per
    (step, flow) — see the module docstring for the multi-packet
    coarsening rule."""
    import jax.numpy as jnp

    got = rx > 0
    seen = fm["fm_dlast"] >= 0.0
    delay_s = jnp.asarray(delay_s, jnp.float32)
    t_s = jnp.asarray(t_s, jnp.float32)
    bins = jnp.clip(
        (delay_s / jnp.float32(bin_width_s)).astype(jnp.int32),
        0,
        FLOW_DELAY_BINS - 1,
    )
    one_hot = (
        bins[..., None]
        == jnp.arange(FLOW_DELAY_BINS, dtype=jnp.int32)
    ).astype(jnp.int32)
    out = dict(fm)
    out["fm_tx"] = fm["fm_tx"] + tx.astype(jnp.int32)
    out["fm_txb"] = fm["fm_txb"] + tx_bytes.astype(jnp.int32)
    out["fm_rx"] = fm["fm_rx"] + rx.astype(jnp.int32)
    out["fm_rxb"] = fm["fm_rxb"] + rx_bytes.astype(jnp.int32)
    out["fm_lost"] = fm["fm_lost"] + lost.astype(jnp.int32)
    out["fm_dsum"] = fm["fm_dsum"] + delay_s * rx.astype(jnp.float32)
    out["fm_jsum"] = fm["fm_jsum"] + jnp.where(
        got & seen, jnp.abs(delay_s - fm["fm_dlast"]), 0.0
    )
    out["fm_dlast"] = jnp.where(got, delay_s, fm["fm_dlast"])
    out["fm_t0"] = jnp.where(
        (tx > 0) & (fm["fm_t0"] < 0.0), t_s, fm["fm_t0"]
    )
    out["fm_t1"] = jnp.where(got, t_s, fm["fm_t1"])
    out["fm_hist"] = fm["fm_hist"] + one_hot * rx.astype(jnp.int32)[..., None]
    return out


def flow_ring_write(ring, counter, row):
    """Write this step's event ``row`` at ring slot ``counter % CAP``
    (modular recycling).  ``ring`` is ``(..., CAP, COLS)``, ``row`` the
    matching ``(..., COLS)`` int32 vector (step ``-1`` = no event this
    step — the slot is still overwritten, so a slot always describes
    the LAST step that owned it).

    This is the subsystem's one sparse op: a
    ``jax.lax.dynamic_update_slice`` whose start index is the modular
    step counter — registered per engine as a ``SparseSite`` contract
    (mode ``clip``, provenance operand+mod) in
    ``analysis/jaxpr/sparse_registry.py``.  ``.at[].set`` is avoided
    deliberately: it may lower to scatter, which the no-gather engines
    ban outright."""
    import jax
    import jax.numpy as jnp

    idx = jnp.asarray(counter, jnp.int32) % jnp.int32(ring.shape[-2])
    starts = tuple(jnp.int32(0) for _ in range(ring.ndim - 2)) + (
        idx,
        jnp.int32(0),
    )
    return jax.lax.dynamic_update_slice(
        ring, row.astype(jnp.int32)[..., None, :], starts
    )


# --- host-side reduction ---------------------------------------------


def decode_packet_rings(rings) -> list[PacketEvent]:
    """Merge ring snapshots (one per chunk boundary) into one event
    list, sorted by step and deduped on the step column (unique per
    event — every engine stamps rows with its monotonic step counter,
    so the same event fetched at two chunk boundaries collapses).

    Each snapshot is a ``(CAP, COLS)`` array slice (pick the replica /
    config lane before calling); rows with step ``< 0`` are empty
    slots.  Snapshots may arrive flipped or rotated — order inside a
    ring is irrelevant, the step column is the total order."""
    by_step: dict[int, PacketEvent] = {}
    for ring in rings:
        arr = np.asarray(ring)
        if arr.ndim != 2 or arr.shape[-1] != RING_COLS:
            raise ValueError(
                f"ring snapshot must be (cap, {RING_COLS}), got "
                f"{arr.shape} — slice the replica lane first"
            )
        for r in arr[arr[:, 0] >= 0]:
            by_step[int(r[0])] = PacketEvent(*(int(v) for v in r))
    return [by_step[s] for s in sorted(by_step)]


def reduce_flow_stats(fm: dict) -> dict[int, FlowStats]:
    """Fetched ``fm_*`` columns (leaves sliced to ``(F,)`` /
    ``(F, BINS)``) → host :class:`FlowStats`, flow ids 1-based as
    upstream's classifier assigns them.  Flows with no activity are
    omitted (the host monitor only materialises a flow on its first
    packet)."""
    tx = np.asarray(fm["fm_tx"]).reshape(-1)
    if (tx == np.iinfo(np.int32).max).any():
        raise ValueError(
            "fm_tx saturated int32 — shorten the horizon or shard flows"
        )
    F = tx.shape[0]
    get = lambda k: np.asarray(fm[k]).reshape(F, -1).squeeze(-1)  # noqa: E731
    rx = get("fm_rx")
    lost = get("fm_lost")
    dlast = np.asarray(fm["fm_dlast"], np.float64).reshape(-1)
    t0 = np.asarray(fm["fm_t0"], np.float64).reshape(-1)
    t1 = np.asarray(fm["fm_t1"], np.float64).reshape(-1)
    out: dict[int, FlowStats] = {}
    for i in range(F):
        if tx[i] == 0 and rx[i] == 0 and lost[i] == 0:
            continue
        out[i + 1] = FlowStats(
            tx_packets=int(tx[i]),
            tx_bytes=int(get("fm_txb")[i]),
            rx_packets=int(rx[i]),
            rx_bytes=int(get("fm_rxb")[i]),
            lost_packets=int(lost[i]),
            delay_sum_s=float(np.asarray(fm["fm_dsum"])[i]),
            jitter_sum_s=float(np.asarray(fm["fm_jsum"])[i]),
            last_delay_s=float(dlast[i]) if dlast[i] >= 0 else None,
            time_first_tx_s=float(t0[i]) if t0[i] >= 0 else None,
            time_last_rx_s=float(t1[i]) if t1[i] >= 0 else None,
        )
    return out


def host_reference_stats(
    steps, n_flows: int | None = None
) -> dict[int, FlowStats]:
    """Pure-NumPy reference accumulator: the host monitor's
    ``_on_send`` / ``_on_deliver`` / ``_on_drop`` arithmetic applied to
    a per-step event stream, under the same one-observation-per-
    (step, flow) jitter rule the device columns use.  ``steps`` is an
    iterable of dicts with keys ``t_s`` and per-flow arrays ``tx``,
    ``tx_bytes``, ``rx``, ``rx_bytes``, ``delay_s``, ``lost`` (exactly
    :func:`flow_accumulate`'s operands) — the oracle the device columns
    are validated against per engine."""
    stats: dict[int, FlowStats] = {}
    last: dict[int, float] = {}
    for ev in steps:
        t_s = float(ev["t_s"])
        F = len(np.atleast_1d(ev["tx"])) if n_flows is None else n_flows
        for i in range(F):
            tx = int(np.atleast_1d(ev["tx"])[i])
            rx = int(np.atleast_1d(ev["rx"])[i])
            lost = int(np.atleast_1d(ev.get("lost", np.zeros(F)))[i])
            if tx == 0 and rx == 0 and lost == 0:
                continue
            st = stats.setdefault(i + 1, FlowStats())
            st.tx_packets += tx
            st.tx_bytes += int(np.atleast_1d(ev["tx_bytes"])[i])
            st.lost_packets += lost
            if tx and st.time_first_tx_s is None:
                st.time_first_tx_s = t_s
            if rx:
                delay = float(np.atleast_1d(ev["delay_s"])[i])
                st.rx_packets += rx
                st.rx_bytes += int(np.atleast_1d(ev["rx_bytes"])[i])
                st.delay_sum_s += delay * rx
                if i + 1 in last:
                    st.jitter_sum_s += abs(delay - last[i + 1])
                last[i + 1] = delay
                st.last_delay_s = delay
                st.time_last_rx_s = t_s
    return stats


# --- export: the ONE XML serializer + pcap emission ------------------


def serialize_flow_stats_xml(
    stats: dict[int, FlowStats],
    flows: dict[FiveTuple, int],
    filename: str,
) -> None:
    """flow-monitor.cc ``SerializeToXmlFile``: the standard FlowMonitor
    XML shape (attribute names match upstream's parser ecosystem).
    Shared by the host monitor and :class:`DeviceFlowMonitor` — one
    serializer, two producers (REG001 trace-name parity)."""
    with open(filename, "w") as f:
        f.write("<?xml version=\"1.0\" ?>\n<FlowMonitor>\n  <FlowStats>\n")
        for fid, st in sorted(stats.items()):
            f.write(
                f'    <Flow flowId="{fid}" '
                f'txPackets="{st.tx_packets}" txBytes="{st.tx_bytes}" '
                f'rxPackets="{st.rx_packets}" rxBytes="{st.rx_bytes}" '
                f'lostPackets="{st.lost_packets}" '
                f'delaySum="+{st.delay_sum_s * 1e9:.0f}ns" '
                f'jitterSum="+{st.jitter_sum_s * 1e9:.0f}ns" />\n'
            )
        f.write("  </FlowStats>\n  <Ipv4FlowClassifier>\n")
        for t, fid in (flows or {}).items():
            f.write(
                f'    <Flow flowId="{fid}" sourceAddress="{t.source}" '
                f'destinationAddress="{t.destination}" '
                f'protocol="{t.protocol}" sourcePort="{t.source_port}" '
                f'destinationPort="{t.destination_port}" />\n'
            )
        f.write("  </Ipv4FlowClassifier>\n</FlowMonitor>\n")


def write_events_pcap(
    events,
    filename: str,
    *,
    verdicts=(VERDICT_RX,),
    data_link_type: int | None = None,
    snap_len: int = 65535,
) -> int:
    """Emit decoded ring events as a classic libpcap file in the
    ``network/trace_helper`` frame format (same magic/version/record
    layout as :class:`~tpudes.network.trace_helper.PcapFileWrapper`),
    so the device run opens in tcpdump/wireshark and — the round trip
    this repo cares about — ``traffic/ingest.read_pcap`` reads it back
    into a trace-replay table.

    The device rings carry sizes, not payload bytes, so frames are
    zero-filled and capped at ``snap_len`` while the record header
    keeps the ORIGINAL length — exactly what ``read_pcap`` returns, so
    the round trip is lossless on (µs time, wire bytes).  Returns the
    record count."""
    from tpudes.network.trace_helper import (
        DLT_RAW,
        PCAP_MAGIC,
        PCAP_VERSION,
    )

    dlt = DLT_RAW if data_link_type is None else int(data_link_type)
    n = 0
    with open(filename, "wb") as f:
        f.write(
            struct.pack(
                "<IHHiIII",
                PCAP_MAGIC, PCAP_VERSION[0], PCAP_VERSION[1],
                0, 0, snap_len, dlt,
            )
        )
        for ev in events:
            if ev.verdict not in verdicts:
                continue
            sec, usec = divmod(int(ev.t_us), 1_000_000)
            cap = min(int(ev.size), snap_len)
            f.write(
                struct.pack("<IIII", sec, usec, cap, int(ev.size))
                + b"\x00" * cap
            )
            n += 1
    return n


def validate_flowmon_xml(text: str) -> tuple[list, int]:
    """Schema-check a FlowMonitor XML document (the shared serializer's
    output, or upstream ns-3's — same attribute ecosystem).  Returns
    ``(problems, n_flows)``; empty problems = valid.  Messages are
    actionable: they name the element, the attribute and what to fix."""
    import xml.etree.ElementTree as ET

    problems: list[str] = []
    try:
        root = ET.fromstring(text)
    except ET.ParseError as e:
        return [f"not well-formed XML ({e}) — is this a FlowMonitor "
                "SerializeToXmlFile output?"], 0
    if root.tag != "FlowMonitor":
        return [f"root element is <{root.tag}>, expected <FlowMonitor> "
                "(SerializeToXmlFile writes <FlowMonitor> at top level)"], 0
    stats = root.find("FlowStats")
    if stats is None:
        return ["missing <FlowStats> section under <FlowMonitor>"], 0
    int_attrs = ("txPackets", "txBytes", "rxPackets", "rxBytes",
                 "lostPackets")
    ns_attrs = ("delaySum", "jitterSum")
    seen_ids: set = set()
    n = 0
    for i, flow in enumerate(stats.findall("Flow")):
        n += 1
        where = f"FlowStats/Flow[{i}]"
        fid = flow.get("flowId")
        if fid is None:
            problems.append(f"{where}: missing flowId attribute")
        elif fid in seen_ids:
            problems.append(f"{where}: duplicate flowId {fid}")
        else:
            seen_ids.add(fid)
        for a in int_attrs:
            v = flow.get(a)
            if v is None:
                problems.append(f"{where}: missing {a} attribute")
            elif not v.lstrip("-").isdigit():
                problems.append(
                    f"{where}: {a}={v!r} is not an integer"
                )
            elif int(v) < 0:
                problems.append(f"{where}: {a}={v} is negative")
        for a in ns_attrs:
            v = flow.get(a)
            if v is None:
                problems.append(f"{where}: missing {a} attribute")
            elif not (v.startswith("+") and v.endswith("ns")):
                problems.append(
                    f"{where}: {a}={v!r} must be '+<nanoseconds>ns' "
                    "(upstream ns-3 Time serialization)"
                )
    for i, flow in enumerate(
        root.findall("Ipv4FlowClassifier/Flow")
    ):
        where = f"Ipv4FlowClassifier/Flow[{i}]"
        for a in ("flowId", "sourceAddress", "destinationAddress"):
            if flow.get(a) is None:
                problems.append(f"{where}: missing {a} attribute")
    return problems, n


#: pcapng section-header magic — a different container format
_PCAPNG_MAGIC = 0x0A0D0D0A
#: classic-pcap magic accepted in either byte order, µs or ns ticks
_PCAP_MAGICS = (0xA1B2C3D4, 0xA1B23C4D)


def validate_pcap(data: bytes) -> tuple[list, int]:
    """Structurally validate a classic libpcap capture: both byte
    orders, both the microsecond and nanosecond magic.  Returns
    ``(problems, n_records)``.  Walks every record header and checks it
    against the remaining bytes, so a truncated or corrupt file names
    the exact offset."""
    if len(data) < 24:
        return [f"file is {len(data)} bytes — a pcap global header is "
                "24 bytes; not a capture file"], 0
    (magic,) = struct.unpack("<I", data[:4])
    if magic == _PCAPNG_MAGIC or struct.unpack(">I", data[:4])[0] == _PCAPNG_MAGIC:
        return ["pcapng container, not classic pcap — convert with "
                "`tcpdump -r in.pcapng -w out.pcap` or read with a "
                "pcapng-aware tool"], 0
    endian = None
    for e in ("<", ">"):
        (m,) = struct.unpack(e + "I", data[:4])
        if m in _PCAP_MAGICS:
            endian = e
            magic = m
            break
    if endian is None:
        return [f"unknown magic 0x{magic:08X} — expected classic pcap "
                "0xA1B2C3D4 (µs) or 0xA1B23C4D (ns) in either byte "
                "order"], 0
    ver_major, ver_minor, _tz, _sig, snap_len, _dlt = struct.unpack(
        endian + "HHiIII", data[4:24]
    )
    problems: list[str] = []
    if ver_major != 2:
        problems.append(
            f"version {ver_major}.{ver_minor} — classic pcap is 2.x"
        )
    if snap_len == 0:
        problems.append("snap_len is 0 — every record would be empty")
    off = 24
    n = 0
    while off < len(data):
        if off + 16 > len(data):
            problems.append(
                f"truncated record header at byte {off} "
                f"({len(data) - off} bytes left, need 16)"
            )
            break
        _sec, _sub, cap, orig = struct.unpack(
            endian + "IIII", data[off:off + 16]
        )
        if cap > snap_len:
            problems.append(
                f"record {n} at byte {off}: incl_len {cap} exceeds "
                f"snap_len {snap_len}"
            )
            break
        if cap > orig:
            problems.append(
                f"record {n} at byte {off}: incl_len {cap} exceeds "
                f"orig_len {orig}"
            )
        if off + 16 + cap > len(data):
            problems.append(
                f"record {n} at byte {off}: declares {cap} payload "
                f"bytes but only {len(data) - off - 16} remain "
                "(truncated capture)"
            )
            break
        off += 16 + cap
        n += 1
    return problems, n


class DeviceFlowMonitor:
    """Host wrapper over one lane's reduced device telemetry: the same
    reporting surface the host :class:`FlowMonitor` exposes
    (``GetFlowStats`` / ``SerializeToXmlFile``) plus the device-only
    exports (pcap, trace-replay round trip).

    ``five_tuples`` optionally names each flow id's classifier tuple
    for the XML's Ipv4FlowClassifier section; device engines have no
    IP layer, so it defaults to empty (the section is emitted empty —
    parsers that only read FlowStats are unaffected)."""

    def __init__(
        self,
        fm: dict,
        rings=(),
        five_tuples: dict[int, FiveTuple] | None = None,
    ):
        self.stats = reduce_flow_stats(fm)
        self.events = decode_packet_rings(rings) if len(rings) else []
        self._flows = {
            t: fid for fid, t in (five_tuples or {}).items()
        }

    def GetFlowStats(self) -> dict[int, FlowStats]:
        return self.stats

    def SerializeToXmlFile(self, filename: str, *_args) -> None:
        serialize_flow_stats_xml(self.stats, self._flows, filename)

    def WritePcap(self, filename: str, **kw) -> int:
        return write_events_pcap(self.events, filename, **kw)

    def ToTrafficProgram(self, n_entities: int | None = None, **kw):
        """Delivered ring events → exact trace-replay
        :class:`~tpudes.traffic.TrafficProgram` (one entity per flow id
        seen, or ``n_entities`` fixed lanes), closing the ingest loop
        against our own output without touching the filesystem."""
        from tpudes.traffic.ingest import ingest_traces

        rx = [e for e in self.events if e.verdict == VERDICT_RX]
        flows = sorted({e.flow for e in rx})
        if n_entities is not None:
            flows = list(range(n_entities))
        sources = []
        for fl in flows:
            mine = [e for e in rx if e.flow == fl]
            sources.append(
                (
                    np.asarray([e.t_us for e in mine], np.int64),
                    np.asarray([e.size for e in mine], np.int64),
                )
            )
        return ingest_traces(sources, t0_us=0, **kw)
