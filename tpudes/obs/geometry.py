"""Geometry-refresh observability: who recomputed the loss matrices.

Device-resident mobility (``tpudes.ops.mobility``) moves the geometry
refresh INSIDE the compiled scan; the host LTE TTI controller's
per-window ``BatchableRegistry`` refresh remains as the
``TPUDES_DEVICE_GEOM=0`` fallback.  :class:`GeomTelemetry` counts both
sides so the bench rows (``mobile_bss`` / ``lte_mobility``) and any
interactive session can SAY which regime a run took and how hard the
``geom_stride`` knob worked:

- ``device_refreshes`` — in-kernel loss-matrix recomputes (the
  ``lax.cond`` firings of the geometry stage, ``ceil(steps/stride)``);
- ``host_refreshes`` — per-window host geometry rebuilds (the
  controller fallback path, one per conservative window);
- ``steps`` — geometry-consuming steps, so ``stride_hit_rate`` =
  1 - refreshes/steps is the share of steps served from the carried
  snapshot.

Follows the :class:`tpudes.obs.fuzz.FuzzTelemetry` shape: recording is
a dict update, snapshots computed on demand, reset explicit.
``python -m tpudes.obs --geometry metrics.json`` is the schema gate.
"""

from __future__ import annotations

__all__ = ["GeomTelemetry", "validate_geometry_metrics"]


class GeomTelemetry:
    """Process-wide geometry-refresh counters, per engine."""

    _engines: dict[str, dict] = {}

    @classmethod
    def _engine(cls, engine: str) -> dict:
        return cls._engines.setdefault(
            engine,
            {"device_refreshes": 0, "host_refreshes": 0, "steps": 0},
        )

    @classmethod
    def record_device(cls, engine: str, refreshes: int, steps: int) -> None:
        e = cls._engine(engine)
        e["device_refreshes"] += int(refreshes)
        e["steps"] += int(steps)

    @classmethod
    def record_host(cls, engine: str, refreshes: int = 1) -> None:
        cls._engine(engine)["host_refreshes"] += int(refreshes)

    @classmethod
    def snapshot(cls) -> dict:
        engines = {}
        for name, e in sorted(cls._engines.items()):
            steps = e["steps"]
            engines[name] = {
                "device_refreshes": e["device_refreshes"],
                "host_refreshes": e["host_refreshes"],
                "steps": steps,
                "stride_hit_rate": (
                    round(1.0 - e["device_refreshes"] / steps, 4)
                    if steps > 0
                    else 0.0
                ),
            }
        return {"version": 1, "engines": engines}

    @classmethod
    def engine(cls, engine: str) -> dict:
        return dict(cls._engine(engine))

    @classmethod
    def reset(cls) -> None:
        cls._engines = {}


def validate_geometry_metrics(doc) -> list[str]:
    """Schema check for a :meth:`GeomTelemetry.snapshot` document
    (dependency-free, mirroring ``validate_fuzz_metrics``).  Returns
    human-readable problems; empty means valid."""
    from tpudes.obs.schema import make_need

    problems: list[str] = []
    need = make_need(problems)

    if not isinstance(doc, dict):
        return ["top level: not a JSON object"]
    if doc.get("version") != 1:
        problems.append("version: expected 1")
    engines = need(doc, "engines", dict, "top level")
    if engines is not None:
        for name, e in engines.items():
            where = f"engines.{name}"
            dev = need(e, "device_refreshes", int, where)
            need(e, "host_refreshes", int, where)
            steps = need(e, "steps", int, where)
            rate = need(e, "stride_hit_rate", (int, float), where)
            for k, v in (("device_refreshes", dev), ("steps", steps)):
                if isinstance(v, int) and v < 0:
                    problems.append(f"{where}.{k}: negative")
            if (
                isinstance(dev, int)
                and isinstance(steps, int)
                and steps > 0
                and dev > steps
            ):
                problems.append(f"{where}: device_refreshes > steps")
            if isinstance(rate, (int, float)) and not (
                0.0 <= float(rate) <= 1.0
            ):
                problems.append(f"{where}.stride_hit_rate: outside [0, 1]")
    return problems
