"""Serving-layer observability: the StudyServer's metrics surface.

:class:`ServingTelemetry` is the process-global registry
:class:`tpudes.serving.StudyServer` records into — queue depth,
coalesce rate, batch occupancy, per-engine launch latency and
end-to-end study latency, plus (ISSUE 13) the failure/recovery
counters (requeues, members lost, retry-budget exhaustion, chaos
injections per kind, checkpoint saves/restores) and per-SLO-class
attainment — and :func:`validate_serving_metrics` is the schema gate
the CI serving/chaos smokes run over dumped snapshots
(``python -m tpudes.obs --serving metrics.json``).

The registry follows the :class:`tpudes.obs.device.CompileTelemetry`
shape: recording is a dict update (always cheap, no knob), snapshots
are computed on demand, and the latency samples are bounded rings
(:data:`ServingTelemetry.CAP`) so a long-lived server cannot grow host
memory without limit — percentiles describe the recent window, which
is what operating dashboards want anyway.
"""

from __future__ import annotations

__all__ = ["ServingTelemetry", "validate_serving_metrics"]


def _percentile(samples: list[float], q: float) -> float:
    """Nearest-rank percentile (q in [0, 1]) of a non-empty list."""
    xs = sorted(samples)
    idx = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
    return xs[idx]


class ServingTelemetry:
    """Process-wide serving metrics registry.

    Counters are cumulative since the last :meth:`reset`; the latency
    rings keep the most recent :data:`CAP` samples per engine.  A
    *coalesced* launch is one that carried more than one real study;
    *pad_points* counts the duplicated tail points a pow2 config-bucket
    pad added (device work spent on no study — the occupancy cost of
    executable reuse).
    """

    #: bound on retained latency samples per engine (recent window)
    CAP = 4096

    _counters: dict[str, int] = {}
    _queue_depth = 0
    _queue_depth_max = 0
    _engines: dict[str, dict] = {}
    #: failure/recovery counters (ISSUE 13): requeues, member loss,
    #: retry-budget exhaustion, chaos injections, checkpoint traffic
    _failures: dict[str, int] = {}
    #: SLO class -> {"studies", "attained", "latency_s" ring}
    _slo: dict[str, dict] = {}

    # --- recording hooks (called by tpudes.serving) ----------------------

    @classmethod
    def _bump(cls, name: str, n: int = 1) -> None:
        cls._counters[name] = cls._counters.get(name, 0) + int(n)

    @classmethod
    def _engine(cls, engine: str) -> dict:
        return cls._engines.setdefault(
            engine,
            {
                "launches": 0,
                "studies": 0,
                "coalesced_launches": 0,
                "real_points": 0,
                "padded_points": 0,
                "launch_wall_s": [],
                "study_latency_s": [],
            },
        )

    @classmethod
    def record_submit(cls, engine: str, queue_depth: int) -> None:
        cls._bump("submitted")
        cls._queue_depth = int(queue_depth)
        cls._queue_depth_max = max(cls._queue_depth_max, int(queue_depth))

    @classmethod
    def record_reject(cls, tenant: str) -> None:
        del tenant  # per-tenant breakdown is the server's, not global
        cls._bump("rejected")

    @classmethod
    def record_dispatch(cls, engine: str, n_real: int, n_padded: int,
                        queue_depth: int) -> None:
        cls._queue_depth = int(queue_depth)
        e = cls._engine(engine)
        e["launches"] += 1
        e["real_points"] += int(n_real)
        e["padded_points"] += int(n_padded)
        cls._bump("launches")
        if n_real > 1:
            e["coalesced_launches"] += 1
            cls._bump("coalesced_launches")
            cls._bump("coalesced_studies", n_real)
        cls._bump("pad_points", int(n_padded) - int(n_real))

    @classmethod
    def record_launch_done(cls, engine: str, wall_s: float) -> None:
        ring = cls._engine(engine)["launch_wall_s"]
        ring.append(float(wall_s))
        del ring[: max(0, len(ring) - cls.CAP)]

    @classmethod
    def record_study_done(cls, engine: str, latency_s: float,
                          slo: str | None = None,
                          attained: bool | None = None) -> None:
        e = cls._engine(engine)
        e["studies"] += 1
        cls._bump("completed")
        ring = e["study_latency_s"]
        ring.append(float(latency_s))
        del ring[: max(0, len(ring) - cls.CAP)]
        if slo is not None:
            s = cls._slo.setdefault(
                slo, {"studies": 0, "attained": 0, "latency_s": []}
            )
            s["studies"] += 1
            if attained:
                s["attained"] += 1
            s["latency_s"].append(float(latency_s))
            del s["latency_s"][: max(0, len(s["latency_s"]) - cls.CAP)]

    # --- failure/recovery hooks (ISSUE 13) --------------------------------

    @classmethod
    def _fail_bump(cls, name: str, n: int = 1) -> None:
        cls._failures[name] = cls._failures.get(name, 0) + int(n)

    @classmethod
    def record_requeue(cls, engine: str, n_studies: int) -> None:
        """A batch transiently failed and went back to the queue."""
        del engine
        cls._fail_bump("requeued_batches")
        cls._fail_bump("requeued_studies", n_studies)

    @classmethod
    def record_member_lost(cls, n_members: int = 1) -> None:
        cls._fail_bump("members_lost", n_members)

    @classmethod
    def record_retry_exhausted(cls, n: int = 1) -> None:
        cls._fail_bump("retry_budget_exhausted", n)

    @classmethod
    def record_injected(cls, kind: str) -> None:
        """A chaos schedule fired (kind-tagged, plus the total the
        schema gates on)."""
        cls._fail_bump("injected_failures")
        cls._fail_bump(f"injected_{kind}")

    @classmethod
    def record_checkpoint(cls, event: str) -> None:
        """``event`` is ``save`` or ``restore``."""
        cls._fail_bump(f"checkpoint_{event}s")

    @classmethod
    def record_backstop(cls) -> None:
        """The scheduler loop's belt-and-braces catch fired — a bug
        the per-batch poisoning should have handled.  Counted (never
        silently swallowed) so a hot backstop shows up on dashboards."""
        cls._fail_bump("scheduler_backstop")

    @classmethod
    def record_queue_depth(cls, depth: int) -> None:
        cls._queue_depth = int(depth)
        cls._queue_depth_max = max(cls._queue_depth_max, int(depth))

    @classmethod
    def record_warm(cls, engine: str, n_programs: int, wall_s: float) -> None:
        del engine
        cls._bump("warm_programs", n_programs)
        cls._warm_wall = getattr(cls, "_warm_wall", 0.0) + float(wall_s)

    # --- reading ----------------------------------------------------------

    @classmethod
    def snapshot(cls) -> dict:
        """The exported metrics document (see
        :func:`validate_serving_metrics` for the schema)."""

        def dist(ring: list[float]) -> dict:
            if not ring:
                return {"p50": 0.0, "p99": 0.0, "n": 0}
            return {
                "p50": round(_percentile(ring, 0.50), 6),
                "p99": round(_percentile(ring, 0.99), 6),
                "n": len(ring),
            }

        counters = {
            k: cls._counters.get(k, 0)
            for k in (
                "submitted", "completed", "rejected", "launches",
                "coalesced_launches", "coalesced_studies", "pad_points",
                "warm_programs",
            )
        }
        done = counters["completed"]
        engines = {}
        for name, e in sorted(cls._engines.items()):
            occupancy = (
                e["real_points"] / e["padded_points"]
                if e["padded_points"]
                else 0.0
            )
            engines[name] = {
                "launches": e["launches"],
                "studies": e["studies"],
                "coalesced_launches": e["coalesced_launches"],
                "batch_occupancy": round(occupancy, 4),
                "launch_wall_s": dist(e["launch_wall_s"]),
                "study_latency_s": dist(e["study_latency_s"]),
            }
        failures = {
            k: cls._failures.get(k, 0)
            for k in (
                "requeued_batches", "requeued_studies", "members_lost",
                "retry_budget_exhausted", "injected_failures",
                "checkpoint_saves", "checkpoint_restores",
                "scheduler_backstop",
            )
        }
        # kind-tagged injection counters ride along verbatim
        failures.update({
            k: v for k, v in sorted(cls._failures.items())
            if k.startswith("injected_")
        })
        slo = {}
        for name, s in sorted(cls._slo.items()):
            slo[name] = {
                "studies": s["studies"],
                "attained": s["attained"],
                "attainment": round(
                    s["attained"] / s["studies"], 4
                ) if s["studies"] else 0.0,
                "latency_s": dist(s["latency_s"]),
            }
        return {
            "version": 1,
            "counters": counters,
            "coalesce_rate": round(
                counters["coalesced_studies"] / done, 4
            ) if done else 0.0,
            "warm_wall_s": round(getattr(cls, "_warm_wall", 0.0), 3),
            "queue": {
                "depth": cls._queue_depth,
                "depth_max": cls._queue_depth_max,
            },
            "failures": failures,
            "slo": slo,
            "engines": engines,
        }

    @classmethod
    def reset(cls) -> None:
        cls._counters = {}
        cls._engines = {}
        cls._queue_depth = 0
        cls._queue_depth_max = 0
        cls._warm_wall = 0.0
        cls._failures = {}
        cls._slo = {}


def validate_serving_metrics(doc) -> list[str]:
    """Schema check for a :meth:`ServingTelemetry.snapshot` document
    (dependency-free, mirroring ``validate_chrome_trace``).  Returns a
    list of human-readable problems; empty means valid."""
    from tpudes.obs.schema import make_need

    problems: list[str] = []
    need = make_need(problems)

    if not isinstance(doc, dict):
        return ["top level: not a JSON object"]
    if doc.get("version") != 1:
        problems.append("version: expected 1")
    counters = need(doc, "counters", dict, "top level")
    if counters is not None:
        for k in (
            "submitted", "completed", "rejected", "launches",
            "coalesced_launches", "coalesced_studies", "pad_points",
        ):
            v = need(counters, k, int, "counters")
            if isinstance(v, int) and v < 0:
                problems.append(f"counters.{k}: negative")
    need(doc, "coalesce_rate", (int, float), "top level")
    queue = need(doc, "queue", dict, "top level")
    if queue is not None:
        need(queue, "depth", int, "queue")
        need(queue, "depth_max", int, "queue")
    failures = need(doc, "failures", dict, "top level")
    if failures is not None:
        for k in (
            "requeued_batches", "requeued_studies", "members_lost",
            "retry_budget_exhausted", "injected_failures",
            "checkpoint_saves", "checkpoint_restores",
        ):
            v = need(failures, k, int, "failures")
            if isinstance(v, int) and v < 0:
                problems.append(f"failures.{k}: negative")
    slo = need(doc, "slo", dict, "top level")
    if slo is not None:
        for name, s in slo.items():
            where = f"slo.{name}"
            if not isinstance(s, dict):
                problems.append(f"{where}: not an object")
                continue
            n = need(s, "studies", int, where)
            att = need(s, "attained", int, where)
            rate = need(s, "attainment", (int, float), where)
            if rate is not None and not (0.0 <= rate <= 1.0):
                problems.append(f"{where}.attainment: not in [0, 1]")
            if (
                isinstance(n, int) and isinstance(att, int) and att > n
            ):
                problems.append(f"{where}: attained > studies")
            d = need(s, "latency_s", dict, where)
            if d is not None:
                need(d, "p50", (int, float), f"{where}.latency_s")
                need(d, "p99", (int, float), f"{where}.latency_s")
                need(d, "n", int, f"{where}.latency_s")
    engines = need(doc, "engines", dict, "top level")
    if engines is not None:
        for name, e in engines.items():
            where = f"engines.{name}"
            need(e, "launches", int, where)
            need(e, "studies", int, where)
            need(e, "coalesced_launches", int, where)
            occ = need(e, "batch_occupancy", (int, float), where)
            if occ is not None and not (0.0 <= occ <= 1.0):
                problems.append(f"{where}.batch_occupancy: not in [0, 1]")
            for dk in ("launch_wall_s", "study_latency_s"):
                d = need(e, dk, dict, where)
                if d is not None:
                    need(d, "p50", (int, float), f"{where}.{dk}")
                    need(d, "p99", (int, float), f"{where}.{dk}")
                    need(d, "n", int, f"{where}.{dk}")
    return problems
