"""Host event-loop profiler — the engine-side half of tpudes.obs.

One ``HostProfiler`` is attached to a ``SimulatorImpl`` at construction
when the ``TpudesObs`` GlobalValue is 1.  The engine then routes every
executed event through an instrumented ``_invoke`` which feeds:

- per-event-type counts and cumulative wall time (type = the callback's
  ``__qualname__``),
- a bounded span list for the Chrome-trace export,
- the flight-recorder ring (dumped on exception / invariant trip),
- queue-depth tracking via :class:`InstrumentedScheduler`,
- window stats from ``JaxSimulatorImpl`` (events/window, batch-refresh
  count) and the propagation-cache hit rate reported by batched
  channels.

When the knob is 0 the engine's hot loop runs the exact pre-obs byte
code: no profiler is constructed, the scheduler stays un-wrapped, and
the ``_invoke`` swap (an instance attribute) happens in
``SimulatorImpl.__init__`` only when enabled — that structural
zero-cost contract is pinned by tests/test_obs.py.  (The module itself
may still be imported with the knob off — ``ShowProgress`` reuses
:class:`RunStats` — which costs nothing per event.)
"""

from __future__ import annotations

import time

from tpudes.core.global_value import GlobalValue
from tpudes.obs.flight_recorder import FlightRecorder


def enabled() -> bool:
    """The one observability knob (bound via CommandLine / Bind /
    NS_GLOBAL_VALUE like every engine knob)."""
    return bool(GlobalValue.GetValueFailSafe("TpudesObs", 0))


class RunStats:
    """Events/s and simulated-vs-wall rate meter between samples.

    Owns the bookkeeping ShowProgress used to carry privately; the
    engine profiler holds one (``HostProfiler.run_stats``) so progress
    reporting and the trace export read the same numbers.
    """

    def __init__(self):
        self.wall_start = time.monotonic()
        self._last = (self.wall_start, 0, 0.0)

    def sample(self, events: int, sim_s: float) -> dict:
        now = time.monotonic()
        last_wall, last_events, last_sim = self._last
        dt = max(now - last_wall, 1e-9)
        snap = dict(
            events=events,
            sim_s=sim_s,
            wall_s=now - self.wall_start,
            dt_wall=dt,
            ev_per_s=(events - last_events) / dt,
            sim_per_wall=(sim_s - last_sim) / dt,
        )
        self._last = (now, events, sim_s)
        return snap


class InstrumentedScheduler:
    """Transparent scheduler wrapper counting inserts/pops so the
    profiler can track queue depth without an O(n) ``len`` scan per
    event.

    Deliberately does NOT forward ``run_native``: with obs enabled the
    engine must take the Python dispatch loop so the instrumented
    ``_invoke`` sees every event.  The insert/pop delta over-counts
    cancelled events (the inner schedulers purge them internally,
    invisibly to this wrapper), so the profiler is handed a live-depth
    probe and periodically resynchronizes against it — see
    ``HostProfiler.on_pop``.
    """

    __slots__ = ("_inner", "_obs")

    def __init__(self, inner, obs: "HostProfiler"):
        self._inner = inner
        self._obs = obs
        obs.depth_probe = inner.__len__  # exact live (non-cancelled) count

    def Insert(self, ev) -> None:
        self._obs.on_insert()
        self._inner.Insert(ev)

    def IsEmpty(self) -> bool:
        return self._inner.IsEmpty()

    def PeekNext(self):
        return self._inner.PeekNext()

    def RemoveNext(self):
        ev = self._inner.RemoveNext()
        self._obs.on_pop()
        return ev

    def Remove(self, ev) -> None:
        self._inner.Remove(ev)

    def __len__(self):
        return len(self._inner)


class HostProfiler:
    """Per-run host-side metrics sink (see module docstring)."""

    MAX_SPANS = 20_000

    def __init__(self, ring_capacity: int | None = None):
        if ring_capacity is None:
            ring_capacity = int(GlobalValue.GetValueFailSafe("TpudesObsRing", 512))
        self.run_stats = RunStats()
        self.recorder = FlightRecorder(ring_capacity)
        self.event_count = 0
        self.counts: dict[str, int] = {}
        self.wall: dict[str, float] = {}
        # queue depth: insert/pop delta, resynced every RESYNC_EVERY
        # pops against the exact live count (the delta over-counts
        # events the inner scheduler lazily purged after a Cancel)
        self.queue_depth = 0
        self.queue_depth_max = 0
        self.inserts = 0
        self.depth_probe = None  # set by InstrumentedScheduler
        self._pops_since_sync = 0
        # bounded Chrome-trace spans: (label, t0_s, dur_s, sim_ts, context)
        self.spans: list[tuple] = []
        self.spans_dropped = 0
        # windowed-engine stats (span list bounded, totals exact)
        self.windows: list[tuple] = []  # (t0_s, dur_s, events, refreshes)
        self.windows_total = 0
        self.window_events = 0
        self.window_refreshes = 0
        # batched-channel propagation cache
        self.cache_hits = 0
        self.cache_misses = 0

    #: pops between exact-depth resyncs: bounds cancel-drift at O(1)
    #: amortized probe cost (the probe is an O(n) live-count scan)
    RESYNC_EVERY = 4096

    # --- scheduler hooks ---------------------------------------------------
    def on_insert(self) -> None:
        self.inserts += 1
        self.queue_depth += 1
        if self.queue_depth > self.queue_depth_max:
            self.queue_depth_max = self.queue_depth

    def on_pop(self) -> None:
        self.queue_depth -= 1
        self._pops_since_sync += 1
        if self._pops_since_sync >= self.RESYNC_EVERY:
            self.resync_depth()

    def resync_depth(self) -> int:
        """Snap ``queue_depth`` to the exact live count (drops the
        phantom depth accumulated from cancelled-then-purged events)."""
        self._pops_since_sync = 0
        if self.depth_probe is not None:
            self.queue_depth = self.depth_probe()
        return self.queue_depth

    # --- engine hooks ------------------------------------------------------
    def record(self, label: str, t0: float, dur_s: float, ev) -> None:
        """``t0`` is absolute ``time.monotonic()``; spans store seconds
        since run start so the export timeline begins at ~0."""
        self.counts[label] = self.counts.get(label, 0) + 1
        self.wall[label] = self.wall.get(label, 0.0) + dur_s
        if len(self.spans) < self.MAX_SPANS:
            self.spans.append(
                (label, t0 - self.run_stats.wall_start, dur_s, ev.ts, ev.context)
            )
        else:
            self.spans_dropped += 1

    def on_window(self, t0: float, dur_s: float, events: int, refreshes: int) -> None:
        self.windows_total += 1
        self.window_events += events
        self.window_refreshes += refreshes
        if len(self.windows) < self.MAX_SPANS:
            self.windows.append(
                (t0 - self.run_stats.wall_start, dur_s, events, refreshes)
            )

    def prop_cache(self, hit: bool) -> None:
        if hit:
            self.cache_hits += 1
        else:
            self.cache_misses += 1

    # --- failure paths -----------------------------------------------------
    def trip(self, message: str) -> None:
        """Engine invariant violated: dump the tail, then fail loudly."""
        self.recorder.dump(reason=f"invariant trip: {message}")
        raise RuntimeError(f"tpudes.obs invariant trip: {message}")

    def dump_crash(self, exc: BaseException) -> None:
        self.recorder.dump(reason=f"{type(exc).__name__}: {exc}")

    # --- summary -----------------------------------------------------------
    def cache_hit_rate(self) -> float | None:
        total = self.cache_hits + self.cache_misses
        return self.cache_hits / total if total else None

    def summary(self) -> dict:
        """Everything the exporter / bench integration reads, as one
        plain dict."""
        n_windows = self.windows_total
        return {
            "events": self.event_count,
            "event_types": {
                label: {
                    "count": self.counts[label],
                    "wall_s": self.wall.get(label, 0.0),
                }
                for label in sorted(self.counts)
            },
            "queue": {
                "inserts": self.inserts,
                "depth": self.resync_depth(),
                "depth_max": self.queue_depth_max,
            },
            "windows": {
                "count": n_windows,
                "events": self.window_events,
                "events_per_window": (
                    self.window_events / n_windows if n_windows else 0.0
                ),
                "batch_refreshes": self.window_refreshes,
            },
            "prop_cache": {
                "hits": self.cache_hits,
                "misses": self.cache_misses,
                "hit_rate": self.cache_hit_rate(),
            },
            "spans_dropped": self.spans_dropped,
            "wall_s": time.monotonic() - self.run_stats.wall_start,
        }
