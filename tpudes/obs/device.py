"""Device-side observability: XLA compile telemetry + metric plumbing.

The device engines (tpudes/parallel) accumulate their metrics *inside*
the scan carry — drops, retransmits, scheduler grants, cwnd-cut events,
queue histograms — and fetch them once at run end with the outcome
arrays, so the hot loop never syncs with the host.  What lives here is
the part that must be process-global:

- :class:`CompileTelemetry` — every engine records one entry per
  jit-cache miss (compile count + wall time of the compiling call).
  This pins the "one executable serves the family" property as a
  *metric*: a 9-scheduler LTE sweep must show ``compiles == 1``.
  Recording is always on (a dict update per compile is free); the
  registry deliberately survives ``reset_world`` because XLA's compile
  caches do too.
- :func:`device_metrics_enabled` — the engines consult this at
  lowering/build time; the extra carry buffers exist only when the
  ``TpudesObs`` knob is up, so a disabled run compiles the exact
  pre-obs program.
- :class:`ChunkStream` — the landing strip for chunked-horizon runs:
  each fixed-size while_loop segment returns a small device metrics
  tree alongside the carry, and the engine records it here *after
  dispatching the next segment*, so the D2H fetch overlaps the next
  chunk's compute instead of serializing the pipeline.
"""

from __future__ import annotations

import time
from contextlib import contextmanager


def device_metrics_enabled() -> bool:
    """The engines consult this when building a device program: the
    same ``TpudesObs`` knob that arms the host profiler."""
    from tpudes.obs.profiler import enabled

    return enabled()


class CompileTelemetry:
    """Process-wide per-engine compile counters."""

    _entries: dict[str, dict] = {}

    @classmethod
    def record(cls, engine: str, wall_s: float) -> None:
        entry = cls._entries.setdefault(
            engine, {"compiles": 0, "wall_s": 0.0}
        )
        entry["compiles"] += 1
        entry["wall_s"] += float(wall_s)

    @classmethod
    def snapshot(cls) -> dict[str, dict]:
        return {
            engine: {"compiles": e["compiles"], "wall_s": round(e["wall_s"], 3)}
            for engine, e in sorted(cls._entries.items())
        }

    @classmethod
    def compiles(cls, engine: str) -> int:
        return cls._entries.get(engine, {}).get("compiles", 0)

    @classmethod
    def reset(cls) -> None:
        cls._entries.clear()

    @classmethod
    @contextmanager
    def timed(cls, engine: str, compiling: bool):
        """Record one compile entry for the wrapped block when
        ``compiling`` (a jit-cache miss) — the single plumbing shape
        every parallel engine uses.  The caller must block on the
        result inside the block (``jax.block_until_ready``) or the
        recorded wall time under-counts the async compile."""
        if not compiling:
            yield
            return
        t0 = time.monotonic()
        yield
        cls.record(engine, time.monotonic() - t0)


class KernelProfile:
    """Per-stage device-kernel timings (the ISSUE-6 measurement seam).

    The engines' profiling harnesses (e.g.
    :func:`tpudes.parallel.kernels_pallas.profile_sm_stages`) record
    the median wall time of each stage of a fused kernel chain here, so
    "the win is measured, not asserted": bench's ``lte_kernel_profile``
    row and any interactive session read the same registry.  Like
    :class:`CompileTelemetry`, the registry survives ``reset_world``
    (it describes executables, not simulation state)."""

    _entries: dict[str, dict[str, dict]] = {}

    @classmethod
    def record(
        cls, engine: str, stage: str, wall_s: float, batch: int
    ) -> None:
        cls._entries.setdefault(engine, {})[stage] = {
            "wall_s": float(wall_s),
            "batch": int(batch),
        }

    @classmethod
    def stages(cls, engine: str) -> dict[str, dict]:
        return dict(cls._entries.get(engine, {}))

    @classmethod
    def snapshot(cls) -> dict[str, dict]:
        return {
            engine: {
                stage: {"wall_us": round(e["wall_s"] * 1e6, 1),
                        "batch": e["batch"]}
                for stage, e in stages.items()
            }
            for engine, stages in sorted(cls._entries.items())
        }

    @classmethod
    def reset(cls) -> None:
        cls._entries.clear()


class ChunkStream:
    """Per-chunk metrics streamed by chunked-horizon engine runs.

    Bounded (oldest entries drop past :data:`CAP`) because a long
    streaming run would otherwise grow host memory without limit; the
    stream is a progress feed, not an archive."""

    CAP = 4096
    _entries: list[dict] = []
    _dropped = 0

    @classmethod
    def record(cls, engine: str, t_end: int, metrics: dict) -> None:
        cls._entries.append(
            {"engine": engine, "t_end": int(t_end), "metrics": metrics}
        )
        if len(cls._entries) > cls.CAP:
            del cls._entries[: len(cls._entries) - cls.CAP]
            cls._dropped += 1

    @classmethod
    def entries(cls, engine: str | None = None) -> list[dict]:
        if engine is None:
            return list(cls._entries)
        return [e for e in cls._entries if e["engine"] == engine]

    @classmethod
    def reset(cls) -> None:
        cls._entries.clear()
        cls._dropped = 0


