"""tpudes.obs — unified observability across all three execution layers.

One GlobalValue knob, ``TpudesObs`` (bound like every engine knob:
``GlobalValue.Bind``, ``--TpudesObs=1`` on any CommandLine script, or
``NS_GLOBAL_VALUE``), turns on:

- the **host event-loop profiler** (:mod:`tpudes.obs.profiler`):
  per-event-type counts and wall time, queue depth, per-window stats
  and the propagation-cache hit rate on the windowed engine;
- the **flight recorder** (:mod:`tpudes.obs.flight_recorder`): the last
  ``TpudesObsRing`` events, dumped on an exception or invariant trip;
- **on-device metric accumulators** in the parallel engines, fetched
  once at run end (no host sync in the scan), plus process-wide XLA
  compile telemetry (:mod:`tpudes.obs.device` — always on, it costs one
  dict update per compile);
- the **Chrome-trace export** (:mod:`tpudes.obs.export`): set
  ``TpudesObsTrace=/path/trace.json`` and ``Simulator.Destroy`` writes
  a chrome://tracing / Perfetto loadable timeline.  Validate with
  ``python -m tpudes.obs trace.json``;
- the **device FlowMonitor** (:mod:`tpudes.obs.flowmon`): per-flow
  FlowStats columns and a packet-event ring riding each compiled
  engine's scan carry, reduced on the host into the same ``FlowStats``
  objects the host monitor produces.  Export through the shared
  ns-3-parity XML serializer, write delivered packets as pcap, merge
  flow spans into the Chrome trace, or round-trip a device run back
  into a trace-replay ``TrafficProgram``.  Validate the artifacts with
  ``python -m tpudes.obs --flowmon flowmon.xml`` / ``--pcap out.pcap``.

With the knob at 0 the engines run their pre-obs code paths unchanged
(pinned by the overhead test in tests/test_obs.py).
"""

from tpudes.obs.device import (
    ChunkStream,
    CompileTelemetry,
    device_metrics_enabled,
)
from tpudes.obs.distributed import (
    DistributedTelemetry,
    validate_distributed_metrics,
)
from tpudes.obs.export import (
    assert_valid_chrome_trace,
    chrome_trace,
    export_chrome_trace,
    export_on_destroy,
    validate_chrome_trace,
)
from tpudes.obs.flight_recorder import FlightRecorder
from tpudes.obs.flowmon import (
    DeviceFlowMonitor,
    decode_packet_rings,
    host_reference_stats,
    reduce_flow_stats,
    serialize_flow_stats_xml,
    validate_flowmon_xml,
    validate_pcap,
    write_events_pcap,
)
from tpudes.obs.fuzz import FuzzTelemetry, validate_fuzz_metrics
from tpudes.obs.grad import GradTelemetry, validate_grad_metrics
from tpudes.obs.profiler import (
    HostProfiler,
    InstrumentedScheduler,
    RunStats,
    enabled,
)
from tpudes.obs.serving import ServingTelemetry, validate_serving_metrics
from tpudes.obs.traffic import TrafficTelemetry, validate_traffic_metrics

__all__ = [
    "TrafficTelemetry",
    "validate_traffic_metrics",
    "ChunkStream",
    "CompileTelemetry",
    "DeviceFlowMonitor",
    "DistributedTelemetry",
    "FlightRecorder",
    "FuzzTelemetry",
    "GradTelemetry",
    "validate_grad_metrics",
    "HostProfiler",
    "InstrumentedScheduler",
    "RunStats",
    "ServingTelemetry",
    "assert_valid_chrome_trace",
    "chrome_trace",
    "decode_packet_rings",
    "device_metrics_enabled",
    "enabled",
    "export_chrome_trace",
    "export_on_destroy",
    "host_reference_stats",
    "reduce_flow_stats",
    "serialize_flow_stats_xml",
    "validate_chrome_trace",
    "validate_distributed_metrics",
    "validate_flowmon_xml",
    "validate_fuzz_metrics",
    "validate_pcap",
    "validate_serving_metrics",
    "write_events_pcap",
]
