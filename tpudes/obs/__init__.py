"""tpudes.obs — unified observability across all three execution layers.

One GlobalValue knob, ``TpudesObs`` (bound like every engine knob:
``GlobalValue.Bind``, ``--TpudesObs=1`` on any CommandLine script, or
``NS_GLOBAL_VALUE``), turns on:

- the **host event-loop profiler** (:mod:`tpudes.obs.profiler`):
  per-event-type counts and wall time, queue depth, per-window stats
  and the propagation-cache hit rate on the windowed engine;
- the **flight recorder** (:mod:`tpudes.obs.flight_recorder`): the last
  ``TpudesObsRing`` events, dumped on an exception or invariant trip;
- **on-device metric accumulators** in the parallel engines, fetched
  once at run end (no host sync in the scan), plus process-wide XLA
  compile telemetry (:mod:`tpudes.obs.device` — always on, it costs one
  dict update per compile);
- the **Chrome-trace export** (:mod:`tpudes.obs.export`): set
  ``TpudesObsTrace=/path/trace.json`` and ``Simulator.Destroy`` writes
  a chrome://tracing / Perfetto loadable timeline.  Validate with
  ``python -m tpudes.obs trace.json``.

With the knob at 0 the engines run their pre-obs code paths unchanged
(pinned by the overhead test in tests/test_obs.py).
"""

from tpudes.obs.device import (
    ChunkStream,
    CompileTelemetry,
    device_metrics_enabled,
)
from tpudes.obs.distributed import (
    DistributedTelemetry,
    validate_distributed_metrics,
)
from tpudes.obs.export import (
    assert_valid_chrome_trace,
    chrome_trace,
    export_chrome_trace,
    export_on_destroy,
    validate_chrome_trace,
)
from tpudes.obs.flight_recorder import FlightRecorder
from tpudes.obs.fuzz import FuzzTelemetry, validate_fuzz_metrics
from tpudes.obs.grad import GradTelemetry, validate_grad_metrics
from tpudes.obs.profiler import (
    HostProfiler,
    InstrumentedScheduler,
    RunStats,
    enabled,
)
from tpudes.obs.serving import ServingTelemetry, validate_serving_metrics
from tpudes.obs.traffic import TrafficTelemetry, validate_traffic_metrics

__all__ = [
    "TrafficTelemetry",
    "validate_traffic_metrics",
    "ChunkStream",
    "CompileTelemetry",
    "DistributedTelemetry",
    "FlightRecorder",
    "FuzzTelemetry",
    "GradTelemetry",
    "validate_grad_metrics",
    "HostProfiler",
    "InstrumentedScheduler",
    "RunStats",
    "ServingTelemetry",
    "assert_valid_chrome_trace",
    "chrome_trace",
    "device_metrics_enabled",
    "enabled",
    "export_chrome_trace",
    "export_on_destroy",
    "validate_chrome_trace",
    "validate_distributed_metrics",
    "validate_fuzz_metrics",
    "validate_serving_metrics",
]
