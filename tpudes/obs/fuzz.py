"""Fuzz-harness observability: the differential fuzzer's metrics surface.

:class:`FuzzTelemetry` is the process-global registry
:mod:`tpudes.fuzz` records into — scenario throughput per engine,
oracle-pair coverage counts (how many times each pair actually ran,
and how many diverged), and shrink-loop effort — and
:func:`validate_fuzz_metrics` is the schema gate the CI fuzz smoke
runs over a dumped snapshot (``python -m tpudes.obs --fuzz
metrics.json``).

Follows the :class:`tpudes.obs.serving.ServingTelemetry` shape:
recording is a dict update, snapshots are computed on demand, reset is
explicit (the harness resets at campaign start so a snapshot describes
exactly one campaign).
"""

from __future__ import annotations

__all__ = ["FuzzTelemetry", "validate_fuzz_metrics"]


class FuzzTelemetry:
    """Process-wide fuzz metrics registry (cumulative since reset)."""

    _counters: dict[str, int] = {}
    _engines: dict[str, dict] = {}

    # --- recording hooks (called by tpudes.fuzz.harness) -----------------

    @classmethod
    def _bump(cls, name: str, n: int = 1) -> None:
        cls._counters[name] = cls._counters.get(name, 0) + int(n)

    @classmethod
    def _engine(cls, engine: str) -> dict:
        return cls._engines.setdefault(
            engine, {"scenarios": 0, "wall_s": 0.0, "pairs": {}}
        )

    @classmethod
    def record_scenario(cls, engine: str, wall_s: float) -> None:
        cls._bump("scenarios")
        e = cls._engine(engine)
        e["scenarios"] += 1
        e["wall_s"] += float(wall_s)

    @classmethod
    def record_pair(cls, engine: str, pair: str, diverged: bool) -> None:
        cls._bump("pair_runs")
        p = cls._engine(engine)["pairs"].setdefault(
            pair, {"runs": 0, "divergences": 0}
        )
        p["runs"] += 1
        if diverged:
            p["divergences"] += 1
            cls._bump("divergences")

    @classmethod
    def record_shrink(cls, engine: str, iterations: int) -> None:
        del engine
        cls._bump("shrinks")
        cls._bump("shrink_iterations", iterations)

    # --- reading ----------------------------------------------------------

    @classmethod
    def snapshot(cls) -> dict:
        counters = {
            k: cls._counters.get(k, 0)
            for k in (
                "scenarios", "pair_runs", "divergences", "shrinks",
                "shrink_iterations",
            )
        }
        engines = {}
        for name, e in sorted(cls._engines.items()):
            wall = e["wall_s"]
            engines[name] = {
                "scenarios": e["scenarios"],
                "wall_s": round(wall, 3),
                "scenarios_per_s": (
                    round(e["scenarios"] / wall, 4) if wall > 0 else 0.0
                ),
                "pairs": {
                    k: dict(v) for k, v in sorted(e["pairs"].items())
                },
            }
        return {"version": 1, "counters": counters, "engines": engines}

    @classmethod
    def reset(cls) -> None:
        cls._counters = {}
        cls._engines = {}


def validate_fuzz_metrics(doc) -> list[str]:
    """Schema check for a :meth:`FuzzTelemetry.snapshot` document
    (dependency-free, mirroring ``validate_serving_metrics``).  Returns
    human-readable problems; empty means valid."""
    from tpudes.obs.schema import make_need

    problems: list[str] = []
    need = make_need(problems)

    if not isinstance(doc, dict):
        return ["top level: not a JSON object"]
    if doc.get("version") != 1:
        problems.append("version: expected 1")
    counters = need(doc, "counters", dict, "top level")
    if counters is not None:
        for k in (
            "scenarios", "pair_runs", "divergences", "shrinks",
            "shrink_iterations",
        ):
            v = need(counters, k, int, "counters")
            if isinstance(v, int) and v < 0:
                problems.append(f"counters.{k}: negative")
    engines = need(doc, "engines", dict, "top level")
    if engines is not None:
        for name, e in engines.items():
            where = f"engines.{name}"
            need(e, "scenarios", int, where)
            need(e, "wall_s", (int, float), where)
            need(e, "scenarios_per_s", (int, float), where)
            pairs = need(e, "pairs", dict, where)
            if pairs is not None:
                for pname, p in pairs.items():
                    pw = f"{where}.pairs.{pname}"
                    runs = need(p, "runs", int, pw)
                    div = need(p, "divergences", int, pw)
                    if (
                        isinstance(runs, int)
                        and isinstance(div, int)
                        and div > runs
                    ):
                        problems.append(f"{pw}: divergences > runs")
    return problems
