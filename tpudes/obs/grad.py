"""Gradient observability: what the diff subsystem descended and how.

:class:`GradTelemetry` is the process-wide accounting of every grad /
calibration launch (the :class:`~tpudes.obs.traffic.TrafficTelemetry`
shape: recording is a dict update, snapshots on demand, reset
explicit): per engine it keeps launch/step counters and BOUNDED rings
of the recent loss values and gradient norms — so a bench row or an
interactive session can SAY whether a descent is converging, how hard
the landscape pushes back (grad-norm trajectory), and whether any
step produced a non-finite gradient (the canary for a surrogate
temperature set too cold).

``python -m tpudes.obs --grad metrics.json`` is the schema gate.
"""

from __future__ import annotations

import math

__all__ = ["GradTelemetry", "validate_grad_metrics"]

#: ring capacity (loss / grad-norm histories per engine)
_RING = 256


class GradTelemetry:
    """Process-wide gradient counters, per engine."""

    _engines: dict[str, dict] = {}

    @classmethod
    def _engine(cls, engine: str) -> dict:
        return cls._engines.setdefault(
            engine,
            {
                "launches": 0, "steps": 0, "loss_ring": [],
                "grad_norm_ring": [], "last_loss": None,
                "nonfinite": 0, "batched_points": 0,
            },
        )

    @classmethod
    def _push(cls, e: dict, loss: float, grad_norm: float) -> None:
        if not (math.isfinite(loss) and math.isfinite(grad_norm)):
            e["nonfinite"] += 1
        e["loss_ring"].append(float(loss))
        e["grad_norm_ring"].append(float(grad_norm))
        del e["loss_ring"][:-_RING]
        del e["grad_norm_ring"][:-_RING]
        e["last_loss"] = float(loss)

    @classmethod
    def record(
        cls, engine: str, *, loss: float, grad_norm: float,
        batched: int | None = None,
    ) -> None:
        """One grad launch (a ``grad_*`` call — possibly a C-point
        vmap-of-grad batch, counted in ``batched_points``)."""
        e = cls._engine(engine)
        e["launches"] += 1
        e["steps"] += 1
        e["batched_points"] += int(batched or 1)
        cls._push(e, loss, grad_norm)

    @classmethod
    def record_descent(cls, engine: str, losses, grad_norms) -> None:
        """One compiled descent loop: the whole per-iteration history
        in one record (the scan's stacked outputs)."""
        e = cls._engine(engine)
        e["launches"] += 1
        for lo, gn in zip(losses, grad_norms):
            e["steps"] += 1
            cls._push(e, float(lo), float(gn))

    @classmethod
    def snapshot(cls) -> dict:
        engines = {}
        for name, e in sorted(cls._engines.items()):
            engines[name] = {
                "launches": e["launches"],
                "steps": e["steps"],
                "batched_points": e["batched_points"],
                "last_loss": e["last_loss"],
                "loss_ring": [round(v, 6) for v in e["loss_ring"]],
                "grad_norm_ring": [
                    round(v, 6) for v in e["grad_norm_ring"]
                ],
                "nonfinite": e["nonfinite"],
            }
        return {"version": 1, "engines": engines}

    @classmethod
    def engine(cls, engine: str) -> dict:
        return dict(cls._engine(engine))

    @classmethod
    def reset(cls) -> None:
        cls._engines = {}


def validate_grad_metrics(doc) -> list[str]:
    """Schema check for a :meth:`GradTelemetry.snapshot` document
    (dependency-free, mirroring ``validate_traffic_metrics``)."""
    from tpudes.obs.schema import make_need

    problems: list[str] = []
    need = make_need(problems)

    if not isinstance(doc, dict):
        return ["top level: not a JSON object"]
    if doc.get("version") != 1:
        problems.append("version: expected 1")
    engines = need(doc, "engines", dict, "top level")
    if engines is not None:
        for name, e in engines.items():
            where = f"engines.{name}"
            for k in ("launches", "steps", "batched_points",
                      "nonfinite"):
                v = need(e, k, int, where)
                if isinstance(v, int) and v < 0:
                    problems.append(f"{where}.{k}: negative")
            last = e.get("last_loss")
            if last is not None and not isinstance(last, (int, float)):
                problems.append(f"{where}.last_loss: not a number")
            for ring in ("loss_ring", "grad_norm_ring"):
                r = need(e, ring, list, where)
                if r is None:
                    continue
                if len(r) > _RING:
                    problems.append(
                        f"{where}.{ring}: over the {_RING} cap"
                    )
                if not all(isinstance(v, (int, float)) for v in r):
                    problems.append(f"{where}.{ring}: non-number entry")
            steps = e.get("steps")
            r = e.get("loss_ring")
            if (
                isinstance(steps, int) and isinstance(r, list)
                and len(r) > steps
            ):
                problems.append(
                    f"{where}: loss_ring longer than steps"
                )
    return problems
