"""Flight recorder: a bounded ring buffer of the last N executed events.

Upstream ns-3 has no analog — when a compiled engine or a long scalar
run dies, the only forensics are whatever the user happened to log.
The recorder keeps the tail of the event stream at O(1) cost per event
and dumps it exactly once, on the first exception that escapes an event
callback or on an engine invariant trip (time moving backwards).

Capacity comes from the ``TpudesObsRing`` GlobalValue; the recorder
only exists at all when ``TpudesObs=1`` (see tpudes/obs/profiler.py).
"""

from __future__ import annotations

import sys
from collections import deque


class FlightRecorder:
    """Ring of ``(sim_ts, context, uid, label)`` tuples, newest last."""

    def __init__(self, capacity: int = 512):
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self.dumped = False  # dump-once guard (exceptions propagate)

    def note(self, ts: int, context: int, uid: int, label: str) -> None:
        self._ring.append((ts, context, uid, label))

    def __len__(self) -> int:
        return len(self._ring)

    def entries(self) -> list:
        return list(self._ring)

    def to_dicts(self) -> list[dict]:
        return [
            {"ts": ts, "context": ctx, "uid": uid, "event": label}
            for ts, ctx, uid, label in self._ring
        ]

    def dump(self, reason: str = "", stream=None) -> None:
        """Write the ring to ``stream`` (default stderr), once."""
        if self.dumped:
            return
        self.dumped = True
        stream = stream if stream is not None else sys.stderr
        stream.write(
            f"=== tpudes flight recorder: last {len(self._ring)} events"
            f"{' — ' + reason if reason else ''} ===\n"
        )
        for ts, ctx, uid, label in self._ring:
            stream.write(f"  ts={ts} ctx={ctx} uid={uid} {label}\n")
        stream.write("=== end flight recorder ===\n")
