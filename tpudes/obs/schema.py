"""Shared primitive for the dependency-free obs schema validators
(``validate_serving_metrics``, ``validate_fuzz_metrics``): one
``need()`` closure per problems list, so every validator reports
type/presence violations with identical wording.
"""

from __future__ import annotations

__all__ = ["make_need"]


def make_need(problems: list[str]):
    """A ``need(obj, key, types, where)`` closure that appends a
    human-readable problem on failure and returns the value (or None)."""

    def need(obj, key, types, where):
        if not isinstance(obj, dict):
            problems.append(f"{where}: not an object")
            return None
        if key not in obj:
            problems.append(f"{where}: missing key {key!r}")
            return None
        if not isinstance(obj[key], types):
            problems.append(
                f"{where}.{key}: expected {types}, got "
                f"{type(obj[key]).__name__}"
            )
            return None
        return obj[key]

    return need
