"""Chrome-trace-event (Perfetto-loadable) export of a profiled run.

The output is the JSON Object Format of the Trace Event spec: a top
object with a ``traceEvents`` array, loadable in ``chrome://tracing``
and https://ui.perfetto.dev unchanged.  Spans are wall-clock (ts/dur in
microseconds since run start) because the question the exporter answers
is "where did the *wall time* go"; each span carries the simulated
timestamp and context in ``args`` so the two clocks can be correlated.

Layout: tid 0 carries host event spans, tid 1 the engine windows, plus
a queue-depth counter track and one metadata record per track.  When a
FlowMonitor (host or device) is passed along, tid 2 carries one span
per flow — those run on the *simulated* clock (first tx → last rx, µs),
which the track name flags so the two time bases aren't conflated.  The
validator is dependency-free (no jsonschema in the image) and is what
the CI smoke step runs over a real exported trace.
"""

from __future__ import annotations

import json

#: event phases the validator accepts (Trace Event Format, table 1)
_KNOWN_PHASES = set("BEXiICnbesftTPNODMVvRcG()")

_PID = 1
_TID_EVENTS = 0
_TID_WINDOWS = 1
_TID_FLOWS = 2


def flow_trace_events(stats) -> list[dict]:
    """Per-flow "X" spans for the flow track (tid 2) from a
    ``{flow_id: FlowStats}`` map — the shape both
    ``FlowMonitor.GetFlowStats`` and
    ``DeviceFlowMonitor.GetFlowStats`` return.  Unlike the wall-clock
    tracks, these run on the simulated clock (first tx → last rx)."""
    events: list[dict] = [
        {"ph": "M", "pid": _PID, "tid": _TID_FLOWS, "name": "thread_name",
         "args": {"name": "flows (sim time)"}},
    ]
    for fid, st in sorted(stats.items()):
        t0 = st.time_first_tx_s
        if t0 is None or t0 < 0:
            continue
        t1 = st.time_last_rx_s
        end = t1 if t1 is not None and t1 >= t0 else t0
        events.append({
            "ph": "X", "pid": _PID, "tid": _TID_FLOWS,
            "name": f"flow {fid}", "cat": "flow",
            "ts": round(t0 * 1e6, 3), "dur": round((end - t0) * 1e6, 3),
            "args": {
                "txPackets": st.tx_packets, "txBytes": st.tx_bytes,
                "rxPackets": st.rx_packets, "rxBytes": st.rx_bytes,
                "lostPackets": st.lost_packets,
                "delaySumNs": round(st.delay_sum_s * 1e9),
                "jitterSumNs": round(st.jitter_sum_s * 1e9),
            },
        })
    return events


def chrome_trace(profiler, flow_stats=None) -> dict:
    """Build the trace document from a ``HostProfiler``; pass a
    ``{flow_id: FlowStats}`` map to merge per-flow spans as tid 2."""
    events: list[dict] = [
        {"ph": "M", "pid": _PID, "tid": _TID_EVENTS, "name": "process_name",
         "args": {"name": "tpudes"}},
        {"ph": "M", "pid": _PID, "tid": _TID_EVENTS, "name": "thread_name",
         "args": {"name": "host events"}},
        {"ph": "M", "pid": _PID, "tid": _TID_WINDOWS, "name": "thread_name",
         "args": {"name": "engine windows"}},
    ]
    depth = 0
    for label, t0, dur_s, sim_ts, context in profiler.spans:
        events.append({
            "ph": "X", "pid": _PID, "tid": _TID_EVENTS,
            "name": label, "cat": "event",
            "ts": round(t0 * 1e6, 3), "dur": round(dur_s * 1e6, 3),
            "args": {"sim_ts": sim_ts, "context": context},
        })
    for i, (t0, dur_s, n_events, refreshes) in enumerate(profiler.windows):
        events.append({
            "ph": "X", "pid": _PID, "tid": _TID_WINDOWS,
            "name": "window", "cat": "window",
            "ts": round(t0 * 1e6, 3), "dur": round(dur_s * 1e6, 3),
            "args": {"index": i, "events": n_events, "refreshes": refreshes},
        })
        depth += n_events
        events.append({
            "ph": "C", "pid": _PID, "tid": _TID_WINDOWS,
            "name": "events_cum", "ts": round(t0 * 1e6, 3),
            "args": {"events": depth},
        })
    events.append({
        "ph": "C", "pid": _PID, "tid": _TID_EVENTS, "name": "queue_depth",
        "ts": 0,
        "args": {"depth_max": profiler.queue_depth_max,
                 "depth_final": profiler.resync_depth()},
    })
    if flow_stats:
        events.extend(flow_trace_events(flow_stats))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": profiler.summary(),
    }


def export_chrome_trace(profiler, path: str, flow_stats=None) -> dict:
    doc = chrome_trace(profiler, flow_stats)
    with open(path, "w") as f:
        json.dump(doc, f)
    return doc


def export_on_destroy(profiler) -> None:
    """Engine hook: write the trace if ``TpudesObsTrace`` names a path
    (called from ``Simulator.Destroy`` while GlobalValues are live)."""
    from tpudes.core.global_value import GlobalValue

    path = GlobalValue.GetValueFailSafe("TpudesObsTrace", "")
    if path:
        export_chrome_trace(profiler, str(path))


# --- schema validation (dependency-free) -----------------------------------

def validate_chrome_trace(doc) -> list[str]:
    """Return every way ``doc`` violates the Trace Event JSON Object
    Format (empty list = valid).  Checks structure, required per-phase
    fields, and value types — the contract chrome://tracing actually
    relies on."""
    problems: list[str] = []
    if not isinstance(doc, dict):
        return ["top level is not an object"]
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["'traceEvents' missing or not an array"]
    if not events:
        problems.append("'traceEvents' is empty")
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            problems.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if not isinstance(ph, str) or len(ph) != 1 or ph not in _KNOWN_PHASES:
            problems.append(f"{where}: bad phase {ph!r}")
            continue
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            problems.append(f"{where}: missing/empty 'name'")
        for field in ("pid", "tid"):
            if field in ev and not isinstance(ev[field], int):
                problems.append(f"{where}: '{field}' is not an integer")
        if "args" in ev and not isinstance(ev["args"], dict):
            problems.append(f"{where}: 'args' is not an object")
        if ph in "XBEiIC":
            ts = ev.get("ts")
            if not isinstance(ts, (int, float)) or ts < 0:
                problems.append(f"{where}: '{ph}' needs numeric ts >= 0")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"{where}: 'X' needs numeric dur >= 0")
        if ph == "M" and "args" not in ev:
            problems.append(f"{where}: metadata record without 'args'")
    return problems


def assert_valid_chrome_trace(doc) -> None:
    problems = validate_chrome_trace(doc)
    if problems:
        raise ValueError(
            "invalid Chrome trace: " + "; ".join(problems[:10])
            + (f" (+{len(problems) - 10} more)" if len(problems) > 10 else "")
        )
