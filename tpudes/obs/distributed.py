"""Distributed/hybrid PDES observability: the window protocol's metrics.

:class:`DistributedTelemetry` is the process-global registry the hybrid
window drivers (:mod:`tpudes.parallel.hybrid`) record into — one record
per granted window per rank: the grant size in slots, boundary-traffic
volume (packets demuxed out of / injected into the device buffers), and
the wall time of each protocol phase (device poll/D2H, flush exchange,
grant reduction, window advance).  ``MpiInterface``'s transport
counters ride :meth:`record_transport`.

Rank processes snapshot at exit; the parent merges the per-rank
snapshots with :meth:`absorb` so one document describes the whole
launch.  :func:`validate_distributed_metrics` is the schema gate
(``python -m tpudes.obs --distributed metrics.json``) the CI hybrid
smoke runs over the dumped artifact — following the
:class:`~tpudes.obs.serving.ServingTelemetry` /
:class:`~tpudes.obs.fuzz.FuzzTelemetry` shape: recording is a dict
update, snapshots are computed on demand, reset is explicit.
"""

from __future__ import annotations

import time

__all__ = [
    "DistributedTelemetry",
    "validate_distributed_metrics",
    "wall_now",
]


def wall_now() -> float:
    """Monotonic wall clock for the window drivers' phase telemetry.
    Lives HERE (not in ``tpudes/parallel/``) because wall-clock reads
    are an observability concern: the analysis JP001 rule bans ``time.*``
    module-wide on the device path, and the drivers' per-phase timing is
    host-side bookkeeping that belongs to this registry."""
    return time.monotonic()

_PHASES = ("poll", "flush", "grant", "advance")


class DistributedTelemetry:
    """Process-wide hybrid-PDES metrics registry (cumulative since
    reset)."""

    _ranks: dict[int, dict] = {}

    @classmethod
    def _rank(cls, rank: int) -> dict:
        return cls._ranks.setdefault(
            int(rank),
            {
                "windows": 0,
                "grant_slots_sum": 0,
                "grant_slots_max": 0,
                "tx_pkts": 0,
                "rx_pkts": 0,
                "transport_tx": 0,
                "transport_rx": 0,
                **{f"{p}_wall_s": 0.0 for p in _PHASES},
            },
        )

    @classmethod
    def record_window(
        cls,
        rank: int,
        *,
        grant_slots: int,
        tx_pkts: int,
        rx_pkts: int,
        poll_wall_s: float,
        flush_wall_s: float,
        grant_wall_s: float,
        advance_wall_s: float,
    ) -> None:
        r = cls._rank(rank)
        r["windows"] += 1
        r["grant_slots_sum"] += int(grant_slots)
        r["grant_slots_max"] = max(r["grant_slots_max"], int(grant_slots))
        r["tx_pkts"] += int(tx_pkts)
        r["rx_pkts"] += int(rx_pkts)
        r["poll_wall_s"] += float(poll_wall_s)
        r["flush_wall_s"] += float(flush_wall_s)
        r["grant_wall_s"] += float(grant_wall_s)
        r["advance_wall_s"] += float(advance_wall_s)

    @classmethod
    def record_transport(cls, rank: int, tx: int, rx: int) -> None:
        """Fold in ``MpiInterface``'s per-rank rx/tx frame counters."""
        r = cls._rank(rank)
        r["transport_tx"] += int(tx)
        r["transport_rx"] += int(rx)

    @classmethod
    def absorb(cls, snapshot: dict) -> None:
        """Merge a rank process's snapshot into this registry (the
        parent-side gather after a ``transport="mpi"`` launch)."""
        for rank, r in snapshot.get("ranks", {}).items():
            mine = cls._rank(int(rank))
            mine["windows"] += r["windows"]
            # the raw sum rides the snapshot so the merge is exact;
            # reconstructing from the 3-decimal rounded mean would
            # drift on long runs
            mine["grant_slots_sum"] += r["grant_slots_sum"]
            mine["grant_slots_max"] = max(
                mine["grant_slots_max"], r["grant_slots_max"]
            )
            for k in ("tx_pkts", "rx_pkts", "transport_tx", "transport_rx"):
                mine[k] += r[k]
            for p in _PHASES:
                mine[f"{p}_wall_s"] += r[f"{p}_wall_s"]

    # --- reading ----------------------------------------------------------

    @classmethod
    def snapshot(cls) -> dict:
        ranks = {}
        counters = {"windows": 0, "boundary_tx": 0, "boundary_rx": 0}
        for rank, r in sorted(cls._ranks.items()):
            wall = sum(r[f"{p}_wall_s"] for p in _PHASES)
            n = r["windows"]
            ranks[str(rank)] = {
                "windows": n,
                "wall_s": round(wall, 6),
                "windows_per_s": round(n / wall, 3) if wall > 0 else 0.0,
                "grant_slots_sum": r["grant_slots_sum"],
                "grant_slots_mean": (
                    round(r["grant_slots_sum"] / n, 3) if n else 0.0
                ),
                "grant_slots_max": r["grant_slots_max"],
                "tx_pkts": r["tx_pkts"],
                "rx_pkts": r["rx_pkts"],
                "transport_tx": r["transport_tx"],
                "transport_rx": r["transport_rx"],
                **{
                    f"{p}_wall_s": round(r[f"{p}_wall_s"], 6)
                    for p in _PHASES
                },
            }
            counters["windows"] += n
            counters["boundary_tx"] += r["tx_pkts"]
            counters["boundary_rx"] += r["rx_pkts"]
        return {"version": 1, "counters": counters, "ranks": ranks}

    @classmethod
    def reset(cls) -> None:
        cls._ranks = {}


def validate_distributed_metrics(doc) -> list[str]:
    """Schema check for a :meth:`DistributedTelemetry.snapshot`
    document (dependency-free, mirroring ``validate_serving_metrics``).
    Returns human-readable problems; empty means valid."""
    from tpudes.obs.schema import make_need

    problems: list[str] = []
    need = make_need(problems)

    if not isinstance(doc, dict):
        return ["top level: not a JSON object"]
    if doc.get("version") != 1:
        problems.append("version: expected 1")
    counters = need(doc, "counters", dict, "top level")
    if counters is not None:
        for k in ("windows", "boundary_tx", "boundary_rx"):
            v = need(counters, k, int, "counters")
            if isinstance(v, int) and v < 0:
                problems.append(f"counters.{k}: negative")
    ranks = need(doc, "ranks", dict, "top level")
    if ranks is not None:
        for name, r in ranks.items():
            where = f"ranks.{name}"
            if not name.isdigit():
                problems.append(f"{where}: rank key is not an integer")
            windows = need(r, "windows", int, where)
            need(r, "wall_s", (int, float), where)
            need(r, "windows_per_s", (int, float), where)
            need(r, "grant_slots_sum", int, where)
            need(r, "grant_slots_mean", (int, float), where)
            need(r, "grant_slots_max", int, where)
            for k in ("tx_pkts", "rx_pkts", "transport_tx", "transport_rx"):
                need(r, k, int, where)
            for p in _PHASES:
                need(r, f"{p}_wall_s", (int, float), where)
            if isinstance(windows, int) and windows < 0:
                problems.append(f"{where}.windows: negative")
    return problems
