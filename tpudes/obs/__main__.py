"""Validate tpudes.obs export files against their schemas.

Usage::

    python -m tpudes.obs <trace.json> [more.json ...]
    python -m tpudes.obs --serving <metrics.json> [more.json ...]
    python -m tpudes.obs --fuzz <metrics.json> [more.json ...]
    python -m tpudes.obs --distributed <metrics.json> [more.json ...]
    python -m tpudes.obs --geometry <metrics.json> [more.json ...]
    python -m tpudes.obs --traffic <metrics.json> [more.json ...]
    python -m tpudes.obs --grad <metrics.json> [more.json ...]
    python -m tpudes.obs --flowmon <flowmon.xml> [more.xml ...]
    python -m tpudes.obs --pcap <capture.pcap> [more.pcap ...]

Default mode checks Chrome-trace exports against the Trace Event
format; ``--serving`` checks :class:`tpudes.obs.serving.ServingTelemetry`
snapshot dumps against the serving-metrics schema; ``--fuzz`` checks
:class:`tpudes.obs.fuzz.FuzzTelemetry` snapshot dumps against the
fuzz-metrics schema; ``--distributed`` checks
:class:`tpudes.obs.distributed.DistributedTelemetry` snapshot dumps
against the hybrid-PDES window-protocol schema; ``--geometry`` checks
:class:`tpudes.obs.geometry.GeomTelemetry` snapshot dumps against the
geometry-refresh schema (device recomputes vs host refreshes, stride
hit rate); ``--traffic`` checks
:class:`tpudes.obs.traffic.TrafficTelemetry` snapshot dumps against
the workload schema (offered vs delivered load, per-model launch
counts, burst duty cycle); ``--grad`` checks
:class:`tpudes.obs.grad.GradTelemetry` snapshot dumps against the
gradient schema (grad-norm/loss rings, descent step counters,
non-finite canaries); ``--flowmon`` checks FlowMonitor XML exports
(ours or upstream ns-3's ``SerializeToXmlFile``) for the standard
FlowStats attribute set; ``--pcap`` structurally validates classic
libpcap captures (both byte orders, µs and ns magic) record by record
— these two read XML / raw bytes, not JSON.  Exit 0 when every
file is valid, 1 on
violations, 2 on usage / unreadable input.  These are the schema gates
the CI smoke steps run over the artifacts an example (``TpudesObs=1``),
the serving smoke, and the fuzz smoke produce.
"""

from __future__ import annotations

import json
import sys

from tpudes.obs.distributed import validate_distributed_metrics
from tpudes.obs.export import validate_chrome_trace
from tpudes.obs.fuzz import validate_fuzz_metrics
from tpudes.obs.geometry import validate_geometry_metrics
from tpudes.obs.grad import validate_grad_metrics
from tpudes.obs.serving import validate_serving_metrics
from tpudes.obs.traffic import validate_traffic_metrics


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    serving = "--serving" in argv
    fuzz = "--fuzz" in argv
    distributed = "--distributed" in argv
    geometry = "--geometry" in argv
    traffic = "--traffic" in argv
    grad = "--grad" in argv
    flowmon = "--flowmon" in argv
    pcap = "--pcap" in argv
    argv = [
        a for a in argv
        if a not in ("--serving", "--fuzz", "--distributed",
                     "--geometry", "--traffic", "--grad",
                     "--flowmon", "--pcap")
    ]
    if (
        not argv
        or serving + fuzz + distributed + geometry + traffic + grad
        + flowmon + pcap > 1
        or any(a in ("-h", "--help") for a in argv)
    ):
        print(__doc__, file=sys.stderr)
        return 2
    if flowmon or pcap:
        # non-JSON modes: FlowMonitor XML / raw libpcap bytes
        from tpudes.obs.flowmon import validate_flowmon_xml, validate_pcap

        rc = 0
        for path in argv:
            try:
                if pcap:
                    with open(path, "rb") as f:
                        problems, n = validate_pcap(f.read())
                else:
                    with open(path, encoding="utf-8") as f:
                        problems, n = validate_flowmon_xml(f.read())
            except OSError as e:
                print(f"{path}: unreadable ({e})", file=sys.stderr)
                return 2
            if problems:
                rc = 1
                for p in problems:
                    print(f"{path}: {p}")
            else:
                kind = "pcap capture" if pcap else "FlowMonitor XML"
                print(f"{path}: valid {kind} ({n} records)")
        return rc
    if serving:
        validate, kind = validate_serving_metrics, "serving metrics"
    elif fuzz:
        validate, kind = validate_fuzz_metrics, "fuzz metrics"
    elif distributed:
        validate, kind = validate_distributed_metrics, "distributed metrics"
    elif geometry:
        validate, kind = validate_geometry_metrics, "geometry metrics"
    elif traffic:
        validate, kind = validate_traffic_metrics, "traffic metrics"
    elif grad:
        validate, kind = validate_grad_metrics, "gradient metrics"
    else:
        validate, kind = validate_chrome_trace, "Chrome trace"
    rc = 0
    for path in argv:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"{path}: unreadable ({e})", file=sys.stderr)
            return 2
        problems = validate(doc)
        if problems:
            rc = 1
            for p in problems:
                print(f"{path}: {p}")
        else:
            if serving:
                n = len(doc["engines"])
            elif fuzz:
                n = doc["counters"]["scenarios"]
            elif distributed:
                n = doc["counters"]["windows"]
            elif geometry or traffic or grad:
                n = len(doc["engines"])
            else:
                n = len(doc["traceEvents"])
            print(f"{path}: valid {kind} ({n} records)")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
