"""Validate a Chrome-trace export against the Trace Event format.

Usage::

    python -m tpudes.obs <trace.json> [more.json ...]

Exit 0 when every file is a valid trace, 1 on violations, 2 on usage /
unreadable input.  This is the schema gate the CI smoke step runs over
the trace exported by an example under ``TpudesObs=1``.
"""

from __future__ import annotations

import json
import sys

from tpudes.obs.export import validate_chrome_trace


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv or any(a in ("-h", "--help") for a in argv):
        print(__doc__, file=sys.stderr)
        return 2
    rc = 0
    for path in argv:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"{path}: unreadable ({e})", file=sys.stderr)
            return 2
        problems = validate_chrome_trace(doc)
        if problems:
            rc = 1
            for p in problems:
                print(f"{path}: {p}")
        else:
            n = len(doc["traceEvents"])
            print(f"{path}: valid Chrome trace ({n} records)")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
