"""Interference chunk-SNR / PER kernel.

Reference parity: src/wifi/model/interference-helper.{h,cc} (upstream
path; mount empty at survey — SURVEY.md §0).  Upstream tracks overlapping
signals as noise-interference events and splits a received PPDU into SNR
"chunks" at each event boundary, multiplying per-chunk success
probabilities into a packet success rate (SURVEY.md §3.2).

TPU-first design: per received frame we carry a FIXED number K of
candidate interferers (padded + masked).  2K+2 boundary times → 2K+1
chunks, all static shapes: sort, midpoint-test activity, elementwise
success, product.  One frame is one row; vmap gives the
(frame × replica) batch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpudes.ops.wifi_error import mode_chunk_success_rate

BOLTZMANN = 1.380649e-23


def thermal_noise_w(bandwidth_hz, noise_figure_db=7.0, temperature_k=290.0):
    """Noise floor in watts: F · k·T·B (WifiPhy::SetNoiseFigure math)."""
    nt = BOLTZMANN * temperature_k * bandwidth_hz
    return 10.0 ** (noise_figure_db / 10.0) * nt


def frame_success_rate(
    signal_w: jax.Array,        # () received frame power in W
    frame_start: jax.Array,     # () frame start time (s, or any unit)
    frame_end: jax.Array,       # () frame end
    mode_index: jax.Array,      # () int32 WifiMode id
    data_rate_bps: jax.Array,   # () PHY data rate (bits/s of payload)
    noise_w: jax.Array,         # () noise floor in W
    int_power_w: jax.Array,     # (K,) interferer powers
    int_start: jax.Array,       # (K,) interferer start times
    int_end: jax.Array,         # (K,) interferer end times
    int_mask: jax.Array,        # (K,) 1.0 = real interferer, 0.0 = padding
) -> jax.Array:
    """Packet success probability of one frame under K padded interferers.

    Mirrors InterferenceHelper::CalculatePayloadPer: chunked SNR between
    interference-event boundaries, per-chunk NIST success, product.
    """
    # clip interferer intervals to the frame, padding collapses to empty
    s = jnp.clip(int_start, frame_start, frame_end)
    e = jnp.clip(int_end, frame_start, frame_end)
    s = jnp.where(int_mask > 0, s, frame_start)
    e = jnp.where(int_mask > 0, e, frame_start)

    bounds = jnp.concatenate(
        [jnp.stack([frame_start, frame_end]), s, e]
    )  # (2K+2,)
    bounds = jnp.sort(bounds)
    c_start = bounds[:-1]                       # (2K+1,)
    c_end = bounds[1:]
    dur = jnp.maximum(c_end - c_start, 0.0)
    mid = 0.5 * (c_start + c_end)               # (2K+1,)

    # interference active at each chunk midpoint: (2K+1, K) → (2K+1,)
    active = (
        (int_start[None, :] <= mid[:, None])
        & (mid[:, None] < int_end[None, :])
        & (int_mask[None, :] > 0)
    )
    i_w = jnp.sum(jnp.where(active, int_power_w[None, :], 0.0), axis=-1)

    snr = signal_w / (noise_w + i_w)
    nbits = data_rate_bps * dur
    succ = mode_chunk_success_rate(snr, nbits, mode_index)
    # zero-length chunks contribute success=1 (nbits=0 ⇒ (1-pe)^0)
    return jnp.prod(jnp.where(dur > 0, succ, 1.0))


#: batched over frames: all args gain a leading frame axis
batch_frame_success_rate = jax.vmap(frame_success_rate)


def snr_db(signal_w: jax.Array, noise_w: jax.Array, interference_w: jax.Array = 0.0):
    return 10.0 * jnp.log10(signal_w / (noise_w + interference_w))
