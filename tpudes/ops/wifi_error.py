"""NIST error-rate model as a batched TPU kernel.

Reference parity: src/wifi/model/nist-error-rate-model.{h,cc} and the
WifiMode/ WifiTxVector mode metadata in src/wifi/model/wifi-mode.{h,cc}
(upstream paths; mount empty at survey — SURVEY.md §0).  The underlying
math is public: per-modulation AWGN BER (erfc closed forms) and the
union bound over the first ten terms of the K=7 convolutional code
distance spectrum (Frenger/Haccoun–Bégin weight tables, as used by the
NIST 802.11 model doc).

TPU-first design: a *mode* is an integer index into constant arrays
(constellation size, coding-rate class, data rate).  ``chunk_success_rate``
is pure elementwise math over (snr, nbits, mode) arrays — vmapping it over
a (tx × rx × chunk × replica) batch is the whole point (SURVEY.md §3.2:
the NistErrorRateModel::GetChunkSuccessRate leaf of the WiFi hot path).
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.scipy.special import erfc

# --- coding-rate classes (bValue in upstream terms) ------------------------
# index: 0 → rate 1/2 (b=1), 1 → rate 2/3 (b=2), 2 → rate 3/4 (b=3),
#        3 → rate 5/6 (b=5)
# Python lists are the float64 source of truth (used by the test oracle);
# the jnp arrays the kernel reads are built from them below.
B_FACTOR_TABLE = [1.0 / 2.0, 1.0 / 4.0, 1.0 / 6.0, 1.0 / 10.0]

# union-bound distance-spectrum weights a_d and distances d for the K=7
# convolutional code at each puncturing (first ten terms; rate 1/2 has
# nine published terms, padded with zero)
PE_COEFFS_TABLE = [
    # rate 1/2 (free distance 10)
    [36.0, 211.0, 1404.0, 11633.0, 77433.0, 502690.0, 3322763.0,
     21292910.0, 134365911.0, 0.0],
    # rate 2/3 (free distance 6)
    [3.0, 70.0, 285.0, 1276.0, 6160.0, 27128.0, 117019.0,
     498860.0, 2103891.0, 8784123.0],
    # rate 3/4 (free distance 5)
    [42.0, 201.0, 1492.0, 10469.0, 62935.0, 379644.0, 2253373.0,
     13073811.0, 75152755.0, 428005675.0],
    # rate 5/6 (free distance 4)
    [92.0, 528.0, 8694.0, 79453.0, 792114.0, 7375573.0, 67884974.0,
     610875423.0, 5427275376.0, 47664215639.0],
]
PE_EXPONENTS_TABLE = [
    [10.0, 12.0, 14.0, 16.0, 18.0, 20.0, 22.0, 24.0, 26.0, 28.0],
    [6.0, 7.0, 8.0, 9.0, 10.0, 11.0, 12.0, 13.0, 14.0, 15.0],
    [5.0, 6.0, 7.0, 8.0, 9.0, 10.0, 11.0, 12.0, 13.0, 14.0],
    [4.0, 5.0, 6.0, 7.0, 8.0, 9.0, 10.0, 11.0, 12.0, 13.0],
]

# NOTE: kept as numpy at module scope so importing this module never
# touches a JAX backend (the driver's dryrun forces the CPU platform
# *after* imports; a module-level jnp.array would pin the default
# backend first).  jnp.asarray at the use sites is free inside jit.
import numpy as _np

_B_FACTOR = _np.array(B_FACTOR_TABLE)
_PE_COEFFS = _np.array(PE_COEFFS_TABLE)
_PE_EXPONENTS = _np.array(PE_EXPONENTS_TABLE)

RATE_1_2, RATE_2_3, RATE_3_4, RATE_5_6 = 0, 1, 2, 3


# erfc-argument divisors per upstream's GetMQamBer closed forms: z =
# √(snr/div).  Only 16-QAM coincides with the textbook 2(M-1)/3; the
# higher orders use (√M−1)·log2(√M)-family constants (ADVICE r2 medium).
# Shared by the jnp kernel and the f64 oracle so they cannot drift.
QAM_DIVISORS = {16.0: 10.0, 64.0: 21.0, 256.0: 60.0, 1024.0: 155.0}


def _qam_ber(snr: jax.Array, m: jax.Array) -> jax.Array:
    """Gray-coded square M-QAM AWGN BER:
    2(1-1/√M)/log2(M) · erfc(√(snr/div(M))) with upstream's per-M
    divisors (QAM_DIVISORS) — no extra ½ factor."""
    log2m = jnp.log2(m)
    # divisors pinned to the snr dtype: a where() over bare python
    # floats would select at f64 under ambient x64 (JXL002)
    d16, d64, d256, d1024 = (
        jnp.asarray(QAM_DIVISORS[k], snr.dtype)
        for k in (16.0, 64.0, 256.0, 1024.0)
    )
    div = jnp.where(
        m <= 16.0, d16, jnp.where(m <= 64.0, d64, jnp.where(m <= 256.0, d256, d1024))
    )
    z = jnp.sqrt(snr / div)
    return (2.0 * (1.0 - 1.0 / jnp.sqrt(m)) / log2m) * erfc(z)


def uncoded_ber(snr: jax.Array, constellation: jax.Array) -> jax.Array:
    """Per-bit AWGN error probability by constellation size.

    BPSK (2): ½erfc(√snr); QPSK (4): ½erfc(√(snr/2)); M-QAM: closed form.
    ``snr`` is linear per-symbol SNR, as in the upstream call convention.
    """
    constellation = jnp.asarray(constellation, dtype=snr.dtype)
    bpsk = 0.5 * erfc(jnp.sqrt(snr))
    qpsk = 0.5 * erfc(jnp.sqrt(snr / 2.0))
    qam = _qam_ber(snr, jnp.maximum(constellation, 16.0))
    return jnp.where(
        constellation <= 2.0, bpsk, jnp.where(constellation <= 4.0, qpsk, qam)
    )


def coded_pe(ber: jax.Array, rate_class: jax.Array) -> jax.Array:
    """First-event error probability union bound (CalculatePe): with
    D = √(4p(1-p)), pe = factor(b) · Σ a_k D^e_k, clamped to [0, 1]."""
    p = jnp.clip(ber, 0.0, 0.5)
    d = jnp.sqrt(4.0 * p * (1.0 - p))
    # dtypes pinned f32: the host tables are f64 numpy, and an
    # unpinned asarray would ride f64 through the whole PSR chain
    # under ambient x64 (analysis rule JXL002)
    coeffs = jnp.asarray(_PE_COEFFS, jnp.float32)[rate_class]  # (..., 10)
    exps = jnp.asarray(_PE_EXPONENTS, jnp.float32)[rate_class]  # (..., 10)
    factor = jnp.asarray(_B_FACTOR, jnp.float32)[rate_class]
    # stable evaluation: a_k D^e_k = exp(log a_k + e_k log D); D=0 → 0
    log_d = jnp.log(jnp.maximum(d, 1e-35))
    terms = jnp.where(
        coeffs[..., :] > 0.0,
        jnp.exp(jnp.log(jnp.maximum(coeffs, 1e-35)) + exps * log_d[..., None]),
        0.0,
    )
    pe = factor * jnp.sum(terms, axis=-1)
    return jnp.clip(pe, 0.0, 1.0)


def chunk_success_rate(
    snr: jax.Array, nbits: jax.Array, constellation: jax.Array, rate_class: jax.Array
) -> jax.Array:
    """NistErrorRateModel::GetChunkSuccessRate: (1 - pe)^nbits via the
    numerically stable exp(nbits·log1p(-pe)) form."""
    ber = uncoded_ber(snr, constellation)
    pe = coded_pe(ber, rate_class)
    pe = jnp.minimum(pe, 1.0 - 1e-12)
    return jnp.exp(nbits * jnp.log1p(-pe))


# --- mode registry ---------------------------------------------------------


@dataclass(frozen=True)
class WifiMode:
    """One entry of the WifiMode registry (wifi-mode.{h,cc} analog):
    enough metadata for rate selection, duration math, and the error
    kernel's (constellation, rate_class) lookup."""

    name: str
    index: int
    constellation: int      # 2 BPSK, 4 QPSK, 16/64/256/1024 QAM
    rate_class: int         # RATE_* above
    data_rate_bps: int      # PHY data rate at 20 MHz, 800 ns GI, 1 SS
    bits_per_symbol: float  # data bits per OFDM symbol (duration math)
    standard: str = "ofdm"

    def GetDataRate(self) -> int:
        return self.data_rate_bps

    def GetUniqueName(self) -> str:
        return self.name


def _ofdm_modes():
    # 802.11a/g 20 MHz OFDM: 48 data subcarriers, 4 µs symbol
    table = [
        ("OfdmRate6Mbps", 2, RATE_1_2, 6e6),
        ("OfdmRate9Mbps", 2, RATE_3_4, 9e6),
        ("OfdmRate12Mbps", 4, RATE_1_2, 12e6),
        ("OfdmRate18Mbps", 4, RATE_3_4, 18e6),
        ("OfdmRate24Mbps", 16, RATE_1_2, 24e6),
        ("OfdmRate36Mbps", 16, RATE_3_4, 36e6),
        ("OfdmRate48Mbps", 64, RATE_2_3, 48e6),
        ("OfdmRate54Mbps", 64, RATE_3_4, 54e6),
    ]
    return [
        WifiMode(name, i, m, b, int(rate), rate * 4e-6)
        for i, (name, m, b, rate) in enumerate(table)
    ]


def _ht_he_modes(start_index: int):
    # HT/VHT/HE MCS ladder (1 SS, 20 MHz, long GI); HE rates use 13.6 µs
    # symbols but the error-model metadata (constellation, rate) is what
    # matters here — duration math uses bits_per_symbol.
    ladder = [
        ("HtMcs0", 2, RATE_1_2, 6.5e6),
        ("HtMcs1", 4, RATE_1_2, 13e6),
        ("HtMcs2", 4, RATE_3_4, 19.5e6),
        ("HtMcs3", 16, RATE_1_2, 26e6),
        ("HtMcs4", 16, RATE_3_4, 39e6),
        ("HtMcs5", 64, RATE_2_3, 52e6),
        ("HtMcs6", 64, RATE_3_4, 58.5e6),
        ("HtMcs7", 64, RATE_5_6, 65e6),
        ("VhtMcs8", 256, RATE_3_4, 78e6),
        ("VhtMcs9", 256, RATE_5_6, 86.7e6),
        ("HeMcs10", 1024, RATE_3_4, 97.5e6),
        ("HeMcs11", 1024, RATE_5_6, 108.3e6),
    ]
    return [
        WifiMode(name, start_index + i, m, b, int(rate), rate * 4e-6, standard="ht")
        for i, (name, m, b, rate) in enumerate(ladder)
    ]


OFDM_MODES = _ofdm_modes()
HT_MODES = _ht_he_modes(len(OFDM_MODES))
ALL_MODES = OFDM_MODES + HT_MODES
MODES_BY_NAME = {m.name: m for m in ALL_MODES}

#: constant per-mode lookup arrays for the kernel side — index with the
#: integer mode id carried in packed tx tensors (numpy at module scope;
#: see the backend note above _B_FACTOR)
MODE_CONSTELLATION = _np.array([m.constellation for m in ALL_MODES], dtype=_np.float32)
MODE_RATE_CLASS = _np.array([m.rate_class for m in ALL_MODES], dtype=_np.int32)
MODE_DATA_RATE = _np.array([m.data_rate_bps for m in ALL_MODES], dtype=_np.float32)


def mode_chunk_success_rate(
    snr: jax.Array, nbits: jax.Array, mode_index: jax.Array
) -> jax.Array:
    """Success rate with the mode resolved from the registry by index —
    the form the window kernel uses on packed tensors."""
    constellation = jnp.asarray(MODE_CONSTELLATION)[mode_index]
    rate_class = jnp.asarray(MODE_RATE_CLASS)[mode_index]
    return chunk_success_rate(snr, nbits, constellation, rate_class)


# --- table-based error model (table-based-error-rate-model.{h,cc} analog) --
#
# Upstream's default model for HE ships link-simulation PER LUTs keyed
# (MCS, payload 1458/32 B) and interpolates PER linearly over SNR dB,
# scaling to other sizes via PER_L = 1-(1-PER_ref)^(L/L_ref).  The LUT
# *architecture* (grid, interpolation, size-scaling law) is reproduced
# here; the table values themselves are generated at first use from the
# NIST closed forms above — the reference's tables come from offline PHY
# simulations this build cannot rerun, so ours are a documented
# deviation in provenance, not in mechanism.

TABLE_SNR_MIN_DB = -5.0
TABLE_SNR_STEP_DB = 0.5
TABLE_SNR_POINTS = 91            # -5 .. +40 dB
TABLE_REF_SIZE_BYTES = 1458      # upstream's large-payload table size

_PER_TABLE_CACHE: dict = {}


def per_table() -> "_np.ndarray":
    """(n_modes, TABLE_SNR_POINTS) float64 PER at TABLE_REF_SIZE_BYTES,
    generated once from the NIST closed forms."""
    tbl = _PER_TABLE_CACHE.get("table")
    if tbl is None:
        snrs_db = TABLE_SNR_MIN_DB + TABLE_SNR_STEP_DB * _np.arange(TABLE_SNR_POINTS)
        nbits = 8.0 * TABLE_REF_SIZE_BYTES
        tbl = _np.empty((len(ALL_MODES), TABLE_SNR_POINTS))
        for m in ALL_MODES:
            for j, snr_db in enumerate(snrs_db):
                ok = chunk_success_rate_py(
                    10.0 ** (snr_db / 10.0), nbits, m.constellation, m.rate_class
                )
                tbl[m.index, j] = 1.0 - ok
        _PER_TABLE_CACHE["table"] = tbl
    return tbl


def table_chunk_success_rate_py(snr: float, nbits: float, mode_index: int) -> float:
    """Host float64 LUT path: linear PER interpolation over SNR dB at the
    reference size, then the (1-PER)^(L/L_ref) size-scaling law."""
    tbl = per_table()[mode_index]
    snr_db = 10.0 * math.log10(max(snr, 1e-30))
    x = (snr_db - TABLE_SNR_MIN_DB) / TABLE_SNR_STEP_DB
    if x <= 0.0:
        per_ref = tbl[0]
    elif x >= TABLE_SNR_POINTS - 1:
        per_ref = tbl[-1]
    else:
        lo = int(x)
        frac = x - lo
        per_ref = tbl[lo] * (1.0 - frac) + tbl[lo + 1] * frac
    per_ref = min(per_ref, 1.0 - 1e-12)
    ref_bits = 8.0 * TABLE_REF_SIZE_BYTES
    return math.exp((nbits / ref_bits) * math.log1p(-per_ref))


def table_chunk_success_rate(
    snr: jax.Array, nbits: jax.Array, mode_index: jax.Array
) -> jax.Array:
    """Jittable LUT path mirroring :func:`table_chunk_success_rate_py` —
    the kernel-side form for PER-LUT studies on packed batches."""
    tbl = jnp.asarray(per_table(), dtype=jnp.float32)      # (M, K)
    snr_db = 10.0 * jnp.log10(jnp.maximum(snr, 1e-30))
    x = jnp.clip(
        (snr_db - TABLE_SNR_MIN_DB) / TABLE_SNR_STEP_DB, 0.0, TABLE_SNR_POINTS - 1.0
    )
    lo = jnp.clip(x.astype(jnp.int32), 0, TABLE_SNR_POINTS - 2)
    frac = x - lo.astype(x.dtype)
    row = tbl[mode_index]                                   # (..., K)
    per_lo = jnp.take_along_axis(row, lo[..., None], axis=-1)[..., 0]
    per_hi = jnp.take_along_axis(row, (lo + 1)[..., None], axis=-1)[..., 0]
    per_ref = jnp.minimum(per_lo * (1.0 - frac) + per_hi * frac, 1.0 - 1e-7)
    ref_bits = 8.0 * TABLE_REF_SIZE_BYTES
    return jnp.exp((nbits / ref_bits) * jnp.log1p(-per_ref))


# --- scalar host-side reference (float64, for tests & referee runs) --------


def chunk_success_rate_py(snr: float, nbits: float, constellation: int, rate_class: int) -> float:
    """Pure-Python float64 oracle mirroring the kernel; used by unit tests
    as the tolerance reference (SURVEY.md §4: f32 vs f64 checks)."""
    if constellation <= 2:
        ber = 0.5 * math.erfc(math.sqrt(snr))
    elif constellation <= 4:
        ber = 0.5 * math.erfc(math.sqrt(snr / 2.0))
    else:
        m = float(constellation)
        z = math.sqrt(snr / QAM_DIVISORS[m])
        ber = (2.0 * (1.0 - 1.0 / math.sqrt(m)) / math.log2(m)) * math.erfc(z)
    p = min(max(ber, 0.0), 0.5)
    d = math.sqrt(4.0 * p * (1.0 - p))
    coeffs = PE_COEFFS_TABLE[rate_class]
    exps = PE_EXPONENTS_TABLE[rate_class]
    factor = B_FACTOR_TABLE[rate_class]
    pe = factor * sum(c * d**e for c, e in zip(coeffs, exps) if c > 0)
    pe = min(pe, 1.0 - 1e-12)
    return math.exp(nbits * math.log1p(-pe))
