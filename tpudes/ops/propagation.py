"""Propagation loss & delay kernels — pure, jittable, vmappable.

Reference parity: src/propagation/model/propagation-loss-model.{h,cc} and
propagation-delay-model.{h,cc} (upstream module paths; the reference mount
was empty at survey time — see SURVEY.md §0 — so parity is against the
upstream ns-3 model semantics the north star names).

Design (TPU-first, SURVEY.md §7 step 5): every model is a pure function
``(tx_power_dbm, d, params...) -> rx_power_dbm`` over arrays of pairwise
distances. The O(N_tx × N_rx) loop in YansWifiChannel::Send (SURVEY.md
§3.2) becomes one batched evaluation over a distance matrix. Stochastic
models (Nakagami, random delay) take an explicit ``jax.random`` key — the
replica axis is one extra vmap over keys.

All math is float32 by default (TPU native); hosts may pass float64 arrays
when x64 is enabled for referee runs (SURVEY.md §7 hard part 5).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

SPEED_OF_LIGHT = 299792458.0

# --- helpers ---------------------------------------------------------------


def distance(pos_a: jax.Array, pos_b: jax.Array) -> jax.Array:
    """Euclidean distance between position rows (..., 3)."""
    return jnp.sqrt(jnp.sum((pos_a - pos_b) ** 2, axis=-1))


def pairwise_distance(positions: jax.Array) -> jax.Array:
    """(N, 3) positions -> (N, N) distance matrix (the YansWifiChannel
    tx×rx geometry in one shot)."""
    diff = positions[:, None, :] - positions[None, :, :]
    return jnp.sqrt(jnp.sum(diff * diff, axis=-1))


def dbm_to_w(dbm: jax.Array) -> jax.Array:
    return 10.0 ** ((dbm - 30.0) / 10.0)


def w_to_dbm(w: jax.Array) -> jax.Array:
    return 10.0 * jnp.log10(w) + 30.0


def db_to_ratio(db: jax.Array) -> jax.Array:
    return 10.0 ** (db / 10.0)


def ratio_to_db(ratio: jax.Array) -> jax.Array:
    return 10.0 * jnp.log10(ratio)


# --- deterministic loss models --------------------------------------------


def friis(
    tx_power_dbm: jax.Array,
    d: jax.Array,
    frequency_hz: float = 5.15e9,
    system_loss: float = 1.0,
    min_loss_db: float = 0.0,
) -> jax.Array:
    """Friis free-space loss (FriisPropagationLossModel::DoCalcRxPower).

    rx = tx - max(minLoss, -10 log10(λ² / (16 π² d² L))); d <= 0 gives
    tx - minLoss, matching the upstream short-distance clamp.
    """
    lam = SPEED_OF_LIGHT / frequency_hz
    numerator = lam * lam
    denominator = 16.0 * math.pi * math.pi * d * d * system_loss
    loss_db = -10.0 * jnp.log10(numerator / denominator)
    loss_db = jnp.maximum(loss_db, min_loss_db)
    return jnp.where(d <= 0.0, tx_power_dbm - min_loss_db, tx_power_dbm - loss_db)


#: Friis loss at 1 m, 5.15 GHz (upstream LogDistance default reference loss)
DEFAULT_REFERENCE_LOSS_DB = 46.6777


def log_distance(
    tx_power_dbm: jax.Array,
    d: jax.Array,
    exponent: float = 3.0,
    reference_distance: float = 1.0,
    reference_loss_db: float = DEFAULT_REFERENCE_LOSS_DB,
) -> jax.Array:
    """Log-distance path loss (LogDistancePropagationLossModel):
    L = L0 + 10 n log10(d/d0); d <= d0 pays only L0."""
    path_loss = reference_loss_db + 10.0 * exponent * jnp.log10(
        jnp.maximum(d, reference_distance) / reference_distance
    )
    return tx_power_dbm - path_loss


def three_log_distance(
    tx_power_dbm: jax.Array,
    d: jax.Array,
    d0: float = 1.0,
    d1: float = 200.0,
    d2: float = 500.0,
    exponent0: float = 1.9,
    exponent1: float = 3.8,
    exponent2: float = 3.8,
    reference_loss_db: float = DEFAULT_REFERENCE_LOSS_DB,
) -> jax.Array:
    """Three-slope log-distance (ThreeLogDistancePropagationLossModel):
    cumulative piecewise slopes over [d0,d1), [d1,d2), [d2,∞);
    0 dB path loss below d0 (upstream semantics)."""
    below_d0 = d < d0
    d = jnp.maximum(d, d0)
    # cumulative loss at the active breakpoints
    seg0 = 10.0 * exponent0 * jnp.log10(jnp.clip(d, d0, d1) / d0)
    seg1 = 10.0 * exponent1 * jnp.log10(jnp.clip(d, d1, d2) / d1)
    seg2 = 10.0 * exponent2 * jnp.log10(jnp.maximum(d, d2) / d2)
    loss = reference_loss_db + seg0 + seg1 + seg2
    return tx_power_dbm - jnp.where(below_d0, 0.0, loss)


def two_ray_ground(
    tx_power_dbm: jax.Array,
    d: jax.Array,
    height_tx: jax.Array,
    height_rx: jax.Array,
    frequency_hz: float = 5.15e9,
    system_loss: float = 1.0,
    min_distance: float = 0.5,
) -> jax.Array:
    """Two-ray ground reflection (TwoRayGroundPropagationLossModel):
    Friis below the crossover distance 4π·ht·hr/λ, d⁻⁴ ground-bounce
    beyond it."""
    lam = SPEED_OF_LIGHT / frequency_hz
    crossover = 4.0 * math.pi * height_tx * height_rx / lam
    friis_rx = friis(tx_power_dbm, d, frequency_hz, system_loss)
    d_safe = jnp.maximum(d, min_distance)
    ground_loss_db = -10.0 * jnp.log10(
        (height_tx * height_tx * height_rx * height_rx)
        / (d_safe**4 * system_loss)
    )
    ground_rx = tx_power_dbm - ground_loss_db
    rx = jnp.where(d <= crossover, friis_rx, ground_rx)
    return jnp.where(d <= min_distance, tx_power_dbm, rx)


def fixed_rss(tx_power_dbm: jax.Array, d: jax.Array, rss_dbm: float = -150.0) -> jax.Array:
    """FixedRssLossModel: receive power is a constant, geometry ignored."""
    return jnp.broadcast_to(jnp.asarray(rss_dbm, dtype=jnp.result_type(d)), jnp.shape(d))


def range_loss(
    tx_power_dbm: jax.Array, d: jax.Array, max_range: float = 250.0
) -> jax.Array:
    """RangePropagationLossModel: full power within MaxRange, -1000 dBm
    beyond (upstream uses -1000 as 'nothing')."""
    return jnp.where(d <= max_range, tx_power_dbm, jnp.full_like(jnp.asarray(d), -1000.0))


def matrix_loss(
    tx_power_dbm: jax.Array, loss_db: jax.Array
) -> jax.Array:
    """MatrixPropagationLossModel: explicit per-pair loss table."""
    return tx_power_dbm - loss_db


def cost231_hata(
    tx_power_dbm: jax.Array,
    d: jax.Array,
    frequency_hz: float = 2.0e9,
    bs_height: float = 50.0,
    ss_height: float = 3.0,
    min_distance: float = 0.5,
    shadowing_db: float = 0.0,
    large_city: bool = False,
) -> jax.Array:
    """COST-231 Hata urban model (Cost231PropagationLossModel).

    L = 46.3 + 33.9 log10(f_MHz) - 13.82 log10(hb) - a(hm)
        + (44.9 - 6.55 log10(hb)) log10(d_km) + C
    """
    f_mhz = frequency_hz / 1e6
    d_km = jnp.maximum(d, min_distance) / 1000.0
    log_f = math.log10(f_mhz)
    if large_city:
        a_hm = 3.2 * (jnp.log10(11.75 * ss_height)) ** 2 - 4.97
        c = 3.0
    else:
        a_hm = (1.1 * log_f - 0.7) * ss_height - (1.56 * log_f - 0.8)
        c = 0.0
    loss = (
        46.3
        + 33.9 * log_f
        - 13.82 * jnp.log10(bs_height)
        - a_hm
        + (44.9 - 6.55 * jnp.log10(bs_height)) * jnp.log10(d_km)
        + c
        + shadowing_db
    )
    return jnp.where(d <= min_distance, tx_power_dbm, tx_power_dbm - loss)


def okumura_hata(
    tx_power_dbm: jax.Array,
    d: jax.Array,
    frequency_hz: float = 2.16e9,
    bs_height: float = 30.0,
    ss_height: float = 1.0,
    environment: str = "urban",
    city_size: str = "large",
) -> jax.Array:
    """Okumura-Hata (OkumuraHataPropagationLossModel; LTE default outdoor
    model).  Classic Hata for f ≤ 1.5 GHz, COST-231 extension above."""
    f_mhz = frequency_hz / 1e6
    d_km = jnp.maximum(d, 1e-3) / 1000.0
    log_f = math.log10(f_mhz)
    log_hb = jnp.log10(jnp.asarray(bs_height, dtype=jnp.float32))

    if f_mhz <= 1500.0:
        if city_size == "large":
            if f_mhz < 200.0:
                a_hm = 8.29 * (jnp.log10(1.54 * ss_height)) ** 2 - 1.1
            else:
                a_hm = 3.2 * (jnp.log10(11.75 * ss_height)) ** 2 - 4.97
        else:
            a_hm = (1.1 * log_f - 0.7) * ss_height - (1.56 * log_f - 0.8)
        loss = (
            69.55
            + 26.16 * log_f
            - 13.82 * log_hb
            - a_hm
            + (44.9 - 6.55 * log_hb) * jnp.log10(d_km)
        )
    else:  # COST-231 extension (1.5–2 GHz band used by LTE scenarios)
        if city_size == "large":
            a_hm = 3.2 * (jnp.log10(11.75 * ss_height)) ** 2 - 4.97
            c = 3.0
        else:
            a_hm = (1.1 * log_f - 0.7) * ss_height - (1.56 * log_f - 0.8)
            c = 0.0
        loss = (
            46.3
            + 33.9 * log_f
            - 13.82 * log_hb
            - a_hm
            + (44.9 - 6.55 * log_hb) * jnp.log10(d_km)
            + c
        )
    if environment == "suburban":
        loss = loss - 2.0 * (jnp.log10(f_mhz / 28.0)) ** 2 - 5.4
    elif environment == "open":
        loss = loss - 4.78 * (math.log10(f_mhz)) ** 2 + 18.33 * math.log10(f_mhz) - 40.94
    return tx_power_dbm - loss


# --- stochastic loss models ------------------------------------------------


def nakagami(
    key: jax.Array,
    tx_power_dbm: jax.Array,
    d: jax.Array,
    m0: float = 1.5,
    m1: float = 0.75,
    m2: float = 0.75,
    d1: float = 80.0,
    d2: float = 200.0,
) -> jax.Array:
    """Nakagami-m fast fading (NakagamiPropagationLossModel): received
    power is Gamma(m, P/m)-distributed, m selected by distance band.

    ``key`` batches over the replica axis: vmap over keys yields
    independent fading draws per replica for the same geometry.
    """
    m = jnp.where(d < d1, m0, jnp.where(d < d2, m1, m2))
    power_w = dbm_to_w(tx_power_dbm)
    # Gamma(shape=m, scale=P/m) via standard-gamma * scale
    draw = jax.random.gamma(key, m, shape=jnp.shape(m)) * (power_w / m)
    return w_to_dbm(jnp.maximum(draw, 1e-30))


def random_loss(
    key: jax.Array,
    tx_power_dbm: jax.Array,
    d: jax.Array,
    low_db: float = 0.0,
    high_db: float = 10.0,
) -> jax.Array:
    """RandomPropagationLossModel with a uniform variate (upstream default
    is ConstantRandomVariable; pass low==high for that)."""
    loss = jax.random.uniform(
        key, shape=jnp.shape(d), minval=low_db, maxval=high_db
    )
    return tx_power_dbm - loss


def log_normal_shadowing(
    key: jax.Array,
    tx_power_dbm: jax.Array,
    d: jax.Array,
    sigma_db: float = 8.0,
) -> jax.Array:
    """Log-normal shadowing term (the stochastic half of many 3GPP
    models): adds N(0, sigma²) dB. Kept separate so deterministic parts
    stay cacheable per-window."""
    return tx_power_dbm + sigma_db * jax.random.normal(key, shape=jnp.shape(d))


# --- delay models ----------------------------------------------------------


def constant_speed_delay_s(d: jax.Array, speed: float = SPEED_OF_LIGHT) -> jax.Array:
    """ConstantSpeedPropagationDelayModel::GetDelay in seconds."""
    return d / speed


def random_delay_s(key: jax.Array, shape, low_s: float = 0.0, high_s: float = 1.0) -> jax.Array:
    """RandomPropagationDelayModel::GetDelay with a uniform variate."""
    return jax.random.uniform(key, shape=shape, minval=low_s, maxval=high_s)


# --- model chaining (PropagationLossModel::SetNext) ------------------------


def chain(*models):
    """Compose loss models the way upstream chains them: the rx power of
    model k is the tx power of model k+1.  Each element is a callable
    ``(tx_dbm, d) -> rx_dbm`` (close over params / keys first)."""

    def composed(tx_power_dbm, d):
        rx = tx_power_dbm
        for m in models:
            rx = m(rx, d)
        return rx

    return composed
