"""Device-resident mobility kernels — node motion as traced operands.

The last structural ❌ family of the engine capability matrix was
mobility: any moving topology either fell back to the host DES or paid
the LTE TTI controller's per-window host geometry refresh (host
recompute → H2D → fresh operands every window).  This module lifts the
motion itself onto the device: every supported model is a CLOSED-FORM
pure function ``positions_at(params, t_us) -> (N, 3)`` of simulation
time, so the engines' scan bodies can evaluate geometry at any step
without integrating state — and therefore without any dependence on
the step cadence (a ``geom_stride=K`` run samples the *same*
trajectory a stride-1 run samples, just less often).

Model family (``MOB_MODEL_IDS``), dispatched by a TRACED model id the
same way the LTE engine dispatches its FF-MAC scheduler id — one
compiled executable serves every model:

- ``static`` / ``const_velocity`` — ``p(t) = p0 + v·t`` (static is the
  ``v = 0`` point of the same branch; ConstantVelocityMobilityModel
  semantics).
- ``random_walk`` — per-(node, segment) speed/direction draws from a
  ``fold_in``-keyed stream (pure in ``(mob_seed, segment, node)``, so
  the trajectory is one integer), displacement summed over the static
  segment grid and folded back into the bounds rectangle by the
  triangle-wave reflection (the closed form of elastic rebound).  The
  DEVICE walk is a re-keyed walk: it matches the host
  RandomWalk2dMobilityModel in distribution (speed band, segment
  cadence, bounds), not step for step — host parity for walks is
  statistical, like the PHY coin flips.
- ``waypoint`` — per-node ``(time, position)`` tables with linear
  interpolation, clamped at both ends (a node PAUSES at its final
  waypoint; a zero-duration or zero-displacement segment is a pause —
  WaypointMobilityModel semantics, bit-matching the host interpolation
  up to f32).

Every per-node parameter (bases, velocities, speed bands, waypoint
tables, the model id, the walk seed) is a RUNTIME operand of the
compiled engines; only the SHAPES (node count, waypoint-table width,
walk-segment count) and the segment length are trace-time constants
(:meth:`MobilityProgram.shape_key`).

``TPUDES_DEVICE_GEOM=0`` is the family kill switch: the engine
lowerings refuse mobile graphs again (restoring the host-DES /
per-window-host-refresh fallback), and the LTE engine's mobile runner
takes the precomputed-positions per-window path (see
``tpudes/parallel/lte_sm.py``) — pinned bit-equal to the carried
geometry.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass

import numpy as np

__all__ = [
    "GEOM_COHERENCE_M",
    "MOB_MODEL_IDS",
    "MobilityProgram",
    "build_position_fn",
    "device_geom_enabled",
    "fold_into_bounds",
    "max_speed_mps",
    "trajectory_positions",
    "walk_segment_velocities",
    "warn_geom_stride",
]

#: mobility model short name → traced dispatch id (the scheduler-id
#: pattern: the id is a runtime operand selecting the position branch,
#: so the whole family rides one compiled executable)
MOB_MODEL_IDS = {
    "static": 0,
    "const_velocity": 1,
    "random_walk": 2,
    "waypoint": 3,
}

#: the geometry-coherence length scale (meters) behind the
#: ``geom_stride`` advisory in ``lower_bss``/``lower_lte_sm``: once the
#: fastest node can move further than this between two geometry
#: refreshes, the strided loss matrix is a materially stale snapshot
#: (log-distance loss moves ~1 dB over ~2 m at short range), so the
#: lowering warns — the stride still RUNS (the contract is accuracy
#: advice, not a refusal), mirroring the COMPILE_AMORTIZE_TTIS warning.
GEOM_COHERENCE_M = 2.0

#: root key of every device walk stream (the FUZZ_ROOT_SEED pattern):
#: segment draws are fold_in(fold_in(PRNGKey(root), mob_seed), segment)
_MOB_ROOT_SEED = 0x6E0B17


def device_geom_enabled() -> bool:
    """Device-resident mobility is on unless ``TPUDES_DEVICE_GEOM``
    says otherwise (read per call so tests can A/B without
    re-importing — the TPUDES_BUCKETING/TPUDES_PALLAS contract)."""
    raw = os.environ.get("TPUDES_DEVICE_GEOM")
    if raw is None:
        return True
    return raw.strip().lower() not in {"0", "false", "no", "off"}


@dataclass(frozen=True)
class MobilityProgram:
    """One node batch's motion, ready to ride a device engine.

    All array fields are RUNTIME operands of the compiled program;
    :meth:`shape_key` is the only part that belongs in an engine cache
    key.  Build via the factory classmethods or
    ``tpudes.models.mobility.device_mobility_program`` (the live-graph
    extractor)."""

    model: str                    # key of MOB_MODEL_IDS
    base_pos: np.ndarray          # (N, 3) f32 position at t = 0
    velocity: np.ndarray          # (N, 3) f32 (const_velocity)
    speed: np.ndarray             # (N, 2) f32 per-node [min, max] m/s (walk)
    bounds: np.ndarray            # (4,) f32 (xmin, xmax, ymin, ymax) (walk)
    wp_t: np.ndarray              # (N, W) i32 waypoint times (µs), sorted
    wp_p: np.ndarray              # (N, W, 3) f32 waypoint positions
    seg_us: int = 1_000_000       # walk segment length (trace-time constant)
    n_seg: int = 1                # walk segment-grid length (shape)
    mob_seed: int = 0             # walk stream seed (runtime operand)

    @property
    def n(self) -> int:
        return int(self.base_pos.shape[0])

    def shape_key(self) -> tuple:
        """The trace-time identity: everything that changes the
        compiled program's shape.  Model id and every array are
        deliberately ABSENT — they are traced operands, so a sweep
        across the model family reuses one executable."""
        return (
            self.n, int(self.wp_t.shape[1]), int(self.n_seg),
            int(self.seg_us),
        )

    def param_key(self) -> tuple:
        """Hashable identity of the FULL parameter set (serving-layer
        coalesce keys: studies with different trajectories must not
        coalesce even though the params are traced)."""
        return (
            self.model, self.base_pos.tobytes(), self.velocity.tobytes(),
            self.speed.tobytes(), self.bounds.tobytes(),
            self.wp_t.tobytes(), self.wp_p.tobytes(),
            int(self.seg_us), int(self.n_seg), int(self.mob_seed),
        )

    def operands(self) -> dict:
        """The traced-operand dict ``build_position_fn`` consumes.

        The walk's per-(node, segment) velocity table is materialized
        HERE (eagerly — jax PRNG draws are spec'd identical eager vs
        traced), not inside the position kernel: it is loop-invariant,
        and as an operand a refresh pays one (S,) einsum instead of
        O(S·N) draws + trig per cond firing.  Different seeds are just
        different operand values — the one-executable property holds.

        Memoized on the (immutable) program so repeat launches — bench
        iterations, fuzz oracle-pair reruns — skip the re-materialize
        + H2D; the cache is dropped on pickling (procmesh study specs
        cross process boundaries)."""
        import jax.numpy as jnp

        cached = self.__dict__.get("_operands_cache")
        if cached is None:
            cached = dict(
                mob_id=jnp.int32(MOB_MODEL_IDS[self.model]),
                mob_base=jnp.asarray(self.base_pos, jnp.float32),
                mob_vel=jnp.asarray(self.velocity, jnp.float32),
                mob_speed=jnp.asarray(self.speed, jnp.float32),
                mob_bounds=jnp.asarray(self.bounds, jnp.float32),
                mob_wp_t=jnp.asarray(self.wp_t, jnp.int32),
                mob_wp_p=jnp.asarray(self.wp_p, jnp.float32),
                mob_walk_vels=walk_segment_velocities(self),
            )
            object.__setattr__(self, "_operands_cache", cached)
        return dict(cached)

    def __getstate__(self):
        state = dict(self.__dict__)
        state.pop("_operands_cache", None)  # device arrays stay local
        return state

    # --- factories --------------------------------------------------------

    @classmethod
    def _fill(cls, model: str, base: np.ndarray, **kw) -> "MobilityProgram":
        base = np.asarray(base, np.float32)
        n = base.shape[0]
        defaults = dict(
            velocity=np.zeros((n, 3), np.float32),
            speed=np.zeros((n, 2), np.float32),
            bounds=np.zeros((4,), np.float32),
            wp_t=np.zeros((n, 2), np.int32),
            wp_p=np.broadcast_to(base[:, None, :], (n, 2, 3)).copy(),
        )
        defaults.update(kw)
        return cls(model=model, base_pos=base, **defaults)

    @classmethod
    def static(cls, base) -> "MobilityProgram":
        return cls._fill("static", base)

    @classmethod
    def constant_velocity(cls, base, velocity) -> "MobilityProgram":
        return cls._fill(
            "const_velocity", base,
            velocity=np.asarray(velocity, np.float32),
        )

    @classmethod
    def random_walk(
        cls, base, bounds, speed, *, seg_s: float = 1.0,
        horizon_us: int, mob_seed: int = 0,
    ) -> "MobilityProgram":
        """``speed`` is (N, 2) per-node [min, max] m/s — a [0, 0] row
        pins that node in place (how mixed static+walking batches ride
        one model id).  ``horizon_us`` sizes the static segment grid."""
        base = np.asarray(base, np.float32)
        seg_us = max(1, int(round(seg_s * 1e6)))
        n_seg = int(horizon_us) // seg_us + 1
        return cls._fill(
            "random_walk", base,
            speed=np.asarray(speed, np.float32).reshape(base.shape[0], 2),
            bounds=np.asarray(bounds, np.float32).reshape(4),
            seg_us=seg_us, n_seg=n_seg, mob_seed=int(mob_seed),
        )

    @classmethod
    def waypoints(cls, wp_t, wp_p) -> "MobilityProgram":
        """``wp_t`` (N, W) µs ascending per row, ``wp_p`` (N, W, 3);
        nodes hold the first entry before its time and PAUSE at the
        last entry forever after (the upstream clamp)."""
        wp_t = np.asarray(wp_t, np.int64)
        wp_p = np.asarray(wp_p, np.float32)
        if wp_t.shape[1] < 2:  # interp needs two columns; repeat the last
            wp_t = np.concatenate([wp_t, wp_t], axis=1)
            wp_p = np.concatenate([wp_p, wp_p], axis=1)
        if (np.diff(wp_t, axis=1) < 0).any():
            raise ValueError("waypoint times must ascend per node")
        # the device clock is int32 µs: a waypoint past ~35.8 simulated
        # minutes would WRAP negative under a silent astype and snap
        # the node to the wrong leg at t=0 — clamp instead (ordering
        # survives, and the pause-at-final interp makes a clamped
        # far-future waypoint behave as 'still en route' for every
        # representable t)
        wp_t = np.minimum(wp_t, np.int64(2**31 - 1))
        return cls._fill(
            "waypoint", wp_p[:, 0, :],
            wp_t=wp_t.astype(np.int32), wp_p=wp_p,
        )


def walk_segment_velocities(prog: MobilityProgram):
    """(n_seg, N, 2) per-(segment, node) walk velocities — pure in
    ``(mob_seed, segment, node)`` via two ``fold_in`` hops, so the
    whole trajectory is the one integer seed.  Zero-band nodes get
    zero vectors (speed interpolation from a [0, 0] band)."""
    import jax
    import jax.numpy as jnp

    n = prog.n
    speed = jnp.asarray(prog.speed, jnp.float32)
    key = jax.random.fold_in(
        jax.random.PRNGKey(_MOB_ROOT_SEED), int(prog.mob_seed)
    )

    def seg_vel(s):
        u = jax.random.uniform(jax.random.fold_in(key, s), (n, 2))
        spd = speed[:, 0] + u[:, 0] * (speed[:, 1] - speed[:, 0])
        ang = jnp.float32(2.0 * math.pi) * u[:, 1]
        return jnp.stack(
            [spd * jnp.cos(ang), spd * jnp.sin(ang)], axis=-1
        )                                                  # (N, 2)

    return jax.vmap(seg_vel)(jnp.arange(int(prog.n_seg)))


def fold_into_bounds(x, lo, hi):
    """Triangle-wave reflection of ``x`` into ``[lo, hi]`` — the closed
    form of elastic wall rebound (a straight-line path with reflections
    unrolled is a straight line in the unfolded plane).  Degenerate
    bounds (``hi <= lo``) clamp to ``lo``."""
    import jax.numpy as jnp

    span = hi - lo
    y = jnp.mod(x - lo, 2.0 * span)
    folded = lo + span - jnp.abs(span - y)
    return jnp.where(span > 0.0, folded, jnp.broadcast_to(lo, x.shape))


def build_position_fn(prog: MobilityProgram):
    """Closed-form position kernel for ``prog``'s SHAPE class: returns
    ``pos_fn(ops, t_us) -> (N, 3)`` where ``ops`` is
    :meth:`MobilityProgram.operands` (all traced) and ``t_us`` a traced
    scalar.  Every model branch is evaluated and the traced
    ``mob_id`` selects — the dispatch shape of the LTE scheduler id,
    which is what keeps the family on one executable."""
    import jax.numpy as jnp

    n_seg = int(prog.n_seg)
    seg_us = float(prog.seg_us)
    W = int(prog.wp_t.shape[1])

    def pos_fn(ops, t_us):
        t_s = t_us.astype(jnp.float32) * jnp.float32(1e-6)
        base = ops["mob_base"]

        # static / const_velocity (static rides v = 0)
        p_cv = base + ops["mob_vel"] * t_s

        # random walk: the per-(node, segment) velocity table rides as
        # a loop-invariant OPERAND (walk_segment_velocities); a refresh
        # only sums displacement and triangle-folds into bounds (z
        # inherits the base plane)
        vels = ops["mob_walk_vels"]                        # (S, N, 2)
        dt = jnp.clip(
            t_us.astype(jnp.float32)
            - jnp.arange(n_seg, dtype=jnp.float32) * seg_us,
            0.0, seg_us,
        ) * jnp.float32(1e-6)                              # (S,)
        disp = jnp.einsum("snk,s->nk", vels, dt)           # (N, 2)
        bx = fold_into_bounds(
            base[:, 0] + disp[:, 0], ops["mob_bounds"][0],
            ops["mob_bounds"][1],
        )
        by = fold_into_bounds(
            base[:, 1] + disp[:, 1], ops["mob_bounds"][2],
            ops["mob_bounds"][3],
        )
        # a zero-speed-band node is pinned: it must NOT be folded into
        # the walkers' rectangle (a static AP may sit outside it)
        moving = ops["mob_speed"][:, 1] > 0.0
        p_walk = jnp.stack(
            [
                jnp.where(moving, bx, base[:, 0]),
                jnp.where(moving, by, base[:, 1]),
                base[:, 2],
            ],
            axis=-1,
        )

        # waypoint table: clamp-interpolate each node's row
        wt = ops["mob_wp_t"]                               # (N, W)
        wp = ops["mob_wp_p"]                               # (N, W, 3)
        idx = jnp.clip(
            jnp.sum(wt <= t_us, axis=1) - 1, 0, W - 2
        )                                                  # (N,)
        t0 = jnp.take_along_axis(wt, idx[:, None], axis=1)[:, 0]
        t1 = jnp.take_along_axis(wt, idx[:, None] + 1, axis=1)[:, 0]
        p0 = jnp.take_along_axis(wp, idx[:, None, None], axis=1)[:, 0]
        p1 = jnp.take_along_axis(wp, idx[:, None, None] + 1, axis=1)[:, 0]
        frac = jnp.clip(
            (t_us - t0).astype(jnp.float32)
            / jnp.maximum((t1 - t0).astype(jnp.float32), 1.0),
            0.0, 1.0,
        )                                                  # (N,)
        p_wp = p0 + (p1 - p0) * frac[:, None]

        mid = ops["mob_id"]
        return jnp.where(
            mid == MOB_MODEL_IDS["random_walk"], p_walk,
            jnp.where(mid == MOB_MODEL_IDS["waypoint"], p_wp, p_cv),
        )

    return pos_fn


def max_speed_mps(prog: MobilityProgram) -> float:
    """Upper bound on any node's speed over the whole run — the input
    of the geometry-coherence stride advisory."""
    if prog.model in ("static",):
        return 0.0
    if prog.model == "const_velocity":
        return float(
            np.sqrt((prog.velocity.astype(np.float64) ** 2).sum(-1)).max()
        ) if prog.velocity.size else 0.0
    if prog.model == "random_walk":
        return float(prog.speed[:, 1].max()) if prog.speed.size else 0.0
    # waypoint: fastest leg over the table (zero-duration legs are
    # pauses by the interp clamp, not infinite speeds)
    t = prog.wp_t.astype(np.float64)
    p = prog.wp_p.astype(np.float64)
    dt = np.diff(t, axis=1) * 1e-6                        # (N, W-1)
    dp = np.sqrt((np.diff(p, axis=1) ** 2).sum(-1))       # (N, W-1)
    with np.errstate(divide="ignore", invalid="ignore"):
        v = np.where(dt > 0.0, dp / np.maximum(dt, 1e-30), 0.0)
    return float(v.max()) if v.size else 0.0


def warn_geom_stride(
    who: str, mobility: MobilityProgram, geom_stride: int, step_s: float
) -> None:
    """Advise when a stride outruns the geometry coherence the max
    node speed implies (the COMPILE_AMORTIZE_TTIS warning shape: the
    run still executes, the accuracy regime is just named loudly).
    ``step_s`` is the engine's nominal inter-step spacing — exactly
    1 ms for the LTE TTI clock, the offered-event estimate for the
    event-stepped BSS loop."""
    speed = max_speed_mps(mobility)
    drift_m = speed * geom_stride * step_s
    if drift_m > GEOM_COHERENCE_M:
        import warnings

        warnings.warn(
            f"{who}: geom_stride={geom_stride} lets the fastest node "
            f"({speed:.1f} m/s) drift ~{drift_m:.1f} m between "
            f"geometry refreshes (> the ~{GEOM_COHERENCE_M:.0f} m "
            "coherence scale of the loss models) — the strided loss "
            "matrix is a materially stale snapshot; lower the stride "
            "or accept the documented staleness",
            stacklevel=3,
        )


#: one jitted sampler per SHAPE class (build_position_fn closes over
#: shapes only, operands ride as arguments) — a fresh jit per call
#: would recompile the kernel for every lowering guard / fuzz build
_TRAJ_SAMPLERS: dict = {}


def trajectory_positions(prog: MobilityProgram, t_grid_us) -> np.ndarray:
    """Host-side trajectory samples ``(T, N, 3)`` through the SAME
    compiled position kernel the engines trace — the single source of
    truth for lowering guards (mutual-sensing over the whole run) and
    the ``TPUDES_DEVICE_GEOM=0`` precomputed-positions fallback, whose
    bit-equality contract depends on both paths sharing this kernel."""
    import jax
    import jax.numpy as jnp

    fn = _TRAJ_SAMPLERS.get(prog.shape_key())
    if fn is None:
        pos_fn = build_position_fn(prog)
        # ONE vmapped dispatch for the whole grid (a per-t loop would
        # pay T dispatches + D2H round trips — seconds at stride=1
        # horizons, worse over a tunneled accelerator); pinned
        # bit-equal to the scan's in-loop evaluation by the
        # device_geom_off tests
        fn = jax.jit(jax.vmap(pos_fn, in_axes=(None, 0)))
        _TRAJ_SAMPLERS[prog.shape_key()] = fn
    return np.asarray(
        fn(
            prog.operands(),
            jnp.asarray([int(t) for t in t_grid_us], jnp.int32),
        )
    )
