"""Pure JAX kernels — the lifted math segments of the reference's hot
loops (SURVEY.md §3.2, §3.4): propagation, WiFi error rates,
interference chunking, spectrum/LTE RB math.

Everything here is side-effect free, static-shaped, and composable under
jit / vmap / shard_map; hosts pack state into tensors, call these, and
turn the results back into events (SURVEY.md §7 design stance).
"""

from tpudes.ops import propagation
from tpudes.ops import wifi_error
from tpudes.ops import interference
from tpudes.ops.propagation import (
    distance,
    pairwise_distance,
    dbm_to_w,
    w_to_dbm,
    friis,
    log_distance,
    three_log_distance,
    two_ray_ground,
    nakagami,
    constant_speed_delay_s,
)
from tpudes.ops.wifi_error import (
    WifiMode,
    ALL_MODES,
    MODES_BY_NAME,
    chunk_success_rate,
    mode_chunk_success_rate,
)
from tpudes.ops.interference import frame_success_rate, batch_frame_success_rate, thermal_noise_w
