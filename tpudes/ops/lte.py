"""LTE TTI kernels — per-RB SINR, CQI mapping, MI-based TB error model.

Reference parity: src/lte/model/lte-spectrum-phy.{h,cc},
lte-interference.{h,cc}, lte-mi-error-model.{h,cc}, and the CQI
generation in lte-ue-phy / lte-amc (upstream paths; mount empty at
survey — SURVEY.md §0, §2.6, §3.4).  SURVEY.md calls this TTI path "the
most natural Pallas/XLA kernel in the whole reference": everything from
MultiModelSpectrumChannel::StartTx to GetTbDecodificationStats is dense
array math over the RB grid.

TPU-first design: one jitted call per TTI evaluates EVERY cell and UE at
once — (T transmitters × RB) PSDs and (T × U) gains in, per-UE
(SINR, CQI, MI, BLER, decode coin flips) out.  No per-UE Python, no
per-RB loops; the replica axis is one more vmap.

Error-model note (documented deviation): upstream's LteMiErrorModel
interpolates vendor-fit BLER curves (PiroEW2010) from large LUTs that
could not be read (empty mount).  This module uses the same *structure*
— per-RB mutual information → effective MI → TB BLER with HARQ-IR MI
accumulation — with a principled analytic model: normalized MI from
Shannon capacity with the LENA SNR gap Γ = -ln(5·BER)/1.5, and a
finite-blocklength Gaussian waterfall calibrated so a CQI-matched
transport block sees the standard 10 % first-transmission BLER target.
Tests validate the structural properties (monotonicity, waterfall,
HARQ gain, f32↔f64 parity), not bitwise LUT equality.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as _np
from jax.scipy.special import erfc

# --- constants (3GPP TS 36.211/36.213 public values) -----------------------

RB_BANDWIDTH_HZ = 180e3          # 12 subcarriers × 15 kHz
RE_PER_RB_DATA = 120.0           # ~168 REs/RB/TTI minus PDCCH + RS overhead
TTI_S = 1e-3
BOLTZMANN_T = 1.380649e-23 * 290.0

#: TS 36.213 Table 7.2.3-1 — CQI index → spectral efficiency (bits/RE).
#: Index 0 = out of range (not schedulable).
CQI_EFFICIENCY = [
    0.0, 0.1523, 0.2344, 0.3770, 0.6016, 0.8770, 1.1758, 1.4766,
    1.9141, 2.4063, 2.7305, 3.3223, 3.9023, 4.5234, 5.1152, 5.5547,
]

#: Per-MCS spectral efficiency (bits/RE), MCS 0-28, interpolating the
#: TS 36.213 I_TBS ladder between the CQI anchor points; modulation
#: order Qm is 2 (MCS<10), 4 (MCS<17), 6 (MCS≥17).
MCS_EFFICIENCY = [
    # QPSK (0-9)
    0.1523, 0.1943, 0.2344, 0.3008, 0.3770, 0.4385, 0.5879, 0.7402,
    0.9023, 1.0273,
    # 16-QAM (10-16)
    1.1758, 1.3262, 1.4766, 1.6953, 1.9141, 2.1602, 2.4063,
    # 64-QAM (17-28)
    2.5703, 2.7305, 3.0293, 3.3223, 3.6094, 3.9023, 4.2129, 4.5234,
    4.8193, 5.1152, 5.3320, 5.5547,
]
MCS_QM = [2.0] * 10 + [4.0] * 7 + [6.0] * 12
#: effective code rate per MCS: efficiency / modulation order
MCS_ECR = [e / q for e, q in zip(MCS_EFFICIENCY, MCS_QM)]

#: LENA CQI mapping SNR gap Γ = -ln(5·BER)/1.5 at target BER 5e-5
#: (Piro et al., the lte-amc "PiroEW2010" model).
SNR_GAP = -math.log(5.0 * 5e-5) / 1.5

#: Gaussian-waterfall dispersion: σ = DISPERSION/√tb_bits.  The decode
#: margin is set so a CQI-matched TB has 10 % first-tx BLER (the LTE
#: link-adaptation target).
BLER_DISPERSION = 1.4
BLER_TARGET_Q = 1.281551  # Φ⁻¹(0.9): Q(1.2816) = 0.1

# numpy at module scope so importing never pins a JAX backend (same rule
# as ops/wifi_error.py)
_CQI_EFF = _np.array(CQI_EFFICIENCY, dtype=_np.float32)
_MCS_EFF = _np.array(MCS_EFFICIENCY, dtype=_np.float32)
_MCS_QM = _np.array(MCS_QM, dtype=_np.float32)
_MCS_ECR = _np.array(MCS_ECR, dtype=_np.float32)
#: CQI → highest MCS whose efficiency does not exceed the CQI's
_CQI_TO_MCS = _np.array(
    [
        max([m for m in range(29) if MCS_EFFICIENCY[m] <= CQI_EFFICIENCY[c]] or [0])
        for c in range(16)
    ],
    dtype=_np.int32,
)


def noise_psd_w(noise_figure_db: float) -> float:
    """Thermal noise PSD (W/Hz) at the given receiver noise figure."""
    return float(10.0 ** (noise_figure_db / 10.0) * BOLTZMANN_T)


def tbs_bits(mcs, n_rb):
    """Transport-block size in bits for an MCS over n_rb resource blocks
    (efficiency × data REs; the TS 36.213 TBS-table analog)."""
    return jnp.floor(jnp.asarray(_MCS_EFF)[mcs] * n_rb * RE_PER_RB_DATA)


def tbs_bits_py(mcs: int, n_rb: int) -> int:
    return int(MCS_EFFICIENCY[mcs] * n_rb * RE_PER_RB_DATA)


# --- per-TTI SINR ----------------------------------------------------------


def tti_sinr(
    tx_psd_w: jax.Array,   # (T, RB) transmit PSD per transmitter over RBs
    gain: jax.Array,       # (T, U) linear path gain transmitter→receiver
    serving: jax.Array,    # (U,) int32: index into T of each rx's server
    noise_psd: float,
) -> jax.Array:
    """(U, RB) per-RB SINR: serving-cell signal over other-cell
    interference + thermal noise (LteInterference chunk processing,
    dense over the grid; SURVEY.md §3.4).

    Works for downlink (T = eNBs, U = UEs) and uplink (T = UEs, U = eNB
    listening ports) alike — the caller orients the gain matrix.
    """
    seen = tx_psd_w[:, None, :] * gain[:, :, None]        # (T, U, RB)
    total = jnp.sum(seen, axis=0)                         # (U, RB)
    sig = jnp.take_along_axis(seen, serving[None, :, None], axis=0)[0]
    return sig / (total - sig + noise_psd)


def tti_sinr_py(tx_psd_w, gain, serving, noise_psd):
    """Float64 scalar-loop oracle for :func:`tti_sinr` (SURVEY.md §4:
    tolerance-based PHY validation)."""
    t, rb = len(tx_psd_w), len(tx_psd_w[0])
    u = len(serving)
    out = [[0.0] * rb for _ in range(u)]
    for ui in range(u):
        for r in range(rb):
            total = sum(tx_psd_w[ti][r] * gain[ti][ui] for ti in range(t))
            sig = tx_psd_w[serving[ui]][r] * gain[serving[ui]][ui]
            out[ui][r] = sig / (total - sig + noise_psd)
    return out


# --- CQI -------------------------------------------------------------------


def cqi_from_sinr(sinr: jax.Array, dtype=None, surrogate=None) -> jax.Array:
    """Wideband CQI from mean per-RB SINR: spectral efficiency
    log2(1 + SINR/Γ) mapped to the highest CQI the efficiency supports
    (lte-amc CreateCqiFeedbacks, PiroEW2010 mapping).

    ``dtype`` (e.g. ``jnp.bfloat16``) selects the mixed-precision mode:
    the gapped SINR ratio is computed at that precision while the log2
    transcendental and the table comparison stay f32 — the engine's
    compute-in-low/accumulate-in-f32 policy.  The CQI error budget this
    buys is at most ±1 index at efficiency-boundary SINRs
    (tests/test_ops_lte_kernels.py pins it).

    ``surrogate`` (a :class:`tpudes.diff.Surrogacy`, duck-typed — ops
    never imports diff) replaces the 16-level comparison staircase
    with a temperature-controlled sigmoid sum so ``jax.grad`` sees a
    smooth CQI; the return becomes FLOAT (soft index, or the hard
    index straight-through when ``surrogate.ste``).  ``surrogate=None``
    is the identical legacy integer program."""
    x = sinr if dtype is None else sinr.astype(dtype)
    se = jnp.log2((1.0 + x / SNR_GAP).astype(jnp.float32))
    # highest cqi with efficiency <= se
    eff = jnp.asarray(_CQI_EFF)                            # (16,)
    if surrogate is None:
        return jnp.sum(
            (eff[None, :] <= se[..., None]) & (eff[None, :] > 0.0), axis=-1
        )
    hard = jnp.sum(
        ((eff[None, :] <= se[..., None]) & (eff[None, :] > 0.0)).astype(
            jnp.float32
        ),
        axis=-1,
    )
    from tpudes.diff.surrogate import soft_staircase  # lazy: diff is optional

    soft = soft_staircase(
        se, _CQI_EFF[1:], _np.ones(15, _np.float32), surrogate.temp
    )
    return surrogate.blend(hard, soft)


def eff_from_sinr(sinr: jax.Array, surrogate=None) -> jax.Array:
    """Quantized spectral efficiency (bits/RE) the CQI ladder grants at
    this SINR: ``CQI_EFFICIENCY[cqi_from_sinr(sinr)]`` written as a
    staircase so a surrogate can smooth it — the hard point of the
    SINR→CQI→MCS→rate chain the diff engines differentiate through.
    ``surrogate=None`` keeps the exact staircase (zero gradient a.e.)."""
    se = jnp.log2(1.0 + sinr / SNR_GAP)
    steps = _CQI_EFF[1:] - _CQI_EFF[:-1]                   # (15,)
    hard = jnp.sum(
        steps * (se[..., None] >= _CQI_EFF[1:]).astype(jnp.float32),
        axis=-1,
    )
    if surrogate is None:
        return hard
    from tpudes.diff.surrogate import soft_staircase

    soft = soft_staircase(se, _CQI_EFF[1:], steps, surrogate.temp)
    return surrogate.blend(hard, soft)


#: modulation-order ladder anchors: the granted efficiency at which Qm
#: steps 2→4 (first 16-QAM MCS) and 4→6 (first 64-QAM MCS)
_QM_EDGES = _np.array(
    [MCS_EFFICIENCY[10], MCS_EFFICIENCY[17]], dtype=_np.float32
)


def qm_from_eff(eff: jax.Array, surrogate=None) -> jax.Array:
    """Modulation order from granted spectral efficiency: the 2/4/6
    staircase at the 16-QAM/64-QAM boundary efficiencies (the
    ``MCS_QM`` ladder as a function of efficiency instead of an
    integer MCS gather, so the diff chain can smooth it)."""
    steps = _np.array([2.0, 2.0], _np.float32)
    hard = 2.0 + jnp.sum(
        steps * (eff[..., None] >= _QM_EDGES).astype(jnp.float32), axis=-1
    )
    if surrogate is None:
        return hard
    from tpudes.diff.surrogate import soft_staircase

    soft = 2.0 + soft_staircase(eff, _QM_EDGES, steps, surrogate.temp)
    return surrogate.blend(hard, soft)


def decode_ok(coin: jax.Array, bler: jax.Array, surrogate=None) -> jax.Array:
    """TB decode indicator: the hard threshold ``coin >= bler`` (what
    :func:`tti_phy_step` wires in — bit-identical legacy trace at
    ``surrogate=None``), or its temperature-smoothed sigmoid so a
    SAMPLED-decode diff program keeps gradients flowing through the
    BLER waterfall instead of dying at the comparison.  (The
    expected-KPI chain in :mod:`tpudes.diff.lte_grad` needs no coin at
    all — its decode expectation is ``1 − BLER``.)  Returns bool when
    ``surrogate=None``, f32 in [0, 1] otherwise."""
    if surrogate is None:
        return coin >= bler
    hard = (coin >= bler).astype(jnp.float32)
    from tpudes.diff.surrogate import soft_sigmoid

    soft = soft_sigmoid(coin - bler, surrogate.gate_temp)
    return surrogate.blend(hard, soft)


def cqi_from_sinr_py(sinr: float) -> int:
    se = math.log2(1.0 + sinr / SNR_GAP)
    cqi = 0
    for c in range(1, 16):
        if CQI_EFFICIENCY[c] <= se:
            cqi = c
    return cqi


def mcs_from_cqi(cqi: jax.Array) -> jax.Array:
    return jnp.asarray(_CQI_TO_MCS)[cqi]


def mcs_from_cqi_py(cqi: int) -> int:
    return int(_CQI_TO_MCS[cqi])


# --- MI-based error model --------------------------------------------------


def mi_per_rb(sinr: jax.Array, qm: jax.Array, dtype=None) -> jax.Array:
    """Normalized per-RB mutual information in [0, 1]: gapped Shannon
    capacity capped at the modulation order (the MIESM structure of
    LteMiErrorModel with an analytic MI curve — see module docstring).

    ``dtype`` selects the mixed-precision mode (same policy as
    :func:`cqi_from_sinr`: ratio at ``dtype``, log2 and the final
    normalization in f32)."""
    x = sinr if dtype is None else sinr.astype(dtype)
    cap = jnp.log2((1.0 + x / SNR_GAP).astype(jnp.float32))
    return jnp.minimum(cap, qm) / qm


def tb_bler_ecr(
    mi_eff: jax.Array, ecr: jax.Array, tb_bits_: jax.Array, dtype=None
) -> jax.Array:
    """:func:`tb_bler` on a pre-gathered effective code rate — the form
    the fused device kernel uses (its per-UE MCS is static, so the
    table gather happens once at build time instead of per TTI).

    ``dtype`` selects the mixed-precision mode: the waterfall argument
    ``z`` is computed at that precision while the dispersion sqrt and
    the erfc tail stay f32.  The BLER budget this buys is |Δmi| ≤ the
    dtype's half-ulp at 1.0 propagated through the waterfall slope
    (tests/test_ops_lte_kernels.py pins it)."""
    sigma = BLER_DISPERSION / jnp.sqrt(jnp.maximum(tb_bits_, 24.0))
    margin = BLER_TARGET_Q * sigma
    if dtype is None:
        z = (mi_eff - (ecr - margin)) / sigma
    else:
        z = (
            (mi_eff.astype(dtype) - (ecr - margin).astype(dtype))
            / sigma.astype(dtype)
        ).astype(jnp.float32)
    return jnp.clip(0.5 * erfc(z / math.sqrt(2.0)), 0.0, 1.0)


def tb_bler(mi_eff: jax.Array, mcs: jax.Array, tb_bits_: jax.Array) -> jax.Array:
    """TB block-error rate from effective MI: Gaussian waterfall around
    the code rate with finite-blocklength dispersion, margin calibrated
    to 10 % BLER when MI exactly matches the code rate
    (GetTbDecodificationStats analog)."""
    return tb_bler_ecr(mi_eff, jnp.asarray(_MCS_ECR)[mcs], tb_bits_)


def tb_bler_py(mi_eff: float, mcs: int, tb_bits_: float) -> float:
    ecr = MCS_ECR[mcs]
    sigma = BLER_DISPERSION / math.sqrt(max(tb_bits_, 24.0))
    margin = BLER_TARGET_Q * sigma
    z = (mi_eff - (ecr - margin)) / sigma
    return min(max(0.5 * math.erfc(z / math.sqrt(2.0)), 0.0), 1.0)


def mi_eff_py(sinr_rbs, qm: float) -> float:
    if not sinr_rbs:
        return 0.0
    total = 0.0
    for s in sinr_rbs:
        total += min(math.log2(1.0 + s / SNR_GAP), qm) / qm
    return total / len(sinr_rbs)


# --- fused TTI PHY step ----------------------------------------------------


def tti_phy_step(
    tx_psd_w: jax.Array,   # (T, RB) data PSD actually transmitted this TTI
    ref_psd_w: jax.Array,  # (T, RB) full-power reference PSD (RS-like)
    gain: jax.Array,       # (T, U)
    serving: jax.Array,    # (U,) int32
    alloc: jax.Array,      # (U, RB) bool: RBs carrying this UE's TB
    mcs: jax.Array,        # (U,) int32
    tb_bits_: jax.Array,   # (U,) float32 (0 → no TB this TTI)
    mi_acc: jax.Array,     # (U,) float32 accumulated HARQ-IR MI
    key: jax.Array,
    noise_psd: float,
    ref_gain: jax.Array | None = None,  # (T, U) gain for CQI measurement
):
    """One TTI of the LTE PHY for every receiver at once.

    Data decoding uses the PSD actually transmitted this TTI (real
    interference); CQI is measured from ``ref_psd_w``, the full-load
    reference-signal PSD, as upstream UEs measure RS under the
    worst-case all-cells-loaded assumption — otherwise an idle serving
    cell could never report a CQI and an idle interferer would inflate
    one.  ``ref_gain`` (default: ``gain``) lets the CQI measurement see
    a different interference geometry than data decoding — uplink SRS
    sounding is orthogonal within a cell, so the UL caller passes a
    gain matrix with co-served transmitters masked out.

    Returns ``(ok, bler, cqi, mi_new)``:
      ok     (U,) bool — TB decoded this TTI (False where tb_bits==0)
      bler   (U,) float32 — the BLER each draw was taken against
      cqi    (U,) int32 — wideband CQI measured this TTI
      mi_new (U,) float32 — accumulated MI including this transmission
    """
    sinr = tti_sinr(tx_psd_w, gain, serving, noise_psd)    # (U, RB)
    qm = jnp.asarray(_MCS_QM)[mcs]                         # (U,)
    mi_rb = mi_per_rb(sinr, qm[:, None])                   # (U, RB)
    n_alloc = jnp.sum(alloc, axis=1)
    mi_eff = jnp.sum(jnp.where(alloc, mi_rb, 0.0), axis=1) / jnp.maximum(
        n_alloc, 1.0
    )
    mi_new = jnp.minimum(mi_acc + mi_eff, 1.0)             # HARQ-IR cap
    bler = tb_bler(mi_new, mcs, tb_bits_)
    coin = jax.random.uniform(key, bler.shape)
    has_tb = tb_bits_ > 0.0
    ok = has_tb & decode_ok(coin, bler)
    ref_sinr = tti_sinr(
        ref_psd_w, gain if ref_gain is None else ref_gain, serving, noise_psd
    )
    # subband-aware wideband CQI: average only where the serving cell's
    # reference actually transmits (under FFR each cell's RS occupies
    # its subband; averaging silent RBs would report zero-signal CQI)
    ref_on = jnp.take(ref_psd_w > 0.0, serving, axis=0)    # (U, RB)
    n_on = jnp.sum(ref_on, axis=1)
    mean_sinr = jnp.where(
        n_on > 0,
        jnp.sum(jnp.where(ref_on, ref_sinr, 0.0), axis=1)
        / jnp.maximum(n_on, 1),
        jnp.mean(ref_sinr, axis=1),
    )
    cqi = cqi_from_sinr(mean_sinr)
    return ok, bler, cqi, mi_new
