"""AODV tests — upstream src/aodv/test strategy (aodv-test-suite.cc +
the chain regression): on-demand discovery (silent until traffic),
RREQ flood dedup, RREP path setup, multihop data beyond radio range,
queue-drain of the first packets, discovery failure drop, route expiry
+ re-discovery, and the structural contrast with proactive DSDV."""


from tpudes.core import Seconds, Simulator
from tpudes.helper.applications import UdpEchoClientHelper, UdpEchoServerHelper
from tpudes.helper.containers import NodeContainer
from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
from tpudes.models.internet.aodv import (
    AODV_PROT_NUMBER,
    AodvHeader,
    AodvHelper,
    AodvRoutingProtocol,
)
from tpudes.models.internet.ipv4 import Ipv4L3Protocol
from tpudes.models.mobility import ListPositionAllocator, MobilityHelper, Vector
from tpudes.network.address import Ipv4Address


def _reset():
    from tpudes.core.world import reset_world

    reset_world()


def _adhoc_chain(n=3, spacing=80.0, **aodv_attrs):
    from tpudes.models.wifi import (
        WifiHelper,
        WifiMacHelper,
        YansWifiChannelHelper,
        YansWifiPhyHelper,
    )

    nodes = NodeContainer()
    nodes.Create(n)
    alloc = ListPositionAllocator()
    for i in range(n):
        alloc.Add(Vector(i * spacing, 0.0, 0.0))
    mob = MobilityHelper()
    mob.SetPositionAllocator(alloc)
    mob.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    mob.Install(nodes)

    channel = YansWifiChannelHelper.Default().Create()
    phy = YansWifiPhyHelper()
    phy.SetChannel(channel)
    wifi = WifiHelper()
    wifi.SetRemoteStationManager(
        "tpudes::ConstantRateWifiManager", DataMode="OfdmRate6Mbps"
    )
    mac = WifiMacHelper()
    mac.SetType("tpudes::AdhocWifiMac")
    devices = wifi.Install(phy, mac, [nodes.Get(i) for i in range(n)])

    stack = InternetStackHelper()
    stack.SetRoutingHelper(AodvHelper(**aodv_attrs))
    stack.Install(nodes)
    ifc = Ipv4AddressHelper("10.1.1.0", "255.255.255.0").Assign(devices)
    return nodes, devices, ifc


def _aodv(node) -> AodvRoutingProtocol:
    return node.GetObject(Ipv4L3Protocol).GetRoutingProtocol()


def test_silent_until_traffic_then_discovers():
    """The reactive signature: zero control packets before the first
    data send; RREQ/RREP only afterwards (DSDV floods from t=0)."""
    _reset()
    nodes, devices, ifc = _adhoc_chain(3)
    ctrl = []
    for i in range(3):
        nodes.Get(i).GetObject(Ipv4L3Protocol).TraceConnectWithoutContext(
            "Tx",
            lambda pkt, idx: ctrl.append(Simulator.Now().GetSeconds())
            if pkt.FindHeader(AodvHeader) is not None
            else None,
        )
    server = UdpEchoServerHelper(9)
    sapps = server.Install(nodes.Get(2))
    sapps.Start(Seconds(0.0))
    client = UdpEchoClientHelper(ifc.GetAddress(2), 9)
    client.SetAttribute("MaxPackets", 3)
    client.SetAttribute("Interval", Seconds(0.2))
    capps = client.Install(nodes.Get(0))
    capps.Start(Seconds(2.0))
    Simulator.Stop(Seconds(4.0))
    Simulator.Run()
    assert ctrl, "no AODV control traffic at all"
    assert min(ctrl) >= 2.0, f"control traffic before first send: {min(ctrl)}"
    assert sapps.Get(0).received == 3
    assert capps.Get(0).received == 3
    _reset()


def test_multihop_beyond_radio_range():
    """At 80 m spacing node 0 cannot hear node 4: data must relay
    through the discovered 4-hop path, including the queued first
    packet."""
    _reset()
    nodes, devices, ifc = _adhoc_chain(5)
    server = UdpEchoServerHelper(9)
    sapps = server.Install(nodes.Get(4))
    sapps.Start(Seconds(0.0))
    client = UdpEchoClientHelper(ifc.GetAddress(4), 9)
    client.SetAttribute("MaxPackets", 4)
    client.SetAttribute("Interval", Seconds(0.25))
    capps = client.Install(nodes.Get(0))
    capps.Start(Seconds(1.0))
    Simulator.Stop(Seconds(4.0))
    Simulator.Run()
    assert sapps.Get(0).received == 4
    assert capps.Get(0).received == 4
    # forwarders hold routes toward both endpoints
    mid = _aodv(nodes.Get(2))
    assert mid.GetNRoutes() >= 2
    _reset()


def test_rreq_flood_is_deduplicated():
    """Every node forwards a given RREQ at most once — the flood is
    O(N) per discovery, not exponential."""
    _reset()
    nodes, devices, ifc = _adhoc_chain(4, spacing=60.0)  # denser: overlap
    rreq_tx = [0]
    for i in range(4):
        nodes.Get(i).GetObject(Ipv4L3Protocol).TraceConnectWithoutContext(
            "Tx",
            lambda pkt, idx: rreq_tx.__setitem__(0, rreq_tx[0] + 1)
            if (
                pkt.FindHeader(AodvHeader) is not None
                and pkt.FindHeader(AodvHeader).msg_type == AodvHeader.RREQ
            )
            else None,
        )
    server = UdpEchoServerHelper(9)
    server.Install(nodes.Get(3)).Start(Seconds(0.0))
    client = UdpEchoClientHelper(ifc.GetAddress(3), 9)
    client.SetAttribute("MaxPackets", 1)
    client.Install(nodes.Get(0)).Start(Seconds(1.0))
    Simulator.Stop(Seconds(3.0))
    Simulator.Run()
    # one discovery: at most one RREQ per node (4), plus the reply
    # path's own discovery (the server answers to a known reverse
    # route, so none) — allow retries headroom but forbid a storm
    assert 1 <= rreq_tx[0] <= 8, rreq_tx[0]
    _reset()


def test_unreachable_destination_drops_after_retries():
    _reset()
    nodes, devices, ifc = _adhoc_chain(2)
    drops = []
    _aodv(nodes.Get(0)).TraceConnectWithoutContext(
        "Drop", lambda pkt, dst: drops.append(dst)
    )
    client = UdpEchoClientHelper(Ipv4Address("10.1.1.200"), 9)  # nobody
    client.SetAttribute("MaxPackets", 1)
    client.Install(nodes.Get(0)).Start(Seconds(0.5))
    Simulator.Stop(Seconds(12.0))  # 3 tries x 2.8 s net traversal
    Simulator.Run()
    assert drops and str(drops[0]) == "10.1.1.200"
    _reset()


def test_route_expires_and_rediscovers():
    _reset()
    nodes, devices, ifc = _adhoc_chain(
        3, ActiveRouteTimeout=Seconds(0.5)
    )
    rreqs = []
    _aodv(nodes.Get(0)).TraceConnectWithoutContext(
        "Rreq", lambda orig, dst: rreqs.append(Simulator.Now().GetSeconds())
    )
    server = UdpEchoServerHelper(9)
    sapps = server.Install(nodes.Get(2))
    sapps.Start(Seconds(0.0))
    # two bursts separated by > ActiveRouteTimeout
    for t in (1.0, 3.0):
        client = UdpEchoClientHelper(ifc.GetAddress(2), 9)
        client.SetAttribute("MaxPackets", 1)
        client.Install(nodes.Get(0)).Start(Seconds(t))
    Simulator.Stop(Seconds(5.0))
    Simulator.Run()
    assert len(rreqs) >= 2, rreqs  # the second burst re-discovered
    assert sapps.Get(0).received == 2
    _reset()


def test_intermediate_node_with_fresh_route_replies():
    """Node 1 already holds a fresh route to node 2 (from earlier
    traffic); a new discovery from node 0 is answered by node 1 without
    the RREQ ever reaching node 2 — unless DestinationOnly."""
    _reset()
    nodes, devices, ifc = _adhoc_chain(3)
    server = UdpEchoServerHelper(9)
    server.Install(nodes.Get(2)).Start(Seconds(0.0))
    # prime node 1's route to node 2
    c1 = UdpEchoClientHelper(ifc.GetAddress(2), 9)
    c1.SetAttribute("MaxPackets", 1)
    c1.Install(nodes.Get(1)).Start(Seconds(0.5))
    # then node 0 discovers; count RREQs arriving AT node 2
    rreq_at_2 = [0]
    nodes.Get(2).GetObject(Ipv4L3Protocol).TraceConnectWithoutContext(
        "LocalDeliver",
        lambda h, p, i: rreq_at_2.__setitem__(0, rreq_at_2[0] + 1)
        if h.protocol == AODV_PROT_NUMBER
        and Simulator.Now().GetSeconds() > 1.0
        and p.PeekHeader(AodvHeader) is not None
        and p.PeekHeader(AodvHeader).msg_type == AodvHeader.RREQ
        else None,
    )
    c0 = UdpEchoClientHelper(ifc.GetAddress(2), 9)
    c0.SetAttribute("MaxPackets", 1)
    c0apps = c0.Install(nodes.Get(0))
    c0apps.Start(Seconds(1.5))
    Simulator.Stop(Seconds(3.0))
    Simulator.Run()
    assert c0apps.Get(0).received == 1
    assert rreq_at_2[0] == 0, "intermediate reply should stop the flood"
    _reset()


def test_sequence_freshness_guards_the_table():
    _reset()
    nodes, devices, ifc = _adhoc_chain(2)
    a = _aodv(nodes.Get(0))
    via1 = Ipv4Address("10.1.1.7")
    via2 = Ipv4Address("10.1.1.8")
    dst = Ipv4Address("10.1.1.99")
    a._learn(dst, via1, 1, hops=2, seq=10)
    a._learn(dst, via2, 1, hops=1, seq=8)   # stale seq: ignored
    assert a._table[dst.addr][0] == via1
    a._learn(dst, via2, 1, hops=1, seq=10)  # same seq, fewer hops: wins
    assert a._table[dst.addr][0] == via2
    a._learn(dst, via1, 1, hops=5, seq=12)  # fresher seq wins regardless
    assert a._table[dst.addr][0] == via1
    _reset()


def test_header_roundtrip():
    h = AodvHeader(
        AodvHeader.RREQ, hop_count=3, rreq_id=77,
        dst=Ipv4Address("10.0.0.5"), dst_seq=9,
        orig=Ipv4Address("10.0.0.1"), orig_seq=4,
    )
    raw = h.Serialize()
    assert len(raw) == h.GetSerializedSize() == 24
    h2 = AodvHeader.Deserialize(raw)
    assert (h2.msg_type, h2.hop_count, h2.rreq_id) == (1, 3, 77)
    assert h2.dst == h.dst and h2.orig == h.orig
    assert (h2.dst_seq, h2.orig_seq) == (9, 4)
