"""internet-apps: DHCP and (next) Radvd/SLAAC — upstream
src/internet-apps/test strategy: the handshake configures real
interfaces that real traffic then uses."""

from tpudes.core import Seconds, Simulator
from tpudes.helper.applications import UdpEchoClientHelper, UdpEchoServerHelper
from tpudes.helper.containers import NodeContainer
from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
from tpudes.models.csma import CsmaHelper
from tpudes.models.internet.dhcp import DhcpHeader, DhcpHelper
from tpudes.network.address import Ipv4Address


def _reset():
    from tpudes.core.world import reset_world

    reset_world()


def _lan(n_clients=3):
    nodes = NodeContainer()
    nodes.Create(n_clients + 1)  # node 0 = DHCP server
    csma = CsmaHelper()
    csma.SetChannelAttribute("DataRate", "100Mbps")
    csma.SetChannelAttribute("Delay", "6560ns")
    devices = csma.Install(nodes)
    InternetStackHelper().Install(nodes)
    # only the server is statically configured
    a = Ipv4AddressHelper("10.0.0.0", "255.255.255.0")
    a.Assign([devices.Get(0)])
    helper = DhcpHelper()
    server = helper.InstallDhcpServer(
        nodes.Get(0), PoolAddresses="10.0.0.10", LeaseTime=4.0
    )
    server.SetStartTime(Seconds(0.0))
    clients = helper.InstallDhcpClient(
        [nodes.Get(i) for i in range(1, n_clients + 1)]
    )
    for i, c in enumerate(clients):
        c.SetStartTime(Seconds(0.1 + 0.05 * i))
    return nodes, devices, server, clients


def test_dhcp_handshake_assigns_distinct_pool_addresses():
    _reset()
    nodes, devices, server, clients = _lan(3)
    Simulator.Stop(Seconds(2.0))
    Simulator.Run()
    addrs = [c.address for c in clients]
    assert all(a is not None for a in addrs), addrs
    assert len({a.addr for a in addrs}) == 3
    pool = {Ipv4Address(f"10.0.0.{10 + i}").addr for i in range(3)}
    assert {a.addr for a in addrs} == pool
    _reset()


def test_dhcp_configured_address_carries_real_traffic():
    _reset()
    nodes, devices, server, clients = _lan(2)
    srv_rx = [0]
    echo = UdpEchoServerHelper(9)
    sapps = echo.Install(nodes.Get(0))
    sapps.Start(Seconds(0.0))
    sapps.Get(0).TraceConnectWithoutContext(
        "Rx", lambda pkt, *a: srv_rx.__setitem__(0, srv_rx[0] + 1)
    )
    client = UdpEchoClientHelper(Ipv4Address("10.0.0.1"), 9)
    client.SetAttribute("MaxPackets", 3)
    client.SetAttribute("Interval", Seconds(0.1))
    capps = client.Install(nodes.Get(1))
    capps.Start(Seconds(1.0))  # after the lease
    Simulator.Stop(Seconds(2.0))
    Simulator.Run()
    assert srv_rx[0] == 3
    assert capps.Get(0).received == 3
    _reset()


def test_dhcp_lease_renews_at_half_lease():
    _reset()
    nodes, devices, server, clients = _lan(1)
    leases = []
    clients[0].TraceConnectWithoutContext(
        "NewLease", lambda addr: leases.append(Simulator.Now().GetSeconds())
    )
    Simulator.Stop(Seconds(7.0))
    Simulator.Run()
    # initial lease + at least two T1 (= 2 s) renewals, same address
    assert len(leases) >= 3, leases
    assert clients[0].address == Ipv4Address("10.0.0.10")
    _reset()


def test_dhcp_header_roundtrip():
    from tpudes.network.address import Ipv4Mask, Mac48Address

    h = DhcpHeader(
        DhcpHeader.ACK, xid=7, yiaddr=Ipv4Address("10.0.0.42"),
        chaddr=Mac48Address("00:11:22:33:44:55"),
        server_id=Ipv4Address("10.0.0.1"),
        mask=Ipv4Mask("255.255.255.0"),
        gateway=Ipv4Address("10.0.0.1"), lease_s=30,
    )
    raw = h.Serialize()
    assert len(raw) == h.GetSerializedSize() == 36
    h2, n = DhcpHeader.Deserialize(raw)
    assert n == 36 and h2.msg_type == DhcpHeader.ACK and h2.xid == 7
    assert h2.yiaddr == h.yiaddr and h2.chaddr == h.chaddr
    assert h2.mask.mask == h.mask.mask and h2.lease_s == 30


# --- Radvd + SLAAC ---------------------------------------------------------

def test_radvd_slaac_autoconfigures_and_routes():
    """host --csma-- router --p2p-- remote: the host starts with only a
    link-local address; the router's RA gives it an EUI-64 global
    address under the advertised prefix AND a default route good enough
    to ping the remote's off-link address (RFC 4862 + 4861)."""
    from tpudes.helper.internet import Ipv6AddressHelper
    from tpudes.helper.point_to_point import PointToPointHelper
    from tpudes.models.internet.icmpv6 import (
        Icmpv6L4Protocol,
        Ping6,
        RadvdApplication,
    )
    from tpudes.models.internet.ipv6 import (
        Ipv6InterfaceAddress,
        Ipv6L3Protocol,
        Ipv6StaticRouting,
    )
    from tpudes.network.address import Ipv6Address, Ipv6Prefix

    _reset()
    nodes = NodeContainer()
    nodes.Create(3)  # 0 host, 1 router, 2 remote
    csma = CsmaHelper()
    lan = csma.Install([nodes.Get(0), nodes.Get(1)])
    p2p = PointToPointHelper()
    wan = p2p.Install(nodes.Get(1), nodes.Get(2))
    InternetStackHelper().Install(nodes)

    a = Ipv6AddressHelper()
    a.SetBase("2001:db8:99::", 64)
    wan_ifc = a.Assign(wan)
    # router's LAN-side global address (the prefix it will advertise)
    r6 = nodes.Get(1).GetObject(Ipv6L3Protocol)
    r_lan_if = r6.AddInterface(lan.Get(1))
    r6.AddAddress(
        r_lan_if,
        Ipv6InterfaceAddress(Ipv6Address("2001:db8:50::1"), Ipv6Prefix(64)),
    )
    r6.GetRoutingProtocol().AddNetworkRouteTo(
        Ipv6Address("2001:db8:50::"), Ipv6Prefix(64), r_lan_if
    )
    # remote's route back to the LAN via the router
    nodes.Get(2).GetObject(Ipv6L3Protocol).GetRoutingProtocol(
    ).SetDefaultRoute(wan_ifc.GetAddress(0, 1), 1)
    # the host only registers its v6 interface (no address assigned)
    h6 = nodes.Get(0).GetObject(Ipv6L3Protocol)
    h6.AddInterface(lan.Get(0))

    radvd = RadvdApplication(Interval=0.5)
    radvd.AddConfiguration(lan.Get(1), "2001:db8:50::", 64)
    nodes.Get(1).AddApplication(radvd)
    radvd.SetStartTime(Seconds(0.1))

    autoconf = []
    nodes.Get(0).GetObject(Icmpv6L4Protocol).TraceConnectWithoutContext(
        "Autoconf", lambda addr: autoconf.append(addr)
    )

    ping = Ping6(Remote=str(wan_ifc.GetAddress(1, 1)), Interval=0.2)
    nodes.Get(0).AddApplication(ping)
    ping.SetStartTime(Seconds(1.0))  # after the first RA
    ping.SetStopTime(Seconds(2.0))
    Simulator.Stop(Seconds(2.5))
    Simulator.Run()

    assert len(autoconf) == 1  # one SLAAC event, not one per RA
    expected = Ipv6Address.MakeAutoconfiguredAddress(
        lan.Get(0).GetAddress(), Ipv6Address("2001:db8:50::")
    )
    assert autoconf[0] == expected
    iface = h6.GetInterface(h6.GetInterfaceForDevice(lan.Get(0)))
    assert any(a.GetLocal() == expected for a in iface.addresses)
    assert len(ping.rtts) >= 4, ping.rtts
    _reset()


def test_dhcp_lease_expires_when_server_dies():
    """r5 review: the Expiry trace must actually fire — stop the server
    and the client loses its lease at the deadline, then restarts
    discovery."""
    _reset()
    nodes, devices, server, clients = _lan(1)
    expiries = []
    clients[0].TraceConnectWithoutContext(
        "Expiry", lambda *a: expiries.append(Simulator.Now().GetSeconds())
    )
    server.SetStopTime(Seconds(1.0))  # lease is 4 s: renewals go dark
    Simulator.Stop(Seconds(8.0))
    Simulator.Run()
    assert expiries, "no expiry despite a dead server"
    assert expiries[0] >= 4.0
    _reset()
