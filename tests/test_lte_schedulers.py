"""The FF-MAC scheduler family beyond PF/RR (SURVEY.md §2.6 lists
PF, RR, FD/TD-MT, TTA, TD/FD-BET, CQA, PSS) — each algorithm pinned on
the behavioral signature upstream's lte test suites check: MT starves,
BET equalizes bits, CQA honors urgency, PSS honors targets."""

from tpudes.models.lte.scheduler import (
    CqaFfMacScheduler,
    FdBetFfMacScheduler,
    FdMtFfMacScheduler,
    PssFfMacScheduler,
    SCHEDULERS,
    SchedCandidate,
    TdBetFfMacScheduler,
    TdMtFfMacScheduler,
    TtaFfMacScheduler,
)

RBGS = list(range(13))
RBG = 2


def _full_buffer(cqis, **extra):
    return [
        SchedCandidate(rnti=i + 1, cqi=c, queue_bytes=1 << 30, **extra)
        for i, c in enumerate(cqis)
    ]


def _run(sched, cqis, ttis, cands_fn=None):
    """Drive full-buffer TTIs; returns served bits per rnti."""
    served = {i + 1: 0 for i in range(len(cqis))}
    for tti in range(ttis):
        cands = cands_fn(tti) if cands_fn else _full_buffer(cqis)
        allocs = sched.schedule(tti, cands, list(RBGS), RBG)
        bits = {a.rnti: a.tb_bytes * 8 for a in allocs}
        for r, b in bits.items():
            served[r] += b
        if hasattr(sched, "end_tti"):
            sched.end_tti(
                {a.rnti: a.tb_bytes * 8 for a in allocs}, list(served)
            )
    return served


def test_registry_has_the_nine_upstream_algorithms():
    names = {c.name for c in set(SCHEDULERS.values())}
    assert names == {
        "pf", "rr", "tdmt", "fdmt", "tta", "tdbet", "fdbet", "cqa", "pss"
    }


def test_tdmt_gives_the_whole_tti_to_the_best_channel():
    sched = TdMtFfMacScheduler()
    for tti in range(20):
        allocs = sched.schedule(tti, _full_buffer([15, 8, 4]), list(RBGS), RBG)
        assert len(allocs) == 1 and allocs[0].rnti == 1
    # the starved UEs never appear — MT's defining (anti-)fairness
    served = _run(TdMtFfMacScheduler(), [15, 8, 4], 50)
    assert served[2] == 0 and served[3] == 0


def test_fdmt_serves_by_rate_order():
    sched = FdMtFfMacScheduler()
    cands = [
        SchedCandidate(rnti=1, cqi=4, queue_bytes=300),
        SchedCandidate(rnti=2, cqi=15, queue_bytes=300),
    ]
    allocs = sched.schedule(0, cands, list(RBGS), RBG)
    # the high-rate UE is filled first (light load: both fit)
    assert allocs[0].rnti == 2
    assert sorted(a.rnti for a in allocs) == [1, 2]


def test_bet_equalizes_bits_across_unequal_channels():
    """BET's defining property: UEs at CQI 15 and CQI 6 end up with
    ~equal BITS (RR would give them equal AIRTIME, hence unequal bits)."""
    for cls in (TdBetFfMacScheduler, FdBetFfMacScheduler):
        served = _run(cls(alpha=0.1), [15, 6], 3000)
        ratio = served[1] / max(served[2], 1)
        assert 0.8 < ratio < 1.25, (cls.__name__, served)


def test_tta_multiplexes_and_skips_dead_channels():
    sched = TtaFfMacScheduler()
    served = _run(sched, [12, 12, 12], 30)
    assert all(v > 0 for v in served.values())
    allocs = sched.schedule(99, _full_buffer([0, 12, 12]), list(RBGS), RBG)
    assert all(a.rnti != 1 for a in allocs)  # CQI 0 never scheduled


def test_cqa_urgency_beats_channel():
    sched = CqaFfMacScheduler()
    cands = [
        SchedCandidate(rnti=1, cqi=15, queue_bytes=1 << 30, hol_delay_ms=0.0),
        SchedCandidate(rnti=2, cqi=6, queue_bytes=1 << 30, hol_delay_ms=45.0),
    ]
    allocs = sched.schedule(0, cands, list(RBGS), RBG)
    assert allocs[0].rnti == 2, "stale HOL must outrank the better channel"
    # with equal delay groups the channel term decides again
    cands[1].hol_delay_ms = 0.0
    allocs = sched.schedule(1, cands, list(RBGS), RBG)
    assert allocs[0].rnti == 1


def test_pss_priority_set_meets_target_then_yields():
    sched = PssFfMacScheduler(alpha=0.1)
    # rnti 1: great channel, no target; rnti 2: poor channel, 1 Mbps TBR
    def cands(_tti):
        return [
            SchedCandidate(rnti=1, cqi=15, queue_bytes=1 << 30),
            SchedCandidate(rnti=2, cqi=5, queue_bytes=1 << 30,
                           tbr_bps=1_000_000.0),
        ]

    served = _run(sched, [15, 5], 2000, cands_fn=cands)
    # the targeted flow is protected: it reaches (around) its TBR even
    # though pure PF/MT would starve its poor channel
    got_bps = served[2] / 2.0 * 1000 / 1000  # bits over 2000 ms -> bps
    assert served[2] > 0
    assert got_bps > 500_000, got_bps
    # and the best-effort flow still gets the (larger) remainder
    assert served[1] > served[2]


def test_all_schedulers_run_in_the_full_lena_loop():
    """End-to-end: each registered algorithm drives a small lena grid
    for 30 TTIs without error and serves every UE's buffer."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from tests.test_lte import _build_lena
    from tpudes.core import Seconds, Simulator
    from tpudes.core.world import reset_world

    for name in ("tdmt", "fdmt", "tta", "tdbet", "fdbet", "cqa", "pss"):
        reset_world()
        lte, enbs, ues = _build_lena(1, 3, scheduler=name)
        Simulator.Stop(Seconds(0.03))
        Simulator.Run()
        assert lte.controller.stats["ttis"] >= 30, name
        assert lte.controller.stats["dl_tbs"] > 0, name
    reset_world()


def test_sm_engine_lowers_every_registered_scheduler():
    """r5 review forbade silently mis-lowering non-pf/rr algorithms;
    r6 closes the gap the right way: every registered FF-MAC scheduler
    now lowers to the traced-id dispatch (tests/test_lte_sm.py pins the
    per-family behavior) while a custom class still refuses loudly."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    from tests.test_lte import _build_lena
    from tpudes.core.world import reset_world
    from tpudes.parallel.lte_sm import lower_lte_sm

    reset_world()
    lte, enbs, ues = _build_lena(1, 2, scheduler="tdmt")
    prog = lower_lte_sm(lte, 1.0)
    assert prog.scheduler == "tdmt"
    reset_world()
