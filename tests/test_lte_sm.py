"""Device-resident LTE SM engine tests.

Validates tpudes/parallel/lte_sm.py — lowering guards, determinism,
HARQ/drop conservation, the int32-overflow-free bit accounting, the
replica axis (vmap + mesh sharding), and statistical parity against the
host TTI controller on an identical scenario (the SURVEY.md §7 step-8
"same scenario, two engines" check).
"""

import math

import numpy as np
import pytest

from tpudes.parallel.lte_sm import (
    LteSmProgram,
    UnliftableLteScenarioError,
    lower_lte_sm,
    run_lte_sm,
)


def _toy_prog(n_ttis=300, scheduler="pf", n_ue=6, n_enb=2, n_rb=25):
    rng = np.random.default_rng(5)
    serving = (np.arange(n_ue) % n_enb).astype(np.int32)
    # serving path 20 dB above every interferer, ±6 dB per-UE spread:
    # every UE lands at a usable CQI
    gain = 10.0 ** rng.uniform(-11.6, -11.0, size=(n_enb, n_ue))
    gain[serving, np.arange(n_ue)] = 10.0 ** rng.uniform(
        -9.6, -9.0, size=(n_ue,)
    )
    return LteSmProgram(
        gain=gain,
        serving=serving,
        tx_power_dbm=np.full((n_enb,), 30.0),
        noise_psd=10.0 ** 0.9 * 1.380649e-23 * 290.0,
        n_rb=n_rb,
        n_ttis=n_ttis,
        scheduler=scheduler,
    )


def _build_helper_scenario(n_enbs=2, ues_per_cell=3, scheduler="pf"):
    from tpudes.helper.containers import NodeContainer
    from tpudes.models.lte import LteHelper
    from tpudes.models.lte.scheduler import resolve_scheduler
    from tpudes.models.mobility import (
        ListPositionAllocator,
        MobilityHelper,
        Vector,
    )

    lte = LteHelper()
    lte.SetSchedulerType(resolve_scheduler(scheduler))
    enbs = NodeContainer()
    enbs.Create(n_enbs)
    ues = NodeContainer()
    ues.Create(n_enbs * ues_per_cell)
    ea = ListPositionAllocator()
    for i in range(n_enbs):
        ea.Add(Vector(i * 500.0, 0.0, 30.0))
    me = MobilityHelper()
    me.SetPositionAllocator(ea)
    me.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    me.Install(enbs)
    ua = ListPositionAllocator()
    rng = np.random.default_rng(9)
    for c in range(n_enbs):
        for _ in range(ues_per_cell):
            r = 200.0 * math.sqrt(rng.uniform())
            a = 2 * math.pi * rng.uniform()
            ua.Add(Vector(c * 500.0 + r * math.cos(a), r * math.sin(a), 1.5))
    mu = MobilityHelper()
    mu.SetPositionAllocator(ua)
    mu.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    mu.Install(ues)
    lte.InstallEnbDevice(enbs)
    ue_devs = lte.InstallUeDevice(ues)
    ue_list = [ue_devs.Get(i) for i in range(ue_devs.GetN())]
    lte.Attach(ue_list)
    lte.ActivateDataRadioBearer(ue_list)
    return lte, ue_list


class TestLowering:
    def test_lower_from_helper(self):
        lte, _ = _build_helper_scenario()
        prog = lower_lte_sm(lte, 0.25)
        assert prog.n_enb == 2 and prog.n_ue == 6
        assert prog.n_ttis == 250
        assert prog.scheduler == "pf"
        assert (prog.serving == np.array([0, 1] * 3)).all() or set(
            prog.serving
        ) <= {0, 1}

    def test_rejects_non_sm_bearer(self):
        lte, ue_list = _build_helper_scenario()
        # re-activate one UE with a UM bearer on top
        enb = ue_list[0].rrc.serving_enb
        ctx = enb.rrc.ues[ue_list[0].rrc.rnti]
        enb.rrc.setup_bearer(ctx, "um")
        with pytest.raises(UnliftableLteScenarioError):
            lower_lte_sm(lte, 0.1)

    def test_rejects_unattached_ue(self):
        from tpudes.helper.containers import NodeContainer
        from tpudes.models.mobility import (
            ListPositionAllocator,
            MobilityHelper,
            Vector,
        )

        lte, _ = _build_helper_scenario()
        extra = NodeContainer()
        extra.Create(1)
        a = ListPositionAllocator()
        a.Add(Vector(50.0, 50.0, 1.5))
        m = MobilityHelper()
        m.SetPositionAllocator(a)
        m.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
        m.Install(extra)
        lte.InstallUeDevice(extra)  # installed but never attached
        with pytest.raises(UnliftableLteScenarioError):
            lower_lte_sm(lte, 0.1)

    def _with_walker(self):
        from tpudes.helper.containers import NodeContainer
        from tpudes.models.mobility import MobilityHelper

        lte, _ = _build_helper_scenario()
        walker = NodeContainer()
        walker.Create(1)
        m = MobilityHelper()
        m.SetPositionAllocator(
            "tpudes::RandomDiscPositionAllocator", X=0.0, Y=0.0, Rho=10.0
        )
        m.SetMobilityModel(
            "tpudes::RandomWalk2dMobilityModel",
        )
        m.Install(walker)
        dev = lte.InstallUeDevice(walker)
        lte.Attach([dev.Get(0)])
        lte.ActivateDataRadioBearer([dev.Get(0)])
        return lte

    def test_mobile_geometry_lifts_by_default(self):
        # the ISSUE-10 flip: moving UEs ride the device geometry
        # pipeline instead of being refused
        lte = self._with_walker()
        prog = lower_lte_sm(lte, 0.3)
        assert prog.mobility is not None
        assert prog.mobility.model == "random_walk"
        assert prog.pathloss is not None and prog.enb_pos is not None

    def test_mobile_geometry_refused_under_kill_switch(self, monkeypatch):
        # TPUDES_DEVICE_GEOM=0 restores the loud refusal (the host
        # controller's per-window refresh is the fallback path)
        lte = self._with_walker()
        monkeypatch.setenv("TPUDES_DEVICE_GEOM", "0")
        with pytest.raises(UnliftableLteScenarioError):
            lower_lte_sm(lte, 0.3)

    def test_mobile_enb_still_refused(self):
        from tpudes.models.mobility import (
            ConstantVelocityMobilityModel,
            MobilityModel,
            Vector,
        )

        lte, _ = _build_helper_scenario()
        enb_node = lte.controller.enbs[0].GetNode()
        old = enb_node.GetObject(MobilityModel)
        cv = ConstantVelocityMobilityModel()
        cv.SetPosition(old.GetPosition())
        cv.SetVelocity(Vector(1.0, 0.0, 0.0))
        # replace the model in the aggregation ring (GetObject returns
        # the first match, so appending would not take effect)
        ring = enb_node._aggregates
        ring[ring.index(old)] = cv
        cv._aggregates = ring
        with pytest.raises(UnliftableLteScenarioError):
            lower_lte_sm(lte, 0.3)


class TestSmEngine:
    def test_deterministic_per_key(self):
        import jax

        prog = _toy_prog()
        a = run_lte_sm(prog, jax.random.PRNGKey(7))
        b = run_lte_sm(prog, jax.random.PRNGKey(7))
        c = run_lte_sm(prog, jax.random.PRNGKey(8))
        np.testing.assert_array_equal(a["rx_bits"], b["rx_bits"])
        assert (a["rx_bits"] != c["rx_bits"]).any()

    def test_conservation_new_tbs(self):
        import jax

        prog = _toy_prog(n_ttis=500)
        out = run_lte_sm(prog, jax.random.PRNGKey(0))
        # every first transmission ends decoded, dropped, or pending:
        # ok counts retransmission successes too, so compare TB-wise:
        # ok_tbs = new_tbs - drops - pending; pending is not exported but
        # bounded by E (one in-flight TB per UE at most)
        slack = prog.n_ue
        assert (out["ok"] + out["drops"] <= out["new_tbs"] + out["retx"]).all()
        assert (out["new_tbs"] >= out["ok"] + out["drops"] - slack).all()

    def test_every_ue_served_under_pf(self):
        import jax

        prog = _toy_prog(n_ttis=400)
        out = run_lte_sm(prog, jax.random.PRNGKey(1))
        assert (out["rx_bits"] > 0).all()

    def test_rr_time_shares_equal(self):
        import jax

        prog = _toy_prog(n_ttis=900, scheduler="rr")
        out = run_lte_sm(prog, jax.random.PRNGKey(2))
        # 3 UEs per cell, 900 TTIs: each UE wins ~300 TTIs
        tbs = out["new_tbs"] + out["retx"]
        assert tbs.min() > 250 and tbs.max() < 350

    def test_rx_bits_exact_past_int32(self):
        import jax

        # one UE hogging 100 RB at peak MCS: ~66k bits/TTI; 40k TTIs
        # crosses 2^31 bits — the lo/hi accounting must stay exact
        prog = LteSmProgram(
            gain=np.array([[1e-7]]),
            serving=np.zeros(1, np.int32),
            tx_power_dbm=np.array([46.0]),
            noise_psd=10.0 ** 0.9 * 1.380649e-23 * 290.0,
            n_rb=100,
            n_ttis=40_000,
            scheduler="pf",
        )
        out = run_lte_sm(prog, jax.random.PRNGKey(3))
        total = int(out["rx_bits"][0])
        assert total > 2**31
        # exact multiple of the (single, static) TB size
        from tpudes.ops.lte import tbs_bits_py

        consts_tb = None
        for mcs in range(29):
            tb = tbs_bits_py(mcs, 100)
            if total % max(tb, 1) == 0 and total // max(tb, 1) == int(
                out["ok"][0]
            ):
                consts_tb = tb
                break
        assert consts_tb is not None

    def test_replica_axis_vmap(self):
        import jax

        prog = _toy_prog(n_ttis=200)
        out = run_lte_sm(prog, jax.random.PRNGKey(4), replicas=4)
        assert out["rx_bits"].shape == (4, prog.n_ue)
        # replicas see different decode draws but identical physics:
        # totals agree within Monte-Carlo noise
        totals = out["rx_bits"].sum(axis=1).astype(float)
        assert totals.std() / totals.mean() < 0.1
        # not all byte-identical
        assert len({int(t) for t in totals}) > 1 or totals.std() == 0

    def test_replica_axis_mesh_sharded_matches_vmap(self):
        import jax

        if len(jax.devices()) < 4:
            pytest.skip("needs the virtual multi-device mesh")
        from tpudes.parallel.mesh import replica_mesh

        prog = _toy_prog(n_ttis=150)
        mesh = replica_mesh(4)
        plain = run_lte_sm(prog, jax.random.PRNGKey(5), replicas=8)
        shard = run_lte_sm(prog, jax.random.PRNGKey(5), replicas=8, mesh=mesh)
        # sharding the replica axis must not change the computation
        np.testing.assert_array_equal(plain["rx_bits"], shard["rx_bits"])
        np.testing.assert_array_equal(plain["ok"], shard["ok"])


class TestSchedulerFamily:
    """All nine FF-MAC schedulers ride one jitted program (the traced
    scheduler-id dispatch) — behavior pins for each family plus the
    one-compile-serves-all property the perf story depends on."""

    def test_lowering_accepts_every_registered_scheduler(self):
        from tpudes.core.world import reset_world
        from tpudes.parallel.lte_sm import SM_SCHED_IDS

        for sched in SM_SCHED_IDS:
            reset_world()
            lte, _ = _build_helper_scenario(scheduler=sched)
            prog = lower_lte_sm(lte, 0.05)
            assert prog.scheduler == sched
        reset_world()

    def test_custom_scheduler_class_still_refused(self):
        """The refusal list names structural constraints only — but an
        unregistered user scheduler class has arbitrary host semantics
        and must never be silently approximated (the round-2 rule)."""
        from tpudes.models.lte.scheduler import FfMacScheduler

        class MyScheduler(FfMacScheduler):
            def schedule(self, tti, candidates, free_rbgs, rbg_size):
                return []

        lte, _ = _build_helper_scenario()
        for enb in lte.controller.enbs:
            enb.scheduler = MyScheduler()
        with pytest.raises(UnliftableLteScenarioError) as ei:
            lower_lte_sm(lte, 0.1)
        # no registered family name in the message: the device engine
        # no longer refuses any upstream scheduler
        for name in ("pf", "rr", "tdmt", "fdmt", "tta",
                     "tdbet", "fdbet", "cqa", "pss"):
            assert f" {name}" not in str(ei.value).lower()

    def test_one_compiled_program_serves_all_nine(self):
        """The scheduler id is a traced operand: sweeping the family
        reuses ONE cache entry (one XLA executable), not nine."""
        import dataclasses

        import jax

        from tpudes.parallel import lte_sm as mod
        from tpudes.parallel.runtime import RUNTIME

        RUNTIME.clear("lte_sm")
        base = _toy_prog(n_ttis=120)
        outs = {}
        for sched in mod.SM_SCHED_IDS:
            prog = dataclasses.replace(base, scheduler=sched)
            outs[sched] = run_lte_sm(prog, jax.random.PRNGKey(2))
        assert RUNTIME.size("lte_sm") == 1
        # and the dispatch actually differentiates the families
        assert (
            outs["tdmt"]["new_tbs"] != outs["pf"]["new_tbs"]
        ).any()

    def test_mt_winner_takes_all(self):
        import jax

        import dataclasses

        prog = dataclasses.replace(_toy_prog(n_ttis=400), scheduler="tdmt")
        out = run_lte_sm(prog, jax.random.PRNGKey(3))
        # per cell, exactly the best-rate UE is ever scheduled; the
        # others starve (max-throughput is maximally unfair)
        for c in range(prog.n_enb):
            members = np.where(prog.serving == c)[0]
            tbs = (out["new_tbs"] + out["retx"])[members]
            assert (tbs > 0).sum() == 1, tbs
            winner = members[np.argmax(tbs)]
            assert out["mcs"][winner] == out["mcs"][members].max()

    def test_bet_equalizes_bits_where_rr_equalizes_airtime(self):
        import dataclasses

        import jax

        base = _toy_prog(n_ttis=1200)
        bet = run_lte_sm(
            dataclasses.replace(base, scheduler="fdbet"), jax.random.PRNGKey(4)
        )
        rr = run_lte_sm(
            dataclasses.replace(base, scheduler="rr"), jax.random.PRNGKey(4)
        )
        def cv(x):
            x = x.astype(float)
            return x.std() / x.mean()

        # BET: served BITS converge to equal across unequal-CQI UEs;
        # RR gives equal airtime, so its bit spread tracks the MCS spread
        assert cv(bet["rx_bits"]) < 0.5 * cv(rr["rx_bits"])
        # while its airtime (TB count) spread is the wider one
        assert cv((bet["new_tbs"] + bet["retx"])) > cv(
            rr["new_tbs"] + rr["retx"]
        )

    def test_degenerate_families_coincide(self):
        """Full-buffer degeneracies pinned: TD≡FD within MT, TTA≡RR,
        CQA≡PSS≡PF — same decode draws, identical outcomes."""
        import dataclasses

        import jax

        base = _toy_prog(n_ttis=250)
        runs = {
            s: run_lte_sm(
                dataclasses.replace(base, scheduler=s), jax.random.PRNGKey(6)
            )
            for s in ("pf", "cqa", "pss", "rr", "tta", "tdmt", "fdmt",
                      "tdbet", "fdbet")
        }
        for a, b in (("cqa", "pf"), ("pss", "pf"), ("tta", "rr"),
                     ("fdmt", "tdmt"), ("fdbet", "tdbet")):
            np.testing.assert_array_equal(
                runs[a]["rx_bits"], runs[b]["rx_bits"], err_msg=f"{a} vs {b}"
            )


class TestHostDeviceParity:
    def test_sm_engine_matches_host_controller(self):
        """The device engine and the host TTI loop run the SAME lowered
        scenario; aggregate and per-cell DL throughput must agree within
        Monte-Carlo + timing-model tolerance (the deviations documented
        in the lte_sm module docstring, all bounded)."""
        import jax

        from tpudes.core.nstime import Seconds
        from tpudes.core.simulator import Simulator

        sim_time = 0.4
        lte, _ = _build_helper_scenario(n_enbs=2, ues_per_cell=3)
        prog = lower_lte_sm(lte, sim_time)

        # host engine
        Simulator.Stop(Seconds(sim_time))
        Simulator.Run()
        stats = lte.GetRlcStats()
        host_bits = sum(s["dl_rx_bytes"] for s in stats) * 8
        host_cell = {}
        for s in stats:
            host_cell[s["cell_id"]] = (
                host_cell.get(s["cell_id"], 0) + s["dl_rx_bytes"] * 8
            )

        # device engine, same program
        out = run_lte_sm(prog, jax.random.PRNGKey(11))
        dev_bits = int(out["rx_bits"].sum())
        dev_cell = {}
        cell_ids = [e.GetCellId() for e in lte.controller.enbs]
        for u in range(prog.n_ue):
            c = cell_ids[int(prog.serving[u])]
            dev_cell[c] = dev_cell.get(c, 0) + int(out["rx_bits"][u])

        assert dev_bits == pytest.approx(host_bits, rel=0.15)
        for c in host_cell:
            assert dev_cell[c] == pytest.approx(host_cell[c], rel=0.2)

    @pytest.mark.parametrize(
        "sched", ["pf", "rr", "tdmt", "fdmt", "tta", "tdbet", "fdbet",
                  "cqa", "pss"]
    )
    def test_scheduler_fairness_parity(self, sched):
        """Device vs host on the SAME lowered scenario, per scheduler:
        aggregate DL throughput within the documented timing-model
        tolerance AND per-UE fairness shares matching — the quantity
        each scheduler family actually differentiates.  MT gets a wider
        share tolerance: the device's single HARQ process redirects the
        winner's TTIs to the runner-up during the 8 ms HARQ RTT (module
        docstring deviation), which the host's overlapping processes
        don't."""
        import jax

        from tpudes.core.nstime import Seconds
        from tpudes.core.simulator import Simulator
        from tpudes.core.world import reset_world

        sim_time = 0.3
        reset_world()
        lte, _ = _build_helper_scenario(
            n_enbs=2, ues_per_cell=3, scheduler=sched
        )
        prog = lower_lte_sm(lte, sim_time)
        assert prog.scheduler == sched

        Simulator.Stop(Seconds(sim_time))
        Simulator.Run()
        host = np.array(
            [s["dl_rx_bytes"] * 8 for s in lte.GetRlcStats()], dtype=float
        )
        out = run_lte_sm(prog, jax.random.PRNGKey(11))
        dev = out["rx_bits"].astype(float)
        reset_world()

        assert dev.sum() == pytest.approx(host.sum(), rel=0.15), sched
        host_share = host / host.sum()
        dev_share = dev / dev.sum()
        tol = 0.15 if sched in ("tdmt", "fdmt") else 0.05
        np.testing.assert_allclose(
            dev_share, host_share, atol=tol,
            err_msg=f"{sched}: shares {dev_share} vs host {host_share}",
        )

    def test_sm_engine_matches_host_under_bf16(self):
        """ISSUE-6 budget pin: the bf16 mixed-precision mode must hold
        the SAME host-parity tolerances as f32 — the precision knob
        buys speed, not a different simulator."""
        import jax

        from tpudes.core.nstime import Seconds
        from tpudes.core.simulator import Simulator

        sim_time = 0.4
        lte, _ = _build_helper_scenario(n_enbs=2, ues_per_cell=3)
        prog = lower_lte_sm(lte, sim_time, precision="bf16")
        assert prog.precision == "bf16"

        Simulator.Stop(Seconds(sim_time))
        Simulator.Run()
        host_bits = sum(
            s["dl_rx_bytes"] for s in lte.GetRlcStats()
        ) * 8

        out = run_lte_sm(prog, jax.random.PRNGKey(11))
        assert int(out["rx_bits"].sum()) == pytest.approx(
            host_bits, rel=0.15
        )
        # the bf16-rounded CQI still matches the host's f32 steady
        # state away from efficiency boundaries: allow ±1 index
        host_cqi = np.asarray(lte.controller._cqi_dl)
        assert np.abs(out["cqi"].astype(int) - host_cqi).max() <= 1

    def test_sm_engine_cqi_matches_host(self):
        """Static full-buffer geometry: the device engine's precomputed
        CQI equals the host controller's steady-state applied CQI."""
        import jax

        from tpudes.core.nstime import Seconds
        from tpudes.core.simulator import Simulator

        lte, _ = _build_helper_scenario(n_enbs=2, ues_per_cell=3)
        prog = lower_lte_sm(lte, 0.02)
        out = run_lte_sm(prog, jax.random.PRNGKey(0))
        Simulator.Stop(Seconds(0.02))
        Simulator.Run()
        host_cqi = np.asarray(lte.controller._cqi_dl)
        np.testing.assert_array_equal(out["cqi"], host_cqi)
