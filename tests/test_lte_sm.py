"""Device-resident LTE SM engine tests.

Validates tpudes/parallel/lte_sm.py — lowering guards, determinism,
HARQ/drop conservation, the int32-overflow-free bit accounting, the
replica axis (vmap + mesh sharding), and statistical parity against the
host TTI controller on an identical scenario (the SURVEY.md §7 step-8
"same scenario, two engines" check).
"""

import math

import numpy as np
import pytest

from tpudes.parallel.lte_sm import (
    LteSmProgram,
    UnliftableLteScenarioError,
    lower_lte_sm,
    run_lte_sm,
)


def _toy_prog(n_ttis=300, scheduler="pf", n_ue=6, n_enb=2, n_rb=25):
    rng = np.random.default_rng(5)
    serving = (np.arange(n_ue) % n_enb).astype(np.int32)
    # serving path 20 dB above every interferer, ±6 dB per-UE spread:
    # every UE lands at a usable CQI
    gain = 10.0 ** rng.uniform(-11.6, -11.0, size=(n_enb, n_ue))
    gain[serving, np.arange(n_ue)] = 10.0 ** rng.uniform(
        -9.6, -9.0, size=(n_ue,)
    )
    return LteSmProgram(
        gain=gain,
        serving=serving,
        tx_power_dbm=np.full((n_enb,), 30.0),
        noise_psd=10.0 ** 0.9 * 1.380649e-23 * 290.0,
        n_rb=n_rb,
        n_ttis=n_ttis,
        scheduler=scheduler,
    )


def _build_helper_scenario(n_enbs=2, ues_per_cell=3, scheduler="pf"):
    from tpudes.helper.containers import NodeContainer
    from tpudes.models.lte import LteHelper
    from tpudes.models.mobility import (
        ListPositionAllocator,
        MobilityHelper,
        Vector,
    )

    lte = LteHelper()
    lte.SetSchedulerType(
        "tpudes::PfFfMacScheduler"
        if scheduler == "pf"
        else "tpudes::RrFfMacScheduler"
    )
    enbs = NodeContainer()
    enbs.Create(n_enbs)
    ues = NodeContainer()
    ues.Create(n_enbs * ues_per_cell)
    ea = ListPositionAllocator()
    for i in range(n_enbs):
        ea.Add(Vector(i * 500.0, 0.0, 30.0))
    me = MobilityHelper()
    me.SetPositionAllocator(ea)
    me.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    me.Install(enbs)
    ua = ListPositionAllocator()
    rng = np.random.default_rng(9)
    for c in range(n_enbs):
        for _ in range(ues_per_cell):
            r = 200.0 * math.sqrt(rng.uniform())
            a = 2 * math.pi * rng.uniform()
            ua.Add(Vector(c * 500.0 + r * math.cos(a), r * math.sin(a), 1.5))
    mu = MobilityHelper()
    mu.SetPositionAllocator(ua)
    mu.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    mu.Install(ues)
    lte.InstallEnbDevice(enbs)
    ue_devs = lte.InstallUeDevice(ues)
    ue_list = [ue_devs.Get(i) for i in range(ue_devs.GetN())]
    lte.Attach(ue_list)
    lte.ActivateDataRadioBearer(ue_list)
    return lte, ue_list


class TestLowering:
    def test_lower_from_helper(self):
        lte, _ = _build_helper_scenario()
        prog = lower_lte_sm(lte, 0.25)
        assert prog.n_enb == 2 and prog.n_ue == 6
        assert prog.n_ttis == 250
        assert prog.scheduler == "pf"
        assert (prog.serving == np.array([0, 1] * 3)).all() or set(
            prog.serving
        ) <= {0, 1}

    def test_rejects_non_sm_bearer(self):
        lte, ue_list = _build_helper_scenario()
        # re-activate one UE with a UM bearer on top
        enb = ue_list[0].rrc.serving_enb
        ctx = enb.rrc.ues[ue_list[0].rrc.rnti]
        enb.rrc.setup_bearer(ctx, "um")
        with pytest.raises(UnliftableLteScenarioError):
            lower_lte_sm(lte, 0.1)

    def test_rejects_unattached_ue(self):
        from tpudes.helper.containers import NodeContainer
        from tpudes.models.mobility import (
            ListPositionAllocator,
            MobilityHelper,
            Vector,
        )

        lte, _ = _build_helper_scenario()
        extra = NodeContainer()
        extra.Create(1)
        a = ListPositionAllocator()
        a.Add(Vector(50.0, 50.0, 1.5))
        m = MobilityHelper()
        m.SetPositionAllocator(a)
        m.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
        m.Install(extra)
        lte.InstallUeDevice(extra)  # installed but never attached
        with pytest.raises(UnliftableLteScenarioError):
            lower_lte_sm(lte, 0.1)

    def test_rejects_mobile_geometry(self):
        from tpudes.helper.containers import NodeContainer
        from tpudes.models.mobility import MobilityHelper

        lte, _ = _build_helper_scenario()
        walker = NodeContainer()
        walker.Create(1)
        m = MobilityHelper()
        m.SetPositionAllocator(
            "tpudes::RandomDiscPositionAllocator", X=0.0, Y=0.0, Rho=10.0
        )
        m.SetMobilityModel(
            "tpudes::RandomWalk2dMobilityModel",
        )
        m.Install(walker)
        dev = lte.InstallUeDevice(walker)
        lte.Attach([dev.Get(0)])
        lte.ActivateDataRadioBearer([dev.Get(0)])
        with pytest.raises(UnliftableLteScenarioError):
            lower_lte_sm(lte, 0.1)


class TestSmEngine:
    def test_deterministic_per_key(self):
        import jax

        prog = _toy_prog()
        a = run_lte_sm(prog, jax.random.PRNGKey(7))
        b = run_lte_sm(prog, jax.random.PRNGKey(7))
        c = run_lte_sm(prog, jax.random.PRNGKey(8))
        np.testing.assert_array_equal(a["rx_bits"], b["rx_bits"])
        assert (a["rx_bits"] != c["rx_bits"]).any()

    def test_conservation_new_tbs(self):
        import jax

        prog = _toy_prog(n_ttis=500)
        out = run_lte_sm(prog, jax.random.PRNGKey(0))
        # every first transmission ends decoded, dropped, or pending:
        # ok counts retransmission successes too, so compare TB-wise:
        # ok_tbs = new_tbs - drops - pending; pending is not exported but
        # bounded by E (one in-flight TB per UE at most)
        slack = prog.n_ue
        assert (out["ok"] + out["drops"] <= out["new_tbs"] + out["retx"]).all()
        assert (out["new_tbs"] >= out["ok"] + out["drops"] - slack).all()

    def test_every_ue_served_under_pf(self):
        import jax

        prog = _toy_prog(n_ttis=400)
        out = run_lte_sm(prog, jax.random.PRNGKey(1))
        assert (out["rx_bits"] > 0).all()

    def test_rr_time_shares_equal(self):
        import jax

        prog = _toy_prog(n_ttis=900, scheduler="rr")
        out = run_lte_sm(prog, jax.random.PRNGKey(2))
        # 3 UEs per cell, 900 TTIs: each UE wins ~300 TTIs
        tbs = out["new_tbs"] + out["retx"]
        assert tbs.min() > 250 and tbs.max() < 350

    def test_rx_bits_exact_past_int32(self):
        import jax

        # one UE hogging 100 RB at peak MCS: ~66k bits/TTI; 40k TTIs
        # crosses 2^31 bits — the lo/hi accounting must stay exact
        prog = LteSmProgram(
            gain=np.array([[1e-7]]),
            serving=np.zeros(1, np.int32),
            tx_power_dbm=np.array([46.0]),
            noise_psd=10.0 ** 0.9 * 1.380649e-23 * 290.0,
            n_rb=100,
            n_ttis=40_000,
            scheduler="pf",
        )
        out = run_lte_sm(prog, jax.random.PRNGKey(3))
        total = int(out["rx_bits"][0])
        assert total > 2**31
        # exact multiple of the (single, static) TB size
        from tpudes.ops.lte import tbs_bits_py

        consts_tb = None
        for mcs in range(29):
            tb = tbs_bits_py(mcs, 100)
            if total % max(tb, 1) == 0 and total // max(tb, 1) == int(
                out["ok"][0]
            ):
                consts_tb = tb
                break
        assert consts_tb is not None

    def test_replica_axis_vmap(self):
        import jax

        prog = _toy_prog(n_ttis=200)
        out = run_lte_sm(prog, jax.random.PRNGKey(4), replicas=4)
        assert out["rx_bits"].shape == (4, prog.n_ue)
        # replicas see different decode draws but identical physics:
        # totals agree within Monte-Carlo noise
        totals = out["rx_bits"].sum(axis=1).astype(float)
        assert totals.std() / totals.mean() < 0.1
        # not all byte-identical
        assert len({int(t) for t in totals}) > 1 or totals.std() == 0

    def test_replica_axis_mesh_sharded_matches_vmap(self):
        import jax

        if len(jax.devices()) < 4:
            pytest.skip("needs the virtual multi-device mesh")
        from tpudes.parallel.mesh import replica_mesh

        prog = _toy_prog(n_ttis=150)
        mesh = replica_mesh(4)
        plain = run_lte_sm(prog, jax.random.PRNGKey(5), replicas=8)
        shard = run_lte_sm(prog, jax.random.PRNGKey(5), replicas=8, mesh=mesh)
        # sharding the replica axis must not change the computation
        np.testing.assert_array_equal(plain["rx_bits"], shard["rx_bits"])
        np.testing.assert_array_equal(plain["ok"], shard["ok"])


class TestHostDeviceParity:
    def test_sm_engine_matches_host_controller(self):
        """The device engine and the host TTI loop run the SAME lowered
        scenario; aggregate and per-cell DL throughput must agree within
        Monte-Carlo + timing-model tolerance (the deviations documented
        in the lte_sm module docstring, all bounded)."""
        import jax

        from tpudes.core.nstime import Seconds
        from tpudes.core.simulator import Simulator

        sim_time = 0.4
        lte, _ = _build_helper_scenario(n_enbs=2, ues_per_cell=3)
        prog = lower_lte_sm(lte, sim_time)

        # host engine
        Simulator.Stop(Seconds(sim_time))
        Simulator.Run()
        stats = lte.GetRlcStats()
        host_bits = sum(s["dl_rx_bytes"] for s in stats) * 8
        host_cell = {}
        for s in stats:
            host_cell[s["cell_id"]] = (
                host_cell.get(s["cell_id"], 0) + s["dl_rx_bytes"] * 8
            )

        # device engine, same program
        out = run_lte_sm(prog, jax.random.PRNGKey(11))
        dev_bits = int(out["rx_bits"].sum())
        dev_cell = {}
        cell_ids = [e.GetCellId() for e in lte.controller.enbs]
        for u in range(prog.n_ue):
            c = cell_ids[int(prog.serving[u])]
            dev_cell[c] = dev_cell.get(c, 0) + int(out["rx_bits"][u])

        assert dev_bits == pytest.approx(host_bits, rel=0.15)
        for c in host_cell:
            assert dev_cell[c] == pytest.approx(host_cell[c], rel=0.2)

    def test_sm_engine_cqi_matches_host(self):
        """Static full-buffer geometry: the device engine's precomputed
        CQI equals the host controller's steady-state applied CQI."""
        import jax

        from tpudes.core.nstime import Seconds
        from tpudes.core.simulator import Simulator

        lte, _ = _build_helper_scenario(n_enbs=2, ues_per_cell=3)
        prog = lower_lte_sm(lte, 0.02)
        out = run_lte_sm(prog, jax.random.PRNGKey(0))
        Simulator.Stop(Seconds(0.02))
        Simulator.Run()
        host_cqi = np.asarray(lte.controller._cqi_dl)
        np.testing.assert_array_equal(out["cqi"], host_cqi)
