"""Host-mirror parity for the device traffic stage (ISSUE-14).

The documented bands: EXACT for trace replay (an empirical trace —
here, the send times of a REAL host DES application — shipped as
operand tables must replay event for event), distribution-band for
the generative models (the host apps draw from the seeded MRG32k3a
streams, the device tables from fold_in-keyed threefry — same
distributions, different realizations, so parity is statistical like
the PHY coin flips).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudes.traffic import TrafficProgram, bounded_pareto_mean
from tpudes.traffic.host import arrival_times, offered_packets


def _host_app_run(app_ctor, sim_s, run=1):
    """Build a 2-node p2p graph, run ``app_ctor(remote)`` on node 0
    for ``sim_s``, return the app's Tx timestamps (µs ints)."""
    from tpudes.core import Seconds, Simulator
    from tpudes.core.rng import RngSeedManager
    from tpudes.core.world import reset_world
    from tpudes.helper.containers import NodeContainer
    from tpudes.helper.internet import (
        InternetStackHelper,
        Ipv4AddressHelper,
    )
    from tpudes.helper.point_to_point import PointToPointHelper
    from tpudes.models.applications import UdpServer
    from tpudes.network.address import InetSocketAddress

    reset_world()
    try:
        RngSeedManager.SetRun(run)
        nodes = NodeContainer()
        nodes.Create(2)
        p2p = PointToPointHelper()
        p2p.SetDeviceAttribute("DataRate", "100Mbps")
        p2p.SetChannelAttribute("Delay", "1ms")
        devs = p2p.Install(nodes)
        stack = InternetStackHelper()
        stack.Install(nodes)
        addr = Ipv4AddressHelper()
        addr.SetBase("10.0.0.0", "255.255.255.0")
        ifs = addr.Assign(devs)
        srv = UdpServer(Port=9)
        nodes.Get(1).AddApplication(srv)
        srv.SetStartTime(Seconds(0))
        app = app_ctor(InetSocketAddress(ifs.GetAddress(1), 9))
        nodes.Get(0).AddApplication(app)
        app.SetStartTime(Seconds(0.0))
        app.SetStopTime(Seconds(sim_s))
        times: list[int] = []
        app.TraceConnectWithoutContext(
            "Tx", lambda p: times.append(Simulator.Now().ticks // 1000)
        )
        Simulator.Stop(Seconds(sim_s + 0.05))
        Simulator.Run()
        Simulator.Destroy()
        return times
    finally:
        reset_world()


def test_trace_replay_of_host_onoff_app_is_exact():
    """The intended workflow end to end: a REAL host application's
    send times become a compressed trace table, and the device stage
    replays them EXACTLY (cumulative counts at every probe time, and
    the walked gap chain reproduces the event list)."""
    from tpudes.core.rng import (
        ConstantRandomVariable,
        ExponentialRandomVariable,
    )
    from tpudes.models.applications import OnOffApplication

    times = _host_app_run(
        lambda remote: OnOffApplication(
            Remote=remote, DataRate="100kbps", PacketSize=500,
            OnTime=ConstantRandomVariable(Constant=0.08),
            OffTime=ExponentialRandomVariable(Mean=0.12),
        ),
        sim_s=2.0,
    )
    assert len(times) > 10
    prog = TrafficProgram.trace_replay(
        np.asarray(times, np.int64)[None, :]
    )
    # host mirror replays exactly
    assert arrival_times(prog, 0, 2_100_000) == times
    # device kernels replay exactly: cumulative count at arbitrary
    # probes, and the gap chain walks the event list
    from tpudes.traffic.device import build_cum_fn, build_gap_fn

    cum = build_cum_fn(prog)
    ops = prog.operands()
    for probe in (0, times[3] - 1, times[3], times[-1], 2_100_000):
        want = sum(1 for v in times if v <= probe)
        assert int(np.asarray(cum(ops, jnp.int32(probe)))[0]) == want
    gap = build_gap_fn(prog)
    key = jax.random.PRNGKey(0)
    walked, t = [], times[0]
    while len(walked) < len(times):
        walked.append(t)
        g = int(
            np.asarray(gap(ops, key, jnp.full((1,), t, jnp.int32)))[0]
        )
        if g >= 2**29:
            break
        t += g
    assert walked == times


def test_host_onoff_app_vs_device_onoff_model_band():
    """Distribution band: the host OnOffApplication (Pareto ON /
    exponential OFF, seeded MRG32k3a) vs the device onoff model with
    the SAME distribution parameters (fold_in tables) — mean offered
    packets over the horizon agree within the documented ±35% band
    (independent realizations of a bursty process at a ~50-cycle
    horizon)."""
    from tpudes.core.rng import (
        ExponentialRandomVariable,
        ParetoRandomVariable,
    )
    from tpudes.models.applications import OnOffApplication

    sim_s = 6.0
    peak_pps = 25.0  # 100 kbps at 500 B
    on = (1.5, 0.05, 0.5)
    off_mean = 0.1
    host_counts = [
        len(
            _host_app_run(
                lambda remote: OnOffApplication(
                    Remote=remote, DataRate="100kbps", PacketSize=500,
                    OnTime=ParetoRandomVariable(
                        Scale=on[1], Shape=on[0], Bound=on[2]
                    ),
                    OffTime=ExponentialRandomVariable(Mean=off_mean),
                ),
                sim_s=sim_s, run=r,
            )
        )
        for r in (1, 2, 3)
    ]
    dev_counts = [
        float(
            np.floor(
                offered_packets(
                    TrafficProgram.onoff(
                        1, peak_pps, horizon_us=int(sim_s * 1e6),
                        on=on, off_mean_s=off_mean, tr_seed=s,
                    ),
                    int(sim_s * 1e6),
                )
            )[0]
        )
        for s in (1, 2, 3)
    ]
    h, d = np.mean(host_counts), np.mean(dev_counts)
    assert abs(h - d) <= 0.35 * max(h, d), (host_counts, dev_counts)


def test_ppbp_app_vs_device_mean_rate_band():
    """The PPBP host generator (Poisson bursts, Pareto lengths,
    overlap-summing) against the device onoff model's mean-rate
    accounting: long-run offered rate within a ±40% band of the
    analytic PPBP mean (burst_rate × arrival_rate × mean_burst_len) —
    the gross-divergence detector for the host mirror itself."""
    from tpudes.core.rng import ParetoRandomVariable
    from tpudes.models.applications import PPBPApplication

    sim_s = 8.0
    counts = [
        len(
            _host_app_run(
                lambda remote: PPBPApplication(
                    Remote=remote, BurstRate="100kbps", PacketSize=500,
                    MeanBurstArrivals=2.0,
                    BurstLength=ParetoRandomVariable(
                        Scale=0.1, Shape=1.5, Bound=1.0
                    ),
                ),
                sim_s=sim_s, run=r,
            )
        )
        for r in (1, 2)
    ]
    peak_pps = 25.0
    mean_len = bounded_pareto_mean(1.5, 0.1, 1.0)
    analytic = peak_pps * 2.0 * mean_len * sim_s
    h = np.mean(counts)
    assert abs(h - analytic) <= 0.4 * max(h, analytic), (
        counts, analytic,
    )


def test_bss_cbr_workload_matches_host_echo_scenario():
    """The engine-level anchor restated at fuzz scale: the BSS engine
    driven by the cbr WORKLOAD program reproduces the legacy path the
    host-parity suite already pins — so the whole host-parity story
    transfers to the traffic stage through bit-equality."""
    from tpudes.parallel.programs import toy_bss_program
    from tpudes.parallel.replicated import run_replicated_bss

    prog = toy_bss_program(n_sta=3, sim_end_us=200_000)
    key = jax.random.PRNGKey(5)
    base = run_replicated_bss(prog, 3, key)
    tp = TrafficProgram.cbr(prog.start_us, prog.interval_us)
    out = run_replicated_bss(
        dataclasses.replace(prog, traffic=tp), 3, key
    )
    for f in ("srv_rx", "cli_rx", "tx_data", "drops"):
        np.testing.assert_array_equal(
            np.asarray(base[f]), np.asarray(out[f])
        )


@pytest.mark.parametrize("model", ["mmpp", "onoff"])
def test_device_generative_models_hit_their_nominal_rate(model):
    """Self-consistency of the fluid accounting: each generative
    model's realized offered count over a long horizon lands within
    ±30% of rate_pps × horizon (the envelope the fuzz rates are
    budgeted against)."""
    h = 4_000_000
    if model == "mmpp":
        p = TrafficProgram.mmpp(
            2, 50.0, horizon_us=h, epoch_s=0.05, tr_seed=7
        )
    else:
        p = TrafficProgram.onoff(
            2, 50.0 / 0.4, horizon_us=h, on=(1.5, 0.05, 0.5),
            off_mean_s=0.15, tr_seed=7,
        )
    got = offered_packets(p, h)
    want = p.rate_pps.astype(np.float64) * h * 1e-6
    assert (np.abs(got - want) <= 0.3 * want + 5).all(), (got, want)
