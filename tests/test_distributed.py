"""Space-parallel PDES tests (SURVEY.md §2.3/§3.3).

The upstream yardstick (src/mpi/test, simple-distributed examples): a
partitioned run must reproduce the sequential run's results exactly —
same packets, same simulated timestamps — because the conservative
grant never lets a rank outrun a message that could still reach it.
"""

import pytest

import _distributed_targets as targets

from tpudes.parallel.mpi import INF_TS, LaunchDistributed, MpiInterface


def test_sequential_oracle_runs():
    out = targets.run_chain(0, 1)
    assert len(out["server_rx"]) == 5
    assert len(out["client_rx"]) == 5
    assert all(size == 333 for _, size in out["server_rx"])


def test_two_rank_run_reproduces_sequential_traces_exactly():
    seq = targets.run_chain(0, 1)
    ranks = LaunchDistributed(targets.run_chain, 2)
    # rank 0 owns the client, rank 1 the server
    assert ranks[1]["server_rx"] == seq["server_rx"]
    assert ranks[0]["client_rx"] == seq["client_rx"]
    assert ranks[0]["server_rx"] == [] and ranks[1]["client_rx"] == []
    # both ranks actually ran granted windows
    assert ranks[0]["windows"] > 1 and ranks[1]["windows"] > 1


def test_null_message_engine_reproduces_sequential_traces():
    """The CMB engine must match the sequential oracle exactly, like
    the granted-window engine — but without any global barrier."""
    seq = targets.run_chain(0, 1)
    ranks = LaunchDistributed(
        targets.run_chain, 2,
        args=(5, 0.1, "tpudes::NullMessageSimulatorImpl"),
    )
    assert ranks[1]["server_rx"] == seq["server_rx"]
    assert ranks[0]["client_rx"] == seq["client_rx"]
    assert ranks[0]["nulls"] > 0 and ranks[1]["nulls"] > 0
    # no granted windows — the null-message loop doesn't use them
    assert ranks[0]["windows"] == 0


def test_three_rank_chain_delivers():
    ranks = LaunchDistributed(targets.run_chain_three_ranks, 3)
    assert len(ranks[2]["server_rx"]) == 3
    assert ranks[0]["server_rx"] == [] and ranks[1]["server_rx"] == []


def test_asymmetric_stop_closes_out_cleanly():
    """An immediate rank-local Simulator.Stop() must not strand peers
    in the collective (r4 review: EOFError / 120 s hang)."""
    ranks = LaunchDistributed(targets.run_asymmetric_stop, 2, timeout_s=60)
    assert ranks[1]["server_rx"] == 3


def test_bursty_window_exceeding_pipe_buffer_does_not_deadlock():
    """300 x 512B messages in one granted window ≫ the OS pipe buffer;
    the spooled threaded flush must drain it (r4 review)."""
    ranks = LaunchDistributed(targets.run_bursty_window, 2, timeout_s=60)
    assert ranks[1]["rx"] == 300
    # tpudes must not drag its jax-heavy engine modules into the ranks
    assert not ranks[0]["heavy_loaded"] and not ranks[1]["heavy_loaded"]


def test_zero_delay_remote_link_is_rejected():
    MpiInterface._enabled = True  # simulate an enabled rank
    try:
        with pytest.raises(ValueError, match="positive delay"):
            MpiInterface.RegisterLookahead(0)
    finally:
        MpiInterface._enabled = False


def test_lookahead_registry_tracks_minimum():
    MpiInterface._enabled = True
    try:
        MpiInterface._lookahead_ts = INF_TS
        MpiInterface.RegisterLookahead(5_000_000)
        MpiInterface.RegisterLookahead(2_000_000)
        MpiInterface.RegisterLookahead(9_000_000)
        assert MpiInterface.MinLookahead() == 2_000_000
    finally:
        MpiInterface._enabled = False
        MpiInterface._lookahead_ts = INF_TS
