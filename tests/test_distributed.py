"""Space-parallel PDES tests (SURVEY.md §2.3/§3.3).

The upstream yardstick (src/mpi/test, simple-distributed examples): a
partitioned run must reproduce the sequential run's results exactly —
same packets, same simulated timestamps — because the conservative
grant never lets a rank outrun a message that could still reach it.
"""

import pytest

import _distributed_targets as targets

from tpudes.parallel.mpi import INF_TS, LaunchDistributed, MpiInterface


def test_sequential_oracle_runs():
    out = targets.run_chain(0, 1)
    assert len(out["server_rx"]) == 5
    assert len(out["client_rx"]) == 5
    assert all(size == 333 for _, size in out["server_rx"])


def test_two_rank_run_reproduces_sequential_traces_exactly():
    seq = targets.run_chain(0, 1)
    ranks = LaunchDistributed(targets.run_chain, 2)
    # rank 0 owns the client, rank 1 the server
    assert ranks[1]["server_rx"] == seq["server_rx"]
    assert ranks[0]["client_rx"] == seq["client_rx"]
    assert ranks[0]["server_rx"] == [] and ranks[1]["client_rx"] == []
    # both ranks actually ran granted windows
    assert ranks[0]["windows"] > 1 and ranks[1]["windows"] > 1


def test_null_message_engine_reproduces_sequential_traces():
    """The CMB engine must match the sequential oracle exactly, like
    the granted-window engine — but without any global barrier."""
    seq = targets.run_chain(0, 1)
    ranks = LaunchDistributed(
        targets.run_chain, 2,
        args=(5, 0.1, "tpudes::NullMessageSimulatorImpl"),
    )
    assert ranks[1]["server_rx"] == seq["server_rx"]
    assert ranks[0]["client_rx"] == seq["client_rx"]
    assert ranks[0]["nulls"] > 0 and ranks[1]["nulls"] > 0
    # no granted windows — the null-message loop doesn't use them
    assert ranks[0]["windows"] == 0


def test_three_rank_chain_delivers():
    ranks = LaunchDistributed(targets.run_chain_three_ranks, 3)
    assert len(ranks[2]["server_rx"]) == 3
    assert ranks[0]["server_rx"] == [] and ranks[1]["server_rx"] == []


def test_asymmetric_stop_closes_out_cleanly():
    """An immediate rank-local Simulator.Stop() must not strand peers
    in the collective (r4 review: EOFError / 120 s hang)."""
    ranks = LaunchDistributed(targets.run_asymmetric_stop, 2, timeout_s=60)
    assert ranks[1]["server_rx"] == 3


def test_bursty_window_exceeding_pipe_buffer_does_not_deadlock():
    """300 x 512B messages in one granted window ≫ the OS pipe buffer;
    the spooled threaded flush must drain it (r4 review)."""
    ranks = LaunchDistributed(targets.run_bursty_window, 2, timeout_s=60)
    assert ranks[1]["rx"] == 300
    # tpudes must not drag its jax-heavy engine modules into the ranks
    assert not ranks[0]["heavy_loaded"] and not ranks[1]["heavy_loaded"]


def test_zero_delay_remote_link_is_rejected():
    MpiInterface._enabled = True  # simulate an enabled rank
    try:
        with pytest.raises(ValueError, match="positive delay"):
            MpiInterface.RegisterLookahead(0)
    finally:
        MpiInterface._enabled = False


def test_lookahead_registry_tracks_minimum():
    MpiInterface._enabled = True
    try:
        MpiInterface._lookahead_ts = INF_TS
        MpiInterface.RegisterLookahead(5_000_000)
        MpiInterface.RegisterLookahead(2_000_000)
        MpiInterface.RegisterLookahead(9_000_000)
        assert MpiInterface.MinLookahead() == 2_000_000
    finally:
        MpiInterface._enabled = False
        MpiInterface._lookahead_ts = INF_TS


# --- ISSUE-9 satellites: lookahead validation + framed wire format --------


def test_zero_delay_error_names_the_offending_channel():
    """Satellite: the Enable-time validation must name the channel so
    a degenerate grant is debuggable from the message alone."""
    MpiInterface._enabled = True
    try:
        with pytest.raises(ValueError, match="myChannel.*degenerates"):
            MpiInterface.RegisterLookahead(0, source="myChannel")
        with pytest.raises(ValueError, match="-3 ticks"):
            MpiInterface.RegisterLookahead(-3, source="neg")
    finally:
        MpiInterface._enabled = False


def test_remote_channel_registration_carries_source():
    """PointToPointRemoteChannel registers its delay with a named
    source, so a zero Delay attribute fails with the channel named."""
    from tpudes.core import Seconds
    from tpudes.models.p2p import PointToPointRemoteChannel

    MpiInterface._enabled = True
    MpiInterface._size = 2
    try:
        with pytest.raises(
            ValueError, match="PointToPointRemoteChannel.*degenerates"
        ):
            PointToPointRemoteChannel(delay=Seconds(0))
    finally:
        MpiInterface._enabled = False
        MpiInterface._size = 1
        MpiInterface._lookahead_ts = INF_TS


def test_run_requires_a_registered_lookahead():
    """Satellite regression: a >1-rank engine with NOTHING registered
    must fail loudly at Run start, not spin a degenerate grant."""
    from tpudes.parallel.distributed import DistributedSimulatorImpl

    MpiInterface._enabled = True
    MpiInterface._size = 2
    MpiInterface._rank = 0
    MpiInterface._lookahead_ts = INF_TS
    try:
        impl = DistributedSimulatorImpl()
        with pytest.raises(RuntimeError, match="no remote channel"):
            impl._require_lookahead()
    finally:
        MpiInterface._enabled = False
        MpiInterface._size = 1
        MpiInterface._rank = 0
        MpiInterface._lookahead_ts = INF_TS


def test_wire_frame_roundtrip():
    from tpudes.parallel.mpi import pack_frame, unpack_frame

    msg = ("pkt", 123456, 7, 0, {"payload": list(range(10))})
    assert unpack_frame(pack_frame(msg)) == msg


def test_wire_frame_truncation_raises_before_unpickling():
    from tpudes.parallel.mpi import (
        WireFormatError,
        pack_frame,
        unpack_frame,
    )

    frame = pack_frame(("lbts", 42))
    # a partial pipe read (any strict prefix) must raise, never
    # reach the unpickler with garbage
    for cut in (0, 1, 4, len(frame) - 1):
        with pytest.raises(WireFormatError, match="truncated|mismatch"):
            unpack_frame(frame[:cut])
    # trailing garbage = length mismatch
    with pytest.raises(WireFormatError, match="mismatch"):
        unpack_frame(frame + b"\x00")


def test_wire_frame_version_mismatch_raises():
    from tpudes.parallel.mpi import (
        WIRE_VERSION,
        WireFormatError,
        pack_frame,
        unpack_frame,
    )

    frame = pack_frame(("lbts", 42))
    foreign = bytes((WIRE_VERSION + 1,)) + frame[1:]
    with pytest.raises(WireFormatError, match="version"):
        unpack_frame(foreign)


def test_corrupted_frame_raises_not_silently_diverges():
    """Satellite regression: flipping bytes in the length field (the
    partial-read shape) raises rather than desyncing the protocol."""
    from tpudes.parallel.mpi import (
        WireFormatError,
        pack_frame,
        unpack_frame,
    )

    frame = bytearray(pack_frame(("pkt", 99, 1, 0, b"x" * 64)))
    frame[2] ^= 0xFF  # corrupt the declared length
    with pytest.raises(WireFormatError):
        unpack_frame(bytes(frame))
