"""Engine-seam lifting tests: one GlobalValue flip runs a stock
scenario's object graph on the replica axis.

The north-star contract (BASELINE.json): ``SimulatorImplementationType=
tpudes::JaxSimulatorImpl`` + ``JaxReplicas=R`` — no per-example
plumbing.  Unliftable graphs must fall back to the scalar engine with a
loud warning, never a silent mis-lowering.
"""

import math

import numpy as np
import pytest

from tpudes.core import GlobalValue, Seconds, Simulator
from tpudes.helper.applications import UdpEchoClientHelper, UdpEchoServerHelper
from tpudes.helper.containers import NetDeviceContainer, NodeContainer
from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
from tpudes.models.mobility import (
    ListPositionAllocator,
    MobilityHelper,
    Vector,
)
from tpudes.models.wifi import (
    WifiHelper,
    WifiMacHelper,
    YansWifiChannelHelper,
    YansWifiPhyHelper,
)

from tests.test_lte_sm import _build_helper_scenario


def _use_jax_engine(replicas):
    GlobalValue.Bind(
        "SimulatorImplementationType", "tpudes::JaxSimulatorImpl"
    )
    GlobalValue.Bind("JaxReplicas", replicas)


def _build_small_bss(n_stas=4, sim_time=1.5):
    nodes = NodeContainer()
    nodes.Create(n_stas + 1)
    alloc = ListPositionAllocator()
    alloc.Add(Vector(0.0, 0.0, 0.0))
    for i in range(n_stas):
        a = 2 * math.pi * i / n_stas
        alloc.Add(Vector(20.0 * math.cos(a), 20.0 * math.sin(a), 0.0))
    mob = MobilityHelper()
    mob.SetPositionAllocator(alloc)
    mob.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    mob.Install(nodes)
    channel = YansWifiChannelHelper.Default().Create()
    phy = YansWifiPhyHelper()
    phy.SetChannel(channel)
    wifi = WifiHelper()
    wifi.SetRemoteStationManager(
        "tpudes::ConstantRateWifiManager", DataMode="OfdmRate54Mbps"
    )
    ap_mac = WifiMacHelper()
    ap_mac.SetType("tpudes::ApWifiMac")
    ap_devices = wifi.Install(phy, ap_mac, [nodes.Get(0)])
    sta_mac = WifiMacHelper()
    sta_mac.SetType("tpudes::StaWifiMac")
    wifi.Install(phy, sta_mac, [nodes.Get(i) for i in range(1, n_stas + 1)])
    stack = InternetStackHelper()
    stack.Install(nodes)
    address = Ipv4AddressHelper()
    address.SetBase("10.1.3.0", "255.255.255.0")
    devices = NetDeviceContainer()
    for i in range(n_stas + 1):
        devices.Add(nodes.Get(i).GetDevice(0))
    interfaces = address.Assign(devices)
    server = UdpEchoServerHelper(9)
    server_apps = server.Install(nodes.Get(0))
    server_apps.Start(Seconds(0.4))
    server_apps.Stop(Seconds(sim_time))
    rx = [0]
    server_apps.Get(0).TraceConnectWithoutContext(
        "Rx", lambda pkt, *a: rx.__setitem__(0, rx[0] + 1)
    )
    for i in range(n_stas):
        helper = UdpEchoClientHelper(interfaces.GetAddress(0), 9)
        helper.SetAttribute("MaxPackets", 1_000_000)
        helper.SetAttribute("Interval", Seconds(0.1))
        helper.SetAttribute("PacketSize", 512)
        apps = helper.Install(nodes.Get(1 + i))
        apps.Start(Seconds(1.0 + 0.001 * i))
        apps.Stop(Seconds(sim_time))
    return rx


def test_bss_lift_via_engine_seam():
    _use_jax_engine(8)
    rx = _build_small_bss(sim_time=1.5)
    Simulator.Stop(Seconds(1.5))
    Simulator.Run()
    res = Simulator.GetImpl().replicated_result
    assert res is not None and res["kind"] == "bss"
    assert res["replicas"] == 8
    srv = np.asarray(res["out"]["srv_rx"])
    assert srv.shape == (8,)
    assert srv.mean() > 0
    # the scalar event path did NOT run the scenario
    assert rx[0] == 0
    # the clock advanced to the stop horizon
    assert Simulator.Now().GetSeconds() == pytest.approx(1.5)


def test_lte_lift_via_engine_seam():
    _use_jax_engine(4)
    lte, _ = _build_helper_scenario(n_enbs=2, ues_per_cell=2)
    Simulator.Stop(Seconds(0.2))
    Simulator.Run()
    res = Simulator.GetImpl().replicated_result
    assert res is not None and res["kind"] == "lte_sm"
    out = res["out"]
    assert out["rx_bits"].shape == (4, 4)
    assert (out["rx_bits"].sum(axis=1) > 0).all()
    # the host TTI loop did not also run the scenario
    assert lte.controller.stats["ttis"] == 0
    assert lte.controller.lifted


def test_unliftable_graph_falls_back_with_warning():
    # a bare p2p echo slice: no lowering represents it
    from tpudes.helper.applications import (
        UdpEchoClientHelper as Client,
        UdpEchoServerHelper as Server,
    )
    from tpudes.helper.internet import (
        InternetStackHelper as Stack,
        Ipv4AddressHelper as Addr,
    )
    from tpudes.helper.point_to_point import PointToPointHelper

    _use_jax_engine(4)
    nodes = NodeContainer()
    nodes.Create(2)
    p2p = PointToPointHelper()
    p2p.SetDeviceAttribute("DataRate", "5Mbps")
    p2p.SetChannelAttribute("Delay", Seconds(0.002))
    devices = p2p.Install(nodes)
    Stack().Install(nodes)
    addr = Addr()
    addr.SetBase("10.1.1.0", "255.255.255.0")
    interfaces = addr.Assign(devices)
    server_apps = Server(9).Install(nodes.Get(1))
    server_apps.Start(Seconds(1.0))
    server_apps.Stop(Seconds(10.0))
    rx = [0]
    server_apps.Get(0).TraceConnectWithoutContext(
        "Rx", lambda pkt, *a: rx.__setitem__(0, rx[0] + 1)
    )
    client = Client(interfaces.GetAddress(1), 9)
    client.SetAttribute("MaxPackets", 1)
    client.SetAttribute("Interval", Seconds(1.0))
    client.SetAttribute("PacketSize", 1024)
    capps = client.Install(nodes.Get(0))
    capps.Start(Seconds(2.0))
    capps.Stop(Seconds(10.0))
    Simulator.Stop(Seconds(10.0))
    with pytest.warns(UserWarning, match="no lowering"):
        Simulator.Run()
    # the scalar fallback ran the scenario correctly
    assert rx[0] == 1
    assert Simulator.GetImpl().replicated_result is None


def test_lift_without_stop_warns_and_falls_back():
    _use_jax_engine(4)
    fired = [0]
    Simulator.Schedule(Seconds(0.1), lambda: fired.__setitem__(0, 1))
    with pytest.warns(UserWarning, match="Stop"):
        Simulator.Run()
    assert fired[0] == 1


def test_default_engine_ignores_jax_replicas():
    # JaxReplicas without the engine flip is inert: the scalar default
    # engine runs normally
    GlobalValue.Bind("JaxReplicas", 8)
    rx = _build_small_bss(n_stas=2, sim_time=1.3)
    Simulator.Stop(Seconds(1.3))
    Simulator.Run()
    assert rx[0] > 0
    assert not hasattr(Simulator.GetImpl(), "replicated_result")
