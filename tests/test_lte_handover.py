"""RLC-AM, A3-RSRP handover, and EPC remote-host tests.

Upstream analogs: src/lte/test/lte-test-rlc-am-transmitter.cc /
lte-test-rlc-am-e2e.cc (AM delivers under loss), lte-test-handover-*
(X2 handover moves a UE between cells without losing bearers).
"""


from tpudes.core import MilliSeconds, Seconds, Simulator
from tpudes.helper.containers import NodeContainer
from tpudes.models.lte import LteHelper
from tpudes.models.lte.rlc import LteRlcAm, LteRlcUm, make_rlc
from tpudes.models.mobility import (
    ConstantVelocityMobilityModel,
    ListPositionAllocator,
    MobilityHelper,
    Vector,
)
from tpudes.network.packet import Packet


# --- RLC-AM unit level ------------------------------------------------------
def _pump(tx, rx, n_rounds, opportunity=120, drop=lambda i: False):
    """Drive tx→rx for n_rounds opportunities, dropping PDUs on
    ``drop(i)``; Simulator carries the STATUS feedback."""
    sent = 0
    for i in range(n_rounds):
        pdu = tx.NotifyTxOpportunity(opportunity)
        if pdu is not None:
            sent += 1
            if not drop(i):
                rx.ReceivePdu(pdu)
        # let STATUS (2 ms) land between opportunities
        Simulator.Stop(MilliSeconds(5))
        Simulator.Run()
    return sent


def _am_pair():
    tx, rx = make_rlc("am"), make_rlc("am")
    rx.status_callback = tx.ReceiveStatus
    got = []
    rx.rx_sdu_callback = lambda p: got.append(p.GetSize())
    return tx, rx, got


def test_am_delivers_all_sdus_without_loss():
    tx, rx, got = _am_pair()
    for _ in range(10):
        tx.TransmitPdcpPdu(Packet(300))
    _pump(tx, rx, 40)
    assert got == [300] * 10


def test_am_recovers_lost_pdus_where_um_tears():
    drop = lambda i: i % 4 == 1  # noqa: E731 — lose every 4th PDU
    tx, rx, got = _am_pair()
    for _ in range(12):
        tx.TransmitPdcpPdu(Packet(500))
    _pump(tx, rx, 120, drop=drop)
    assert got == [500] * 12, "AM must retransmit across losses"
    assert tx.stats_retx_pdus > 0
    assert tx.stats_dropped_pdus == 0

    # UM under the identical loss pattern tears SDUs
    um_tx, um_rx = LteRlcUm(), LteRlcUm()
    um_got = []
    um_rx.rx_sdu_callback = lambda p: um_got.append(p.GetSize())
    for _ in range(12):
        um_tx.TransmitPdcpPdu(Packet(500))
    for i in range(120):
        pdu = um_tx.NotifyTxOpportunity(120)
        if pdu is not None and not drop(i):
            um_rx.ReceivePdu(pdu)
    assert len(um_got) < 12


def test_am_in_order_delivery_despite_reordering_gap():
    tx, rx, got = _am_pair()
    for size in (200, 300, 400):
        tx.TransmitPdcpPdu(Packet(size))
    p0 = tx.NotifyTxOpportunity(204 + 4)
    p1 = tx.NotifyTxOpportunity(304 + 4)
    p2 = tx.NotifyTxOpportunity(404 + 4)
    rx.ReceivePdu(p0)
    rx.ReceivePdu(p2)          # gap: p1 missing
    assert got == [200], "delivery must stall at the gap"
    rx.ReceivePdu(p1)          # late arrival fills it
    assert got == [200, 300, 400]


def test_am_gives_up_after_max_retx():
    tx, rx, got = _am_pair()
    tx.TransmitPdcpPdu(Packet(100))
    pdu = tx.NotifyTxOpportunity(200)
    assert pdu is not None
    # peer never gets it; NACK it repeatedly with real time between
    # (NACKs inside the suppression window are rightly ignored)
    for _ in range(LteRlcAm.MAX_RETX + 1):
        Simulator.Stop(MilliSeconds(LteRlcAm.NACK_IGNORE_WINDOW_MS + 1))
        Simulator.Run()
        tx.ReceiveStatus(pdu.sn + 1, [pdu.sn])
        tx.NotifyTxOpportunity(200)  # drains the retx queue each time
    assert tx.stats_dropped_pdus == 1
    assert not tx._retx and pdu.sn not in tx._unacked


def test_am_nack_flood_within_window_is_suppressed():
    """Per-PDU STATUS cadence must not burn the retx budget on one real
    loss (r4 review: duplicate NACKs reached MAX_RETX)."""
    tx, rx, got = _am_pair()
    tx.TransmitPdcpPdu(Packet(100))
    pdu = tx.NotifyTxOpportunity(200)
    for _ in range(10):  # flood of NACKs at the same instant
        tx.ReceiveStatus(pdu.sn + 1, [pdu.sn])
    assert tx._retx_count.get(pdu.sn, 0) <= 1
    assert tx.stats_dropped_pdus == 0


def test_am_poll_timer_recovers_lost_tail_pdu():
    """The LAST PDU of a burst is lost: no further data means no STATUS
    from the peer — t-PollRetransmit must resend it (r4 review)."""
    tx, rx, got = _am_pair()
    tx.TransmitPdcpPdu(Packet(300))
    tx.TransmitPdcpPdu(Packet(300))
    p0 = tx.NotifyTxOpportunity(310)
    p1 = tx.NotifyTxOpportunity(310)   # the tail — gets lost
    rx.ReceivePdu(p0)
    # run long enough for poll timeout + retx round trips
    for _ in range(6):
        Simulator.Stop(MilliSeconds(LteRlcAm.POLL_RETRANSMIT_MS + 5))
        Simulator.Run()
        retx = tx.NotifyTxOpportunity(310)
        if retx is not None:
            rx.ReceivePdu(retx)
    assert got == [300, 300], "poll-retransmit must recover the tail"


def test_am_resegments_retx_for_small_opportunities():
    """A big NACKed PDU must split across shrunken opportunities, not
    stall the bearer (r4 review)."""
    tx, rx, got = _am_pair()
    tx.TransmitPdcpPdu(Packet(1200))
    big = tx.NotifyTxOpportunity(1300)   # whole SDU in one PDU — lost
    assert big is not None
    Simulator.Stop(MilliSeconds(LteRlcAm.NACK_IGNORE_WINDOW_MS + 1))
    Simulator.Run()
    tx.ReceiveStatus(big.sn + 1, [big.sn])
    # only 400-byte opportunities from now on
    parts = []
    for _ in range(8):
        p = tx.NotifyTxOpportunity(400)
        if p is not None:
            parts.append(p)
            rx.ReceivePdu(p)
    assert len(parts) >= 3, "retx must re-segment to fit"
    assert got == [1200], "re-segmented SDU must reassemble"


def test_am_overlapping_retx_parts_do_not_corrupt():
    """An original whole PDU AND later re-segmented parts both arrive:
    coverage-based reassembly must deliver the SDU exactly once."""
    tx, rx, got = _am_pair()
    tx.TransmitPdcpPdu(Packet(1000))
    whole = tx.NotifyTxOpportunity(1100)
    Simulator.Stop(MilliSeconds(LteRlcAm.NACK_IGNORE_WINDOW_MS + 1))
    Simulator.Run()
    tx.ReceiveStatus(whole.sn + 1, [whole.sn])  # spurious NACK (raced)
    half = tx.NotifyTxOpportunity(600)          # re-segmented head
    rx.ReceivePdu(half)                         # part arrives first
    rx.ReceivePdu(whole)                        # then the stale whole
    assert got == [1000]
    assert rx.stats_rx_pdus == 2


def test_am_buffer_reports_retx_backlog():
    tx, rx, got = _am_pair()
    tx.TransmitPdcpPdu(Packet(100))
    pdu = tx.NotifyTxOpportunity(200)
    assert tx.BufferBytes() == 0
    Simulator.Stop(MilliSeconds(LteRlcAm.NACK_IGNORE_WINDOW_MS + 1))
    Simulator.Run()
    tx.ReceiveStatus(pdu.sn + 1, [pdu.sn])
    assert tx.BufferBytes() >= pdu.size_bytes


# --- A3 handover + X2-lite --------------------------------------------------
def _two_cell_moving_ue(rlc_mode="am", start_x=220.0, speed=100.0, ttt=160):
    lte = LteHelper()
    enbs = NodeContainer()
    enbs.Create(2)
    ues = NodeContainer()
    ues.Create(1)
    ea = ListPositionAllocator()
    ea.Add(Vector(0, 0, 30.0))
    ea.Add(Vector(500, 0, 30.0))
    me = MobilityHelper()
    me.SetPositionAllocator(ea)
    me.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    me.Install(enbs)
    ua = ListPositionAllocator()
    ua.Add(Vector(start_x, 0, 1.5))
    mu = MobilityHelper()
    mu.SetPositionAllocator(ua)
    mu.SetMobilityModel("tpudes::ConstantVelocityMobilityModel")
    mu.Install(ues)
    ues.Get(0).GetObject(ConstantVelocityMobilityModel).SetVelocity(
        Vector(speed, 0.0, 0.0)
    )
    enb_devs = lte.InstallEnbDevice(enbs)
    ue_devs = lte.InstallUeDevice(ues)
    lte.Attach([ue_devs.Get(0)])
    lte.ActivateDataRadioBearer([ue_devs.Get(0)], mode=rlc_mode)
    lte.SetHandoverAlgorithmType("tpudes::A3RsrpHandoverAlgorithm")
    lte.SetHandoverAlgorithmAttribute("TimeToTrigger", ttt)
    lte.AddX2Interface(enbs)
    return lte, enb_devs, ue_devs


def test_a3_handover_moves_ue_between_cells():
    lte, enb_devs, ue_devs = _two_cell_moving_ue(rlc_mode="sm")
    assert ue_devs.Get(0).rrc.serving_enb is enb_devs.Get(0)
    Simulator.Stop(Seconds(1.5))
    Simulator.Run()
    c = lte.controller
    assert c.stats["handovers"] == 1
    assert ue_devs.Get(0).rrc.serving_enb is enb_devs.Get(1)
    tti, imsi, src, dst = c.handover_log[0]
    assert (src, dst) == (enb_devs.Get(0).GetCellId(), enb_devs.Get(1).GetCellId())
    # A3 geometry: Friis + 3 dB hysteresis crosses at ~293 m, + TTT;
    # the UE (220 m + 100 m/s) must hand over in roughly [730, 1100] ms
    assert 700 <= tti <= 1200, tti
    # traffic continues at the target cell after the move
    assert c.stats["dl_ok"] > tti * 0.8


def test_handover_is_lossless_for_am_bearers():
    lte, enb_devs, ue_devs = _two_cell_moving_ue(rlc_mode="am")
    bearer = next(iter(ue_devs.Get(0).rrc.bearers.values()))
    got = []
    bearer.dl_rx.rx_sdu_callback = lambda p: got.append(p.GetSize())
    n_fed = [0]

    def feed():
        bearer.dl_pdcp.TransmitSdu(Packet(600))
        n_fed[0] += 1
        if n_fed[0] < 140:
            Simulator.Schedule(MilliSeconds(10), feed)

    feed()
    Simulator.Stop(Seconds(1.5))
    Simulator.Run()
    assert lte.controller.stats["handovers"] == 1
    assert len(got) == n_fed[0], "AM + X2-lite must lose no SDUs"


def test_no_x2_means_no_handover():
    lte, enb_devs, ue_devs = _two_cell_moving_ue(rlc_mode="sm")
    lte.controller.x2_enabled = False
    Simulator.Stop(Seconds(1.2))
    Simulator.Run()
    assert lte.controller.stats["handovers"] == 0
    assert ue_devs.Get(0).rrc.serving_enb is enb_devs.Get(0)


def test_hysteresis_blocks_marginal_neighbors():
    # UE sits just past midpoint (260 m): best cell differs from serving
    # but by < 3 dB, so A3 must never fire
    lte, enb_devs, ue_devs = _two_cell_moving_ue(
        rlc_mode="sm", start_x=260.0, speed=0.001
    )
    Simulator.Stop(Seconds(1.0))
    Simulator.Run()
    assert lte.controller.stats["handovers"] == 0


def test_a3_pending_entries_expire_when_measurements_stop():
    """Promoted EVT003 regression: a (ue, target) entry whose UE stops
    being measured (detach / controller teardown) must be swept by the
    algorithm's scheduled expiry instead of leaking forever.  A live A3
    condition is re-confirmed every measurement period, so only
    abandoned entries can age past the lapse window."""
    from tpudes.models.lte.handover import A3RsrpHandoverAlgorithm

    algo = A3RsrpHandoverAlgorithm(TimeToTrigger=256)
    # enter the pending dict at t=0: neighbour 5 dB above serving
    assert algo.evaluate(0, 0, 0, [10.0, 15.0]) is None
    assert (0, 1) in algo._entered
    # the UE vanishes (no further evaluate calls) — run past the lapse
    Simulator.Stop(MilliSeconds(4 * (256 + 80)))
    Simulator.Run()
    assert algo._entered == {}


def test_a3_sweep_keeps_live_entries():
    """The expiry sweep must NOT touch an entry that keeps being
    re-confirmed every measurement period (the sweep fires mid-run,
    between confirmations, and must leave the live entry alone)."""
    from tpudes.models.lte.handover import (
        MEASUREMENT_PERIOD_TTIS,
        A3RsrpHandoverAlgorithm,
    )

    algo = A3RsrpHandoverAlgorithm(TimeToTrigger=1000)
    row = [10.0, 15.0]
    for t in range(0, 2001, MEASUREMENT_PERIOD_TTIS):
        Simulator.Schedule(
            MilliSeconds(t), lambda t=t: algo.evaluate(t, 0, 0, row)
        )
    # the sweep (lapse = 2 periods + TTT = 1080 ms) fires at least once
    # inside this horizon while confirmations keep arriving
    Simulator.Stop(MilliSeconds(2001))
    Simulator.Run()
    assert (0, 1) in algo._entered
    assert algo._entered[(0, 1)][1] == 2000


# --- EPC with a true remote host -------------------------------------------
def test_remote_host_traffic_through_backhaul_and_pgw():
    """lena-simple-epc shape: remote host → p2p backhaul → PGW → DL
    bearer → UE, and the uplink back out to the remote host."""
    from tpudes.helper.applications import UdpClientHelper, UdpServerHelper
    from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
    from tpudes.helper.point_to_point import PointToPointHelper
    from tpudes.models.internet.ipv4 import Ipv4L3Protocol, Ipv4StaticRouting
    from tpudes.models.lte.epc import EpcHelper
    from tpudes.network.address import Ipv4Address, Ipv4Mask

    lte = LteHelper()
    epc = EpcHelper()
    remote = NodeContainer()
    remote.Create(1)
    InternetStackHelper().Install(remote)
    p2p = PointToPointHelper()
    p2p.SetDeviceAttribute("DataRate", "1Gbps")
    p2p.SetChannelAttribute("Delay", "5ms")
    backhaul = p2p.Install(remote.Get(0), epc.GetPgwNode())
    ifc = Ipv4AddressHelper("1.0.0.0", "255.0.0.0").Assign(backhaul)
    routing = remote.Get(0).GetObject(Ipv4L3Protocol).GetRoutingProtocol()
    assert isinstance(routing, Ipv4StaticRouting)
    routing.AddNetworkRouteTo(
        Ipv4Address(EpcHelper.UE_NETWORK), Ipv4Mask(EpcHelper.UE_MASK),
        remote.Get(0).GetObject(Ipv4L3Protocol).GetInterfaceForDevice(
            backhaul.Get(0)
        ),
        gateway=ifc.GetAddress(1),
    )

    enbs = NodeContainer()
    enbs.Create(1)
    ues = NodeContainer()
    ues.Create(1)
    ea = ListPositionAllocator()
    ea.Add(Vector(0, 0, 30.0))
    me = MobilityHelper()
    me.SetPositionAllocator(ea)
    me.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    me.Install(enbs)
    ua = ListPositionAllocator()
    ua.Add(Vector(70.0, 0, 1.5))
    mu = MobilityHelper()
    mu.SetPositionAllocator(ua)
    mu.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    mu.Install(ues)
    lte.InstallEnbDevice(enbs)
    ue_devs = lte.InstallUeDevice(ues)
    InternetStackHelper().Install(ues)
    lte.Attach([ue_devs.Get(0)])
    lte.ActivateDataRadioBearer([ue_devs.Get(0)], mode="um")
    (ue_addr,) = epc.AssignUeIpv4Address([ue_devs.Get(0)])

    dl_rx = [0]
    server = UdpServerHelper(1000)
    sapps = server.Install(ues.Get(0))
    sapps.Start(Seconds(0.0))
    sapps.Get(0).TraceConnectWithoutContext(
        "Rx", lambda pkt, *a: dl_rx.__setitem__(0, dl_rx[0] + 1)
    )
    dl = UdpClientHelper(ue_addr, 1000)
    dl.SetAttribute("MaxPackets", 8)
    dl.SetAttribute("Interval", Seconds(0.02))
    dl.SetAttribute("PacketSize", 300)
    dl.Install(remote.Get(0)).Start(Seconds(0.01))

    ul_server = UdpServerHelper(2000)
    ul_apps = ul_server.Install(remote.Get(0))
    ul_apps.Start(Seconds(0.0))
    ul = UdpClientHelper(ifc.GetAddress(0), 2000)
    ul.SetAttribute("MaxPackets", 6)
    ul.SetAttribute("Interval", Seconds(0.02))
    ul.SetAttribute("PacketSize", 150)
    ul.Install(ues.Get(0)).Start(Seconds(0.02))

    Simulator.Stop(Seconds(0.5))
    Simulator.Run()
    assert dl_rx[0] == 8, "all DL packets must reach the UE app"
    assert ul_apps.Get(0).received == 6, "all UL packets must reach the remote host"


# --- eNB RRC stranded-context sweep ----------------------------------------


def test_stranded_context_reclaimed_after_reattach_elsewhere():
    """Promoted EVT003 regression (LteEnbRrc.ues): a UE that re-attaches
    to another cell OUTSIDE the handover remove_ue path must have its
    old eNB-side UeContext reclaimed by the scheduled stranded-context
    sweep instead of leaking forever."""
    from tpudes.models.lte.device import (
        LteEnbNetDevice,
        LteEnbRrc,
        LteUeNetDevice,
    )

    src, dst = LteEnbNetDevice(), LteEnbNetDevice()
    ue = LteUeNetDevice()
    ctx = src.rrc.add_ue(ue)
    ue.rrc.connect(src, ctx.rnti)
    # raw re-attach: no remove_ue on the old cell
    ctx2 = dst.rrc.add_ue(ue)
    ue.rrc.connect(dst, ctx2.rnti)
    assert len(src.rrc.ues) == 1, "stranded until the sweep fires"
    Simulator.Stop(MilliSeconds(2 * LteEnbRrc.STRANDED_UE_LAPSE_MS))
    Simulator.Run()
    assert src.rrc.ues == {}
    assert list(dst.rrc.ues) == [ctx2.rnti]


def test_disconnect_releases_enb_context_after_lapse():
    """LteUeRrc.disconnect (RRC release) leaves the eNB context to the
    lapse sweep — reclaimed, but only after the grace window."""
    from tpudes.models.lte.device import (
        LteEnbNetDevice,
        LteEnbRrc,
        LteUeNetDevice,
        LteUeRrc,
    )

    enb = LteEnbNetDevice()
    ue = LteUeNetDevice()
    ctx = enb.rrc.add_ue(ue)
    ue.rrc.connect(enb, ctx.rnti)
    ue.rrc.disconnect()
    assert ue.rrc.state == LteUeRrc.IDLE
    assert len(enb.rrc.ues) == 1, "grace window: not reclaimed inline"
    Simulator.Stop(MilliSeconds(2 * LteEnbRrc.STRANDED_UE_LAPSE_MS))
    Simulator.Run()
    assert enb.rrc.ues == {}


def test_sweep_keeps_claimed_contexts():
    """The sweep armed by one UE's departure must not touch a context
    its UE still claims."""
    from tpudes.models.lte.device import (
        LteEnbNetDevice,
        LteEnbRrc,
        LteUeNetDevice,
    )

    enb = LteEnbNetDevice()
    stay, leave = LteUeNetDevice(), LteUeNetDevice()
    ctx_stay = enb.rrc.add_ue(stay)
    stay.rrc.connect(enb, ctx_stay.rnti)
    ctx_leave = enb.rrc.add_ue(leave)
    leave.rrc.connect(enb, ctx_leave.rnti)
    leave.rrc.disconnect()
    Simulator.Stop(MilliSeconds(2 * LteEnbRrc.STRANDED_UE_LAPSE_MS))
    Simulator.Run()
    assert list(enb.rrc.ues) == [ctx_stay.rnti]


def test_same_cell_reattach_reclaims_old_context():
    """Review fix: a UE re-establishing on the SAME cell under a fresh
    RNTI abandons its old context just like a re-attach elsewhere — the
    sweep must reclaim it (connect() notes the detach for any previous
    serving cell, not only a different one)."""
    from tpudes.models.lte.device import (
        LteEnbNetDevice,
        LteEnbRrc,
        LteUeNetDevice,
    )

    enb = LteEnbNetDevice()
    ue = LteUeNetDevice()
    ctx = enb.rrc.add_ue(ue)
    ue.rrc.connect(enb, ctx.rnti)
    ctx2 = enb.rrc.add_ue(ue)  # RRC re-establishment: fresh RNTI
    ue.rrc.connect(enb, ctx2.rnti)
    assert len(enb.rrc.ues) == 2, "old context stranded until the sweep"
    Simulator.Stop(MilliSeconds(2 * LteEnbRrc.STRANDED_UE_LAPSE_MS))
    Simulator.Run()
    assert list(enb.rrc.ues) == [ctx2.rnti]


def test_detach_during_pending_sweep_keeps_full_grace():
    """Review fix: a detach landing while a sweep is already pending
    keeps its OWN full lapse window (per-context timestamps) — a
    re-attach inside that window survives the earlier-armed sweep."""
    from tpudes.models.lte.device import (
        LteEnbNetDevice,
        LteEnbRrc,
        LteUeNetDevice,
    )

    lapse = LteEnbRrc.STRANDED_UE_LAPSE_MS
    enb = LteEnbNetDevice()
    ue1, ue2 = LteUeNetDevice(), LteUeNetDevice()
    ctx1 = enb.rrc.add_ue(ue1)
    ue1.rrc.connect(enb, ctx1.rnti)
    ctx2 = enb.rrc.add_ue(ue2)
    ue2.rrc.connect(enb, ctx2.rnti)
    ue1.rrc.disconnect()  # t=0: arms the sweep for t=lapse
    # t=lapse-1: ue2 detaches; t=lapse+1: it re-attaches (same RNTI) —
    # well inside ITS grace window even though the pending sweep fires
    # at t=lapse, 1 ms after its detach
    Simulator.Schedule(MilliSeconds(lapse - 1), ue2.rrc.disconnect)
    Simulator.Schedule(
        MilliSeconds(lapse + 1), lambda: ue2.rrc.connect(enb, ctx2.rnti)
    )
    Simulator.Stop(MilliSeconds(3 * lapse))
    Simulator.Run()
    assert ctx1.rnti not in enb.rrc.ues, "lapsed context reclaimed"
    assert ctx2.rnti in enb.rrc.ues, "re-attach inside its grace survives"
    assert enb.rrc._unclaimed_since == {}, "re-claimed context unmarked"
