"""MultiModelSpectrumChannel + SpectrumWifiPhy tests.

Upstream analogs: spectrum-converter test (power conservation across
model conversion), wifi-phy-interference tests, and the LTE/WiFi
coexistence examples that motivate the multi-model channel.
"""


import pytest

from tpudes.core import Seconds, Simulator
from tpudes.models.spectrum import (
    MultiModelSpectrumChannel,
    SpectrumConverter,
    SpectrumModel,
    SpectrumSignalParameters,
    SpectrumValue,
    lte_spectrum_model,
)
from tpudes.models.wifi.spectrum_phy import (
    SpectrumWifiPhy,
    wifi_spectrum_model,
)


def test_converter_conserves_power_on_overlap():
    a = SpectrumModel.FromCenters([100.0, 300.0], 200.0)   # [0,200),[200,400)
    b = SpectrumModel.FromCenters([50.0, 150.0, 250.0, 350.0], 100.0)
    v = SpectrumValue(a)
    v.values[:] = (1.0, 3.0)
    out = SpectrumConverter(a, b).Convert(v)
    # finer model: each target band inherits its parent's PSD
    assert list(out.values) == [1.0, 1.0, 3.0, 3.0]
    assert out.TotalPowerW() == pytest.approx(v.TotalPowerW())

    # and back: coarse bands average their children
    back = SpectrumConverter(b, a).Convert(out)
    assert list(back.values) == [1.0, 3.0]


def test_converter_drops_power_outside_overlap():
    a = SpectrumModel.FromCenters([100.0], 200.0)          # [0, 200)
    b = SpectrumModel.FromCenters([250.0], 100.0)          # [200, 300)
    v = SpectrumValue(a)
    v.values[:] = 5.0
    out = SpectrumConverter(a, b).Convert(v)
    assert out.TotalPowerW() == 0.0


def _spectrum_bss(n_stas=2):
    """AP + STAs on SpectrumWifiPhy over a MultiModelSpectrumChannel."""
    from tpudes.helper.applications import (
        UdpEchoClientHelper,
        UdpEchoServerHelper,
    )
    from tpudes.helper.containers import NetDeviceContainer, NodeContainer
    from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
    from tpudes.models.mobility import (
        ListPositionAllocator,
        MobilityHelper,
        Vector,
    )
    from tpudes.models.propagation import LogDistancePropagationLossModel
    from tpudes.models.wifi import WifiHelper, WifiMacHelper

    nodes = NodeContainer()
    nodes.Create(n_stas + 1)
    alloc = ListPositionAllocator()
    alloc.Add(Vector(0, 0, 0))
    for i in range(n_stas):
        alloc.Add(Vector(10.0 + 2 * i, 0, 0))
    mob = MobilityHelper()
    mob.SetPositionAllocator(alloc)
    mob.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    mob.Install(nodes)

    channel = MultiModelSpectrumChannel()
    channel.AddPropagationLossModel(LogDistancePropagationLossModel())

    class SpectrumPhyHelper:
        def Create(self, node, device):
            phy = SpectrumWifiPhy()
            phy.SetDevice(device)
            phy.SetChannel(channel)
            return phy

    phy_helper = SpectrumPhyHelper()
    wifi = WifiHelper()
    wifi.SetRemoteStationManager(
        "tpudes::ConstantRateWifiManager", DataMode="OfdmRate54Mbps"
    )
    ap_mac = WifiMacHelper()
    ap_mac.SetType("tpudes::ApWifiMac")
    ap_devs = wifi.Install(phy_helper, ap_mac, [nodes.Get(0)])
    sta_mac = WifiMacHelper()
    sta_mac.SetType("tpudes::StaWifiMac")
    sta_devs = wifi.Install(
        phy_helper, sta_mac, [nodes.Get(1 + i) for i in range(n_stas)]
    )
    InternetStackHelper().Install(nodes)
    devices = NetDeviceContainer()
    devices.Add(ap_devs.Get(0))
    for i in range(n_stas):
        devices.Add(sta_devs.Get(i))
    ifc = Ipv4AddressHelper("10.1.4.0", "255.255.255.0").Assign(devices)

    server = UdpEchoServerHelper(9)
    sapps = server.Install(nodes.Get(0))
    sapps.Start(Seconds(0.1))
    rx = [0]
    sapps.Get(0).TraceConnectWithoutContext(
        "Rx", lambda *a: rx.__setitem__(0, rx[0] + 1)
    )
    for i in range(n_stas):
        c = UdpEchoClientHelper(ifc.GetAddress(0), 9)
        c.SetAttribute("MaxPackets", 5)
        c.SetAttribute("Interval", Seconds(0.05))
        c.Install(nodes.Get(1 + i)).Start(Seconds(0.3 + 0.001 * i))
    return nodes, channel, rx


def test_wifi_over_spectrum_channel_delivers():
    nodes, channel, rx = _spectrum_bss(2)
    Simulator.Stop(Seconds(1.0))
    Simulator.Run()
    assert rx[0] == 10, "echo traffic must flow over the spectrum medium"


def test_foreign_psd_jams_the_wifi_band():
    """An LTE-model PSD blasted onto the shared channel lands as
    converted in-band interference and kills WiFi delivery — the
    coexistence effect the multi-model channel exists to capture."""
    from tpudes.models.mobility import MobilityModel
    from tpudes.models.spectrum import SpectrumPhy

    nodes, channel, rx = _spectrum_bss(2)
    wifi_phy = nodes.Get(0).GetDevice(0).GetPhy()
    center = float(wifi_phy.frequency)

    class Jammer(SpectrumPhy):
        def GetRxSpectrumModel(self):
            return None

        def GetMobility(self):
            return nodes.Get(0).GetObject(MobilityModel)

        def GetDevice(self):
            return nodes.Get(0).GetDevice(0)

        def StartRx(self, params):
            pass

    jammer = Jammer()
    channel.AddRx(jammer)
    model = lte_spectrum_model(25, center)  # overlapping the WiFi band
    psd = SpectrumValue(model)
    psd.values[:] = 1.0  # absurdly strong: guaranteed jam

    def blast():
        channel.StartTx(SpectrumSignalParameters(psd, 0.05, jammer))
        Simulator.Schedule(Seconds(0.05), blast)

    Simulator.Schedule(Seconds(0.0), blast)
    Simulator.Stop(Seconds(1.0))
    Simulator.Run()
    assert rx[0] == 0, "a saturating in-band jammer must block delivery"


def test_wifi_spectrum_model_shape():
    m = wifi_spectrum_model(5.18e9, 20)
    assert m.GetNumBands() == 4
    total = sum(b.width for b in m.bands)
    assert total == pytest.approx(20e6)
    assert m.bands[0].fl == pytest.approx(5.18e9 - 10e6)