"""tpudes.analysis pass fixtures: per rule, a true positive drawn from
a tpudes/ idiom, a suppressed variant, and a clean case.

These run the passes over in-memory snippets (analyze_source), so they
pin the *rules*; tests/test_analysis_gate.py pins the repo-wide gate.
"""

import textwrap

from tpudes.analysis import analyze_source


def _codes(src, path="tpudes/models/fixture.py", select=None, extra=None):
    findings = analyze_source(
        textwrap.dedent(src), path=path, select=select, extra_modules=extra
    )
    return [f.code for f in findings]


# --- jit-purity (JP) -------------------------------------------------------

def test_jp_wall_clock_in_ops_scope():
    src = """
    import time

    def airtime(n):
        t0 = time.perf_counter()
        return n * t0
    """
    assert _codes(src, path="tpudes/ops/fixture.py", select=["JP"]) == ["JP001"]


def test_jp_wall_clock_outside_device_path_needs_tracing():
    # same snippet in a models/ file is host code — not flagged
    src = """
    import time

    def airtime(n):
        t0 = time.perf_counter()
        return n * t0
    """
    assert _codes(src, select=["JP"]) == []


def test_jp_print_and_host_rng_in_traced_function():
    src = """
    import jax
    import numpy as np

    @jax.jit
    def step(x):
        print(x)
        return x + np.random.uniform()
    """
    assert _codes(src, select=["JP"]) == ["JP002", "JP003"]


def test_jp_captured_list_mutation_in_jitted_function():
    src = """
    import jax

    _log = []

    @jax.jit
    def step(x):
        _log.append(x)
        return x + 1
    """
    assert _codes(src, select=["JP"]) == ["JP004"]


def test_jp_self_mutation_in_scan_body():
    src = """
    import jax

    class Engine:
        def run(self, s0, keys):
            def step(s, k):
                self.steps += 1
                return s, k
            return jax.lax.scan(step, s0, keys)
    """
    assert _codes(src, select=["JP"]) == ["JP004"]


def test_jp_suppressed_and_clean():
    suppressed = """
    import jax

    _log = []

    @jax.jit
    def step(x):
        _log.append(x)  # tpudes: ignore[JP004]
        return x + 1
    """
    assert _codes(suppressed, select=["JP"]) == []
    clean = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        local = []
        local.append(x)
        return jnp.sort(jnp.stack(local))
    """
    assert _codes(clean, select=["JP"]) == []


def test_jp005_host_sync_in_step_and_cond_bodies():
    """block_until_ready / .item() / np.asarray inside functions handed
    to lax control flow or jit — per-iteration device fences (the
    serialization ISSUE 5's async runtime removes)."""
    src = """
    import jax
    import jax.numpy as jnp
    import numpy as np

    def run(s0, horizon):
        def cond(c):
            return c[0].item() < horizon

        def body(c):
            t, s = c
            s.block_until_ready()
            return t + 1, jnp.asarray(np.asarray(s) + 1)

        return jax.lax.while_loop(cond, body, s0)
    """
    assert _codes(
        src, path="tpudes/parallel/fixture.py", select=["JP005"]
    ) == ["JP005", "JP005", "JP005"]


def test_jp005_host_side_sync_is_clean():
    """The same calls in a HOST driver function (not traced) are the
    legitimate run-end fetch — module-wide scoping would flag every
    run_* entry point in tpudes/parallel."""
    src = """
    import jax
    import numpy as np

    def run_engine(fn, s0):
        out = fn(s0)
        jax.block_until_ready(out)
        host = np.asarray(out)
        return int(host.sum()), out.item() if out.ndim == 0 else None
    """
    assert _codes(
        src, path="tpudes/parallel/fixture.py", select=["JP005"]
    ) == []


def test_jp005_from_import_and_suppression():
    flagged = """
    import jax
    from numpy import asarray

    @jax.jit
    def step(x):
        return asarray(x) + 1
    """
    assert _codes(flagged, select=["JP005"]) == ["JP005"]
    suppressed = """
    import jax
    from numpy import asarray

    @jax.jit
    def step(x):
        return asarray(x) + 1  # tpudes: ignore[JP005]
    """
    assert _codes(suppressed, select=["JP005"]) == []


# --- rng-discipline (RNG) --------------------------------------------------

def test_rng_key_reuse_without_split():
    src = """
    import jax

    def draw(key):
        backoff = jax.random.uniform(key, (4,))
        coin = jax.random.bernoulli(key)
        return backoff, coin
    """
    assert _codes(src, select=["RNG001"]) == ["RNG001"]


def test_rng_split_between_uses_is_clean():
    src = """
    import jax

    def draw(key):
        k1, k2 = jax.random.split(key)
        backoff = jax.random.uniform(k1, (4,))
        coin = jax.random.bernoulli(k2)
        return backoff, coin
    """
    assert _codes(src, select=["RNG001"]) == []


def test_rng_mutually_exclusive_branches_are_clean():
    # the replicated.py step_fn idiom: both arms split the same key
    src = """
    import jax

    def step(key, agg):
        if agg:
            k_back, k_mpdu = jax.random.split(key)
            u = jax.random.uniform(k_back)
        else:
            k_back, k_coin = jax.random.split(key)
            u = jax.random.uniform(k_coin)
        return u
    """
    assert _codes(src, select=["RNG001"]) == []


def test_rng_reuse_suppressed():
    src = """
    import jax

    def draw(key):
        a = jax.random.uniform(key)
        b = jax.random.normal(key)  # tpudes: ignore[RNG001]
        return a + b
    """
    assert _codes(src, select=["RNG001"]) == []


def test_rng_stdlib_bypass_outside_core_rng():
    src = """
    import random

    def jitter():
        return random.uniform(0.0, 0.1)
    """
    assert _codes(src, select=["RNG002"]) == ["RNG002"]
    # the seeded-stream home itself is exempt
    assert _codes(src, path="tpudes/core/rng.py", select=["RNG002"]) == []


# --- determinism (DET) -----------------------------------------------------

def test_det_schedule_from_set_iteration():
    src = """
    from tpudes.core.simulator import Simulator

    def arm(devices):
        backlog = set(devices)
        for dev in backlog:
            Simulator.Schedule(1, dev.poll)
    """
    assert _codes(src, select=["DET"]) == ["DET001"]


def test_det_sorted_set_iteration_is_clean():
    src = """
    from tpudes.core.simulator import Simulator

    def arm(devices):
        backlog = set(devices)
        for dev in sorted(backlog, key=lambda d: d.node_id):
            Simulator.Schedule(1, dev.poll)
    """
    assert _codes(src, select=["DET"]) == []


def test_det_id_in_sort_key():
    src = """
    def rank(targets):
        targets.sort(key=lambda d: (d.rssi, id(d)))
        return targets
    """
    assert _codes(src, select=["DET"]) == ["DET002"]


def test_det_suppressed_and_stable_key_clean():
    suppressed = """
    from tpudes.core.simulator import Simulator

    def arm(devices):
        backlog = set(devices)
        for dev in backlog:
            Simulator.Schedule(1, dev.poll)  # tpudes: ignore[DET001]
    """
    assert _codes(suppressed, select=["DET"]) == []
    clean = """
    def rank(targets):
        targets.sort(key=lambda d: (d.rssi, d.node_id))
        return targets
    """
    assert _codes(clean, select=["DET"]) == []


# --- event-hygiene (EVT) ---------------------------------------------------

def test_evt_dropped_schedule_in_class_with_teardown():
    src = """
    from tpudes.core.simulator import Simulator

    class Pinger:
        def StartApplication(self):
            Simulator.Schedule(1.0, self._send)

        def StopApplication(self):
            pass
    """
    assert _codes(src, select=["EVT001"]) == ["EVT001"]


def test_evt_kept_eventid_is_clean():
    src = """
    from tpudes.core.simulator import Simulator

    class Pinger:
        def StartApplication(self):
            self._ev = Simulator.Schedule(1.0, self._send)

        def StopApplication(self):
            self._ev.Cancel()
    """
    assert _codes(src, select=["EVT001"]) == []


def test_evt_swallowed_callback_exception():
    src = """
    from tpudes.core.simulator import Simulator

    def on_timer(sock):
        try:
            sock.poll()
        except Exception:
            pass
    """
    assert _codes(src, select=["EVT002"]) == ["EVT002"]
    handled = """
    from tpudes.core.simulator import Simulator

    def on_timer(sock, log):
        try:
            sock.poll()
        except Exception as e:
            log.warning(e)
    """
    assert _codes(handled, select=["EVT002"]) == []


def test_evt_reassembly_buffer_without_expiry_matches_advice_bug():
    # the PRE-fix tpudes/models/sixlowpan.py shape (ADVICE.md low):
    # per-(src, tag) buffers deleted only on completed coverage, class
    # schedules nothing -> a lost fragment strands the buffer forever
    prefix = """
    class SixLowPanNetDevice:
        def __init__(self):
            self._frags = {}

        def _reassemble(self, fh, packet, sender):
            key = (str(sender), fh.tag)
            buf = self._frags.setdefault(key, {"ranges": [], "total": fh.size})
            buf["ranges"].append((fh.offset, fh.offset + packet.GetSize()))
            covered = 0
            for s, e in sorted(buf["ranges"]):
                if s > covered:
                    return None
                covered = max(covered, e)
            if covered < buf["total"]:
                return None
            del self._frags[key]
            return buf
    """
    assert _codes(prefix, select=["EVT003"]) == ["EVT003"]
    # the POST-fix shape schedules an expiry event -> clean
    fixed = """
    from tpudes.core.simulator import Simulator

    class SixLowPanNetDevice:
        def __init__(self):
            self._frags = {}

        def _reassemble(self, fh, packet, sender):
            key = (str(sender), fh.tag)
            buf = self._frags.setdefault(key, {"ranges": []})
            buf["timer"] = Simulator.Schedule(60.0, self._expire, key)
            buf["ranges"].append(fh.offset)
            if len(buf["ranges"]) < 2:
                return None
            del self._frags[key]
            return buf

        def _expire(self, key):
            self._frags.pop(key, None)
    """
    assert _codes(fixed, select=["EVT003"]) == []


# --- registry-parity (REG) -------------------------------------------------

_DECL = """
from tpudes.core.object import TypeId


class FooDevice:
    tid = (
        TypeId("tpudes::FooDevice")
        .AddAttribute("BeaconInterval", "beacon period", 0.1)
        .AddTraceSource("PhyTxBegin", "(packet)")
    )
"""


def test_reg_dead_declarations_flagged():
    assert _codes(_DECL, select=["REG"]) == ["REG001", "REG001"]


def test_reg_referenced_declarations_clean():
    user = """
    def configure(dev, pkt):
        dev.SetAttribute("BeaconInterval", 0.2)
        dev.phy_tx_begin(pkt)
    """
    assert _codes(
        _DECL, select=["REG"],
        extra=[("tests/fixture_user.py", textwrap.dedent(user))],
    ) == []


def test_reg_suppressed():
    suppressed = _DECL.replace(
        '.AddAttribute("BeaconInterval", "beacon period", 0.1)',
        '.AddAttribute("BeaconInterval", "beacon period", 0.1)'
        '  # tpudes: ignore[REG001]',
    ).replace(
        '.AddTraceSource("PhyTxBegin", "(packet)")',
        '.AddTraceSource("PhyTxBegin", "(packet)")  # tpudes: ignore',
    )
    assert _codes(suppressed, select=["REG"]) == []


# --- style (LNT, the ported lint.py gates) ---------------------------------

def test_lnt_unused_import_and_bare_except():
    src = """
    import struct

    def parse(data):
        try:
            return data[0]
        except:
            return None
    """
    assert _codes(src, select=["LNT"]) == ["LNT003", "LNT005"]


def test_lnt_syntax_error_and_tab():
    assert _codes("def broken(:\n", select=["LNT"]) == ["LNT001"]
    assert sorted(_codes("x = 1\n\ty = 2\n", select=["LNT"])) == [
        "LNT001", "LNT002",
    ]  # the tab is also a syntax error here


def test_lnt_duplicate_import():
    src = """
    import struct
    import struct

    def size(h):
        return struct.calcsize(h)
    """
    assert _codes(src, select=["LNT"]) == ["LNT004"]


def test_lnt_suppression_without_codes_silences_line():
    src = """
    import struct  # tpudes: ignore

    def parse(data):
        return data[0]
    """
    assert _codes(src, select=["LNT"]) == []


# --- select/ignore plumbing ------------------------------------------------

def test_select_prefix_filters_other_passes():
    src = """
    import struct

    def jitter(key):
        import jax

        a = jax.random.uniform(key)
        return a + jax.random.normal(key)
    """
    # unused import AND key reuse present; select narrows to one
    assert _codes(src, select=["RNG"]) == ["RNG001"]
    assert _codes(src, select=["LNT"]) == ["LNT003"]


def test_jp_subscript_mutation_of_captured_dict():
    src = """
    import jax

    _cache = {}

    @jax.jit
    def step(x):
        _cache[0] = x
        return x + 1
    """
    assert _codes(src, select=["JP"]) == ["JP004"]


def test_jp_local_subscript_assignment_is_clean():
    src = """
    import jax

    @jax.jit
    def step(x):
        scratch = {}
        scratch[0] = x
        return x + 1
    """
    assert _codes(src, select=["JP"]) == []


def test_plugin_registration_keeps_builtin_passes():
    from tpudes.analysis import Pass, register_pass
    from tpudes.analysis.engine import ALL_PASSES

    class _ProbePass(Pass):
        name = "probe"
        codes = {"PRB001": "probe rule (test-only)"}

    register_pass(_ProbePass)
    try:
        # builtins must still run after a plugin registered first
        assert _codes("try:\n    pass\nexcept:\n    pass\n",
                      select=["LNT"]) == ["LNT005"]
    finally:
        ALL_PASSES[:] = [p for p in ALL_PASSES
                         if not isinstance(p, _ProbePass)]


def test_overlapping_paths_not_double_counted(tmp_path):
    from tpudes.analysis import analyze_paths

    sub = tmp_path / "pkg"
    sub.mkdir()
    f = sub / "mod.py"
    f.write_text("try:\n    pass\nexcept:\n    pass\n")
    findings = analyze_paths([sub, f], root=tmp_path, select=["LNT"])
    assert [x.code for x in findings] == ["LNT005"]


def test_rng_fold_in_fanout_from_one_parent_is_clean():
    src = """
    import jax

    def derive(key):
        k1 = jax.random.fold_in(key, 1)
        k2 = jax.random.fold_in(key, 2)
        return jax.random.uniform(k1), jax.random.uniform(k2)
    """
    assert _codes(src, select=["RNG001"]) == []


def test_rng_split_of_already_drawn_key_is_flagged():
    src = """
    import jax

    def draw(key):
        u = jax.random.uniform(key)
        k1, k2 = jax.random.split(key)
        return u, k1, k2
    """
    assert _codes(src, select=["RNG001"]) == ["RNG001"]


def test_rng_rebind_from_unknown_source_is_clean():
    src = """
    import jax

    def draw(key, make_key):
        a = jax.random.uniform(key)
        key = make_key()
        b = jax.random.uniform(key)
        return a + b
    """
    assert _codes(src, select=["RNG001"]) == []


def test_det_same_name_sorted_rebind_is_clean():
    src = """
    from tpudes.core.simulator import Simulator

    def arm(devices):
        backlog = set(devices)
        backlog = sorted(backlog)
        for dev in backlog:
            Simulator.Schedule(1, dev.poll)
    """
    assert _codes(src, select=["DET"]) == []


# --- trace-arity (TRC001, the ROADMAP open item) ---------------------------

_TRC_SOURCE = '''
from tpudes.core.object import Object, TypeId


class Mac(Object):
    tid = (
        TypeId("tpudes::Mac")
        .AddTraceSource("MacTx", "(packet, power)")
    )

    def send(self, packet, power):
        self.mac_tx(packet, power)
'''


def test_trc_sink_too_narrow_for_fired_arity():
    sink = """
    def wire(mac):
        mac.TraceConnectWithoutContext("MacTx", lambda p: p.GetSize())
    """
    assert _codes(
        sink, select=["TRC"],
        extra=[("tpudes/models/mac_fixture.py", _TRC_SOURCE)],
    ) == ["TRC001"]


def test_trc_matching_sink_and_vararg_sink_are_clean():
    sink = """
    def wire(mac):
        mac.TraceConnectWithoutContext("MacTx", lambda p, power: p)
        mac.TraceConnectWithoutContext("MacTx", lambda *args: None)
    """
    assert _codes(
        sink, select=["TRC"],
        extra=[("tpudes/models/mac_fixture.py", _TRC_SOURCE)],
    ) == []


def test_trc_context_connect_shifts_the_window():
    # TraceConnect prepends the context string: a 2-param sink is now
    # too narrow for a 2-arg fire, a 3-param sink fits
    sink = """
    def wire(mac):
        mac.TraceConnect("MacTx", "/path", lambda p, power: p)
        mac.TraceConnect("MacTx", "/path", lambda ctx, p, power: p)
    """
    assert _codes(
        sink, select=["TRC"],
        extra=[("tpudes/models/mac_fixture.py", _TRC_SOURCE)],
    ) == ["TRC001"]


def test_trc_defaults_widen_the_window_and_suppression_works():
    clean = """
    def wire(mac):
        mac.TraceConnectWithoutContext("MacTx", lambda p, power=None, extra=0: p)
    """
    assert _codes(
        clean, select=["TRC"],
        extra=[("tpudes/models/mac_fixture.py", _TRC_SOURCE)],
    ) == []
    suppressed = """
    def wire(mac):
        mac.TraceConnectWithoutContext("MacTx", lambda p: p)  # tpudes: ignore[TRC001]
    """
    assert _codes(
        suppressed, select=["TRC"],
        extra=[("tpudes/models/mac_fixture.py", _TRC_SOURCE)],
    ) == []


def test_trc_unfired_trace_name_is_not_guessed_at():
    # TracedValue-style sources never fire via self.<field>(...): with
    # no observed fire site the pass stays silent rather than guessing
    sink = """
    def wire(sock):
        sock.TraceConnectWithoutContext("CongestionWindow", lambda old: old)
    """
    assert _codes(sink, select=["TRC"]) == []


def test_trc_module_level_def_sink_is_resolved():
    sink = """
    def on_tx(packet):
        return packet

    def wire(mac):
        mac.TraceConnectWithoutContext("MacTx", on_tx)
    """
    assert _codes(
        sink, select=["TRC"],
        extra=[("tpudes/models/mac_fixture.py", _TRC_SOURCE)],
    ) == ["TRC001"]


# --- cross-replica shape (SHP) --------------------------------------------

def test_shp_trailing_replica_axis_flagged():
    # per-replica state with the replica operand smuggled into a
    # trailing position: traces fine, silently breaks sharding (axis
    # match) and bucket slice-back (axis 0 slice)
    src = """
    import jax.numpy as jnp

    def run_engine(prog, replicas):
        state = jnp.zeros((prog.n, replicas))
        return state
    """
    assert _codes(
        src, path="tpudes/parallel/fixture.py", select=["SHP"]
    ) == ["SHP001"]


def test_shp_leading_replica_axis_and_outside_parallel_clean():
    leading = """
    import jax.numpy as jnp

    def run_engine(prog, replicas):
        r_pad = 1 << (replicas - 1).bit_length()
        state = jnp.zeros((r_pad, prog.n))
        hist = jnp.zeros((replicas, prog.n, 4))
        return state, hist
    """
    assert _codes(
        leading, path="tpudes/parallel/fixture.py", select=["SHP"]
    ) == []
    # the same trailing shape outside tpudes/parallel/ is host-side
    # code with no sharding/bucketing contract — not flagged
    trailing = """
    import numpy as np

    def summarize(n, replicas):
        return np.zeros((n, replicas))
    """
    assert _codes(trailing, select=["SHP"]) == []


def test_shp_inherited_binding_kwarg_shape_and_suppression():
    # the engines' build() closures: `replicas` bound in the enclosing
    # scope, constructor uses shape= keyword, broadcast_to's shape is
    # its second positional
    src = """
    import jax.numpy as jnp

    def lower(prog, replicas):
        def body(carry):
            q = jnp.full(shape=(prog.n, replicas), fill_value=0)
            b = jnp.broadcast_to(carry, (prog.n, replicas))
            return q, b
        return body
    """
    assert _codes(
        src, path="tpudes/parallel/fixture.py", select=["SHP"]
    ) == ["SHP001", "SHP001"]
    suppressed = """
    import jax.numpy as jnp

    def run_engine(prog, replicas):
        return jnp.zeros((prog.n, replicas))  # tpudes: ignore[SHP001]
    """
    assert _codes(
        suppressed, path="tpudes/parallel/fixture.py", select=["SHP"]
    ) == []


# --- time units (TIM) ------------------------------------------------------

def test_tim_bare_number_delay_flagged():
    src = """
    from tpudes.core import Simulator

    def arm(cb):
        Simulator.Schedule(5, cb)
        Simulator.Stop(2.5)
    """
    assert _codes(src, select=["TIM"]) == ["TIM001", "TIM001"]


def test_tim_mixed_time_plus_literal_and_now_arithmetic():
    src = """
    from tpudes.core import Seconds, Simulator

    def arm(cb):
        Simulator.Schedule(Seconds(1) + 5, cb)
        deadline = Simulator.Now() + 100
        if Simulator.Now() > 100:
            return deadline
    """
    assert _codes(src, select=["TIM"]) == ["TIM001", "TIM001", "TIM001"]


def test_tim_unit_safe_zero_and_impl_layer_clean():
    clean = """
    from tpudes.core import MilliSeconds, Seconds, Simulator

    def arm(cb, impl):
        Simulator.Schedule(Seconds(1) + MilliSeconds(5), cb)
        Simulator.Schedule(0, cb)
        Simulator.Stop(Seconds(2))
        impl.Schedule(500, cb)  # SimulatorImpl speaks ticks by design
        if Simulator.Now() > Seconds(1):
            return Simulator.NowTicks() + 100
    """
    assert _codes(clean, select=["TIM"]) == []
    suppressed = """
    from tpudes.core import Simulator

    def arm(cb):
        Simulator.Schedule(5, cb)  # tpudes: ignore[TIM001]
    """
    assert _codes(suppressed, select=["TIM"]) == []


# --- key-discipline (KEY) --------------------------------------------------

def test_key_shape_derived_split_flagged():
    src = """
    import jax

    def per_window_keys(key, n_windows):
        return jax.random.split(key, n_windows)
    """
    assert _codes(
        src, path="tpudes/parallel/fixture.py", select=["KEY"]
    ) == ["KEY001"]


def test_key_fixed_arity_split_clean():
    # a fixed-arity split of an already-folded key is pure in its
    # inputs — the discipline only forbids shape-derived counts
    src = """
    import jax

    def draw(kk):
        k_a, k_b = jax.random.split(kk)
        k_c, k_d, k_e = jax.random.split(kk, 3)
        return (
            jax.random.uniform(k_a, (4,)),
            jax.random.uniform(k_b, (4,)),
        )
    """
    assert _codes(
        src, path="tpudes/parallel/fixture.py", select=["KEY"]
    ) == []


def test_key_raw_key_reuse_flagged_and_rebinding_clean():
    src = """
    import jax

    def correlated(key, n):
        u = jax.random.uniform(key, (n,))
        v = jax.random.normal(key, (n,))
        return u + v
    """
    assert _codes(
        src, path="tpudes/ops/fixture.py", select=["KEY"]
    ) == ["KEY001"]

    clean = """
    import jax

    def independent(key, n):
        u = jax.random.uniform(jax.random.fold_in(key, 0), (n,))
        v = jax.random.normal(jax.random.fold_in(key, 1), (n,))
        key = jax.random.fold_in(key, 2)
        w = jax.random.uniform(key, (n,))
        return u + v + w
    """
    assert _codes(
        clean, path="tpudes/ops/fixture.py", select=["KEY"]
    ) == []


def test_key_scope_is_device_packages_only_and_suppressible():
    src = """
    import jax

    def correlated(key, n):
        u = jax.random.uniform(key, (n,))
        return u + jax.random.normal(key, (n,))
    """
    # host-side model code draws from the seeded stream API instead —
    # out of scope for the fold_in discipline
    assert _codes(
        src, path="tpudes/models/fixture.py", select=["KEY"]
    ) == []

    suppressed = """
    import jax

    def correlated(key, n):
        u = jax.random.uniform(key, (n,))
        return u + jax.random.normal(key, (n,))  # tpudes: ignore[KEY001]
    """
    assert _codes(
        suppressed, path="tpudes/parallel/fixture.py", select=["KEY"]
    ) == []


def test_key_per_function_scopes_do_not_cross_contaminate():
    # the same key NAME drawn once in each of two functions is not reuse
    src = """
    import jax

    def a(key):
        return jax.random.uniform(key, (3,))

    def b(key):
        return jax.random.normal(key, (3,))
    """
    assert _codes(
        src, path="tpudes/parallel/fixture.py", select=["KEY"]
    ) == []


def test_key_split_num_keyword_also_flagged():
    # the keyword spelling must not slip past the gate
    src = """
    import jax

    def per_replica_keys(key, r_pad):
        return jax.random.split(key, num=r_pad)
    """
    assert _codes(
        src, path="tpudes/parallel/fixture.py", select=["KEY"]
    ) == ["KEY001"]


def test_key_stdlib_random_is_not_a_key_draw():
    # stdlib random has no key argument — must not read as key reuse
    src = """
    import random

    def host_jitter(lo, hi):
        a = random.uniform(lo, hi)
        b = random.uniform(lo, hi)
        return a + b
    """
    assert _codes(
        src, path="tpudes/parallel/fixture.py", select=["KEY"]
    ) == []


# --- serving-liveness (SRV) ------------------------------------------------

def test_srv_bare_blocking_waits_flagged_in_serving():
    src = """
    def demux(self, conn, q):
        self._cond.wait()
        item = q.get()
        blob = conn.recv_bytes()
        msg = conn.recv()
        return item, blob, msg
    """
    assert _codes(
        src, path="tpudes/serving/fixture.py", select=["SRV"]
    ) == ["SRV001"] * 4


def test_srv_bounded_and_disambiguated_calls_clean():
    src = """
    def demux(self, conn, q, timeout):
        self._cond.wait(timeout=0.05)
        self._ev.wait(timeout)
        item = q.get(timeout=1.0)
        default = self._map.get("key")
        if conn.poll(0.5):
            blob = conn.recv_bytes()  # tpudes: ignore[SRV001]
        return item, default
    """
    assert _codes(
        src, path="tpudes/serving/fixture.py", select=["SRV"]
    ) == []


def test_srv_scope_is_serving_and_procmesh_only():
    src = """
    def drain(self, conn):
        return conn.recv_bytes()
    """
    # same shape outside the scoped paths: host DES code is not flagged
    assert _codes(
        src, path="tpudes/models/fixture.py", select=["SRV"]
    ) == []
    assert _codes(
        src, path="tpudes/parallel/mpi.py", select=["SRV"]
    ) == []
    # but procmesh.py IS in scope
    assert _codes(
        src, path="tpudes/parallel/procmesh.py", select=["SRV"]
    ) == ["SRV001"]
