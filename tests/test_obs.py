"""tpudes.obs tests: host profiler, flight recorder, Chrome-trace
export, on-device metric accumulators, compile telemetry, and the two
acceptance gates — host/device metric parity on a deterministic
dumbbell, and the TpudesObs=0 zero-cost contract.
"""

import io
import json
import subprocess
import sys
from pathlib import Path
from time import perf_counter

import jax
import numpy as np
import pytest

from tpudes.core import Seconds, Simulator
from tpudes.core.global_value import GlobalValue
from tpudes.core.simulator import DefaultSimulatorImpl
from tpudes.core.world import reset_world
from tpudes.obs import (
    CompileTelemetry,
    FlightRecorder,
    validate_chrome_trace,
)

REPO = Path(__file__).resolve().parent.parent


def _echo_pair(packets=3):
    from tpudes.helper.applications import UdpEchoClientHelper, UdpEchoServerHelper
    from tpudes.helper.containers import NodeContainer
    from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
    from tpudes.helper.point_to_point import PointToPointHelper

    nodes = NodeContainer()
    nodes.Create(2)
    p2p = PointToPointHelper()
    p2p.SetDeviceAttribute("DataRate", "5Mbps")
    p2p.SetChannelAttribute("Delay", "2ms")
    devices = p2p.Install(nodes)
    InternetStackHelper().Install(nodes)
    ifc = Ipv4AddressHelper("10.1.1.0", "255.255.255.0").Assign(devices)
    UdpEchoServerHelper(9).Install(nodes.Get(1)).Start(Seconds(0.0))
    client = UdpEchoClientHelper(ifc.GetAddress(1), 9)
    client.SetAttribute("MaxPackets", packets)
    client.SetAttribute("Interval", Seconds(0.1))
    client.SetAttribute("PacketSize", 400)
    client.Install(nodes.Get(0)).Start(Seconds(0.1))
    return nodes, devices


# --- host profiler ---------------------------------------------------------

def test_disabled_is_structurally_zero_cost():
    """TpudesObs=0 must leave the engine byte-identical to pre-obs code:
    no profiler, no scheduler wrapper, the class ``_invoke`` un-shadowed."""
    impl = Simulator.GetImpl()
    assert impl._obs is None
    assert "_invoke" not in impl.__dict__  # no instance-attr swap
    from tpudes.obs.profiler import InstrumentedScheduler

    assert not isinstance(impl._events, InstrumentedScheduler)


def test_profiler_counts_types_and_queue_depth():
    GlobalValue.Bind("TpudesObs", 1)
    _echo_pair(packets=3)
    Simulator.Stop(Seconds(1.0))
    Simulator.Run()
    obs = Simulator.GetImpl()._obs
    assert obs is not None
    assert obs.event_count == Simulator.GetEventCount() > 0
    summary = obs.summary()
    assert sum(t["count"] for t in summary["event_types"].values()) == obs.event_count
    assert all(t["wall_s"] >= 0.0 for t in summary["event_types"].values())
    # the echo exchange schedules receives while others are pending
    assert summary["queue"]["depth_max"] >= 2
    assert summary["queue"]["inserts"] >= obs.event_count
    # event-type labels are callback qualnames
    assert any("Receive" in name for name in summary["event_types"])


def test_window_stats_on_jax_engine():
    GlobalValue.Bind("TpudesObs", 1)
    GlobalValue.Bind(
        "SimulatorImplementationType", "tpudes::JaxSimulatorImpl"
    )
    _echo_pair(packets=5)
    Simulator.Stop(Seconds(1.0))
    Simulator.Run()
    impl = Simulator.GetImpl()
    obs = impl._obs
    w = obs.summary()["windows"]
    assert w["count"] == impl.windows_run > 0
    assert w["events"] == obs.event_count
    assert w["events_per_window"] == pytest.approx(
        w["events"] / w["count"]
    )


def test_show_progress_reads_profiler_stats():
    GlobalValue.Bind("TpudesObs", 1)
    from tpudes.core.show_progress import ShowProgress

    _echo_pair(packets=8)
    buf = io.StringIO()
    sp = ShowProgress(Seconds(0.25), stream=buf)
    # one meter: ShowProgress samples the engine profiler's RunStats
    assert sp._stats is Simulator.GetImpl()._obs.run_stats
    Simulator.Stop(Seconds(1.2))
    Simulator.Run()
    lines = [
        ln for ln in buf.getvalue().splitlines()
        if ln.startswith("ShowProgress:")
    ]
    assert len(lines) >= 2
    assert "ev/s" in lines[0] and "sim-s/wall-s" in lines[0]
    assert "q=" in lines[0]  # live queue depth column, profiler-only


def test_show_progress_still_works_without_obs():
    from tpudes.core.show_progress import ShowProgress

    _echo_pair(packets=8)
    buf = io.StringIO()
    ShowProgress(Seconds(0.25), stream=buf)
    Simulator.Stop(Seconds(1.2))
    Simulator.Run()
    lines = buf.getvalue().splitlines()
    assert lines and all("q=" not in ln for ln in lines)


# --- flight recorder -------------------------------------------------------

def test_flight_recorder_ring_is_bounded_and_keeps_the_tail():
    rec = FlightRecorder(capacity=4)
    for i in range(10):
        rec.note(i, 0, i, f"ev{i}")
    assert len(rec) == 4
    assert [e[3] for e in rec.entries()] == ["ev6", "ev7", "ev8", "ev9"]


def test_flight_recorder_dumps_on_event_exception(capsys):
    GlobalValue.Bind("TpudesObs", 1)
    GlobalValue.Bind("TpudesObsRing", 8)

    def noop():
        pass

    def boom():
        raise ValueError("kaput")

    for i in range(20):
        Simulator.Schedule(Seconds(0.01 * i), noop)
    Simulator.Schedule(Seconds(0.5), boom)
    with pytest.raises(ValueError, match="kaput"):
        Simulator.Run()
    err = capsys.readouterr().err
    assert "flight recorder" in err and "kaput" in err
    # capacity knob honored: 8 entries + 2 frame lines
    body = [ln for ln in err.splitlines() if ln.startswith("  ts=")]
    assert len(body) == 8
    assert "boom" in body[-1]  # newest last == the fatal event


# --- Chrome-trace export ---------------------------------------------------

def test_chrome_trace_export_schema_and_cli(tmp_path):
    trace = tmp_path / "trace.json"
    GlobalValue.Bind("TpudesObs", 1)
    GlobalValue.Bind("TpudesObsTrace", str(trace))
    _echo_pair(packets=3)
    Simulator.Stop(Seconds(1.0))
    Simulator.Run()
    Simulator.Destroy()  # writes the export
    doc = json.loads(trace.read_text())
    assert validate_chrome_trace(doc) == []
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert spans and all("sim_ts" in s["args"] for s in spans)
    assert doc["otherData"]["events"] > 0
    # the CLI validator gates the same file (the CI smoke step)
    proc = subprocess.run(
        [sys.executable, "-m", "tpudes.obs", str(trace)],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "valid Chrome trace" in proc.stdout


def test_chrome_trace_validator_rejects_malformed():
    assert validate_chrome_trace([]) == ["top level is not an object"]
    assert validate_chrome_trace({}) == ["'traceEvents' missing or not an array"]
    bad_ph = {"traceEvents": [{"ph": "Z", "name": "x", "ts": 0}]}
    assert any("bad phase" in p for p in validate_chrome_trace(bad_ph))
    no_dur = {"traceEvents": [
        {"ph": "X", "name": "x", "ts": 1, "pid": 0, "tid": 0}
    ]}
    assert any("dur" in p for p in validate_chrome_trace(no_dur))
    neg_ts = {"traceEvents": [
        {"ph": "i", "name": "x", "ts": -5, "pid": 0, "tid": 0}
    ]}
    assert any("ts" in p for p in validate_chrome_trace(neg_ts))


# --- device metric accumulators -------------------------------------------

def _deterministic_dumbbell(sim_s=2.0, max_bytes_per_flow=20_000):
    """Two budget-limited flows through an uncongested bottleneck: the
    per-flow delivered/drop/retransmit totals are independent of the
    departure interleaving (every packet is eventually served, none is
    ever dropped), so both engines must finish the budgets with zero
    drops and zero retransmissions — deterministically."""
    from tpudes.scenarios import build_dumbbell

    db, sinks = build_dumbbell(
        2, sim_s, variant="TcpNewReno", queue="200p", seg_bytes=1000
    )
    from tpudes.models.applications import BulkSendApplication
    from tpudes.network.node import NodeList

    bulks = [
        app
        for i in range(NodeList.GetNNodes())
        for a in range(NodeList.GetNode(i).GetNApplications())
        if isinstance(
            app := NodeList.GetNode(i).GetApplication(a), BulkSendApplication
        )
    ]
    for bulk in bulks:
        bulk.SetAttribute("MaxBytes", max_bytes_per_flow)
    return db, sinks, bulks


def test_dumbbell_device_metrics_match_host_traced_counts():
    """Acceptance gate: device-accumulated drop/retransmit counters ==
    the host engine's TracedCallback-derived counts on a deterministic
    dumbbell (and the delivered byte count agrees exactly)."""
    from tpudes.parallel.tcp_dumbbell import lower_dumbbell, run_tcp_dumbbell

    sim_s, budget = 2.0, 20_000
    db, sinks, bulks = _deterministic_dumbbell(sim_s, budget)
    prog = lower_dumbbell(sim_s)

    # --- host side: counters derived purely from TracedCallbacks -------
    host_drops, host_retx = [], []
    from tpudes.network.node import NodeList

    for i in range(NodeList.GetNNodes()):
        node = NodeList.GetNode(i)
        for d in range(node.GetNDevices()):
            q = getattr(node.GetDevice(d), "GetQueue", lambda: None)()
            if q is not None:
                q.TraceConnectWithoutContext(
                    "Drop", lambda p: host_drops.append(p)
                )

    def hook_retransmit():
        for bulk in bulks:
            bulk._socket.TraceConnectWithoutContext(
                "Retransmit", lambda seq: host_retx.append(seq)
            )

    Simulator.Schedule(Seconds(0.15), hook_retransmit)  # after app starts
    Simulator.Stop(Seconds(sim_s))
    Simulator.Run()
    host_rx = [s.GetTotalRx() for s in sinks]
    assert host_rx == [budget, budget]  # both budgets completed
    reset_world()

    # --- device side: obs accumulators fetched once at run end ---------
    GlobalValue.Bind("TpudesObs", 1)
    out = run_tcp_dumbbell(prog, jax.random.PRNGKey(0), replicas=3)
    delivered = np.asarray(out["delivered"])
    dev_drops = np.asarray(out["drops"])
    dev_retx = np.asarray(out["retx"])
    dev_cuts = np.asarray(out["cwnd_cuts"])
    # deterministic: every replica identical
    assert (delivered == delivered[0]).all()
    # parity with the host TracedCallback counts, per flow
    assert (delivered[0] * prog.seg_bytes).tolist() == host_rx
    assert dev_drops.sum() == len(host_drops) == 0
    assert dev_retx.sum() == len(host_retx) == 0
    assert dev_cuts.sum() == 0  # no loss -> no cwnd reduction anywhere


def test_dumbbell_obs_accumulators_consistent_under_loss():
    from tpudes.parallel.tcp_dumbbell import (
        OBS_QHIST_BINS,
        lower_dumbbell,
        run_tcp_dumbbell,
    )
    from tpudes.scenarios import build_dumbbell

    sim_s = 3.0
    build_dumbbell(4, sim_s, variant="TcpNewReno", queue="10p")
    prog = lower_dumbbell(sim_s)
    reset_world()
    GlobalValue.Bind("TpudesObs", 1)
    out = run_tcp_dumbbell(prog, jax.random.PRNGKey(1), replicas=4)
    drops = np.asarray(out["drops"])
    retx = np.asarray(out["retx"])
    cuts = np.asarray(out["cwnd_cuts"])
    hist = np.asarray(out["queue_hist"])
    assert hist.shape == (4, OBS_QHIST_BINS)
    # one histogram increment per slot per replica
    assert (hist.sum(axis=1) == prog.n_slots).all()
    assert drops.sum() > 0  # the 10p queue overflows
    assert cuts.sum() > 0  # losses triggered window reductions
    # every retransmission is a detected loss; detection trails the
    # drop by ack_lag so the consumed count never exceeds the drops
    assert 0 < retx.sum() <= drops.sum()


def test_dumbbell_obs_off_omits_metric_keys_and_matches():
    from tpudes.parallel.tcp_dumbbell import lower_dumbbell, run_tcp_dumbbell
    from tpudes.scenarios import build_dumbbell

    build_dumbbell(2, 1.0, variant="TcpNewReno")
    prog = lower_dumbbell(1.0)
    reset_world()
    out_off = run_tcp_dumbbell(prog, jax.random.PRNGKey(2), replicas=2)
    assert "retx" not in out_off and "queue_hist" not in out_off
    GlobalValue.Bind("TpudesObs", 1)
    out_on = run_tcp_dumbbell(prog, jax.random.PRNGKey(2), replicas=2)
    # the accumulators ride along without disturbing the outcome
    np.testing.assert_array_equal(
        np.asarray(out_off["delivered"]), np.asarray(out_on["delivered"])
    )


def test_lte_sweep_compile_telemetry_pins_single_executable():
    """PR 2's 'one executable serves the family' claim, as a metric: a
    scheduler sweep over the same lowered program records ONE compile."""
    import dataclasses

    from tpudes.parallel.lte_sm import run_lte_sm

    sys.path.insert(0, str(REPO / "tests"))
    from test_lte_sm import _toy_prog

    prog = _toy_prog(n_enb=2, n_ue=4, n_ttis=40)
    from tpudes.parallel.runtime import RUNTIME

    RUNTIME.clear("lte_sm")
    CompileTelemetry.reset()
    for sched in ("pf", "rr", "fdmt"):
        run_lte_sm(
            dataclasses.replace(prog, scheduler=sched),
            jax.random.PRNGKey(0), replicas=2,
        )
    snap = CompileTelemetry.snapshot()
    assert snap["lte_sm"]["compiles"] == 1
    assert snap["lte_sm"]["wall_s"] > 0


def test_bss_retx_metric_rides_the_carry():
    sys.path.insert(0, str(REPO / "tests"))
    from test_replicated import _lowered_program

    prog = _lowered_program()
    GlobalValue.Bind("TpudesObs", 1)
    from tpudes.parallel.replicated import run_replicated_bss

    out = run_replicated_bss(prog, 8, jax.random.PRNGKey(3))
    assert out["all_done"]
    retx = np.asarray(out["retx"])
    assert retx.shape == (8,)
    # retransmissions are attempts that are not first tries
    assert (retx >= 0).all()
    assert (retx <= np.asarray(out["tx_data"])).all()


# --- zero-cost contract ----------------------------------------------------

def _storm(impl, n):
    def noop():
        pass

    for i in range(n):
        impl.Schedule(i, noop, ())


def _pristine_run(impl):
    """The pre-obs DefaultSimulatorImpl loop, verbatim — the no-obs
    baseline the acceptance criterion compares against."""
    impl._stop = False
    events = impl._events
    while not impl._stop:
        impl._process_events_with_context()
        if events.IsEmpty():
            break
        ev = events.RemoveNext()
        impl.current_ts = ev.ts
        impl.current_context = ev.context
        impl.current_uid = ev.uid
        impl._event_count += 1
        ev.invoke()


def test_obs_disabled_overhead_within_3_percent():
    """TpudesObs=0 runtime pinned within 3% of a no-obs run on the host
    event loop (the denominator loop of every bench.py row).  The
    Python scheduler is forced on both sides so the identical dispatch
    path is measured."""
    GlobalValue.Bind("SchedulerType", "tpudes::PyHeapScheduler")
    N = 50_000

    def once(run_fn):
        impl = DefaultSimulatorImpl()
        assert impl._obs is None
        _storm(impl, N)
        t0 = perf_counter()
        run_fn(impl)
        dt = perf_counter() - t0
        assert impl._event_count == N
        return dt

    for attempt in range(3):
        # interleave the two sides: measuring all knob0 samples and
        # THEN all pristine samples lets a monotonic load change (CI
        # neighbors spinning up/down) bias the ratio — alternating
        # keeps min-vs-min comparing the same load regime
        k_samples, p_samples = [], []
        for _ in range(5):
            k_samples.append(once(DefaultSimulatorImpl.Run))
            p_samples.append(once(_pristine_run))
        knob0, pristine = min(k_samples), min(p_samples)
        if knob0 <= pristine * 1.03:
            return
    pytest.fail(
        f"TpudesObs=0 run {knob0:.4f}s vs no-obs {pristine:.4f}s "
        f"({knob0 / pristine:.3f}x > 1.03x)"
    )


def _flowmon_runner(engine):
    """A small deterministic run thunk per device engine (the program is
    built once so both knob settings exercise the identical key)."""
    key = jax.random.PRNGKey(0)
    if engine == "dumbbell":
        from tpudes.parallel.tcp_dumbbell import lower_dumbbell, run_tcp_dumbbell
        from tpudes.scenarios import build_dumbbell

        build_dumbbell(2, 1.0, variant="TcpNewReno")
        prog = lower_dumbbell(1.0)
        reset_world()
        return lambda: run_tcp_dumbbell(prog, key, replicas=2)
    if engine == "bss":
        sys.path.insert(0, str(REPO / "tests"))
        from test_replicated import _lowered_program

        from tpudes.parallel.replicated import run_replicated_bss

        prog = _lowered_program()
        return lambda: run_replicated_bss(prog, 4, key)
    if engine == "lte_sm":
        from tpudes.parallel.lte_sm import run_lte_sm
        from tpudes.parallel.programs import toy_lte_program

        prog = toy_lte_program(n_enb=2, n_ue=3, n_ttis=40)
        return lambda: run_lte_sm(prog, key, replicas=2)
    from tpudes.parallel.wired import run_wired, wired_chain

    prog = wired_chain(n_links=3, n_flows=2, n_slots=60)
    return lambda: run_wired(prog, key, replicas=2)


@pytest.mark.parametrize("engine", ["dumbbell", "bss", "lte_sm", "wired"])
def test_flowmon_off_reuses_the_pre_obs_executable(engine):
    """TpudesObs=0 compiles the exact pre-obs program on every engine:
    binding the knob to 0 after an unset-knob run is a runner-cache HIT
    (unchanged cache key) and records no new compile — the FlowMonitor
    columns are structurally absent, not merely unused."""
    from tpudes.parallel.runtime import RUNTIME

    run = _flowmon_runner(engine)
    RUNTIME.clear(engine)
    CompileTelemetry.reset()
    out_unset = run()
    keys0 = {k for k in RUNTIME._runners if k[0] == engine}
    compiles0 = CompileTelemetry.snapshot()[engine]["compiles"]
    assert compiles0 >= 1
    GlobalValue.Bind("TpudesObs", 0)
    out_zero = run()
    assert {k for k in RUNTIME._runners if k[0] == engine} == keys0
    assert CompileTelemetry.snapshot()[engine]["compiles"] == compiles0
    assert "flow" not in out_unset and "flow" not in out_zero


@pytest.mark.parametrize(
    "mod, site",
    [
        ("tpudes.parallel.tcp_dumbbell", "dumbbell.flow_ring"),
        ("tpudes.parallel.replicated", "bss.flow_ring"),
        ("tpudes.parallel.lte_sm", "lte_sm.flow_ring"),
        ("tpudes.parallel.wired", "wired.flow_ring"),
    ],
)
def test_flowmon_ring_sparse_site_is_audited(mod, site):
    """TpudesObs=1 adds exactly one class of sparse op per engine — the
    packet ring's mod-bounded slot write — and it is a REGISTERED
    SparseSite whose contract the traced obs jaxpr upholds (JXL008):
    zero new unaudited findings beyond the registry rows."""
    import importlib

    from tpudes.analysis.jaxpr import sparse_registry as SR
    from tpudes.analysis.jaxpr.trace import trace_entry

    man = importlib.import_module(mod).trace_manifest()
    variant = next(v for v in man.variants() if v.name == "obs")
    seen_sites = set()
    for entry in variant.build():
        records = SR.audit_entry(
            man.engine, f"{variant.name}/{entry.name}", trace_entry(entry)
        )
        assert all(r["ok"] for r in records), (entry.name, records)
        seen_sites |= {r["site"] for r in records}
    assert site in seen_sites, seen_sites


def test_queue_depth_resyncs_after_cancellations():
    """Cancelled events are purged inside the wrapped scheduler without
    a visible pop; the profiler's periodic resync must snap the depth
    back to the exact live count instead of drifting upward forever."""
    GlobalValue.Bind("TpudesObs", 1)

    def noop():
        pass

    ids = [Simulator.Schedule(Seconds(5.0 + 0.001 * i), noop) for i in range(500)]
    for eid in ids:
        eid.Cancel()
    Simulator.Schedule(Seconds(0.1), noop)
    Simulator.Stop(Seconds(1.0))
    Simulator.Run()
    obs = Simulator.GetImpl()._obs
    # without the resync the 500 phantom entries would linger
    assert obs.resync_depth() == 0
    assert obs.summary()["queue"]["depth"] == 0


def test_window_totals_are_exact_beyond_the_span_cap():
    from tpudes.obs import HostProfiler

    obs = HostProfiler(ring_capacity=8)
    obs.MAX_SPANS = 10
    for i in range(25):
        obs.on_window(obs.run_stats.wall_start, 0.001, 2, 1)
    w = obs.summary()["windows"]
    assert len(obs.windows) == 10          # export list stays bounded
    assert w["count"] == 25                # totals stay exact
    assert w["events"] == 50
    assert w["events_per_window"] == pytest.approx(2.0)
