"""Device dumbbell engine: the full 17-variant family + RED/ECN.

VERDICT r4 weak #2 / r5 #2: config #2 is the *variants comparison*, so
the replica engine must sweep the whole TcpCongestionOps family (incl.
BBR, DCTCP, and the r6 additions H-TCP, YeAH, LEDBAT, TCP-LP) with no
silent host fallback, and the bottleneck AQM must lower too (RED
marking is what makes DCTCP meaningful).  The scalar DES remains the
oracle: per-variant goodput parity pins mirror the existing
NewReno/Vegas ones.
"""

import jax
import numpy as np
import pytest

from tpudes.core import Seconds, Simulator
from tpudes.models.internet.tcp import TcpL4Protocol
from tpudes.models.traffic_control import TrafficControlHelper
from tpudes.parallel.tcp_dumbbell import (
    VARIANTS,
    lower_dumbbell,
    run_tcp_dumbbell,
)
from tpudes.scenarios import build_dumbbell

SIM_S = 4.0


def _reset():
    from tpudes.core.world import reset_world

    reset_world()


def _red_dumbbell(variant, n_flows=3, min_th=5.0, max_th=15.0,
                  use_ecn=True, max_size=1000):
    """build_dumbbell + RED root qdisc on the bottleneck (the
    test_ecn_dctcp harness shape)."""
    db, sinks = build_dumbbell(
        n_flows, SIM_S, variant=variant, bottleneck_rate="5Mbps"
    )
    if use_ecn:
        for i in range(n_flows):
            db.GetLeft(i).GetObject(TcpL4Protocol).SetAttribute("UseEcn", True)
            db.GetRight(i).GetObject(TcpL4Protocol).SetAttribute("UseEcn", True)
    tch = TrafficControlHelper()
    tch.SetRootQueueDisc(
        "tpudes::RedQueueDisc", MinTh=min_th, MaxTh=max_th,
        MaxSize=max_size, LinkBandwidth="5Mbps", UseEcn=use_ecn,
        UseHardDrop=not use_ecn,
    )
    tch.Install(db.GetBottleneckDevices().Get(0))
    return db, sinks


def test_all_seventeen_variants_lift_and_progress():
    """One flow per variant — the whole family on the replica axis in a
    single program, every flow making progress (no silent fallback)."""
    _reset()
    build_dumbbell(
        len(VARIANTS), SIM_S, variants=list(VARIANTS),
        bottleneck_rate="13Mbps",
    )
    prog = lower_dumbbell(SIM_S)
    assert prog.n_flows == len(VARIANTS)
    assert sorted(prog.variant_idx.tolist()) == list(range(len(VARIANTS)))
    # DCTCP is the only ECN-capable flow without explicit UseEcn
    assert prog.ecn.sum() == 1
    assert prog.ecn[VARIANTS.index("TcpDctcp")]
    out = run_tcp_dumbbell(prog, jax.random.PRNGKey(0), replicas=8)
    delivered = np.asarray(out["delivered"])
    assert (delivered > 0).all(), delivered.mean(0)
    util = delivered.sum(1) / prog.n_slots
    assert (util > 0.85).all(), util


def test_red_lowering_reads_qdisc():
    _reset()
    _red_dumbbell("TcpDctcp", min_th=4.0, max_th=12.0, max_size=200)
    prog = lower_dumbbell(SIM_S)
    assert prog.qdisc == "red"
    assert prog.red_min_th == 4.0
    assert prog.red_max_th == 12.0
    assert prog.queue_cap == 200
    assert prog.red_use_ecn and not prog.red_use_hard_drop
    assert prog.ecn.all()


@pytest.mark.parametrize(
    "variant",
    ["TcpBbr", "TcpWestwood", "TcpIllinois",
     "TcpHtcp", "TcpYeah", "TcpLedbat", "TcpLp"],
)
def test_new_variant_goodput_parity(variant):
    """Host socket stack vs slot model, ±25% aggregate goodput — the
    same pin the original six variants carry (r6 extends the sweep to
    the last four host variants: H-TCP, YeAH, LEDBAT, TCP-LP)."""
    _reset()
    db, sinks = build_dumbbell(
        3, SIM_S, variant=variant, bottleneck_rate="3Mbps"
    )
    Simulator.Stop(Seconds(SIM_S))
    Simulator.Run()
    host = sum(s.GetTotalRx() * 8.0 / (SIM_S - 0.1) / 1e6 for s in sinks)

    _reset()
    build_dumbbell(3, SIM_S, variant=variant, bottleneck_rate="3Mbps")
    prog = lower_dumbbell(SIM_S)
    out = run_tcp_dumbbell(prog, jax.random.PRNGKey(3), replicas=8)
    dev = float(np.asarray(out["goodput_mbps"]).sum(1).mean())
    _reset()
    assert dev == pytest.approx(host, rel=0.25), (
        f"{variant}: device {dev:.2f} vs host {host:.2f} Mbps"
    )


def test_scavenger_variants_yield_to_reno():
    """LEDBAT and TCP-LP are scavengers: competing with a NewReno flow
    each takes less than Reno does, while the pipe stays full — the
    behavioral signature that distinguishes them from the loss-based
    family (not just an aggregate-goodput pin)."""
    _reset()
    build_dumbbell(
        3, SIM_S, variants=["TcpNewReno", "TcpLedbat", "TcpLp"],
        bottleneck_rate="5Mbps",
    )
    prog = lower_dumbbell(SIM_S)
    out = run_tcp_dumbbell(prog, jax.random.PRNGKey(11), replicas=8)
    g = np.asarray(out["goodput_mbps"]).mean(0)
    util = np.asarray(out["delivered"]).sum(1) / prog.n_slots
    _reset()
    assert g[1] < g[0], f"LEDBAT {g[1]:.2f} should yield to Reno {g[0]:.2f}"
    assert g[2] < g[0], f"TCP-LP {g[2]:.2f} should yield to Reno {g[0]:.2f}"
    assert (util > 0.85).all(), util


def test_dctcp_over_red_parity_and_shallow_queue():
    """DCTCP + marking RED: full throughput at a shallow queue, ~no
    drops — on BOTH engines, with goodput parity."""
    _reset()
    db, sinks = _red_dumbbell("TcpDctcp")
    Simulator.Stop(Seconds(SIM_S))
    Simulator.Run()
    host = sum(s.GetTotalRx() * 8.0 / (SIM_S - 0.1) / 1e6 for s in sinks)

    _reset()
    _red_dumbbell("TcpDctcp")
    prog = lower_dumbbell(SIM_S)
    out = run_tcp_dumbbell(prog, jax.random.PRNGKey(5), replicas=8)
    dev = float(np.asarray(out["goodput_mbps"]).sum(1).mean())
    mean_q = float(np.asarray(out["mean_queue"]).mean())
    drops = int(np.asarray(out["drops"]).sum())
    _reset()
    assert dev == pytest.approx(host, rel=0.25), (
        f"device {dev:.2f} vs host {host:.2f} Mbps"
    )
    # the AQM governs by marking: queue sits near the thresholds, far
    # from the 1000-packet hard cap, and (virtually) nothing drops
    assert mean_q < 60.0, mean_q
    assert drops <= 8, drops


def test_red_early_drops_replace_tail_loss_for_non_ecn():
    """NewReno over drop-mode RED: losses happen early (queue never
    reaches the hard cap), unlike the tail-drop fifo baseline."""
    _reset()
    _red_dumbbell("TcpNewReno", use_ecn=False, max_size=1000)
    prog = lower_dumbbell(SIM_S)
    assert prog.qdisc == "red" and not prog.red_use_ecn
    out = run_tcp_dumbbell(prog, jax.random.PRNGKey(9), replicas=8)
    mean_q = float(np.asarray(out["mean_queue"]).mean())
    drops = int(np.asarray(out["drops"]).sum())
    util = np.asarray(out["delivered"]).sum(1) / prog.n_slots
    _reset()
    assert drops > 0, "RED must early-drop non-ECT traffic"
    assert mean_q < 100.0, mean_q   # far below the 1000-pkt cap
    # RED trades a little utilization for its short queue (occasional
    # underrun after synchronized early drops) — 0.75 still means the
    # pipe is governed, not starved
    assert (util > 0.75).all(), util


def test_rfc3168_ecn_newreno_keeps_throughput():
    """NewReno + UseEcn over marking RED: one CE mark = one halving per
    window (r5 review regression: a fractional mark residue kept the
    loss response firing for hundreds of RTTs, collapsing cwnd)."""
    _reset()
    db, sinks = _red_dumbbell("TcpNewReno", use_ecn=True)
    Simulator.Stop(Seconds(SIM_S))
    Simulator.Run()
    host = sum(s.GetTotalRx() * 8.0 / (SIM_S - 0.1) / 1e6 for s in sinks)

    _reset()
    _red_dumbbell("TcpNewReno", use_ecn=True)
    prog = lower_dumbbell(SIM_S)
    assert prog.ecn.all() and prog.red_use_ecn
    out = run_tcp_dumbbell(prog, jax.random.PRNGKey(7), replicas=8)
    dev = float(np.asarray(out["goodput_mbps"]).sum(1).mean())
    drops = int(np.asarray(out["drops"]).sum())
    _reset()
    assert dev == pytest.approx(host, rel=0.25), (
        f"device {dev:.2f} vs host {host:.2f} Mbps"
    )
    assert drops <= 8, drops  # marking replaces dropping
