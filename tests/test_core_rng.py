"""RNG: MRG32k3a stream independence, run/substream selection,
distribution sanity (statistical, tolerance-based — mirroring upstream
random-variable-stream test suite; SURVEY.md 4)."""

import math

from tpudes.core.global_value import GlobalValue
from tpudes.core.rng import (
    BernoulliRandomVariable,
    ConstantRandomVariable,
    DeterministicRandomVariable,
    EmpiricalRandomVariable,
    ErlangRandomVariable,
    ExponentialRandomVariable,
    GammaRandomVariable,
    LogNormalRandomVariable,
    NormalRandomVariable,
    ParetoRandomVariable,
    RngSeedManager,
    RngStream,
    SequentialRandomVariable,
    TriangularRandomVariable,
    UniformRandomVariable,
    WeibullRandomVariable,
    ZipfRandomVariable,
)

N = 20000


def mean_of(rv, n=N):
    return sum(rv.GetValue() for _ in range(n)) / n


def test_rand_u01_range_and_determinism():
    a = RngStream(1, 0, 0)
    b = RngStream(1, 0, 0)
    va = [a.RandU01() for _ in range(1000)]
    vb = [b.RandU01() for _ in range(1000)]
    assert va == vb  # same position = bitwise identical
    assert all(0.0 <= v < 1.0 for v in va)


def test_streams_differ():
    a = RngStream(1, 0, 0)
    b = RngStream(1, 1, 0)
    c = RngStream(1, 0, 1)
    va = [a.RandU01() for _ in range(100)]
    vb = [b.RandU01() for _ in range(100)]
    vc = [c.RandU01() for _ in range(100)]
    assert va != vb and va != vc and vb != vc


def test_stream_jump_equals_iteration():
    # substream jump is 2^76 steps: statistically uncorrelated, and two
    # jumps from the same base must equal one double jump
    a = RngStream(1, 3, 4)
    b = RngStream(1, 3, 4)
    assert [a.RandU01() for _ in range(10)] == [b.RandU01() for _ in range(10)]


def test_run_number_selects_substream():
    RngSeedManager.SetRun(1)
    rv1 = UniformRandomVariable(Stream=5)
    v1 = [rv1.GetValue() for _ in range(50)]
    RngSeedManager.SetRun(2)
    rv2 = UniformRandomVariable(Stream=5)
    v2 = [rv2.GetValue() for _ in range(50)]
    assert v1 != v2
    # back to run 1 reproduces exactly (the replica reproducibility contract)
    RngSeedManager.SetRun(1)
    rv3 = UniformRandomVariable(Stream=5)
    assert [rv3.GetValue() for _ in range(50)] == v1


def test_auto_stream_allocation_unique():
    RngSeedManager.Reset()
    rvs = [UniformRandomVariable() for _ in range(5)]
    seqs = [[rv.GetValue() for _ in range(20)] for rv in rvs]
    for i in range(5):
        for j in range(i + 1, 5):
            assert seqs[i] != seqs[j]


def test_uniform_moments():
    rv = UniformRandomVariable(Min=2.0, Max=6.0, Stream=11)
    m = mean_of(rv)
    assert abs(m - 4.0) < 0.05
    assert all(2.0 <= rv.GetValue() < 6.0 for _ in range(1000))


def test_exponential_moments():
    rv = ExponentialRandomVariable(Mean=3.0, Stream=12)
    assert abs(mean_of(rv) - 3.0) < 0.1


def test_exponential_bound():
    rv = ExponentialRandomVariable(Mean=3.0, Bound=4.0, Stream=13)
    assert all(rv.GetValue() <= 4.0 for _ in range(2000))


def test_normal_moments():
    rv = NormalRandomVariable(Mean=5.0, Variance=4.0, Stream=14)
    vals = [rv.GetValue() for _ in range(N)]
    m = sum(vals) / N
    var = sum((v - m) ** 2 for v in vals) / N
    assert abs(m - 5.0) < 0.06
    assert abs(var - 4.0) < 0.15


def test_lognormal_moments():
    mu, sigma = 0.5, 0.4
    rv = LogNormalRandomVariable(Mu=mu, Sigma=sigma, Stream=15)
    expected = math.exp(mu + sigma**2 / 2)
    assert abs(mean_of(rv) - expected) < 0.05


def test_pareto_mean():
    rv = ParetoRandomVariable(Scale=1.0, Shape=3.0, Stream=16)
    assert abs(mean_of(rv) - 1.5) < 0.05  # alpha*xm/(alpha-1)


def test_weibull_mean():
    rv = WeibullRandomVariable(Scale=2.0, Shape=2.0, Stream=17)
    expected = 2.0 * math.gamma(1.5)
    assert abs(mean_of(rv) - expected) < 0.05


def test_gamma_mean():
    rv = GammaRandomVariable(Alpha=2.5, Beta=2.0, Stream=18)
    assert abs(mean_of(rv) - 5.0) < 0.12


def test_gamma_alpha_below_one():
    rv = GammaRandomVariable(Alpha=0.5, Beta=1.0, Stream=19)
    assert abs(mean_of(rv) - 0.5) < 0.05


def test_erlang_mean():
    rv = ErlangRandomVariable(K=3, Lambda=2.0, Stream=20)
    assert abs(mean_of(rv) - 1.5) < 0.05


def test_triangular_mean():
    rv = TriangularRandomVariable(Min=0.0, Max=1.0, Mean=0.5, Stream=21)
    assert abs(mean_of(rv) - 0.5) < 0.02


def test_constant_and_deterministic():
    assert ConstantRandomVariable(Constant=7.5).GetValue() == 7.5
    rv = DeterministicRandomVariable(values=[1, 2, 3])
    assert [rv.GetValue() for _ in range(5)] == [1, 2, 3, 1, 2]


def test_sequential():
    rv = SequentialRandomVariable(Min=0.0, Max=3.0, Increment=1.0, Consecutive=2)
    assert [rv.GetValue() for _ in range(8)] == [0, 0, 1, 1, 2, 2, 0, 0]


def test_bernoulli_mean():
    rv = BernoulliRandomVariable(Probability=0.3, Stream=22)
    assert abs(mean_of(rv) - 0.3) < 0.02


def test_zipf_support():
    rv = ZipfRandomVariable(N=5, Alpha=1.0, Stream=23)
    vals = {rv.GetValue() for _ in range(2000)}
    assert vals <= {1.0, 2.0, 3.0, 4.0, 5.0}
    assert 1.0 in vals


def test_empirical_interpolation():
    rv = EmpiricalRandomVariable(Interpolate=True, Stream=24)
    rv.CDF(0.0, 0.0)
    rv.CDF(10.0, 1.0)
    vals = [rv.GetValue() for _ in range(N)]
    assert all(0.0 <= v <= 10.0 for v in vals)
    assert abs(sum(vals) / N - 5.0) < 0.15


def test_global_rngrun_binding():
    GlobalValue.Bind("RngRun", 17)
    assert RngSeedManager.GetRun() == 17
