"""Propagation kernel validation vs float64 closed forms.

Mirrors upstream's propagation-loss-model-test-suite.cc approach:
analytic expected values, tolerance compares (SURVEY.md §4 — f32 vs f64
tolerance discipline)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudes.ops import propagation as P

C = 299792458.0


def test_friis_matches_closed_form():
    # expected values computed from the textbook formula at 5.15 GHz
    f = 5.15e9
    lam = C / f
    for d in [10.0, 100.0, 1000.0]:
        loss = -10 * math.log10(lam**2 / (16 * math.pi**2 * d**2))
        got = float(P.friis(jnp.float32(20.0), jnp.float32(d), f))
        assert got == pytest.approx(20.0 - loss, abs=1e-3)


def test_friis_zero_distance_clamps_to_min_loss():
    got = float(P.friis(jnp.float32(17.0), jnp.float32(0.0), min_loss_db=3.0))
    assert got == pytest.approx(14.0, abs=1e-5)


def test_log_distance_reference_point():
    # at d = d0 the loss is exactly the reference loss
    got = float(P.log_distance(jnp.float32(0.0), jnp.float32(1.0)))
    assert got == pytest.approx(-P.DEFAULT_REFERENCE_LOSS_DB, abs=1e-4)
    # one decade at exponent 3 adds 30 dB
    got10 = float(P.log_distance(jnp.float32(0.0), jnp.float32(10.0)))
    assert got10 == pytest.approx(-P.DEFAULT_REFERENCE_LOSS_DB - 30.0, abs=1e-3)


def test_three_log_distance_slopes():
    ref = P.DEFAULT_REFERENCE_LOSS_DB
    # inside first segment: only exponent0 active
    got = float(P.three_log_distance(jnp.float32(0.0), jnp.float32(100.0)))
    assert got == pytest.approx(-(ref + 19.0 * math.log10(100.0)), abs=1e-3)
    # beyond d2: all three slopes accumulate
    d = 1000.0
    expect = ref + 19.0 * math.log10(200.0) + 38.0 * math.log10(500.0 / 200.0) + 38.0 * math.log10(d / 500.0)
    got = float(P.three_log_distance(jnp.float32(0.0), jnp.float32(d)))
    assert got == pytest.approx(-expect, abs=1e-3)


def test_two_ray_ground_crossover_continuity_regions():
    f = 5.15e9
    lam = C / f
    ht = hr = 10.0
    crossover = 4 * math.pi * ht * hr / lam
    # far field: d^-4 law
    d = 4 * crossover
    expect = 10 * math.log10(ht**2 * hr**2 / d**4)
    got = float(P.two_ray_ground(jnp.float32(0.0), jnp.float32(d), ht, hr, f))
    assert got == pytest.approx(expect, abs=1e-3)
    # near field equals Friis
    d_near = crossover / 4
    got_near = float(P.two_ray_ground(jnp.float32(0.0), jnp.float32(d_near), ht, hr, f))
    friis = float(P.friis(jnp.float32(0.0), jnp.float32(d_near), f))
    assert got_near == pytest.approx(friis, abs=1e-4)


def test_range_loss_cuts_off():
    d = jnp.array([100.0, 250.0, 251.0])
    got = np.asarray(P.range_loss(jnp.float32(10.0), d, max_range=250.0))
    assert got[0] == pytest.approx(10.0)
    assert got[1] == pytest.approx(10.0)
    assert got[2] < -900.0


def test_nakagami_mean_preserves_power():
    # Gamma(m, P/m) has mean P: the fading is unit-mean by construction
    key = jax.random.PRNGKey(0)
    keys = jax.random.split(key, 4000)
    tx = jnp.float32(10.0)  # dBm → 10 mW
    draws = jax.vmap(lambda k: P.nakagami(k, tx, jnp.float32(50.0)))(keys)
    mean_w = float(jnp.mean(P.dbm_to_w(draws)))
    assert mean_w == pytest.approx(0.01, rel=0.05)


def test_pairwise_distance_and_delay():
    pos = jnp.array([[0.0, 0.0, 0.0], [3.0, 4.0, 0.0], [0.0, 0.0, 12.0]])
    d = np.asarray(P.pairwise_distance(pos))
    assert d[0, 1] == pytest.approx(5.0)
    assert d[0, 2] == pytest.approx(12.0)
    assert d[1, 1] == pytest.approx(0.0)
    delay = float(P.constant_speed_delay_s(jnp.float32(C)))
    assert delay == pytest.approx(1.0)


def test_models_are_jit_and_vmap_compatible():
    d = jnp.linspace(1.0, 500.0, 64)
    fn = jax.jit(lambda dd: P.log_distance(16.0, dd))
    out = fn(d)
    assert out.shape == (64,)
    # batched over a replica axis of keys
    keys = jax.random.split(jax.random.PRNGKey(1), 8)
    out = jax.vmap(lambda k: P.nakagami(k, 16.0, d))(keys)
    assert out.shape == (8, 64)
    assert bool(jnp.all(jnp.isfinite(out)))


def test_chain_composition():
    composed = P.chain(
        lambda tx, d: P.log_distance(tx, d),
        lambda tx, d: tx - 2.0,  # constant extra loss stage
    )
    base = float(P.log_distance(jnp.float32(5.0), jnp.float32(42.0)))
    got = float(composed(jnp.float32(5.0), jnp.float32(42.0)))
    assert got == pytest.approx(base - 2.0, abs=1e-5)


def test_okumura_hata_monotone_in_distance():
    d = jnp.array([200.0, 500.0, 1000.0, 5000.0])
    rx = np.asarray(P.okumura_hata(jnp.float32(43.0), d))
    assert np.all(np.diff(rx) < 0)


def test_cost231_hata_small_vs_large_city():
    rx_small = float(P.cost231_hata(jnp.float32(43.0), jnp.float32(1000.0)))
    rx_large = float(P.cost231_hata(jnp.float32(43.0), jnp.float32(1000.0), large_city=True))
    assert rx_large < rx_small  # large-city correction adds loss at 2 GHz
