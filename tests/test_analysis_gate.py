"""Tier-1 CI gate: ``python -m tpudes.analysis`` over the repo must be
clean against tools/analysis_baseline.json, and the gate must actually
bite — a file with a true positive exits nonzero.

Runs inside the normal pytest tier-1 command, no extra CI wiring.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _run(*args, cwd=REPO):
    # PYTHONPATH keeps tpudes importable when cwd is not the repo root
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "tpudes.analysis", *args],
        cwd=cwd, capture_output=True, text=True, timeout=300, env=env,
    )


def test_repo_clean_against_baseline():
    proc = _run()
    assert proc.returncode == 0, (
        "new analysis findings (fix them or, for pre-existing debt, "
        "re-baseline with --write-baseline):\n"
        + proc.stdout + proc.stderr
    )


def test_true_positive_file_fails_the_gate(tmp_path):
    bad = tmp_path / "bad_model.py"
    bad.write_text(
        "from tpudes.core.simulator import Simulator\n"
        "\n"
        "def arm(devices):\n"
        "    backlog = set(devices)\n"
        "    for dev in backlog:\n"
        "        Simulator.Schedule(1, dev.poll)\n"
    )
    proc = _run(str(bad))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "DET001" in proc.stdout


def test_json_output_is_machine_readable(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("try:\n    pass\nexcept:\n    pass\n")
    proc = _run(str(bad), "--json")
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["findings"][0]["code"] == "LNT005"


def test_list_rules_covers_every_pass():
    proc = _run("--list-rules")
    assert proc.returncode == 0
    for code in ("JP001", "RNG001", "DET001", "EVT001", "REG001", "LNT001",
                 "TRC001"):
        assert code in proc.stdout


def test_baseline_file_is_wellformed():
    data = json.loads((REPO / "tools" / "analysis_baseline.json").read_text())
    assert data["version"] == 1
    assert all(
        isinstance(v, int) and v > 0 for v in data["counts"].values()
    )


def test_lint_shim_still_gates():
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint.py")],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_subtree_scan_honors_the_baseline():
    # all 15 baselined findings live under tpudes/, and baseline keys
    # are root-relative — an explicit-path scan from the repo root must
    # not report frozen debt as new
    proc = _run("tpudes")
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_misspelled_path_is_an_error_not_a_green_gate():
    proc = _run("tpudes/modles")
    assert proc.returncode == 2
    assert "no such file" in proc.stderr


def test_write_baseline_refuses_narrowed_runs(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("try:\n    pass\nexcept:\n    pass\n")
    before = (REPO / "tools" / "analysis_baseline.json").read_text()
    for narrowed in ([str(bad), "--write-baseline"],
                     ["--select", "LNT", "--write-baseline"]):
        proc = _run(*narrowed)
        assert proc.returncode == 2, proc.stdout + proc.stderr
    assert (REPO / "tools" / "analysis_baseline.json").read_text() == before


def test_missing_default_roots_is_an_error_not_a_green_gate(tmp_path):
    proc = _run(cwd=tmp_path)
    assert proc.returncode == 2
    assert "default roots" in proc.stderr
