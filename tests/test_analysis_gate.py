"""Tier-1 CI gate: ``python -m tpudes.analysis`` over the repo must be
clean against tools/analysis_baseline.json, and the gate must actually
bite — a file with a true positive exits nonzero.

Runs inside the normal pytest tier-1 command, no extra CI wiring.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _run(*args, cwd=REPO):
    # PYTHONPATH keeps tpudes importable when cwd is not the repo root
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "tpudes.analysis", *args],
        cwd=cwd, capture_output=True, text=True, timeout=300, env=env,
    )


def test_repo_clean_against_baseline():
    proc = _run()
    assert proc.returncode == 0, (
        "new analysis findings (fix them or, for pre-existing debt, "
        "re-baseline with --write-baseline):\n"
        + proc.stdout + proc.stderr
    )


def test_true_positive_file_fails_the_gate(tmp_path):
    bad = tmp_path / "bad_model.py"
    bad.write_text(
        "from tpudes.core.simulator import Simulator\n"
        "\n"
        "def arm(devices):\n"
        "    backlog = set(devices)\n"
        "    for dev in backlog:\n"
        "        Simulator.Schedule(1, dev.poll)\n"
    )
    proc = _run(str(bad))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "DET001" in proc.stdout


def test_json_output_is_machine_readable(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("try:\n    pass\nexcept:\n    pass\n")
    proc = _run(str(bad), "--json")
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["findings"][0]["code"] == "LNT005"


def test_list_rules_covers_every_pass():
    proc = _run("--list-rules")
    assert proc.returncode == 0
    for code in ("JP001", "RNG001", "DET001", "EVT001", "REG001", "LNT001",
                 "TRC001", "KEY001", "JXL001", "JXL002", "JXL003", "JXL004",
                 "JXL005", "JXL006", "JXL007", "JXL008"):
        assert code in proc.stdout


def test_baseline_file_is_wellformed():
    data = json.loads((REPO / "tools" / "analysis_baseline.json").read_text())
    assert data["version"] == 1
    assert all(
        isinstance(v, int) and v > 0 for v in data["counts"].values()
    )


def test_lint_shim_still_gates():
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint.py")],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_subtree_scan_honors_the_baseline():
    # all 15 baselined findings live under tpudes/, and baseline keys
    # are root-relative — an explicit-path scan from the repo root must
    # not report frozen debt as new
    proc = _run("tpudes")
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_misspelled_path_is_an_error_not_a_green_gate():
    proc = _run("tpudes/modles")
    assert proc.returncode == 2
    assert "no such file" in proc.stderr


def test_write_baseline_refuses_narrowed_runs(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("try:\n    pass\nexcept:\n    pass\n")
    before = (REPO / "tools" / "analysis_baseline.json").read_text()
    for narrowed in ([str(bad), "--write-baseline"],
                     ["--select", "LNT", "--write-baseline"]):
        proc = _run(*narrowed)
        assert proc.returncode == 2, proc.stdout + proc.stderr
    assert (REPO / "tools" / "analysis_baseline.json").read_text() == before


def test_missing_default_roots_is_an_error_not_a_green_gate(tmp_path):
    proc = _run(cwd=tmp_path)
    assert proc.returncode == 2
    assert "default roots" in proc.stderr


def test_jaxpr_gate_is_clean_against_baseline():
    """ISSUE-12 acceptance: the trace manifests cover every device
    engine and the JXL pass family reports zero unbaselined findings
    (the four by-design wired egress-donation entries live in the
    baseline)."""
    proc = _run("--jaxpr")
    assert proc.returncode == 0, (
        "new jaxpr-analysis findings (fix them or, for structural "
        "debt, re-baseline with --jaxpr --write-baseline):\n"
        + proc.stdout + proc.stderr
    )


def test_jaxpr_flag_composes_with_select_and_json():
    # --select JXL005 --no-baseline must surface exactly the known
    # egress-donation findings, machine-readably
    proc = _run("--jaxpr", "--select", "JXL005", "--no-baseline",
                "--json")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    codes = {f["code"] for f in payload["findings"]}
    paths = {f["path"] for f in payload["findings"]}
    assert codes == {"JXL005"}
    assert paths == {
        "tpudes/parallel/wired.py", "tpudes/parallel/hybrid.py",
    }


def test_sarif_output_is_schema_shaped(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("try:\n    pass\nexcept:\n    pass\n")
    proc = _run(str(bad), "--format", "sarif", "--no-baseline")
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    # the minimal SARIF 2.1.0 profile GitHub code scanning ingests
    assert doc["version"] == "2.1.0"
    assert doc["$schema"].endswith("sarif-schema-2.1.0.json")
    run = doc["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"] == "tpudes-analysis"
    rule_ids = [r["id"] for r in driver["rules"]]
    assert rule_ids == sorted(rule_ids) and len(set(rule_ids)) == len(rule_ids)
    for r in driver["rules"]:
        assert r["shortDescription"]["text"]
    # the driver advertises the full rule set, jaxpr family included
    assert {"LNT005", "KEY001", "JXL001", "JXL005"} <= set(rule_ids)
    assert run["results"], "the planted LNT005 must appear as a result"
    for res in run["results"]:
        assert res["ruleId"] in rule_ids
        assert rule_ids[res["ruleIndex"]] == res["ruleId"]
        assert res["level"] == "error"
        assert res["message"]["text"]
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"]
        assert loc["region"]["startLine"] >= 1
        assert loc["region"]["startColumn"] >= 1


def test_ast_cache_cold_then_warm(tmp_path):
    """The per-file content-hash cache: a warm run re-parses nothing,
    reports full hits, produces identical findings, and is measurably
    faster than the cold run that populated the cache."""
    cache = tmp_path / "cache.json"
    cold = _run("--json", "--cache", str(cache))
    assert cold.returncode == 0, cold.stdout + cold.stderr
    cold_payload = json.loads(cold.stdout)
    assert cold_payload["cache"]["hits"] == 0
    assert cold_payload["cache"]["misses"] > 100
    assert cache.exists()

    warm = _run("--json", "--cache", str(cache))
    assert warm.returncode == 0, warm.stdout + warm.stderr
    warm_payload = json.loads(warm.stdout)
    assert warm_payload["cache"]["misses"] == 0
    assert warm_payload["cache"]["hits"] == cold_payload["cache"]["misses"]
    assert warm_payload["findings"] == cold_payload["findings"]
    assert warm_payload["baselined"] == cold_payload["baselined"]
    # the whole point: the analysis phase collapses (cold runs every
    # pass over ~200 files, warm only hashes them)
    assert warm_payload["elapsed_s"] < cold_payload["elapsed_s"]


def test_ast_cache_invalidates_on_content_change(tmp_path):
    """A findings-relevant edit must not be masked by the cache."""
    import shutil

    proj = tmp_path / "proj"
    (proj / "tpudes").mkdir(parents=True)
    (proj / "tpudes" / "mod.py").write_text("x = 1\n")
    shutil.copytree(REPO / "tpudes" / "analysis",
                    proj / "tpudes" / "analysis")
    cache = tmp_path / "cache.json"
    first = _run("--json", "--cache", str(cache), "--no-baseline",
                 cwd=proj)
    assert first.returncode == 0, first.stdout + first.stderr
    (proj / "tpudes" / "mod.py").write_text(
        "try:\n    pass\nexcept:\n    pass\n"
    )
    second = _run("--json", "--cache", str(cache), "--no-baseline",
                  cwd=proj)
    payload = json.loads(second.stdout)
    assert second.returncode == 1
    assert any(f["code"] == "LNT005" for f in payload["findings"])


def _tiny_proj(tmp_path):
    proj = tmp_path / "proj"
    (proj / "tpudes").mkdir(parents=True)
    (proj / "tpudes" / "mod.py").write_text("x = 1\n")
    (proj / "tests").mkdir()
    (proj / "tests" / "t.py").write_text("y = 2\n")
    return proj


def _collect(proj):
    from tpudes.analysis.engine import collect_modules

    return collect_modules([proj / "tpudes", proj / "tests"], proj)


def test_jaxpr_cache_key_tracks_modules_rules_and_tracer(
    tmp_path, monkeypatch
):
    """ISSUE-16 satellite: the jaxpr section's key must move when a
    traced tpudes/ module, the JXL pass family, or the jax install
    changes — and must NOT move on test-file edits (retracing every
    manifest because a test changed would make the cache useless)."""
    from tpudes.analysis import cache as C

    proj = _tiny_proj(tmp_path)
    sha0 = C.AnalysisCache.jaxpr_sha(_collect(proj))

    (proj / "tests" / "t.py").write_text("y = 3\n")
    assert C.AnalysisCache.jaxpr_sha(_collect(proj)) == sha0

    (proj / "tpudes" / "mod.py").write_text("x = 2\n")
    sha1 = C.AnalysisCache.jaxpr_sha(_collect(proj))
    assert sha1 != sha0

    monkeypatch.setattr(C, "_jaxpr_rules_fp", "0" * 64)
    assert C.AnalysisCache.jaxpr_sha(_collect(proj)) != sha1
    monkeypatch.undo()

    monkeypatch.setattr(C, "_jax_version", lambda: "999.0")
    assert C.AnalysisCache.jaxpr_sha(_collect(proj)) != sha1


def test_jaxpr_cache_section_roundtrips_and_resets_with_store(tmp_path):
    from tpudes.analysis.base import Finding
    from tpudes.analysis.cache import CACHE_VERSION, AnalysisCache

    path = tmp_path / "cache.json"
    cache = AnalysisCache(path)
    f = Finding("tpudes/parallel/wired.py", 9, 1, "JXL007", "quadratic")
    cache.put_jaxpr("abc", [f])
    cache.save()

    again = AnalysisCache(path)
    served = again.get_jaxpr("abc")
    assert served is not None and served[0].to_json() == f.to_json()
    assert again.get_jaxpr("other-key") is None

    # a rules-fingerprint mismatch drops the jaxpr section with the
    # rest of the store
    data = json.loads(path.read_text())
    data["rules"] = "stale"
    assert data["version"] == CACHE_VERSION
    path.write_text(json.dumps(data))
    assert AnalysisCache(path).get_jaxpr("abc") is None


def test_engine_serves_and_invalidates_cached_jaxpr_findings(
    tmp_path, monkeypatch
):
    """Cold run executes the JXL family and stores the findings; warm
    run serves them without re-running; a tpudes/ edit re-runs; a
    narrowed (--select) cold run never writes."""
    import tpudes.analysis.jaxpr as jx
    from tpudes.analysis import engine
    from tpudes.analysis.base import Finding
    from tpudes.analysis.cache import AnalysisCache

    calls = []

    class StubJaxprPass:
        name = "stub-jaxpr"
        codes = {"JXL999": "stub rule"}
        project_wide = True

        def check_project(self, mods):
            calls.append(1)
            return [Finding("tpudes/mod.py", 1, 1, "JXL999", "stub")]

    monkeypatch.setattr(jx, "JAXPR_PASSES", (StubJaxprPass,))
    proj = _tiny_proj(tmp_path)

    def run(cache, **kw):
        out = engine.run_passes(_collect(proj), jaxpr=True, cache=cache,
                                **kw)
        return [f for f in out if f.code == "JXL999"]

    cache = AnalysisCache(tmp_path / "cache.json")
    assert len(run(cache)) == 1 and len(calls) == 1
    cache.save()

    warm = AnalysisCache(tmp_path / "cache.json")
    assert len(run(warm)) == 1
    assert len(calls) == 1, "warm run must serve, not re-trace"

    # selection narrows the output but still reads the cached set
    assert run(warm, select=["LNT"]) == []
    assert len(run(warm, select=["JXL"])) == 1
    assert len(calls) == 1

    (proj / "tpudes" / "mod.py").write_text("x = 2\n")
    assert len(run(warm)) == 1
    assert len(calls) == 2, "a tpudes/ edit must invalidate"

    # a narrowed COLD run re-traces but must not poison the store
    cold = AnalysisCache(tmp_path / "cache2.json")
    assert len(run(cold, select=["JXL"])) == 1
    assert len(calls) == 3
    cold.save()
    assert not (tmp_path / "cache2.json").exists()


def test_jaxpr_warm_cache_analysis_is_subsecond():
    """ISSUE-16 satellite: CI reruns the --jaxpr gate between rounds;
    with the default cache warm it must answer in under a second (no
    jax import, no manifest tracing).  The first run warms the cache
    when a fresh checkout arrives cold."""
    _run("--jaxpr")
    warm = _run("--jaxpr", "--json")
    assert warm.returncode == 0, warm.stdout + warm.stderr
    payload = json.loads(warm.stdout)
    assert payload["elapsed_s"] < 1.0, payload["elapsed_s"]


def test_cost_requires_jaxpr():
    proc = _run("--cost")
    assert proc.returncode == 2
    assert "--jaxpr" in proc.stderr


@pytest.mark.slow
def test_cost_report_cli_end_to_end(tmp_path):
    """``--jaxpr --cost``: full-repo scale report with the wired
    worklist, plus the JSON artifact CI uploads."""
    out = tmp_path / "cost.json"
    proc = _run("--jaxpr", "--cost", "--cost-out", str(out))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OVER BUDGET" in proc.stdout
    assert "ROADMAP item 2" in proc.stdout
    report = json.loads(out.read_text())
    assert report["projection_nodes"] == [100000, 1000000]
    assert "wired/advance:n_nodes" in report["worklist"]
    assert "wired_space/advance:n_nodes" in report["worklist"]
    by_axis = {
        (r["engine"], r["axis"]): r for r in report["entries"]
    }
    wired_row = by_axis[("wired", "n_nodes")]
    assert wired_row["mem_exponent"] >= 1.99
    assert wired_row["projected"]["1e6_nodes"]["bytes"] > 0


def test_write_baseline_without_jaxpr_refuses_to_drop_jxl_entries():
    # the ratchet holds JXL trace findings; a plain --write-baseline
    # would silently delete them and break the --jaxpr gate later
    before = (REPO / "tools" / "analysis_baseline.json").read_text()
    proc = _run("--write-baseline")
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "--jaxpr" in proc.stderr
    assert (REPO / "tools" / "analysis_baseline.json").read_text() == before
