"""Tier-1 CI gate: ``python -m tpudes.analysis`` over the repo must be
clean against tools/analysis_baseline.json, and the gate must actually
bite — a file with a true positive exits nonzero.

Runs inside the normal pytest tier-1 command, no extra CI wiring.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _run(*args, cwd=REPO):
    # PYTHONPATH keeps tpudes importable when cwd is not the repo root
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "tpudes.analysis", *args],
        cwd=cwd, capture_output=True, text=True, timeout=300, env=env,
    )


def test_repo_clean_against_baseline():
    proc = _run()
    assert proc.returncode == 0, (
        "new analysis findings (fix them or, for pre-existing debt, "
        "re-baseline with --write-baseline):\n"
        + proc.stdout + proc.stderr
    )


def test_true_positive_file_fails_the_gate(tmp_path):
    bad = tmp_path / "bad_model.py"
    bad.write_text(
        "from tpudes.core.simulator import Simulator\n"
        "\n"
        "def arm(devices):\n"
        "    backlog = set(devices)\n"
        "    for dev in backlog:\n"
        "        Simulator.Schedule(1, dev.poll)\n"
    )
    proc = _run(str(bad))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "DET001" in proc.stdout


def test_json_output_is_machine_readable(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("try:\n    pass\nexcept:\n    pass\n")
    proc = _run(str(bad), "--json")
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["findings"][0]["code"] == "LNT005"


def test_list_rules_covers_every_pass():
    proc = _run("--list-rules")
    assert proc.returncode == 0
    for code in ("JP001", "RNG001", "DET001", "EVT001", "REG001", "LNT001",
                 "TRC001", "KEY001", "JXL001", "JXL002", "JXL003", "JXL004",
                 "JXL005"):
        assert code in proc.stdout


def test_baseline_file_is_wellformed():
    data = json.loads((REPO / "tools" / "analysis_baseline.json").read_text())
    assert data["version"] == 1
    assert all(
        isinstance(v, int) and v > 0 for v in data["counts"].values()
    )


def test_lint_shim_still_gates():
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint.py")],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_subtree_scan_honors_the_baseline():
    # all 15 baselined findings live under tpudes/, and baseline keys
    # are root-relative — an explicit-path scan from the repo root must
    # not report frozen debt as new
    proc = _run("tpudes")
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_misspelled_path_is_an_error_not_a_green_gate():
    proc = _run("tpudes/modles")
    assert proc.returncode == 2
    assert "no such file" in proc.stderr


def test_write_baseline_refuses_narrowed_runs(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("try:\n    pass\nexcept:\n    pass\n")
    before = (REPO / "tools" / "analysis_baseline.json").read_text()
    for narrowed in ([str(bad), "--write-baseline"],
                     ["--select", "LNT", "--write-baseline"]):
        proc = _run(*narrowed)
        assert proc.returncode == 2, proc.stdout + proc.stderr
    assert (REPO / "tools" / "analysis_baseline.json").read_text() == before


def test_missing_default_roots_is_an_error_not_a_green_gate(tmp_path):
    proc = _run(cwd=tmp_path)
    assert proc.returncode == 2
    assert "default roots" in proc.stderr


def test_jaxpr_gate_is_clean_against_baseline():
    """ISSUE-12 acceptance: the trace manifests cover every device
    engine and the JXL pass family reports zero unbaselined findings
    (the four by-design wired egress-donation entries live in the
    baseline)."""
    proc = _run("--jaxpr")
    assert proc.returncode == 0, (
        "new jaxpr-analysis findings (fix them or, for structural "
        "debt, re-baseline with --jaxpr --write-baseline):\n"
        + proc.stdout + proc.stderr
    )


def test_jaxpr_flag_composes_with_select_and_json():
    # --select JXL005 --no-baseline must surface exactly the known
    # egress-donation findings, machine-readably
    proc = _run("--jaxpr", "--select", "JXL005", "--no-baseline",
                "--json")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    codes = {f["code"] for f in payload["findings"]}
    paths = {f["path"] for f in payload["findings"]}
    assert codes == {"JXL005"}
    assert paths == {
        "tpudes/parallel/wired.py", "tpudes/parallel/hybrid.py",
    }


def test_sarif_output_is_schema_shaped(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("try:\n    pass\nexcept:\n    pass\n")
    proc = _run(str(bad), "--format", "sarif", "--no-baseline")
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    # the minimal SARIF 2.1.0 profile GitHub code scanning ingests
    assert doc["version"] == "2.1.0"
    assert doc["$schema"].endswith("sarif-schema-2.1.0.json")
    run = doc["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"] == "tpudes-analysis"
    rule_ids = [r["id"] for r in driver["rules"]]
    assert rule_ids == sorted(rule_ids) and len(set(rule_ids)) == len(rule_ids)
    for r in driver["rules"]:
        assert r["shortDescription"]["text"]
    # the driver advertises the full rule set, jaxpr family included
    assert {"LNT005", "KEY001", "JXL001", "JXL005"} <= set(rule_ids)
    assert run["results"], "the planted LNT005 must appear as a result"
    for res in run["results"]:
        assert res["ruleId"] in rule_ids
        assert rule_ids[res["ruleIndex"]] == res["ruleId"]
        assert res["level"] == "error"
        assert res["message"]["text"]
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"]
        assert loc["region"]["startLine"] >= 1
        assert loc["region"]["startColumn"] >= 1


def test_ast_cache_cold_then_warm(tmp_path):
    """The per-file content-hash cache: a warm run re-parses nothing,
    reports full hits, produces identical findings, and is measurably
    faster than the cold run that populated the cache."""
    cache = tmp_path / "cache.json"
    cold = _run("--json", "--cache", str(cache))
    assert cold.returncode == 0, cold.stdout + cold.stderr
    cold_payload = json.loads(cold.stdout)
    assert cold_payload["cache"]["hits"] == 0
    assert cold_payload["cache"]["misses"] > 100
    assert cache.exists()

    warm = _run("--json", "--cache", str(cache))
    assert warm.returncode == 0, warm.stdout + warm.stderr
    warm_payload = json.loads(warm.stdout)
    assert warm_payload["cache"]["misses"] == 0
    assert warm_payload["cache"]["hits"] == cold_payload["cache"]["misses"]
    assert warm_payload["findings"] == cold_payload["findings"]
    assert warm_payload["baselined"] == cold_payload["baselined"]
    # the whole point: the analysis phase collapses (cold runs every
    # pass over ~200 files, warm only hashes them)
    assert warm_payload["elapsed_s"] < cold_payload["elapsed_s"]


def test_ast_cache_invalidates_on_content_change(tmp_path):
    """A findings-relevant edit must not be masked by the cache."""
    import shutil

    proj = tmp_path / "proj"
    (proj / "tpudes").mkdir(parents=True)
    (proj / "tpudes" / "mod.py").write_text("x = 1\n")
    shutil.copytree(REPO / "tpudes" / "analysis",
                    proj / "tpudes" / "analysis")
    cache = tmp_path / "cache.json"
    first = _run("--json", "--cache", str(cache), "--no-baseline",
                 cwd=proj)
    assert first.returncode == 0, first.stdout + first.stderr
    (proj / "tpudes" / "mod.py").write_text(
        "try:\n    pass\nexcept:\n    pass\n"
    )
    second = _run("--json", "--cache", str(cache), "--no-baseline",
                  cwd=proj)
    payload = json.loads(second.stdout)
    assert second.returncode == 1
    assert any(f["code"] == "LNT005" for f in payload["findings"])


def test_write_baseline_without_jaxpr_refuses_to_drop_jxl_entries():
    # the ratchet holds JXL trace findings; a plain --write-baseline
    # would silently delete them and break the --jaxpr gate later
    before = (REPO / "tools" / "analysis_baseline.json").read_text()
    proc = _run("--write-baseline")
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "--jaxpr" in proc.stderr
    assert (REPO / "tools" / "analysis_baseline.json").read_text() == before
