"""IPv4 fragmentation/reassembly (VERDICT r4 missing #5).

Upstream analog: src/internet/test/ipv4-fragmentation-test.cc strategy —
a datagram larger than the egress MTU must cross the wire as real
offset/MF fragments and reassemble only at the final destination; DF
forbids it; a lost fragment kills the datagram; a smaller second hop
re-fragments.
"""


from tpudes.core import Seconds, Simulator
from tpudes.helper.applications import UdpEchoClientHelper, UdpEchoServerHelper
from tpudes.helper.containers import NodeContainer
from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
from tpudes.helper.point_to_point import PointToPointHelper
from tpudes.models.internet.ipv4 import Ipv4Header, Ipv4L3Protocol


def _reset():
    from tpudes.core.world import reset_world

    reset_world()


def _pair(mtu=600):
    nodes = NodeContainer()
    nodes.Create(2)
    p2p = PointToPointHelper()
    p2p.SetDeviceAttribute("DataRate", "10Mbps")
    p2p.SetChannelAttribute("Delay", "1ms")
    devices = p2p.Install(nodes)
    for i in range(2):
        devices.Get(i).SetMtu(mtu)
    InternetStackHelper().Install(nodes)
    ifc = Ipv4AddressHelper("10.1.1.0", "255.255.255.0").Assign(devices)
    return nodes, devices, ifc


def test_large_datagram_fragments_and_reassembles():
    _reset()
    nodes, devices, ifc = _pair(mtu=600)
    frames = []
    devices.Get(0).TraceConnectWithoutContext(
        "PhyTxEnd", lambda pkt, *a: frames.append(pkt)
    )
    server = UdpEchoServerHelper(9)
    sapps = server.Install(nodes.Get(1))
    sapps.Start(Seconds(0.1))
    client = UdpEchoClientHelper(ifc.GetAddress(1), 9)
    client.SetAttribute("MaxPackets", 1)
    client.SetAttribute("PacketSize", 2000)  # 2028 B IP datagram
    capps = client.Install(nodes.Get(0))
    capps.Start(Seconds(0.5))
    Simulator.Stop(Seconds(2.0))
    Simulator.Run()
    assert sapps.Get(0).received == 1
    assert capps.Get(0).received == 1  # the echo reply fragments too
    # the wire carried real fragments: offsets tile [0, 2008)
    heads = [p.FindHeader(Ipv4Header) for p in frames]
    heads = [h for h in heads if h is not None and h.protocol == 17]
    assert len(heads) == 4  # 2008 payload bytes / 576 B 8-aligned chunks
    offs = sorted((h.fragment_offset, h.payload_size, h.more_fragments)
                  for h in heads)
    covered = 0
    for off, size, mf in offs:
        assert off == covered, offs
        assert off % 8 == 0
        covered = off + size
    assert covered == 2000 + 8  # UDP payload + UDP header
    assert offs[-1][2] is False and all(mf for _, _, mf in offs[:-1])
    _reset()


def test_lost_fragment_kills_the_datagram():
    from tpudes.network.error_model import ReceiveListErrorModel

    _reset()
    nodes, devices, ifc = _pair(mtu=600)
    em = ReceiveListErrorModel()
    em.SetList([1])  # second frame to arrive at the server = a fragment
    devices.Get(1).SetReceiveErrorModel(em)
    server = UdpEchoServerHelper(9)
    sapps = server.Install(nodes.Get(1))
    sapps.Start(Seconds(0.1))
    client = UdpEchoClientHelper(ifc.GetAddress(1), 9)
    client.SetAttribute("MaxPackets", 1)
    client.SetAttribute("PacketSize", 2000)
    client.Install(nodes.Get(0)).Start(Seconds(0.5))
    drops = []
    nodes.Get(1).GetObject(Ipv4L3Protocol).TraceConnectWithoutContext(
        "Drop", lambda h, p, r: drops.append(r)
    )
    Simulator.Stop(Seconds(40.0))  # past the 30 s reassembly timeout
    Simulator.Run()
    assert sapps.Get(0).received == 0
    assert Ipv4L3Protocol.DROP_FRAGMENT_TIMEOUT in drops
    _reset()


def test_refragmentation_across_smaller_second_hop():
    """n0 --1500-- r --400-- n1: the router re-fragments."""
    _reset()
    nodes = NodeContainer()
    nodes.Create(3)
    p2p = PointToPointHelper()
    p2p.SetDeviceAttribute("DataRate", "10Mbps")
    p2p.SetChannelAttribute("Delay", "1ms")
    d01 = p2p.Install(nodes.Get(0), nodes.Get(1))
    d12 = p2p.Install(nodes.Get(1), nodes.Get(2))
    for i in range(2):
        d12.Get(i).SetMtu(400)
    InternetStackHelper().Install(nodes)
    a = Ipv4AddressHelper("10.1.1.0", "255.255.255.0")
    i01 = a.Assign(d01)
    a.SetBase("10.1.2.0", "255.255.255.0")
    i12 = a.Assign(d12)
    from tpudes.models.internet.ipv4 import Ipv4StaticRouting

    r0 = nodes.Get(0).GetObject(Ipv4L3Protocol).GetRoutingProtocol()
    r0.SetDefaultRoute(i01.GetAddress(1), 1)
    r2 = nodes.Get(2).GetObject(Ipv4L3Protocol).GetRoutingProtocol()
    r2.SetDefaultRoute(i12.GetAddress(0), 1)

    server = UdpEchoServerHelper(9)
    sapps = server.Install(nodes.Get(2))
    sapps.Start(Seconds(0.1))
    client = UdpEchoClientHelper(i12.GetAddress(1), 9)
    client.SetAttribute("MaxPackets", 2)
    client.SetAttribute("Interval", Seconds(0.2))
    client.SetAttribute("PacketSize", 1200)
    capps = client.Install(nodes.Get(0))
    capps.Start(Seconds(0.5))
    Simulator.Stop(Seconds(3.0))
    Simulator.Run()
    assert sapps.Get(0).received == 2
    assert capps.Get(0).received == 2
    _reset()


def test_double_fragmentation_600_then_400():
    """Both hops fragment (600 then 400): the router re-fragments
    NON-first fragments, which must never overwrite the reassembler's
    original-packet tag (r5 review regression)."""
    _reset()
    nodes = NodeContainer()
    nodes.Create(3)
    p2p = PointToPointHelper()
    p2p.SetDeviceAttribute("DataRate", "10Mbps")
    p2p.SetChannelAttribute("Delay", "1ms")
    d01 = p2p.Install(nodes.Get(0), nodes.Get(1))
    d12 = p2p.Install(nodes.Get(1), nodes.Get(2))
    for i in range(2):
        d01.Get(i).SetMtu(600)
        d12.Get(i).SetMtu(400)
    InternetStackHelper().Install(nodes)
    a = Ipv4AddressHelper("10.1.1.0", "255.255.255.0")
    i01 = a.Assign(d01)
    a.SetBase("10.1.2.0", "255.255.255.0")
    i12 = a.Assign(d12)
    from tpudes.models.internet.ipv4 import Ipv4StaticRouting

    nodes.Get(0).GetObject(Ipv4L3Protocol).GetRoutingProtocol(
    ).SetDefaultRoute(i01.GetAddress(1), 1)
    nodes.Get(2).GetObject(Ipv4L3Protocol).GetRoutingProtocol(
    ).SetDefaultRoute(i12.GetAddress(0), 1)

    got = []
    server = UdpEchoServerHelper(9)
    sapps = server.Install(nodes.Get(2))
    sapps.Start(Seconds(0.1))
    sapps.Get(0).TraceConnectWithoutContext(
        "Rx", lambda pkt, *a: got.append(pkt.GetSize())
    )
    client = UdpEchoClientHelper(i12.GetAddress(1), 9)
    client.SetAttribute("MaxPackets", 1)
    client.SetAttribute("PacketSize", 2000)
    capps = client.Install(nodes.Get(0))
    capps.Start(Seconds(0.5))
    Simulator.Stop(Seconds(3.0))
    Simulator.Run()
    # delivered intact: the full 2000 B application payload
    assert got == [2000], got
    assert capps.Get(0).received == 1
    _reset()


def test_df_forbids_fragmentation_and_drops():
    _reset()
    nodes, devices, ifc = _pair(mtu=600)
    l3 = nodes.Get(0).GetObject(Ipv4L3Protocol)
    drops = []
    l3.TraceConnectWithoutContext("Drop", lambda h, p, r: drops.append(r))
    from tpudes.network.packet import Packet

    header = Ipv4Header(
        source=ifc.GetAddress(0), destination=ifc.GetAddress(1),
        protocol=17, payload_size=1500,
    )
    header.dont_fragment = True
    ok = l3._fragment_and_send(
        l3.GetInterface(1), Packet(1500), header, None, 1
    )
    assert ok is False
    assert Ipv4L3Protocol.DROP_FRAGMENT_DF in drops
    _reset()


def test_fragment_wire_bits_roundtrip():
    h = Ipv4Header(protocol=17, payload_size=480)
    h.more_fragments = True
    h.fragment_offset = 1480
    h2, n = Ipv4Header.Deserialize(h.Serialize())
    assert n == 20
    assert h2.more_fragments and h2.fragment_offset == 1480
    assert not h2.dont_fragment
