"""ISSUE 8 gates: the differential fuzzing subsystem.

- **Seed determinism**: a corpus entry is ONE integer — the same seed
  always derives the same in-envelope config, different seeds differ.
- **Corpus replay**: every ``tests/fuzz_corpus/`` entry (bucketing
  pads, chunk boundaries, sweep demux — 3 per engine) replays clean,
  deterministically, through the real oracle-pair machinery.
- **Planted bug end-to-end**: with ``TPUDES_FUZZ_PLANTED_BUG=1`` the
  scalar-vs-chunked oracle detects the deliberate dumbbell divergence,
  the shrinker reduces it to <= 2 replicas and <= 32 slots, the
  artifact round-trips, and ``replay`` reproduces the diff
  bit-identically.
- **Telemetry**: campaign counters pass the ``--fuzz`` schema gate.
"""

import json
from pathlib import Path

import pytest

CORPUS_DIR = Path(__file__).parent / "fuzz_corpus"


# --- seeded generation ----------------------------------------------------


def test_seed_derives_identical_configs():
    from tpudes.fuzz import scenario_config
    from tpudes.fuzz.engines import ENGINE_FUZZERS

    for eng in ENGINE_FUZZERS:
        a = scenario_config(eng, 11)
        b = scenario_config(eng, 11)
        c = scenario_config(eng, 12)
        assert a == b, eng
        assert a != c, eng


def test_draws_stay_in_envelope():
    from tpudes.fuzz import scenario_config
    from tpudes.fuzz.engines import ENGINE_FUZZERS

    for eng, fz in ENGINE_FUZZERS.items():
        for seed in range(6):
            cfg = scenario_config(eng, seed)
            assert fz.envelope.contains(cfg) == [], (eng, seed, cfg)


def test_envelope_contains_honors_shrink_floors():
    from tpudes.fuzz import ScenarioGen
    from tpudes.parallel.tcp_dumbbell import FUZZ_ENVELOPE

    cfg = FUZZ_ENVELOPE.draw(ScenarioGen(0))
    shrunk = dict(cfg, replicas=1, sim_ms=8)  # below envelope minima
    assert FUZZ_ENVELOPE.contains(shrunk) == []
    assert FUZZ_ENVELOPE.contains(dict(cfg, replicas=99)) == ["replicas"]
    assert FUZZ_ENVELOPE.contains(dict(cfg, variant="TcpBogus")) == [
        "variant"
    ]


def test_shrink_moves_are_strictly_smaller():
    from tpudes.fuzz import scenario_config
    from tpudes.fuzz.engines import ENGINE_FUZZERS

    for eng, fz in ENGINE_FUZZERS.items():
        cfg = scenario_config(eng, 3)
        axes = fz.envelope.axes
        for label, cand in fz.shrink_moves(cfg):
            changed = {k for k in cfg if cand[k] != cfg[k]}
            assert len(changed) == 1, (eng, label, changed)
            (k,) = changed
            if axes[k][0] == "int":
                assert cand[k] < cfg[k], (eng, label)
            else:
                # choice axes jump straight to the move's simplest
                # value (which may be numerically larger, e.g. the BSS
                # slowest-traffic interval): once applied, the same
                # move must no longer be offered
                assert label not in dict(fz.shrink_moves(cand)), (
                    eng, label,
                )


# --- first_diff ------------------------------------------------------------


def test_first_diff_reports_field_and_index():
    import numpy as np

    from tpudes.fuzz.engines import first_diff

    a = {"x": np.array([[1, 2], [3, 4]]), "y": np.array([1.0])}
    b = {"x": np.array([[1, 2], [3, 5]]), "y": np.array([1.0])}
    d = first_diff(a, b)
    assert d == {"field": "x", "index": [1, 1], "lhs": 4, "rhs": 5}
    assert first_diff(a, a) is None
    # tolerance mode passes near-equal floats, exact mode does not
    c = {"x": a["x"], "y": np.array([1.0 + 1e-7])}
    assert first_diff(a, c, rtol=1e-5) is None
    assert first_diff(a, c)["field"] == "y"
    # NaNs in the same position agree in both modes
    n1 = {"z": np.array([np.nan, 1.0])}
    n2 = {"z": np.array([np.nan, 1.0])}
    assert first_diff(n1, n2) is None and first_diff(n1, n2, rtol=1e-5) is None


def test_first_diff_catches_missing_fields_and_json_roundtrips():
    import json

    import numpy as np

    from tpudes.fuzz.artifact import _jsonable
    from tpudes.fuzz.engines import first_diff

    # a mode that drops (or invents) a result field is a divergence
    a = {"x": np.array([1]), "y": np.array([2])}
    b = {"x": np.array([1])}
    d = first_diff(a, b)
    assert d == {"field": "y", "index": [], "lhs": True, "rhs": False}
    # every branch's index survives the artifact JSON round-trip
    # unchanged (replay checks fresh == recorded)
    shape = first_diff({"x": np.zeros((2, 2))}, {"x": np.zeros((2, 3))})
    for diff in (d, shape):
        assert diff == json.loads(json.dumps(_jsonable(diff)))


def test_replay_rejects_unknown_engine():
    import pytest as _pytest

    from tpudes.fuzz import replay

    with _pytest.raises(ValueError, match="unknown engine"):
        replay({"engine": "bsss", "seed": 1})


# --- corpus replay (the tier-1 regression gate) ---------------------------


def _corpus_entries():
    return sorted(CORPUS_DIR.glob("*.json"))


def test_corpus_has_three_seeds_per_engine():
    by_engine: dict[str, int] = {}
    for path in _corpus_entries():
        doc = json.loads(path.read_text())
        assert doc["kind"] == "tpudes-fuzz-corpus", path
        by_engine[doc["engine"]] = by_engine.get(doc["engine"], 0) + 1
    # ISSUE-10 added 2 mobile stride-boundary seeds each for the two
    # radio engines (mobility + geom_stride draws); ISSUE-14 added 3
    # burst-boundary seeds (bss/lte_sm/dumbbell traffic draws)
    assert by_engine == {
        "bss": 6, "lte_sm": 6, "dumbbell": 4, "as_flows": 3, "wired": 3,
    }


@pytest.mark.parametrize(
    "path", _corpus_entries(), ids=lambda p: p.stem
)
def test_corpus_entry_replays_clean(path):
    from tpudes.fuzz import replay

    doc = json.loads(path.read_text())
    divs = replay(doc)
    assert divs == [], [d.render() for d in divs]


# --- planted bug: detect -> shrink -> artifact -> replay ------------------


def test_planted_bug_detected_shrunk_and_replayed(monkeypatch, tmp_path):
    from tpudes.fuzz import replay, run_scenario, shrink_divergence
    from tpudes.fuzz.artifact import (
        artifact_doc,
        load_artifact,
        write_artifact,
    )
    from tpudes.fuzz.engines import ENGINE_FUZZERS

    monkeypatch.setenv("TPUDES_FUZZ_PLANTED_BUG", "1")
    fz = ENGINE_FUZZERS["dumbbell"]
    # a small in-envelope config so the shrink loop stays cheap; the
    # planted divergence is horizon/replica-independent, so shrinking
    # must reach the floors
    cfg = dict(
        n_flows=2, variant="TcpNewReno", variant_mix="homogeneous",
        bottleneck_mbps=10, bottleneck_delay_ms=5, queue_pkts=25,
        seg_bytes=1000, sim_ms=900, replicas=3, chunk_divisor=2,
        key_seed=7, traffic="off", tr_burst=0.1, tr_phase=0.0,
    )
    assert fz.envelope.contains(cfg) == []
    divs = run_scenario(fz, cfg, pairs=["chunked_vs_single"], record=False)
    assert len(divs) == 1, "planted divergence must be detected"
    assert divs[0].pair == "chunked_vs_single"
    assert divs[0].diff["field"] == "delivered"

    scfg, sdiff, iters = shrink_divergence(fz, divs[0])
    assert iters > 0
    assert scfg["replicas"] <= 2, scfg
    prog = fz.build(scfg)
    assert prog.n_slots <= 32, (scfg, prog.n_slots)

    doc = artifact_doc(
        "dumbbell", 0, divs[0].pair, scfg, sdiff,
        original_config=cfg, shrink_iterations=iters,
    )
    path = write_artifact(tmp_path, doc)
    loaded = load_artifact(path)
    assert loaded["env"]["TPUDES_FUZZ_PLANTED_BUG"] == "1"
    # replay must reproduce the recorded first_diff bit-identically
    rep = replay(loaded)
    assert len(rep) == 1 and rep[0].diff == sdiff

    # ...and with the flag off, the same scenario is clean (the flag
    # gates nothing but the self-test corruption)
    monkeypatch.delenv("TPUDES_FUZZ_PLANTED_BUG")
    assert run_scenario(
        fz, cfg, pairs=["chunked_vs_single"], record=False
    ) == []


# --- telemetry -------------------------------------------------------------


def test_fuzz_telemetry_snapshot_passes_schema_gate():
    from tpudes.obs.fuzz import FuzzTelemetry, validate_fuzz_metrics

    FuzzTelemetry.reset()
    FuzzTelemetry.record_scenario("dumbbell", 1.5)
    FuzzTelemetry.record_pair("dumbbell", "chunked_vs_single", False)
    FuzzTelemetry.record_pair("dumbbell", "swept_vs_point", True)
    FuzzTelemetry.record_shrink("dumbbell", 7)
    snap = FuzzTelemetry.snapshot()
    assert validate_fuzz_metrics(snap) == []
    assert snap["counters"]["divergences"] == 1
    assert snap["counters"]["shrink_iterations"] == 7
    e = snap["engines"]["dumbbell"]
    assert e["scenarios"] == 1 and e["scenarios_per_s"] > 0
    FuzzTelemetry.reset()
    assert FuzzTelemetry.snapshot()["counters"]["scenarios"] == 0


def test_fuzz_metrics_schema_rejects_malformed_docs():
    from tpudes.obs.fuzz import validate_fuzz_metrics

    assert validate_fuzz_metrics([]) != []
    assert validate_fuzz_metrics({"version": 1}) != []
    bad = {
        "version": 1,
        "counters": {
            "scenarios": 1, "pair_runs": 1, "divergences": 2,
            "shrinks": 0, "shrink_iterations": 0,
        },
        "engines": {
            "bss": {
                "scenarios": 1, "wall_s": 1.0, "scenarios_per_s": 1.0,
                "pairs": {"x": {"runs": 1, "divergences": 2}},
            }
        },
    }
    assert any("divergences > runs" in p for p in validate_fuzz_metrics(bad))


def test_obs_cli_validates_fuzz_metrics(tmp_path, capsys):
    from tpudes.obs.__main__ import main
    from tpudes.obs.fuzz import FuzzTelemetry

    FuzzTelemetry.reset()
    FuzzTelemetry.record_scenario("bss", 0.5)
    p = tmp_path / "m.json"
    p.write_text(json.dumps(FuzzTelemetry.snapshot()))
    FuzzTelemetry.reset()
    assert main(["--fuzz", str(p)]) == 0
    p.write_text(json.dumps({"version": 1}))
    assert main(["--fuzz", str(p)]) == 1


# --- envelope declarations -------------------------------------------------


def test_every_engine_declares_an_envelope():
    from tpudes.fuzz.engines import ENGINE_FUZZERS

    for eng, fz in ENGINE_FUZZERS.items():
        env = fz.envelope
        assert env.engine == eng
        assert {"replicas", "key_seed"} <= set(env.axes), eng
        assert env.floors.get("replicas", 99) == 1, eng
