"""802.11n (HT) slice: HT rates, A-MPDU aggregation under BlockAck,
MinstrelHt, table-based error model.

Mirrors upstream's wifi aggregation/block-ack test suites (SURVEY.md §4;
src/wifi/test/wifi-aggregation-test.cc, block-ack-test-suite.cc): count
PPDUs vs MPDUs to prove aggregation happened, force partial loss to
prove per-MPDU BlockAck retransmission, and pin the LUT error model
against its closed-form source.
"""

import math

import pytest

from tpudes.core import Seconds, Simulator
from tpudes.helper.containers import NodeContainer
from tpudes.models.mobility import ListPositionAllocator, MobilityHelper, Vector
from tpudes.models.wifi import (
    MinstrelHtWifiManager,
    NistErrorRateModel,
    TableBasedErrorRateModel,
    WifiHelper,
    WifiMacHelper,
    YansWifiChannelHelper,
    YansWifiPhyHelper,
    ppdu_duration_s,
)
from tpudes.models.wifi.mac import BLOCK_ACK_SIZE, WifiMacType, _ampdu_subframe_bytes
from tpudes.network.packet import Packet
from tpudes.ops.wifi_error import (
    HT_MODES,
    MODES_BY_NAME,
    chunk_success_rate_py,
    table_chunk_success_rate_py,
)


def _ht_pair(distance=10.0, manager=("tpudes::ConstantRateWifiManager", {"DataMode": "HtMcs7"}),
             max_ampdu=65535, phy_attrs=None):
    """Two-node adhoc HT link: returns (nodes, devices)."""
    nodes = NodeContainer()
    nodes.Create(2)
    mobility = MobilityHelper()
    alloc = ListPositionAllocator()
    alloc.Add(Vector(0, 0, 0))
    alloc.Add(Vector(distance, 0, 0))
    mobility.SetPositionAllocator(alloc)
    mobility.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    mobility.Install(nodes)

    channel = YansWifiChannelHelper.Default().Create()
    phy = YansWifiPhyHelper()
    phy.SetChannel(channel)
    for k, v in (phy_attrs or {}).items():
        phy.Set(k, v)
    wifi = WifiHelper()
    wifi.SetStandard("80211n")
    wifi.SetRemoteStationManager(manager[0], **manager[1])
    mac = WifiMacHelper()
    mac.SetType("tpudes::AdhocWifiMac", MaxAmpduSize=max_ampdu)
    devices = wifi.Install(phy, mac, nodes)
    return nodes, devices


def test_ht_ppdu_duration():
    # HT-mixed preamble is 36 µs (16 µs beyond legacy), 4 µs symbols
    mode = MODES_BY_NAME["HtMcs7"]  # 65 Mbps -> NDBPS = 260
    d = ppdu_duration_s(1000, mode)
    assert d == pytest.approx(36e-6 + math.ceil(8022 / 260) * 4e-6)
    # legacy modes are unchanged
    legacy = ppdu_duration_s(1000, MODES_BY_NAME["OfdmRate54Mbps"])
    assert legacy == pytest.approx(20e-6 + math.ceil(8022 / 216) * 4e-6)


def test_ht_ladder_monotone_rates():
    rates = [m.data_rate_bps for m in HT_MODES]
    assert rates == sorted(rates)
    assert MODES_BY_NAME["HtMcs0"].data_rate_bps == 6_500_000
    assert MODES_BY_NAME["VhtMcs9"].constellation == 256
    assert MODES_BY_NAME["HeMcs11"].constellation == 1024


def test_ampdu_aggregation_reduces_ppdu_count():
    """10 frames enqueued while the medium is busy must leave as a few
    A-MPDUs (after the ADDBA handshake), not 10 DATA/ACK exchanges."""
    nodes, devices = _ht_pair()
    got = []
    devices[1].SetReceiveCallback(lambda dev, pkt, proto, sender: got.append(pkt.GetSize()) or True)

    ppdus = []  # (size_bytes proxy: count tx begins at the sender PHY)
    devices[0].GetPhy().TraceConnectWithoutContext(
        "PhyTxBegin", lambda pkt, pw: ppdus.append(pkt)
    )
    # burst of 10 frames in one instant: first exchange runs the ADDBA
    # handshake; by the time data wins access, the queue is deep -> agg
    def burst():
        for _ in range(10):
            devices[0].Send(Packet(700), devices[1].GetAddress(), 0x0800)

    Simulator.Schedule(Seconds(1.0), burst)
    Simulator.Stop(Seconds(2))
    Simulator.Run()
    assert len(got) == 10
    from tpudes.models.wifi.phy import AmpduTag

    ampdus = [p for p in ppdus if p.PeekPacketTag(AmpduTag) is not None]
    assert ampdus, "no A-MPDU was ever transmitted"
    total_mpdus = sum(len(p.PeekPacketTag(AmpduTag).subframes) for p in ampdus)
    assert total_mpdus >= 10
    assert len(ampdus) <= 4, f"burst fragmented into {len(ampdus)} A-MPDUs"
    Simulator.Destroy()


def test_ampdu_respects_size_limit():
    """MaxAmpduSize bounds the aggregate: with a small cap the burst
    needs proportionally more PPDUs."""
    cap = 3 * _ampdu_subframe_bytes(700 + 8 + 24)  # ~3 MPDUs of 700B+LLC
    nodes, devices = _ht_pair(max_ampdu=cap)
    got = []
    devices[1].SetReceiveCallback(lambda dev, pkt, proto, sender: got.append(1) or True)
    from tpudes.models.wifi.phy import AmpduTag

    ampdus = []
    devices[0].GetPhy().TraceConnectWithoutContext(
        "PhyTxBegin",
        lambda pkt, pw: ampdus.append(pkt.PeekPacketTag(AmpduTag))
        if pkt.PeekPacketTag(AmpduTag) is not None
        else None,
    )

    def burst():
        for _ in range(9):
            devices[0].Send(Packet(700), devices[1].GetAddress(), 0x0800)

    Simulator.Schedule(Seconds(1.0), burst)
    Simulator.Stop(Seconds(2))
    Simulator.Run()
    assert len(got) == 9
    assert all(len(t.subframes) <= 3 for t in ampdus)
    assert any(len(t.subframes) == 3 for t in ampdus)
    Simulator.Destroy()


def test_block_ack_selective_retransmission():
    """At a marginal SNR some MPDUs of each A-MPDU fail; the BlockAck
    bitmap must retransmit exactly the losers until everything lands."""
    # 48 m at default power/loss -> per-MPDU PSR ≈ 0.66 for 700 B at
    # HtMcs3 (the NIST curve is steep: 45 m ≈ 0.97, 50 m ≈ 0.12) —
    # forces partial BlockAck bitmaps while BAs (32 B at 24 Mbps) survive
    nodes, devices = _ht_pair(
        distance=48.0,
        manager=("tpudes::ConstantRateWifiManager", {"DataMode": "HtMcs3"}),
    )
    got = []
    devices[1].SetReceiveCallback(lambda dev, pkt, proto, sender: got.append(1) or True)
    outcomes = []  # (n_ok, n_fail) per A-MPDU exchange
    devices[0].GetMac().TraceConnectWithoutContext(
        "AmpduTxOk", lambda to, ok, fail: outcomes.append((ok, fail))
    )

    def burst():
        for _ in range(16):
            devices[0].Send(Packet(700), devices[1].GetAddress(), 0x0800)

    Simulator.Schedule(Seconds(1.0), burst)
    Simulator.Stop(Seconds(4))
    Simulator.Run()
    # every frame eventually delivered exactly once (BA dedup) …
    assert len(got) == 16
    # … and at least one exchange had a partial bitmap (real selective
    # retransmission, not all-or-nothing)
    assert any(ok > 0 and fail > 0 for ok, fail in outcomes), outcomes
    assert sum(ok for ok, _ in outcomes) == 16
    Simulator.Destroy()


def test_minstrel_ht_converges_upward_on_clean_link():
    nodes, devices = _ht_pair(
        distance=5.0, manager=("tpudes::MinstrelHtWifiManager", {})
    )
    sm = devices[0].GetMac()._station_manager
    assert isinstance(sm, MinstrelHtWifiManager)
    got = []
    devices[1].SetReceiveCallback(lambda dev, pkt, proto, sender: got.append(1) or True)

    def feed(i=[0]):
        devices[0].Send(Packet(700), devices[1].GetAddress(), 0x0800)
        i[0] += 1
        if i[0] < 200:
            Simulator.Schedule(Seconds(0.004), feed)

    Simulator.Schedule(Seconds(1.0), feed)
    Simulator.Stop(Seconds(3))
    Simulator.Run()
    assert len(got) >= 190
    best = sm._best_rate(sm._st(devices[1].GetAddress()))
    # clean 5 m link: best throughput estimate should sit in the upper
    # half of the HT ladder
    assert best >= len(HT_MODES) // 2, f"best={best}"
    Simulator.Destroy()


def test_table_error_model_matches_nist_source():
    """LUT interpolation must track its closed-form source within the
    grid resolution, and preserve monotonicity in SNR."""
    for name in ("HtMcs0", "HtMcs4", "HtMcs7", "VhtMcs9"):
        mode = MODES_BY_NAME[name]
        prev = 0.0
        for snr_db in (2.0, 5.25, 8.4, 12.7, 18.0, 25.1):
            snr = 10 ** (snr_db / 10)
            lut = table_chunk_success_rate_py(snr, 8 * 1458, mode.index)
            exact = chunk_success_rate_py(snr, 8 * 1458, mode.constellation, mode.rate_class)
            assert lut == pytest.approx(exact, abs=0.05), (name, snr_db)
            assert lut >= prev - 1e-9
            prev = lut


def test_table_error_model_size_scaling():
    mode = MODES_BY_NAME["HtMcs3"]
    snr = 10 ** (1.15)  # mid-curve
    big = table_chunk_success_rate_py(snr, 8 * 1458, mode.index)
    small = table_chunk_success_rate_py(snr, 8 * 32, mode.index)
    # (1-PER)^(L/Lref): smaller frames succeed more often
    assert small > big
    assert small == pytest.approx(big ** (32 / 1458), rel=1e-6)


def test_phy_error_rate_model_attribute():
    nodes, devices = _ht_pair(phy_attrs={"ErrorRateModel": "tpudes::TableBasedErrorRateModel"})
    phy = devices[0].GetPhy()
    assert isinstance(phy.interference.error_model, TableBasedErrorRateModel)
    # default stays NIST
    _, dev2 = _ht_pair()
    assert isinstance(dev2[0].GetPhy().interference.error_model, NistErrorRateModel)
    Simulator.Destroy()


def test_block_ack_header_serialization_roundtrip():
    """The compressed-BA wire form must round-trip the bitmap (pcap and
    cross-rank transport see bytes, not header objects)."""
    from tpudes.models.wifi.mac import WifiMacHeader
    from tpudes.network.address import Mac48Address

    h = WifiMacHeader(
        WifiMacType.BLOCK_ACK,
        addr1=Mac48Address("00:00:00:00:00:01"),
        addr2=Mac48Address("00:00:00:00:00:02"),
    )
    h.ba_seqs = (100, 101, 103, 107, 130)
    data = h.Serialize()
    assert len(data) == h.GetSerializedSize() == BLOCK_ACK_SIZE - 4
    h2 = WifiMacHeader.Deserialize(data)
    assert h2.frame_type == WifiMacType.BLOCK_ACK
    assert set(h2.ba_seqs) == {100, 101, 103, 107, 130}
    assert h2.addr1 == h.addr1 and h2.addr2 == h.addr2


def test_block_ack_wide_set_acks_max_coverage_subset():
    """A pathological ack set spanning more than one 64-seq window must
    serialize the start that covers the MOST seqs — never a bitmap that
    silently acks almost nothing (r5 review fix; per-destination
    sequence counters make such sets unreachable in normal operation)."""
    from tpudes.models.wifi.mac import WifiMacHeader
    from tpudes.network.address import Mac48Address

    h = WifiMacHeader(
        WifiMacType.BLOCK_ACK,
        addr1=Mac48Address("00:00:00:00:00:01"),
        addr2=Mac48Address("00:00:00:00:00:02"),
    )
    h.ba_seqs = (10, 80, 150, 151, 152)
    h2 = WifiMacHeader.Deserialize(h.Serialize())
    # the 150-window covers three seqs; 10 and 80 cover one each
    assert set(h2.ba_seqs) == {150, 151, 152}


def test_sequence_counters_are_per_destination():
    """BA sessions are per-peer, so each destination must see a dense
    sequence stream even when traffic interleaves across peers."""
    from tpudes.models.wifi.mac import AdhocWifiMac
    from tpudes.network.address import Mac48Address

    mac = AdhocWifiMac()
    a = Mac48Address("00:00:00:00:00:0a")
    b = Mac48Address("00:00:00:00:00:0b")
    seqs_a = [mac._next_seq(a) for _ in range(3)]
    seqs_b = [mac._next_seq(b) for _ in range(3)]
    assert seqs_a == [1, 2, 3]
    assert seqs_b == [1, 2, 3]


def test_window_kernel_table_error_model():
    """The synthetic window kernel's LUT option must track the NIST form
    on the same batch within LUT resolution."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpudes.parallel.kernels import WindowParams, wifi_phy_window

    pos = jnp.asarray(
        np.array([[0, 0, 0], [20, 0, 0], [0, 25, 0], [15, 15, 0]], np.float32)
    )
    tx = jnp.asarray([1, 0, 1, 0])
    mode = jnp.full((4,), MODES_BY_NAME["HtMcs4"].index, jnp.int32)
    size = jnp.full((4,), 700.0, jnp.float32)
    key = jax.random.PRNGKey(0)
    _, sinr_n, _ = wifi_phy_window(pos, tx, mode, size, key, WindowParams())
    _, sinr_t, _ = wifi_phy_window(
        pos, tx, mode, size, key, WindowParams(error_model="table")
    )
    # identical geometry -> identical SINR; PER differs only by LUT error
    assert np.allclose(np.asarray(sinr_n), np.asarray(sinr_t))


def test_ampdu_end_to_end_with_table_model():
    """Aggregation + LUT error model together on a clean link."""
    nodes, devices = _ht_pair(
        phy_attrs={"ErrorRateModel": "tpudes::TableBasedErrorRateModel"}
    )
    got = []
    devices[1].SetReceiveCallback(lambda dev, pkt, proto, sender: got.append(1) or True)

    def burst():
        for _ in range(8):
            devices[0].Send(Packet(400), devices[1].GetAddress(), 0x0800)

    Simulator.Schedule(Seconds(1.0), burst)
    Simulator.Stop(Seconds(2))
    Simulator.Run()
    assert len(got) == 8
    Simulator.Destroy()
