"""IPv6: addressing, L3, ND, ICMPv6 echo, dual-stack sockets.

Mirrors upstream's ipv6 test suites (SURVEY.md §4;
src/internet/test/ipv6-address-helper-test-suite.cc,
ipv6-forwarding-test.cc, icmpv6-redirect-test.cc strategy): unit pins
on address algebra, then end-to-end exchanges over p2p (no ND), CSMA
(real NS/NA resolution), and a forwarding chain with static routes.
"""


from tpudes.core import Seconds, Simulator
from tpudes.helper.applications import UdpEchoClientHelper, UdpEchoServerHelper
from tpudes.helper.containers import NodeContainer
from tpudes.helper.internet import (
    InternetStackHelper,
    Ipv4AddressHelper,
    Ipv6AddressHelper,
)
from tpudes.helper.point_to_point import PointToPointHelper
from tpudes.network.address import (
    Inet6SocketAddress,
    Ipv6Address,
    Ipv6Prefix,
    Mac48Address,
)


def _reset():
    from tpudes.core.world import reset_world

    reset_world()


# --- address algebra -------------------------------------------------------

def test_address_parsing_and_compression():
    a = Ipv6Address("2001:db8::1")
    assert str(a) == "2001:db8::1"
    assert Ipv6Address(a.to_bytes()) == a
    assert Ipv6Address("::").IsAny()
    assert Ipv6Address("::1").IsLoopback()
    assert Ipv6Address("ff02::1").IsMulticast()
    assert Ipv6Address("fe80::42").IsLinkLocal()
    assert not a.IsMulticast() and not a.IsLinkLocal()


def test_prefix_match_and_combine():
    p = Ipv6Prefix(64)
    assert p.IsMatch(Ipv6Address("2001:db8::1"), Ipv6Address("2001:db8::ffff"))
    assert not p.IsMatch(Ipv6Address("2001:db8:1::1"), Ipv6Address("2001:db8::1"))
    assert str(Ipv6Address("2001:db8::1234").CombinePrefix(p)) == "2001:db8::"


def test_eui64_autoconfiguration():
    mac = Mac48Address("00:11:22:33:44:55")
    ll = Ipv6Address.MakeAutoconfiguredLinkLocalAddress(mac)
    # RFC 4291: flip the U/L bit, insert ff:fe
    assert str(ll) == "fe80::211:22ff:fe33:4455"
    g = Ipv6Address.MakeAutoconfiguredAddress(mac, Ipv6Address("2001:db8::"))
    assert str(g) == "2001:db8::211:22ff:fe33:4455"
    sol = Ipv6Address.MakeSolicitedAddress(Ipv6Address("2001:db8::abcd:1234"))
    assert str(sol) == "ff02::1:ffcd:1234"
    assert sol.IsSolicitedMulticast()


# --- end-to-end builders ---------------------------------------------------

def _p2p_pair():
    nodes = NodeContainer()
    nodes.Create(2)
    p2p = PointToPointHelper()
    p2p.SetDeviceAttribute("DataRate", "5Mbps")
    p2p.SetChannelAttribute("Delay", "2ms")
    devices = p2p.Install(nodes)
    InternetStackHelper().Install(nodes)
    addr = Ipv6AddressHelper()
    addr.SetBase("2001:db8::", 64)
    ifaces = addr.Assign(devices)
    return nodes, devices, ifaces


def test_v6_udp_echo_over_p2p():
    _reset()
    nodes, devices, ifaces = _p2p_pair()
    server = UdpEchoServerHelper(9)
    sapps = server.Install(nodes.Get(1))
    sapps.Start(Seconds(0.5))
    client = UdpEchoClientHelper(ifaces.GetAddress(1, 1), 9)
    client.SetAttribute("MaxPackets", 5)
    client.SetAttribute("Interval", Seconds(0.1))
    client.SetAttribute("PacketSize", 256)
    capps = client.Install(nodes.Get(0))
    capps.Start(Seconds(1.0))
    Simulator.Stop(Seconds(3.0))
    Simulator.Run()
    assert sapps.Get(0).received == 5
    assert capps.Get(0).received == 5
    _reset()


def test_link_local_auto_assigned():
    _reset()
    nodes, devices, ifaces = _p2p_pair()
    from tpudes.models.internet.ipv6 import Ipv6L3Protocol

    ipv6 = nodes.Get(0).GetObject(Ipv6L3Protocol)
    iface = ipv6.GetInterface(1)
    ll = iface.GetLinkLocalAddress()
    assert ll is not None and ll.GetLocal().IsLinkLocal()
    expected = Ipv6Address.MakeAutoconfiguredLinkLocalAddress(
        devices.Get(0).GetAddress()
    )
    assert ll.GetLocal() == expected
    _reset()


def test_ping6_over_p2p():
    _reset()
    nodes, devices, ifaces = _p2p_pair()
    from tpudes.models.internet.icmpv6 import Ping6

    ping = Ping6(Remote=str(ifaces.GetAddress(1, 1)), Interval=0.2, Size=56)
    nodes.Get(0).AddApplication(ping)
    ping.SetStartTime(Seconds(1.0))
    ping.SetStopTime(Seconds(2.0))
    Simulator.Stop(Seconds(3.0))
    Simulator.Run()
    assert len(ping.rtts) >= 4
    # 2 ms each way + serialization
    assert all(0.004 <= r < 0.01 for r in ping.rtts), ping.rtts
    _reset()


def test_ping6_with_nd_over_csma():
    """CSMA devices need ARP/ND: the first echo rides behind a real
    NS/NA exchange (solicited-node multicast, EUI-64 learning)."""
    _reset()
    from tpudes.models.csma import CsmaHelper

    nodes = NodeContainer()
    nodes.Create(3)
    csma = CsmaHelper()
    csma.SetChannelAttribute("DataRate", "100Mbps")
    csma.SetChannelAttribute("Delay", "6560ns")
    devices = csma.Install(nodes)
    InternetStackHelper().Install(nodes)
    addr = Ipv6AddressHelper()
    addr.SetBase("2001:db8:1::", 64)
    ifaces = addr.Assign(devices)

    from tpudes.models.internet.icmpv6 import Icmpv6L4Protocol, Ping6

    ping = Ping6(Remote=str(ifaces.GetAddress(2, 1)), Interval=0.2)
    nodes.Get(0).AddApplication(ping)
    ping.SetStartTime(Seconds(1.0))
    ping.SetStopTime(Seconds(2.0))
    Simulator.Stop(Seconds(3.0))
    Simulator.Run()
    assert len(ping.rtts) >= 4
    # the resolver learned the target's MAC
    nd = nodes.Get(0).GetObject(Icmpv6L4Protocol)
    learned = [
        e.mac for cache in nd._caches.values() for e in cache.values()
        if e.mac is not None
    ]
    assert devices.Get(2).GetAddress() in learned
    _reset()


def test_v6_forwarding_chain_with_static_routes():
    """n0 -- r -- n1: hop limit decrements across the router; the
    default routes point at the router's per-link addresses."""
    _reset()
    from tpudes.models.internet.ipv6 import Ipv6L3Protocol, Ipv6StaticRouting

    nodes = NodeContainer()
    nodes.Create(3)
    p2p = PointToPointHelper()
    p2p.SetDeviceAttribute("DataRate", "5Mbps")
    p2p.SetChannelAttribute("Delay", "1ms")
    d01 = p2p.Install(nodes.Get(0), nodes.Get(1))
    d12 = p2p.Install(nodes.Get(1), nodes.Get(2))
    InternetStackHelper().Install(nodes)
    a = Ipv6AddressHelper()
    a.SetBase("2001:db8:a::", 64)
    i01 = a.Assign(d01)
    a.SetBase("2001:db8:b::", 64)
    i12 = a.Assign(d12)

    # default routes toward the middle router
    r0 = nodes.Get(0).GetObject(Ipv6L3Protocol).GetRoutingProtocol()
    assert isinstance(r0, Ipv6StaticRouting)
    r0.SetDefaultRoute(i01.GetAddress(1, 1), 1)
    r2 = nodes.Get(2).GetObject(Ipv6L3Protocol).GetRoutingProtocol()
    r2.SetDefaultRoute(i12.GetAddress(0, 1), 1)

    server = UdpEchoServerHelper(7)
    sapps = server.Install(nodes.Get(2))
    sapps.Start(Seconds(0.5))
    client = UdpEchoClientHelper(i12.GetAddress(1, 1), 7)
    client.SetAttribute("MaxPackets", 3)
    client.SetAttribute("Interval", Seconds(0.1))
    capps = client.Install(nodes.Get(0))
    capps.Start(Seconds(1.0))

    hop_limits = []
    nodes.Get(2).GetObject(Ipv6L3Protocol).TraceConnectWithoutContext(
        "LocalDeliver", lambda h, p, i: hop_limits.append(h.hop_limit)
    )
    Simulator.Stop(Seconds(3.0))
    Simulator.Run()
    assert capps.Get(0).received == 3
    # one forwarding hop: 64 - 1
    assert hop_limits and all(h == 63 for h in hop_limits)
    _reset()


def test_hop_limit_expiry_generates_time_exceeded():
    _reset()
    from tpudes.models.internet.icmpv6 import Icmpv6L4Protocol
    from tpudes.models.internet.ipv6 import Ipv6L3Protocol

    nodes, devices, ifaces = _p2p_pair()
    # send an echo with hop limit 1 through... a 2-node p2p delivers
    # directly; instead set DefaultHopLimit=1 on a 3-node chain
    _reset()
    from tpudes.models.internet.ipv6 import Ipv6StaticRouting

    nodes = NodeContainer()
    nodes.Create(3)
    p2p = PointToPointHelper()
    d01 = p2p.Install(nodes.Get(0), nodes.Get(1))
    d12 = p2p.Install(nodes.Get(1), nodes.Get(2))
    InternetStackHelper().Install(nodes)
    a = Ipv6AddressHelper()
    a.SetBase("2001:db8:a::", 64)
    i01 = a.Assign(d01)
    a.SetBase("2001:db8:b::", 64)
    i12 = a.Assign(d12)
    r0 = nodes.Get(0).GetObject(Ipv6L3Protocol).GetRoutingProtocol()
    r0.SetDefaultRoute(i01.GetAddress(1, 1), 1)
    ipv6_0 = nodes.Get(0).GetObject(Ipv6L3Protocol)
    ipv6_0.default_hop_limit = 1  # expires at the router

    errors = []
    icmp0 = nodes.Get(0).GetObject(Icmpv6L4Protocol)
    icmp0.register_error_listener(
        lambda t, c, inner, src: errors.append((t, c, src))
    )
    icmp0.SendEcho(i12.GetAddress(1, 1), 0x77, 1)
    Simulator.Stop(Seconds(1.0))
    Simulator.Run()
    from tpudes.models.internet.icmpv6 import Icmpv6Header

    assert errors and errors[0][0] == Icmpv6Header.TIME_EXCEEDED
    _reset()


def test_dual_stack_same_port_both_families():
    """One server node answers v4 and v6 echo on the same port."""
    _reset()
    nodes = NodeContainer()
    nodes.Create(2)
    p2p = PointToPointHelper()
    devices = p2p.Install(nodes)
    InternetStackHelper().Install(nodes)
    a4 = Ipv4AddressHelper()
    a4.SetBase("10.0.0.0", "255.255.255.0")
    i4 = a4.Assign(devices)
    a6 = Ipv6AddressHelper()
    a6.SetBase("2001:db8::", 64)
    i6 = a6.Assign(devices)

    server = UdpEchoServerHelper(9)
    sapps = server.Install(nodes.Get(1))
    sapps.Start(Seconds(0.2))

    c4 = UdpEchoClientHelper(i4.GetAddress(1), 9)
    c4.SetAttribute("MaxPackets", 2)
    c4.SetAttribute("Interval", Seconds(0.1))
    a4pps = c4.Install(nodes.Get(0))
    a4pps.Start(Seconds(1.0))

    c6 = UdpEchoClientHelper(i6.GetAddress(1, 1), 9)
    c6.SetAttribute("MaxPackets", 2)
    c6.SetAttribute("Interval", Seconds(0.1))
    a6pps = c6.Install(nodes.Get(0))
    a6pps.Start(Seconds(1.0))

    Simulator.Stop(Seconds(3.0))
    Simulator.Run()
    assert sapps.Get(0).received == 4
    assert a4pps.Get(0).received == 2
    assert a6pps.Get(0).received == 2
    _reset()


def test_v6_socket_close_frees_port_and_family_mismatch_is_loud():
    """r5 review regressions: Close() must deallocate v6 endpoints (the
    port leaked and the dead rx_callback kept firing), and a v4-bound
    socket given a v6 peer must fail with an error, not silently send
    from an endpoint replies can never reach."""
    _reset()
    from tpudes.models.internet.udp import UdpL4Protocol

    nodes = NodeContainer()
    nodes.Create(1)
    InternetStackHelper().Install(nodes)
    udp = nodes.Get(0).GetObject(UdpL4Protocol)
    s1 = udp.CreateSocket()
    assert s1.Bind(Inet6SocketAddress(Ipv6Address.GetAny(), 9)) == 0
    s1.Close()
    s2 = udp.CreateSocket()
    assert s2.Bind(Inet6SocketAddress(Ipv6Address.GetAny(), 9)) == 0
    s3 = udp.CreateSocket()
    assert s3.Bind() == 0  # v4 endpoint
    assert s3.Connect(Inet6SocketAddress(Ipv6Address("2001:db8::1"), 5)) == -1
    _reset()
