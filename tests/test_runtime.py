"""The shared engine runtime (tpudes/parallel/runtime.py): LRU runner
cache, shape bucketing with exact mask correctness, warm-call compile
guarantees for every device engine, and the persistent-cache wiring.

The compile-count assertions are the PR-4 recompile-regression gates:
back-to-back identical calls compile exactly once per engine, and a
horizon×replica sweep compiles one program per replica *bucket* (the
horizon is a traced operand, so it never forces a recompile at all).
"""

import dataclasses

import jax
import numpy as np
import pytest

from tpudes.obs.device import CompileTelemetry
from tpudes.parallel.runtime import (
    RUNTIME,
    EngineRuntime,
    bucket_replicas,
    configure_persistent_cache,
    pow2_bucket,
    replica_keys,
)


@pytest.fixture(autouse=True)
def _fresh_runtime():
    RUNTIME.clear()
    CompileTelemetry.reset()
    yield
    RUNTIME.clear()


# --- program fixtures: the shared synthetic builders (also what
# bench.bench_mesh runs, so bench and tests cannot drift apart) ----------


def _lte_prog(n_ttis=60):
    from tpudes.parallel.programs import toy_lte_program

    return toy_lte_program(n_enb=2, n_ue=4, n_ttis=n_ttis)


def _tcp_prog(n_slots=250):
    from tpudes.parallel.programs import toy_dumbbell_program

    return toy_dumbbell_program(n_flows=3, n_slots=n_slots)


def _as_prog():
    from tpudes.parallel.programs import toy_as_program

    return toy_as_program(n_nodes=64, n_flows=3)


def _bss_prog():
    from tpudes.parallel.programs import toy_bss_program

    return toy_bss_program(n_sta=4, sim_end_us=60_000)


# --- LRU cache semantics (the replicated.py eviction regression) --------


class TestEngineRuntimeLRU:
    def test_hit_refreshes_eviction_order(self):
        """The pre-runtime per-engine dicts popped the insertion-oldest
        entry, so a HOT entry could be evicted while a stale one
        survived.  True LRU: a hit moves the entry to the back."""
        rt = EngineRuntime(capacity=2)
        rt.runner("e", ("a",), lambda: "A")
        rt.runner("e", ("b",), lambda: "B")
        rt.runner("e", ("a",), lambda: "A2")       # hit: refresh "a"
        rt.runner("e", ("c",), lambda: "C")        # evicts "b", NOT "a"
        val, compiled = rt.runner("e", ("a",), lambda: "A3")
        assert val == "A" and not compiled         # hot entry survived
        _, compiled_b = rt.runner("e", ("b",), lambda: "B2")
        assert compiled_b                          # stale entry evicted

    def test_miss_reports_compiled_new_once(self):
        rt = EngineRuntime()
        _, first = rt.runner("e", (1,), lambda: object())
        _, second = rt.runner("e", (1,), lambda: object())
        assert first and not second
        assert rt.stats()["hits"] == 1 and rt.stats()["misses"] == 1

    def test_per_engine_size_and_clear(self):
        rt = EngineRuntime()
        rt.runner("x", (1,), lambda: 1)
        rt.runner("x", (2,), lambda: 2)
        rt.runner("y", (1,), lambda: 3)
        assert rt.size("x") == 2 and rt.size("y") == 1 and rt.size() == 3
        rt.clear("x")
        assert rt.size("x") == 0 and rt.size("y") == 1


# --- bucketing policy ---------------------------------------------------


def test_pow2_bucket_and_mesh_rounding():
    assert [pow2_bucket(n) for n in (1, 2, 3, 5, 8, 9)] == [1, 2, 4, 8, 8, 16]
    assert bucket_replicas(None) is None
    assert bucket_replicas(5) == 8
    from tpudes.parallel.mesh import replica_mesh

    mesh3 = replica_mesh(3)
    # pow2 first, then rounded up to a multiple of the mesh size so the
    # sharded axis always divides evenly
    assert bucket_replicas(5, mesh3) == 9


def test_bucketing_env_kill_switch(monkeypatch):
    monkeypatch.setenv("TPUDES_BUCKETING", "0")
    assert bucket_replicas(5) == 5


def test_replica_keys_rows_independent_of_padding():
    """Row i of replica_keys(key, n) must not depend on n — the whole
    exactness argument for replica bucketing rests on this (and it is
    FALSE for jax.random.split, which is why the engines don't use it
    for the replica axis)."""
    k = jax.random.PRNGKey(3)
    a = np.asarray(replica_keys(k, 5))
    b = np.asarray(replica_keys(k, 8))
    np.testing.assert_array_equal(a, b[:5])


# --- warm-call guarantee: repeat-call compile count == 1 per engine -----


def test_lte_sm_warm_call_compiles_once():
    from tpudes.parallel.lte_sm import run_lte_sm

    prog = _lte_prog()
    a = run_lte_sm(prog, jax.random.PRNGKey(0), replicas=3)
    b = run_lte_sm(prog, jax.random.PRNGKey(0), replicas=3)
    assert CompileTelemetry.compiles("lte_sm") == 1
    np.testing.assert_array_equal(a["rx_bits"], b["rx_bits"])


def test_as_flows_warm_call_compiles_once():
    from tpudes.parallel.as_flows import run_as_flows

    prog = _as_prog()
    a = run_as_flows(prog, jax.random.PRNGKey(0), replicas=3)
    b = run_as_flows(prog, jax.random.PRNGKey(0), replicas=3)
    assert CompileTelemetry.compiles("as_flows") == 1
    np.testing.assert_array_equal(
        np.asarray(a["goodput_bps"]), np.asarray(b["goodput_bps"])
    )


def test_bss_warm_call_compiles_once():
    from tpudes.parallel.replicated import run_replicated_bss

    prog = _bss_prog()
    a = run_replicated_bss(prog, 3, jax.random.PRNGKey(0))
    b = run_replicated_bss(prog, 3, jax.random.PRNGKey(0))
    assert CompileTelemetry.compiles("bss") == 1
    assert a["all_done"]
    np.testing.assert_array_equal(a["srv_rx"], b["srv_rx"])


def test_dumbbell_warm_call_compiles_once():
    from tpudes.parallel.tcp_dumbbell import run_tcp_dumbbell

    prog = _tcp_prog()
    a = run_tcp_dumbbell(prog, jax.random.PRNGKey(0), replicas=3)
    b = run_tcp_dumbbell(prog, jax.random.PRNGKey(0), replicas=3)
    assert CompileTelemetry.compiles("dumbbell") == 1
    np.testing.assert_array_equal(
        np.asarray(a["delivered"]), np.asarray(b["delivered"])
    )


# --- shape bucketing: sweeps hit the cache, results stay exact ----------


def test_horizon_replica_sweep_compiles_per_bucket():
    """5 nearby horizons × 3 replica counts = 15 points; horizons are a
    traced operand (zero programs) and the replica counts {3, 4, 6}
    land in buckets {4, 8} — so the sweep compiles exactly 2 programs,
    not 15."""
    from tpudes.parallel.lte_sm import run_lte_sm

    base = _lte_prog()
    replica_counts = (3, 4, 6)
    buckets = {bucket_replicas(r) for r in replica_counts}
    for n_ttis in (50, 55, 60, 61, 70):
        for r in replica_counts:
            run_lte_sm(
                dataclasses.replace(base, n_ttis=n_ttis),
                jax.random.PRNGKey(1),
                replicas=r,
            )
    assert CompileTelemetry.compiles("lte_sm") == len(buckets) == 2
    assert RUNTIME.size("lte_sm") == 2


def test_eight_point_sweep_compiles_at_most_four():
    """The PR-4 acceptance gate: an 8-point horizon×replica sweep used
    to compile 8 programs (every (n_slots, replicas) pair was a cache
    key); it must now compile ≤ 4."""
    from tpudes.parallel.tcp_dumbbell import run_tcp_dumbbell

    base = _tcp_prog()
    points = [
        (200, 2), (220, 2), (240, 3), (260, 3),
        (280, 4), (300, 5), (320, 6), (340, 8),
    ]
    for n_slots, r in points:
        run_tcp_dumbbell(
            dataclasses.replace(base, n_slots=n_slots),
            jax.random.PRNGKey(0),
            replicas=r,
        )
    assert CompileTelemetry.compiles("dumbbell") <= 4
    assert RUNTIME.size("dumbbell") <= 4


def test_bucketed_results_equal_unbucketed_exactly(monkeypatch):
    """Mask correctness: padding the replica axis to a bucket must not
    change any real replica's outcome, bit for bit (per-replica fold_in
    keying makes each replica's stream independent of the padded axis
    size).  A/B via the TPUDES_BUCKETING kill switch."""
    from tpudes.parallel.lte_sm import run_lte_sm
    from tpudes.parallel.replicated import run_replicated_bss
    from tpudes.parallel.tcp_dumbbell import run_tcp_dumbbell

    lte, tcp, bss = _lte_prog(), _tcp_prog(), _bss_prog()
    key = jax.random.PRNGKey(9)
    on = {
        "lte": run_lte_sm(lte, key, replicas=5),
        "tcp": run_tcp_dumbbell(tcp, key, replicas=5),
        "bss": run_replicated_bss(bss, 5, key),
    }
    monkeypatch.setenv("TPUDES_BUCKETING", "0")
    RUNTIME.clear()
    off = {
        "lte": run_lte_sm(lte, key, replicas=5),
        "tcp": run_tcp_dumbbell(tcp, key, replicas=5),
        "bss": run_replicated_bss(bss, 5, key),
    }
    np.testing.assert_array_equal(on["lte"]["rx_bits"], off["lte"]["rx_bits"])
    np.testing.assert_array_equal(on["lte"]["ok"], off["lte"]["ok"])
    np.testing.assert_array_equal(
        np.asarray(on["tcp"]["delivered"]), np.asarray(off["tcp"]["delivered"])
    )
    np.testing.assert_array_equal(
        np.asarray(on["tcp"]["cwnd_final"]), np.asarray(off["tcp"]["cwnd_final"])
    )
    for k in ("srv_rx", "cli_rx", "tx_data", "drops"):
        np.testing.assert_array_equal(on["bss"][k], off["bss"][k])
    # and the sliced shapes advertise the REQUESTED replica count
    assert on["lte"]["rx_bits"].shape[0] == 5
    assert np.asarray(on["tcp"]["delivered"]).shape[0] == 5
    assert on["bss"]["srv_rx"].shape[0] == 5


def test_bss_max_steps_is_traced_not_baked():
    """max_steps sweeps share one executable (it is a while_loop bound
    operand, not a compile-time constant)."""
    from tpudes.parallel.replicated import run_replicated_bss

    prog = _bss_prog()
    outs = [
        run_replicated_bss(prog, 3, jax.random.PRNGKey(0), max_steps=m)
        for m in (30_000, 40_000, 50_000)
    ]
    assert CompileTelemetry.compiles("bss") == 1
    # a bound the run never hits cannot change the outcome
    np.testing.assert_array_equal(outs[0]["srv_rx"], outs[2]["srv_rx"])


# --- persistent compilation cache wiring --------------------------------


def test_persistent_cache_config(tmp_path, monkeypatch):
    old = jax.config.jax_compilation_cache_dir
    try:
        monkeypatch.delenv("TPUDES_CACHE_DIR", raising=False)
        assert configure_persistent_cache() is None
        monkeypatch.setenv("TPUDES_CACHE_DIR", str(tmp_path))
        assert configure_persistent_cache() == str(tmp_path)
        assert jax.config.jax_compilation_cache_dir == str(tmp_path)
    finally:
        jax.config.update("jax_compilation_cache_dir", old)
