"""Window engine + kernel + mesh tests.

SURVEY.md §4 analog of the MPI tests: the virtual 8-device CPU mesh is
the mpirun-on-localhost harness; JaxSimulatorImpl vs DefaultSimulatorImpl
trace equivalence is the determinism oracle."""

import jax
import jax.numpy as jnp
import numpy as np

from tpudes.core import GlobalValue, Seconds, Simulator
from tpudes.parallel import (
    make_replica_batch,
    replica_mesh,
    shard_leading_axis,
    sharded_window_step,
    wifi_phy_window,
)
from tpudes.parallel.kernels import replicated


def _first_slice_trace():
    """Run the first.cc topology, return the (time, event) trace."""
    from tpudes.helper.applications import UdpEchoClientHelper, UdpEchoServerHelper
    from tpudes.helper.containers import NodeContainer
    from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
    from tpudes.helper.point_to_point import PointToPointHelper

    trace = []
    nodes = NodeContainer()
    nodes.Create(2)
    p2p = PointToPointHelper()
    p2p.SetDeviceAttribute("DataRate", "5Mbps")
    p2p.SetChannelAttribute("Delay", "2ms")
    devices = p2p.Install(nodes)
    stack = InternetStackHelper()
    stack.Install(nodes)
    address = Ipv4AddressHelper()
    address.SetBase("10.1.1.0", "255.255.255.0")
    interfaces = address.Assign(devices)
    server = UdpEchoServerHelper(9)
    server_apps = server.Install(nodes.Get(1))
    server_apps.Start(Seconds(1.0))
    server_apps.Stop(Seconds(10.0))
    client = UdpEchoClientHelper(interfaces.GetAddress(1), 9)
    client.SetAttribute("MaxPackets", 3)
    client.SetAttribute("Interval", Seconds(1.0))
    client.SetAttribute("PacketSize", 1024)
    client_apps = client.Install(nodes.Get(0))
    client_apps.Start(Seconds(2.0))
    client_apps.Stop(Seconds(10.0))
    server_apps.Get(0).TraceConnectWithoutContext(
        "Rx", lambda pkt, *a: trace.append(("server", Simulator.NowTicks(), pkt.GetSize()))
    )
    client_apps.Get(0).TraceConnectWithoutContext(
        "Rx", lambda pkt, *a: trace.append(("client", Simulator.NowTicks(), pkt.GetSize()))
    )
    Simulator.Stop(Seconds(11))
    Simulator.Run()
    count = Simulator.GetEventCount()
    Simulator.Destroy()
    import tpudes.network.node as nn

    nn.NodeList.Reset()
    return trace, count


def test_degenerate_trace_parity_with_default_engine():
    """The step-4 oracle: with no batchable channels, JaxSimulatorImpl
    reproduces DefaultSimulatorImpl's trace EXACTLY (same ticks)."""
    from tpudes.core.rng import RngSeedManager

    RngSeedManager.Reset()
    GlobalValue.Bind("SimulatorImplementationType", "tpudes::DefaultSimulatorImpl")
    base_trace, base_count = _first_slice_trace()

    RngSeedManager.Reset()
    GlobalValue.Bind("SimulatorImplementationType", "tpudes::JaxSimulatorImpl")
    jax_trace, jax_count = _first_slice_trace()

    assert base_trace == jax_trace
    assert base_count == jax_count
    assert len(base_trace) == 6  # 3 at server + 3 echoed at client


def test_jax_engine_runs_wifi_with_cached_windows():
    """WiFi BSS under the window engine: same delivery outcome as the
    scalar engine for a strong-margin geometry, and the cache actually
    engaged (windows_run > 0)."""
    import tests.test_wifi as tw
    from tpudes.network.packet import Packet

    def run_engine(engine):
        from tpudes.core.rng import RngSeedManager

        RngSeedManager.Reset()
        GlobalValue.Bind("SimulatorImplementationType", engine)
        import tpudes.parallel  # registers JaxBatchMinPhys

        GlobalValue.Bind("JaxBatchMinPhys", 2)  # engage the cache at 4 phys
        nodes, devices = tw._wifi_nodes(
            4,
            [(0, 0, 0), (8, 0, 0), (0, 8, 0), (8, 8, 0)],
            lambda i, m: m.SetType("tpudes::AdhocWifiMac"),
        )
        got = []
        devices[1].SetReceiveCallback(lambda dev, pkt, proto, sender: got.append(pkt.GetSize()) or True)
        for k in range(5):
            Simulator.Schedule(
                Seconds(1.0 + 0.05 * k), devices[0].Send, Packet(300), devices[1].GetAddress(), 0x0800
            )
        Simulator.Stop(Seconds(2))
        Simulator.Run()
        impl = Simulator.GetImpl()
        windows = getattr(impl, "windows_run", None)
        Simulator.Destroy()
        import tpudes.network.node as nn
        from tpudes.parallel.engine import BatchableRegistry

        nn.NodeList.Reset()
        BatchableRegistry.reset()
        return got, windows

    got_default, _ = run_engine("tpudes::DefaultSimulatorImpl")
    got_jax, windows = run_engine("tpudes::JaxSimulatorImpl")
    assert got_default == [300] * 5
    assert got_jax == got_default
    assert windows and windows > 0


def test_wifi_phy_window_kernel_basics():
    # two close nodes, node 0 transmitting: node 1 decodes; a lone far
    # node below sensitivity does not
    positions = jnp.array([[0.0, 0, 0], [10.0, 0, 0], [30000.0, 0, 0]])
    tx = jnp.array([True, False, False])
    mode = jnp.zeros(3, jnp.int32)
    size = jnp.full(3, 500.0)
    ok, sinr, rx_dbm = wifi_phy_window(positions, tx, mode, size, jax.random.PRNGKey(0))
    assert bool(ok[0, 1])
    assert not bool(ok[0, 2])  # below sensitivity at 30 km
    assert not bool(ok[0, 0])  # no self-reception
    assert float(sinr[0, 1]) > 100  # strong link


def test_wifi_phy_window_interference_symmetry():
    # two simultaneous transmitters near one receiver: mutual interference
    # drives SINR to ~0 dB and both frames die at high order modulation
    positions = jnp.array([[0.0, 0, 0], [2.0, 0, 0], [1.0, 1.0, 0]])
    tx = jnp.array([True, True, False])
    mode = jnp.full(3, 7, jnp.int32)  # 54 Mbps
    size = jnp.full(3, 1000.0)
    ok, sinr, _ = wifi_phy_window(positions, tx, mode, size, jax.random.PRNGKey(1))
    assert float(sinr[0, 2]) < 3.0  # ~0 dB SIR
    assert not bool(ok[0, 2]) and not bool(ok[1, 2])
    # transmitters are half-duplex: they never receive
    assert not bool(ok[0, 1]) and not bool(ok[1, 0])


def test_replicated_vmap_axis():
    r, n = 8, 16
    positions, tx, mode, size, keys = make_replica_batch(r, n)
    run = replicated()
    ok, sinr, rx = run(positions, tx, mode, size, keys)
    assert ok.shape == (r, n, n)
    # same topology, same tx set, different keys: deterministic parts equal
    np.testing.assert_allclose(np.asarray(rx[0]), np.asarray(rx[1]), rtol=1e-6)


def test_sharded_window_step_on_virtual_mesh():
    """The 8-device CPU mesh exercise: shard_map + pmin grant + psum —
    the MPI-on-localhost analog (SURVEY.md §4)."""
    mesh = replica_mesh()
    n_dev = len(mesh.devices)
    assert n_dev == 8, "conftest must force 8 virtual devices"
    r, n = 2 * n_dev, 12
    positions, tx, mode, size, keys = make_replica_batch(r, n)
    positions, tx, mode, size, keys = shard_leading_axis(mesh, positions, tx, mode, size, keys)
    next_ts = jnp.arange(r, dtype=jnp.int32) + 100  # per-replica next event times
    (next_ts,) = shard_leading_axis(mesh, next_ts)
    lookahead = jnp.array([7], dtype=jnp.int32)

    step = sharded_window_step(mesh)
    ok, sinr, delivered, grant = jax.jit(step)(positions, tx, mode, size, keys, next_ts, lookahead)
    assert ok.shape == (r, n, n)
    assert int(grant) == 100 + 7  # global min across shards + lookahead
    # delivered is psum'd across shards: equals the global sum of ok
    assert int(delivered) == int(jnp.sum(ok))
    assert int(delivered) > 0


def test_multi_window_scan_jit():
    from tpudes.parallel import multi_window_scan

    positions = jax.random.uniform(jax.random.PRNGKey(3), (24, 3), maxval=40.0)
    mode = jnp.zeros(24, jnp.int32)
    size = jnp.full(24, 700.0)
    total = multi_window_scan(positions, 0.25, mode, size, jax.random.PRNGKey(4), n_windows=8)
    assert int(total) > 0


def test_lte_window_cache_beats_per_event_dispatch():
    """Cross-consumer check (VERDICT r5 weak #3): the LTE TTI controller
    registers as a second BatchableRegistry consumer beside
    YansWifiChannel, and on a mobile LTE graph the windowed engine's
    once-per-window geometry/SINR refresh replaces the per-TTI-event
    rebuild the scalar engine pays."""
    from tpudes.core.rng import RngSeedManager
    from tpudes.core.world import reset_world
    from tpudes.models.lte.controller import LteTtiController
    from tpudes.parallel.engine import BatchableRegistry

    sim_s = 0.05  # 50 TTIs

    def run(engine, window_ns=None):
        reset_world()
        RngSeedManager.Reset()
        GlobalValue.Bind("SimulatorImplementationType", engine)
        if window_ns is not None:
            GlobalValue.Bind("JaxWindowNs", window_ns)
        import tests.test_lte as tl
        from tpudes.models.mobility import MobilityHelper

        lte, _, ue_devs = tl._build_lena(1, 2)
        # make the geometry non-static: a (zero-velocity) walker model
        # on one UE — identical physics, but the controller can no
        # longer prove the gain matrix constant across TTIs
        walker = MobilityHelper()
        walker.SetMobilityModel("tpudes::ConstantVelocityMobilityModel")
        node = ue_devs.Get(0).GetNode()
        from tpudes.models.mobility import MobilityModel, Vector

        old = node.GetObject(MobilityModel)
        pos = old.GetPosition()
        walker.Install(node)
        new = [
            m for m in node._aggregates
            if isinstance(m, MobilityModel) and m is not old
        ]
        # the freshly-installed model must be the one GetObject resolves
        node._aggregates.remove(old)
        new[0].SetPosition(Vector(pos.x, pos.y, pos.z))

        c = lte.controller
        # ISSUE-10: the mobile refresh is now the geometry-only slice
        # of _rebuild (bit-equal, cheaper) — the per-window-vs-per-event
        # contract is about GEOMETRY REFRESHES, so count both kinds
        rebuilds = [0]
        orig = c._rebuild
        orig_geom = c._refresh_geometry

        def counting():
            rebuilds[0] += 1
            orig()

        def counting_geom():
            rebuilds[0] += 1
            orig_geom()

        c._rebuild = counting
        c._refresh_geometry = counting_geom
        members = BatchableRegistry.members()
        assert any(isinstance(m, LteTtiController) for m in members)

        Simulator.Stop(Seconds(sim_s))
        Simulator.Run()
        ttis = c.stats["ttis"]
        ok = c.stats["dl_ok"]
        reset_world()
        return rebuilds[0], ttis, ok

    per_event, ttis_a, ok_a = run("tpudes::DefaultSimulatorImpl")
    windowed, ttis_b, ok_b = run(
        "tpudes::JaxSimulatorImpl", window_ns=10_000_000
    )
    assert ttis_a == ttis_b == 50
    assert ok_a > 0 and ok_b > 0
    # per-event: ~one rebuild per TTI; windowed: ~one per 10 ms window
    assert per_event >= 45, per_event
    assert windowed <= per_event // 4, (windowed, per_event)

    # both consumer kinds coexist in the registry
    reset_world()
    from tpudes.models.lte import LteHelper
    from tpudes.models.wifi.channel import YansWifiChannel

    ch = YansWifiChannel()
    lte = LteHelper()
    kinds = {type(m).__name__ for m in BatchableRegistry.members()}
    assert {"YansWifiChannel", "LteTtiController"} <= kinds, kinds
    del ch, lte
    reset_world()


# --- ISSUE-9 satellite: shard_map compat shim, both kwarg spellings -------


def test_resolve_shard_map_new_jax_top_level():
    """jax.shard_map exists -> top-level fn + check_vma spelling."""
    import types

    from tpudes.parallel.mesh import resolve_shard_map

    def fake_shard_map(f, **kw):  # pragma: no cover - never called
        return f

    stub = types.SimpleNamespace(shard_map=fake_shard_map)
    fn, kw = resolve_shard_map(stub)
    assert fn is fake_shard_map
    assert kw == {"check_vma": False}


def test_resolve_shard_map_experimental_check_vma():
    """Newer experimental home: signature speaks check_vma."""
    import types

    from tpudes.parallel.mesh import resolve_shard_map

    def exp_shard_map(f, mesh=None, in_specs=None, out_specs=None,
                      check_vma=True):  # pragma: no cover
        return f

    stub = types.SimpleNamespace(
        __name__="fakejax",
        experimental=types.SimpleNamespace(
            shard_map=types.SimpleNamespace(shard_map=exp_shard_map)
        ),
    )
    fn, kw = resolve_shard_map(stub)
    assert fn is exp_shard_map
    assert kw == {"check_vma": False}


def test_resolve_shard_map_experimental_check_rep():
    """Older experimental home: the check_rep spelling (previously the
    `# pragma: no cover` branch) resolves without importing real jax."""
    import types

    from tpudes.parallel.mesh import resolve_shard_map

    def exp_shard_map(f, mesh=None, in_specs=None, out_specs=None,
                      check_rep=True):  # pragma: no cover
        return f

    stub = types.SimpleNamespace(
        __name__="fakejax",
        experimental=types.SimpleNamespace(
            shard_map=types.SimpleNamespace(shard_map=exp_shard_map)
        ),
    )
    fn, kw = resolve_shard_map(stub)
    assert fn is exp_shard_map
    assert kw == {"check_rep": False}


def test_resolve_shard_map_unintrospectable_signature_defaults_rep():
    """A C-accelerated callable whose signature cannot be inspected
    falls back to the conservative check_rep spelling."""
    import types

    from tpudes.parallel.mesh import resolve_shard_map

    stub = types.SimpleNamespace(
        __name__="fakejax",
        experimental=types.SimpleNamespace(
            shard_map=types.SimpleNamespace(shard_map=len)  # builtin
        ),
    )
    fn, kw = resolve_shard_map(stub)
    assert fn is len
    assert kw == {"check_rep": False}


def test_resolve_shard_map_real_jax_resolves():
    """Whatever the installed jax vintage, the shim must resolve to a
    callable + exactly one replication-check kwarg."""
    from tpudes.parallel.mesh import resolve_shard_map

    fn, kw = resolve_shard_map()
    assert callable(fn)
    assert list(kw.values()) == [False]
    assert set(kw) <= {"check_vma", "check_rep"}
