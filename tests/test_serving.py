"""ISSUE 7 gates: the StudyServer serving layer.

- **Coalescing correctness**: studies merged onto one config-axis
  launch produce results BIT-equal to per-study solo launches, for all
  four engines (the PR-5 sweep equality, now end-to-end through the
  queue/demux path), including when the batch pads to a pow2 bucket.
- **One launch**: a coalesced batch is exactly ONE device launch, and
  a repeat batch of the same bucket adds no fresh XLA compile.
- **Batching deadline**: a lone study is dispatched alone within its
  max-wait — never starved waiting for batchmates.
- **Admission control**: the per-tenant cap rejects with
  AdmissionError; rejected studies appear in the metrics.
- **Warm pool**: the hot engine/bucket set is compiled at server
  start, so serving traffic pays zero fresh compiles.
- **Metrics**: snapshots validate against the serving schema
  (the CI smoke's ``python -m tpudes.obs --serving`` gate).
"""

import dataclasses
import threading
import time

import jax
import numpy as np
import pytest

from tpudes.obs.device import ChunkStream, CompileTelemetry
from tpudes.obs.serving import ServingTelemetry, validate_serving_metrics
from tpudes.parallel.runtime import RUNTIME
from tpudes.serving import AdmissionError, StudyServer

KEY = jax.random.PRNGKey(11)


@pytest.fixture(autouse=True)
def _fresh_runtime():
    RUNTIME.clear()
    CompileTelemetry.reset()
    ChunkStream.reset()
    ServingTelemetry.reset()
    yield
    RUNTIME.clear()
    ServingTelemetry.reset()


def _lte_prog(n_ttis=60):
    from tpudes.parallel.programs import toy_lte_program

    return toy_lte_program(n_enb=2, n_ue=4, n_ttis=n_ttis)


def _tcp_prog(n_slots=120):
    from tpudes.parallel.programs import toy_dumbbell_program

    return toy_dumbbell_program(n_flows=3, n_slots=n_slots)


def _bss_prog(sim_end_us=60_000):
    from tpudes.parallel.programs import toy_bss_program

    return toy_bss_program(n_sta=4, sim_end_us=sim_end_us)


def _as_prog():
    from tpudes.parallel.programs import toy_as_program

    return toy_as_program(n_nodes=64, n_flows=3)


def _assert_equal(a: dict, b: dict):
    for k in b:
        np.testing.assert_array_equal(
            np.asarray(a[k]), np.asarray(b[k]), err_msg=f"field {k!r}"
        )


# --- coalescing correctness: bit-equal to solo, all four engines --------


def test_lte_coalesced_bit_equal_to_solo_and_one_launch():
    from tpudes.parallel.lte_sm import run_lte_sm

    prog = _lte_prog()
    scheds = ("pf", "rr", "fdmt")
    with StudyServer(start=False) as server:
        handles = [
            server.submit_study(
                "lte_sm", dataclasses.replace(prog, scheduler=s), KEY,
                replicas=3, tenant=f"user{i}",
            )
            for i, s in enumerate(scheds)
        ]
        server.pump()
        assert RUNTIME.launches("lte_sm") == 1, "3 studies, ONE launch"
        for h, s in zip(handles, scheds):
            solo = run_lte_sm(
                dataclasses.replace(prog, scheduler=s), KEY, replicas=3
            )
            _assert_equal(h.result(timeout=1), solo)
            assert h.batch_size == 3
        m = server.metrics()
    assert m["counters"]["coalesced_launches"] == 1
    assert m["counters"]["coalesced_studies"] == 3
    assert m["counters"]["pad_points"] == 1  # 3 -> pow2 bucket 4
    assert m["coalesce_rate"] == 1.0


def test_bss_coalesced_bit_equal_to_solo():
    from tpudes.parallel.replicated import run_replicated_bss

    prog = _bss_prog()
    ends = (40_000, 60_000)
    with StudyServer(start=False) as server:
        handles = [
            server.submit_study(
                "bss", dataclasses.replace(prog, sim_end_us=e), KEY, 5
            )
            for e in ends
        ]
        server.pump()
        assert RUNTIME.launches("bss") == 1
        for h, e in zip(handles, ends):
            solo = run_replicated_bss(
                dataclasses.replace(prog, sim_end_us=e), 5, KEY
            )
            got = h.result(timeout=1)
            # steps may differ (the coalesced launch shares one step
            # budget; finished replicas are fixed points) — compare
            # outcomes, as the sweep equality tests do
            for k in ("srv_rx", "cli_rx", "tx_data", "drops", "all_done"):
                np.testing.assert_array_equal(
                    np.asarray(got[k]), np.asarray(solo[k]), err_msg=k
                )


def test_tcp_coalesced_bit_equal_to_solo():
    from tpudes.parallel.tcp_dumbbell import (
        _variant_ecn,
        _variant_point,
        run_tcp_dumbbell,
    )

    prog = _tcp_prog()
    points = (["TcpNewReno"] * 3, ["TcpCubic"] * 3, ["TcpVegas"] * 3)

    def with_variants(p):
        ids = _variant_point(p)
        return dataclasses.replace(
            prog, variant_idx=ids, ecn=_variant_ecn(ids)
        )

    with StudyServer(start=False) as server:
        handles = [
            server.submit_study("dumbbell", with_variants(p), KEY, 4)
            for p in points
        ]
        server.pump()
        assert RUNTIME.launches("dumbbell") == 1
        for h, p in zip(handles, points):
            solo = run_tcp_dumbbell(with_variants(p), KEY, replicas=4)
            _assert_equal(h.result(timeout=1), solo)


def test_as_coalesced_bit_equal_to_solo():
    from tpudes.parallel.as_flows import run_as_flows

    prog = _as_prog()
    scales = (0.5, 1.0, 2.0)
    with StudyServer(start=False) as server:
        handles = [
            server.submit_study(
                "as_flows", prog, KEY, 5, rate_scale=s
            )
            for s in scales
        ]
        server.pump()
        assert RUNTIME.launches("as_flows") == 1
        for h, s in zip(handles, scales):
            solo = run_as_flows(prog, KEY, replicas=5, rate_scale=[s])[0]
            _assert_equal(h.result(timeout=1), solo)


# --- executable reuse: pow2 buckets + the plain single path -------------


def test_single_study_rides_the_plain_executable():
    from tpudes.parallel.lte_sm import run_lte_sm

    prog = _lte_prog()
    solo = run_lte_sm(prog, KEY, replicas=3)  # compiles the plain runner
    compiles = CompileTelemetry.compiles("lte_sm")
    with StudyServer(start=False) as server:
        h = server.submit_study("lte_sm", prog, KEY, replicas=3)
        server.pump()
        _assert_equal(h.result(timeout=1), solo)
        assert h.batch_size == 1
    assert CompileTelemetry.compiles("lte_sm") == compiles, (
        "a lone study must reuse the plain (non-sweep) executable"
    )


def test_repeat_batches_of_one_bucket_share_one_executable():
    prog = _lte_prog()
    with StudyServer(start=False) as server:
        for s in ("pf", "rr", "tdmt"):
            server.submit_study(
                "lte_sm", dataclasses.replace(prog, scheduler=s), KEY, 3
            )
        server.pump()  # 3 -> bucket 4: compiles the C=4 executable
        compiles = CompileTelemetry.compiles("lte_sm")
        for s in ("pf", "rr", "fdmt", "tdbet"):
            server.submit_study(
                "lte_sm", dataclasses.replace(prog, scheduler=s), KEY, 3
            )
        server.pump()  # exactly the bucket: same executable
        assert CompileTelemetry.compiles("lte_sm") == compiles
        assert RUNTIME.launches("lte_sm") == 2


# --- batching deadline: a lone study is never starved -------------------


def test_lone_study_dispatches_alone_within_max_wait():
    from tpudes.parallel.lte_sm import run_lte_sm

    prog = _lte_prog()
    run_lte_sm(prog, KEY, replicas=3)  # pre-compile the plain runner
    with StudyServer(max_wait_s=0.15, max_batch=8) as server:
        t0 = time.monotonic()
        h = server.submit_study("lte_sm", prog, KEY, replicas=3)
        result = h.result(timeout=30)
        waited = time.monotonic() - t0
    assert h.batch_size == 1, "no batchmates ever arrived"
    assert result["rx_bits"].shape == (3, 4)
    # it waited for batchmates up to (about) the deadline, then ran
    assert waited >= 0.5 * 0.15, f"dispatched before the window ({waited})"
    assert waited < 20.0, "starved far past the batching deadline"


# --- admission control ---------------------------------------------------


def test_tenant_cap_rejects_with_admission_error():
    prog = _lte_prog()
    with StudyServer(start=False, tenant_cap=2) as server:
        server.submit_study("lte_sm", prog, KEY, 3, tenant="a")
        server.submit_study("lte_sm", prog, KEY, 3, tenant="a")
        with pytest.raises(AdmissionError):
            server.submit_study("lte_sm", prog, KEY, 3, tenant="a")
        # another tenant is unaffected
        server.submit_study("lte_sm", prog, KEY, 3, tenant="b")
        server.pump()
        m = server.metrics()
    assert m["counters"]["rejected"] == 1
    assert m["counters"]["completed"] == 3


def test_cap_releases_as_studies_complete():
    prog = _lte_prog()
    with StudyServer(start=False, tenant_cap=2) as server:
        server.submit_study("lte_sm", prog, KEY, 3, tenant="a")
        server.submit_study("lte_sm", prog, KEY, 3, tenant="a")
        server.pump()
        # completed studies freed the cap
        h = server.submit_study("lte_sm", prog, KEY, 3, tenant="a")
        server.pump()
        assert h.result(timeout=1)["rx_bits"].shape == (3, 4)


# --- warm pool -----------------------------------------------------------


def test_warm_pool_precompiles_serving_buckets():
    prog = _lte_prog()
    server = StudyServer(start=False, max_batch=4)
    n = server.warm(
        [dict(engine="lte_sm", prog=prog, key=KEY, replicas=3)]
    )
    assert n == 3  # plain + C=2 + C=4 buckets
    compiles = CompileTelemetry.compiles("lte_sm")
    assert compiles >= 1
    # serving traffic of any batch size <= max_batch: zero fresh compiles
    for s in ("pf", "rr", "fdmt"):
        server.submit_study(
            "lte_sm", dataclasses.replace(prog, scheduler=s), KEY, 3
        )
    server.pump()
    server.submit_study("lte_sm", prog, KEY, 3)
    server.pump()
    assert CompileTelemetry.compiles("lte_sm") == compiles
    assert server.metrics()["counters"]["warm_programs"] == 3
    server.close()


# --- coalescing boundaries ----------------------------------------------


def test_uncoalescible_ecn_mismatch_is_served_solo():
    import numpy as _np

    from tpudes.parallel.tcp_dumbbell import run_tcp_dumbbell

    prog = _tcp_prog()
    # declared ECN disagrees with the variants' REQUIRES_ECN -> the
    # sweep contract cannot represent it; must be served solo
    odd = dataclasses.replace(prog, ecn=_np.ones(prog.n_flows, bool))
    with StudyServer(start=False) as server:
        h1 = server.submit_study("dumbbell", odd, KEY, 4)
        h2 = server.submit_study("dumbbell", odd, KEY, 4)
        server.pump()
        assert RUNTIME.launches("dumbbell") == 2, "solo studies never merge"
        solo = run_tcp_dumbbell(odd, KEY, replicas=4)
        _assert_equal(h1.result(timeout=1), solo)
        _assert_equal(h2.result(timeout=1), solo)
        assert h1.batch_size == 1 and h2.batch_size == 1


def test_different_engines_and_keys_do_not_coalesce():
    prog = _lte_prog()
    tcp = _tcp_prog()
    other_key = jax.random.PRNGKey(12)
    with StudyServer(start=False) as server:
        server.submit_study("lte_sm", prog, KEY, 3)
        server.submit_study("dumbbell", tcp, KEY, 4)
        server.submit_study("lte_sm", prog, other_key, 3)
        server.pump()
    # different engine or different PRNG key -> three separate launches
    assert RUNTIME.launches("lte_sm") == 2
    assert RUNTIME.launches("dumbbell") == 1


# --- background server ---------------------------------------------------


def test_background_server_coalesces_concurrent_clients():
    from tpudes.parallel.lte_sm import run_lte_sm

    prog = _lte_prog()
    run_lte_sm(prog, KEY, replicas=3)  # pre-compile the plain runner
    scheds = ("pf", "rr", "fdmt", "tdmt", "tta", "fdbet")
    results = {}
    with StudyServer(max_wait_s=0.2, max_batch=8) as server:
        def client(i, s):
            h = server.submit_study(
                "lte_sm", dataclasses.replace(prog, scheduler=s), KEY,
                replicas=3, tenant=f"user{i}",
            )
            results[i] = (h.result(timeout=60), h.batch_size)

        threads = [
            threading.Thread(target=client, args=(i, s))
            for i, s in enumerate(scheds)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        m = server.metrics()
    assert m["counters"]["completed"] == len(scheds)
    assert m["counters"]["coalesced_launches"] >= 1, (
        "concurrent compatible studies must share a launch"
    )
    # every result is the solo result, whatever batch it rode in
    for i, s in enumerate(scheds):
        solo = run_lte_sm(
            dataclasses.replace(prog, scheduler=s), KEY, replicas=3
        )
        _assert_equal(results[i][0], solo)


def test_close_completes_every_outstanding_handle():
    prog = _lte_prog()
    server = StudyServer(max_wait_s=60.0)  # deadline far away
    handles = [
        server.submit_study(
            "lte_sm", dataclasses.replace(prog, scheduler=s), KEY, 3
        )
        for s in ("pf", "rr")
    ]
    server.close()  # must force-dispatch + demux, not strand
    assert all(h.done() for h in handles)
    assert handles[0].result()["rx_bits"].shape == (3, 4)


# --- metrics surface -----------------------------------------------------


def test_metrics_snapshot_validates_and_dumps(tmp_path):
    import json

    from tpudes.obs.__main__ import main as obs_main

    prog = _lte_prog()
    with StudyServer(start=False) as server:
        for s in ("pf", "rr"):
            server.submit_study(
                "lte_sm", dataclasses.replace(prog, scheduler=s), KEY, 3
            )
        server.pump()
        m = server.metrics()
    assert validate_serving_metrics(m) == []
    assert m["engines"]["lte_sm"]["launches"] == 1
    assert m["engines"]["lte_sm"]["studies"] == 2
    assert m["engines"]["lte_sm"]["batch_occupancy"] == 1.0  # 2 = pow2
    assert m["engines"]["lte_sm"]["launch_wall_s"]["n"] == 1
    assert m["engines"]["lte_sm"]["study_latency_s"]["p99"] >= 0.0
    path = tmp_path / "serving.json"
    path.write_text(json.dumps(m))
    assert obs_main(["--serving", str(path)]) == 0


def test_metrics_validator_rejects_malformed():
    assert validate_serving_metrics([]) != []
    assert validate_serving_metrics({"version": 1}) != []
    good = ServingTelemetry.snapshot()
    bad = dict(good)
    bad["engines"] = {"x": {"launches": "no"}}
    assert validate_serving_metrics(bad) != []


# --- runtime window sweep ------------------------------------------------


def test_runtime_poll_retires_finished_without_blocking():
    from tpudes.parallel.lte_sm import run_lte_sm

    prog = _lte_prog(n_ttis=40)
    f1 = RUNTIME.submit(run_lte_sm, prog, KEY, replicas=2)
    f2 = RUNTIME.submit(run_lte_sm, prog, KEY, replicas=2)
    f1.block()
    f2.block()
    assert RUNTIME.poll() == 2
    assert RUNTIME.stats()["in_flight"] == 0
    assert f1.done() and f2.done()


def test_submit_after_close_raises():
    """Review fix: a closed server never strands a handle — a racing
    submit after close() must raise instead of silently enqueueing a
    study no scheduler will ever drain."""
    server = StudyServer(start=False)
    server.close()
    with pytest.raises(RuntimeError, match="closed"):
        server.submit_study("dumbbell", _tcp_prog(), KEY, 1)
