"""Measured-trace ingestion (tpudes.traffic.ingest, ISSUE-15):
pcap/CSV → compressed exact-replay tables, round-tripped against
traffic the repo's own host applications generated through its own
pcap writer (ROADMAP item 4 remainder d)."""

import struct

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from tpudes.traffic import (  # noqa: E402
    TraceIngestError,
    TrafficProgram,
    ingest_traces,
    read_csv_trace,
    read_pcap,
)

PAYLOAD = 500
#: p2p wire bytes: payload + 8 UDP + 20 IPv4 + 2 PPP
WIRE = PAYLOAD + 30


def _ppbp_capture(tmp_path, sim_s=4.0, run=1):
    """Run a PPBPApplication over a p2p link with pcap enabled;
    return (pcap path, app Tx times µs, packets sent)."""
    from tpudes.core import Seconds, Simulator
    from tpudes.core.rng import ParetoRandomVariable, RngSeedManager
    from tpudes.core.world import reset_world
    from tpudes.helper.containers import NodeContainer
    from tpudes.helper.internet import (
        InternetStackHelper,
        Ipv4AddressHelper,
    )
    from tpudes.helper.point_to_point import PointToPointHelper
    from tpudes.models.applications import PPBPApplication, UdpServer
    from tpudes.network.address import InetSocketAddress

    reset_world()
    RngSeedManager.SetRun(run)
    nodes = NodeContainer()
    nodes.Create(2)
    p2p = PointToPointHelper()
    p2p.SetDeviceAttribute("DataRate", "100Mbps")
    p2p.SetChannelAttribute("Delay", "1ms")
    devs = p2p.Install(nodes)
    InternetStackHelper().Install(nodes)
    addr = Ipv4AddressHelper()
    addr.SetBase("10.0.0.0", "255.255.255.0")
    ifs = addr.Assign(devs)
    srv = UdpServer(Port=9)
    nodes.Get(1).AddApplication(srv)
    srv.SetStartTime(Seconds(0))
    app = PPBPApplication(
        Remote=InetSocketAddress(ifs.GetAddress(1), 9),
        BurstRate="100kbps",
        PacketSize=PAYLOAD,
        MeanBurstArrivals=2.0,
        BurstLength=ParetoRandomVariable(Scale=0.1, Shape=1.5, Bound=1.0),
    )
    nodes.Get(0).AddApplication(app)
    app.SetStartTime(Seconds(0.0))
    app.SetStopTime(Seconds(sim_s))
    times: list[int] = []
    app.TraceConnectWithoutContext(
        "Tx", lambda p: times.append(Simulator.Now().ticks // 1000)
    )
    p2p.EnablePcap(str(tmp_path / "ppbp"), devs.Get(0))
    Simulator.Stop(Seconds(sim_s + 0.05))
    Simulator.Run()
    Simulator.Destroy()  # flush + close the pcap
    return tmp_path / "ppbp-0-0.pcap", times, app.sent_packets


class TestPcapRoundTrip:
    def test_ppbp_capture_round_trips_into_exact_replay_tables(
        self, tmp_path
    ):
        """PPBP-generated traffic through the repo's own pcap writer,
        back through the ingester: every sent packet appears with its
        wire size at its µs send time, and the resulting
        TrafficProgram replays the capture EXACTLY on the device cum
        kernel."""
        path, tx_times, sent = _ppbp_capture(tmp_path)
        t, b = read_pcap(str(path))
        assert sent > 5  # the scenario actually generated traffic
        assert len(t) == sent == len(tx_times)
        assert (b == WIRE).all()
        # on the idle link every capture timestamp is the app's send
        # tick plus the constant serialization delay (530 B at
        # 100 Mbps ≈ 42.4 µs; ±1 µs from the sub-µs tick truncation
        # on both sides) — no queueing jitter to corrupt the trace's
        # relative timing
        offs = t - np.asarray(tx_times)
        assert (42 <= offs).all() and (offs <= 43).all(), offs

        tp = ingest_traces([(t, b)])
        assert tp.model == "trace"
        # the compressed table carries exactly the capture, rebased to
        # the first arrival and same-µs coalesced
        t0 = int(t.min())
        uniq, counts = np.unique(t - t0, return_counts=True)
        live = np.asarray(tp.arr_t[0]) < np.int32(2**30)
        np.testing.assert_array_equal(
            np.asarray(tp.arr_t[0])[live], uniq
        )
        np.testing.assert_array_equal(
            np.asarray(tp.arr_b[0])[live], counts * WIRE
        )
        # device replay: cumulative offered packets at the horizon ==
        # coalesced arrival count, and offered BYTES are conserved
        from tpudes.traffic.host import offered_packets

        horizon = int(uniq.max()) + 1
        assert offered_packets(tp, horizon)[0] == len(uniq)
        assert np.asarray(tp.arr_b[0])[live].sum() == sent * WIRE

    def test_device_window_bits_match_the_capture(self, tmp_path):
        """The LTE backlog fill (build_bits_fn) over an ingested
        capture returns exactly the capture's bytes in every window —
        the engine-facing half of the round trip."""
        import jax.numpy as jnp

        from tpudes.traffic.device import build_bits_fn

        path, _, _ = _ppbp_capture(tmp_path, run=2)
        t, b = read_pcap(str(path))
        tp = ingest_traces([(t, b)])
        bits_fn = jax.jit(build_bits_fn(tp))
        ops = tp.operands()
        key = jax.random.PRNGKey(0)
        t0 = int(t.min())
        horizon = int(t.max()) - t0 + 1
        win = max(1, horizon // 7)
        total = 0.0
        for lo in range(0, horizon + win, win):
            total += float(
                bits_fn(
                    ops, key, jnp.int32(lo), jnp.int32(lo + win)
                )[0]
            )
        assert total == float(b.sum() * 8)

    def test_endianness_nanosecond_and_pcapng(self, tmp_path):
        """Byte-swapped and nanosecond captures parse; pcapng refuses
        loudly with conversion advice."""
        rec = struct.pack(">IIII", 1, 500, 4, 64) + b"abcd"
        big = tmp_path / "big.pcap"
        big.write_bytes(
            struct.pack(">IHHiIII", 0xA1B2C3D4, 2, 4, 0, 0, 65535, 9)
            + rec
        )
        t, b = read_pcap(str(big))
        assert t.tolist() == [1_000_500] and b.tolist() == [64]
        ns = tmp_path / "ns.pcap"
        ns.write_bytes(
            struct.pack("<IHHiIII", 0xA1B23C4D, 2, 4, 0, 0, 65535, 9)
            + struct.pack("<IIII", 1, 500_000, 4, 64)
            + b"abcd"
        )
        t, b = read_pcap(str(ns))
        assert t.tolist() == [1_000_500]
        png = tmp_path / "x.pcapng"
        png.write_bytes(struct.pack("<I", 0x0A0D0D0A) + b"\0" * 20)
        with pytest.raises(TraceIngestError, match="pcapng"):
            read_pcap(str(png))
        with pytest.raises(TraceIngestError, match="not a libpcap"):
            garbage = tmp_path / "g.pcap"
            garbage.write_bytes(b"Z" * 24)
            read_pcap(str(garbage))


class TestCsvAndCompression:
    def test_csv_units_header_and_coalescing(self, tmp_path):
        p = tmp_path / "t.csv"
        p.write_text(
            "time,bytes\n0.001,100\n0.001,50\n0.250,700\n"
        )
        t, b = read_csv_trace(str(p))
        assert t.tolist() == [1000, 1000, 250000]
        tp = ingest_traces([(t, b)])
        # same-µs arrivals coalesce LOSSLESSLY (bytes sum)
        assert np.asarray(tp.arr_t[0])[:2].tolist() == [0, 249000]
        assert np.asarray(tp.arr_b[0])[:2].tolist() == [150, 700]
        # ms units
        p2 = tmp_path / "ms.csv"
        p2.write_text("5,10\n7,20\n")
        t2, _ = read_csv_trace(str(p2), time_unit="ms")
        assert t2.tolist() == [5000, 7000]
        with pytest.raises(TraceIngestError, match="time_unit"):
            read_csv_trace(str(p2), time_unit="h")
        with pytest.raises(TraceIngestError, match="no packet rows"):
            empty = tmp_path / "e.csv"
            empty.write_text("time,bytes\n")
            read_csv_trace(str(empty))

    def test_multi_entity_common_epoch_and_pad_to(self, tmp_path):
        """Relative timing between entities survives the rebase; a
        pad_to capacity joins an existing sweep's shape class."""
        e0 = (np.array([1_000_000, 1_000_400]), np.array([100, 200]))
        e1 = (np.array([1_000_200]), np.array([50]))
        tp = ingest_traces([e0, e1], pad_to=6)
        assert tp.arr_t.shape == (2, 6)
        assert np.asarray(tp.arr_t[0])[:2].tolist() == [0, 400]
        assert np.asarray(tp.arr_t[1])[0] == 200
        # shape-compatible with a synthetic 6-row trace program
        synth = TrafficProgram.trace_replay(
            np.full((2, 6), 2**30, np.int64)
        )
        assert tp.shape_key() == synth.shape_key()

    def test_refusals_are_loud(self):
        big_t = np.arange(5000) * 10
        big_b = np.full(5000, 100)
        with pytest.raises(TraceIngestError, match="max_rows"):
            ingest_traces([(big_t, big_b)], max_rows=1000)
        with pytest.raises(TraceIngestError, match="pad_to"):
            ingest_traces(
                [(np.array([0, 1, 2]), np.array([1, 1, 1]))], pad_to=2
            )
        with pytest.raises(TraceIngestError, match="epoch"):
            ingest_traces(
                [(np.array([5]), np.array([1]))], t0_us=10
            )
        with pytest.raises(TraceIngestError, match="horizon"):
            ingest_traces(
                [(np.array([0, 2**31]), np.array([1, 1]))], t0_us=0
            )
        with pytest.raises(TraceIngestError, match="empty"):
            ingest_traces(
                [(np.zeros(0, np.int64), np.zeros(0, np.int64))]
            )
