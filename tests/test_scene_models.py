"""Antenna, buildings, topology readers, CsvReader tests.

Upstream analogs: src/antenna/test (pattern values at canonical
angles), src/buildings/test (wall-loss classification), topology-read
parsing tests, csv-reader test suite.
"""

import io
import math

import numpy as np
import pytest

from tpudes.core.csv_reader import CsvReader
from tpudes.models.antenna import (
    Angles,
    CosineAntennaModel,
    IsotropicAntennaModel,
    ParabolicAntennaModel,
    ThreeGppAntennaModel,
)
from tpudes.models.buildings import (
    Building,
    BuildingsPropagationLossModel,
    batch_wall_crossings,
)
from tpudes.helper.topology_read import TopologyReaderHelper


# --- antenna ----------------------------------------------------------------
def test_isotropic_gain_everywhere():
    a = IsotropicAntennaModel(Gain=3.0)
    for az in (-math.pi, 0.0, 1.0):
        assert a.GetGainDb(Angles(az)) == 3.0


def test_parabolic_pattern_values():
    a = ParabolicAntennaModel(Orientation=0.0, Beamwidth=70.0,
                              MaxAttenuation=20.0)
    assert a.GetGainDb(Angles(0.0)) == pytest.approx(0.0)
    # at the -3dB half-beamwidth (35°): 12·(35/70)² = 3 dB down
    assert a.GetGainDb(Angles(math.radians(35))) == pytest.approx(-3.0)
    # backlobe clamps at MaxAttenuation
    assert a.GetGainDb(Angles(math.pi)) == pytest.approx(-20.0)


def test_cosine_boresight_and_beamwidth():
    a = CosineAntennaModel(Orientation=0.0, HorizontalBeamwidth=120.0,
                           MaxGain=5.0)
    assert a.GetGainDb(Angles(0.0)) == pytest.approx(5.0)
    # the -3 dB point sits at half the beamwidth by construction
    assert a.GetGainDb(Angles(math.radians(60))) == pytest.approx(5.0 - 3.0)


def test_three_gpp_element_pattern():
    a = ThreeGppAntennaModel(Orientation=0.0)
    assert a.GetGainDb(Angles(0.0)) == pytest.approx(8.0)
    # 65° horizontal: 12·(65/65)² = 12 dB down
    assert a.GetGainDb(Angles(math.radians(65.0))) == pytest.approx(8.0 - 12.0)


def test_angles_from_positions():
    class V:
        def __init__(self, x, y, z):
            self.x, self.y, self.z = x, y, z

    ang = Angles.FromPositions(V(0, 0, 0), V(1, 1, 0))
    assert ang.azimuth == pytest.approx(math.pi / 4)
    assert ang.inclination == pytest.approx(math.pi / 2)


# --- buildings --------------------------------------------------------------
def test_wall_crossings_through_and_inside():
    Building(x_min=10, x_max=20, y_min=-5, y_max=5, z_min=0, z_max=10,
             ExternalWallsType=Building.CONCRETE_WITH_WINDOWS)  # 7 dB walls
    tx = np.array([[0.0, 0.0, 1.5]])
    through = np.array([[30.0, 0.0, 1.5]])     # crosses both walls
    inside = np.array([[15.0, 0.0, 1.5]])      # ends inside: one wall
    clear = np.array([[0.0, 30.0, 1.5]])       # misses entirely
    assert batch_wall_crossings(tx, through)[0, 0] == pytest.approx(14.0)
    assert batch_wall_crossings(tx, inside)[0, 0] == pytest.approx(7.0)
    assert batch_wall_crossings(tx, clear)[0, 0] == 0.0


def test_buildings_loss_model_chains_on_outdoor():
    from tpudes.models.propagation import LogDistancePropagationLossModel

    Building(x_min=40, x_max=60, y_min=-10, y_max=10,
             ExternalWallsType=Building.CONCRETE_WITHOUT_WINDOWS)  # 15 dB
    model = BuildingsPropagationLossModel(
        outdoor_model=LogDistancePropagationLossModel()
    )
    p_tx = np.array([[0.0, 0.0, 1.5]])
    p_rx = np.array([[100.0, 0.0, 1.5]])    # through both walls: 30 dB
    d = np.array([[100.0]])
    base = model.outdoor.batch_rx_power(0.0, d)
    full = model.batch_rx_power(0.0, d, p_tx, p_rx)
    assert float(np.asarray(base - full)[0, 0]) == pytest.approx(30.0)


def test_lte_controller_applies_buildings_and_antenna():
    """A building between eNB and UE + a sector antenna pointed away
    must both depress the DL gain matrix."""
    from tpudes.helper.containers import NodeContainer
    from tpudes.models.antenna import ParabolicAntennaModel
    from tpudes.models.lte import LteHelper
    from tpudes.models.mobility import (
        ListPositionAllocator,
        MobilityHelper,
        Vector,
    )

    lte = LteHelper()
    enbs = NodeContainer()
    enbs.Create(1)
    ues = NodeContainer()
    ues.Create(2)
    ea = ListPositionAllocator()
    ea.Add(Vector(0, 0, 30))
    me = MobilityHelper()
    me.SetPositionAllocator(ea)
    me.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    me.Install(enbs)
    ua = ListPositionAllocator()
    ua.Add(Vector(100, 0, 1.5))    # east
    ua.Add(Vector(-100, 0, 1.5))   # west
    mu = MobilityHelper()
    mu.SetPositionAllocator(ua)
    mu.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    mu.Install(ues)
    enb_devs = lte.InstallEnbDevice(enbs)
    ue_devs = lte.InstallUeDevice(ues)
    lte.Attach([ue_devs.Get(0), ue_devs.Get(1)])
    ctrl = lte.controller
    ctrl._rebuild()
    sym = ctrl._gain_dl.copy()
    assert sym[0, 0] == pytest.approx(sym[0, 1], rel=1e-6)

    # east-facing sector: the west UE loses its backlobe attenuation
    enb_devs.Get(0).phy.antenna = ParabolicAntennaModel(
        Orientation=0.0, MaxAttenuation=20.0
    )
    ctrl._dirty = True
    ctrl._rebuild()
    with_ant = ctrl._gain_dl.copy()
    assert with_ant[0, 0] == pytest.approx(sym[0, 0], rel=1e-6)
    assert 10 * np.log10(with_ant[0, 1] / sym[0, 1]) == pytest.approx(-20.0)

    # drop a tall building across the east path (the 30 m eNB clears a
    # default 10 m roof): only the east UE suffers
    Building(x_min=40, x_max=60, y_min=-10, y_max=10, z_min=0, z_max=50,
             ExternalWallsType=Building.CONCRETE_WITH_WINDOWS)
    ctrl._dirty = True
    ctrl._rebuild()
    with_bld = ctrl._gain_dl
    assert 10 * np.log10(with_bld[0, 0] / with_ant[0, 0]) == pytest.approx(-14.0)
    assert with_bld[0, 1] == pytest.approx(with_ant[0, 1], rel=1e-6)


def test_rem_reflects_antenna_and_buildings():
    """The REM grid must see the same scene the controller does."""
    from tpudes.helper.containers import NodeContainer
    from tpudes.models.lte import LteHelper, RadioEnvironmentMapHelper
    from tpudes.models.antenna import ParabolicAntennaModel
    from tpudes.models.mobility import (
        ListPositionAllocator,
        MobilityHelper,
        Vector,
    )

    lte = LteHelper()
    enbs = NodeContainer()
    enbs.Create(1)
    ea = ListPositionAllocator()
    ea.Add(Vector(0, 0, 30))
    me = MobilityHelper()
    me.SetPositionAllocator(ea)
    me.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    me.Install(enbs)
    enb_devs = lte.InstallEnbDevice(enbs)
    ues = NodeContainer()
    ues.Create(1)
    ua = ListPositionAllocator()
    ua.Add(Vector(50, 0, 1.5))
    mu = MobilityHelper()
    mu.SetPositionAllocator(ua)
    mu.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    mu.Install(ues)
    ue_devs = lte.InstallUeDevice(ues)
    lte.Attach([ue_devs.Get(0)])
    rem = RadioEnvironmentMapHelper(lte)
    flat, _ = rem.Compute(-200, 200, -200, 200, 9)
    enb_devs.Get(0).phy.antenna = ParabolicAntennaModel(
        Orientation=0.0, MaxAttenuation=20.0
    )
    shaped, _ = rem.Compute(-200, 200, -200, 200, 9)
    mid = 4  # the y=0 row; east column > west column under the sector
    assert shaped[mid, -1] > shaped[mid, 0]
    # a single-cell map has no interference: backlobe drops SINR by
    # the full attenuation
    assert flat[mid, 0] - shaped[mid, 0] == pytest.approx(20.0, abs=0.5)


# --- topology readers -------------------------------------------------------
def test_inet_reader_round_trip(tmp_path):
    f = tmp_path / "topo.inet"
    f.write_text(
        "3 2\n"
        "0 10.0 20.0\n"
        "1 30.0 20.0\n"
        "2 50.0 20.0\n"
        "0 1 1.0\n"
        "1 2 2.5\n"
    )
    h = TopologyReaderHelper()
    h.SetFileName(str(f))
    h.SetFileType("Inet")
    reader = h.GetTopologyReader()
    assert reader.NodesSize() == 3 and reader.LinksSize() == 2
    g = reader.ToGraph()
    assert g.n == 3 and g.m == 2
    assert tuple(g.pos[1]) == (30.0, 20.0)
    assert g.is_connected()


def test_orbis_and_rocketfuel_readers(tmp_path):
    orbis = tmp_path / "topo.orbis"
    orbis.write_text("a b\nb c\nc a\n")
    h = TopologyReaderHelper()
    h.SetFileName(str(orbis))
    h.SetFileType("Orbis")
    r = h.GetTopologyReader()
    assert r.NodesSize() == 3 and r.LinksSize() == 3

    rf = tmp_path / "topo.rf"
    rf.write_text("Seattle,WA Portland,OR 2.5\nPortland,OR Boise,ID 4\n")
    h.SetFileName(str(rf))
    h.SetFileType("Rocketfuel")
    r = h.GetTopologyReader()
    assert r.NodesSize() == 3 and r.LinksSize() == 2
    assert r.GetLinks()[0][2]["weight"] == 2.5


def test_topology_graph_runs_in_flow_engine(tmp_path):
    """A read topology drops into the config-#5 flow engine."""
    import jax

    from tpudes.parallel.as_flows import AsFlowsProgram, run_as_flows

    f = tmp_path / "line.inet"
    f.write_text(
        "4 3\n0 0 0\n1 1 0\n2 2 0\n3 3 0\n0 1 1\n1 2 1\n2 3 1\n"
    )
    h = TopologyReaderHelper()
    h.SetFileName(str(f))
    h.SetFileType("Inet")
    g = h.GetTopologyReader().ToGraph()
    prog = AsFlowsProgram(
        n=g.n, edges=g.edges, delay_s=g.delay_s, rate_bps=g.rate_bps,
        src=np.array([0], np.int32), dst=np.array([3], np.int32),
        flow_bps=np.array([1e5]), pkt_bytes=512, sim_s=1.0,
        max_hops=8, spf_rounds=8, rate_jitter=0.0,
    )
    out = run_as_flows(prog, jax.random.PRNGKey(0), replicas=2)
    assert int(np.asarray(out["hops"])[0]) == 3
    assert not np.asarray(out["unreachable"]).any()


# --- csv reader -------------------------------------------------------------
def test_csv_reader_types_comments_quotes():
    src = io.StringIO(
        "# a comment line\n"
        "1,hello,3.5,true\n"
        "\n"
        '2,"with, comma",4.5,false\n'
    )
    r = CsvReader(src)
    assert r.FetchNextRow()
    assert r.GetValue(0, int) == 1
    assert r.GetValue(1) == "hello"
    assert r.GetValue(2, float) == 3.5
    assert r.GetValue(3, bool) is True
    assert r.FetchNextRow()
    assert r.GetValue(1) == "with, comma"
    assert r.GetValue(3, bool) is False
    assert not r.FetchNextRow()
    assert r.row_number == 2
    with pytest.raises(IndexError):
        r.GetValue(0)

def test_building_floor_attributes_set_and_read_like_upstream():
    """Promoted REG001 regression: the NFloors and Type attributes must
    bind to live fields — settable at construction and via
    SetAttribute, readable via the upstream getter surface."""
    b = Building(x_min=0, x_max=10, y_min=0, y_max=10, z_min=0, z_max=12,
                 NFloors=4, Type=Building.OFFICE)
    assert b.GetNFloors() == 4
    assert b.GetBuildingType() == Building.OFFICE
    assert b.IsOffice() and not b.IsResidential()
    assert b.GetAttribute("NFloors") == 4
    assert b.GetAttribute("Type") == Building.OFFICE
    b.SetAttribute("NFloors", 3)
    b.SetAttribute("Type", Building.COMMERCIAL)
    assert b.GetNFloors() == 3 and b.IsCommercial()
    # floor classification: 12 m / 3 floors = 4 m per floor
    assert b.floor_height_m() == pytest.approx(4.0)
    assert b.floor_at(1.5) == 0
    assert b.floor_at(5.0) == 1
    assert b.floor_at(11.9) == 2
    assert b.floor_at(12.0) == 2  # clamped at the roof


def test_same_building_floor_penetration_by_type():
    """ITU-R P.1238 floor factors (upstream itu-r-1238 model): the
    loss model must charge Lf for endpoints sharing a multi-floor
    building, by building type, and nothing for same-floor pairs."""
    from tpudes.models.buildings import batch_floor_penetration

    b = Building(x_min=0, x_max=20, y_min=0, y_max=20, z_min=0, z_max=9,
                 NFloors=3, Type=Building.RESIDENTIAL)
    ground = np.array([[5.0, 5.0, 1.5]])
    same = np.array([[15.0, 15.0, 1.5]])     # same floor
    one_up = np.array([[5.0, 5.0, 4.5]])     # floor 1
    two_up = np.array([[5.0, 5.0, 7.5]])     # floor 2
    outside = np.array([[50.0, 50.0, 1.5]])
    assert batch_floor_penetration(ground, same)[0, 0] == 0.0
    assert batch_floor_penetration(ground, one_up)[0, 0] == pytest.approx(4.0)
    assert batch_floor_penetration(ground, two_up)[0, 0] == pytest.approx(8.0)
    assert batch_floor_penetration(ground, outside)[0, 0] == 0.0

    b.SetBuildingType(Building.OFFICE)       # 15 + 4(n-1)
    assert batch_floor_penetration(ground, one_up)[0, 0] == pytest.approx(15.0)
    assert batch_floor_penetration(ground, two_up)[0, 0] == pytest.approx(19.0)
    b.SetBuildingType(Building.COMMERCIAL)   # 6 + 3(n-1)
    assert batch_floor_penetration(ground, two_up)[0, 0] == pytest.approx(9.0)


def test_loss_model_charges_floors_in_calc_rx_power():
    """The scalar CalcRxPower path must see the floor term too (the
    model routes through the batched kernel)."""
    Building(x_min=0, x_max=20, y_min=0, y_max=20, z_min=0, z_max=9,
             NFloors=3, Type=Building.RESIDENTIAL)

    class M:
        def __init__(self, x, y, z):
            self._p = type("V", (), {"x": x, "y": y, "z": z})()

        def GetPosition(self):
            return self._p

    model = BuildingsPropagationLossModel()
    same = model.CalcRxPower(0.0, M(5, 5, 1.5), M(15, 15, 1.5))
    up2 = model.CalcRxPower(0.0, M(5, 5, 1.5), M(5, 5, 7.5))
    assert same == pytest.approx(0.0)     # indoor same floor: no walls
    assert up2 == pytest.approx(-8.0)     # two floors at 4 dB each
