"""Test configuration.

JAX runs on a virtual 8-device CPU mesh (SURVEY.md 4: the analog of ns-3's
mpirun-on-localhost distributed test harness) — set before any jax import.
Every test gets a fresh simulator world.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# a sitecustomize may force an accelerator platform regardless of
# JAX_PLATFORMS (e.g. the axon TPU plugin); pin the test backend to the
# virtual CPU mesh explicitly
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def fresh_world():
    """Reset all process-global simulator state between tests."""
    from tpudes.core.world import reset_world

    yield
    reset_world()
