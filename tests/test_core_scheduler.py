"""Scheduler implementations: ordering, FIFO-at-same-ts, lazy removal.

Mirrors upstream scheduler test strategy (src/core/test/...; SURVEY.md 4):
all five queue types must produce identical (ts, uid) pop order.
"""

import random

import pytest

from tpudes.core.event import Event
from tpudes.core.scheduler import (
    CalendarScheduler,
    HeapScheduler,
    ListScheduler,
    MapScheduler,
    PriorityQueueScheduler,
    create_scheduler,
)

ALL = [HeapScheduler, ListScheduler, MapScheduler, CalendarScheduler, PriorityQueueScheduler]


def make_events(n, seed=42):
    rng = random.Random(seed)
    return [Event(rng.randrange(0, 10_000_000), uid, 0, lambda: None, ()) for uid in range(n)]


@pytest.mark.parametrize("cls", ALL)
def test_pop_order(cls):
    events = make_events(500)
    s = cls()
    for e in events:
        s.Insert(e)
    expected = sorted(events, key=lambda e: (e.ts, e.uid))
    popped = []
    while not s.IsEmpty():
        popped.append(s.RemoveNext())
    assert popped == expected


@pytest.mark.parametrize("cls", ALL)
def test_same_ts_fifo(cls):
    s = cls()
    events = [Event(100, uid, 0, lambda: None, ()) for uid in range(50)]
    shuffled = events[:]
    random.Random(7).shuffle(shuffled)
    for e in shuffled:
        s.Insert(e)
    assert [s.RemoveNext().uid for _ in range(50)] == list(range(50))


@pytest.mark.parametrize("cls", ALL)
def test_cancel_skipped(cls):
    s = cls()
    events = make_events(100)
    for e in events:
        s.Insert(e)
    for e in events[::3]:
        s.Remove(e)
    live = sorted((e for e in events if not e.cancelled), key=lambda e: (e.ts, e.uid))
    popped = []
    while not s.IsEmpty():
        popped.append(s.RemoveNext())
    assert popped == live


@pytest.mark.parametrize("cls", ALL)
def test_all_cancelled_is_empty(cls):
    s = cls()
    events = make_events(20)
    for e in events:
        s.Insert(e)
    for e in events:
        s.Remove(e)
    assert s.IsEmpty()


@pytest.mark.parametrize("cls", ALL)
def test_interleaved_insert_pop(cls):
    rng = random.Random(3)
    s = cls()
    uid = 0
    last = (-1, -1)
    now = 0
    for _ in range(2000):
        if s.IsEmpty() or rng.random() < 0.55:
            e = Event(now + rng.randrange(0, 1000), uid, 0, lambda: None, ())
            uid += 1
            s.Insert(e)
        else:
            e = s.RemoveNext()
            key = (e.ts, e.uid)
            assert key > last or key[0] >= last[0]
            now = e.ts
            last = key


def test_factory_names():
    for name in (
        "tpudes::HeapScheduler",
        "tpudes::MapScheduler",
        "tpudes::ListScheduler",
        "tpudes::CalendarScheduler",
        "ns3::MapScheduler",
    ):
        assert create_scheduler(name) is not None
    with pytest.raises(ValueError):
        create_scheduler("nope")
