"""Packet/node layer: Packet value semantics, addresses, queues, error
models, SimpleNetDevice delivery (parity with upstream
src/network/test/; SURVEY.md 2.2, 4)."""

import pytest

from tpudes.core.nstime import MilliSeconds, Seconds
from tpudes.core.simulator import Simulator
from tpudes.network.address import (
    InetSocketAddress,
    Ipv4Address,
    Ipv4Mask,
    Mac48Address,
)
from tpudes.network.data_rate import DataRate
from tpudes.network.error_model import ListErrorModel, RateErrorModel
from tpudes.network.net_device import SimpleChannel, SimpleNetDevice
from tpudes.network.node import Node, NodeList
from tpudes.network.packet import Header, LlcSnapHeader, Packet, Tag
from tpudes.network.queue import DropTailQueue, QueueSize


class FakeHeader(Header):
    def __init__(self, x=0):
        self.x = x

    def GetSerializedSize(self):
        return 4

    def Serialize(self):
        return self.x.to_bytes(4, "big")


class FlowTag(Tag):
    def __init__(self, flow_id):
        self.flow_id = flow_id


def test_packet_headers_lifo_and_size():
    p = Packet(100)
    p.AddHeader(FakeHeader(1))
    p.AddHeader(FakeHeader(2))
    assert p.GetSize() == 108
    h = p.RemoveHeader(FakeHeader)
    assert h.x == 2  # last added = front of packet
    assert p.RemoveHeader().x == 1
    assert p.GetSize() == 100


def test_packet_copy_value_semantics():
    p = Packet(50)
    p.AddHeader(FakeHeader(9))
    c = p.Copy()
    c.RemoveHeader()
    assert p.GetSize() == 54  # original unaffected (COW)
    assert c.GetSize() == 50
    assert c.GetUid() == p.GetUid()  # copies share uid, as in ns-3


def test_packet_tags():
    p = Packet(10)
    p.AddPacketTag(FlowTag(7))
    c = p.Copy()
    assert c.PeekPacketTag(FlowTag).flow_id == 7
    removed = c.RemovePacketTag(FlowTag)
    assert removed.flow_id == 7
    assert c.PeekPacketTag(FlowTag) is None
    assert p.PeekPacketTag(FlowTag).flow_id == 7  # original keeps its tag


def test_packet_wire_serialization():
    p = Packet(b"abc")
    p.AddHeader(LlcSnapHeader(0x0806))
    raw = p.ToBytes()
    assert len(raw) == 11
    h, consumed = LlcSnapHeader.Deserialize(raw)
    assert consumed == 8 and h.ether_type == 0x0806
    assert raw[8:] == b"abc"


def test_mac48_allocate_unique():
    a, b = Mac48Address.Allocate(), Mac48Address.Allocate()
    assert a != b
    assert str(Mac48Address("00:00:00:00:00:01")) == "00:00:00:00:00:01"
    assert Mac48Address.GetBroadcast().IsBroadcast()


def test_ipv4_address_and_mask():
    a = Ipv4Address("10.1.1.5")
    m = Ipv4Mask("255.255.255.0")
    assert str(a.CombineMask(m)) == "10.1.1.0"
    assert m.IsMatch(a, Ipv4Address("10.1.1.200"))
    assert not m.IsMatch(a, Ipv4Address("10.1.2.5"))
    assert m.GetPrefixLength() == 24
    assert Ipv4Mask("/16").GetPrefixLength() == 16
    assert str(a.GetSubnetDirectedBroadcast(m)) == "10.1.1.255"
    sa = InetSocketAddress("10.1.1.5", 80)
    assert sa.GetPort() == 80 and sa.GetIpv4() == a


def test_data_rate_parsing_and_tx_time():
    r = DataRate("5Mbps")
    assert r.GetBitRate() == 5_000_000
    t = r.CalculateBytesTxTime(625)  # 5000 bits @ 5Mbps = 1ms
    assert t == MilliSeconds(1)
    assert DataRate("1kbps").GetBitRate() == 1000
    with pytest.raises(ValueError):
        DataRate("5flops")


def test_drop_tail_queue_packet_mode():
    q = DropTailQueue(MaxSize="2p")
    drops = []
    q.TraceConnectWithoutContext("Drop", drops.append)
    assert q.Enqueue(Packet(100)) and q.Enqueue(Packet(100))
    assert not q.Enqueue(Packet(100))  # full -> tail drop
    assert len(drops) == 1
    assert q.GetNPackets() == 2
    assert q.Dequeue().GetSize() == 100
    assert q.GetNPackets() == 1


def test_drop_tail_queue_byte_mode():
    q = DropTailQueue(MaxSize="250B")
    assert q.Enqueue(Packet(100)) and q.Enqueue(Packet(100))
    assert not q.Enqueue(Packet(100))  # 300B > 250B
    assert q.GetNBytes() == 200


def test_queue_size_parsing():
    assert QueueSize("10p").mode == QueueSize.PACKETS
    assert QueueSize("64kB").value == 64000


def test_rate_error_model_statistics():
    em = RateErrorModel(ErrorRate=0.1, ErrorUnit=RateErrorModel.ERROR_UNIT_PACKET)
    em.AssignStreams(50)
    n = 10000
    corrupted = sum(1 for _ in range(n) if em.IsCorrupt(Packet(10)))
    assert abs(corrupted / n - 0.1) < 0.02


def test_list_error_model_deterministic():
    em = ListErrorModel()
    p1, p2, p3 = Packet(1), Packet(1), Packet(1)
    em.SetList([p2.GetUid()])
    assert not em.IsCorrupt(p1)
    assert em.IsCorrupt(p2)
    assert not em.IsCorrupt(p3)
    em.Disable()
    assert not em.IsCorrupt(p2)


def test_node_registry_and_device():
    n1, n2 = Node(), Node()
    assert NodeList.GetNNodes() == 2
    assert NodeList.GetNode(n1.GetId()) is n1
    d = SimpleNetDevice()
    assert n1.AddDevice(d) == 0
    assert d.GetNode() is n1 and n1.GetDevice(0) is d


def test_simple_channel_end_to_end_delivery():
    n1, n2 = Node(), Node()
    d1, d2 = SimpleNetDevice(), SimpleNetDevice()
    n1.AddDevice(d1)
    n2.AddDevice(d2)
    ch = SimpleChannel(Delay=MilliSeconds(5))
    d1.SetChannel(ch)
    d2.SetChannel(ch)

    got = []
    d2.SetReceiveCallback(
        lambda dev, pkt, proto, sender: got.append(
            (pkt.GetSize(), proto, str(sender), Simulator.Now())
        )
    )
    Simulator.Schedule(Seconds(1), d1.Send, Packet(123), d2.GetAddress(), 0x0800)
    Simulator.Run()
    assert len(got) == 1
    size, proto, sender, t = got[0]
    assert size == 123 and proto == 0x0800
    assert sender == str(d1.GetAddress())
    assert t == Seconds(1) + MilliSeconds(5)


def test_simple_device_error_model_drop_trace():
    n1, n2 = Node(), Node()
    d1, d2 = SimpleNetDevice(), SimpleNetDevice()
    n1.AddDevice(d1)
    n2.AddDevice(d2)
    ch = SimpleChannel()
    d1.SetChannel(ch)
    d2.SetChannel(ch)
    em = ListErrorModel()
    d2.SetReceiveErrorModel(em)

    got, dropped = [], []
    d2.SetReceiveCallback(lambda dev, pkt, proto, sender: got.append(pkt))
    d2.TraceConnectWithoutContext("PhyRxDrop", dropped.append)

    p_lost = Packet(10)
    em.SetList([p_lost.GetUid()])
    Simulator.Schedule(Seconds(1), d1.Send, p_lost, d2.GetAddress(), 0)
    Simulator.Schedule(Seconds(2), d1.Send, Packet(20), d2.GetAddress(), 0)
    Simulator.Run()
    assert len(got) == 1 and got[0].GetSize() == 20
    assert len(dropped) == 1


def test_broadcast_reaches_all_but_sender():
    nodes = [Node() for _ in range(4)]
    devs = [SimpleNetDevice() for _ in range(4)]
    ch = SimpleChannel()
    for n, d in zip(nodes, devs):
        n.AddDevice(d)
        d.SetChannel(ch)
    got = []
    for i, d in enumerate(devs):
        d.SetReceiveCallback(lambda dev, pkt, proto, sender, i=i: got.append(i))
    Simulator.Schedule(Seconds(1), devs[0].Send, Packet(5), Mac48Address.GetBroadcast(), 0)
    Simulator.Run()
    assert sorted(got) == [1, 2, 3]


def test_disposed_application_never_starts_or_stops():
    """Upstream Application::DoDispose cancels the pending start/stop
    events (the promoted EVT001 baseline finding): a disposed app must
    not fire either callback when the simulation runs on."""
    from tpudes.network.application import Application

    calls = []

    class Probe(Application):
        tid = Application.tid

        def StartApplication(self):
            calls.append("start")

        def StopApplication(self):
            calls.append("stop")

    node = Node()
    app = Probe()
    app.SetStartTime(Seconds(1.0))
    app.SetStopTime(Seconds(2.0))
    node.AddApplication(app)
    Simulator.Schedule(Seconds(0.5), app.Dispose)
    Simulator.Stop(Seconds(3.0))
    Simulator.Run()
    assert calls == []

    # un-disposed control: both fire
    Simulator.Destroy()
    node2 = Node()
    app2 = Probe()
    app2.SetStartTime(Seconds(0.1))
    app2.SetStopTime(Seconds(0.2))
    node2.AddApplication(app2)
    Simulator.Stop(Seconds(1.0))
    Simulator.Run()
    assert calls == ["start", "stop"]
