"""Bridge tests — upstream src/bridge/examples/csma-bridge strategy:
two CSMA segments joined by a learning switch; flooding before
learning, unicast confinement after, end-to-end IP traffic."""

import pytest

from tpudes.core import Seconds, Simulator
from tpudes.helper.applications import UdpEchoClientHelper, UdpEchoServerHelper
from tpudes.helper.containers import NetDeviceContainer, NodeContainer
from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
from tpudes.models.bridge import BridgeHelper, BridgeNetDevice
from tpudes.models.csma import CsmaHelper


def _bridged_lans(hosts_per_side=2):
    """host0,host1 ── csmaA ── [bridge] ── csmaB ── host2,host3; one IP
    subnet spanning both segments (the classic csma-bridge.cc)."""
    hosts = NodeContainer()
    hosts.Create(2 * hosts_per_side)
    switch = NodeContainer()
    switch.Create(1)
    csma = CsmaHelper()
    csma.SetChannelAttribute("DataRate", "100Mbps")
    csma.SetChannelAttribute("Delay", Seconds(2e-6))

    seg_a = NodeContainer()
    for i in range(hosts_per_side):
        seg_a.Add(hosts.Get(i))
    seg_a.Add(switch.Get(0))
    devs_a = csma.Install(seg_a)
    seg_b = NodeContainer()
    for i in range(hosts_per_side, 2 * hosts_per_side):
        seg_b.Add(hosts.Get(i))
    seg_b.Add(switch.Get(0))
    devs_b = csma.Install(seg_b)

    ports = NetDeviceContainer()
    ports.Add(devs_a.Get(hosts_per_side))   # switch's port on A
    ports.Add(devs_b.Get(hosts_per_side))   # switch's port on B
    bridge = BridgeHelper().Install(switch.Get(0), ports)

    InternetStackHelper().Install(hosts)
    host_devs = NetDeviceContainer()
    for i in range(hosts_per_side):
        host_devs.Add(devs_a.Get(i))
    for i in range(hosts_per_side):
        host_devs.Add(devs_b.Get(i))
    ifc = Ipv4AddressHelper("10.1.1.0", "255.255.255.0").Assign(host_devs)
    return hosts, host_devs, ifc, bridge


def test_cross_segment_echo_through_the_bridge():
    hosts, devs, ifc, bridge = _bridged_lans()
    server = UdpEchoServerHelper(9)
    sapps = server.Install(hosts.Get(3))    # segment B
    sapps.Start(Seconds(0.0))
    rx = [0]
    sapps.Get(0).TraceConnectWithoutContext(
        "Rx", lambda *a: rx.__setitem__(0, rx[0] + 1)
    )
    client = UdpEchoClientHelper(ifc.GetAddress(3), 9)
    client.SetAttribute("MaxPackets", 5)
    client.SetAttribute("Interval", Seconds(0.01))
    cli_rx = [0]
    capps = client.Install(hosts.Get(0))    # segment A
    capps.Start(Seconds(0.1))
    capps.Get(0).TraceConnectWithoutContext(
        "Rx", lambda *a: cli_rx.__setitem__(0, cli_rx[0] + 1)
    )
    Simulator.Stop(Seconds(1.0))
    Simulator.Run()
    assert rx[0] == 5 and cli_rx[0] == 5


def test_learning_confines_unicast_to_one_segment():
    hosts, devs, ifc, bridge = _bridged_lans()
    # same-segment traffic (host0 → host1 on A): after learning, the
    # bridge must not forward those unicasts onto segment B
    b_sniff = [0]
    devs.Get(2).TraceConnectWithoutContext(   # a host NIC on segment B
        "PromiscSniffer", lambda p: b_sniff.__setitem__(0, b_sniff[0] + 1)
    )
    server = UdpEchoServerHelper(9)
    sapps = server.Install(hosts.Get(1))
    sapps.Start(Seconds(0.0))
    client = UdpEchoClientHelper(ifc.GetAddress(1), 9)
    client.SetAttribute("MaxPackets", 20)
    client.SetAttribute("Interval", Seconds(0.01))
    client.Install(hosts.Get(0)).Start(Seconds(0.1))
    Simulator.Stop(Seconds(1.0))
    Simulator.Run()
    assert sapps.Get(0).received == 20
    # segment B sees the initial ARP broadcast + at most the first
    # unlearned flood, then silence: far fewer than the 40+ frames A saw
    assert b_sniff[0] <= 6, b_sniff[0]


def test_switch_with_management_stack_does_not_corrupt_floods():
    """The management-plane configuration (IP stack on the switch,
    upstream csma-bridge-one-hop): the node's ARP handler strips
    headers in place — flooded frames must be unaffected (r4 review:
    by-reference delivery crashed every receiving host)."""
    hosts, devs, ifc, bridge = _bridged_lans()
    switch = bridge.GetNode()
    InternetStackHelper().Install(switch)
    server = UdpEchoServerHelper(9)
    sapps = server.Install(hosts.Get(3))
    sapps.Start(Seconds(0.0))
    client = UdpEchoClientHelper(ifc.GetAddress(3), 9)
    client.SetAttribute("MaxPackets", 3)
    client.SetAttribute("Interval", Seconds(0.01))
    client.Install(hosts.Get(0)).Start(Seconds(0.1))
    Simulator.Stop(Seconds(1.0))
    Simulator.Run()  # the broken version raised IndexError here
    assert sapps.Get(0).received == 3


def test_port_without_sendfrom_is_rejected():
    """A port type that would re-stamp source MACs (base SendFrom
    fallback) must be refused, as upstream's SupportsSendFrom abort."""
    from tpudes.models.p2p import PointToPointNetDevice

    bridge = BridgeNetDevice()
    with pytest.raises(ValueError, match="SendFrom"):
        bridge.AddBridgePort(PointToPointNetDevice())


def test_learning_table_expires():
    from tpudes.network.address import Mac48Address

    bridge = BridgeNetDevice(ExpirationTime=Seconds(0.05))

    class Port:
        def SetPromiscReceiveCallback(self, cb):
            pass

        def SetReceiveCallback(self, cb):
            pass

    p = Port()
    bridge._ports.append(p)
    mac = Mac48Address(77)
    bridge._learn_station(mac, p)
    assert bridge._lookup(mac) is p
    Simulator.Stop(Seconds(0.1))
    Simulator.Run()
    assert bridge._lookup(mac) is None, "expired entry must age out"

def test_learning_table_aging_sweep_purges_stranded_entries():
    """Promoted EVT003 finding: an entry for a station the bridge never
    hears about (or looks up) again must still age OUT of the table —
    the periodic sweep, not just _lookup's lazy expiry, bounds it."""
    from tpudes.network.address import Mac48Address

    bridge = BridgeNetDevice(ExpirationTime=Seconds(0.05))

    class Port:
        def SetPromiscReceiveCallback(self, cb):
            pass

        def SetReceiveCallback(self, cb):
            pass

    p = Port()
    bridge._ports.append(p)
    bridge._learn_station(Mac48Address(78), p)
    assert len(bridge._learn) == 1
    Simulator.Stop(Seconds(0.2))
    Simulator.Run()
    # no _lookup ever ran: the sweep alone must have purged the entry
    assert len(bridge._learn) == 0
    # and the sweep chain disarms once the table is empty (no immortal
    # self-rescheduling event keeping every simulation alive)
    assert not bridge._age_event.IsPending()
