"""Traffic-control tests — upstream src/traffic-control/test strategy:
qdisc unit behavior (RED probability regions, CoDel sojourn law) plus
system-level behavior on the dumbbell bottleneck."""

import pytest

from tpudes.core import MilliSeconds, Seconds, Simulator
from tpudes.models.traffic_control import (
    CoDelQueueDisc,
    FifoQueueDisc,
    QueueDiscItem,
    RedQueueDisc,
    TrafficControlHelper,
    TrafficControlLayer,
)
from tpudes.network.packet import Packet
from tpudes.scenarios import build_dumbbell


def _item(size=1000):
    return QueueDiscItem(Packet(size), None, 0x0800)


def test_fifo_tail_drops_at_capacity():
    q = FifoQueueDisc(MaxSize=3)
    assert all(q.Enqueue(_item()) for _ in range(3))
    assert not q.Enqueue(_item())
    assert q.GetNPackets() == 3
    assert q.stats_dropped == 1


def test_red_no_drops_below_min_threshold():
    q = RedQueueDisc(MinTh=5.0, MaxTh=15.0, MaxSize=100)
    for _ in range(200):  # queue never exceeds 3
        q.Enqueue(_item())
        q.Enqueue(_item())
        q.Dequeue()
        q.Dequeue()
    assert q.stats_early_drops == 0
    assert q.stats_forced_drops == 0


def test_red_drops_probabilistically_between_thresholds():
    q = RedQueueDisc(MinTh=2.0, MaxTh=6.0, MaxSize=100, QW=0.2, LInterm=5.0)
    accepted = dropped = 0
    for _ in range(600):
        if q.Enqueue(_item()):
            accepted += 1
        else:
            dropped += 1
        if q.GetNPackets() > 4:   # hold the queue inside the band
            q.Dequeue()
    assert dropped > 10, "early drops must engage inside the band"
    assert accepted > dropped, "but most packets pass"
    assert q.stats_forced_drops == 0


def test_codel_drops_on_persistent_sojourn():
    q = CoDelQueueDisc(MaxSize=1000)
    # fill, then drain slowly so sojourn >> target (5 ms)
    for _ in range(50):
        q.Enqueue(_item())
    drops_before = q.stats_target_drops
    for _ in range(50):
        Simulator.Stop(MilliSeconds(20))
        Simulator.Run()
        q.Enqueue(_item())
        q.Dequeue()
    assert q.stats_target_drops > drops_before, "CoDel must engage"


def test_codel_idle_below_target_never_drops():
    q = CoDelQueueDisc(MaxSize=1000)
    for _ in range(100):
        q.Enqueue(_item())
        q.Dequeue()  # zero sojourn
    assert q.stats_target_drops == 0 and q.stats_dropped == 0


@pytest.mark.parametrize("disc,kw", [
    ("tpudes::RedQueueDisc",
     dict(MinTh=10.0, MaxTh=30.0, MaxSize=60, LinkBandwidth="5Mbps")),
    ("tpudes::CoDelQueueDisc", dict(MaxSize=200)),
])
def test_qdisc_on_dumbbell_keeps_throughput_and_sheds(disc, kw):
    db, sinks = build_dumbbell(
        4, 4.0, variant="TcpNewReno", bottleneck_rate="5Mbps"
    )
    tch = TrafficControlHelper()
    tch.SetRootQueueDisc(disc, **kw)
    (qdisc,) = tch.Install(db.GetBottleneckDevices().Get(0))
    Simulator.Stop(Seconds(4.0))
    Simulator.Run()
    tput = sum(s.GetTotalRx() for s in sinks) * 8 / 3.9 / 1e6
    assert tput > 3.0, f"{disc}: collapsed to {tput:.2f} Mbps"
    assert qdisc.stats_dropped > 0, "an AQM at a bottleneck must shed"
    # the backlog lived in the qdisc (flow control worked)
    assert qdisc.stats_enqueued > 1000


def test_qdisc_shapes_arp_resolved_csma_traffic():
    """TC must intercept at the device boundary so ARP-resolved unicast
    (CSMA/WiFi) rides the qdisc too (r4 review: the Ipv4Interface hook
    missed the ArpL3Protocol send path entirely)."""
    from tpudes.helper.applications import (
        UdpEchoClientHelper,
        UdpEchoServerHelper,
    )
    from tpudes.helper.containers import NodeContainer
    from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
    from tpudes.models.csma import CsmaHelper

    nodes = NodeContainer()
    nodes.Create(2)
    csma = CsmaHelper()
    csma.SetChannelAttribute("DataRate", "10Mbps")
    devices = csma.Install(nodes)
    InternetStackHelper().Install(nodes)
    ifc = Ipv4AddressHelper("10.1.9.0", "255.255.255.0").Assign(devices)
    tch = TrafficControlHelper()
    tch.SetRootQueueDisc("tpudes::FifoQueueDisc", MaxSize=100)
    (qdisc,) = tch.Install(devices.Get(0))
    server = UdpEchoServerHelper(9)
    sapps = server.Install(nodes.Get(1))
    sapps.Start(Seconds(0.0))
    rx = [0]
    sapps.Get(0).TraceConnectWithoutContext(
        "Rx", lambda *a: rx.__setitem__(0, rx[0] + 1)
    )
    c = UdpEchoClientHelper(ifc.GetAddress(1), 9)
    c.SetAttribute("MaxPackets", 5)
    c.SetAttribute("Interval", Seconds(0.01))
    c.Install(nodes.Get(0)).Start(Seconds(0.1))
    Simulator.Stop(Seconds(1.0))
    Simulator.Run()
    assert rx[0] == 5
    # ARP request + 5 ARP-resolved UDP unicasts all rode the qdisc
    assert qdisc.stats_enqueued >= 6, qdisc.stats_enqueued


def test_tc_layer_routes_ip_sends_through_qdisc():
    db, sinks = build_dumbbell(2, 2.0, bottleneck_rate="2Mbps")
    tch = TrafficControlHelper()
    tch.SetRootQueueDisc("tpudes::FifoQueueDisc", MaxSize=50)
    (qdisc,) = tch.Install(db.GetBottleneckDevices().Get(0))
    left_router = db.GetLeft()
    tc = left_router.GetObject(TrafficControlLayer)
    assert tc is not None
    assert tc.GetRootQueueDisc(db.GetBottleneckDevices().Get(0)) is qdisc
    Simulator.Stop(Seconds(2.0))
    Simulator.Run()
    assert qdisc.stats_enqueued > 0
    assert sum(s.GetTotalRx() for s in sinks) > 0

# --- FqCoDel / PIE / TBF (VERDICT r4 #9) -----------------------------------

def _flow_item(size, sport, dport=9, proto=17):
    from tpudes.models.internet.ipv4 import Ipv4Header
    from tpudes.models.internet.udp import UdpHeader
    from tpudes.network.address import Ipv4Address

    p = Packet(size)
    p.AddHeader(UdpHeader(sport, dport, size))
    p.AddHeader(Ipv4Header(
        Ipv4Address("10.0.0.1"), Ipv4Address("10.0.0.2"), proto,
        payload_size=size + 8,
    ))
    return QueueDiscItem(p, None, 0x0800)


def test_fqcodel_isolates_sparse_flow_from_bulk():
    """RFC 8290's point: a sparse flow's packets do not wait behind a
    bulk flow's standing queue — they dequeue promptly via the
    new-flow/DRR machinery."""
    from tpudes.models.traffic_control import FqCoDelQueueDisc

    q = FqCoDelQueueDisc()
    for _ in range(100):
        q.Enqueue(_flow_item(1000, sport=1111))  # bulk flow backlog
    q.Enqueue(_flow_item(100, sport=2222))       # sparse flow, one packet
    sizes = [q.Dequeue().GetSize() for _ in range(3)]
    # the sparse packet (100 B + UDP/IP headers = 128 B) comes out
    # within the first DRR rounds, far ahead of FIFO position 101
    assert 128 in sizes, sizes


def test_fqcodel_drr_shares_capacity_between_bulk_flows():
    from tpudes.models.traffic_control import FqCoDelQueueDisc

    q = FqCoDelQueueDisc()
    for _ in range(50):
        q.Enqueue(_flow_item(1000, sport=1111))
        q.Enqueue(_flow_item(1000, sport=2222))
    # interleaved service: first 20 dequeues touch both flows evenly
    from tpudes.models.internet.udp import UdpHeader

    ports = [
        q.Dequeue().packet.FindHeader(UdpHeader).source_port
        for _ in range(20)
    ]
    assert 8 <= ports.count(1111) <= 12, ports


def test_pie_steers_queue_delay_to_target():
    """Overloaded PIE bottleneck on the dumbbell: early drops engage
    and keep the standing queue far below the 1000-packet cap that
    fifo would fill (the RFC 8033 latency objective)."""
    from tpudes.core.world import reset_world
    from tpudes.helper.applications import UdpClientHelper, UdpServerHelper
    from tpudes.helper.containers import NodeContainer
    from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
    from tpudes.helper.point_to_point import PointToPointHelper
    from tpudes.models.traffic_control import PieQueueDisc

    reset_world()
    nodes = NodeContainer()
    nodes.Create(2)
    p2p = PointToPointHelper()
    p2p.SetDeviceAttribute("DataRate", "2Mbps")
    p2p.SetChannelAttribute("Delay", "2ms")
    devices = p2p.Install(nodes)
    InternetStackHelper().Install(nodes)
    ifc = Ipv4AddressHelper("10.1.1.0", "255.255.255.0").Assign(devices)
    tch = TrafficControlHelper()
    tch.SetRootQueueDisc("tpudes::PieQueueDisc")
    (qdisc,) = tch.Install(devices.Get(0))

    server = UdpServerHelper(9)
    server.Install(nodes.Get(1)).Start(Seconds(0.0))
    client = UdpClientHelper(ifc.GetAddress(1), 9)
    client.SetAttribute("MaxPackets", 0)
    client.SetAttribute("Interval", Seconds(0.002))  # 4 Mbps offered
    client.SetAttribute("PacketSize", 1000)
    client.Install(nodes.Get(0)).Start(Seconds(0.1))
    Simulator.Stop(Seconds(4.0))
    Simulator.Run()
    assert qdisc.stats_early_drops > 0, "PIE never engaged"
    # 15 ms target at 2 Mbps = ~3.7 packets; leave generous headroom —
    # the point is it is nowhere near the 1000-packet fifo blowup
    assert qdisc.GetNPackets() < 50, qdisc.GetNPackets()
    reset_world()


def test_tbf_shapes_to_token_rate():
    from tpudes.core.world import reset_world
    from tpudes.helper.applications import UdpClientHelper, UdpServerHelper
    from tpudes.helper.containers import NodeContainer
    from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
    from tpudes.helper.point_to_point import PointToPointHelper

    reset_world()
    nodes = NodeContainer()
    nodes.Create(2)
    p2p = PointToPointHelper()
    p2p.SetDeviceAttribute("DataRate", "10Mbps")  # the link is NOT the cap
    p2p.SetChannelAttribute("Delay", "1ms")
    devices = p2p.Install(nodes)
    InternetStackHelper().Install(nodes)
    ifc = Ipv4AddressHelper("10.1.1.0", "255.255.255.0").Assign(devices)
    tch = TrafficControlHelper()
    tch.SetRootQueueDisc("tpudes::TbfQueueDisc", Rate="2Mbps", Burst=10_000)
    tch.Install(devices.Get(0))

    rx_bytes = [0]
    server = UdpServerHelper(9)
    sapps = server.Install(nodes.Get(1))
    sapps.Start(Seconds(0.0))
    sapps.Get(0).TraceConnectWithoutContext(
        "Rx", lambda pkt, *a: rx_bytes.__setitem__(0, rx_bytes[0] + pkt.GetSize())
    )
    client = UdpClientHelper(ifc.GetAddress(1), 9)
    client.SetAttribute("MaxPackets", 0)
    client.SetAttribute("Interval", Seconds(0.001))  # 8 Mbps offered
    client.SetAttribute("PacketSize", 1000)
    client.Install(nodes.Get(0)).Start(Seconds(0.1))
    Simulator.Stop(Seconds(2.1))
    Simulator.Run()
    mbps = rx_bytes[0] * 8 / 2.0 / 1e6
    # shaped to the 2 Mbps token rate (+ the 10 kB initial burst)
    assert 1.7 < mbps < 2.4, mbps
    reset_world()


@pytest.mark.parametrize("disc,kw", [
    ("tpudes::TbfQueueDisc", {"Rate": "3Mbps", "Burst": 10_000}),
    ("tpudes::PieQueueDisc", {}),
])
def test_shaping_discs_terminate_on_event_exhaustion(disc, kw):
    """r5 review regressions: TBF's round-to-nearest wake delay could
    respawn 0-tick wakes forever at non-power-of-two rates (3 Mbps
    livelocked), and PIE's update timer re-armed unconditionally so
    Simulator.Run() without Stop() never returned."""
    from tpudes.core.world import reset_world
    from tpudes.helper.applications import UdpClientHelper, UdpServerHelper
    from tpudes.helper.containers import NodeContainer
    from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
    from tpudes.helper.point_to_point import PointToPointHelper

    reset_world()
    nodes = NodeContainer()
    nodes.Create(2)
    p2p = PointToPointHelper()
    devices = p2p.Install(nodes)
    InternetStackHelper().Install(nodes)
    ifc = Ipv4AddressHelper("10.1.1.0", "255.255.255.0").Assign(devices)
    tch = TrafficControlHelper()
    tch.SetRootQueueDisc(disc, **kw)
    tch.Install(devices.Get(0))
    server = UdpServerHelper(9)
    sapps = server.Install(nodes.Get(1))
    sapps.Start(Seconds(0.0))
    c = UdpClientHelper(ifc.GetAddress(1), 9)
    c.SetAttribute("MaxPackets", 20)
    c.SetAttribute("Interval", Seconds(0.001))
    c.SetAttribute("PacketSize", 1000)
    c.Install(nodes.Get(0)).Start(Seconds(0.1))
    Simulator.Run()  # NO Stop(): must terminate on event exhaustion
    assert sapps.Get(0).received == 20
    reset_world()


def test_pie_rejected_enqueue_on_idle_disc_arms_no_timer():
    """ADVICE.md low (PIE Tupdate mis-arm): a packet rejected by the
    queue-limit check on an otherwise idle disc must not start the
    recurring probability-update chain — only an ACCEPTED packet arms
    Tupdate."""
    from tpudes.core.world import reset_world
    from tpudes.models.traffic_control import PieQueueDisc

    reset_world()
    disc = PieQueueDisc(MaxSize=0)          # every enqueue rejected
    assert not disc.Enqueue(_item())
    assert not disc._timer_started
    assert Simulator.IsFinished(), "rejected enqueue scheduled an event"

    # the flip side: an accepted packet DOES arm the update chain
    reset_world()
    disc = PieQueueDisc()
    assert disc.Enqueue(_item())
    assert disc._timer_started
    assert not Simulator.IsFinished()
    reset_world()
