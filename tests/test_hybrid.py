"""Hybrid space×replica PDES: device-engine ranks (ISSUE-9 tentpole).

The pinned contracts of ROADMAP item 4(b):

- a 1-rank hybrid run is BIT-identical to the plain device engine;
- an N-rank run is timestamp-EXACT against the sequential host DES on
  a deterministic cross-partition scenario (mirroring
  tests/test_distributed.py's yardstick for the host engines);
- the three transports (in-process lockstep, space-lane batched, one
  OS process per rank) all produce identical results, because they
  issue the identical advance/operand sequence.
"""

import pytest

import jax

from tpudes.obs.distributed import (
    DistributedTelemetry,
    validate_distributed_metrics,
)
from tpudes.parallel.hybrid import run_hybrid
from tpudes.parallel.wired import (
    UnliftableWiredError,
    run_wired,
    run_wired_host,
    wired_chain,
    wired_weak_chain,
)

KEY = jax.random.key(7)
FIELDS = ("deliver_slot", "delivered", "served")


def _cross_partition_prog(**kw):
    """Deterministic 2-partition chain where every flow crosses the
    boundary (each flow runs to the chain tail on the far rank)."""
    kw.setdefault("n_slots", 400)
    return wired_chain(n_links=6, n_flows=3, ranks=2, **kw)


# --- the acceptance-criteria pins ------------------------------------------


def test_one_rank_hybrid_bit_identical_to_plain_engine():
    prog = wired_chain(n_links=6, n_flows=3, n_slots=400, ranks=1)
    plain = run_wired(prog, KEY, replicas=2)
    hybrid = run_hybrid(prog, KEY, replicas=2, ranks=1, transport="local")
    for k in FIELDS:
        assert (plain[k] == hybrid[k]).all(), k
    # no boundary => infinite lookahead => a single granted window
    assert hybrid["windows"] == 1


def test_two_rank_hybrid_timestamp_exact_vs_host_des():
    prog = _cross_partition_prog()
    host = run_wired_host(prog)
    hybrid = run_hybrid(prog, KEY, replicas=2, ranks=2, transport="local")
    assert (hybrid["deliver_slot"][0] == host["deliver_slot"]).all()
    assert (hybrid["deliver_slot"][1] == host["deliver_slot"]).all()
    assert (hybrid["served"][0] == host["served"]).all()
    # the window protocol actually ran granted windows
    assert hybrid["windows"] > 1
    # and traffic really crossed the partition boundary
    assert hybrid["delivered"].sum() > 0


def test_transports_identical():
    prog = _cross_partition_prog()
    plain = run_wired(prog, KEY, replicas=2)
    local = run_hybrid(prog, KEY, replicas=2, transport="local")
    batched = run_hybrid(prog, KEY, replicas=2, transport="batched")
    for k in FIELDS:
        assert (plain[k] == local[k]).all(), k
        assert (plain[k] == batched[k]).all(), k
    assert local["windows"] == batched["windows"]


@pytest.mark.slow
def test_mpi_transport_identical():
    """One spawned OS process per rank, boundary traffic over the
    framed MpiInterface pipes — results equal the in-process run."""
    prog = _cross_partition_prog()
    plain = run_wired(prog, KEY, replicas=2)
    out = run_hybrid(prog, KEY, replicas=2, transport="mpi",
                     timeout_s=240.0)
    for k in FIELDS:
        assert (plain[k] == out[k]).all(), k
    assert out["windows"] > 1
    assert out["loop_wall_s"] > 0


def test_jitter_replicas_cross_partition():
    """Per-replica phase jitter derives from GLOBAL (replica, flow)
    ids, so every rank draws identical phases for shared flows."""
    prog = _cross_partition_prog(jitter_slots=5)
    plain = run_wired(prog, KEY, replicas=3)
    hybrid = run_hybrid(prog, KEY, replicas=3, transport="local")
    batched = run_hybrid(prog, KEY, replicas=3, transport="batched")
    for k in FIELDS:
        assert (plain[k] == hybrid[k]).all(), k
        assert (plain[k] == batched[k]).all(), k


# --- bounded windows (the weak-scaling cadence knob) -----------------------


def test_bounded_grants_change_schedule_not_results():
    prog = _cross_partition_prog()
    free = run_hybrid(prog, KEY, replicas=1, transport="batched")
    bounded = run_hybrid(prog, KEY, replicas=1, transport="batched",
                         window_slots=11)
    for k in FIELDS:
        assert (free[k] == bounded[k]).all(), k
    assert bounded["windows"] >= free["windows"]


def test_bounded_grants_window_one_rank():
    """With a bound, even a boundary-free 1-rank run pays the window
    cadence — the fixed-discipline baseline of the weak-scaling row."""
    prog = wired_chain(n_links=6, n_flows=3, n_slots=400, ranks=1)
    plain = run_wired(prog, KEY, replicas=1)
    bounded = run_hybrid(prog, KEY, replicas=1, ranks=1,
                         transport="local", window_slots=50)
    for k in FIELDS:
        assert (plain[k] == bounded[k]).all(), k
    assert bounded["windows"] == 8  # ceil(400 / 50)


# --- weak-scaling scenario -------------------------------------------------


def test_weak_chain_hybrid_exact_all_rank_counts():
    for ranks in (1, 2, 4):
        wp = wired_weak_chain(ranks, links_per_rank=2, n_slots=1500)
        host = run_wired_host(wp)
        out = run_hybrid(wp, KEY, replicas=1, transport="batched",
                         window_slots=240)
        assert (out["deliver_slot"][0] == host["deliver_slot"]).all(), ranks


def test_batched_rejects_ragged_partitions():
    """Non-uniform per-rank resident sets cannot stack as lanes — the
    error names the counts and points at the ragged-capable transports."""
    prog = wired_chain(n_links=6, n_flows=4, n_slots=300, ranks=2)
    from tpudes.parallel.wired import build_wired_space_advance

    with pytest.raises(UnliftableWiredError, match="uniform"):
        build_wired_space_advance(prog, 1)


def test_batched_rank_count_must_match_partitioning():
    prog = _cross_partition_prog()
    with pytest.raises(ValueError, match="ranks"):
        run_hybrid(prog, KEY, replicas=1, ranks=3, transport="batched")


# --- telemetry -------------------------------------------------------------


def test_distributed_telemetry_schema_after_run():
    DistributedTelemetry.reset()
    prog = _cross_partition_prog()
    run_hybrid(prog, KEY, replicas=1, transport="local")
    snap = DistributedTelemetry.snapshot()
    assert validate_distributed_metrics(snap) == []
    assert set(snap["ranks"]) == {"0", "1"}
    assert snap["counters"]["windows"] > 0
    # chain topology: rank 0 sends downstream, rank 1 receives
    assert snap["ranks"]["0"]["tx_pkts"] > 0
    assert snap["ranks"]["1"]["rx_pkts"] == snap["ranks"]["0"]["tx_pkts"]
    DistributedTelemetry.reset()


def test_distributed_telemetry_absorb_merges_rank_snapshots():
    DistributedTelemetry.reset()
    DistributedTelemetry.record_window(
        0, grant_slots=10, tx_pkts=2, rx_pkts=0, poll_wall_s=0.1,
        flush_wall_s=0.2, grant_wall_s=0.3, advance_wall_s=0.4,
    )
    child = DistributedTelemetry.snapshot()
    DistributedTelemetry.reset()
    DistributedTelemetry.absorb(child)
    DistributedTelemetry.absorb(child)
    snap = DistributedTelemetry.snapshot()
    assert validate_distributed_metrics(snap) == []
    assert snap["ranks"]["0"]["windows"] == 2
    assert snap["ranks"]["0"]["tx_pkts"] == 4
    assert snap["counters"]["windows"] == 2
    DistributedTelemetry.reset()


def test_distributed_schema_rejects_malformed():
    assert validate_distributed_metrics([]) != []
    assert validate_distributed_metrics({"version": 1}) != []
    ok = {
        "version": 1,
        "counters": {"windows": 1, "boundary_tx": 0, "boundary_rx": 0},
        "ranks": {"0": {
            "windows": 1, "wall_s": 0.1, "windows_per_s": 10.0,
            "grant_slots_sum": 5, "grant_slots_mean": 5.0,
            "grant_slots_max": 5,
            "tx_pkts": 0, "rx_pkts": 0, "transport_tx": 0,
            "transport_rx": 0, "poll_wall_s": 0.0, "flush_wall_s": 0.0,
            "grant_wall_s": 0.1, "advance_wall_s": 0.0,
        }},
    }
    assert validate_distributed_metrics(ok) == []
    bad = {**ok, "ranks": {"x": ok["ranks"]["0"]}}
    assert validate_distributed_metrics(bad) != []
    bad2 = {**ok, "counters": {"windows": -1, "boundary_tx": 0,
                               "boundary_rx": 0}}
    assert validate_distributed_metrics(bad2) != []


def test_obs_cli_distributed_gate(tmp_path, capsys):
    import json

    from tpudes.obs.__main__ import main as obs_main

    DistributedTelemetry.reset()
    prog = _cross_partition_prog()
    run_hybrid(prog, KEY, replicas=1, transport="local")
    p = tmp_path / "distributed.json"
    p.write_text(json.dumps(DistributedTelemetry.snapshot()))
    assert obs_main(["--distributed", str(p)]) == 0
    p.write_text(json.dumps({"version": 2}))
    assert obs_main(["--distributed", str(p)]) == 1
    DistributedTelemetry.reset()
