"""HT (802.11n) BSS on the replica axis vs the sequential DES.

The aggregated analog of test_replicated.py: the same saturated HT BSS
(QoS + A-MPDU under BlockAck, HtMcs rates) is run (a) scalar with the
full ADDBA/BA machinery, (b) lowered onto the replica axis where every
data exchange is a backlog-sized A-MPDU with per-MPDU decode.  Parity
is statistical (SURVEY.md §4) on delivered-frame counts.
"""

import jax
import numpy as np
from dataclasses import replace

from tpudes.core import Seconds, Simulator
from tpudes.core.rng import RngSeedManager
from tpudes.parallel.replicated import lower_bss, run_replicated_bss

N_STAS = 4
SIM_TIME = 1.6
RADIUS = 16.0      # solid SNR for HtMcs7 — losses come from collisions
#: moderate load — both engines deliver ~the offered traffic (tight pin)
INTERVAL_MODERATE = 0.002
#: deep saturation — 512 B / 0.5 ms per STA (×2 with echoes ≈ 66 Mbps
#: offered) saturates single-MPDU HtMcs7; queues build, A-MPDUs fill
INTERVAL_SATURATED = 0.0005


def _reset_world():
    from tpudes.core.world import reset_world

    reset_world()


def _build_ht_bss(interval=INTERVAL_MODERATE):
    """The shared config-#3 factory in HT trim (one 16 m ring)."""
    from tpudes.scenarios import build_bss

    return build_bss(
        N_STAS, SIM_TIME, radii=(RADIUS,), interval_s=interval,
        data_mode="HtMcs7", standard="80211n",
    )


def _lowered_program(interval=INTERVAL_MODERATE):
    _reset_world()
    sta_devices, ap_device, clients, _ = _build_ht_bss(interval)
    prog = lower_bss(
        [sta_devices.Get(i) for i in range(N_STAS)], ap_device, clients, SIM_TIME
    )
    _reset_world()
    return prog


def _des_counts(interval, runs):
    counts = []
    for run in range(1, runs + 1):
        _reset_world()
        RngSeedManager.SetRun(run)
        _, _, _, rx = _build_ht_bss(interval)
        Simulator.Stop(Seconds(SIM_TIME))
        Simulator.Run()
        counts.append(rx[0])
    _reset_world()
    return np.array(counts, dtype=np.float64)


def test_ht_lowering_fields():
    from tpudes.ops.wifi_error import MODES_BY_NAME

    prog = _lowered_program()
    assert prog.data_mode_idx == MODES_BY_NAME["HtMcs7"].index
    # QoS AC_BE: AIFS = SIFS + 3 slots = 43 µs
    assert prog.aifs_us == 43
    # subframe: delimiter(4) + [512+8+20+8+24] + FCS(4), padded to 4
    assert prog.subframe_bytes == 580
    # 65535 // 580 = 112, capped at the 64-frame BlockAck window
    assert prog.max_mpdus == 64


def test_ht_statistical_parity_moderate_load():
    """At ~70% utilization both engines deliver close to the offered
    load — a tight cross-engine pin of the HT timing + decode path."""
    des = _des_counts(INTERVAL_MODERATE, 5)
    prog = _lowered_program(INTERVAL_MODERATE)
    out = run_replicated_bss(prog, 128, jax.random.PRNGKey(11))
    assert out["all_done"]
    rep = np.asarray(out["srv_rx"], dtype=np.float64)

    offered = N_STAS * int((SIM_TIME - 1.0) / INTERVAL_MODERATE + 1)
    assert 0 < rep.mean() <= offered
    assert 0 < des.mean() <= offered
    assert abs(des.mean() - rep.mean()) <= 0.10 * des.mean() + 2.0, (
        f"DES mean {des.mean():.1f} vs replicated mean {rep.mean():.1f} "
        f"(des {des}, rep std {rep.std():.1f})"
    )


def test_ht_statistical_parity_saturated():
    """Deep saturation: same order of delivered traffic.  The host DES
    has high run-to-run spread here (a collided ADDBA handshake stalls
    that peer's aggregation for ADDBA_RETRY_S = 1 s, i.e. the rest of
    the window), so the pin is deliberately loose — ±35%."""
    des = _des_counts(INTERVAL_SATURATED, 5)
    prog = _lowered_program(INTERVAL_SATURATED)
    out = run_replicated_bss(prog, 128, jax.random.PRNGKey(11))
    assert out["all_done"]
    rep = np.asarray(out["srv_rx"], dtype=np.float64)
    assert abs(des.mean() - rep.mean()) <= 0.35 * des.mean(), (
        f"DES mean {des.mean():.1f} vs replicated mean {rep.mean():.1f} "
        f"(des {des}, rep std {rep.std():.1f})"
    )


def test_aggregation_outperforms_single_mpdu():
    """Under saturation an aggregated BSS must deliver materially more
    than the same scenario forced to single-MPDU exchanges."""
    prog = _lowered_program(INTERVAL_SATURATED)
    agg = run_replicated_bss(prog, 64, jax.random.PRNGKey(3))
    single = run_replicated_bss(
        replace(prog, max_mpdus=1, subframe_bytes=0), 64, jax.random.PRNGKey(3)
    )
    a = float(np.asarray(agg["srv_rx"]).mean())
    s = float(np.asarray(single["srv_rx"]).mean())
    assert a > 1.5 * s, f"aggregated {a:.1f} vs single-MPDU {s:.1f}"


def test_ht_deterministic_and_bounded():
    prog = _lowered_program()
    a = run_replicated_bss(prog, 32, jax.random.PRNGKey(7))
    b = run_replicated_bss(prog, 32, jax.random.PRNGKey(7))
    np.testing.assert_array_equal(np.asarray(a["srv_rx"]), np.asarray(b["srv_rx"]))
    cli = np.asarray(a["cli_rx"]).sum(axis=1)
    srv = np.asarray(a["srv_rx"])
    assert (cli <= srv).all()
