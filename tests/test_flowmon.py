"""Device-resident FlowMonitor (tpudes.obs.flowmon): accumulator
parity with the host monitor, per-engine oracle validation, packet-ring
decode, the ONE shared XML serializer, pcap round-trip back into a
trace-replay TrafficProgram, and the ``--flowmon`` / ``--pcap`` CLI
validator modes.
"""

import struct
import subprocess
import sys
from pathlib import Path

import jax
import numpy as np
import pytest

from tpudes.core.global_value import GlobalValue
from tpudes.core.world import reset_world
from tpudes.models.flow_monitor import FiveTuple, FlowMonitor, FlowStats
from tpudes.obs.flowmon import (
    FLOW_RING_CAP,
    VERDICT_RX,
    VERDICT_TX,
    DeviceFlowMonitor,
    PacketEvent,
    decode_packet_rings,
    flow_accumulate,
    flow_carry,
    flow_ring_write,
    host_reference_stats,
    reduce_flow_stats,
    serialize_flow_stats_xml,
    validate_flowmon_xml,
    validate_pcap,
    write_events_pcap,
)

REPO = Path(__file__).resolve().parent.parent
KEY = jax.random.PRNGKey(0)


# --- the shared one-packet-per-step sequence -------------------------------
#
# Two flows, at most one packet per (step, flow): the regime where the
# device's one-observation-per-step jitter coarsening coincides exactly
# with the host monitor's per-packet RFC-3550 chain.
#
# Each entry: (flow index, size, delay_s or None for a drop)

_SEQ = [
    {0: (100, 0.0030)},
    {0: (100, 0.0042), 1: (300, 0.0020)},
    {0: (100, 0.0034)},
    {0: (100, 0.0046), 1: (300, 0.0030)},
    {0: (100, 0.0038)},
    {1: (300, None)},  # flow 1's packet is dropped this step
    {0: (100, 0.0042)},
    {0: (100, 0.0034), 1: (300, 0.0020)},
    {1: (300, 0.0030)},
]
_STEP_S = 0.01


class _StubPacket:
    _next_uid = 0

    def __init__(self, size):
        _StubPacket._next_uid += 1
        self._uid, self._size = _StubPacket._next_uid, size

    def GetUid(self):
        return self._uid

    def GetSize(self):
        return self._size

    def PeekHeader(self):
        return None


class _StubHeader:
    def __init__(self, flow):
        self.source = "10.0.0.1"
        self.destination = f"10.0.1.{flow + 1}"
        self.protocol = 17


def _live_monitor_stats():
    """Replay _SEQ through a LIVE host FlowMonitor's probe callbacks
    (time injected, periodic sweep off — pure accumulator arithmetic)."""
    mon = FlowMonitor()
    mon._stopped = True  # no Simulator needed for the expiry sweep
    now = [0.0]
    mon._now_s = lambda: now[0]
    # fix flow-id assignment to the flow index order
    for f in range(2):
        mon.classifier.Classify(_StubHeader(f), _StubPacket(0))
    for k, step in enumerate(_SEQ):
        t = k * _STEP_S
        for f, (size, delay) in step.items():
            pkt = _StubPacket(size)
            now[0] = t
            mon._on_send(_StubHeader(f), pkt, 0)
            if delay is None:
                now[0] = t + 0.001
                mon._on_drop(_StubHeader(f), pkt, "queue")
            else:
                now[0] = t + delay
                mon._on_deliver(_StubHeader(f), pkt, 0)
    return mon


def _step_arrays():
    """_SEQ as flow_accumulate operand dicts (the +20 matches the host
    probe's GetSize()+20 IP-header accounting)."""
    steps = []
    for k, step in enumerate(_SEQ):
        tx = np.zeros(2, np.int32)
        txb = np.zeros(2, np.int32)
        rx = np.zeros(2, np.int32)
        rxb = np.zeros(2, np.int32)
        lost = np.zeros(2, np.int32)
        delay = np.zeros(2, np.float32)
        for f, (size, d) in step.items():
            tx[f], txb[f] = 1, size + 20
            if d is None:
                lost[f] = 1
            else:
                rx[f], rxb[f], delay[f] = 1, size + 20, d
        steps.append(
            dict(t_s=k * _STEP_S, tx=tx, tx_bytes=txb, rx=rx,
                 rx_bytes=rxb, delay_s=delay, lost=lost)
        )
    return steps


def _device_columns():
    import jax.numpy as jnp

    fm = flow_carry(2)
    for ev in _step_arrays():
        fm = flow_accumulate(
            {k: v for k, v in fm.items() if k != "fm_ring"},
            t_s=ev["t_s"],
            tx=jnp.asarray(ev["tx"]),
            tx_bytes=jnp.asarray(ev["tx_bytes"]),
            rx=jnp.asarray(ev["rx"]),
            rx_bytes=jnp.asarray(ev["rx_bytes"]),
            delay_s=jnp.asarray(ev["delay_s"]),
            lost=jnp.asarray(ev["lost"]),
            bin_width_s=0.001,
        )
    return fm


def test_flow_accumulate_matches_live_flow_monitor():
    """The acceptance gate at the accumulator level: on a shared
    one-packet-per-step sequence the device columns reproduce a live
    host FlowMonitor — counts and bytes exact, delay/jitter sums and
    last-delay within f32 tolerance."""
    host = _live_monitor_stats().GetFlowStats()
    dev = reduce_flow_stats(_device_columns())
    assert set(dev) == set(host) == {1, 2}
    for fid in host:
        h, d = host[fid], dev[fid]
        assert (d.tx_packets, d.tx_bytes) == (h.tx_packets, h.tx_bytes)
        assert (d.rx_packets, d.rx_bytes) == (h.rx_packets, h.rx_bytes)
        assert d.lost_packets == h.lost_packets
        assert d.delay_sum_s == pytest.approx(h.delay_sum_s, rel=1e-5)
        assert d.jitter_sum_s == pytest.approx(h.jitter_sum_s, rel=1e-5)
        assert d.last_delay_s == pytest.approx(h.last_delay_s, rel=1e-5)
        # device stamps flow times at the step clock; the live monitor
        # stamps delivery at send-time + delay — sub-step skew only
        assert d.time_first_tx_s == pytest.approx(h.time_first_tx_s)
        assert abs(d.time_last_rx_s - h.time_last_rx_s) < 0.005
    assert _live_monitor_stats().stats[2].lost_packets == 1


def test_host_reference_stats_is_the_same_oracle():
    """host_reference_stats (the NumPy oracle the engine tests use)
    agrees with the live monitor on every field it defines with step-
    clock semantics."""
    host = _live_monitor_stats().GetFlowStats()
    ref = host_reference_stats(_step_arrays())
    for fid in host:
        h, r = host[fid], ref[fid]
        assert (r.tx_packets, r.tx_bytes, r.rx_packets, r.rx_bytes,
                r.lost_packets) == (
            h.tx_packets, h.tx_bytes, h.rx_packets, h.rx_bytes,
            h.lost_packets,
        )
        assert r.delay_sum_s == pytest.approx(h.delay_sum_s)
        assert r.jitter_sum_s == pytest.approx(h.jitter_sum_s)
    # and it is exactly what the device columns integrate to
    dev = reduce_flow_stats(_device_columns())
    for fid, r in ref.items():
        assert dev[fid].delay_sum_s == pytest.approx(r.delay_sum_s, rel=1e-5)
        assert dev[fid].time_first_tx_s == pytest.approx(r.time_first_tx_s)
        assert dev[fid].time_last_rx_s == pytest.approx(r.time_last_rx_s)


# --- packet-event ring ------------------------------------------------------


def test_ring_write_wraps_and_decode_dedups():
    import jax.numpy as jnp

    cap = 8
    ring = jnp.full((cap, 5), -1, jnp.int32)
    snaps = []
    for step in range(20):
        row = jnp.asarray(
            [step, step * 1000, step % 3, 100 + step, VERDICT_RX],
            jnp.int32,
        )
        ring = flow_ring_write(ring, jnp.int32(step), row)
        if step == 7:
            snaps.append(np.asarray(ring))
    # final snapshot arrives flipped, as the engines emit it (lax.rev
    # freshness) — decode's step-keyed ordering must not care
    snaps.append(np.asarray(ring)[::-1])
    events = decode_packet_rings(snaps)
    # steps 0..7 from the boundary snapshot, 12..19 from the final one;
    # 8..11 were recycled between snapshots (the bounded-ring contract)
    assert [e.step for e in events] == list(range(8)) + list(range(12, 20))
    assert all(e.t_us == e.step * 1000 for e in events)
    assert len({e.step for e in events}) == len(events)


def test_decode_rejects_batched_snapshot():
    with pytest.raises(ValueError, match="slice the replica lane"):
        decode_packet_rings([np.zeros((2, FLOW_RING_CAP, 5), np.int32)])


def test_reduce_skips_inactive_flows_and_maps_sentinels():
    fm = {k: np.asarray(v) for k, v in flow_carry(3).items()}
    fm["fm_tx"] = np.asarray([2, 0, 0], np.int32)
    fm["fm_txb"] = np.asarray([200, 0, 0], np.int32)
    stats = reduce_flow_stats({k: v for k, v in fm.items() if k != "fm_ring"})
    assert set(stats) == {1}  # flows 2 and 3 never materialise
    st = stats[1]
    assert st.tx_packets == 2 and st.rx_packets == 0
    assert st.last_delay_s is None
    assert st.time_first_tx_s is None and st.time_last_rx_s is None


# --- per-engine device-vs-oracle validation --------------------------------


def test_wired_flowstats_match_host_des_oracle_exactly():
    """Wired acceptance: per-flow tx/rx counts AND the delay sums from
    the device columns equal the host DES oracle's per-packet deliver
    slots — exact, not approximate (integer slot arithmetic on both
    sides; slot→seconds scaling happens in the host finalize)."""
    from tpudes.obs.device import ChunkStream
    from tpudes.parallel.wired import (
        WIRED_PKT_BYTES,
        packet_table,
        run_wired,
        run_wired_host,
        wired_chain,
    )

    prog = wired_chain(n_links=3, n_flows=2, n_slots=60, jitter_slots=0)
    GlobalValue.Bind("TpudesObs", 1)
    ChunkStream.reset()
    res = run_wired(prog, KEY, replicas=1, window_slots=16)
    flow = res["flow"]

    host = run_wired_host(prog)
    pkt_flow, pkt_birth, _ = packet_table(prog)
    F = prog.n_flows
    rx_host = np.zeros(F, np.int64)
    dsum_host = np.zeros(F, np.float64)
    dl = host["deliver_slot"]
    for p in range(len(pkt_flow)):
        if dl[p] >= 0:
            rx_host[pkt_flow[p]] += 1
            dsum_host[pkt_flow[p]] += (dl[p] - pkt_birth[p]) * prog.slot_s
    tx_host = np.bincount(pkt_flow[pkt_birth < prog.n_slots], minlength=F)

    np.testing.assert_array_equal(flow["fm_rx"][0], rx_host)
    np.testing.assert_array_equal(flow["fm_tx"][0], tx_host)
    np.testing.assert_allclose(flow["fm_dsum"][0], dsum_host, rtol=1e-6)
    np.testing.assert_array_equal(
        flow["fm_rxb"][0], rx_host * WIRED_PKT_BYTES
    )
    assert (flow["fm_hist"][0].sum(-1) == flow["fm_rx"][0]).all()
    # ring: chunk-boundary snapshots + the final ring decode into one
    # deduped event log with both verdicts present
    snaps = [
        np.asarray(e["metrics"]["fm_ring"])[0]
        for e in ChunkStream.entries("wired")
    ]
    events = decode_packet_rings(snaps + [flow["fm_ring"][0]])
    assert events and {e.verdict for e in events} <= {VERDICT_TX, VERDICT_RX}
    assert sum(e.verdict == VERDICT_RX for e in events) > 0


def test_lte_flowstats_match_delivered_tb_counters():
    """LTE acceptance: fm_rx equals the engine's per-UE delivered-TB
    counter exactly; each delivery contributes one 1-TTI delay."""
    from tpudes.parallel.lte_sm import run_lte_sm
    from tpudes.parallel.programs import toy_lte_program

    prog = toy_lte_program(n_enb=2, n_ue=3, n_ttis=50)
    GlobalValue.Bind("TpudesObs", 1)
    res = run_lte_sm(prog, KEY, chunk_ttis=16)
    flow = res["flow"]
    np.testing.assert_array_equal(flow["fm_rx"], res["ok"])
    np.testing.assert_allclose(
        flow["fm_dsum"], res["ok"] * 1e-3, rtol=1e-6
    )
    assert (flow["fm_hist"].sum(-1) == flow["fm_rx"]).all()
    assert (flow["fm_rxb"] == flow["fm_txb"]).all()  # acked bytes both ways
    stats = reduce_flow_stats(
        {k: v for k, v in flow.items() if k != "fm_ring"}
    )
    assert sum(st.rx_packets for st in stats.values()) == int(
        np.sum(res["ok"])
    )


def test_bss_flowstats_match_served_counters():
    """BSS acceptance: per-replica MPDU totals from the flow columns
    equal the engine's served/tx/drop counters exactly (flow = node)."""
    sys.path.insert(0, str(REPO / "tests"))
    from test_replicated import _lowered_program

    from tpudes.parallel.replicated import run_replicated_bss

    prog = _lowered_program()
    GlobalValue.Bind("TpudesObs", 1)
    out = run_replicated_bss(prog, 4, jax.random.PRNGKey(3))
    flow = out["flow"]
    np.testing.assert_array_equal(
        flow["fm_rx"].sum(-1), out["srv_rx"] + out["cli_rx"].sum(-1)
    )
    np.testing.assert_array_equal(flow["fm_lost"].sum(-1), out["drops"])
    np.testing.assert_array_equal(flow["fm_tx"].sum(-1), out["tx_data"])
    assert (flow["fm_rxb"] == flow["fm_rx"] * prog.data_bytes).all()
    assert (flow["fm_hist"].sum(-1) == flow["fm_rx"]).all()


def test_dumbbell_flowstats_match_host_flow_monitor():
    """Dumbbell acceptance: a live FlowMonitor on the host engine vs the
    device columns, same deterministic scenario.  Goodput bytes and loss
    are exact; the host's two extra control packets (SYN/FIN) are the
    only count difference; jitter sums agree to float tolerance."""
    from tpudes.core import Seconds, Simulator
    from tpudes.core.config import Config
    from tpudes.models.applications import BulkSendApplication
    from tpudes.models.flow_monitor import FlowMonitorHelper
    from tpudes.network.node import NodeList
    from tpudes.parallel.tcp_dumbbell import lower_dumbbell, run_tcp_dumbbell
    from tpudes.scenarios import build_dumbbell

    sim_s, budget = 2.0, 20_000
    # host MSS = device segment size, so both segment the budget alike
    Config.SetDefault("tpudes::TcpSocketBase::SegmentSize", 1000)
    build_dumbbell(2, sim_s, variant="TcpNewReno", queue="200p",
                   seg_bytes=1000)
    for i in range(NodeList.GetNNodes()):
        node = NodeList.GetNode(i)
        for a in range(node.GetNApplications()):
            app = node.GetApplication(a)
            if isinstance(app, BulkSendApplication):
                app.SetAttribute("MaxBytes", budget)
    prog = lower_dumbbell(sim_s)
    mon = FlowMonitorHelper().InstallAll()
    Simulator.Stop(Seconds(sim_s))
    Simulator.Run()
    mon.CheckForLostPackets()
    host = {
        fid: st
        for fid, st in mon.GetFlowStats().items()
        # data direction only: the device models the forward path; acks
        # are implicit in its ack_lag pipeline
        if 5000 <= mon.classifier.FindFlow(fid).destination_port < 5100
    }
    reset_world()

    GlobalValue.Bind("TpudesObs", 1)
    out = run_tcp_dumbbell(prog, KEY, replicas=2)
    flow = out["flow"]
    assert len(host) == 2
    for j, fid in enumerate(sorted(host)):
        h = host[fid]
        # goodput (IP+TCP headers stripped: 40 B/packet) is exact
        assert h.rx_bytes - h.rx_packets * 40 == budget
        assert int(flow["fm_rxb"][0][j] - flow["fm_rx"][0][j] * 40) == budget
        # SYN and FIN are the only packets the device does not model
        assert h.rx_packets - 2 == int(flow["fm_rx"][0][j])
        assert h.lost_packets == int(flow["fm_lost"][0][j]) == 0
        assert float(flow["fm_jsum"][0][j]) == pytest.approx(
            h.jitter_sum_s, abs=5e-3
        )
        # both see the same per-packet mean queueing delay regime
        assert float(flow["fm_dsum"][0][j]) / int(
            flow["fm_rx"][0][j]
        ) == pytest.approx(h.delay_sum_s / h.rx_packets, rel=0.15)


# --- the ONE shared XML serializer + validators ----------------------------


def _toy_stats():
    return {
        1: FlowStats(tx_packets=10, tx_bytes=10400, rx_packets=9,
                     rx_bytes=9360, lost_packets=1, delay_sum_s=0.05,
                     jitter_sum_s=0.002, last_delay_s=0.004,
                     time_first_tx_s=0.0, time_last_rx_s=0.9),
        2: FlowStats(tx_packets=3, tx_bytes=1200, rx_packets=3,
                     rx_bytes=1200, lost_packets=0, delay_sum_s=0.01,
                     jitter_sum_s=0.0, last_delay_s=0.003,
                     time_first_tx_s=0.1, time_last_rx_s=0.5),
    }


def test_xml_serializer_is_shared_byte_for_byte(tmp_path):
    """FlowMonitor.SerializeToXmlFile and the device exporter emit THE
    SAME bytes for the same stats — one serializer, two producers."""
    stats = _toy_stats()
    flows = {
        FiveTuple("10.0.0.1", "10.0.1.1", 17, 49152, 9): 1,
        FiveTuple("10.0.0.1", "10.0.1.2", 17, 49152, 9): 2,
    }
    mon = FlowMonitor()
    mon.stats = stats
    mon.classifier._flows = flows
    a, b = tmp_path / "host.xml", tmp_path / "dev.xml"
    mon.SerializeToXmlFile(str(a))
    serialize_flow_stats_xml(stats, flows, str(b))
    assert a.read_bytes() == b.read_bytes()
    problems, n = validate_flowmon_xml(a.read_text())
    assert problems == [] and n == 2


def test_flowmon_xml_validator_names_the_defect():
    bad = (
        '<?xml version="1.0" ?>\n<FlowMonitor>\n  <FlowStats>\n'
        '    <Flow flowId="1" txPackets="x" txBytes="10" rxPackets="9"'
        ' rxBytes="9" lostPackets="0" delaySum="0.05s"'
        ' jitterSum="+2000ns" />\n'
        '    <Flow flowId="1" txPackets="1" txBytes="1" rxPackets="1"'
        ' rxBytes="1" lostPackets="0" delaySum="+1ns"'
        ' jitterSum="+0ns" />\n'
        "  </FlowStats>\n</FlowMonitor>\n"
    )
    problems, n = validate_flowmon_xml(bad)
    assert n == 2
    assert any("txPackets='x' is not an integer" in p for p in problems)
    assert any("delaySum='0.05s'" in p and "+<nanoseconds>ns" in p
               for p in problems)
    assert any("duplicate flowId 1" in p for p in problems)
    assert validate_flowmon_xml("<wrong/>")[0][0].startswith(
        "root element is <wrong>"
    )
    assert "not well-formed XML" in validate_flowmon_xml("{json?}")[0][0]


def _toy_events(n=8):
    return [
        PacketEvent(step=i, t_us=i * 100, flow=1 + i % 2, size=1000,
                    verdict=VERDICT_RX if i % 2 else VERDICT_TX)
        for i in range(n)
    ]


def test_pcap_validator_both_endiannesses_and_ns_magic(tmp_path):
    p = tmp_path / "cap.pcap"
    n = write_events_pcap(_toy_events(), str(p),
                          verdicts=(VERDICT_TX, VERDICT_RX))
    assert n == 8
    data = p.read_bytes()
    assert validate_pcap(data) == ([], 8)
    # big-endian + nanosecond magic, zero-payload records
    hdr = struct.pack(">IHHiIII", 0xA1B23C4D, 2, 4, 0, 0, 65535, 101)
    recs = b"".join(
        struct.pack(">IIII", 0, t * 1000, 16, 16) + bytes(16)
        for t in range(5)
    )
    assert validate_pcap(hdr + recs) == ([], 5)
    # pcapng is rejected with conversion advice, not a parse crash
    problems, _ = validate_pcap(struct.pack("<I", 0x0A0D0D0A) + data[4:])
    assert "pcapng container" in problems[0] and "tcpdump" in problems[0]
    # truncation names the exact record and byte offset
    problems, n = validate_pcap(data[:-3])
    assert n == 7 and "truncated" in problems[0]


def test_obs_cli_flowmon_and_pcap_modes(tmp_path):
    """Satellite: ``python -m tpudes.obs --flowmon/--pcap`` — the CI
    gate over the artifacts a TpudesObs=1 run writes."""
    xml = tmp_path / "flowmon.xml"
    serialize_flow_stats_xml(_toy_stats(), {}, str(xml))
    cap = tmp_path / "run.pcap"
    write_events_pcap(_toy_events(), str(cap), verdicts=(VERDICT_RX,))
    bad = tmp_path / "bad.pcap"
    bad.write_bytes(cap.read_bytes()[:-2])

    def cli(*args):
        return subprocess.run(
            [sys.executable, "-m", "tpudes.obs", *args],
            capture_output=True, text=True, cwd=REPO, timeout=120,
        )

    ok = cli("--flowmon", str(xml))
    assert ok.returncode == 0 and "valid FlowMonitor XML (2 records)" in ok.stdout
    ok = cli("--pcap", str(cap))
    assert ok.returncode == 0 and "valid pcap capture (4 records)" in ok.stdout
    fail = cli("--pcap", str(bad))
    assert fail.returncode == 1 and "truncated" in fail.stdout
    missing = cli("--flowmon", str(tmp_path / "nope.xml"))
    assert missing.returncode == 2
    both = cli("--flowmon", "--pcap", str(xml))
    assert both.returncode == 2  # mutually exclusive modes


# --- pcap round trip: device run -> EnablePcap format -> TrafficProgram ----


def test_device_pcap_roundtrips_into_trace_replay_program(tmp_path):
    """Satellite acceptance: a device run's delivered packets, written
    through the trace_helper pcap frame format, read back by
    traffic/ingest.read_pcap, reproduce the offered load as an exact
    trace-replay TrafficProgram."""
    from tpudes.obs.device import ChunkStream
    from tpudes.parallel.wired import run_wired, wired_chain
    from tpudes.traffic.ingest import ingest_traces, read_pcap

    prog = wired_chain(n_links=3, n_flows=2, n_slots=60, jitter_slots=0)
    GlobalValue.Bind("TpudesObs", 1)
    ChunkStream.reset()
    res = run_wired(prog, KEY, replicas=1, window_slots=16)
    snaps = [
        np.asarray(e["metrics"]["fm_ring"])[0]
        for e in ChunkStream.entries("wired")
    ]
    mon = DeviceFlowMonitor(
        {k: v[0] for k, v in res["flow"].items() if k != "fm_ring"},
        rings=snaps + [res["flow"]["fm_ring"][0]],
    )
    rx = [e for e in mon.events if e.verdict == VERDICT_RX]
    assert rx, "the run must deliver packets"

    cap = tmp_path / "device.pcap"
    n = mon.WritePcap(str(cap))
    assert n == len(rx)
    times_us, bytes_ = read_pcap(str(cap))
    np.testing.assert_array_equal(times_us, [e.t_us for e in rx])
    np.testing.assert_array_equal(bytes_, [e.size for e in rx])

    # the same offered load whether built from the pcap file (one merged
    # lane — frames carry no flow attribution) or straight from the ring
    # (one lane per flow id)
    via_pcap = ingest_traces([(times_us, bytes_)], t0_us=0)
    via_ring = mon.ToTrafficProgram()
    assert via_ring.arr_t.shape[0] == len({e.flow for e in rx})

    def _pairs(p):
        t, b = np.asarray(p.arr_t), np.asarray(p.arr_b)
        m = b > 0
        return sorted(zip(t[m].tolist(), b[m].tolist()))

    want = sorted((e.t_us, e.size) for e in rx)
    assert _pairs(via_pcap) == _pairs(via_ring) == want
    # offered load reproduced: every delivered wire byte is in the table
    assert int(via_pcap.arr_b[via_pcap.arr_b > 0].sum()) == sum(
        e.size for e in rx
    )


# --- DeviceFlowMonitor reporting surface + Chrome-trace flow spans ---------


def test_device_flow_monitor_exports_and_flow_spans(tmp_path):
    from tpudes.obs.export import flow_trace_events, validate_chrome_trace

    fm = {k: np.asarray(v) for k, v in flow_carry(2).items()
          if k != "fm_ring"}
    fm["fm_tx"] = np.asarray([5, 3], np.int32)
    fm["fm_txb"] = np.asarray([5200, 3120], np.int32)
    fm["fm_rx"] = np.asarray([5, 0], np.int32)
    fm["fm_rxb"] = np.asarray([5200, 0], np.int32)
    fm["fm_dsum"] = np.asarray([0.02, 0.0], np.float32)
    fm["fm_t0"] = np.asarray([0.1, 0.2], np.float32)
    fm["fm_t1"] = np.asarray([0.8, -1.0], np.float32)
    mon = DeviceFlowMonitor(fm)
    xml = tmp_path / "flowmon.xml"
    mon.SerializeToXmlFile(str(xml))
    problems, n = validate_flowmon_xml(xml.read_text())
    assert problems == [] and n == 2

    spans = flow_trace_events(mon.GetFlowStats())
    doc = {"traceEvents": spans, "displayTimeUnit": "ms"}
    assert validate_chrome_trace(doc) == []
    xs = [e for e in spans if e["ph"] == "X"]
    assert [e["name"] for e in xs] == ["flow 1", "flow 2"]
    assert xs[0]["ts"] == pytest.approx(0.1e6) and xs[0]["dur"] == pytest.approx(0.7e6)
    assert xs[1]["dur"] == 0.0  # never-delivered flow: zero-length span
    assert xs[0]["args"]["rxPackets"] == 5
