"""Device-resident mobility (ISSUE-10): the geometry pipeline that
melts the mobility ❌ rows.

Pinned contracts:

- **Closed-form kernels** (``tpudes.ops.mobility``): const-velocity is
  exact kinematics, the walk is deterministic in its seed and bounded,
  the waypoint interpolation pauses at the final waypoint and treats
  zero-velocity segments as pauses.
- **Stride contract**: ``geom_stride=1`` is BIT-identical to the
  unconditional per-step recompute program, and the refresh count is
  ``ceil(steps/stride)`` — the geometry stage really skips work.
- **One executable**: mobility model id, every mobility parameter, and
  the stride are traced operands — flipping any of them must not
  recompile (CompileTelemetry pins it on both engines, including a
  model-family flip through the live-graph lowering).
- **Kill switch**: ``TPUDES_DEVICE_GEOM=0`` restores the loud refusal
  on both lowerings; on the LTE engine a mobile program still runs via
  the precomputed-positions per-window fallback, pinned bit-equal.
- **Host parity**: device mobile runs track the host DES with the same
  mobility trace at the documented fuzz bands (exact-trace models),
  including the waypoint edge cases.
- **Coherence advisory**: both lowerings warn when the stride lets the
  fastest node outrun the geometry coherence scale.
"""

import dataclasses
import math
import warnings

import jax
import numpy as np
import pytest

from tpudes.ops.mobility import (
    GEOM_COHERENCE_M,
    MobilityProgram,
    build_position_fn,
    fold_into_bounds,
    max_speed_mps,
    trajectory_positions,
)


def _pos(prog, t_us):
    fn = build_position_fn(prog)
    return np.asarray(fn(prog.operands(), jax.numpy.int32(t_us)))


def _reset_world():
    from tpudes.core.world import reset_world

    reset_world()


# --------------------------------------------------------------------------
# closed-form kernels
# --------------------------------------------------------------------------


class TestMobilityKernels:
    def test_const_velocity_closed_form(self):
        base = np.array([[0, 0, 0], [10, -5, 2]], np.float32)
        vel = np.array([[1, 2, 0], [-0.5, 0, 0]], np.float32)
        prog = MobilityProgram.constant_velocity(base, vel)
        np.testing.assert_allclose(
            _pos(prog, 3_000_000), base + 3.0 * vel, rtol=1e-6
        )

    def test_static_model_never_moves(self):
        base = np.array([[4, 5, 6]], np.float32)
        prog = MobilityProgram.static(base)
        for t in (0, 1, 999_999, 10_000_000):
            np.testing.assert_array_equal(_pos(prog, t), base)

    def test_walk_bounded_deterministic_and_seeded(self):
        base = np.array([[5, 5, 0], [15, 15, 0]], np.float32)
        speed = np.array([[1.0, 3.0], [1.0, 3.0]], np.float32)
        mk = lambda s: MobilityProgram.random_walk(  # noqa: E731
            base, (0.0, 20.0, 0.0, 20.0), speed, seg_s=0.25,
            horizon_us=4_000_000, mob_seed=s,
        )
        a = mk(7)
        for t in (0, 700_000, 1_900_000, 3_500_000):
            p = _pos(a, t)
            assert (p[:, 0] >= 0).all() and (p[:, 0] <= 20).all()
            assert (p[:, 1] >= 0).all() and (p[:, 1] <= 20).all()
            np.testing.assert_array_equal(p, _pos(mk(7), t))
        assert not np.array_equal(_pos(a, 2_000_000), _pos(mk(8), 2_000_000))

    def test_walk_zero_band_node_is_pinned_even_outside_bounds(self):
        # a static AP outside the walkers' rectangle must NOT be folded
        base = np.array([[50, 50, 0], [5, 5, 0]], np.float32)
        speed = np.array([[0.0, 0.0], [1.0, 2.0]], np.float32)
        prog = MobilityProgram.random_walk(
            base, (0.0, 10.0, 0.0, 10.0), speed, seg_s=0.5,
            horizon_us=2_000_000,
        )
        np.testing.assert_array_equal(_pos(prog, 1_500_000)[0], base[0])

    def test_walk_is_cadence_indifferent(self):
        # closed form in t: sampling the trajectory sparsely or densely
        # reads the SAME motion (what makes geom_stride a pure
        # staleness knob, not a different trajectory)
        base = np.array([[5, 5, 0]], np.float32)
        speed = np.array([[1.0, 2.0]], np.float32)
        prog = MobilityProgram.random_walk(
            base, (0.0, 12.0, 0.0, 12.0), speed, seg_s=0.3,
            horizon_us=3_000_000, mob_seed=3,
        )
        dense = trajectory_positions(
            prog, list(range(0, 3_000_001, 100_000))
        )
        np.testing.assert_array_equal(dense[10], _pos(prog, 1_000_000))

    def test_waypoint_interpolation_and_pause_at_final(self):
        wt = np.array([[100_000, 1_100_000, 2_100_000]])
        wp = np.array([[[0, 0, 0], [10, 0, 0], [10, 20, 0]]], np.float32)
        prog = MobilityProgram.waypoints(wt, wp)
        # holds the first waypoint before its time
        np.testing.assert_allclose(_pos(prog, 0), [[0, 0, 0]], atol=1e-6)
        # linear mid-leg
        np.testing.assert_allclose(
            _pos(prog, 600_000), [[5, 0, 0]], atol=1e-5
        )
        # pauses at the final waypoint forever after
        for t in (2_100_000, 5_000_000, 60_000_000):
            np.testing.assert_allclose(
                _pos(prog, t), [[10, 20, 0]], atol=1e-6
            )

    def test_waypoint_zero_velocity_segment_is_a_pause(self):
        # consecutive identical positions = a dwell; consecutive
        # identical TIMES (zero-duration leg) must not divide by zero
        wt = np.array([[0, 1_000_000, 2_000_000, 2_000_000]])
        wp = np.array(
            [[[0, 0, 0], [8, 0, 0], [8, 0, 0], [9, 9, 0]]], np.float32
        )
        prog = MobilityProgram.waypoints(wt, wp)
        np.testing.assert_allclose(
            _pos(prog, 1_500_000), [[8, 0, 0]], atol=1e-5
        )
        out = _pos(prog, 2_000_000)
        assert np.isfinite(out).all()

    def test_fold_into_bounds_identity_and_reflection(self):
        import jax.numpy as jnp

        x = jnp.asarray([2.0, 11.0, -3.0, 23.0])
        out = np.asarray(fold_into_bounds(x, 0.0, 10.0))
        np.testing.assert_allclose(out, [2.0, 9.0, 3.0, 3.0], atol=1e-6)

    def test_max_speed_per_model(self):
        base = np.zeros((2, 3), np.float32)
        assert max_speed_mps(MobilityProgram.static(base)) == 0.0
        cv = MobilityProgram.constant_velocity(
            base, np.array([[3, 4, 0], [0, 0, 0]], np.float32)
        )
        assert max_speed_mps(cv) == pytest.approx(5.0)
        wk = MobilityProgram.random_walk(
            base, (0, 1, 0, 1),
            np.array([[0.5, 2.5], [0, 0]], np.float32),
            horizon_us=1_000_000,
        )
        assert max_speed_mps(wk) == pytest.approx(2.5)
        wp = MobilityProgram.waypoints(
            np.array([[0, 1_000_000]]),
            np.array([[[0, 0, 0], [7, 0, 0]]], np.float32),
        )
        assert max_speed_mps(wp) == pytest.approx(7.0)


# --------------------------------------------------------------------------
# live-graph extraction
# --------------------------------------------------------------------------


class TestExtraction:
    def _nodes(self, models):
        from tpudes.helper.containers import NodeContainer

        nodes = NodeContainer()
        nodes.Create(len(models))
        for i, m in enumerate(models):
            nodes.Get(i).AggregateObject(m)
        return [nodes.Get(i) for i in range(len(models))]

    def test_all_static_returns_none(self):
        from tpudes.models.mobility import (
            ConstantPositionMobilityModel,
            Vector,
            device_mobility_program,
        )

        _reset_world()
        ms = [ConstantPositionMobilityModel() for _ in range(2)]
        for i, m in enumerate(ms):
            m.SetPosition(Vector(i, 0, 0))
        assert device_mobility_program(self._nodes(ms), 1_000_000) is None
        _reset_world()

    def test_mixed_moving_families_raise(self):
        from tpudes.models.mobility import (
            ConstantVelocityMobilityModel,
            UnliftableMobilityError,
            Vector,
            WaypointMobilityModel,
            device_mobility_program,
        )
        from tpudes.core.nstime import Seconds

        _reset_world()
        cv = ConstantVelocityMobilityModel()
        cv.SetPosition(Vector(0, 0, 0))
        cv.SetVelocity(Vector(1, 0, 0))
        wp = WaypointMobilityModel()
        wp.AddWaypoint(Seconds(0), Vector(1, 1, 0))
        wp.AddWaypoint(Seconds(1), Vector(2, 1, 0))
        with pytest.raises(UnliftableMobilityError):
            device_mobility_program(self._nodes([cv, wp]), 1_000_000)
        _reset_world()

    def test_gauss_markov_has_no_device_form(self):
        from tpudes.models.mobility import (
            GaussMarkovMobilityModel,
            UnliftableMobilityError,
            Vector,
            device_mobility_program,
        )

        _reset_world()
        gm = GaussMarkovMobilityModel()
        gm.SetPosition(Vector(0, 0, 0))
        with pytest.raises(UnliftableMobilityError):
            device_mobility_program(self._nodes([gm]), 1_000_000)
        _reset_world()

    def test_static_nodes_ride_a_waypoint_batch_as_pauses(self):
        from tpudes.core.nstime import Seconds
        from tpudes.models.mobility import (
            ConstantPositionMobilityModel,
            Vector,
            WaypointMobilityModel,
            device_mobility_program,
        )

        _reset_world()
        wp = WaypointMobilityModel()
        wp.AddWaypoint(Seconds(0.0), Vector(0, 0, 0))
        wp.AddWaypoint(Seconds(1.0), Vector(6, 0, 0))
        cp = ConstantPositionMobilityModel()
        cp.SetPosition(Vector(9, 9, 9))
        prog = device_mobility_program(
            self._nodes([wp, cp]), 2_000_000
        )
        assert prog.model == "waypoint"
        out = _pos(prog, 1_700_000)
        np.testing.assert_allclose(out[0], [6, 0, 0], atol=1e-5)
        np.testing.assert_allclose(out[1], [9, 9, 9], atol=1e-6)
        _reset_world()


# --------------------------------------------------------------------------
# BSS engine
# --------------------------------------------------------------------------


def _bss_mobile_prog(mobility="const_velocity", speed=1.0, stride=1,
                     n_stas=3, sim_s=1.5):
    from tpudes.parallel.replicated import lower_bss
    from tpudes.scenarios import build_bss

    _reset_world()
    stas, ap, clients, _ = build_bss(
        n_stas, sim_s, mobility=mobility, speed=speed
    )
    prog = lower_bss(
        [stas.Get(i) for i in range(n_stas)], ap, clients, sim_s,
        geom_stride=stride,
    )
    _reset_world()
    return prog


class TestBssMobile:
    def test_stride1_bit_identical_to_per_step_recompute(self):
        from tpudes.parallel.replicated import run_replicated_bss

        prog = _bss_mobile_prog(stride=1)
        a = run_replicated_bss(prog, 8, jax.random.PRNGKey(0))
        b = run_replicated_bss(
            prog, 8, jax.random.PRNGKey(0), geom_per_step=True
        )
        for k in ("srv_rx", "cli_rx", "tx_data", "drops"):
            np.testing.assert_array_equal(
                np.asarray(a[k]), np.asarray(b[k]), err_msg=k
            )

    def test_stride_refresh_accounting(self):
        from tpudes.parallel.replicated import run_replicated_bss

        prog = _bss_mobile_prog(stride=4)
        out = run_replicated_bss(prog, 4, jax.random.PRNGKey(1))
        assert out["geom_stride"] == 4
        assert out["geom_refreshes"] == -(-out["steps"] // 4)
        one = run_replicated_bss(
            dataclasses.replace(prog, geom_stride=1), 4,
            jax.random.PRNGKey(1),
        )
        assert one["geom_refreshes"] == one["steps"]

    def test_params_model_and_stride_are_traced(self):
        # live-graph lowering of BOTH mobile families at the same shape
        # → ONE executable (the CompileTelemetry pin of the acceptance
        # criteria); stride and speed flips ride along free
        from tpudes.obs.device import CompileTelemetry
        from tpudes.parallel.replicated import run_replicated_bss
        from tpudes.parallel.runtime import RUNTIME

        cv = _bss_mobile_prog("const_velocity", speed=0.8, stride=1)
        walk = _bss_mobile_prog("random_walk", speed=0.8, stride=5)
        assert (
            cv.mobility.shape_key() == walk.mobility.shape_key()
        ), "family shapes must be normalized for the one-executable pin"
        RUNTIME.clear("bss")
        CompileTelemetry.reset()
        run_replicated_bss(cv, 4, jax.random.PRNGKey(0))
        assert CompileTelemetry.compiles("bss") == 1
        run_replicated_bss(walk, 4, jax.random.PRNGKey(0))
        run_replicated_bss(
            dataclasses.replace(cv, geom_stride=9), 4, jax.random.PRNGKey(2)
        )
        assert CompileTelemetry.compiles("bss") == 1, (
            "mobility model id / params / stride must be traced operands"
        )

    @pytest.mark.slow  # tier-1 covers this via corpus bss-seed202/244
    def test_chunked_and_swept_mobile_runs_bit_equal(self):
        from tpudes.parallel.replicated import run_replicated_bss

        prog = _bss_mobile_prog(stride=3)
        solo = run_replicated_bss(prog, 5, jax.random.PRNGKey(3))
        chunked = run_replicated_bss(
            prog, 5, jax.random.PRNGKey(3), chunk_steps=11
        )
        swept = run_replicated_bss(
            prog, 5, jax.random.PRNGKey(3),
            sim_end_us=[prog.sim_end_us, prog.sim_end_us * 3 // 4],
        )[0]
        for k in ("srv_rx", "cli_rx", "tx_data", "drops"):
            np.testing.assert_array_equal(
                np.asarray(solo[k]), np.asarray(chunked[k]), err_msg=k
            )
            np.testing.assert_array_equal(
                np.asarray(solo[k]), np.asarray(swept[k]), err_msg=k
            )

    def test_kill_switch_restores_refusal(self, monkeypatch):
        from tpudes.parallel.replicated import UnliftableScenarioError

        monkeypatch.setenv("TPUDES_DEVICE_GEOM", "0")
        with pytest.raises(UnliftableScenarioError, match="DEVICE_GEOM"):
            _bss_mobile_prog()

    def test_trajectory_leaving_sensing_range_is_refused(self):
        from tpudes.parallel.replicated import UnliftableScenarioError

        # 120 m/s tangential drift for 1.5 s sweeps the outer STAs
        # ~180 m out; opposite pairs end ~300 m apart — far beyond the
        # ~220 m log-distance sensing radius at some trajectory sample
        with pytest.raises(UnliftableScenarioError, match="trajectory"):
            _bss_mobile_prog(
                "const_velocity", speed=120.0, n_stas=3, sim_s=1.5,
            )

    @pytest.mark.slow  # multi-device CI runs the full file
    def test_host_parity_const_velocity_trace(self):
        """Device mobile runs vs the host DES with the SAME
        constant-velocity trace (exact-trace model): the documented
        distribution-level band."""
        from tpudes.core import Seconds, Simulator
        from tpudes.core.rng import RngSeedManager
        from tpudes.parallel.replicated import run_replicated_bss
        from tpudes.scenarios import build_bss

        des = []
        for run in range(1, 6):
            _reset_world()
            RngSeedManager.SetRun(run)
            _, _, _, rx = build_bss(
                3, 1.5, mobility="const_velocity", speed=1.0
            )
            Simulator.Stop(Seconds(1.5))
            Simulator.Run()
            des.append(rx[0])
        _reset_world()
        prog = _bss_mobile_prog("const_velocity", speed=1.0)
        out = run_replicated_bss(prog, 64, jax.random.PRNGKey(9))
        assert out["all_done"]
        rep = np.asarray(out["srv_rx"], np.float64)
        des = np.asarray(des, np.float64)
        sem = math.sqrt(
            des.var(ddof=1) / len(des) + rep.var(ddof=1) / len(rep)
        )
        assert abs(des.mean() - rep.mean()) <= 3.0 * sem + 1.5, (
            f"DES {des.mean():.2f} vs device {rep.mean():.2f} "
            f"(sem {sem:.2f})"
        )

    def test_host_parity_waypoint_edges(self):
        """Waypoint trace with a dwell (zero-velocity segment) and a
        final-waypoint pause: device vs host DES on the same table."""
        from tpudes.core import Seconds, Simulator
        from tpudes.core.nstime import Seconds as S
        from tpudes.core.rng import RngSeedManager
        from tpudes.models.mobility import (
            MobilityModel,
            Vector,
            WaypointMobilityModel,
        )
        from tpudes.parallel.replicated import lower_bss, run_replicated_bss
        from tpudes.scenarios import build_bss

        def _graph():
            stas, ap, clients, rx = build_bss(3, 1.5)
            # STA 0 walks 6 m outward, dwells, then pauses at the end
            node = stas.Get(0).GetNode()
            old = node.GetObject(MobilityModel)
            p0 = old.GetPosition()
            wp = WaypointMobilityModel()
            ring = node._aggregates
            ring[ring.index(old)] = wp
            wp._aggregates = ring
            wp.AddWaypoint(S(0.0), p0)
            wp.AddWaypoint(S(0.4), Vector(p0.x + 6.0, p0.y, p0.z))
            wp.AddWaypoint(S(0.8), Vector(p0.x + 6.0, p0.y, p0.z))
            wp.AddWaypoint(S(1.0), Vector(p0.x, p0.y + 4.0, p0.z))
            return stas, ap, clients, rx

        des = []
        for run in range(1, 5):
            _reset_world()
            RngSeedManager.SetRun(run)
            _, _, _, rx = _graph()
            Simulator.Stop(Seconds(1.5))
            Simulator.Run()
            des.append(rx[0])
        _reset_world()
        stas, ap, clients, _ = _graph()
        prog = lower_bss(
            [stas.Get(i) for i in range(3)], ap, clients, 1.5
        )
        _reset_world()
        assert prog.mobility is not None and prog.mobility.model == "waypoint"
        out = run_replicated_bss(prog, 64, jax.random.PRNGKey(4))
        rep = np.asarray(out["srv_rx"], np.float64)
        des = np.asarray(des, np.float64)
        sem = math.sqrt(
            des.var(ddof=1) / len(des) + rep.var(ddof=1) / len(rep)
        )
        assert abs(des.mean() - rep.mean()) <= 3.0 * sem + 1.5

    def test_stride_coherence_warning_boundary(self):
        # ~0.011 s/step estimate at this load; 1 m/s × stride 400 ≈ 4 m
        # drift > the 2 m coherence scale → warn; stride 1 is silent
        with pytest.warns(UserWarning, match="coherence"):
            _bss_mobile_prog(stride=400)
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            _bss_mobile_prog(stride=1)


# --------------------------------------------------------------------------
# LTE engine
# --------------------------------------------------------------------------


def _lte_mobile_prog(mobility="const_velocity", speed=10.0, stride=1,
                     sim_s=0.08, n_enbs=2, upc=2, warn_ok=False):
    from tpudes.parallel.lte_sm import lower_lte_sm
    from tpudes.scenarios import build_lena

    _reset_world()
    lte, _ = build_lena(n_enbs, upc, mobility=mobility, speed=speed)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        prog = lower_lte_sm(lte, sim_s, geom_stride=stride)
    _reset_world()
    return prog


class TestLteMobile:
    @pytest.mark.slow  # tier-1 covers this via corpus lte_sm-seed219/227
    def test_device_geom_off_fallback_bit_equal(self, monkeypatch):
        from tpudes.parallel.lte_sm import run_lte_sm

        for model, stride in (("const_velocity", 1), ("random_walk", 8)):
            prog = _lte_mobile_prog(model, stride=stride)
            on = run_lte_sm(prog, jax.random.PRNGKey(0), replicas=3)
            monkeypatch.setenv("TPUDES_DEVICE_GEOM", "0")
            off = run_lte_sm(prog, jax.random.PRNGKey(0), replicas=3)
            monkeypatch.delenv("TPUDES_DEVICE_GEOM")
            for k in ("rx_bits", "ok", "retx", "drops", "cqi", "sinr"):
                np.testing.assert_array_equal(
                    np.asarray(on[k]), np.asarray(off[k]),
                    err_msg=f"{model}/{k}",
                )

    def test_model_params_and_stride_are_traced(self):
        from tpudes.obs.device import CompileTelemetry
        from tpudes.parallel.lte_sm import run_lte_sm
        from tpudes.parallel.runtime import RUNTIME

        cv = _lte_mobile_prog("const_velocity", stride=1)
        walk = _lte_mobile_prog("random_walk", stride=16)
        assert cv.mobility.shape_key() == walk.mobility.shape_key()
        RUNTIME.clear("lte_sm")
        CompileTelemetry.reset()
        a = run_lte_sm(cv, jax.random.PRNGKey(0), replicas=3)
        assert CompileTelemetry.compiles("lte_sm") == 1
        run_lte_sm(walk, jax.random.PRNGKey(0), replicas=3)
        run_lte_sm(
            dataclasses.replace(cv, geom_stride=5), jax.random.PRNGKey(1),
            replicas=3,
        )
        assert CompileTelemetry.compiles("lte_sm") == 1, (
            "model id / params / stride must be traced operands"
        )
        assert a["geom_refreshes"] == cv.n_ttis  # stride 1 = per TTI

    @pytest.mark.slow  # tier-1 covers chunking via corpus lte_sm-seed227
    def test_scheduler_sweep_and_chunking_bit_equal(self):
        from tpudes.parallel.lte_sm import run_lte_sm

        prog = _lte_mobile_prog(stride=4)
        solo = run_lte_sm(prog, jax.random.PRNGKey(2), replicas=3)
        chunked = run_lte_sm(
            prog, jax.random.PRNGKey(2), replicas=3, chunk_ttis=13
        )
        swept = run_lte_sm(
            prog, jax.random.PRNGKey(2), replicas=3,
            schedulers=[prog.scheduler, "rr"],
        )[0]
        for k in ("rx_bits", "ok", "retx", "drops"):
            np.testing.assert_array_equal(
                np.asarray(solo[k]), np.asarray(chunked[k]), err_msg=k
            )
            np.testing.assert_array_equal(
                np.asarray(solo[k]), np.asarray(swept[k]), err_msg=k
            )

    def test_pallas_and_xla_lowerings_agree_mobile(self, monkeypatch):
        from tpudes.parallel.lte_sm import run_lte_sm

        prog = _lte_mobile_prog(stride=2)
        a = run_lte_sm(prog, jax.random.PRNGKey(5), replicas=2)
        monkeypatch.setenv("TPUDES_PALLAS", "0")
        b = run_lte_sm(prog, jax.random.PRNGKey(5), replicas=2)
        np.testing.assert_array_equal(
            np.asarray(a["rx_bits"]), np.asarray(b["rx_bits"])
        )

    @pytest.mark.slow  # multi-device CI runs the full file
    def test_host_parity_const_velocity_trace(self):
        """Device mobile LTE vs the host TTI controller with the SAME
        constant-velocity trace, at the documented fuzz band."""
        from tpudes.core import Seconds, Simulator
        from tpudes.parallel.lte_sm import lower_lte_sm, run_lte_sm
        from tpudes.scenarios import build_lena

        _reset_world()
        lte, _ = build_lena(
            2, 3, mobility="const_velocity", speed=30.0, drop_seed=3
        )
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            prog = lower_lte_sm(lte, 0.3)
        Simulator.Stop(Seconds(0.3))
        Simulator.Run()
        host = sum(s["dl_rx_bytes"] for s in lte.GetRlcStats()) * 8
        _reset_world()
        out = run_lte_sm(prog, jax.random.PRNGKey(0), replicas=4)
        dev = float(np.asarray(out["rx_bits"]).sum(-1).mean())
        assert abs(host - dev) <= 0.35 * max(host, dev), (host, dev)

    def test_stride_coherence_warning_boundary(self):
        from tpudes.parallel.lte_sm import lower_lte_sm
        from tpudes.scenarios import build_lena

        # 30 m/s × 1 ms TTI: stride 100 drifts 3 m > 2 m → warn;
        # stride 10 drifts 0.3 m → silent
        _reset_world()
        lte, _ = build_lena(2, 2, mobility="const_velocity", speed=30.0)
        with pytest.warns(UserWarning, match="coherence"):
            lower_lte_sm(lte, 0.3, geom_stride=100)
        _reset_world()
        lte, _ = build_lena(2, 2, mobility="const_velocity", speed=30.0)
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            lower_lte_sm(lte, 0.3, geom_stride=10)
        _reset_world()
        assert GEOM_COHERENCE_M == pytest.approx(2.0)

    def test_kill_switch_restores_refusal(self, monkeypatch):
        from tpudes.parallel.lte_sm import (
            UnliftableLteScenarioError,
            lower_lte_sm,
        )
        from tpudes.scenarios import build_lena

        _reset_world()
        lte, _ = build_lena(2, 2, mobility="const_velocity", speed=5.0)
        monkeypatch.setenv("TPUDES_DEVICE_GEOM", "0")
        with pytest.raises(UnliftableLteScenarioError, match="DEVICE_GEOM"):
            lower_lte_sm(lte, 0.3)
        _reset_world()


# --------------------------------------------------------------------------
# the host controller's per-window fallback path
# --------------------------------------------------------------------------


class TestControllerFallback:
    def _run(self):
        from tpudes.core import Seconds, Simulator
        from tpudes.scenarios import build_lena

        _reset_world()
        lte, _ = build_lena(
            2, 2, mobility="const_velocity", speed=5.0, drop_seed=5
        )
        Simulator.Stop(Seconds(0.05))
        Simulator.Run()
        stats = dict(lte.controller.stats)
        _reset_world()
        return stats

    def test_geometry_only_refresh_bit_equal_to_full_rebuild(
        self, monkeypatch
    ):
        # TPUDES_DEVICE_GEOM selects the geometry-only refresh vs the
        # legacy full per-window rebuild — same math, same inputs, so
        # the LTE per-window path must be bit-equal either way
        a = self._run()
        monkeypatch.setenv("TPUDES_DEVICE_GEOM", "0")
        b = self._run()
        assert a == b

    def test_host_refreshes_recorded(self):
        from tpudes.obs.geometry import GeomTelemetry

        GeomTelemetry.reset()
        from tpudes.core import Seconds, Simulator
        from tpudes.parallel.engine import BatchableRegistry
        from tpudes.scenarios import build_lena

        _reset_world()
        lte, _ = build_lena(2, 2, mobility="const_velocity", speed=5.0)
        # drive the per-window refresh the way a windowed engine does
        Simulator.Stop(Seconds(0.01))
        Simulator.Run()
        for member in BatchableRegistry.members():
            if hasattr(member, "refresh_window_cache"):
                member.refresh_window_cache()
        _reset_world()
        snap = GeomTelemetry.snapshot()
        assert snap["engines"]["lte_ctrl"]["host_refreshes"] >= 1


# --------------------------------------------------------------------------
# telemetry schema
# --------------------------------------------------------------------------


def test_geometry_metrics_schema_gate(tmp_path, capsys):
    import json

    from tpudes.obs.__main__ import main as obs_main
    from tpudes.obs.geometry import GeomTelemetry, validate_geometry_metrics

    GeomTelemetry.reset()
    GeomTelemetry.record_device("bss", 5, 20)
    GeomTelemetry.record_host("lte_ctrl", 3)
    snap = GeomTelemetry.snapshot()
    assert validate_geometry_metrics(snap) == []
    assert snap["engines"]["bss"]["stride_hit_rate"] == pytest.approx(0.75)
    p = tmp_path / "geom.json"
    p.write_text(json.dumps(snap))
    assert obs_main(["--geometry", str(p)]) == 0
    bad = {"version": 1, "engines": {"bss": {
        "device_refreshes": 30, "host_refreshes": 0, "steps": 20,
        "stride_hit_rate": 2.0,
    }}}
    assert validate_geometry_metrics(bad) != []
    GeomTelemetry.reset()
    capsys.readouterr()
