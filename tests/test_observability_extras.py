"""ConfigStore, stats pipeline, NetAnim XML, null-message support tests."""

import xml.etree.ElementTree as ET

import pytest

from tpudes.core import Seconds, Simulator
from tpudes.core.config import Config
from tpudes.core.config_store import ConfigStore
from tpudes.core.global_value import GlobalValue
from tpudes.helper.applications import UdpEchoClientHelper, UdpEchoServerHelper
from tpudes.helper.containers import NodeContainer
from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
from tpudes.helper.point_to_point import PointToPointHelper


def _echo_pair(packets=3):
    nodes = NodeContainer()
    nodes.Create(2)
    p2p = PointToPointHelper()
    p2p.SetDeviceAttribute("DataRate", "5Mbps")
    p2p.SetChannelAttribute("Delay", "2ms")
    devices = p2p.Install(nodes)
    InternetStackHelper().Install(nodes)
    ifc = Ipv4AddressHelper("10.1.1.0", "255.255.255.0").Assign(devices)
    server = UdpEchoServerHelper(9)
    sapps = server.Install(nodes.Get(1))
    sapps.Start(Seconds(0.0))
    client = UdpEchoClientHelper(ifc.GetAddress(1), 9)
    client.SetAttribute("MaxPackets", packets)
    client.SetAttribute("Interval", Seconds(0.1))
    client.SetAttribute("PacketSize", 400)
    client.Install(nodes.Get(0)).Start(Seconds(0.1))
    return nodes, devices, sapps


# --- ConfigStore ------------------------------------------------------------
def test_config_store_save_load_round_trip(tmp_path):
    path = str(tmp_path / "config.txt")
    Config.SetDefault("tpudes::PointToPointNetDevice::DataRate", "42Mbps")
    GlobalValue.Bind("RngRun", 77)
    ConfigStore(Mode="Save", Filename=path).ConfigureDefaults()
    text = open(path).read()
    assert 'default tpudes::PointToPointNetDevice::DataRate "42Mbps"' in text
    assert 'global RngRun "77"' in text

    # wipe, then replay the file
    from tpudes.core.world import reset_world

    reset_world()
    from tpudes.core.object import _DEFAULT_OVERRIDES

    _DEFAULT_OVERRIDES.clear()
    assert GlobalValue.GetValue("RngRun") == 1
    ConfigStore(Mode="Load", Filename=path).ConfigureDefaults()
    assert GlobalValue.GetValue("RngRun") == 77
    from tpudes.models.p2p import PointToPointNetDevice

    dev = PointToPointNetDevice()
    assert dev.GetAttribute("DataRate").GetBitRate() == 42_000_000


def test_config_store_rejects_unknown_format():
    with pytest.raises(ValueError, match="RawText"):
        ConfigStore(Mode="Save", FileFormat="Xml")


# --- stats pipeline ---------------------------------------------------------
def test_probe_calculator_pipeline():
    from tpudes.models.stats import (
        CounterCalculator,
        MinMaxAvgTotalCalculator,
        Probe,
    )

    nodes, devices, sapps = _echo_pair(packets=5)
    calc = MinMaxAvgTotalCalculator()
    counter = CounterCalculator()
    probe = Probe(
        sapps.Get(0), "Rx", lambda pkt, *a: pkt.GetSize()
    )
    probe.Connect(calc.Update)
    probe.Connect(counter.Update)
    Simulator.Stop(Seconds(1.0))
    Simulator.Run()
    assert counter.getCount() == 5
    assert calc.getCount() == 5
    assert calc.getMin() == calc.getMax() == 400.0
    assert calc.getMean() == pytest.approx(400.0)
    assert calc.getSum() == 2000.0
    assert calc.getStddev() == pytest.approx(0.0)


def test_gnuplot_helper_emits_plt_and_dat(tmp_path):
    from tpudes.models.stats import GnuplotHelper

    nodes, devices, sapps = _echo_pair(packets=4)
    base = str(tmp_path / "rxbytes")
    helper = GnuplotHelper(base, title="rx", ylabel="bytes")
    helper.PlotProbe(
        sapps.Get(0), "Rx", "server-rx", lambda pkt, *a: pkt.GetSize()
    )
    Simulator.Stop(Seconds(1.0))
    Simulator.Run()
    helper.Finish()
    plt = open(base + ".plt").read()
    assert "set terminal png" in plt and "server-rx" in plt
    rows = open(base + "-0.dat").read().splitlines()
    assert len(rows) == 4
    t0, v0 = rows[0].split()
    assert float(t0) > 0.1 and float(v0) == 400.0


def test_file_aggregator(tmp_path):
    from tpudes.models.stats import FileAggregator

    agg = FileAggregator(str(tmp_path / "a.dat"))
    agg.Write(1.5, t=0.25)
    agg.Write(2.5, t=0.50)
    agg.Close()
    lines = open(tmp_path / "a.dat").read().splitlines()
    assert lines[0].split()[1] == "1.5"
    assert len(lines) == 2


# --- NetAnim ----------------------------------------------------------------
def test_netanim_xml_has_topology_and_packets(tmp_path):
    from tpudes.models.netanim import AnimationInterface
    from tpudes.models.mobility import (
        ListPositionAllocator,
        MobilityHelper,
        Vector,
    )

    nodes, devices, sapps = _echo_pair(packets=3)
    alloc = ListPositionAllocator()
    alloc.Add(Vector(10.0, 20.0, 0.0))
    alloc.Add(Vector(50.0, 20.0, 0.0))
    mob = MobilityHelper()
    mob.SetPositionAllocator(alloc)
    mob.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    mob.Install(nodes)
    path = str(tmp_path / "anim.xml")
    anim = AnimationInterface(path)
    Simulator.Stop(Seconds(1.0))
    Simulator.Run()
    Simulator.Destroy()  # flushes + closes the file
    root = ET.parse(path).getroot()
    assert root.tag == "anim"
    node_els = root.findall("node")
    assert len(node_els) == 2
    assert node_els[0].get("locX") == "10.0"
    links = root.findall("link")
    assert len(links) == 1
    pkts = root.findall("p")
    # 3 requests + 3 echoes, tx/rx matched with ordered times
    assert len(pkts) == 6
    for p in pkts:
        assert float(p.get("fbRx")) > float(p.get("fbTx"))
    assert anim.packets_written == 6