"""Wired-graph per-link-queue device engine (ISSUE-9).

The partition unit of the hybrid PDES: deterministic CBR over explicit
multi-hop paths, timestamps EXACT against the sequential host DES —
the property that lets the space-parallel runs be checked
timestamp-for-timestamp rather than statistically.
"""

import numpy as np
import pytest

import jax

from tpudes.parallel.wired import (
    INF_SLOT,
    UnliftableWiredError,
    WiredProgram,
    packet_table,
    partition_flows,
    partition_lookahead,
    run_wired,
    run_wired_host,
    wired_chain,
    wired_weak_chain,
)

KEY = jax.random.key(7)


# --- program validation ----------------------------------------------------


def test_zero_service_rejected():
    with pytest.raises(UnliftableWiredError, match="service"):
        wired_chain(n_links=3, service=[1, 0, 1])


def test_zero_delay_rejected():
    """delay >= 1 is the FIFO contract: a zero-delay hop would make
    same-slot arrival order depend on event insertion order."""
    with pytest.raises(UnliftableWiredError, match="delay"):
        wired_chain(n_links=3, delay=[2, 0, 2])


def test_bad_link_id_rejected():
    prog = wired_chain(n_links=4)
    with pytest.raises(UnliftableWiredError, match="link id"):
        WiredProgram(
            n_links=4,
            service_slots=np.asarray(prog.service_slots),
            delay_slots=np.asarray(prog.delay_slots),
            paths=np.asarray([[0, 9, -1, -1]], np.int32),
            start_slot=np.asarray([1], np.int32),
            period_slots=np.asarray([5], np.int32),
            n_pkts=np.asarray([3], np.int32),
            n_slots=100,
        )


# --- device vs host oracle (exact timestamps) ------------------------------


def test_device_matches_host_des_exactly():
    prog = wired_chain(n_links=6, n_flows=3, n_slots=500)
    host = run_wired_host(prog)
    dev = run_wired(prog, KEY, replicas=2)
    assert (dev["deliver_slot"][0] == host["deliver_slot"]).all()
    assert (dev["deliver_slot"][1] == host["deliver_slot"]).all()
    assert (dev["served"][0] == host["served"]).all()
    assert dev["delivered"].sum() > 0


def test_windowed_run_bit_identical_to_single_shot():
    """window_slots cuts the horizon into advance() segments — the
    grant-schedule-indifference the hybrid window protocol relies on."""
    prog = wired_chain(n_links=6, n_flows=3, n_slots=500)
    one = run_wired(prog, KEY, replicas=2)
    for window in (7, 63, 500):
        win = run_wired(prog, KEY, replicas=2, window_slots=window)
        for k in ("deliver_slot", "delivered", "served"):
            assert (one[k] == win[k]).all(), (k, window)


def test_jitter_replicas_differ_and_match_host_per_row():
    from tpudes.parallel.wired import _replica_jitter

    prog = wired_chain(n_links=5, n_flows=3, n_slots=400, jitter_slots=6)
    dev = run_wired(prog, KEY, replicas=3)
    jit = np.asarray(_replica_jitter(prog, KEY, 3))
    assert (jit >= 0).all() and (jit <= 6).all()
    # each replica's trajectory is the host DES run at its jitter row
    for r in range(3):
        host = run_wired_host(prog, jitter=jit[r])
        assert (dev["deliver_slot"][r] == host["deliver_slot"]).all(), r
    # some phase actually moved (seed-dependent but jit covers 3x3 rows)
    assert jit.any()


def test_replica_offset_slices_bit_equal():
    """Process p computing [lo, hi) with the global offset reproduces
    the same rows of one big launch — the multi-process replica
    sharding contract of procmesh."""
    prog = wired_chain(n_links=5, n_flows=3, n_slots=300, jitter_slots=4)
    full = run_wired(prog, KEY, replicas=5)
    lo = run_wired(prog, KEY, replicas=3, replica_offset=0)
    hi = run_wired(prog, KEY, replicas=2, replica_offset=3)
    stitched = np.concatenate([lo["deliver_slot"], hi["deliver_slot"]])
    assert (stitched == full["deliver_slot"]).all()


# --- partitioning ----------------------------------------------------------


def test_partition_flows_resident_sets():
    prog = wired_chain(n_links=6, n_flows=3, n_slots=300, ranks=2)
    sub0, flows0, pkts0 = partition_flows(prog, 0)
    sub1, flows1, pkts1 = partition_flows(prog, 1)
    # every flow reaches the chain tail, so rank 1 sees all flows;
    # rank 0 only those entering on its half
    assert set(flows1) == {0, 1, 2}
    pf, _, _ = packet_table(prog)
    assert pkts1.size == pf.size
    # id maps are strictly increasing (FIFO tiebreak order-consistent)
    assert (np.diff(pkts0) > 0).all() and (np.diff(pkts1) > 0).all()


def test_partition_flows_idle_rank_rejected():
    prog = wired_chain(n_links=4, n_flows=2, n_slots=200)
    with pytest.raises(UnliftableWiredError, match="idle"):
        partition_flows(prog, 3)


def test_partition_lookahead_boundary_minimum():
    prog = wired_chain(n_links=6, n_flows=3, n_slots=300, ranks=2,
                       boundary_delay=9)
    owner = np.asarray(prog.link_owner)
    cut = int(np.nonzero(np.diff(owner))[0][0])
    svc = int(prog.service_slots[cut])
    dly = int(prog.delay_slots[cut])
    assert partition_lookahead(prog, 0) == svc + dly
    # the tail rank never sends back on a chain
    assert partition_lookahead(prog, 1) == INF_SLOT


def test_weak_chain_is_uniform_and_aligned():
    wp = wired_weak_chain(4, links_per_rank=3, flows_per_rank=2,
                          n_slots=2000)
    assert wp.n_ranks == 4
    subs = [partition_flows(wp, r) for r in range(4)]
    # uniform partitions: equal per-rank flow/packet counts
    assert len({s[0].n_flows for s in subs}) == 1
    assert len({packet_table(s[0])[0].size for s in subs}) == 1
    # local schedules replay rank 0's block (slot alignment)
    for r in (1, 2, 3):
        assert (np.asarray(subs[r][0].start_slot)
                == np.asarray(subs[0][0].start_slot)).all()
        assert (np.asarray(subs[r][0].period_slots)
                == np.asarray(subs[0][0].period_slots)).all()


def test_weak_chain_device_matches_host():
    wp = wired_weak_chain(2, n_slots=1500)
    host = run_wired_host(wp)
    dev = run_wired(wp, KEY, replicas=1)
    assert (dev["deliver_slot"][0] == host["deliver_slot"]).all()
    # the cross flow delivered something (causal coupling is real)
    assert dev["delivered"][0, -1] >= 1
