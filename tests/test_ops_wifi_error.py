"""NIST error-rate + interference kernel validation.

Mirrors upstream's wifi-error-rate-models-test.cc strategy: known-SNR
spot checks against the float64 closed-form oracle, monotonicity in SNR,
and frame-level PER with deterministic interference layouts."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudes.ops import wifi_error as WE
from tpudes.ops import interference as I


def test_modes_registry_shape():
    assert len(WE.ALL_MODES) == 20
    m = WE.MODES_BY_NAME["OfdmRate54Mbps"]
    assert m.constellation == 64 and m.rate_class == WE.RATE_3_4
    assert WE.MODES_BY_NAME["OfdmRate6Mbps"].data_rate_bps == 6_000_000


@pytest.mark.parametrize("mode_name", ["OfdmRate6Mbps", "OfdmRate12Mbps", "OfdmRate24Mbps", "OfdmRate54Mbps", "VhtMcs9", "HeMcs11"])
def test_kernel_matches_float64_oracle(mode_name):
    m = WE.MODES_BY_NAME[mode_name]
    for snr_db in [2.0, 8.0, 15.0, 25.0, 35.0]:
        snr = 10 ** (snr_db / 10)
        nbits = 12000.0
        want = WE.chunk_success_rate_py(snr, nbits, m.constellation, m.rate_class)
        got = float(
            WE.mode_chunk_success_rate(
                jnp.float32(snr), jnp.float32(nbits), jnp.int32(m.index)
            )
        )
        assert got == pytest.approx(want, abs=2e-3), (mode_name, snr_db)


def test_success_monotone_in_snr():
    snr = 10 ** (jnp.linspace(-2.0, 35.0, 100) / 10.0)
    succ = np.asarray(
        WE.chunk_success_rate(snr, 8000.0, jnp.float32(64), jnp.int32(WE.RATE_3_4))
    )
    assert np.all(np.diff(succ) >= -1e-6)
    assert succ[0] < 1e-3 and succ[-1] > 0.999


def test_higher_order_modulation_needs_more_snr():
    # at a mid SNR, BPSK1/2 succeeds where 64QAM3/4 fails
    snr = jnp.float32(10 ** (8.0 / 10))
    bpsk = float(WE.chunk_success_rate(snr, 4000.0, jnp.float32(2), jnp.int32(WE.RATE_1_2)))
    qam64 = float(WE.chunk_success_rate(snr, 4000.0, jnp.float32(64), jnp.int32(WE.RATE_3_4)))
    assert bpsk > 0.99 and qam64 < 0.05


def test_vmap_over_modes_and_snr_grid():
    snr = 10 ** (jnp.linspace(0, 30, 16) / 10)
    modes = jnp.arange(len(WE.ALL_MODES), dtype=jnp.int32)
    grid = jax.vmap(
        lambda mi: WE.mode_chunk_success_rate(snr, 8000.0, mi)
    )(modes)
    assert grid.shape == (20, 16)
    assert bool(jnp.all((grid >= 0) & (grid <= 1)))


# --- interference chunking -------------------------------------------------


def _mk_frame(signal_dbm=-60.0, noise_dbm=-93.97, k=4):
    signal_w = 10 ** ((signal_dbm - 30) / 10)
    noise_w = 10 ** ((noise_dbm - 30) / 10)
    return dict(
        signal_w=jnp.float32(signal_w),
        frame_start=jnp.float32(0.0),
        frame_end=jnp.float32(1e-3),
        mode_index=jnp.int32(WE.MODES_BY_NAME["OfdmRate6Mbps"].index),
        data_rate_bps=jnp.float32(6e6),
        noise_w=jnp.float32(noise_w),
        int_power_w=jnp.zeros(k, jnp.float32),
        int_start=jnp.zeros(k, jnp.float32),
        int_end=jnp.zeros(k, jnp.float32),
        int_mask=jnp.zeros(k, jnp.float32),
    )


def test_clean_frame_matches_single_chunk():
    f = _mk_frame()
    got = float(I.frame_success_rate(**f))
    snr = float(f["signal_w"] / f["noise_w"])
    want = WE.chunk_success_rate_py(snr, 6e6 * 1e-3, 2, WE.RATE_1_2)
    assert got == pytest.approx(want, rel=1e-3)


def test_strong_interferer_kills_frame():
    f = _mk_frame()
    f["int_power_w"] = f["int_power_w"].at[0].set(float(f["signal_w"]))  # 0 dB SIR
    f["int_start"] = f["int_start"].at[0].set(0.0)
    f["int_end"] = f["int_end"].at[0].set(1e-3)
    f["int_mask"] = f["int_mask"].at[0].set(1.0)
    got = float(I.frame_success_rate(**f))
    assert got < 1e-3


def test_partial_overlap_product_of_chunks():
    # interferer covers half the frame: success = clean(half) * hit(half)
    f = _mk_frame(signal_dbm=-70.0)
    f["int_power_w"] = f["int_power_w"].at[0].set(float(f["signal_w"]) / 10)
    f["int_start"] = f["int_start"].at[0].set(0.5e-3)
    f["int_end"] = f["int_end"].at[0].set(1e-3)
    f["int_mask"] = f["int_mask"].at[0].set(1.0)
    got = float(I.frame_success_rate(**f))

    snr_clean = float(f["signal_w"] / f["noise_w"])
    snr_hit = float(f["signal_w"] / (f["noise_w"] + f["signal_w"] / 10))
    nbits_half = 6e6 * 0.5e-3
    want = WE.chunk_success_rate_py(snr_clean, nbits_half, 2, WE.RATE_1_2) * \
        WE.chunk_success_rate_py(snr_hit, nbits_half, 2, WE.RATE_1_2)
    assert got == pytest.approx(want, rel=5e-3)


def test_padding_interferers_are_inert():
    f = _mk_frame()
    clean = float(I.frame_success_rate(**f))
    # garbage in padded slots must not change the result
    f["int_power_w"] = jnp.full_like(f["int_power_w"], 1.0)
    f["int_start"] = jnp.full_like(f["int_start"], 0.2e-3)
    f["int_end"] = jnp.full_like(f["int_end"], 0.9e-3)
    # mask stays 0
    got = float(I.frame_success_rate(**f))
    assert got == pytest.approx(clean, rel=1e-6)


def test_batched_frames_jit():
    f = _mk_frame()
    batch = {k: jnp.broadcast_to(v, (32,) + v.shape) for k, v in f.items()}
    out = jax.jit(I.batch_frame_success_rate)(**batch)
    assert out.shape == (32,)
    assert bool(jnp.all(jnp.isfinite(out)))


def test_thermal_noise():
    # -94 dBm for 20 MHz at 7 dB noise figure (the classic 802.11 floor)
    n = I.thermal_noise_w(20e6, noise_figure_db=7.0)
    dbm = 10 * math.log10(n) + 30
    assert dbm == pytest.approx(-93.97, abs=0.1)


def test_nist_qam_ber_reference_values():
    """Upstream NIST closed forms (ADVICE r1 high): 16-QAM BER is
    0.375*erfc(sqrt(snr/10)) — no extra 1/2 factor; same family for
    64/256-QAM.  Checks both the jnp kernel and the f64 oracle."""
    snr = 10.0  # 10 dB linear
    want16 = 0.375 * math.erfc(math.sqrt(snr / 10.0))
    got16 = float(WE.uncoded_ber(jnp.asarray(snr), jnp.asarray(16.0)))
    assert got16 == pytest.approx(want16, rel=1e-5)

    # 64-QAM: upstream Get64QamBer uses z = sqrt(snr/(7*3)) = sqrt(snr/21)
    # (ADVICE r2 medium — NOT the generic sqrt(snr/42)); prefactor 7/24
    want64 = (7.0 / 24.0) * math.erfc(math.sqrt(snr / 21.0))
    got64 = float(WE.uncoded_ber(jnp.asarray(snr), jnp.asarray(64.0)))
    assert got64 == pytest.approx(want64, rel=1e-5)

    # 256-QAM: z = sqrt(snr/60), prefactor 15/64; 1024-QAM: z =
    # sqrt(snr/155), prefactor 31/160
    want256 = (15.0 / 64.0) * math.erfc(math.sqrt(snr / 60.0))
    got256 = float(WE.uncoded_ber(jnp.asarray(snr), jnp.asarray(256.0)))
    assert got256 == pytest.approx(want256, rel=1e-5)
    want1024 = (31.0 / 160.0) * math.erfc(math.sqrt(snr / 155.0))
    got1024 = float(WE.uncoded_ber(jnp.asarray(snr), jnp.asarray(1024.0)))
    assert got1024 == pytest.approx(want1024, rel=1e-5)
    # f64 oracle and jnp kernel agree end-to-end on every QAM order
    for m in (16, 64, 256, 1024):
        oracle = WE.chunk_success_rate_py(snr, 4000.0, m, WE.RATE_3_4)
        kernel = float(
            WE.chunk_success_rate(
                jnp.asarray(snr), jnp.asarray(4000.0), jnp.asarray(float(m)),
                jnp.asarray(WE.RATE_3_4),
            )
        )
        assert kernel == pytest.approx(oracle, rel=2e-3)

    # the f64 oracle must produce the success rate implied by the fixed
    # closed form end-to-end (catches a re-introduced 0.5 factor)
    nbits = 1000.0
    p = min(max(want16, 0.0), 0.5)
    dd = math.sqrt(4.0 * p * (1.0 - p))
    pe = WE.B_FACTOR_TABLE[WE.RATE_1_2] * sum(
        c * dd**e
        for c, e in zip(WE.PE_COEFFS_TABLE[WE.RATE_1_2], WE.PE_EXPONENTS_TABLE[WE.RATE_1_2])
        if c > 0
    )
    want_sr = math.exp(nbits * math.log1p(-min(pe, 1.0 - 1e-12)))
    got_sr = WE.chunk_success_rate_py(snr, nbits, 16, WE.RATE_1_2)
    assert got_sr == pytest.approx(want_sr, rel=1e-9)
    # and the jnp kernel must agree with the oracle
    got_kernel = float(WE.chunk_success_rate(
        jnp.asarray(snr), jnp.asarray(nbits), jnp.asarray(16.0), jnp.asarray(WE.RATE_1_2)))
    assert got_kernel == pytest.approx(got_sr, rel=1e-4)


def test_bpsk_qpsk_ber_reference_values():
    snr = 4.0
    assert float(WE.uncoded_ber(jnp.asarray(snr), jnp.asarray(2.0))) == pytest.approx(
        0.5 * math.erfc(math.sqrt(snr)), rel=1e-5)
    assert float(WE.uncoded_ber(jnp.asarray(snr), jnp.asarray(4.0))) == pytest.approx(
        0.5 * math.erfc(math.sqrt(snr / 2.0)), rel=1e-5)
