"""EPC backhaul: GTP-U over modeled S1-U/S5 links (VERDICT r4 #5).

Written delay-first (the r4 instruction): the end-to-end test pins that
the S1-U link's configured delay/capacity actually shows up in UE
traffic — the property the old zero-delay shortcut could not satisfy —
then the wire test decodes real GTP-U/UDP/IP bytes off the S1-U link.
Upstream analogs: src/lte/test/test-epc-tdd-dl.cc strategy +
epc-gtpu-header.cc round-trip.
"""


import pytest

from tpudes.core import Seconds, Simulator
from tpudes.helper.applications import UdpClientHelper, UdpServerHelper
from tpudes.helper.containers import NodeContainer
from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
from tpudes.helper.point_to_point import PointToPointHelper
from tpudes.models.internet.ipv4 import Ipv4L3Protocol
from tpudes.models.lte import LteHelper
from tpudes.models.lte.epc import EpcHelper
from tpudes.models.mobility import ListPositionAllocator, MobilityHelper, Vector
from tpudes.network.address import Ipv4Address, Ipv4Mask


def _reset():
    from tpudes.core.world import reset_world

    reset_world()


def _build(s1u_delay="0ms", s1u_rate="1Gbps"):
    """One eNB, one UE, one remote host behind a zero-delay backhaul;
    returns (epc, remote_node, ue_node, ue_addr, ue_dev)."""
    lte = LteHelper()
    epc = EpcHelper(s1u_delay=s1u_delay, s1u_rate=s1u_rate)
    remote = NodeContainer()
    remote.Create(1)
    InternetStackHelper().Install(remote)
    p2p = PointToPointHelper()
    p2p.SetDeviceAttribute("DataRate", "10Gbps")
    p2p.SetChannelAttribute("Delay", "0ms")
    backhaul = p2p.Install(remote.Get(0), epc.GetPgwNode())
    ifc = Ipv4AddressHelper("1.0.0.0", "255.0.0.0").Assign(backhaul)
    routing = remote.Get(0).GetObject(Ipv4L3Protocol).GetRoutingProtocol()
    routing.AddNetworkRouteTo(
        Ipv4Address(EpcHelper.UE_NETWORK), Ipv4Mask(EpcHelper.UE_MASK),
        remote.Get(0).GetObject(Ipv4L3Protocol).GetInterfaceForDevice(
            backhaul.Get(0)
        ),
        gateway=ifc.GetAddress(1),
    )

    enbs = NodeContainer()
    enbs.Create(1)
    ues = NodeContainer()
    ues.Create(1)
    ea = ListPositionAllocator()
    ea.Add(Vector(0, 0, 30.0))
    me = MobilityHelper()
    me.SetPositionAllocator(ea)
    me.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    me.Install(enbs)
    ua = ListPositionAllocator()
    ua.Add(Vector(70.0, 0, 1.5))
    mu = MobilityHelper()
    mu.SetPositionAllocator(ua)
    mu.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    mu.Install(ues)
    lte.InstallEnbDevice(enbs)
    ue_devs = lte.InstallUeDevice(ues)
    InternetStackHelper().Install(ues)
    lte.Attach([ue_devs.Get(0)])
    lte.ActivateDataRadioBearer([ue_devs.Get(0)], mode="um")
    (ue_addr,) = epc.AssignUeIpv4Address([ue_devs.Get(0)])
    return epc, remote.Get(0), ues.Get(0), ue_addr, ue_devs.Get(0)


def _dl_first_arrival(s1u_delay):
    _reset()
    epc, remote, ue, ue_addr, _ = _build(s1u_delay=s1u_delay)
    arrivals = []
    server = UdpServerHelper(1000)
    sapps = server.Install(ue)
    sapps.Start(Seconds(0.0))
    sapps.Get(0).TraceConnectWithoutContext(
        "Rx", lambda pkt, *a: arrivals.append(Simulator.Now().GetSeconds())
    )
    dl = UdpClientHelper(ue_addr, 1000)
    dl.SetAttribute("MaxPackets", 3)
    dl.SetAttribute("Interval", Seconds(0.05))
    dl.SetAttribute("PacketSize", 300)
    dl.Install(remote).Start(Seconds(0.1))
    Simulator.Stop(Seconds(0.5))
    Simulator.Run()
    _reset()
    assert len(arrivals) == 3, arrivals
    return arrivals[0] - 0.1


def test_s1u_delay_appears_in_end_to_end_latency():
    """The delay-sensitive oracle (written BEFORE the GTP-U tunnel per
    VERDICT r4 weak #8): a 20 ms S1-U link must shift DL delivery by
    ~20 ms vs a 0 ms one.  The old shortcut fails this by design."""
    base = _dl_first_arrival("0ms")
    delayed = _dl_first_arrival("20ms")
    assert delayed - base == pytest.approx(0.020, abs=0.004), (
        f"S1-U delay invisible: {base*1e3:.2f} -> {delayed*1e3:.2f} ms"
    )


def test_s1u_capacity_bounds_downlink_rate():
    """A 1 Mbps S1-U leg must throttle DL below what the radio allows."""
    _reset()
    epc, remote, ue, ue_addr, _ = _build(s1u_rate="1Mbps")
    rx_bytes = [0]
    server = UdpServerHelper(1000)
    sapps = server.Install(ue)
    sapps.Start(Seconds(0.0))
    sapps.Get(0).TraceConnectWithoutContext(
        "Rx", lambda pkt, *a: rx_bytes.__setitem__(0, rx_bytes[0] + pkt.GetSize())
    )
    dl = UdpClientHelper(ue_addr, 1000)
    dl.SetAttribute("MaxPackets", 0)       # saturate
    dl.SetAttribute("Interval", Seconds(0.001))  # 1000 B/ms ≈ 8 Mbps offered
    dl.SetAttribute("PacketSize", 1000)
    dl.Install(remote).Start(Seconds(0.05))
    Simulator.Stop(Seconds(1.05))
    Simulator.Run()
    _reset()
    mbps = rx_bytes[0] * 8 / 1.0 / 1e6
    assert 0.5 < mbps <= 1.1, f"S1-U bottleneck not enforced: {mbps:.2f} Mbps"


def test_gtpu_frames_decode_on_the_s1u_wire():
    """Sniff the SGW-side S1-U device: outer IPv4/UDP:2152 + GTP-U with
    the UE's TEID, inner IPv4 destined to the UE."""
    from tpudes.models.internet.ipv4 import Ipv4Header
    from tpudes.models.internet.udp import UdpHeader
    from tpudes.models.lte.epc import GTPU_PORT, GtpuHeader

    _reset()
    epc, remote, ue, ue_addr, ue_dev = _build(s1u_delay="1ms")
    frames = []
    # the SGW's S1-U device towards the (single) eNB
    sgw_dev = epc.s1u_sgw_devices[0]
    sgw_dev.TraceConnectWithoutContext(
        "PhyTxEnd", lambda pkt, *a: frames.append(pkt.ToBytes())
    )
    server = UdpServerHelper(1000)
    sapps = server.Install(ue)
    sapps.Start(Seconds(0.0))
    dl = UdpClientHelper(ue_addr, 1000)
    dl.SetAttribute("MaxPackets", 2)
    dl.SetAttribute("Interval", Seconds(0.05))
    dl.SetAttribute("PacketSize", 300)
    dl.Install(remote).Start(Seconds(0.1))
    Simulator.Stop(Seconds(0.4))
    Simulator.Run()
    _reset()
    assert frames, "no frames crossed the S1-U link"
    # decode the first data frame: outer IP / UDP / GTP-U / inner IP
    decoded = 0
    for raw in frames:
        if raw[:2] == b"\x00\x21":  # PPP: IP protocol field
            raw = raw[2:]
        outer, n1 = Ipv4Header.Deserialize(raw)
        if outer.protocol != 17:
            continue
        udp, n2 = UdpHeader.Deserialize(raw[n1:])
        if udp.destination_port != GTPU_PORT:
            continue
        gtpu, n3 = GtpuHeader.Deserialize(raw[n1 + n2:])
        inner, _ = Ipv4Header.Deserialize(raw[n1 + n2 + n3:])
        assert gtpu.teid == epc.teid_for_ue(ue_addr)
        assert inner.destination == Ipv4Address(ue_addr)
        decoded += 1
    assert decoded >= 2, "GTP-U data frames must decode"


def test_gtpu_header_roundtrip():
    from tpudes.models.lte.epc import GtpuHeader

    h = GtpuHeader(teid=0xDEADBEEF, payload_size=321)
    raw = h.Serialize()
    assert len(raw) == h.GetSerializedSize() == 8
    h2, n = GtpuHeader.Deserialize(raw)
    assert n == 8 and h2.teid == 0xDEADBEEF and h2.payload_size == 321


def test_uplink_through_sgw_and_pgw():
    """UE → eNB → GTP-U S1-U → SGW → GTP-U S5 → PGW → remote host."""
    _reset()
    epc, remote, ue, ue_addr, _ = _build(s1u_delay="5ms")
    ul_server = UdpServerHelper(2000)
    ul_apps = ul_server.Install(remote)
    ul_apps.Start(Seconds(0.0))
    remote_addr = remote.GetObject(Ipv4L3Protocol).GetAddress(1).GetLocal()
    ul = UdpClientHelper(remote_addr, 2000)
    ul.SetAttribute("MaxPackets", 5)
    ul.SetAttribute("Interval", Seconds(0.02))
    ul.SetAttribute("PacketSize", 150)
    ul.Install(ue).Start(Seconds(0.05))
    Simulator.Stop(Seconds(0.6))
    Simulator.Run()
    _reset()
    assert ul_apps.Get(0).received == 5
