"""WiFi slice tests — mirrors upstream's wifi test suite strategy
(SURVEY.md §4): PHY duration math vs closed form, end-to-end BSS
topologies asserting on delivery counters, deterministic loss via
geometry, DCF contention resolution."""

import math

import pytest

from tpudes.core import Seconds, Simulator
from tpudes.helper.containers import NodeContainer
from tpudes.models.mobility import (
    ListPositionAllocator,
    MobilityHelper,
    Vector,
)
from tpudes.models.wifi import (
    WifiHelper,
    WifiMacHelper,
    YansWifiChannelHelper,
    YansWifiPhyHelper,
    ppdu_duration_s,
)
from tpudes.network.packet import Packet
from tpudes.ops.wifi_error import MODES_BY_NAME


def _wifi_nodes(n, positions, mac_setup, rate_manager=("tpudes::ConstantRateWifiManager", {})):
    """Build n wifi nodes at given positions; mac_setup(i, mac_helper)."""
    nodes = NodeContainer()
    nodes.Create(n)
    mobility = MobilityHelper()
    alloc = ListPositionAllocator()
    for p in positions:
        alloc.Add(Vector(*p))
    mobility.SetPositionAllocator(alloc)
    mobility.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    mobility.Install(nodes)

    channel = YansWifiChannelHelper.Default().Create()
    phy = YansWifiPhyHelper()
    phy.SetChannel(channel)
    wifi = WifiHelper()
    wifi.SetRemoteStationManager(rate_manager[0], **rate_manager[1])

    devices = []
    for i, node in enumerate(nodes):
        mac = WifiMacHelper()
        mac_setup(i, mac)
        dev_container = wifi.Install(phy, mac, [node])
        devices.append(dev_container.Get(0))
    return nodes, devices


def test_ppdu_duration_closed_form():
    # 1000-byte frame at 6 Mbps: 20µs + ceil((16+8000+6)/24)*4µs
    mode = MODES_BY_NAME["OfdmRate6Mbps"]
    d = ppdu_duration_s(1000, mode)
    assert d == pytest.approx(20e-6 + math.ceil(8022 / 24) * 4e-6)
    # 54 Mbps: NDBPS=216
    d54 = ppdu_duration_s(1000, MODES_BY_NAME["OfdmRate54Mbps"])
    assert d54 == pytest.approx(20e-6 + math.ceil(8022 / 216) * 4e-6)
    assert d54 < d


def test_adhoc_unicast_delivery_with_ack():
    nodes, devices = _wifi_nodes(
        2, [(0, 0, 0), (10, 0, 0)], lambda i, m: m.SetType("tpudes::AdhocWifiMac")
    )
    got = []
    devices[1].SetReceiveCallback(lambda dev, pkt, proto, sender: got.append(pkt.GetSize()) or True)
    Simulator.Schedule(
        Seconds(1.0), devices[0].Send, Packet(500), devices[1].GetAddress(), 0x0800
    )
    Simulator.Stop(Seconds(2))
    Simulator.Run()
    assert got == [500]  # LLC stripped before delivery


def test_out_of_range_not_delivered():
    # LogDistance exponent 3: at 10 km rx ≈ -150 dBm, far below sensitivity
    nodes, devices = _wifi_nodes(
        2, [(0, 0, 0), (10000, 0, 0)], lambda i, m: m.SetType("tpudes::AdhocWifiMac")
    )
    got = []
    drops = []
    devices[1].SetReceiveCallback(lambda dev, pkt, proto, sender: got.append(1) or True)
    devices[1].GetPhy().TraceConnectWithoutContext(
        "PhyRxDrop", lambda pkt, reason: drops.append(reason)
    )
    Simulator.Schedule(
        Seconds(1.0), devices[0].Send, Packet(500), devices[1].GetAddress(), 0x0800
    )
    Simulator.Stop(Seconds(2))
    Simulator.Run()
    assert got == []
    assert "below-sensitivity" in drops


def test_infra_association_and_data():
    def setup(i, mac):
        if i == 0:
            mac.SetType("tpudes::ApWifiMac")
        else:
            mac.SetType("tpudes::StaWifiMac")

    nodes, devices = _wifi_nodes(3, [(0, 0, 0), (5, 0, 0), (0, 5, 0)], setup)
    ap_mac = devices[0].GetMac()
    sta1 = devices[1].GetMac()
    sta2 = devices[2].GetMac()
    got = []
    devices[0].SetReceiveCallback(lambda dev, pkt, proto, sender: got.append(pkt.GetSize()) or True)

    # STA enqueues before association: must be held then sent
    Simulator.Schedule(
        Seconds(0.01), devices[1].Send, Packet(200), devices[0].GetAddress(), 0x0800
    )
    Simulator.Stop(Seconds(1))
    Simulator.Run()
    assert sta1.IsAssociated() and sta2.IsAssociated()
    assert ap_mac.IsAssociated(sta1.GetAddress())
    assert got == [200]


def test_intra_bss_relay():
    """STA1 → AP → STA2 relaying through the DS."""

    def setup(i, mac):
        mac.SetType("tpudes::ApWifiMac" if i == 0 else "tpudes::StaWifiMac")

    nodes, devices = _wifi_nodes(3, [(0, 0, 0), (5, 0, 0), (0, 5, 0)], setup)
    got = []
    devices[2].SetReceiveCallback(lambda dev, pkt, proto, sender: got.append(pkt.GetSize()) or True)
    # give association time, then send STA1 → STA2 (addr3 routing via AP)
    Simulator.Schedule(
        Seconds(0.5), devices[1].Send, Packet(300), devices[2].GetAddress(), 0x0800
    )
    Simulator.Stop(Seconds(1.5))
    Simulator.Run()
    assert got == [300]


def test_dcf_contention_both_deliver():
    """Two simultaneous transmitters to a third node: DCF backoff must
    eventually deliver both (retries resolve the collision)."""
    nodes, devices = _wifi_nodes(
        3, [(0, 0, 0), (4, 0, 0), (2, 2, 0)], lambda i, m: m.SetType("tpudes::AdhocWifiMac")
    )
    got = []
    devices[2].SetReceiveCallback(lambda dev, pkt, proto, sender: got.append(str(sender)) or True)
    # exactly simultaneous sends — same tick
    for i in (0, 1):
        Simulator.Schedule(
            Seconds(1.0), devices[i].Send, Packet(400), devices[2].GetAddress(), 0x0800
        )
    Simulator.Stop(Seconds(2))
    Simulator.Run()
    assert sorted(got) == sorted([str(devices[0].GetAddress()), str(devices[1].GetAddress())])


def test_broadcast_no_ack_single_copy():
    nodes, devices = _wifi_nodes(
        3, [(0, 0, 0), (5, 0, 0), (0, 5, 0)], lambda i, m: m.SetType("tpudes::AdhocWifiMac")
    )
    got = [[], []]
    from tpudes.network.address import Mac48Address

    devices[1].SetReceiveCallback(lambda dev, pkt, proto, sender: got[0].append(1) or True)
    devices[2].SetReceiveCallback(lambda dev, pkt, proto, sender: got[1].append(1) or True)
    Simulator.Schedule(
        Seconds(1.0), devices[0].Send, Packet(100), Mac48Address.GetBroadcast(), 0x0800
    )
    Simulator.Stop(Seconds(2))
    Simulator.Run()
    assert got == [[1], [1]]


def test_retry_and_dedup_under_forced_loss():
    """Force every first data rx to fail via interference from a third
    node? Simpler: check the dup cache — deliver once even when the ack
    is lost and the sender retries."""
    nodes, devices = _wifi_nodes(
        2, [(0, 0, 0), (10, 0, 0)], lambda i, m: m.SetType("tpudes::AdhocWifiMac")
    )
    rx_mac = devices[1].GetMac()
    got = []
    devices[1].SetReceiveCallback(lambda dev, pkt, proto, sender: got.append(1) or True)
    # sabotage the first ack: drop it at the sender PHY by forcing the
    # receiver's first ack tx to be preempted — instead simply simulate a
    # retry by sending the same (seq, retry) frame twice via MAC internals
    from tpudes.models.wifi.mac import WifiMacHeader, WifiMacType

    header = WifiMacHeader(
        WifiMacType.DATA,
        addr1=devices[1].GetAddress(),
        addr2=devices[0].GetAddress(),
        addr3=devices[1].GetAddress(),
        seq=7,
    )
    from tpudes.network.packet import LlcSnapHeader

    def send_copy(retry):
        p = Packet(50)
        p.AddHeader(LlcSnapHeader(0x0800))
        h = WifiMacHeader(
            WifiMacType.DATA,
            addr1=header.addr1,
            addr2=header.addr2,
            addr3=header.addr3,
            seq=7,
            retry=retry,
        )
        frame = p.Copy()
        frame.AddHeader(h)
        devices[0].GetPhy().Send(frame, MODES_BY_NAME["OfdmRate6Mbps"])

    # original then a spaced retry: the second must hit the dup cache
    Simulator.Schedule(Seconds(1.0), send_copy, False)
    Simulator.Schedule(Seconds(1.1), send_copy, True)
    Simulator.Stop(Seconds(2))
    Simulator.Run()
    assert got == [1]


def test_arf_rate_climbs():
    nodes, devices = _wifi_nodes(
        2,
        [(0, 0, 0), (5, 0, 0)],
        lambda i, m: m.SetType("tpudes::AdhocWifiMac"),
        rate_manager=("tpudes::ArfWifiManager", {}),
    )
    got = []
    devices[1].SetReceiveCallback(lambda dev, pkt, proto, sender: got.append(1) or True)
    for k in range(25):
        Simulator.Schedule(
            Seconds(0.1 + 0.01 * k), devices[0].Send, Packet(100), devices[1].GetAddress(), 0x0800
        )
    Simulator.Stop(Seconds(2))
    Simulator.Run()
    assert len(got) == 25
    manager = devices[0].GetMac()._station_manager
    st = manager._st(devices[1].GetAddress())
    assert st["rate"] >= 2  # climbed at least two steps after 25 acks


def test_wifi_udp_echo_end_to_end():
    """The first.cc flow over WiFi adhoc + ARP: UDP echo client/server."""
    from tpudes.helper.applications import UdpEchoClientHelper, UdpEchoServerHelper
    from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
    from tpudes.helper.containers import NetDeviceContainer

    def setup(i, mac):
        mac.SetType("tpudes::AdhocWifiMac")

    nodes, devices = _wifi_nodes(2, [(0, 0, 0), (20, 0, 0)], setup)
    stack = InternetStackHelper()
    stack.Install(nodes)
    address = Ipv4AddressHelper()
    address.SetBase("10.1.3.0", "255.255.255.0")
    container = NetDeviceContainer()
    for d in devices:
        container.Add(d)
    interfaces = address.Assign(container)

    server = UdpEchoServerHelper(9)
    server_apps = server.Install(nodes.Get(1))
    server_apps.Start(Seconds(0.5))
    server_apps.Stop(Seconds(5.0))

    client = UdpEchoClientHelper(interfaces.GetAddress(1), 9)
    client.SetAttribute("MaxPackets", 2)
    client.SetAttribute("Interval", Seconds(0.5))
    client.SetAttribute("PacketSize", 256)
    client_apps = client.Install(nodes.Get(0))
    client_apps.Start(Seconds(1.0))
    client_apps.Stop(Seconds(5.0))

    server_rx = []
    client_rx = []
    server_apps.Get(0).TraceConnectWithoutContext("Rx", lambda pkt, *a: server_rx.append(pkt.GetSize()))
    client_apps.Get(0).TraceConnectWithoutContext("Rx", lambda pkt, *a: client_rx.append(pkt.GetSize()))

    Simulator.Stop(Seconds(6))
    Simulator.Run()
    assert server_rx == [256, 256]
    assert client_rx == [256, 256]


def test_sta_disassociate_fires_deassoc_and_rejoins():
    """StaWifiMac.Disassociate (the promoted DeAssoc REG001 finding):
    the trace fires with the AP address, the STA drops out of the BSS,
    and a later beacon re-associates it."""

    def setup(i, mac):
        mac.SetType("tpudes::ApWifiMac" if i == 0 else "tpudes::StaWifiMac")

    nodes, devices = _wifi_nodes(2, [(0, 0, 0), (5, 0, 0)], setup)
    ap_mac = devices[0].GetMac()
    sta = devices[1].GetMac()
    gone = []
    sta.TraceConnectWithoutContext("DeAssoc", lambda ap: gone.append(str(ap)))

    def kick():
        assert sta.IsAssociated()
        sta.Disassociate()
        assert not sta.IsAssociated()
        assert sta.GetBssid() is None

    Simulator.Schedule(Seconds(0.5), kick)
    Simulator.Stop(Seconds(1.5))
    Simulator.Run()
    assert gone == [str(ap_mac.GetAddress())]
    # the next beacons re-ran the scan -> assoc handshake
    assert sta.IsAssociated()


def test_stale_assoc_resp_after_disassociate_is_ignored():
    """A stale DCF-retransmitted ASSOC_RESP arriving after
    Disassociate() cleared the state must NOT silently re-associate the
    STA (there is no outstanding request) — the pre-fix handler would
    flip `_associated` with `_ap=None`, flushing data frames addressed
    to no AP.  A later beacon re-runs the scan→request→response
    handshake and rejoins cleanly."""
    def setup(i, mac):
        mac.SetType("tpudes::ApWifiMac" if i == 0 else "tpudes::StaWifiMac")

    nodes, devices = _wifi_nodes(2, [(0, 0, 0), (5, 0, 0)], setup)
    ap_mac = devices[0].GetMac()
    sta = devices[1].GetMac()

    def race():
        from tpudes.models.wifi.mac import WifiMacHeader, WifiMacType

        sta.Disassociate()
        assert sta.GetBssid() is None
        stale = WifiMacHeader(
            WifiMacType.ASSOC_RESP,
            addr1=sta.GetAddress(),
            addr2=ap_mac.GetAddress(),
            addr3=ap_mac.GetAddress(),
            seq=99,
        )
        sta.Receive(None, stale)
        assert not sta.IsAssociated()
        assert sta.GetBssid() is None

    Simulator.Schedule(Seconds(0.5), race)
    Simulator.Stop(Seconds(1.5))
    Simulator.Run()
    # the next beacons re-ran the genuine handshake
    assert sta.IsAssociated()
    assert str(sta.GetBssid()) == str(ap_mac.GetAddress())
