"""lte_tti_sinr memory-shape regression + the ISSUE-6 mixed-precision
error budget.

Memory shape: the dense (E, U, RB) intermediate was materialized
because the serving-signal ``take_along_axis`` was a SECOND consumer of
it — the fix gathers the serving term directly and contracts the total
over E with one einsum.

Exactness contract (why not plain ``assert_array_equal`` on the whole
kernel): XLA fuses the old form's broadcast-multiply into its reduce
using FMA, so the old total's bits are a property of that one fusion —
no O(U·RB) reformulation (einsum, matmul, sequential or pairwise
re-accumulation; all were measured) reproduces them.  What this file
pins instead:

- the serving-signal term is BIT-exact vs the old gather (same single
  multiply, same rounding);
- the einsum total stays within a 4-ULP envelope of the old form and
  is NO FURTHER from the float64 ground truth than the old form was —
  the drift is re-rounding, not error;
- the compiled program's temp allocation is strictly below the dense
  (E, U, RB) tensor the old form paid.
"""

import jax
import jax.numpy as jnp
import numpy as np

from tpudes.parallel.kernels import lte_tti_sinr


def _dense_reference(tx_psd_w, gain, serving, noise_psd_w):
    """The pre-fix form: materializes the (E, U, RB) seen tensor."""
    seen = tx_psd_w[:, None, :] * gain[:, :, None]
    total = jnp.sum(seen, axis=0)
    sig = jnp.take_along_axis(seen, serving[None, :, None], axis=0)[0]
    return sig / (total - sig + noise_psd_w)


def _scenario(e=7, u=210, rb=100, seed=0):
    rng = np.random.default_rng(seed)
    tx_psd = jnp.asarray(
        rng.uniform(1e-18, 1e-15, size=(e, rb)), jnp.float32
    )
    gain = jnp.asarray(
        rng.uniform(1e-12, 1e-7, size=(e, u)), jnp.float32
    )
    serving = jnp.asarray(rng.integers(0, e, size=(u,)), jnp.int32)
    return tx_psd, gain, serving, 1e-20


def test_serving_signal_term_bit_exact():
    tx_psd, gain, serving, _ = _scenario()

    def new_sig(tx_psd, gain, serving):
        u = jnp.arange(gain.shape[1])
        return tx_psd[serving] * gain[serving, u][:, None]

    def old_sig(tx_psd, gain, serving):
        seen = tx_psd[:, None, :] * gain[:, :, None]
        return jnp.take_along_axis(seen, serving[None, :, None], axis=0)[0]

    np.testing.assert_array_equal(
        np.asarray(jax.jit(new_sig)(tx_psd, gain, serving)),
        np.asarray(jax.jit(old_sig)(tx_psd, gain, serving)),
    )


def _ulp_distance(a: np.ndarray, b: np.ndarray) -> int:
    """Max distance in representable-float steps between f32 arrays."""
    ia = np.asarray(a, np.float32).view(np.int32).astype(np.int64)
    ib = np.asarray(b, np.float32).view(np.int32).astype(np.int64)
    return int(np.abs(ia - ib).max())


def test_total_within_ulp_envelope_and_f64_accuracy():
    for seed, shape in ((0, (7, 210, 100)), (1, (2, 3, 5)), (2, (3, 8, 25))):
        tx_psd, gain, serving, noise = _scenario(*shape, seed=seed)
        new = np.asarray(
            jax.jit(lte_tti_sinr, static_argnums=3)(
                tx_psd, gain, serving, noise
            )
        )
        old = np.asarray(
            jax.jit(_dense_reference, static_argnums=3)(
                tx_psd, gain, serving, noise
            )
        )
        assert _ulp_distance(new, old) <= 4, (
            f"seed {seed}: einsum drifted {_ulp_distance(new, old)} ULP "
            "from the dense form — that is re-rounding no longer, "
            "something changed semantically"
        )
        # float64 oracle: same-order accuracy (the old form's fused
        # FMA skips one rounding, so it can be marginally closer — a
        # 2x envelope distinguishes re-rounding from a real error)
        tx64, g64 = np.asarray(tx_psd, np.float64), np.asarray(gain, np.float64)
        sv = np.asarray(serving)
        seen = tx64[:, None, :] * g64[:, :, None]
        total = seen.sum(axis=0)
        sig = seen[sv, np.arange(g64.shape[1])]
        oracle = sig / (total - sig + noise)
        err_new = np.abs(new - oracle).max()
        err_old = np.abs(old - oracle).max()
        assert err_new <= err_old * 2.0 + 1e-12, (
            f"seed {seed}: new max err {err_new} vs old {err_old}"
        )


def test_peak_memory_has_no_dense_intermediate():
    """The compiled HLO must not allocate an (E, U, RB) buffer: the
    biggest live temp should be O(U·RB)."""
    tx_psd, gain, serving, noise = _scenario()
    e, u = gain.shape
    rb = tx_psd.shape[1]
    compiled = (
        jax.jit(lte_tti_sinr, static_argnums=3)
        .lower(tx_psd, gain, serving, noise)
        .compile()
    )
    analysis = compiled.memory_analysis()
    if analysis is None:  # pragma: no cover - backend-dependent
        return
    dense_bytes = 4 * e * u * rb
    assert analysis.temp_size_in_bytes < dense_bytes, (
        f"temp allocation {analysis.temp_size_in_bytes} B suggests the "
        f"(E,U,RB) intermediate ({dense_bytes} B) is back"
    )


# --- ISSUE-6: the bf16/f32 mixed-precision error budget -----------------
#
# Policy (tpudes/parallel/kernels_pallas.py): PRODUCTS and ratios at
# bf16, every REDUCTION/accumulator and transcendental at f32.  bf16
# keeps f32's 8-bit exponent (the 1e-18 W/Hz PSDs and 1e-12 gains stay
# representable — f16 would flush them to zero) and pays 8 mantissa
# bits, so the budget below is a handful of 2^-8 relative steps.

BF16_EPS = 2.0 ** -8  # half-ulp at 1.0


def test_lte_tti_sinr_bf16_relative_budget():
    """The mixed-precision SINR stays within a few bf16 ulps of the f32
    kernel — products rounded, einsum still f32-accumulating."""
    for seed, shape in ((0, (7, 210, 100)), (3, (3, 24, 25))):
        tx_psd, gain, serving, noise = _scenario(*shape, seed=seed)
        f32 = np.asarray(
            jax.jit(lte_tti_sinr, static_argnums=3)(
                tx_psd, gain, serving, noise
            )
        )
        bf16 = np.asarray(
            jax.jit(
                lambda a, b, c: lte_tti_sinr(
                    a, b, c, noise, dtype=jnp.bfloat16
                )
            )(tx_psd, gain, serving)
        )
        rel = np.abs(bf16 - f32) / np.maximum(np.abs(f32), 1e-30)
        assert rel.max() <= 8 * BF16_EPS, (
            f"seed {seed}: bf16 SINR drifted {rel.max():.2e} rel — "
            "beyond the 8-ulp product-rounding budget"
        )


def test_cqi_bf16_within_one_index():
    """bf16 SINR rounding can flip a CQI only AT an efficiency
    boundary, and only by one index."""
    from tpudes.ops.lte import cqi_from_sinr

    sinr = jnp.asarray(
        np.logspace(-2, 3, 4001, dtype=np.float32)
    )
    f32 = np.asarray(cqi_from_sinr(sinr))
    bf16 = np.asarray(cqi_from_sinr(sinr, dtype=jnp.bfloat16))
    assert np.abs(bf16.astype(int) - f32.astype(int)).max() <= 1
    # and only a small fraction of the sweep sits on a boundary
    assert (bf16 != f32).mean() < 0.05


def test_mi_bf16_budget_and_f32_reduction():
    """Per-RB MI at bf16: |Δmi| bounded by the bf16 half-ulp scaled
    through the log2 slope (the normalized MI lives in [0, 1])."""
    from tpudes.ops.lte import mi_per_rb

    sinr = jnp.asarray(np.logspace(-2, 3, 2001, dtype=np.float32))
    qm = jnp.full_like(sinr, 6.0)
    f32 = np.asarray(mi_per_rb(sinr, qm))
    bf16 = np.asarray(mi_per_rb(sinr, qm, dtype=jnp.bfloat16))
    assert bf16.dtype == np.float32  # the f32-reduction half of the policy
    # d(mi)/d(s) = 1/(qm ln2 (Γ+s)) ≤ ~0.6/Γ per unit s; a relative
    # bf16 step δ·s moves mi by at most δ/(qm ln2) ≈ δ/4.16 — budget 2δ
    assert np.abs(bf16 - f32).max() <= 2 * BF16_EPS


def test_tb_bler_ecr_bf16_budget():
    """BLER at bf16: the waterfall argument z moves by at most the MI
    budget over sigma; pin the resulting BLER band around the 10 %
    design point and exactness far from the cliff."""
    from tpudes.ops.lte import tb_bler_ecr

    ecr = jnp.full((101,), 0.5, jnp.float32)
    tb = jnp.full((101,), 5000.0, jnp.float32)
    mi = jnp.asarray(np.linspace(0.3, 0.7, 101, dtype=np.float32))
    f32 = np.asarray(tb_bler_ecr(mi, ecr, tb))
    bf16 = np.asarray(tb_bler_ecr(mi, ecr, tb, dtype=jnp.bfloat16))
    sigma = 1.4 / np.sqrt(5000.0)
    # max slope of the Gaussian CDF is 1/(sigma*sqrt(2pi))
    budget = 2 * BF16_EPS * 0.7 / (sigma * np.sqrt(2 * np.pi))
    assert np.abs(bf16 - f32).max() <= budget
    # far from the waterfall both saturate (BLER≈1 at MI far below the
    # code rate, ≈0 far above — well past any bf16 perturbation)
    np.testing.assert_allclose(f32[:10], 1.0, atol=1e-12)
    np.testing.assert_allclose(bf16[:10], 1.0, atol=1e-12)
    assert f32[-10:].max() < 1e-12 and bf16[-10:].max() < 1e-12


def test_dtype_none_and_f32_identical():
    """dtype=jnp.float32 must be the EXACT legacy arithmetic — the
    casts are no-ops, not a third rounding mode."""
    from tpudes.ops.lte import cqi_from_sinr, mi_per_rb

    tx_psd, gain, serving, noise = _scenario(3, 24, 25, seed=5)
    np.testing.assert_array_equal(
        np.asarray(lte_tti_sinr(tx_psd, gain, serving, noise)),
        np.asarray(
            lte_tti_sinr(tx_psd, gain, serving, noise, dtype=jnp.float32)
        ),
    )
    sinr = jnp.asarray(np.logspace(-2, 2, 501, dtype=np.float32))
    np.testing.assert_array_equal(
        np.asarray(cqi_from_sinr(sinr)),
        np.asarray(cqi_from_sinr(sinr, dtype=jnp.float32)),
    )
    np.testing.assert_array_equal(
        np.asarray(mi_per_rb(sinr, jnp.full_like(sinr, 4.0))),
        np.asarray(
            mi_per_rb(sinr, jnp.full_like(sinr, 4.0), dtype=jnp.float32)
        ),
    )
