"""lte_tti_sinr memory-shape regression: the dense (E, U, RB)
intermediate was materialized because the serving-signal
``take_along_axis`` was a SECOND consumer of it — the fix gathers the
serving term directly and contracts the total over E with one einsum.

Exactness contract (why not plain ``assert_array_equal`` on the whole
kernel): XLA fuses the old form's broadcast-multiply into its reduce
using FMA, so the old total's bits are a property of that one fusion —
no O(U·RB) reformulation (einsum, matmul, sequential or pairwise
re-accumulation; all were measured) reproduces them.  What this file
pins instead:

- the serving-signal term is BIT-exact vs the old gather (same single
  multiply, same rounding);
- the einsum total stays within a 4-ULP envelope of the old form and
  is NO FURTHER from the float64 ground truth than the old form was —
  the drift is re-rounding, not error;
- the compiled program's temp allocation is strictly below the dense
  (E, U, RB) tensor the old form paid.
"""

import jax
import jax.numpy as jnp
import numpy as np

from tpudes.parallel.kernels import lte_tti_sinr


def _dense_reference(tx_psd_w, gain, serving, noise_psd_w):
    """The pre-fix form: materializes the (E, U, RB) seen tensor."""
    seen = tx_psd_w[:, None, :] * gain[:, :, None]
    total = jnp.sum(seen, axis=0)
    sig = jnp.take_along_axis(seen, serving[None, :, None], axis=0)[0]
    return sig / (total - sig + noise_psd_w)


def _scenario(e=7, u=210, rb=100, seed=0):
    rng = np.random.default_rng(seed)
    tx_psd = jnp.asarray(
        rng.uniform(1e-18, 1e-15, size=(e, rb)), jnp.float32
    )
    gain = jnp.asarray(
        rng.uniform(1e-12, 1e-7, size=(e, u)), jnp.float32
    )
    serving = jnp.asarray(rng.integers(0, e, size=(u,)), jnp.int32)
    return tx_psd, gain, serving, 1e-20


def test_serving_signal_term_bit_exact():
    tx_psd, gain, serving, _ = _scenario()

    def new_sig(tx_psd, gain, serving):
        u = jnp.arange(gain.shape[1])
        return tx_psd[serving] * gain[serving, u][:, None]

    def old_sig(tx_psd, gain, serving):
        seen = tx_psd[:, None, :] * gain[:, :, None]
        return jnp.take_along_axis(seen, serving[None, :, None], axis=0)[0]

    np.testing.assert_array_equal(
        np.asarray(jax.jit(new_sig)(tx_psd, gain, serving)),
        np.asarray(jax.jit(old_sig)(tx_psd, gain, serving)),
    )


def _ulp_distance(a: np.ndarray, b: np.ndarray) -> int:
    """Max distance in representable-float steps between f32 arrays."""
    ia = np.asarray(a, np.float32).view(np.int32).astype(np.int64)
    ib = np.asarray(b, np.float32).view(np.int32).astype(np.int64)
    return int(np.abs(ia - ib).max())


def test_total_within_ulp_envelope_and_f64_accuracy():
    for seed, shape in ((0, (7, 210, 100)), (1, (2, 3, 5)), (2, (3, 8, 25))):
        tx_psd, gain, serving, noise = _scenario(*shape, seed=seed)
        new = np.asarray(
            jax.jit(lte_tti_sinr, static_argnums=3)(
                tx_psd, gain, serving, noise
            )
        )
        old = np.asarray(
            jax.jit(_dense_reference, static_argnums=3)(
                tx_psd, gain, serving, noise
            )
        )
        assert _ulp_distance(new, old) <= 4, (
            f"seed {seed}: einsum drifted {_ulp_distance(new, old)} ULP "
            "from the dense form — that is re-rounding no longer, "
            "something changed semantically"
        )
        # float64 oracle: same-order accuracy (the old form's fused
        # FMA skips one rounding, so it can be marginally closer — a
        # 2x envelope distinguishes re-rounding from a real error)
        tx64, g64 = np.asarray(tx_psd, np.float64), np.asarray(gain, np.float64)
        sv = np.asarray(serving)
        seen = tx64[:, None, :] * g64[:, :, None]
        total = seen.sum(axis=0)
        sig = seen[sv, np.arange(g64.shape[1])]
        oracle = sig / (total - sig + noise)
        err_new = np.abs(new - oracle).max()
        err_old = np.abs(old - oracle).max()
        assert err_new <= err_old * 2.0 + 1e-12, (
            f"seed {seed}: new max err {err_new} vs old {err_old}"
        )


def test_peak_memory_has_no_dense_intermediate():
    """The compiled HLO must not allocate an (E, U, RB) buffer: the
    biggest live temp should be O(U·RB)."""
    tx_psd, gain, serving, noise = _scenario()
    e, u = gain.shape
    rb = tx_psd.shape[1]
    compiled = (
        jax.jit(lte_tti_sinr, static_argnums=3)
        .lower(tx_psd, gain, serving, noise)
        .compile()
    )
    analysis = compiled.memory_analysis()
    if analysis is None:  # pragma: no cover - backend-dependent
        return
    dense_bytes = 4 * e * u * rb
    assert analysis.temp_size_in_bytes < dense_bytes, (
        f"temp allocation {analysis.temp_size_in_bytes} B suggests the "
        f"(E,U,RB) intermediate ({dense_bytes} B) is back"
    )
