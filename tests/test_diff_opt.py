"""Optimization on top of the diff engines (ISSUE-15): calibration
recovers planted parameters, the descent loop is one compile / one
launch, the ES fallback optimizes a BSS design objective in one
megabatched launch per generation, and GradTelemetry passes its
schema gate."""

import dataclasses
import json

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tpudes.diff import (  # noqa: E402
    Surrogacy,
    calibrate_as_flows,
    calibrate_lte,
    descend,
    es_search,
    fd_gradient,
)
from tpudes.parallel.lte_sm import LteSmProgram  # noqa: E402
from tpudes.parallel.programs import (  # noqa: E402
    toy_as_program,
    toy_bss_program,
)

KEY = jax.random.PRNGKey(17)


@pytest.fixture(autouse=True)
def _reset_grad_telemetry():
    from tpudes.obs.grad import GradTelemetry

    yield
    GradTelemetry.reset()


def _lte_scene(n_ue=6, pos_seed=0):
    E = 2
    serving = (np.arange(n_ue) % E).astype(np.int32)
    rng = np.random.default_rng(pos_seed)
    enb_pos = np.array([[0.0, 0.0, 30.0], [600.0, 0.0, 30.0]], np.float32)
    ue_pos = (
        enb_pos[serving]
        + np.c_[rng.uniform(-200, 200, n_ue),
                rng.uniform(-200, 200, n_ue),
                np.full(n_ue, -28.5)]
    ).astype(np.float32)
    prog = LteSmProgram(
        gain=np.full((E, n_ue), 1e-12),
        serving=serving,
        tx_power_dbm=np.full((E,), 43.0),
        noise_psd=10.0**0.9 * 1.380649e-23 * 290.0,
        n_rb=25,
        n_ttis=400,
        scheduler="pf",
        enb_pos=enb_pos,
        pathloss=("log_distance", 3.0, 1.0, 46.67),
    )
    return prog, ue_pos


class TestCalibration:
    def test_as_recovers_planted_flow_rates(self):
        """Plant per-flow rates, synthesize observed goodput KPIs from
        the diff runner, descend from the program's nominal rates —
        the fitted rates land within 10 % of the plant (stochastic
        replica minibatches; the loss must also collapse)."""
        from tpudes.parallel.as_flows import (
            _as_replica_draws,
            build_as_diff,
        )
        from tpudes.parallel.runtime import bucket_replicas

        # modest jitter: the recovery precision floor is the replica
        # minibatches' sample-mean noise on E[exp(jitter·z)], not the
        # optimizer — keep the noise floor under the asserted 10 %
        prog = dataclasses.replace(
            toy_as_program(n_nodes=24, n_flows=3),
            surrogate=Surrogacy(ste=False),
            rate_jitter=0.1,
        )
        planted = np.array([2.2e5, 0.9e5, 1.5e5], np.float32)
        r_pad = bucket_replicas(8, None)
        diff_run = jax.jit(build_as_diff(prog, r_pad))
        # observed KPI: replica-mean per-flow goodput at the plant,
        # averaged over several minibatch draws (what a measurement
        # campaign would see)
        gp = np.mean(
            [
                np.asarray(
                    diff_run(
                        _as_replica_draws(
                            prog, jax.random.fold_in(KEY, i), r_pad
                        ),
                        jnp.float32(1.0),
                        jnp.asarray(planted),
                        jnp.asarray(prog.rate_bps, jnp.float32),
                    )["goodput_bps"]
                ).mean(axis=0)
                for i in range(6)
            ],
            axis=0,
        )
        res = calibrate_as_flows(
            prog, KEY, gp, wrt=("flow_bps",), steps=220, lr=0.06,
            replicas=8,
        )
        rel = np.abs(res.params["flow_bps"] - planted) / planted
        assert (rel < 0.10).all(), (res.params["flow_bps"], planted)
        assert res.loss[-1] < res.loss[0] / 20
        assert res.loss.shape == (220,)
        assert np.isfinite(res.grad_norm).all()

    def test_lte_recovers_planted_exponent_adam_and_lbfgs(self):
        """Plant a propagation exponent, observe per-UE CQIs, recover
        by descent — Adam within 2 %, L-BFGS-lite essentially exact on
        the deterministic objective."""
        from tpudes.diff.lte_grad import build_lte_diff, lte_default_params

        prog, ue_pos = _lte_scene()
        kpi = jax.jit(build_lte_diff(prog, Surrogacy()))
        p = lte_default_params(prog, {"ue_pos": ue_pos})
        p["ploss"] = jnp.asarray([3.45, 1.0, 46.67], jnp.float32)
        observed = np.asarray(kpi(p)["cqi"])
        adam = calibrate_lte(
            prog, KEY, observed, wrt=("ploss",), at={"ue_pos": ue_pos},
            steps=250, lr=0.02, loss="cqi_mse", opt="adam",
        )
        assert abs(adam.params["ploss"][0] - 3.45) < 0.07
        lbfgs = calibrate_lte(
            prog, KEY, observed, wrt=("ploss",), at={"ue_pos": ue_pos},
            steps=80, lr=0.5, loss="cqi_mse", opt="lbfgs",
        )
        assert abs(lbfgs.params["ploss"][0] - 3.45) < 1e-3
        assert lbfgs.loss[-1] < 1e-8

    def test_descent_loop_is_one_launch_one_compile(self):
        """The whole descent is ONE compiled scan: one device launch,
        and a repeat calibration of the same study family re-uses the
        cached program (0 fresh compiles)."""
        from tpudes.diff.lte_grad import build_lte_diff, lte_default_params
        from tpudes.obs.device import CompileTelemetry
        from tpudes.parallel.runtime import RUNTIME

        prog, ue_pos = _lte_scene()
        kpi = jax.jit(build_lte_diff(prog, Surrogacy()))
        p = lte_default_params(prog, {"ue_pos": ue_pos})
        observed = np.asarray(kpi(p)["cqi"])
        calibrate_lte(
            prog, KEY, observed, wrt=("ploss",), at={"ue_pos": ue_pos},
            steps=40, loss="cqi_mse",
        )  # warm
        l0 = RUNTIME.launches("diff_lte")
        c0 = CompileTelemetry.compiles("diff_lte")
        calibrate_lte(
            prog, KEY, observed, wrt=("ploss",), at={"ue_pos": ue_pos},
            steps=40, loss="cqi_mse",
        )
        assert RUNTIME.launches("diff_lte") - l0 == 1
        assert CompileTelemetry.compiles("diff_lte") - c0 == 0

    def test_descend_optimizers_on_a_quadratic(self):
        """Both optimizers minimize a plain quadratic (the sanity
        anchor independent of any engine)."""
        target = jnp.asarray([1.5, -2.0, 0.25], jnp.float32)

        def vg(params, kt, ops):
            del kt, ops

            def f(params):
                d = params["x"] - target
                return jnp.sum(d * d)

            return jax.value_and_grad(f)(params)

        for opt, steps, lr in (("adam", 300, 0.05), ("lbfgs", 30, 1.0)):
            res = descend(
                vg, {"x": jnp.zeros(3)}, steps=steps, lr=lr, key=KEY,
                opt=opt,
            )
            np.testing.assert_allclose(
                res.params["x"], np.asarray(target), atol=5e-2,
            )
            assert res.loss[-1] < 1e-3, opt

    def test_unknown_optimizer_rejected(self):
        with pytest.raises(ValueError, match="adam"):
            descend(
                lambda p, k, o: (0.0, p), {"x": jnp.zeros(2)},
                steps=1, lr=0.1, key=KEY, opt="sgd",
            )

    def test_multi_start_recovers_a_wide_exponent_gap(self):
        """Verify-drill regression: a 0.6-exponent gap lands in a
        local minimum of the quantized-CQI landscape from a single
        far-off start, but multi-start over ``init=`` (same cached
        descent program — one compile, K launches) recovers the plant
        exactly, and no start ever produces a non-finite iterate (the
        domain clamps + step cap)."""
        from tpudes.diff.lte_grad import build_lte_diff, lte_default_params
        from tpudes.obs.device import CompileTelemetry

        # the multi-modality (and which basin each start falls into)
        # depends on the UE geometry; this draw is the verified one —
        # the invariants under test are finiteness, program reuse, and
        # best-of-starts recovery, not any single start's basin
        prog, ue_pos = _lte_scene(pos_seed=4)
        kpi = jax.jit(build_lte_diff(prog, Surrogacy()))
        p = lte_default_params(prog, {"ue_pos": ue_pos})
        p["ploss"] = jnp.asarray([3.6, 1.0, 46.67], jnp.float32)
        observed = np.asarray(kpi(p)["cqi"])
        best = None
        starts = (2.5, 3.0, 3.5, 4.0)
        first = None
        for e0 in starts:
            res = calibrate_lte(
                prog, KEY, observed, wrt=("ploss",),
                at={"ue_pos": ue_pos},
                init={"ploss": np.array([e0, 1.0, 46.67])},
                steps=120, lr=0.5, loss="cqi_mse", opt="lbfgs",
            )
            assert np.isfinite(res.loss).all(), e0
            if first is None:
                first = CompileTelemetry.compiles("diff_lte")
            if best is None or res.final_loss < best.final_loss:
                best = res
        # starts 2..K reuse the first start's compiled descent program
        assert CompileTelemetry.compiles("diff_lte") == first
        assert abs(best.params["ploss"][0] - 3.6) < 1e-3
        assert best.final_loss < 1e-8

    def test_cached_descent_refits_new_observations(self):
        """Regression (review): the cached descent program must fit
        THIS call's observations — targets and non-optimized operands
        ride traced, so a second calibration of the same study family
        with different observed KPIs lands on a different fit."""
        from tpudes.diff.lte_grad import build_lte_diff, lte_default_params

        prog, ue_pos = _lte_scene()
        kpi = jax.jit(build_lte_diff(prog, Surrogacy()))

        def observe(exponent):
            p = lte_default_params(prog, {"ue_pos": ue_pos})
            p["ploss"] = jnp.asarray(
                [exponent, 1.0, 46.67], jnp.float32
            )
            return np.asarray(kpi(p)["cqi"])

        fit = {}
        for exp in (3.45, 2.75):
            fit[exp] = calibrate_lte(
                prog, KEY, observe(exp), wrt=("ploss",),
                at={"ue_pos": ue_pos}, steps=80, lr=0.5,
                loss="cqi_mse", opt="lbfgs",
            ).params["ploss"][0]
        assert abs(fit[3.45] - 3.45) < 1e-3
        assert abs(fit[2.75] - 2.75) < 1e-3


class TestDesignSearch:
    def test_es_improves_bss_objective_one_launch_per_generation(self):
        """The ES fallback: each generation's antithetic population
        rides ONE traffic_sweep launch; the decoded-echo objective
        improves over generations (the ISSUE acceptance row)."""
        from tpudes.diff import bss_interval_design
        from tpudes.parallel.runtime import RUNTIME
        from tpudes.traffic import TrafficProgram

        prog = toy_bss_program(n_sta=3, sim_end_us=40_000)
        tp = TrafficProgram.cbr(
            np.asarray(prog.start_us), np.asarray(prog.interval_us)
        )
        prog = dataclasses.replace(prog, traffic=tp)
        l0 = RUNTIME.launches("bss")
        res = bss_interval_design(
            prog, KEY, replicas=2, generations=3, pop=2
        )
        assert RUNTIME.launches("bss") - l0 == res.launches == 3
        assert res.mean_fitness[-1] > res.mean_fitness[0]
        assert res.theta.shape == (3,)

    def test_es_and_fd_on_an_analytic_bowl(self):
        """es_search climbs and fd_gradient matches the analytic
        gradient of a concave bowl — the megabatch contract without
        any engine in the loop."""
        opt = np.array([0.7, -0.3])

        def evaluate(thetas):
            d = thetas - opt[None, :]
            return -np.sum(d * d, axis=1)

        res = es_search(
            evaluate, np.zeros(2), key=KEY, generations=40, pop=8,
            sigma=0.1, lr=0.5,
        )
        assert np.abs(res.theta - opt).max() < 0.15
        g = fd_gradient(evaluate, np.zeros(2), eps=1e-4)
        np.testing.assert_allclose(g, 2 * opt, rtol=1e-3, atol=1e-4)

    def test_bss_design_requires_traffic_shape_class(self):
        from tpudes.diff import bss_interval_design

        prog = toy_bss_program(n_sta=2)
        with pytest.raises(ValueError, match="traffic"):
            bss_interval_design(prog, KEY, replicas=1)


class TestGradTelemetry:
    def test_records_and_schema_gate(self, tmp_path):
        from tpudes.diff import grad_as_flows
        from tpudes.obs.grad import GradTelemetry, validate_grad_metrics

        GradTelemetry.reset()
        prog = dataclasses.replace(
            toy_as_program(n_nodes=16, n_flows=2),
            surrogate=Surrogacy(),
        )
        grad_as_flows(prog, KEY, 2, loss="neg_goodput")
        grad_as_flows(
            prog, KEY, 2, loss="neg_goodput", rate_scale=[0.5, 1.0]
        )
        snap = GradTelemetry.snapshot()
        assert validate_grad_metrics(snap) == []
        e = snap["engines"]["as_flows"]
        assert e["launches"] == 2
        assert e["batched_points"] == 3
        assert len(e["loss_ring"]) == 2
        assert e["nonfinite"] == 0
        # the CLI gate accepts the dump (the CI artifact path)
        path = tmp_path / "grad.json"
        path.write_text(json.dumps(snap))
        from tpudes.obs.__main__ import main

        assert main(["--grad", str(path)]) == 0

    def test_descent_history_joins_the_rings(self):
        from tpudes.obs.grad import GradTelemetry

        GradTelemetry.reset()
        GradTelemetry.record_descent(
            "diff_lte", [1.0, 0.5, 0.25], [3.0, 2.0, 1.0]
        )
        e = GradTelemetry.engine("diff_lte")
        assert e["steps"] == 3 and e["launches"] == 1
        assert e["loss_ring"] == [1.0, 0.5, 0.25]

    def test_schema_rejects_malformed(self):
        from tpudes.obs.grad import validate_grad_metrics

        assert validate_grad_metrics([]) != []
        assert validate_grad_metrics({"version": 1}) != []
        bad = {
            "version": 1,
            "engines": {
                "x": {
                    "launches": -1, "steps": 0, "batched_points": 0,
                    "nonfinite": 0, "last_loss": None,
                    "loss_ring": [], "grad_norm_ring": ["a"],
                }
            },
        }
        problems = validate_grad_metrics(bad)
        assert any("negative" in p for p in problems)
        assert any("non-number" in p for p in problems)

    def test_nonfinite_canary(self):
        from tpudes.obs.grad import GradTelemetry

        GradTelemetry.reset()
        GradTelemetry.record(
            "diff_as", loss=float("nan"), grad_norm=1.0
        )
        assert GradTelemetry.engine("diff_as")["nonfinite"] == 1
