"""Nix-vector routing tests — upstream src/nix-vector-routing/test
strategy: correct delivery over multi-hop p2p paths, per-packet source
vectors consumed hop by hop, and the scale contract: routing a handful
of flows on a big static graph costs one BFS per flow, not a Dijkstra
per source (VERDICT r4 #8's 'faster than global SPF repair' pin)."""

import time


from tpudes.core import Seconds, Simulator
from tpudes.helper.applications import UdpEchoClientHelper, UdpEchoServerHelper
from tpudes.helper.containers import NodeContainer
from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
from tpudes.helper.point_to_point import PointToPointHelper
from tpudes.models.internet.ipv4 import Ipv4L3Protocol
from tpudes.models.internet.nix_vector import (
    Ipv4NixVectorHelper,
    Ipv4NixVectorRouting,
    NixVector,
)


def _reset():
    from tpudes.core.world import reset_world

    reset_world()


def _p2p_chain(n=4, routing=None):
    nodes = NodeContainer()
    nodes.Create(n)
    stack = InternetStackHelper()
    stack.SetRoutingHelper(routing or Ipv4NixVectorHelper())
    stack.Install(nodes)
    p2p = PointToPointHelper()
    p2p.SetDeviceAttribute("DataRate", "10Mbps")
    p2p.SetChannelAttribute("Delay", "1ms")
    a = Ipv4AddressHelper("10.1.0.0", "255.255.255.252")
    ifcs = []
    for i in range(n - 1):
        d = p2p.Install(nodes.Get(i), nodes.Get(i + 1))
        ifcs.append(a.Assign(d))
        a.NewNetwork()
    return nodes, ifcs


def test_multihop_delivery_over_chain():
    _reset()
    nodes, ifcs = _p2p_chain(5)
    server = UdpEchoServerHelper(9)
    sapps = server.Install(nodes.Get(4))
    sapps.Start(Seconds(0.0))
    client = UdpEchoClientHelper(ifcs[-1].GetAddress(1), 9)
    client.SetAttribute("MaxPackets", 3)
    client.SetAttribute("Interval", Seconds(0.1))
    capps = client.Install(nodes.Get(0))
    capps.Start(Seconds(0.5))
    Simulator.Stop(Seconds(2.0))
    Simulator.Run()
    assert sapps.Get(0).received == 3
    assert capps.Get(0).received == 3
    _reset()


def test_packets_carry_and_consume_the_vector():
    _reset()
    nodes, ifcs = _p2p_chain(4)
    seen = []
    nodes.Get(3).GetObject(Ipv4L3Protocol).TraceConnectWithoutContext(
        "LocalDeliver",
        lambda h, p, i: seen.append(p.PeekPacketTag(NixVector))
        if h.protocol == 17
        else None,
    )
    server = UdpEchoServerHelper(9)
    server.Install(nodes.Get(3)).Start(Seconds(0.0))
    client = UdpEchoClientHelper(ifcs[-1].GetAddress(1), 9)
    client.SetAttribute("MaxPackets", 1)
    client.Install(nodes.Get(0)).Start(Seconds(0.5))
    Simulator.Stop(Seconds(2.0))
    Simulator.Run()
    assert seen and seen[0] is not None
    # a 3-hop path, fully consumed on arrival
    assert len(seen[0].hops) == 3 and seen[0].index == 3
    _reset()


def test_origin_caches_one_bfs_per_destination():
    _reset()
    nodes, ifcs = _p2p_chain(4)
    r0 = nodes.Get(0).GetObject(Ipv4L3Protocol).GetRoutingProtocol()
    assert isinstance(r0, Ipv4NixVectorRouting)
    server = UdpEchoServerHelper(9)
    server.Install(nodes.Get(3)).Start(Seconds(0.0))
    client = UdpEchoClientHelper(ifcs[-1].GetAddress(1), 9)
    client.SetAttribute("MaxPackets", 5)
    client.SetAttribute("Interval", Seconds(0.05))
    client.Install(nodes.Get(0)).Start(Seconds(0.5))
    Simulator.Stop(Seconds(2.0))
    Simulator.Run()
    assert len(r0._cache) == 1  # one vector serves the whole flow
    # intermediate nodes keep NO routing state at all
    r1 = nodes.Get(1).GetObject(Ipv4L3Protocol).GetRoutingProtocol()
    assert len(r1._cache) == 0
    _reset()


def test_scales_better_than_global_spf_on_big_graph():
    """The VERDICT pin: on a 2000-node graph, nix-vector route setup for
    a few flows (one BFS each) beats global SPF's per-source Dijkstra
    repair by a wide margin."""
    from tpudes.helper.topology import BriteTopologyHelper
    from tpudes.models.internet.global_routing import (
        GlobalRouteManager,
        Ipv4GlobalRoutingHelper,
    )

    N, FLOWS = 2000, 5

    def build(routing_helper):
        _reset()
        topo = BriteTopologyHelper(model="BA", n=N, m=2, seed=7)
        stack = InternetStackHelper()
        stack.SetRoutingHelper(routing_helper)
        nodes = topo.BuildTopology(stack)
        return nodes

    # --- global SPF: Dijkstra per SOURCE actually routing ---------------
    nodes = build(Ipv4GlobalRoutingHelper())
    Ipv4GlobalRoutingHelper.PopulateRoutingTables()
    mgr = GlobalRouteManager.Get()
    mgr.Build()
    dsts = [nodes.Get(N - 1 - i) for i in range(FLOWS)]
    dst_addrs = [
        d.GetObject(Ipv4L3Protocol).GetAddress(1).GetLocal() for d in dsts
    ]
    t0 = time.perf_counter()
    for i in range(FLOWS):
        mgr.NextHop(nodes.Get(i).GetId(), dst_addrs[i])
    spf_wall = time.perf_counter() - t0

    # --- nix-vector: one BFS per flow -----------------------------------
    nodes = build(Ipv4NixVectorHelper())
    mgr = GlobalRouteManager.Get()
    mgr.Build()
    dsts = [nodes.Get(N - 1 - i) for i in range(FLOWS)]
    dst_addrs = [
        d.GetObject(Ipv4L3Protocol).GetAddress(1).GetLocal() for d in dsts
    ]
    t0 = time.perf_counter()
    for i in range(FLOWS):
        r = nodes.Get(i).GetObject(Ipv4L3Protocol).GetRoutingProtocol()
        assert r._bfs_path(dst_addrs[i])
    nix_wall = time.perf_counter() - t0
    _reset()

    # BFS (unweighted) must beat the heap-based Dijkstra clearly; 2x is
    # a conservative floor (typically 3-6x) that stays robust under CI
    # noise
    assert nix_wall < spf_wall / 2.0, (
        f"nix {nix_wall*1e3:.1f} ms vs spf {spf_wall*1e3:.1f} ms"
    )
