"""ISSUE 13 gates: deterministic chaos injection + replay.

- Schedules are pure functions of their seed (same seed → same
  events), events fire at exact per-(site, member) ordinals, at most
  once, and invalid site/kind combinations are refused at build.
- Wire injection produces deterministic WireFormatError shapes (never
  silent garbage reaching the unpickler).
- The canonical replay drill recovers every study bit-equal and its
  failure/recovery counters are identical across runs of one seed —
  the ``python -m tpudes.chaos --replay`` contract.
- (slow) A real SIGKILL of a routed member mid-coalesced-batch: the
  fleet requeues onto survivors and every study completes bit-equal.
"""

import json

import pytest

import tpudes.chaos as chaos
from tpudes.chaos import ChaosEvent, ChaosSchedule, canonical_schedule
from tpudes.obs.serving import ServingTelemetry, validate_serving_metrics
from tpudes.parallel.mpi import WireFormatError, pack_frame, unpack_frame
from tpudes.parallel.runtime import RUNTIME


@pytest.fixture(autouse=True)
def _fresh():
    chaos.reset()
    ServingTelemetry.reset()
    yield
    chaos.reset()
    ServingTelemetry.reset()
    RUNTIME.clear()


# --- schedule semantics ----------------------------------------------------


def test_from_seed_is_pure_in_the_seed():
    a = ChaosSchedule.from_seed(42, members=2)
    b = ChaosSchedule.from_seed(42, members=2)
    assert a.events == b.events
    assert ChaosSchedule.from_seed(43, members=2).events != a.events
    c = canonical_schedule(7, members=2)
    d = canonical_schedule(7, members=2)
    assert c.events == d.events


def test_event_fires_at_exact_ordinal_once():
    s = ChaosSchedule([
        ChaosEvent("launch_error", "local_launch", nth=3),
    ])
    assert s.fire("local_launch") is None
    assert s.fire("local_launch") is None
    ev = s.fire("local_launch")
    assert ev is not None and ev.kind == "launch_error"
    assert s.fire("local_launch") is None, "events are single-shot"
    assert s.injected == {"launch_error": 1}
    assert s.remaining() == 0


def test_member_ordinals_are_per_member():
    s = ChaosSchedule([
        ChaosEvent("kill_member", "member_study", nth=2, member=2),
    ])
    # member 1's visits never advance member 2's ordinal
    assert s.fire("member_study", member=1) is None
    assert s.fire("member_study", member=1) is None
    assert s.fire("member_study", member=2) is None
    ev = s.fire("member_study", member=2)
    assert ev is not None and ev.member == 2


def test_checkpoint_kill_tag_counts_per_engine():
    s = ChaosSchedule([
        ChaosEvent("checkpoint_kill", "checkpoint_save", nth=1,
                   param="lte_sm"),
    ])
    # another engine's saves never consume the lte ordinal
    assert s.fire("checkpoint_save", tag="dumbbell") is None
    ev = s.fire("checkpoint_save", tag="lte_sm")
    assert ev is not None and ev.param == "lte_sm"


def test_invalid_events_refused():
    with pytest.raises(ValueError, match="site"):
        ChaosEvent("launch_error", "nowhere", nth=1)
    with pytest.raises(ValueError, match="cannot fire"):
        ChaosEvent("kill_member", "local_launch", nth=1)
    with pytest.raises(ValueError, match="nth"):
        ChaosEvent("launch_error", "local_launch", nth=0)


def test_env_arming_and_reset(monkeypatch):
    monkeypatch.setenv("TPUDES_CHAOS", "9")
    monkeypatch.setenv("TPUDES_CHAOS_MEMBERS", "2")
    chaos.reset()
    s = chaos.armed()
    assert s is not None
    assert s.events == canonical_schedule(9, 2).events
    monkeypatch.delenv("TPUDES_CHAOS")
    chaos.reset()
    assert chaos.armed() is None


# --- wire-layer injection --------------------------------------------------


def test_filter_frame_truncation_raises_wire_error():
    chaos.arm(ChaosSchedule([
        ChaosEvent("wire_truncate", "router_recv", nth=1),
    ]))
    blob = chaos.filter_frame("router_recv", pack_frame(("result", [1])))
    with pytest.raises(WireFormatError):
        unpack_frame(blob)


def test_filter_frame_corruption_raises_wire_error():
    chaos.arm(ChaosSchedule([
        ChaosEvent("wire_corrupt", "router_send", nth=1),
    ]))
    blob = chaos.filter_frame("router_send", pack_frame(("study", {})))
    with pytest.raises(WireFormatError, match="version"):
        unpack_frame(blob)


def test_unarmed_filter_is_identity():
    blob = pack_frame(("result", [1, 2]))
    assert chaos.filter_frame("router_recv", blob) == blob
    assert unpack_frame(blob) == ("result", [1, 2])


# --- the canonical replay drill -------------------------------------------


def test_local_drill_recovers_and_is_deterministic():
    from tpudes.chaos.scenario import run_local_scenario

    r1 = run_local_scenario(7, n_studies=4)
    r2 = run_local_scenario(7, n_studies=4)
    assert r1["completed"] == 4 and r1["equal"]
    f1, f2 = r1["telemetry"]["failures"], r2["telemetry"]["failures"]
    assert f1 == f2, "same seed must reproduce the same recovery counters"
    assert f1["injected_failures"] >= 1
    assert f1["requeued_studies"] >= 1
    assert validate_serving_metrics(r1["telemetry"]) == []


def test_chaos_cli_replay_and_determinism_check(tmp_path):
    from tpudes.chaos.__main__ import main as chaos_main
    from tpudes.obs.__main__ import main as obs_main

    out = tmp_path / "chaos-telemetry.json"
    rc = chaos_main([
        "--replay", "3", "--procs", "1", "--studies", "4",
        "--check", "--quiet", "--out", str(out),
    ])
    assert rc == 0
    doc = json.loads(out.read_text())
    assert doc["failures"]["injected_failures"] >= 1
    assert obs_main(["--serving", str(out)]) == 0


# --- the real thing: SIGKILL a spawned member mid-coalesced-batch ---------


@pytest.mark.slow
def test_member_sigkill_mid_batch_recovers_bit_equal():
    """ISSUE 13 acceptance: kill -9 of a ProcessRouter member while its
    block of a coalesced batch is in flight — every affected study
    completes via requeue, results BIT-equal to a failure-free run
    (the drill compares each against a solo launch)."""
    from tpudes.chaos.scenario import run_scenario

    outs = run_scenario(7, procs=3)
    r0 = outs[0]
    assert r0["completed"] == 6
    assert r0["equal"], "recovered results diverged from solo launches"
    assert r0["members_lost"] >= 1
    assert r0["requeued"] >= 1
    assert r0["excluded"], "the killed member must be excluded"
    # the survivor member (if not the victim) either served or exited
    # cleanly; the killed member's slot is None
    assert any(o is None for o in outs[1:]) or r0["members_lost"] >= 1
    assert validate_serving_metrics(r0["telemetry"]) == []
