"""CSMA tests — upstream src/csma/test strategy: bus delivery,
carrier-sense serialization, broadcast/ARP, promiscuous filtering."""

from tpudes.core import Seconds, Simulator
from tpudes.helper.applications import UdpEchoClientHelper, UdpEchoServerHelper
from tpudes.helper.containers import NodeContainer
from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
from tpudes.models.csma import CsmaChannel, CsmaHelper, CsmaNetDevice, EthernetHeader


def _lan(n=4, rate="100Mbps"):
    nodes = NodeContainer()
    nodes.Create(n)
    csma = CsmaHelper()
    csma.SetChannelAttribute("DataRate", rate)
    csma.SetChannelAttribute("Delay", Seconds(6.56e-6))
    devices = csma.Install(nodes)
    InternetStackHelper().Install(nodes)
    ifc = Ipv4AddressHelper("10.1.2.0", "255.255.255.0").Assign(devices)
    return nodes, devices, ifc


def test_echo_across_the_bus_with_arp():
    nodes, devices, ifc = _lan(4)
    server = UdpEchoServerHelper(9)
    sapps = server.Install(nodes.Get(3))
    sapps.Start(Seconds(0.0))
    got = [0]
    sapps.Get(0).TraceConnectWithoutContext(
        "Rx", lambda *a: got.__setitem__(0, got[0] + 1)
    )
    cli_rx = [0]
    for i in range(3):
        c = UdpEchoClientHelper(ifc.GetAddress(3), 9)
        c.SetAttribute("MaxPackets", 5)
        c.SetAttribute("Interval", Seconds(0.01))
        c.SetAttribute("PacketSize", 300)
        apps = c.Install(nodes.Get(i))
        apps.Start(Seconds(0.1 + 0.0001 * i))
        apps.Get(0).TraceConnectWithoutContext(
            "Rx", lambda *a: cli_rx.__setitem__(0, cli_rx[0] + 1)
        )
    Simulator.Stop(Seconds(1.0))
    Simulator.Run()
    assert got[0] == 15 and cli_rx[0] == 15


def test_channel_admits_one_transmitter():
    """Carrier sense: simultaneous sends serialize via backoff; all
    frames still deliver."""
    nodes, devices, ifc = _lan(3, rate="1Mbps")
    backoffs = [0]
    for i in range(3):
        devices.Get(i).TraceConnectWithoutContext(
            "MacTxBackoff", lambda *a: backoffs.__setitem__(0, backoffs[0] + 1)
        )
    server = UdpEchoServerHelper(9)
    sapps = server.Install(nodes.Get(2))
    sapps.Start(Seconds(0.0))
    got = [0]
    sapps.Get(0).TraceConnectWithoutContext(
        "Rx", lambda *a: got.__setitem__(0, got[0] + 1)
    )
    for i in range(2):  # two stations fire at the same instant
        c = UdpEchoClientHelper(ifc.GetAddress(2), 9)
        c.SetAttribute("MaxPackets", 10)
        c.SetAttribute("Interval", Seconds(0.005))
        c.SetAttribute("PacketSize", 1000)
        c.Install(nodes.Get(i)).Start(Seconds(0.1))
    Simulator.Stop(Seconds(1.0))
    Simulator.Run()
    assert got[0] == 20, "carrier sense must serialize, not lose"
    assert backoffs[0] > 0, "same-instant senders must back off"


def test_unicast_filtered_promiscuous_sees_all():
    nodes, devices, ifc = _lan(3)
    other_host = [0]
    promisc = [0]

    # node 2 is a bystander for 0→1 traffic
    devices.Get(2).SetPromiscReceiveCallback(
        lambda *a: other_host.__setitem__(0, other_host[0] + 1) or True
    )
    devices.Get(2).TraceConnectWithoutContext(
        "PromiscSniffer", lambda p: promisc.__setitem__(0, promisc[0] + 1)
    )
    rx1 = [0]
    server = UdpEchoServerHelper(9)
    sapps = server.Install(nodes.Get(1))
    sapps.Start(Seconds(0.0))
    sapps.Get(0).TraceConnectWithoutContext(
        "Rx", lambda *a: rx1.__setitem__(0, rx1[0] + 1)
    )
    c = UdpEchoClientHelper(ifc.GetAddress(1), 9)
    c.SetAttribute("MaxPackets", 4)
    c.SetAttribute("Interval", Seconds(0.01))
    c.Install(nodes.Get(0)).Start(Seconds(0.1))
    Simulator.Stop(Seconds(1.0))
    Simulator.Run()
    assert rx1[0] == 4
    # bystander's promiscuous tap saw the unicast exchange
    assert promisc[0] >= 8


def test_ethernet_header_round_trip():
    from tpudes.network.address import Mac48Address

    h = EthernetHeader(Mac48Address(7), Mac48Address(9), 0x0806)
    data = h.Serialize()
    assert len(data) == 14
    h2 = EthernetHeader.Deserialize(data)
    assert h2.destination == Mac48Address(7)
    assert h2.source == Mac48Address(9)
    assert h2.ether_type == 0x0806


def test_shared_channel_install():
    nodes = NodeContainer()
    nodes.Create(2)
    more = NodeContainer()
    more.Create(2)
    csma = CsmaHelper()
    ch = CsmaChannel()
    d1 = csma.Install(nodes, ch)
    d2 = csma.Install(more, ch)
    assert ch.GetNDevices() == 4
    assert all(isinstance(d, CsmaNetDevice) for d in list(d1) + list(d2))

def test_arp_request_jitter_staggers_and_still_resolves():
    """Promoted REG001 finding: RequestJitter now actually jitters the
    broadcast request through the seeded stream — resolution (and the
    echo ride on top of it) still completes, and the request leaves
    later than the un-jittered one."""
    from tpudes.models.internet.arp import ArpHeader, ArpL3Protocol

    def run(jitter_s):
        from tpudes.core.world import reset_world

        reset_world()
        nodes, devices, ifc = _lan(2)
        arp = nodes.Get(0).GetObject(ArpL3Protocol)
        arp.SetAttribute("RequestJitter", jitter_s)
        req_ticks = []

        orig = devices.Get(0).Send

        def spy(pkt, dst, proto):
            if proto == ArpL3Protocol.PROT_NUMBER:
                p = pkt.Copy()
                if p.RemoveHeader(ArpHeader).op == ArpHeader.REQUEST:
                    req_ticks.append(Simulator.NowTicks())
            return orig(pkt, dst, proto)

        devices.Get(0).Send = spy
        server = UdpEchoServerHelper(9)
        sapps = server.Install(nodes.Get(1))
        sapps.Start(Seconds(0.0))
        got = [0]
        sapps.Get(0).TraceConnectWithoutContext(
            "Rx", lambda *a: got.__setitem__(0, got[0] + 1)
        )
        c = UdpEchoClientHelper(ifc.GetAddress(1), 9)
        c.SetAttribute("MaxPackets", 2)
        c.SetAttribute("Interval", Seconds(0.05))
        apps = c.Install(nodes.Get(0))
        apps.Start(Seconds(0.1))
        Simulator.Stop(Seconds(0.5))
        Simulator.Run()
        reset_world()
        return got[0], req_ticks

    got0, ticks0 = run(0.0)
    got1, ticks1 = run(0.02)
    assert got0 == 2 and got1 == 2      # resolution completes either way
    assert ticks0 and ticks1
    base = int(0.1 * 1e9)
    assert ticks0[0] == base            # un-jittered: at the app start
    assert base < ticks1[0] <= base + int(0.02 * 1e9)
