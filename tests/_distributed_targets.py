"""Rank entry points for the distributed PDES tests.

Module-level functions (LaunchDistributed uses the spawn start method,
which pickles targets by reference) — deliberately jax-free so child
processes never touch the accelerator runtime.
"""

from __future__ import annotations


def run_chain(rank: int, size: int, n_packets: int = 5, interval_s: float = 0.1,
              engine: str = "tpudes::DistributedSimulatorImpl"):
    """4-node p2p chain n0-n1-n2-n3, echo client on n0 → server on n3.

    Partitioning (size=2): n0,n1 → rank 0; n2,n3 → rank 1 (the middle
    link crosses).  With size=1 (or MPI disabled) this is the sequential
    oracle.  Returns a dict with ``server_rx``/``client_rx`` lists of
    (sim_ticks, packet_size) in arrival order, plus ``events`` and
    ``windows`` counts.
    """
    from tpudes.core import Seconds, Simulator
    from tpudes.core.global_value import GlobalValue
    from tpudes.core.world import reset_world
    from tpudes.helper.applications import (
        UdpEchoClientHelper,
        UdpEchoServerHelper,
    )
    from tpudes.helper.containers import NodeContainer
    from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
    from tpudes.helper.point_to_point import PointToPointHelper
    from tpudes.models.internet.global_routing import Ipv4GlobalRoutingHelper
    from tpudes.parallel.mpi import MpiInterface

    reset_world()
    distributed = MpiInterface.IsEnabled() and MpiInterface.GetSize() > 1
    if distributed:
        GlobalValue.Bind("SimulatorImplementationType", engine)

    left = NodeContainer()
    left.Create(2, system_id=0)
    right = NodeContainer()
    right.Create(2, system_id=1 if distributed else 0)
    n = [left.Get(0), left.Get(1), right.Get(0), right.Get(1)]

    stack = InternetStackHelper()
    stack.SetRoutingHelper(Ipv4GlobalRoutingHelper())
    stack.Install(left)
    stack.Install(right)

    p2p = PointToPointHelper()
    p2p.SetDeviceAttribute("DataRate", "5Mbps")
    p2p.SetChannelAttribute("Delay", "2ms")
    addr = Ipv4AddressHelper("10.1.0.0", "255.255.255.0")
    last_ifc = None
    for i in range(3):
        devs = p2p.Install(n[i], n[i + 1])
        last_ifc = addr.Assign(devs)
        addr.NewNetwork()
    Ipv4GlobalRoutingHelper.PopulateRoutingTables()

    my_rank = MpiInterface.GetSystemId() if distributed else 0
    server_rx: list = []
    client_rx: list = []
    if n[3].GetSystemId() == my_rank or not distributed:
        server = UdpEchoServerHelper(9)
        sapps = server.Install(n[3])
        sapps.Start(Seconds(0.0))
        sapps.Get(0).TraceConnectWithoutContext(
            "Rx",
            lambda pkt, *a: server_rx.append(
                (Simulator.NowTicks(), pkt.GetSize())
            ),
        )
    if n[0].GetSystemId() == my_rank or not distributed:
        client = UdpEchoClientHelper(last_ifc.GetAddress(1), 9)
        client.SetAttribute("MaxPackets", n_packets)
        client.SetAttribute("Interval", Seconds(interval_s))
        client.SetAttribute("PacketSize", 333)
        capps = client.Install(n[0])
        capps.Start(Seconds(0.05))
        capps.Get(0).TraceConnectWithoutContext(
            "Rx",
            lambda pkt, *a: client_rx.append(
                (Simulator.NowTicks(), pkt.GetSize())
            ),
        )

    Simulator.Stop(Seconds(2.0))
    Simulator.Run()
    events = Simulator.GetEventCount()
    impl = Simulator.GetImpl()
    windows = getattr(impl, "windows_run", 0)
    nulls = getattr(impl, "null_messages_sent", 0)
    Simulator.Destroy()
    return dict(
        server_rx=server_rx, client_rx=client_rx,
        events=events, windows=windows, nulls=nulls,
    )


def run_asymmetric_stop(rank: int, size: int):
    """Rank 1's server calls Simulator.Stop() (no delay) after its 3rd
    packet while rank 0 would happily run to its 2 s stop — the window
    protocol must close out cleanly on both sides (r4 review)."""
    from tpudes.core import Seconds, Simulator
    from tpudes.core.global_value import GlobalValue
    from tpudes.core.world import reset_world
    from tpudes.helper.applications import (
        UdpEchoClientHelper,
        UdpEchoServerHelper,
    )
    from tpudes.helper.containers import NodeContainer
    from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
    from tpudes.helper.point_to_point import PointToPointHelper
    from tpudes.parallel.mpi import MpiInterface

    reset_world()
    GlobalValue.Bind(
        "SimulatorImplementationType", "tpudes::DistributedSimulatorImpl"
    )
    a = NodeContainer()
    a.Create(1, system_id=0)
    b = NodeContainer()
    b.Create(1, system_id=1)
    stack = InternetStackHelper()
    stack.Install(a)
    stack.Install(b)
    p2p = PointToPointHelper()
    p2p.SetDeviceAttribute("DataRate", "5Mbps")
    p2p.SetChannelAttribute("Delay", "2ms")
    ifc = Ipv4AddressHelper("10.9.0.0", "255.255.255.0").Assign(
        p2p.Install(a.Get(0), b.Get(0))
    )
    me = MpiInterface.GetSystemId()
    got = [0]
    if me == 1:
        server = UdpEchoServerHelper(9)
        sapps = server.Install(b.Get(0))
        sapps.Start(Seconds(0.0))

        def on_rx(pkt, *args):
            got[0] += 1
            if got[0] == 3:
                Simulator.Stop()  # immediate, rank-local

        sapps.Get(0).TraceConnectWithoutContext("Rx", on_rx)
    if me == 0:
        client = UdpEchoClientHelper(ifc.GetAddress(1), 9)
        client.SetAttribute("MaxPackets", 100)
        client.SetAttribute("Interval", Seconds(0.05))
        client.SetAttribute("PacketSize", 64)
        client.Install(a.Get(0)).Start(Seconds(0.1))
    Simulator.Stop(Seconds(2.0))
    Simulator.Run()
    out = dict(rank=me, server_rx=got[0], now=Simulator.NowTicks())
    Simulator.Destroy()
    return out


def run_bursty_window(rank: int, size: int, n_packets: int = 300):
    """One window carries ``n_packets`` cross-rank messages (far past
    the ~64 KiB OS pipe buffer) — the spooled threaded flush must not
    deadlock (r4 review)."""
    from tpudes.core import Seconds, Simulator
    from tpudes.core.global_value import GlobalValue
    from tpudes.core.world import reset_world
    from tpudes.helper.applications import UdpServerHelper
    from tpudes.helper.containers import NodeContainer
    from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
    from tpudes.helper.point_to_point import PointToPointHelper
    from tpudes.models.applications import UdpClient
    from tpudes.parallel.mpi import MpiInterface

    reset_world()
    GlobalValue.Bind(
        "SimulatorImplementationType", "tpudes::DistributedSimulatorImpl"
    )
    a = NodeContainer()
    a.Create(1, system_id=0)
    b = NodeContainer()
    b.Create(1, system_id=1)
    stack = InternetStackHelper()
    stack.Install(a)
    stack.Install(b)
    p2p = PointToPointHelper()
    p2p.SetDeviceAttribute("DataRate", "1Gbps")
    p2p.SetChannelAttribute("Delay", "5ms")
    ifc = Ipv4AddressHelper("10.8.0.0", "255.255.255.0").Assign(
        p2p.Install(a.Get(0), b.Get(0))
    )
    me = MpiInterface.GetSystemId()
    rx = [0]
    if me == 1:
        server = UdpServerHelper(9)
        sapps = server.Install(b.Get(0))
        sapps.Start(Seconds(0.0))
        sapps.Get(0).TraceConnectWithoutContext(
            "Rx", lambda *a_: rx.__setitem__(0, rx[0] + 1)
        )
    if me == 0:
        # all packets burst within one 5 ms lookahead window; 10 µs
        # spacing > the ~4.3 µs serialization so the tx queue never
        # overflows (the transport, not DropTail, is under test)
        client = UdpClient(
            RemoteAddress=str(ifc.GetAddress(1)),
            RemotePort=9,
            MaxPackets=n_packets,
            Interval=Seconds(0.00001),
            PacketSize=512,
        )
        a.Get(0).AddApplication(client)
        client.SetStartTime(Seconds(0.001))
    Simulator.Stop(Seconds(0.5))
    Simulator.Run()
    # this image's sitecustomize preloads jax into every process, so the
    # controllable invariant is that tpudes itself never pulls the
    # jax-heavy engine submodules into a distributed rank
    import sys as _sys

    out = dict(
        rank=me, rx=rx[0],
        heavy_loaded=any(
            m in _sys.modules
            for m in ("tpudes.parallel.kernels", "tpudes.parallel.mesh")
        ),
    )
    Simulator.Destroy()
    return out


def run_chain_three_ranks(rank: int, size: int):
    """6-node chain over 3 ranks (2 nodes each), echo end-to-end."""
    from tpudes.core import Seconds, Simulator
    from tpudes.core.global_value import GlobalValue
    from tpudes.core.world import reset_world
    from tpudes.helper.applications import (
        UdpEchoClientHelper,
        UdpEchoServerHelper,
    )
    from tpudes.helper.containers import NodeContainer
    from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
    from tpudes.helper.point_to_point import PointToPointHelper
    from tpudes.models.internet.global_routing import Ipv4GlobalRoutingHelper
    from tpudes.parallel.mpi import MpiInterface

    reset_world()
    GlobalValue.Bind(
        "SimulatorImplementationType", "tpudes::DistributedSimulatorImpl"
    )
    nodes = []
    for r in range(3):
        c = NodeContainer()
        c.Create(2, system_id=r)
        nodes += [c.Get(0), c.Get(1)]
    stack = InternetStackHelper()
    stack.SetRoutingHelper(Ipv4GlobalRoutingHelper())
    stack.Install(nodes)
    p2p = PointToPointHelper()
    p2p.SetDeviceAttribute("DataRate", "5Mbps")
    p2p.SetChannelAttribute("Delay", "1ms")
    addr = Ipv4AddressHelper("10.2.0.0", "255.255.255.0")
    last_ifc = None
    for i in range(5):
        devs = p2p.Install(nodes[i], nodes[i + 1])
        last_ifc = addr.Assign(devs)
        addr.NewNetwork()
    Ipv4GlobalRoutingHelper.PopulateRoutingTables()

    me = MpiInterface.GetSystemId()
    server_rx: list = []
    if nodes[5].GetSystemId() == me:
        server = UdpEchoServerHelper(9)
        sapps = server.Install(nodes[5])
        sapps.Start(Seconds(0.0))
        sapps.Get(0).TraceConnectWithoutContext(
            "Rx",
            lambda pkt, *a: server_rx.append(Simulator.NowTicks()),
        )
    if nodes[0].GetSystemId() == me:
        client = UdpEchoClientHelper(last_ifc.GetAddress(1), 9)
        client.SetAttribute("MaxPackets", 3)
        client.SetAttribute("Interval", Seconds(0.2))
        client.SetAttribute("PacketSize", 100)
        client.Install(nodes[0]).Start(Seconds(0.1))
    Simulator.Stop(Seconds(1.5))
    Simulator.Run()
    Simulator.Destroy()
    return dict(server_rx=server_rx)


# --- ISSUE-9: multi-process mesh workers (launch_process_mesh targets) ----


def procmesh_devices(pmesh):
    """Pin the jax.distributed invariant: the global device count sums
    every member's local devices while local stays local."""
    import jax

    return dict(
        process_id=pmesh.process_id,
        num_processes=pmesh.num_processes,
        global_devices=jax.device_count(),
        local_devices=jax.local_device_count(),
        backend=jax.default_backend(),
    )


def procmesh_replica_slice(pmesh, n_replicas: int):
    """Run this process's contiguous replica block of a jittered wired
    program at the GLOBAL offset (the fold_in purity contract)."""
    import jax

    from tpudes.parallel.wired import run_wired, wired_chain

    lo, hi = pmesh.slice_bounds(n_replicas)
    prog = wired_chain(n_links=4, n_flows=2, n_slots=300, jitter_slots=3)
    out = run_wired(prog, jax.random.key(11), replicas=hi - lo,
                    replica_offset=lo)
    return dict(lo=lo, hi=hi, deliver=out["deliver_slot"])


def procmesh_serving_router(pmesh, n_studies: int):
    """Rank 0 runs a StudyServer with a ProcessRouter over the member
    pipes; members run serve_studies.  Returns rank 0's routed results
    + solo references (computed in the SAME process so compile caches
    are warm), members' served counts."""
    import dataclasses

    import jax
    import numpy as np

    from tpudes.parallel.mpi import MpiInterface
    from tpudes.parallel.programs import toy_bss_program
    from tpudes.serving import ProcessRouter, StudyServer, serve_studies

    if pmesh.process_id != 0:
        return dict(served=serve_studies(MpiInterface._conns[0]))

    from tpudes.parallel.replicated import run_replicated_bss

    prog = toy_bss_program(n_sta=4, sim_end_us=40_000)
    key = jax.random.PRNGKey(3)
    horizons = [40_000 + 2_000 * i for i in range(n_studies)]
    router = ProcessRouter(MpiInterface._conns)
    server = StudyServer(max_batch=8, router=router, start=False)
    handles = [
        server.submit_study(
            "bss", dataclasses.replace(prog, sim_end_us=h), key, 2,
            tenant=f"t{i}",
        )
        for i, h in enumerate(horizons)
    ]
    server.pump(force=True)
    results = [h.result(timeout=240) for h in handles]
    server.close()
    equal = True
    for h, res in zip(horizons, results):
        solo = run_replicated_bss(
            dataclasses.replace(prog, sim_end_us=h), 2, key
        )
        for k in solo:
            if not np.array_equal(np.asarray(res[k]), np.asarray(solo[k])):
                equal = False
    return dict(
        routed_batches=router.routed_batches,
        routed_points=router.routed_points,
        equal=equal,
    )
