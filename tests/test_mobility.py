"""Mobility model tests — mirrors upstream's mobility test suite style:
closed-form kinematics checks, bounds containment, trace firing."""


import pytest

from tpudes.core import Seconds, Simulator
from tpudes.models.mobility import (
    CalculateDistance,
    ConstantAccelerationMobilityModel,
    ConstantPositionMobilityModel,
    ConstantVelocityMobilityModel,
    GaussMarkovMobilityModel,
    GridPositionAllocator,
    ListPositionAllocator,
    MobilityHelper,
    MobilityModel,
    RandomDiscPositionAllocator,
    RandomRectanglePositionAllocator,
    RandomWalk2dMobilityModel,
    RandomWaypointMobilityModel,
    Vector,
    WaypointMobilityModel,
    positions_array,
)
from tpudes.network.node import Node


def test_vector_math():
    v = Vector(3, 4, 0)
    assert v.GetLength() == pytest.approx(5.0)
    assert CalculateDistance(Vector(1, 1, 1), Vector(1, 1, 1)) == 0.0
    assert (Vector(1, 2, 3) + Vector(1, 1, 1)).tuple() == (2, 3, 4)


def test_constant_velocity_closed_form():
    m = ConstantVelocityMobilityModel()
    m.SetPosition(Vector(0, 0, 0))
    m.SetVelocity(Vector(1, 2, 0))
    got = []
    Simulator.Schedule(Seconds(2.5), lambda: got.append(m.GetPosition()))
    Simulator.Run()
    assert got[0].x == pytest.approx(2.5)
    assert got[0].y == pytest.approx(5.0)


def test_constant_acceleration():
    m = ConstantAccelerationMobilityModel()
    m.SetPosition(Vector(0, 0, 0))
    m.SetVelocityAndAcceleration(Vector(1, 0, 0), Vector(2, 0, 0))
    got = []
    Simulator.Schedule(Seconds(3.0), lambda: got.append((m.GetPosition(), m.GetVelocity())))
    Simulator.Run()
    pos, vel = got[0]
    assert pos.x == pytest.approx(1 * 3 + 0.5 * 2 * 9)  # 12
    assert vel.x == pytest.approx(1 + 2 * 3)  # 7


def test_course_change_trace_fires():
    m = ConstantPositionMobilityModel()
    hits = []
    m.TraceConnectWithoutContext("CourseChange", lambda model: hits.append(model.GetPosition().x))
    m.SetPosition(Vector(7, 0, 0))
    assert hits == [7]


def test_random_walk_stays_in_bounds():
    m = RandomWalk2dMobilityModel(Bounds=(0.0, 20.0, 0.0, 20.0), Time=0.5, MinSpeed=5.0, MaxSpeed=10.0)
    m.SetPosition(Vector(10, 10, 0))
    samples = []

    def sample():
        p = m.GetPosition()
        samples.append(p)

    for i in range(1, 60):
        Simulator.Schedule(Seconds(i * 0.25), sample)
    Simulator.Stop(Seconds(16))
    Simulator.Run()
    assert len(samples) == 59
    for p in samples:
        assert -1e-6 <= p.x <= 20 + 1e-6 and -1e-6 <= p.y <= 20 + 1e-6
    # it actually moved
    assert max(CalculateDistance(samples[0], s) for s in samples) > 1.0


def test_random_waypoint_reaches_waypoints():
    alloc = ListPositionAllocator()
    alloc.Add(Vector(10, 0, 0))
    alloc.Add(Vector(0, 0, 0))
    m = RandomWaypointMobilityModel(MinSpeed=1.0, MaxSpeed=1.0, Pause=0.5)
    m.SetPositionAllocator(alloc)
    m.SetPosition(Vector(0, 0, 0))
    seen = []
    # at t=10s it must have arrived at (10,0,0) and be pausing
    Simulator.Schedule(Seconds(10.2), lambda: seen.append(m.GetPosition()))
    Simulator.Stop(Seconds(11))
    Simulator.Run()
    assert seen[0].x == pytest.approx(10.0, abs=0.3)


def test_gauss_markov_moves_and_stays_bounded():
    m = GaussMarkovMobilityModel(Bounds=(0.0, 50.0, 0.0, 50.0, 0.0, 0.0), TimeStep=0.5, MeanVelocity=2.0)
    m.SetPosition(Vector(25, 25, 0))
    track = []
    for i in range(1, 40):
        Simulator.Schedule(Seconds(i * 0.5), lambda: track.append(m.GetPosition()))
    Simulator.Stop(Seconds(21))
    Simulator.Run()
    assert max(CalculateDistance(track[0], p) for p in track) > 1.0


def test_waypoint_interpolation():
    m = WaypointMobilityModel()
    m.AddWaypoint(Seconds(0), Vector(0, 0, 0))
    m.AddWaypoint(Seconds(10), Vector(100, 0, 0))
    got = []
    Simulator.Schedule(Seconds(2.5), lambda: got.append((m.GetPosition().x, m.GetVelocity().x)))
    Simulator.Run()
    assert got[0][0] == pytest.approx(25.0)
    assert got[0][1] == pytest.approx(10.0)


def test_grid_allocator_row_first():
    g = GridPositionAllocator(MinX=0.0, MinY=0.0, DeltaX=5.0, DeltaY=10.0, GridWidth=3)
    pts = [g.GetNext() for _ in range(5)]
    assert pts[0].tuple() == (0, 0, 0)
    assert pts[2].tuple() == (10, 0, 0)
    assert pts[3].tuple() == (0, 10, 0)  # wrapped to second row


def test_random_allocators_in_region():
    r = RandomRectanglePositionAllocator(MinX=1.0, MaxX=2.0, MinY=3.0, MaxY=4.0)
    for _ in range(20):
        p = r.GetNext()
        assert 1 <= p.x <= 2 and 3 <= p.y <= 4
    d = RandomDiscPositionAllocator(X=10.0, Y=10.0, Rho=5.0)
    for _ in range(20):
        p = d.GetNext()
        assert CalculateDistance(p, Vector(10, 10, 0)) <= 5.0 + 1e-9


def test_mobility_helper_install_and_positions_array():
    nodes = [Node(), Node(), Node()]
    helper = MobilityHelper()
    helper.SetPositionAllocator(
        "tpudes::GridPositionAllocator", MinX=0.0, MinY=0.0, DeltaX=2.0, DeltaY=2.0, GridWidth=2
    )
    helper.SetMobilityModel("ns3::ConstantPositionMobilityModel")  # ns3:: alias accepted
    helper.Install(nodes)
    for node in nodes:
        assert node.GetObject(MobilityModel) is not None
    arr = positions_array(nodes)
    assert arr.shape == (3, 3)
    assert arr[1][0] == pytest.approx(2.0)
    assert arr[2][1] == pytest.approx(2.0)
