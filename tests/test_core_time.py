"""Time arithmetic and parsing (reference parity: src/core/model/nstime.h
semantics; mirrors upstream time test style — exact tick arithmetic)."""

from tpudes.core.nstime import (
    Time,
    Seconds,
    MilliSeconds,
    MicroSeconds,
    NanoSeconds,
    Minutes,
    Hours,
)


def test_constructors_and_ticks():
    assert Seconds(1).GetNanoSeconds() == 1_000_000_000
    assert MilliSeconds(5).GetNanoSeconds() == 5_000_000
    assert MicroSeconds(7).GetNanoSeconds() == 7_000
    assert NanoSeconds(13).ticks == 13
    assert Minutes(2).GetSeconds() == 120.0
    assert Hours(1).GetSeconds() == 3600.0


def test_arithmetic_exact():
    t = Seconds(1) + MilliSeconds(500)
    assert t.GetNanoSeconds() == 1_500_000_000
    assert (t - Seconds(1)).GetNanoSeconds() == 500_000_000
    assert (t * 2).GetNanoSeconds() == 3_000_000_000
    assert t / Seconds(1) == 1.5
    assert Seconds(10) // Seconds(3) == 3
    assert (Seconds(10) % Seconds(3)).GetSeconds() == 1.0


def test_comparisons():
    assert Seconds(1) < Seconds(2)
    assert Seconds(2) >= MilliSeconds(2000)
    assert Seconds(2) == MilliSeconds(2000)
    assert NanoSeconds(1).IsStrictlyPositive()
    assert Time(0).IsZero()
    assert (-Seconds(1)).IsStrictlyNegative()


def test_string_parsing():
    assert Time("1s") == Seconds(1)
    assert Time("5ms") == MilliSeconds(5)
    assert Time("2.5us") == MicroSeconds(2.5)
    assert Time("100ns").ticks == 100
    assert Time("1min") == Seconds(60)
    assert Time("3") == Seconds(3)  # bare number = seconds, as in ns-3


def test_float_seconds_roundtrip():
    assert abs(Seconds(0.123456789).GetSeconds() - 0.123456789) < 1e-12
