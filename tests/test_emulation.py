"""Emulation tests: FdNetDevice over a socketpair, TapBridge over a
real kernel tap when the environment allows.

Upstream analogs: src/fd-net-device/test (loopback fd pairs) and the
tap-bridge examples' verify scripts.  The socketpair plays the external
world: the test process speaks RAW ETHERNET bytes on one end while the
simulation (RealtimeSimulatorImpl, so sim time tracks the wall clock)
answers on the other.
"""

import os
import socket
import struct
import threading
import time

import pytest

from tpudes.core import Seconds, Simulator
from tpudes.core.global_value import GlobalValue
from tpudes.helper.containers import NodeContainer
from tpudes.helper.internet import InternetStackHelper
from tpudes.models.csma import EthernetHeader
from tpudes.models.fd_net_device import FdNetDevice, FdNetDeviceHelper
from tpudes.models.internet.arp import ArpHeader
from tpudes.models.internet.ipv4 import (
    Ipv4Header,
    Ipv4InterfaceAddress,
    Ipv4L3Protocol,
    Ipv4StaticRouting,
)
from tpudes.models.internet.udp import UdpHeader
from tpudes.network.address import Ipv4Address, Ipv4Mask, Mac48Address
from tpudes.network.packet import Packet


def _fd_node(sock_fd, ip="10.5.0.1"):
    """One simulated host whose NIC is the given fd."""
    nodes = NodeContainer()
    nodes.Create(1)
    InternetStackHelper().Install(nodes)
    dev = FdNetDeviceHelper().Install(nodes.Get(0), sock_fd)
    ipv4 = nodes.Get(0).GetObject(Ipv4L3Protocol)
    if_index = ipv4.AddInterface(dev)
    ipv4.AddAddress(
        if_index, Ipv4InterfaceAddress(Ipv4Address(ip), Ipv4Mask("255.255.255.0"))
    )
    routing = ipv4.GetRoutingProtocol()
    assert isinstance(routing, Ipv4StaticRouting)
    routing.AddNetworkRouteTo(
        Ipv4Address(ip).CombineMask(Ipv4Mask("255.255.255.0")),
        Ipv4Mask("255.255.255.0"), if_index,
    )
    dev.Start()
    return nodes.Get(0), dev


def _udp_frame(dst_mac, src_mac, src_ip, dst_ip, sport, dport, payload: bytes):
    p = Packet(payload)
    p.AddHeader(UdpHeader(sport, dport, len(payload)))
    p.AddHeader(
        Ipv4Header(
            source=Ipv4Address(src_ip), destination=Ipv4Address(dst_ip),
            protocol=17, payload_size=len(payload) + 8,
        )
    )
    return (
        EthernetHeader(dst_mac, src_mac, 0x0800).Serialize() + p.ToBytes()
    )


def test_parse_l3_round_trips_structured_headers():
    payload = b"hello-emu"
    wire = _udp_frame(
        Mac48Address(2), Mac48Address(3), "10.5.0.9", "10.5.0.1", 777, 9,
        payload,
    )
    pkt = FdNetDevice.parse_l3(wire[14:], 0x0800)
    ip = pkt.RemoveHeader(Ipv4Header)
    assert str(ip.source) == "10.5.0.9" and ip.protocol == 17
    udp = pkt.RemoveHeader(UdpHeader)
    assert (udp.source_port, udp.destination_port) == (777, 9)
    assert pkt.GetPayload() == payload

    arp = ArpHeader(
        op=ArpHeader.REQUEST, source_mac=Mac48Address(3),
        source_ip="10.5.0.9", dest_ip="10.5.0.1",
    )
    pkt2 = FdNetDevice.parse_l3(arp.Serialize(), 0x0806)
    h = pkt2.RemoveHeader(ArpHeader)
    assert h.op == ArpHeader.REQUEST and str(h.dest_ip) == "10.5.0.1"


def test_fd_net_device_full_exchange_with_external_world():
    """The test process is the 'real host': it ARPs for the sim node,
    sends it UDP, and the echo comes back out the fd — the dnemu loop."""
    from tpudes.helper.applications import UdpEchoServerHelper

    sim_sock, world_sock = socket.socketpair(socket.AF_UNIX, socket.SOCK_DGRAM)
    GlobalValue.Bind(
        "SimulatorImplementationType", "tpudes::RealtimeSimulatorImpl"
    )
    node, dev = _fd_node(sim_sock.fileno())
    server = UdpEchoServerHelper(9)
    sapps = server.Install(node)
    sapps.Start(Seconds(0.0))
    rx = [0]
    sapps.Get(0).TraceConnectWithoutContext(
        "Rx", lambda *a: rx.__setitem__(0, rx[0] + 1)
    )

    world_mac = Mac48Address(0xEEEE)
    world_log = {"arp_request": 0, "udp_echoes": []}

    def world():
        # 1. ask who has 10.5.0.1 (the sim node must answer ARP)
        arp_req = ArpHeader(
            op=ArpHeader.REQUEST, source_mac=world_mac,
            source_ip="10.5.0.9", dest_mac=Mac48Address(0),
            dest_ip="10.5.0.1",
        )
        world_sock.send(
            EthernetHeader(
                Mac48Address.GetBroadcast(), world_mac, 0x0806
            ).Serialize() + arp_req.Serialize()
        )
        world_sock.settimeout(2.0)
        sim_mac = None
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            frame = world_sock.recv(65536)
            eth = EthernetHeader.Deserialize(frame[:14])
            if eth.ether_type == 0x0806:
                reply = ArpHeader.Deserialize(frame[14:])
                if reply.op == ArpHeader.REPLY:
                    sim_mac = reply.source_mac
                    break
                if reply.op == ArpHeader.REQUEST:
                    # sim may ARP for us first — answer it
                    world_log["arp_request"] += 1
                    ans = ArpHeader(
                        op=ArpHeader.REPLY, source_mac=world_mac,
                        source_ip="10.5.0.9",
                        dest_mac=reply.source_mac,
                        dest_ip=str(reply.source_ip),
                    )
                    world_sock.send(
                        EthernetHeader(
                            reply.source_mac, world_mac, 0x0806
                        ).Serialize() + ans.Serialize()
                    )
        assert sim_mac is not None, "sim node never answered ARP"
        # 2. UDP echo request to the sim server
        world_sock.send(
            _udp_frame(
                sim_mac, world_mac, "10.5.0.9", "10.5.0.1", 777, 9,
                b"ping-from-the-real-world",
            )
        )
        # 3. collect the echo (the sim may ARP for 10.5.0.9 first)
        while time.monotonic() < deadline:
            frame = world_sock.recv(65536)
            eth = EthernetHeader.Deserialize(frame[:14])
            if eth.ether_type == 0x0806:
                req = ArpHeader.Deserialize(frame[14:])
                if req.op == ArpHeader.REQUEST:
                    world_log["arp_request"] += 1
                    ans = ArpHeader(
                        op=ArpHeader.REPLY, source_mac=world_mac,
                        source_ip="10.5.0.9",
                        dest_mac=req.source_mac,
                        dest_ip=str(req.source_ip),
                    )
                    world_sock.send(
                        EthernetHeader(
                            req.source_mac, world_mac, 0x0806
                        ).Serialize() + ans.Serialize()
                    )
            elif eth.ether_type == 0x0800:
                pkt = FdNetDevice.parse_l3(frame[14:], 0x0800)
                pkt.RemoveHeader(Ipv4Header)
                udp = pkt.RemoveHeader(UdpHeader)
                world_log["udp_echoes"].append(
                    (udp.destination_port, pkt.GetPayload())
                )
                return

    t = threading.Thread(target=world)
    t.start()
    Simulator.Stop(Seconds(1.0))
    Simulator.Run()
    t.join(timeout=5)
    dev.Stop()
    assert rx[0] == 1, "sim server must receive the external UDP"
    assert world_log["udp_echoes"] == [(777, b"ping-from-the-real-world")]


def test_fix_checksums_produces_kernel_valid_sums():
    """IPv4/ICMP/TCP checksums rewritten at the boundary verify to 0
    under the receiver's recomputation (r4 review: zero sums made the
    kernel drop ping replies and all TCP)."""
    from tpudes.models.internet.icmp import IcmpEcho, Icmpv4Header
    from tpudes.models.internet.ipv4 import internet_checksum
    from tpudes.models.internet.tcp import TcpHeader

    def verify(frame):
        ihl = (frame[14] & 0x0F) * 4
        assert internet_checksum(frame[14 : 14 + ihl]) == 0
        proto = frame[14 + 9]
        l4 = frame[14 + ihl :]
        if proto == 1:
            assert internet_checksum(l4) == 0
        elif proto == 6:
            pseudo = frame[14 + 12 : 14 + 20] + struct.pack(
                "!BBH", 0, 6, len(l4)
            )
            assert internet_checksum(pseudo + l4) == 0

    # ICMP echo reply
    p = Packet(16)
    p.AddHeader(IcmpEcho(1, 2))
    p.AddHeader(Icmpv4Header(Icmpv4Header.ECHO_REPLY, 0))
    p.AddHeader(Ipv4Header(
        source=Ipv4Address("10.5.0.1"), destination=Ipv4Address("10.5.0.9"),
        protocol=1, payload_size=24,
    ))
    frame = FdNetDevice.fix_checksums(
        EthernetHeader(Mac48Address(1), Mac48Address(2), 0x0800).Serialize()
        + p.ToBytes()
    )
    verify(frame)

    # TCP segment
    p = Packet(b"data")
    p.AddHeader(TcpHeader(1234, 80, seq=7, ack=9, flags=TcpHeader.ACK))
    p.AddHeader(Ipv4Header(
        source=Ipv4Address("10.5.0.1"), destination=Ipv4Address("10.5.0.9"),
        protocol=6, payload_size=24,
    ))
    frame = FdNetDevice.fix_checksums(
        EthernetHeader(Mac48Address(1), Mac48Address(2), 0x0800).Serialize()
        + p.ToBytes()
    )
    verify(frame)


def test_parse_l3_honors_tcp_data_offset_and_ihl():
    """Kernel TCP always carries options (doff > 5); they must not leak
    into the payload (r4 review)."""
    # hand-build: IP(IHL=5) + TCP with 12 bytes of options (doff=8)
    ip = Ipv4Header(
        source=Ipv4Address("10.5.0.9"), destination=Ipv4Address("10.5.0.1"),
        protocol=6, payload_size=32 + 7,
    ).Serialize()
    tcp20 = bytearray(
        struct.pack(
            ">HHIIBBHHH", 5555, 80, 100, 200, 8 << 4, 0x18, 65535, 0, 0
        )
    )
    options = b"\x01" * 12
    payload = b"payload"
    pkt = FdNetDevice.parse_l3(ip + bytes(tcp20) + options + payload, 0x0800)
    from tpudes.models.internet.tcp import TcpHeader as TH

    pkt.RemoveHeader(Ipv4Header)
    tcp = pkt.RemoveHeader(TH)
    assert (tcp.source_port, tcp.destination_port) == (5555, 80)
    assert pkt.GetPayload() == payload, "options leaked into payload"


def test_parse_l3_trims_ethernet_padding():
    """Real NICs pad short frames to 60 bytes; the padding must not
    leak into the UDP payload (r4 review)."""
    payload = b"tiny"
    wire = _udp_frame(
        Mac48Address(2), Mac48Address(3), "10.5.0.9", "10.5.0.1", 7, 9,
        payload,
    )
    padded = wire + b"\x00" * (60 - len(wire)) if len(wire) < 60 else wire
    pkt = FdNetDevice.parse_l3(padded[14:], 0x0800)
    pkt.RemoveHeader(Ipv4Header)
    pkt.RemoveHeader(UdpHeader)
    assert pkt.GetPayload() == payload


def test_reader_restart_while_blocked_is_refused():
    sim_sock, world_sock = socket.socketpair(socket.AF_UNIX, socket.SOCK_DGRAM)
    dev = FdNetDevice()
    nodes = NodeContainer()
    nodes.Create(1)
    nodes.Get(0).AddDevice(dev)
    dev.SetFileDescriptor(sim_sock.fileno())
    dev.Start()
    dev.Stop()
    with pytest.raises(RuntimeError, match="blocked"):
        dev.Start()
    sim_sock.close()
    world_sock.close()


def test_checksum_enabled_global_gates_in_sim_serialization():
    from tpudes.core.global_value import GlobalValue
    from tpudes.models.internet.ipv4 import internet_checksum

    h = Ipv4Header(
        source=Ipv4Address("10.0.0.1"), destination=Ipv4Address("10.0.0.2"),
        protocol=17, payload_size=8,
    )
    assert h.Serialize()[10:12] == b"\x00\x00"
    GlobalValue.Bind("ChecksumEnabled", True)
    try:
        assert internet_checksum(h.Serialize()) == 0
    finally:
        GlobalValue.Bind("ChecksumEnabled", False)


def _tun_available() -> bool:
    try:
        fd = os.open("/dev/net/tun", os.O_RDWR)
        os.close(fd)
        return True
    except OSError:
        return False


@pytest.mark.skipif(not _tun_available(), reason="no /dev/net/tun access")
def test_tap_bridge_reaches_kernel_stack():
    """End-to-end dnemu: a REAL kernel UDP socket sends through a tap
    interface into the simulation; the sim node answers ARP and
    delivers to its UDP server."""
    import subprocess

    from tpudes.helper.applications import UdpEchoServerHelper
    from tpudes.models.fd_net_device import TapBridge, create_tap

    GlobalValue.Bind(
        "SimulatorImplementationType", "tpudes::RealtimeSimulatorImpl"
    )
    # sim host 10.6.0.2 behind a tap; its NIC is the fd side directly
    sim_sock_fd, name = create_tap("tpudes-tap0")
    try:
        subprocess.run(
            ["ip", "addr", "add", "10.6.0.1/24", "dev", name], check=True,
            capture_output=True,
        )
        subprocess.run(
            ["ip", "link", "set", name, "up"], check=True,
            capture_output=True,
        )
    except (OSError, subprocess.SubprocessError):
        os.close(sim_sock_fd)
        pytest.skip("cannot configure the tap interface")

    node, dev = _fd_node(sim_sock_fd, ip="10.6.0.2")
    ipv4 = node.GetObject(Ipv4L3Protocol)
    server = UdpEchoServerHelper(9)
    sapps = server.Install(node)
    sapps.Start(Seconds(0.0))
    rx = [0]
    sapps.Get(0).TraceConnectWithoutContext(
        "Rx", lambda *a: rx.__setitem__(0, rx[0] + 1)
    )

    result = {}

    def world():
        time.sleep(0.1)
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.bind(("10.6.0.1", 0))
        s.settimeout(2.0)
        s.sendto(b"kernel-to-sim", ("10.6.0.2", 9))
        try:
            data, addr = s.recvfrom(4096)
            result["echo"] = (data, addr[0])
        except TimeoutError:
            result["echo"] = None
        s.close()

    t = threading.Thread(target=world)
    t.start()
    Simulator.Stop(Seconds(1.5))
    Simulator.Run()
    t.join(timeout=5)
    dev.Stop()
    os.close(sim_sock_fd)
    assert rx[0] == 1, "kernel UDP must reach the simulated server"
    assert result.get("echo") == (b"kernel-to-sim", "10.6.0.2")