"""Differentiable simulation (tpudes.diff, ISSUE-15): surrogate
exactness pins, finite-difference checks on every exposed operand,
vmap-of-grad batching, and the one-executable grad-sweep contract.

f32 tolerance notes (documented per the ISSUE): the engines are pinned
float32, so central differences carry ~|loss|·2⁻²³/h cancellation
noise on top of O(h²) truncation — each check sizes its step h so both
terms sit well under the asserted rtol (0.02 for the steep LTE chain,
5e-3 for the near-linear AS chain).
"""

import dataclasses

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tpudes.diff import Surrogacy, grad_as_flows, grad_lte_sm  # noqa: E402
from tpudes.parallel.lte_sm import LteSmProgram  # noqa: E402
from tpudes.parallel.programs import (  # noqa: E402
    toy_as_program,
    toy_lte_program,
)

KEY = jax.random.PRNGKey(42)


def _as_prog(**over):
    prog = toy_as_program(n_nodes=24, n_flows=3)
    return dataclasses.replace(prog, **over) if over else prog


def _congested_as_prog(**over):
    """The toy graph pushed near link saturation, where the delivery
    gate actually gates (capacity gradients are zero in the deep
    sparse regime — delivery is pinned at 1)."""
    prog = toy_as_program(n_nodes=24, n_flows=3)
    return dataclasses.replace(
        prog,
        flow_bps=np.full(3, 4e7),
        surrogate=Surrogacy(ste=False),
        **over,
    )


def _lte_pos_prog(n_ue: int = 6, **over):
    """Tiny positional (pathloss-bearing) LTE program + UE positions."""
    E = 2
    serving = (np.arange(n_ue) % E).astype(np.int32)
    rng = np.random.default_rng(7)
    enb_pos = np.array([[0.0, 0.0, 30.0], [600.0, 0.0, 30.0]], np.float32)
    ue_pos = (
        enb_pos[serving]
        + np.c_[rng.uniform(-200, 200, n_ue),
                rng.uniform(-200, 200, n_ue),
                np.full(n_ue, -28.5)]
    ).astype(np.float32)
    prog = LteSmProgram(
        gain=np.full((E, n_ue), 1e-12),
        serving=serving,
        tx_power_dbm=np.full((E,), 43.0),
        noise_psd=10.0**0.9 * 1.380649e-23 * 290.0,
        n_rb=25,
        n_ttis=400,
        scheduler="pf",
        enb_pos=enb_pos,
        pathloss=("log_distance", 3.2, 1.0, 46.67),
        **over,
    )
    return prog, ue_pos


# --- surrogate exactness ----------------------------------------------------


class TestSurrogateExactness:
    def test_surrogate_none_is_same_runner_and_bit_equal(self):
        """The default program IS the legacy program: re-running hits
        the cached runner (no fresh compile) and an explicit
        surrogate=None replace changes nothing."""
        from tpudes.obs.device import CompileTelemetry
        from tpudes.parallel.as_flows import run_as_flows

        prog = _as_prog()
        base = run_as_flows(prog, KEY, replicas=4)
        c0 = CompileTelemetry.compiles("as_flows")
        again = run_as_flows(
            dataclasses.replace(prog, surrogate=None), KEY, replicas=4
        )
        assert CompileTelemetry.compiles("as_flows") - c0 == 0
        for k in base:
            assert np.array_equal(base[k], again[k]), k

    def test_ste_forward_bit_equal_to_legacy(self):
        """Straight-through surrogate: hard forward is BIT-equal to
        surrogate=None (the ste() correction is an exact float zero) —
        the surrogate_off fuzz-pair contract, pinned here."""
        from tpudes.parallel.as_flows import run_as_flows

        prog = _as_prog()
        base = run_as_flows(prog, KEY, replicas=4)
        ste = run_as_flows(
            dataclasses.replace(prog, surrogate=Surrogacy(ste=True)),
            KEY, replicas=4,
        )
        for k in base:
            assert np.array_equal(base[k], ste[k]), k

    def test_soft_surrogate_changes_the_forward(self):
        """ste=False really swaps the delivery gate (the flag is not
        decorative): near saturation the soft program's delivered
        fractions differ (deep in the sparse regime the soft gate's
        correction is below f32 resolution by design)."""
        from tpudes.parallel.as_flows import run_as_flows

        prog = _congested_as_prog()
        base = run_as_flows(
            dataclasses.replace(prog, surrogate=None), KEY, replicas=4
        )
        soft = run_as_flows(prog, KEY, replicas=4)
        assert not np.array_equal(
            base["delivered_frac"], soft["delivered_frac"]
        )

    def test_diff_runner_forward_bit_equal_to_engine(self):
        """The scan-based differentiable runner reproduces the
        production while-loop engine bit for bit (same fluid cores,
        fixed FP_ROUNDS)."""
        from tpudes.parallel.as_flows import (
            _as_replica_draws,
            build_as_diff,
            run_as_flows,
        )
        from tpudes.parallel.runtime import bucket_replicas

        prog = _as_prog()
        out = run_as_flows(prog, KEY, replicas=5)
        r_pad = bucket_replicas(5, None)
        diff_run = jax.jit(build_as_diff(prog, r_pad))
        d = diff_run(
            _as_replica_draws(prog, KEY, r_pad), jnp.float32(1.0),
            jnp.asarray(prog.flow_bps, jnp.float32),
            jnp.asarray(prog.rate_bps, jnp.float32),
        )
        assert np.array_equal(
            np.asarray(d["goodput_bps"])[:5], out["goodput_bps"]
        )
        assert np.array_equal(
            np.asarray(d["delivered_frac"])[:5], out["delivered_frac"]
        )
        # utilization/delay: ≤1 ULP — lifting the capacities from a
        # baked constant to a traced operand changes how XLA strength-
        # reduces the division (documented in build_as_diff)
        np.testing.assert_allclose(
            np.asarray(d["max_util"])[:5], out["max_util"], rtol=2e-7
        )
        reach = ~out["unreachable"]
        np.testing.assert_allclose(
            np.asarray(d["delay_s"])[:5][:, reach],
            out["delay_s"][:, reach],
            rtol=2e-7,
        )

    def test_ops_level_hard_paths_unchanged(self):
        """ops/lte.py surrogate seams: surrogate=None is the identical
        legacy math, eff_from_sinr's hard staircase equals the CQI
        table gather, and the ste identity is bit-exact."""
        from tpudes.diff.surrogate import ste
        from tpudes.ops.lte import (
            _CQI_EFF,
            cqi_from_sinr,
            decode_ok,
            eff_from_sinr,
            qm_from_eff,
        )

        sinr = jnp.asarray(
            np.logspace(-2, 4, 41, dtype=np.float32)
        )
        legacy = cqi_from_sinr(sinr)
        assert np.array_equal(
            np.asarray(legacy),
            np.asarray(cqi_from_sinr(sinr, surrogate=None)),
        )
        eff_hard = np.asarray(eff_from_sinr(sinr))
        assert np.allclose(
            eff_hard, _CQI_EFF[np.asarray(legacy)], atol=1e-6
        )
        qm_hard = np.asarray(qm_from_eff(jnp.asarray(eff_hard)))
        assert set(np.unique(qm_hard)) <= {2.0, 4.0, 6.0}
        coin = jnp.asarray([0.05, 0.5, 0.95], jnp.float32)
        bler = jnp.asarray([0.1, 0.5, 0.9], jnp.float32)
        assert np.array_equal(
            np.asarray(decode_ok(coin, bler)),
            np.asarray(coin >= bler),
        )
        # ste: forward bit-exact, backward takes the soft branch
        hard = jnp.float32(3.0)
        soft = jnp.float32(2.5)
        assert float(ste(hard, soft)) == 3.0
        g = jax.grad(lambda x: ste(jnp.round(x), x * x))(jnp.float32(1.4))
        assert float(g) == pytest.approx(2.8)

    def test_surrogate_flips_compile_separate_runners(self):
        """The Surrogacy config is a cache-key component: a temperature
        flip compiles a fresh executable instead of hitting a stale
        runner."""
        from tpudes.obs.device import CompileTelemetry
        from tpudes.parallel.as_flows import run_as_flows

        prog = _as_prog(surrogate=Surrogacy(gate_temp=0.25))
        run_as_flows(prog, KEY, replicas=2)
        c0 = CompileTelemetry.compiles("as_flows")
        run_as_flows(
            dataclasses.replace(prog, surrogate=Surrogacy(gate_temp=0.5)),
            KEY, replicas=2,
        )
        assert CompileTelemetry.compiles("as_flows") - c0 == 1


# --- finite-difference checks ----------------------------------------------


def _fd_check(loss_at, v0, h, ad, rtol, atol=0.0):
    """Central-difference check of AD gradient ``ad`` at ``v0``."""
    fd = np.zeros_like(np.asarray(v0, np.float64))
    flat0 = np.asarray(v0, np.float64).ravel()
    for i in range(flat0.size):
        p, m = flat0.copy(), flat0.copy()
        p[i] += h
        m[i] -= h
        fd.ravel()[i] = (
            loss_at(p.reshape(np.shape(v0)))
            - loss_at(m.reshape(np.shape(v0)))
        ) / (2 * h)
    np.testing.assert_allclose(np.asarray(ad), fd, rtol=rtol, atol=atol)
    return fd


class TestFiniteDifference:
    def test_as_every_exposed_operand(self):
        """FD vs AD on flow_bps / cap_bps / rate_scale (the AS operand
        surface), soft surrogate so FD sees the differentiated
        forward.  flow/scale probe the sparse regime (near-linear,
        rtol 5e-3); capacity gradients only exist near saturation, so
        cap_bps probes the congested program (rtol 2e-2 — the gate is
        steeper there)."""
        sparse = _as_prog(surrogate=Surrogacy(ste=False))
        congested = _congested_as_prog()

        checks = [
            # (program, operand, h, rtol): steps sized to the
            # operand's scale
            (sparse, "flow_bps", 200.0, 5e-3),
            (congested, "cap_bps", 20000.0, 2e-2),
            (sparse, "rate_scale", 1e-3, 5e-3),
        ]
        for prog, name, h, rtol in checks:
            base = grad_as_flows(prog, KEY, 4, loss="neg_goodput")
            v0 = np.asarray(
                {
                    "flow_bps": prog.flow_bps,
                    "cap_bps": prog.rate_bps,
                    "rate_scale": 1.0,
                }[name],
                np.float64,
            )

            def loss_at(v, prog=prog, name=name):
                return grad_as_flows(
                    prog, KEY, 4, loss="neg_goodput", at={name: v}
                )["loss"]

            fd = _fd_check(
                loss_at, v0, h, base["grads"][name], rtol=rtol,
                atol=1e-10,
            )
            assert np.abs(fd).max() > 0, f"{name}: degenerate FD probe"

    def test_as_delay_and_kpi_losses_differentiate(self):
        prog = _as_prog(surrogate=Surrogacy(ste=False))
        tgt = np.full(3, 5e4, np.float32)
        for loss, kw in [("kpi_mse", {"target": tgt}), ("delay", {})]:
            r = grad_as_flows(prog, KEY, 4, loss=loss, **kw)
            g = r["grads"]["flow_bps"]
            assert np.isfinite(g).all() and np.abs(g).max() > 0, loss

    def test_lte_every_exposed_operand(self):
        """FD vs AD on tx powers, UE/eNB positions, propagation
        params, scheduler weights (the LTE operand surface).  rtol
        0.02 at per-operand steps (f32, steep staircase chain)."""
        prog, ue_pos = _lte_pos_prog()
        at = {"ue_pos": ue_pos}
        base = grad_lte_sm(
            prog, loss="neg_goodput", at=at,
            surrogate=Surrogacy(ste=False),
        )
        defaults = {
            "tx_power_dbm": np.full(2, 43.0),
            "ue_pos": ue_pos.astype(np.float64),
            "enb_pos": np.asarray(prog.enb_pos, np.float64),
            "ploss": np.array([3.2, 1.0, 46.67]),
            "sched_w": np.ones(6),
        }
        steps = {
            # (h, rtol): position probes tolerate more curvature —
            # metre-scale central differences over a chain whose soft
            # staircase bends within metres (see module note)
            "tx_power_dbm": (0.02, 0.02),
            "ue_pos": (0.5, 0.06),
            "enb_pos": (0.5, 0.06),
            "ploss": (0.002, 0.02),
            "sched_w": (0.01, 0.02),
        }
        for name, (h, rtol) in steps.items():
            def loss_at(v, name=name):
                return grad_lte_sm(
                    prog, loss="neg_goodput", at={**at, name: v},
                    surrogate=Surrogacy(ste=False),
                )["loss"]

            fd = _fd_check(
                loss_at, defaults[name], h, base["grads"][name],
                rtol=rtol, atol=3e-4,
            )
            assert np.abs(fd).max() > 0, f"{name}: degenerate FD probe"

    def test_lte_cqi_loss_differentiates_propagation(self):
        prog, ue_pos = _lte_pos_prog()
        tgt = np.linspace(4.0, 14.0, 6).astype(np.float32)
        r = grad_lte_sm(
            prog, loss="cqi_mse", target=tgt, at={"ue_pos": ue_pos},
            surrogate=Surrogacy(ste=False),
        )
        assert np.isfinite(r["grads"]["ploss"]).all()
        assert np.abs(r["grads"]["ploss"][0]) > 0


# --- batching: vmap-of-grad + the one-executable sweep ----------------------


class TestGradBatching:
    def test_as_vmap_of_grad_equals_stacked_solo(self):
        prog = _as_prog(surrogate=Surrogacy())
        cands = np.array(
            [[1e5, 1e5, 1e5], [2e5, 5e4, 1e5], [8e4, 3e5, 6e4]],
            np.float32,
        )
        batched = grad_as_flows(
            prog, KEY, 4, loss="neg_goodput",
            batch={"flow_bps": cands},
        )
        for i in range(3):
            solo = grad_as_flows(
                prog, KEY, 4, loss="neg_goodput",
                at={"flow_bps": cands[i]},
            )
            assert np.float32(solo["loss"]) == np.float32(
                batched["loss"][i]
            )
            for k in solo["grads"]:
                assert np.array_equal(
                    solo["grads"][k], batched["grads"][k][i]
                ), k

    def test_lte_vmap_of_grad_equals_stacked_solo(self):
        prog, ue_pos = _lte_pos_prog()
        at = {"ue_pos": ue_pos}
        cands = np.stack(
            [np.full(2, 40.0), np.full(2, 43.0), np.array([46.0, 38.0])]
        ).astype(np.float32)
        batched = grad_lte_sm(
            prog, loss="neg_goodput", at=at,
            batch={"tx_power_dbm": cands},
        )
        for i in range(3):
            solo = grad_lte_sm(
                prog, loss="neg_goodput",
                at={**at, "tx_power_dbm": cands[i]},
            )
            assert np.float32(solo["loss"]) == np.float32(
                batched["loss"][i]
            )
            assert np.array_equal(
                solo["grads"]["tx_power_dbm"],
                batched["grads"]["tx_power_dbm"][i],
            )

    def test_grad_sweep_is_one_launch_one_executable(self):
        """A grad-of-sweep batch: 1 device launch, 0 fresh compiles
        once warm (CompileTelemetry-pinned, the ISSUE acceptance
        row)."""
        from tpudes.obs.device import CompileTelemetry
        from tpudes.parallel.runtime import RUNTIME

        prog = _as_prog(surrogate=Surrogacy())
        scales = [0.5, 1.0, 2.0, 4.0]
        grad_as_flows(
            prog, KEY, 4, loss="neg_goodput", rate_scale=scales
        )  # warm
        l0 = RUNTIME.launches("diff_as")
        c0 = CompileTelemetry.compiles("diff_as")
        r = grad_as_flows(
            prog, KEY, 4, loss="neg_goodput", rate_scale=scales
        )
        assert RUNTIME.launches("diff_as") - l0 == 1
        assert CompileTelemetry.compiles("diff_as") - c0 == 0
        assert np.shape(r["loss"]) == (4,)
        assert r["grads"]["rate_scale"].shape == (4,)

    def test_loss_averages_requested_replicas_not_the_bucket(self):
        """Regression (review): the objective must average exactly the
        requested replicas — a 5-replica grad loss equals the engine's
        5-replica mean KPI, not the pow2 bucket's 8-row mean."""
        from tpudes.parallel.as_flows import run_as_flows

        prog = _as_prog(surrogate=Surrogacy(ste=True))
        out5 = run_as_flows(prog, KEY, replicas=5)
        want = -float(
            np.asarray(out5["goodput_bps"], np.float64)
            .mean(axis=0).sum() * 1e-6
        )
        got5 = grad_as_flows(prog, KEY, 5, loss="neg_goodput")["loss"]
        got8 = grad_as_flows(prog, KEY, 8, loss="neg_goodput")["loss"]
        assert got5 == pytest.approx(want, rel=1e-5)
        assert got5 != got8

    def test_operand_value_flips_never_recompile(self):
        """Every operand is traced: FD probes / optimizer steps reuse
        the executable (the cache key carries only program identity +
        loss + batching shape)."""
        from tpudes.obs.device import CompileTelemetry

        prog = _as_prog(surrogate=Surrogacy())
        grad_as_flows(prog, KEY, 4, loss="neg_goodput")  # warm
        c0 = CompileTelemetry.compiles("diff_as")
        for scale in (0.7, 1.3, 2.9):
            grad_as_flows(
                prog, KEY, 4, loss="neg_goodput",
                at={"flow_bps": np.asarray(prog.flow_bps) * scale},
            )
        assert CompileTelemetry.compiles("diff_as") - c0 == 0


# --- LTE expected-KPI chain vs the Monte-Carlo engine -----------------------


class TestLteForwardParity:
    def test_expected_goodput_tracks_the_engine(self):
        """The diff chain's expected per-UE goodput sits within a
        ±30 % band of the real SM engine's Monte-Carlo goodput on the
        dominant-gain toy grid (documented deviations: HARQ-IR
        retransmission gain, integer RBG quantization, the CQI-ladder
        vs MCS-ladder efficiency gap)."""
        from tpudes.diff.lte_grad import build_lte_diff, lte_default_params
        from tpudes.parallel.lte_sm import run_lte_sm

        prog = toy_lte_program(n_enb=2, n_ue=4, n_ttis=1000)
        sim_s = prog.n_ttis * 1e-3
        eng = run_lte_sm(prog, KEY)
        eng_bps = np.asarray(eng["rx_bits"], np.float64) / sim_s
        kpi = jax.jit(build_lte_diff(prog, Surrogacy(ste=True)))
        exp_bps = np.asarray(
            kpi(lte_default_params(prog))["tput_bps"], np.float64
        )
        assert eng_bps.shape == exp_bps.shape
        ratio = exp_bps / np.maximum(eng_bps, 1.0)
        assert (0.7 < ratio).all() and (ratio < 1.3).all(), ratio

    def test_gain_based_program_rejects_positional_wrt(self):
        prog = toy_lte_program(n_enb=2, n_ue=4)
        with pytest.raises(ValueError, match="positional"):
            grad_lte_sm(prog, wrt=("ue_pos",))
        # tx-power grads still work on the gain-based program
        r = grad_lte_sm(prog, loss="neg_goodput")
        assert np.isfinite(r["grads"]["tx_power_dbm"]).all()
