"""EDCA/QoS tests — upstream wifi-ac-mapping + EDCA parameter tests:
TOS classification, per-AC parameters, and priority under saturation."""


from tpudes.core import Seconds, Simulator
from tpudes.helper.applications import UdpClientHelper, UdpServerHelper
from tpudes.helper.containers import NetDeviceContainer, NodeContainer
from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
from tpudes.models.mobility import MobilityHelper
from tpudes.models.wifi import (
    WifiHelper,
    WifiMacHelper,
    YansWifiChannelHelper,
    YansWifiPhyHelper,
)
from tpudes.models.wifi.mac import EDCA_PARAMS, AcIndex, classify_ac
from tpudes.models.internet.ipv4 import Ipv4Header
from tpudes.network.packet import Packet


def test_tos_to_ac_mapping():
    # UP = TOS >> 5; the 802.11 table (qos-utils.cc)
    cases = {
        0xC0: AcIndex.AC_VO,  # UP 6
        0xE0: AcIndex.AC_VO,  # UP 7
        0x80: AcIndex.AC_VI,  # UP 4
        0xA0: AcIndex.AC_VI,  # UP 5
        0x00: AcIndex.AC_BE,  # UP 0
        0x60: AcIndex.AC_BE,  # UP 3
        0x20: AcIndex.AC_BK,  # UP 1
        0x40: AcIndex.AC_BK,  # UP 2
    }
    for tos, ac in cases.items():
        p = Packet(100)
        p.AddHeader(Ipv4Header(tos=tos))
        assert classify_ac(p) == ac, hex(tos)
    # no IP header → best effort
    assert classify_ac(Packet(10)) == AcIndex.AC_BE


def test_edca_parameter_set_is_standard():
    assert EDCA_PARAMS[AcIndex.AC_VO] == (2, 3, 7)
    assert EDCA_PARAMS[AcIndex.AC_VI] == (2, 7, 15)
    assert EDCA_PARAMS[AcIndex.AC_BE][0] == 3
    assert EDCA_PARAMS[AcIndex.AC_BK][0] == 7


def _qos_bss(sim_time=2.0):
    """AP + 1 STA; the STA carries a VO flow and a BK flow, both at
    rates that together saturate the medium."""
    nodes = NodeContainer()
    nodes.Create(2)
    mobility = MobilityHelper()
    mobility.SetPositionAllocator(
        "tpudes::RandomDiscPositionAllocator", X=0.0, Y=0.0, Rho=5.0
    )
    mobility.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    mobility.Install(nodes)
    channel = YansWifiChannelHelper.Default().Create()
    phy = YansWifiPhyHelper()
    phy.SetChannel(channel)
    wifi = WifiHelper()
    wifi.SetRemoteStationManager(
        "tpudes::ConstantRateWifiManager", DataMode="OfdmRate6Mbps"
    )
    ap_mac = WifiMacHelper()
    ap_mac.SetType("tpudes::ApWifiMac", QosSupported=True)
    ap_devs = wifi.Install(phy, ap_mac, [nodes.Get(0)])
    sta_mac = WifiMacHelper()
    sta_mac.SetType("tpudes::StaWifiMac", QosSupported=True)
    sta_devs = wifi.Install(phy, sta_mac, [nodes.Get(1)])
    InternetStackHelper().Install(nodes)
    devs = NetDeviceContainer()
    devs.Add(ap_devs.Get(0))
    devs.Add(sta_devs.Get(0))
    ifc = Ipv4AddressHelper("10.1.5.0", "255.255.255.0").Assign(devs)

    rx = {"vo": 0, "bk": 0}
    for key, port, tos in (("vo", 9, 0xC0), ("bk", 10, 0x20)):
        server = UdpServerHelper(port)
        sapps = server.Install(nodes.Get(0))
        sapps.Start(Seconds(0.0))
        sapps.Get(0).TraceConnectWithoutContext(
            "Rx", lambda *a, k=key: rx.__setitem__(k, rx[k] + 1)
        )
        client = UdpClientHelper(ifc.GetAddress(0), port)
        client.SetAttribute("MaxPackets", 0)
        client.SetAttribute("Interval", Seconds(0.002))  # 2x overload each
        client.SetAttribute("PacketSize", 1000)
        client.SetAttribute("Tos", tos)
        client.Install(nodes.Get(1)).Start(Seconds(0.2))
    return nodes, rx


def test_voice_outranks_background_under_saturation():
    nodes, rx = _qos_bss()
    Simulator.Stop(Seconds(2.0))
    Simulator.Run()
    assert rx["vo"] > 0 and rx["bk"] >= 0
    # strict-priority head selection: VO drains first, BK gets leftovers
    assert rx["vo"] >= 3 * max(rx["bk"], 1), rx


def test_qos_off_treats_flows_equally():
    nodes, rx = _qos_bss()
    # flip QoS off on the STA: everything rides AC_BE FIFO, no
    # differentiation (toggling is safe — one queue representation)
    mac = nodes.Get(1).GetDevice(0).GetMac()
    mac.SetAttribute("QosSupported", False)
    Simulator.Stop(Seconds(2.0))
    Simulator.Run()
    assert rx["vo"] > 0 and rx["bk"] > 0
    ratio = rx["vo"] / max(rx["bk"], 1)
    assert 0.5 < ratio < 2.0, rx