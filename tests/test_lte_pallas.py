"""ISSUE-6 gates: the fused Pallas LTE TTI kernel chain.

- **One math core, two lowerings**: ``TPUDES_PALLAS=1`` (the Pallas
  kernel, interpret-mode on CPU — the exact body Mosaic compiles on
  TPU) and ``=0`` (the plain XLA lowering) are BIT-identical for every
  scheduler id, under bucketing on and off, and across the 8-point
  config-axis scheduler sweep.
- **Flags are cache-key components**: flipping the kill switch or the
  precision mode compiles a distinct runner — never reuses a stale
  executable for different arithmetic.
- **Mixed precision**: the bf16 mode sweeps with ≤1 compile and one
  launch (the CI multi-device smoke rides this), stays within the
  engine-level throughput budget of the f32 mode, and holds the same
  HARQ conservation laws.
- **Per-stage profile harness**: profile_sm_stages times every stage
  of the chain and records to obs.KernelProfile.
- **lower_lte_sm horizon warning**: the compile-amortization boundary
  (COMPILE_AMORTIZE_TTIS) warns below the line, not at it.
"""

import dataclasses
import warnings

import jax
import numpy as np
import pytest

from tpudes.obs.device import CompileTelemetry, KernelProfile
from tpudes.parallel.kernels_pallas import (
    build_sm_consts,
    build_sm_step_fn,
    pallas_enabled,
    sm_init_state,
)
from tpudes.parallel.lte_sm import SM_SCHED_IDS, run_lte_sm
from tpudes.parallel.programs import toy_lte_program
from tpudes.parallel.runtime import RUNTIME

KEY = jax.random.PRNGKey(11)

OUT_KEYS = ("rx_bits", "ok", "new_tbs", "retx", "drops")


@pytest.fixture(autouse=True)
def _fresh_runtime():
    RUNTIME.clear()
    yield
    RUNTIME.clear()


def _prog(**kw):
    kw.setdefault("n_enb", 2)
    kw.setdefault("n_ue", 6)
    kw.setdefault("n_ttis", 150)
    return toy_lte_program(**kw)


def _assert_same(a, b, msg=""):
    for k in OUT_KEYS:
        np.testing.assert_array_equal(a[k], b[k], err_msg=f"{msg}: {k}")


def test_pallas_knob_default_on_and_kill_switch(monkeypatch):
    monkeypatch.delenv("TPUDES_PALLAS", raising=False)
    assert pallas_enabled()
    for off in ("0", "false", "no", "OFF"):
        monkeypatch.setenv("TPUDES_PALLAS", off)
        assert not pallas_enabled()
    monkeypatch.setenv("TPUDES_PALLAS", "1")
    assert pallas_enabled()


@pytest.mark.parametrize("sched", list(SM_SCHED_IDS))
def test_interpret_mode_bit_parity_every_scheduler(monkeypatch, sched):
    """The Pallas kernel (interpret on CPU) and the XLA fallback run the
    SAME math core: bit equality per scheduler id."""
    prog = _prog(scheduler=sched)
    monkeypatch.setenv("TPUDES_PALLAS", "1")
    on = run_lte_sm(prog, KEY)
    monkeypatch.setenv("TPUDES_PALLAS", "0")
    off = run_lte_sm(prog, KEY)
    _assert_same(on, off, sched)


def test_step_fn_bit_parity_at_kernel_level():
    """Below the engine: one fused step, both lowerings, same state in,
    bit-identical state out (including the f32 accumulators)."""
    prog = _prog()
    consts = build_sm_consts(prog)
    s = sm_init_state(prog.n_enb, prog.n_ue)
    coin = jax.random.uniform(KEY, (prog.n_ue,))[None, :]
    sid = jax.numpy.int32(0)
    for t in range(3):
        t_j = jax.numpy.int32(t)
        s_p = build_sm_step_fn(consts, True)(s, coin, t_j, sid)
        s_x = build_sm_step_fn(consts, False)(s, coin, t_j, sid)
        for k in s_p:
            np.testing.assert_array_equal(
                np.asarray(s_p[k]), np.asarray(s_x[k]), err_msg=k
            )
        s = s_p


@pytest.mark.parametrize("bucketing", ["1", "0"])
def test_ab_equality_under_bucketing(monkeypatch, bucketing):
    """TPUDES_PALLAS=0 A/B equality composed with the replica-axis
    bucketing knob: 3 replicas pad to 4 (or not at all) identically in
    both kernel modes."""
    monkeypatch.setenv("TPUDES_BUCKETING", bucketing)
    prog = _prog()
    monkeypatch.setenv("TPUDES_PALLAS", "1")
    on = run_lte_sm(prog, KEY, replicas=3)
    monkeypatch.setenv("TPUDES_PALLAS", "0")
    off = run_lte_sm(prog, KEY, replicas=3)
    assert on["rx_bits"].shape == (3, prog.n_ue)
    _assert_same(on, off, f"bucketing={bucketing}")


def test_ab_equality_8_point_scheduler_sweep(monkeypatch):
    """The config-axis megabatch sweeps identically through both
    lowerings — point by point, bit for bit."""
    prog = _prog()
    scheds = list(SM_SCHED_IDS)[:8]
    monkeypatch.setenv("TPUDES_PALLAS", "1")
    on = run_lte_sm(prog, KEY, replicas=2, schedulers=scheds)
    monkeypatch.setenv("TPUDES_PALLAS", "0")
    off = run_lte_sm(prog, KEY, replicas=2, schedulers=scheds)
    assert len(on) == len(off) == 8
    for s, a, b in zip(scheds, on, off):
        _assert_same(a, b, s)


def test_pallas_flag_is_a_cache_key_component(monkeypatch):
    """Flipping the kill switch compiles a SECOND runner instead of
    reusing the other mode's executable (stale-arithmetic hazard)."""
    prog = _prog(n_ttis=40)
    monkeypatch.setenv("TPUDES_PALLAS", "1")
    run_lte_sm(prog, KEY)
    assert RUNTIME.size("lte_sm") == 1
    monkeypatch.setenv("TPUDES_PALLAS", "0")
    run_lte_sm(prog, KEY)
    assert RUNTIME.size("lte_sm") == 2
    # and back: a cache HIT, not a third entry
    monkeypatch.setenv("TPUDES_PALLAS", "1")
    run_lte_sm(prog, KEY)
    assert RUNTIME.size("lte_sm") == 2


def test_precision_is_a_cache_key_component():
    prog = _prog(n_ttis=40)
    run_lte_sm(prog, KEY)
    run_lte_sm(dataclasses.replace(prog, precision="bf16"), KEY)
    assert RUNTIME.size("lte_sm") == 2


def test_invalid_precision_refused():
    with pytest.raises(ValueError, match="precision"):
        run_lte_sm(dataclasses.replace(_prog(), precision="f16"), KEY)


# --- mixed precision ---------------------------------------------------


def test_bf16_sweep_one_launch_one_compile():
    """The CI mixed-precision smoke as a test: an 8-point scheduler
    sweep at bf16 is ONE launch paying at most ONE fresh compile."""
    prog = dataclasses.replace(_prog(), precision="bf16")
    c0 = CompileTelemetry.compiles("lte_sm")
    results = run_lte_sm(
        prog, KEY, replicas=2, schedulers=list(SM_SCHED_IDS)[:8]
    )
    assert RUNTIME.launches("lte_sm") == 1
    assert CompileTelemetry.compiles("lte_sm") - c0 <= 1
    assert len(results) == 8


def test_bf16_engine_outcome_within_budget():
    """Engine-level budget: bf16 rounds the SINR/metric/BLER chain but
    the aggregate served traffic stays within a few percent of f32, and
    the HARQ conservation law holds unchanged."""
    prog = _prog(n_ue=8, n_ttis=400)
    f32 = run_lte_sm(prog, KEY, replicas=4)
    bf16 = run_lte_sm(
        dataclasses.replace(prog, precision="bf16"), KEY, replicas=4
    )
    a = float(f32["rx_bits"].sum())
    b = float(bf16["rx_bits"].sum())
    assert b == pytest.approx(a, rel=0.10), (a, b)
    # conservation: decoded + dropped never exceeds transmissions
    assert (
        bf16["ok"] + bf16["drops"] <= bf16["new_tbs"] + bf16["retx"]
    ).all()


def test_bf16_and_f32_share_no_executable(monkeypatch):
    """bf16 arithmetic must be a different program in BOTH kernel
    modes (precision × pallas = 4 distinct runners)."""
    prog = _prog(n_ttis=40)
    for pallas in ("1", "0"):
        monkeypatch.setenv("TPUDES_PALLAS", pallas)
        for precision in ("f32", "bf16"):
            run_lte_sm(
                dataclasses.replace(prog, precision=precision), KEY
            )
    assert RUNTIME.size("lte_sm") == 4


# --- per-stage profile harness ----------------------------------------


def test_profile_sm_stages_records_every_stage():
    from tpudes.parallel.kernels_pallas import profile_sm_stages

    KernelProfile.reset()
    out = profile_sm_stages(_prog(), replicas=2, iters=2, warm_ttis=4)
    expect = {
        "coin_prng", "admit_retx", "sched_dispatch", "sinr_cqi_harq",
        "harq_update", "fused_step",
    }
    assert expect <= set(out)
    # measured programs are strictly positive; the marginal deltas are
    # clamped at 0 (separately compiled prefixes can fuse differently)
    assert out["coin_prng"] > 0 and out["admit_retx"] > 0
    assert out["fused_step"] > 0
    assert all(out[k] >= 0.0 for k in expect)
    assert out["pallas"] == pallas_enabled()
    recorded = KernelProfile.stages("lte_sm")
    assert expect <= set(recorded)
    snap = KernelProfile.snapshot()["lte_sm"]
    assert snap["fused_step"]["batch"] == 2


# --- the lower_lte_sm compile-amortization warning ---------------------


def _helper_scenario():
    import math as _math

    from tpudes.helper.containers import NodeContainer
    from tpudes.models.lte import LteHelper
    from tpudes.models.mobility import (
        ListPositionAllocator,
        MobilityHelper,
        Vector,
    )

    lte = LteHelper()
    enbs = NodeContainer()
    enbs.Create(1)
    ues = NodeContainer()
    ues.Create(2)
    ea = ListPositionAllocator()
    ea.Add(Vector(0.0, 0.0, 30.0))
    me = MobilityHelper()
    me.SetPositionAllocator(ea)
    me.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    me.Install(enbs)
    ua = ListPositionAllocator()
    for i in range(2):
        ua.Add(Vector(50.0 * _math.cos(i), 50.0 * _math.sin(i), 1.5))
    mu = MobilityHelper()
    mu.SetPositionAllocator(ua)
    mu.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    mu.Install(ues)
    lte.InstallEnbDevice(enbs)
    devs = lte.InstallUeDevice(ues)
    ue_list = [devs.Get(i) for i in range(devs.GetN())]
    lte.Attach(ue_list)
    lte.ActivateDataRadioBearer(ue_list)
    return lte


def test_lower_warns_below_compile_amortization_horizon():
    from tpudes.parallel.lte_sm import COMPILE_AMORTIZE_TTIS, lower_lte_sm

    lte = _helper_scenario()
    with pytest.warns(UserWarning, match="one-time XLA compile"):
        lower_lte_sm(lte, (COMPILE_AMORTIZE_TTIS - 1) / 1000.0)


def test_lower_silent_at_the_boundary():
    from tpudes.parallel.lte_sm import COMPILE_AMORTIZE_TTIS, lower_lte_sm

    lte = _helper_scenario()
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        prog = lower_lte_sm(lte, COMPILE_AMORTIZE_TTIS / 1000.0)
    assert prog.n_ttis == COMPILE_AMORTIZE_TTIS
