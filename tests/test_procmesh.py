"""Multi-process device meshes (ISSUE-9, ROADMAP item 4(a)).

``jax.distributed``-backed scale-out: N local CPU processes join one
coordinator (the identical code path is the multi-host TPU path), the
replica axis splits into contiguous per-process blocks that are
BIT-equal to the single-launch rows, and the serving layer routes
coalesced batches across member processes.
"""

import numpy as np
import pytest

import jax

import _distributed_targets as targets

from tpudes.parallel.procmesh import (
    ProcessMesh,
    launch_process_mesh,
    process_slice,
    supports_global_computation,
)


# --- slicing math (pure host) ----------------------------------------------


def test_process_slice_balanced_cover():
    for n in (1, 5, 8, 13):
        for k in (1, 2, 3, 4):
            slices = [process_slice(n, k, p) for p in range(k)]
            # contiguous cover of [0, n)
            assert slices[0][0] == 0 and slices[-1][1] == n
            for (a, b), (c, d) in zip(slices, slices[1:]):
                assert b == c
            sizes = [hi - lo for lo, hi in slices]
            assert max(sizes) - min(sizes) <= 1


def test_process_mesh_slice_bounds():
    pm = ProcessMesh(1, 2, "127.0.0.1:1")
    assert pm.slice_bounds(5) == (3, 5)


def test_supports_global_computation_gates_cpu():
    # the test harness pins the CPU backend; accelerator backends take
    # the one-computation global-mesh path instead
    assert supports_global_computation() is False


# --- 2-process jax.distributed smoke ---------------------------------------


@pytest.mark.slow
def test_two_process_mesh_global_devices():
    outs = launch_process_mesh(targets.procmesh_devices, 2,
                               timeout_s=240.0)
    assert [o["process_id"] for o in outs] == [0, 1]
    for o in outs:
        assert o["num_processes"] == 2
        # the invariant: global devices = sum of members' local devices
        assert o["global_devices"] == 2 * o["local_devices"]
        assert o["backend"] == "cpu"


@pytest.mark.slow
def test_replica_blocks_bit_equal_to_single_launch():
    """Each member runs its block at the global offset; the stitched
    rows equal one big launch (fold_in purity in the global index)."""
    from tpudes.parallel.wired import run_wired, wired_chain

    R = 5
    outs = launch_process_mesh(
        targets.procmesh_replica_slice, 2, args=(R,), timeout_s=240.0
    )
    assert [(o["lo"], o["hi"]) for o in outs] == [(0, 3), (3, 5)]
    stitched = np.concatenate([o["deliver"] for o in outs], axis=0)
    prog = wired_chain(n_links=4, n_flows=2, n_slots=300, jitter_slots=3)
    ref = run_wired(prog, jax.random.key(11), replicas=R)
    assert (stitched == ref["deliver_slot"]).all()


# --- serving router --------------------------------------------------------


@pytest.mark.slow
def test_study_server_routes_batches_across_processes():
    """A coalesced batch's config points split across the mesh: block 0
    local, the rest over the framed pipes to serve_studies members —
    reassembled bit-equal to solo launches."""
    outs = launch_process_mesh(
        targets.procmesh_serving_router, 2, args=(4,), timeout_s=300.0
    )
    rank0, rank1 = outs
    assert rank0["equal"], "routed results diverged from solo launches"
    assert rank0["routed_batches"] >= 1
    assert rank0["routed_points"] >= 1
    assert rank1["served"] >= 1


# --- router unit behavior (no processes) -----------------------------------


def test_router_declines_unroutable_batches():
    from tpudes.serving import ProcessRouter

    router = ProcessRouter({})
    assert router.launch([], [1, 2]) is None  # no members

    class _Desc:
        spec = None

    class _Req:
        desc = _Desc()

    router2 = ProcessRouter({1: object()})
    # spec-less study stays host-local
    assert router2.launch([_Req()], [1, 2]) is None
    # single-point batches are not worth splitting
    assert router2.launch([_Req()], [1]) is None


def test_closed_router_never_routes():
    from tpudes.serving import ProcessRouter

    router = ProcessRouter({})
    router.close()
    assert router._closed
    assert router.launch([], [1, 2]) is None
