"""FFR tests — upstream lte-test-frequency-reuse strategy: hard reuse
confines each cell to its subband and lifts edge SINR/CQI."""

import numpy as np

from tpudes.core import Seconds, Simulator
from tpudes.helper.containers import NodeContainer
from tpudes.models.lte import LteHelper
from tpudes.models.lte.ffr import LteFrHardAlgorithm, LteFrNoOpAlgorithm
from tpudes.models.mobility import (
    ListPositionAllocator,
    MobilityHelper,
    Vector,
)


def test_hard_reuse_partitions_are_disjoint_and_cover():
    fr = LteFrHardAlgorithm(ReuseFactor=3)
    bands = [fr.allowed_rbgs(c, 13) for c in range(3)]
    flat = sorted(r for b in bands for r in b)
    assert flat == list(range(13)), "subbands must cover every RBG"
    for i in range(3):
        for j in range(i + 1, 3):
            assert not set(bands[i]) & set(bands[j])
    # cells repeat mod the reuse factor
    assert fr.allowed_rbgs(3, 13) == bands[0]
    assert LteFrNoOpAlgorithm().allowed_rbgs(1, 13) == list(range(13))


def _two_close_cells(ffr: bool):
    """Two eNBs 120 m apart, one edge UE each at the midpoint — the
    worst-case co-channel geometry."""
    lte = LteHelper()
    if ffr:
        lte.SetFfrAlgorithmType("tpudes::LteFrHardAlgorithm")
        lte.SetFfrAlgorithmAttribute("ReuseFactor", 2)
    enbs = NodeContainer()
    enbs.Create(2)
    ues = NodeContainer()
    ues.Create(2)
    ea = ListPositionAllocator()
    ea.Add(Vector(0, 0, 30))
    ea.Add(Vector(120, 0, 30))
    me = MobilityHelper()
    me.SetPositionAllocator(ea)
    me.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    me.Install(enbs)
    ua = ListPositionAllocator()
    ua.Add(Vector(55, 0, 1.5))    # edge of cell 1
    ua.Add(Vector(65, 0, 1.5))    # edge of cell 2
    mu = MobilityHelper()
    mu.SetPositionAllocator(ua)
    mu.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    mu.Install(ues)
    lte.InstallEnbDevice(enbs)
    ue_devs = lte.InstallUeDevice(ues)
    ue_list = [ue_devs.Get(i) for i in range(2)]
    lte.Attach(ue_list)
    lte.ActivateDataRadioBearer(ue_list, mode="sm")
    Simulator.Stop(Seconds(0.1))
    Simulator.Run()
    return lte.controller


def test_hard_reuse_confines_allocations_to_subbands():
    ctrl = _two_close_cells(ffr=True)
    alloc = np.asarray(ctrl.last_alloc["dl"])      # (U, n_rb)
    n_rb = alloc.shape[1]
    half = ((ctrl.n_rbg // 2) * ctrl.rbg_size)
    # UE 0 serves from cell 0 (band 0), UE 1 from cell 1 (band 1)
    assert alloc[0, half:].sum() == 0, "cell 0 leaked into band 1"
    assert alloc[1, :half].sum() == 0, "cell 1 leaked into band 0"
    assert alloc[0].sum() > 0 and alloc[1].sum() > 0


def test_hard_reuse_lifts_edge_cqi():
    cqi_reuse1 = _two_close_cells(ffr=False)._cqi_dl.copy()
    cqi_hard = _two_close_cells(ffr=True)._cqi_dl.copy()
    # midpoint UEs drown in co-channel interference at reuse 1; hard
    # reuse removes the dominant interferer on their subband
    assert cqi_hard.min() > cqi_reuse1.min()
    assert cqi_hard.mean() > cqi_reuse1.mean() + 3