"""Spectrum layer tests.

SURVEY.md §2.4: upstream's spectrum module is validated by value-algebra
unit tests (spectrum-value arithmetic, integration) and by delivery
tests through SingleModelSpectrumChannel (tx PSD → loss chain → rx PSD
at every endpoint after the propagation delay).  Same coverage here.
"""

import numpy as np
import pytest

from tpudes.models.spectrum import (
    BandInfo,
    ConstantSpectrumPropagationLossModel,
    SingleModelSpectrumChannel,
    SpectrumModel,
    SpectrumPhy,
    SpectrumSignalParameters,
    SpectrumValue,
    lte_spectrum_model,
)


def _model(n=4, f0=2.0e9, width=180e3):
    return SpectrumModel.FromCenters(
        [f0 + i * width for i in range(n)], width
    )


class TestSpectrumValue:
    def test_arithmetic_elementwise(self):
        m = _model()
        a = SpectrumValue(m, [1.0, 2.0, 3.0, 4.0])
        b = SpectrumValue(m, [4.0, 3.0, 2.0, 1.0])
        np.testing.assert_allclose((a + b).values, 5.0)
        np.testing.assert_allclose((a - b).values, [-3.0, -1.0, 1.0, 3.0])
        np.testing.assert_allclose((a * 2.0).values, [2.0, 4.0, 6.0, 8.0])
        np.testing.assert_allclose((a / b).values, [0.25, 2 / 3, 1.5, 4.0])
        a += b
        np.testing.assert_allclose(a.values, 5.0)

    def test_cross_model_arithmetic_rejected(self):
        a = SpectrumValue(_model(), [1.0] * 4)
        b = SpectrumValue(_model(), [1.0] * 4)  # different uid
        with pytest.raises(ValueError):
            _ = a + b

    def test_copy_isolated(self):
        a = SpectrumValue(_model(), [1.0] * 4)
        c = a.Copy()
        c[0] = 99.0
        assert a[0] == 1.0

    def test_total_power_integrates_bandwidth(self):
        m = _model(n=3, width=100.0)
        v = SpectrumValue(m, [1.0, 2.0, 3.0])  # W/Hz over 100 Hz bands
        assert v.TotalPowerW() == pytest.approx(600.0)

    def test_band_info(self):
        b = BandInfo(90.0, 100.0, 110.0)
        assert b.width == pytest.approx(20.0)


class TestSpectrumModel:
    def test_orthogonality(self):
        a = SpectrumModel.FromCenters([1e9, 1.001e9], 1e6)
        b = SpectrumModel.FromCenters([2e9, 2.001e9], 1e6)
        assert a.IsOrthogonal(b)
        assert not a.IsOrthogonal(a)

    def test_lte_grid(self):
        m = lte_spectrum_model(25, 2.12e9)
        assert m.GetNumBands() == 25
        np.testing.assert_allclose(m.band_widths, 180e3)
        # grid is centered on the carrier
        assert np.mean(m.center_frequencies) == pytest.approx(2.12e9)


class _ProbePhy(SpectrumPhy):
    """Records every StartRx delivery (psd values + arrival time)."""

    def __init__(self, model):
        super().__init__()
        self._model = model
        self.rx = []

    def GetRxSpectrumModel(self):
        return self._model

    def StartRx(self, params):
        from tpudes.core.simulator import Simulator

        self.rx.append(
            (Simulator.Now().GetSeconds(), params.psd.values.copy(),
             params.payload)
        )


def _node_with_phy(model, channel, x):
    from tpudes.models.mobility import ConstantPositionMobilityModel, Vector
    from tpudes.network.node import Node

    node = Node()
    mob = ConstantPositionMobilityModel()
    mob.SetPosition(Vector(x, 0.0, 0.0))
    node.AggregateObject(mob)
    phy = _ProbePhy(model)
    phy.SetMobility(mob)

    class _Dev:
        def GetNode(self):
            return node

    phy.SetDevice(_Dev())
    phy.SetChannel(channel)
    return phy


class TestSingleModelSpectrumChannel:
    def test_delivery_applies_loss_and_delay(self):
        from tpudes.core.simulator import Simulator
        from tpudes.core.nstime import Seconds
        from tpudes.models.propagation import (
            ConstantSpeedPropagationDelayModel,
            FriisPropagationLossModel,
        )

        model = _model(n=4, f0=2.12e9)
        ch = SingleModelSpectrumChannel()
        loss = FriisPropagationLossModel(Frequency=2.12e9)
        ch.AddPropagationLossModel(loss)
        ch.SetPropagationDelayModel(ConstantSpeedPropagationDelayModel())
        tx = _node_with_phy(model, ch, 0.0)
        rx1 = _node_with_phy(model, ch, 300.0)
        rx2 = _node_with_phy(model, ch, 600.0)
        assert ch.GetNDevices() == 3

        psd = SpectrumValue(model, [1e-9] * 4)
        params = SpectrumSignalParameters(psd, duration_s=1e-3, tx_phy=tx)
        params.payload = "tb-1"

        def fire():
            ch.StartTx(params)

        Simulator.Schedule(Seconds(0.0), fire)
        Simulator.Stop(Seconds(0.1))
        Simulator.Run()

        # the sender does not hear itself; both receivers got one signal
        assert tx.rx == []
        assert len(rx1.rx) == 1 and len(rx2.rx) == 1
        t1, psd1, payload1 = rx1.rx[0]
        t2, psd2, _ = rx2.rx[0]
        # propagation delay at c: 300 m → 1 µs, 600 m → 2 µs (the
        # simulator clock quantizes to whole nanoseconds)
        assert t1 == pytest.approx(300.0 / 299792458.0, abs=1e-9)
        assert t2 == pytest.approx(600.0 / 299792458.0, abs=1e-9)
        # Friis: doubling the distance costs 6.02 dB
        ratio_db = 10 * np.log10(psd1[0] / psd2[0])
        assert ratio_db == pytest.approx(6.0206, abs=0.01)
        # rx PSD = tx PSD × linear gain from the loss model
        gain_db = loss.CalcRxPower(0.0, tx.GetMobility(), rx1.GetMobility())
        np.testing.assert_allclose(psd1, 1e-9 * 10 ** (gain_db / 10.0))
        assert payload1 == "tb-1"
        # original tx PSD untouched (per-rx copies)
        np.testing.assert_allclose(psd.values, 1e-9)

    def test_spectrum_loss_chain(self):
        from tpudes.core.simulator import Simulator
        from tpudes.core.nstime import Seconds

        model = _model()
        ch = SingleModelSpectrumChannel()
        ch.AddSpectrumPropagationLossModel(
            ConstantSpectrumPropagationLossModel(loss_db=13.0)
        )
        tx = _node_with_phy(model, ch, 0.0)
        rx = _node_with_phy(model, ch, 10.0)
        psd = SpectrumValue(model, [2e-9] * 4)
        Simulator.Schedule(
            Seconds(0.0),
            lambda: ch.StartTx(SpectrumSignalParameters(psd, 1e-3, tx)),
        )
        Simulator.Stop(Seconds(0.01))
        Simulator.Run()
        _, got, _ = rx.rx[0]
        np.testing.assert_allclose(got, 2e-9 * 10 ** (-1.3), rtol=1e-9)

    def test_mixed_models_rejected(self):
        ch = SingleModelSpectrumChannel()
        _node_with_phy(_model(), ch, 0.0)
        with pytest.raises(ValueError):
            _node_with_phy(_model(), ch, 1.0)  # different uid
