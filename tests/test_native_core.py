"""Native C event core tests.

The contract: CppHeapScheduler + the C dispatch loop are drop-in
replacements for the Python heap + Python loop — identical event
ordering, cancel semantics, stop behavior, injection handling.  The
rest of the suite exercises the native path implicitly (the default
SchedulerType upgrades to it), so these tests pin the *equivalence*
and the explicit fallbacks.
"""

import random

import pytest

from tpudes.core.event import Event
from tpudes.core.global_value import GlobalValue
from tpudes.core.nstime import Seconds
from tpudes.core.scheduler import HeapScheduler, create_scheduler
from tpudes.core.simulator import Simulator

native = pytest.importorskip("tpudes.core.native").get_native()
if native is None:
    pytest.skip("native event core not built", allow_module_level=True)

from tpudes.core.scheduler import CppHeapScheduler  # noqa: E402


def test_default_heap_upgrades_to_native():
    assert isinstance(
        create_scheduler("tpudes::HeapScheduler"), CppHeapScheduler
    )
    assert isinstance(
        create_scheduler("tpudes::PyHeapScheduler"), HeapScheduler
    )


def test_native_and_python_heaps_pop_identically():
    rnd = random.Random(3)
    events = [
        Event(rnd.randrange(10_000), uid, 0, lambda: None, ())
        for uid in range(2_000)
    ]
    a, b = CppHeapScheduler(), HeapScheduler()
    for ev in events:
        a.Insert(ev)
        b.Insert(ev)
    # cancel a random subset through the shared Event objects
    for ev in rnd.sample(events, 300):
        ev.cancel()
    out_a, out_b = [], []
    while not a.IsEmpty():
        out_a.append(a.RemoveNext())
    while not b.IsEmpty():
        out_b.append(b.RemoveNext())
    assert [(e.ts, e.uid) for e in out_a] == [(e.ts, e.uid) for e in out_b]
    assert len(out_a) == 1_700


def test_native_run_equals_python_run_event_for_event():
    """The same scenario through both loops produces the same invocation
    sequence, timestamps, and final event count."""

    def scenario():
        log = []
        impl = Simulator.GetImpl()

        def tick(i):
            log.append((Simulator.NowTicks(), i, impl.current_context))
            if i < 50:
                Simulator.Schedule(Seconds(0.001 * ((i * 7) % 5 + 1)), tick, i + 1)
                Simulator.ScheduleWithContext(
                    i % 4, Seconds(0.002), tick, i + 100
                )

        Simulator.Schedule(Seconds(0.01), tick, 0)
        Simulator.Stop(Seconds(0.5))
        Simulator.Run()
        count = Simulator.GetEventCount()
        Simulator.Destroy()
        return log, count

    from tpudes.core.world import reset_world

    reset_world()
    GlobalValue.Bind("SchedulerType", "tpudes::CppHeapScheduler")
    log_c, count_c = scenario()
    reset_world()
    GlobalValue.Bind("SchedulerType", "tpudes::PyHeapScheduler")
    log_py, count_py = scenario()
    assert log_c == log_py
    assert count_c == count_py
    assert len(log_c) > 100


def test_native_loop_honors_stop_and_event_count():
    GlobalValue.Bind("SchedulerType", "tpudes::CppHeapScheduler")
    seen = []

    def cb(i):
        seen.append((i, Simulator.GetEventCount()))
        if i == 3:
            Simulator.Stop()  # immediate stop from inside the C loop

    for i in range(10):
        Simulator.Schedule(Seconds(0.1 * (i + 1)), cb, i)
    Simulator.Run()
    assert [i for i, _ in seen] == [0, 1, 2, 3]
    # GetEventCount was live inside each callback (ShowProgress contract)
    assert [c for _, c in seen] == [1, 2, 3, 4]


def test_native_loop_yields_for_cross_thread_injection():
    import threading

    GlobalValue.Bind("SchedulerType", "tpudes::CppHeapScheduler")
    impl = Simulator.GetImpl()
    hits = []

    def slow_event():
        # inject from another thread while the C loop is running
        t = threading.Thread(
            target=impl.ScheduleWithContextThreadSafe,
            args=(7, 0, hits.append, ("injected",)),
        )
        t.start()
        t.join()

    Simulator.Schedule(Seconds(0.1), slow_event)
    Simulator.Schedule(Seconds(0.2), hits.append, "second")
    Simulator.Run()
    assert hits == ["injected", "second"]


def test_callback_exception_propagates_cleanly():
    GlobalValue.Bind("SchedulerType", "tpudes::CppHeapScheduler")

    def boom():
        raise RuntimeError("inside C loop")

    Simulator.Schedule(Seconds(0.1), boom)
    with pytest.raises(RuntimeError, match="inside C loop"):
        Simulator.Run()


def test_engine_with_pending_events_is_collectable():
    """impl → scheduler → CHeap → Event(fn=impl._do_stop) → impl is a
    cycle; without GC support in the C type the engine leaked per
    simulation (r4 review, reproduced with a weakref probe)."""
    import gc
    import weakref

    GlobalValue.Bind("SchedulerType", "tpudes::CppHeapScheduler")
    Simulator.Stop(Seconds(1.0))
    Simulator.Stop(Seconds(2.0))  # stays pending after the first fires
    Simulator.Run()
    ref = weakref.ref(Simulator.GetImpl())
    Simulator.Destroy()
    gc.collect()
    assert ref() is None, "engine leaked through the native heap"


def test_len_is_live_count_and_read_only():
    s = CppHeapScheduler()
    evs = [Event(i, i, 0, lambda: None, ()) for i in range(10)]
    for ev in evs:
        s.Insert(ev)
    evs[0].cancel()
    evs[5].cancel()
    assert len(s) == 8
    # len() must not purge: the cancelled head is still popped over
    assert s._h.size() == 10
    assert len(s) == 8


def test_native_mrg32k3a_is_bit_identical_to_python():
    """Every simulation draw must be identical whichever RandU01
    implementation runs — replica results cannot depend on whether the
    C core built."""
    from tpudes.core.rng import RngStream

    a = RngStream(42, 5, 2)
    b = RngStream(42, 5, 2)
    b._native = False   # force the pure-Python recurrence
    assert [a.RandU01() for _ in range(50_000)] == [
        b.RandU01() for _ in range(50_000)
    ]
    assert a._native is not False, "native path did not engage"


def test_rng_stream_state_survives_native_advancement_and_pickle():
    """get_state()/pickle must reflect the TRUE position even after the
    C recurrence has been advancing the stream (r4 review: _s1/_s2
    froze at seed time)."""
    import pickle

    from tpudes.core.rng import RngStream

    a = RngStream(9, 2, 1)
    for _ in range(1234):
        a.RandU01()
    clone = pickle.loads(pickle.dumps(a))
    assert [clone.RandU01() for _ in range(100)] == [
        a.RandU01() for _ in range(100)
    ], "a pickled stream must continue, not rewind"
    s = a.get_state()
    b = RngStream.__new__(RngStream)
    b._s1, b._s2, b._native = list(s[:3]), list(s[3:]), False
    assert b.RandU01() == a.RandU01()


def test_no_native_env_falls_back(monkeypatch):
    import tpudes.core.native as nat

    monkeypatch.setattr(nat, "_tried", False)
    monkeypatch.setattr(nat, "_cached", None)
    monkeypatch.setenv("TPUDES_NO_NATIVE", "1")
    assert nat.get_native() is None
    assert isinstance(
        create_scheduler("tpudes::HeapScheduler"), HeapScheduler
    )
    # restore the real module for subsequent tests
    monkeypatch.delenv("TPUDES_NO_NATIVE")
    monkeypatch.setattr(nat, "_tried", False)
