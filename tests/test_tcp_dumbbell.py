"""TCP-dumbbell replica engine tests (BASELINE config #2).

Mirrors upstream's tcp-variants-comparison validation strategy: the
scalar DES (real sockets) is the oracle; the device packet-slot model
must match it statistically (aggregate goodput) and reproduce each
variant's qualitative signature (Vegas' empty queue, Scalable's
aggression), plus structural invariants (conservation, determinism,
mesh execution).
"""

import jax
import numpy as np
import pytest

from tpudes.core import Seconds, Simulator
from tpudes.parallel.tcp_dumbbell import (
    UnliftableDumbbellError,
    lower_dumbbell,
    run_tcp_dumbbell,
)
from tpudes.scenarios import build_dumbbell

SIM_S = 4.0


def _lowered(n_flows=4, variant="TcpNewReno", rate="5Mbps", **kw):
    build_dumbbell(n_flows, SIM_S, variant=variant, bottleneck_rate=rate, **kw)
    return lower_dumbbell(SIM_S)


def test_lowering_reads_graph_parameters():
    prog = _lowered(3, rate="5Mbps", queue="50p", seg_bytes=500)
    assert prog.n_flows == 3
    assert prog.queue_cap == 50
    assert prog.seg_bytes == 500
    # τ = (500+40)·8 / 5e6
    assert prog.slot_s == pytest.approx(540 * 8 / 5e6)
    # access 100Mbps / bottleneck 5Mbps
    assert prog.burst_cap == 20
    assert prog.n_slots == pytest.approx(SIM_S / prog.slot_s, abs=1)


def test_lowering_rejects_non_dumbbell_graphs():
    from tpudes.core.world import reset_world
    from tpudes.helper.containers import NodeContainer

    nodes = NodeContainer()
    nodes.Create(2)
    with pytest.raises(UnliftableDumbbellError):
        lower_dumbbell(1.0)
    reset_world()
    # access slower than bottleneck → leaf-side queueing unrepresentable
    build_dumbbell(2, SIM_S, bottleneck_rate="5Mbps", access_rate="1Mbps")
    with pytest.raises(UnliftableDumbbellError):
        lower_dumbbell(SIM_S)


def test_conservation_and_utilization():
    prog = _lowered(4)
    out = run_tcp_dumbbell(prog, jax.random.PRNGKey(0), replicas=8)
    delivered = np.asarray(out["delivered"])
    assert (delivered > 0).all(), "every flow must make progress"
    # the bottleneck serves ≤ 1 packet per slot
    assert (delivered.sum(1) <= prog.n_slots).all()
    # backlogged loss-based flows fill the pipe: ≥ 85% utilization
    util = delivered.sum(1) / prog.n_slots
    assert (util > 0.85).all(), util


def test_same_key_is_deterministic():
    prog = _lowered(2)
    a = run_tcp_dumbbell(prog, jax.random.PRNGKey(7), replicas=4)
    b = run_tcp_dumbbell(prog, jax.random.PRNGKey(7), replicas=4)
    np.testing.assert_array_equal(
        np.asarray(a["delivered"]), np.asarray(b["delivered"])
    )


def test_variant_signatures():
    from tpudes.core.world import reset_world

    outs, progs = {}, {}
    for v in ("TcpNewReno", "TcpScalable", "TcpVegas"):
        reset_world()
        progs[v] = _lowered(4, variant=v)
        outs[v] = run_tcp_dumbbell(progs[v], jax.random.PRNGKey(1), replicas=8)
    q_reno = float(np.mean(np.asarray(outs["TcpNewReno"]["mean_queue"])))
    q_vegas = float(np.mean(np.asarray(outs["TcpVegas"]["mean_queue"])))
    drops_vegas = int(np.asarray(outs["TcpVegas"]["drops"]).sum())
    drops_reno = int(np.asarray(outs["TcpNewReno"]["drops"]).sum())
    drops_scal = int(np.asarray(outs["TcpScalable"]["drops"]).sum())
    # Vegas: delay-based — near-empty queue, no losses
    assert q_vegas < 0.4 * q_reno
    assert drops_vegas == 0
    # Scalable backs off least → more overflow events than Reno
    assert drops_scal > drops_reno
    # and all three still fill the pipe
    for v, o in outs.items():
        util = np.asarray(o["delivered"]).sum(1) / progs[v].n_slots
        assert (util > 0.85).all(), (v, util)


def test_statistical_parity_with_scalar_des():
    """Aggregate goodput of the slot model vs real TcpSocketBase over
    the identical graph — the replica engine's oracle contract."""
    from tpudes.core.world import reset_world

    host = {}
    for v in ("TcpNewReno", "TcpVegas"):
        reset_world()
        db, sinks = build_dumbbell(
            3, SIM_S, variant=v, bottleneck_rate="3Mbps"
        )
        Simulator.Stop(Seconds(SIM_S))
        Simulator.Run()
        host[v] = sum(
            s.GetTotalRx() * 8.0 / (SIM_S - 0.1) / 1e6 for s in sinks
        )
    for v in ("TcpNewReno", "TcpVegas"):
        reset_world()
        build_dumbbell(3, SIM_S, variant=v, bottleneck_rate="3Mbps")
        prog = lower_dumbbell(SIM_S)
        out = run_tcp_dumbbell(prog, jax.random.PRNGKey(3), replicas=8)
        dev = float(np.asarray(out["goodput_mbps"]).sum(1).mean())
        assert dev == pytest.approx(host[v], rel=0.25), (
            f"{v}: device {dev:.2f} vs host {host[v]:.2f} Mbps"
        )


def test_early_app_stop_halts_flow():
    """A flow stopped before sim end must stop occupying the bottleneck
    (code-review r4: stop_time was silently ignored)."""
    build_dumbbell(2, 2.0)  # apps Stop at 2.0 s
    prog = lower_dumbbell(4.0)  # but the simulation runs to 4.0 s
    assert (np.asarray(prog.stop_slot) < prog.n_slots).all()
    out = run_tcp_dumbbell(prog, jax.random.PRNGKey(0), replicas=4)
    util = np.asarray(out["delivered"]).sum(1) / prog.n_slots
    # ~half the horizon is post-stop (plus drain): utilization well below 0.75
    assert (util < 0.75).all() and (util > 0.3).all(), util


def test_rejects_mixed_segment_sizes():
    db, _ = build_dumbbell(2, SIM_S)
    db.GetLeft(0).GetApplication(0).send_size = 700
    with pytest.raises(UnliftableDumbbellError, match="SendSize"):
        lower_dumbbell(SIM_S)


def test_rejects_same_side_flow():
    """A left→left flow never crosses the bottleneck — must be rejected,
    not silently forced through the shared queue."""
    from tpudes.core import Seconds
    from tpudes.helper.applications import BulkSendHelper, PacketSinkHelper
    from tpudes.network.address import InetSocketAddress, Ipv4Address

    db, _ = build_dumbbell(3, SIM_S)
    sink = PacketSinkHelper(
        "tpudes::TcpSocketFactory",
        InetSocketAddress(Ipv4Address.GetAny(), 7000),
    )
    sink.Install(db.GetLeft(1)).Start(Seconds(0.0))
    bulk = BulkSendHelper(
        "tpudes::TcpSocketFactory",
        InetSocketAddress(
            Ipv4Address(str(db.GetLeftIpv4Address(1))), 7000
        ),
    )
    bulk.Install(db.GetLeft(0)).Start(Seconds(0.1))
    with pytest.raises(UnliftableDumbbellError, match="cross"):
        lower_dumbbell(SIM_S)


def test_lift_discovers_dumbbell():
    from tpudes.parallel.lift import lift

    build_dumbbell(2, SIM_S)
    kind, prog, commit = lift(SIM_S)
    assert kind == "dumbbell"
    assert prog.n_flows == 2
    commit()


def test_mesh_sharded_run():
    from tpudes.parallel.mesh import replica_mesh

    prog = _lowered(2)
    mesh = replica_mesh(8)
    out = run_tcp_dumbbell(prog, jax.random.PRNGKey(0), replicas=16, mesh=mesh)
    assert np.asarray(out["delivered"]).shape == (16, 2)
    assert int(np.asarray(out["delivered"]).sum()) > 0
