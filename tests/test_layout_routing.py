"""Dumbbell layout + global SPF routing tests.

Mirrors upstream's src/point-to-point-layout tests and
src/internet/test/ipv4-global-routing-test-suite.cc strategy: build the
canned topology, populate tables, assert end-to-end delivery through
multi-hop forwarding.
"""


from tpudes.core import Seconds, Simulator
from tpudes.helper.applications import (
    BulkSendHelper,
    PacketSinkHelper,
    UdpEchoClientHelper,
    UdpEchoServerHelper,
)
from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
from tpudes.helper.layout import PointToPointDumbbellHelper
from tpudes.helper.point_to_point import PointToPointHelper
from tpudes.models.internet.global_routing import (
    GlobalRouteManager,
    Ipv4GlobalRoutingHelper,
)
from tpudes.network.address import InetSocketAddress, Ipv4Address


def _dumbbell(n=3, bottleneck_rate="2Mbps", bottleneck_delay="10ms"):
    leaf = PointToPointHelper()
    leaf.SetDeviceAttribute("DataRate", "10Mbps")
    leaf.SetChannelAttribute("Delay", "1ms")
    bott = PointToPointHelper()
    bott.SetDeviceAttribute("DataRate", bottleneck_rate)
    bott.SetChannelAttribute("Delay", bottleneck_delay)
    db = PointToPointDumbbellHelper(n, leaf, n, leaf, bott)
    stack = InternetStackHelper()
    stack.SetRoutingHelper(Ipv4GlobalRoutingHelper())
    db.InstallStack(stack)
    db.AssignIpv4Addresses(
        Ipv4AddressHelper("10.1.0.0", "255.255.255.0"),
        Ipv4AddressHelper("10.2.0.0", "255.255.255.0"),
        Ipv4AddressHelper("10.3.0.0", "255.255.255.0"),
    )
    Ipv4GlobalRoutingHelper.PopulateRoutingTables()
    return db


def test_dumbbell_shape_and_addresses():
    db = _dumbbell(4)
    assert db.LeftCount() == 4 and db.RightCount() == 4
    # distinct leaf subnets on each side
    lefts = {str(db.GetLeftIpv4Address(i)) for i in range(4)}
    rights = {str(db.GetRightIpv4Address(i)) for i in range(4)}
    assert len(lefts) == 4 and len(rights) == 4
    assert all(a.startswith("10.1.") for a in lefts)
    assert all(a.startswith("10.2.") for a in rights)
    # routers carry 1 bottleneck + n access interfaces (+ loopback)
    from tpudes.models.internet.ipv4 import Ipv4L3Protocol

    left_router = db.GetLeft()
    assert left_router.GetObject(Ipv4L3Protocol).GetNInterfaces() == 1 + 1 + 4


def test_spf_next_hops_cross_dumbbell():
    db = _dumbbell(2)
    mgr = GlobalRouteManager.Get()
    left0 = db.GetLeft(0)
    dst = db.GetRightIpv4Address(1)
    hop = mgr.NextHop(left0.GetId(), Ipv4Address(str(dst)))
    assert hop is not None
    if_index, gw = hop
    assert gw is not None  # leaf's first hop is its access router


def test_udp_echo_across_dumbbell():
    db = _dumbbell(2)
    server = UdpEchoServerHelper(9)
    apps = server.Install(db.GetRight(0))
    apps.Start(Seconds(0.0))
    client = UdpEchoClientHelper(
        Ipv4Address(str(db.GetRightIpv4Address(0))), 9
    )
    client.SetAttribute("MaxPackets", 5)
    client.SetAttribute("Interval", Seconds(0.1))
    client.SetAttribute("PacketSize", 256)
    capps = client.Install(db.GetLeft(0))
    capps.Start(Seconds(0.1))
    got = [0]
    capps.Get(0).TraceConnectWithoutContext(
        "Rx", lambda *a: got.__setitem__(0, got[0] + 1)
    )
    Simulator.Stop(Seconds(2.0))
    Simulator.Run()
    assert got[0] == 5


def test_tcp_bulk_across_dumbbell_bottleneck():
    db = _dumbbell(2, bottleneck_rate="1Mbps", bottleneck_delay="5ms")
    sink = PacketSinkHelper(
        "tpudes::TcpSocketFactory",
        InetSocketAddress(Ipv4Address.GetAny(), 5000),
    )
    sapps = sink.Install(db.GetRight(0))
    sapps.Start(Seconds(0.0))
    bulk = BulkSendHelper(
        "tpudes::TcpSocketFactory",
        InetSocketAddress(Ipv4Address(str(db.GetRightIpv4Address(0))), 5000),
    )
    bulk.SetAttribute("MaxBytes", 200_000)
    bapps = bulk.Install(db.GetLeft(0))
    bapps.Start(Seconds(0.1))
    Simulator.Stop(Seconds(6.0))
    Simulator.Run()
    assert sapps.Get(0).GetTotalRx() == 200_000


def test_unreachable_destination_is_an_error_not_a_hang():
    db = _dumbbell(2)
    mgr = GlobalRouteManager.Get()
    assert mgr.NextHop(db.GetLeft(0).GetId(), Ipv4Address("192.168.99.1")) is None
