"""The traffic stage riding each device engine (ISSUE-14): the
traffic=None bit-identity anchors, the cross-mode bit-equality
contracts (chunking / bucketing / checkpoint / sweeps) WITH workloads
attached, the one-launch mixed workload sweep, and the serving-layer
coalesce-key separation."""

import dataclasses

import jax
import numpy as np
import pytest

from tpudes.parallel.programs import (
    toy_as_program,
    toy_bss_program,
    toy_dumbbell_program,
    toy_lte_program,
    toy_traffic_points,
)
from tpudes.traffic import TrafficProgram

KEY = jax.random.PRNGKey(11)


def _eq(a, b, fields):
    return all(
        np.array_equal(np.asarray(a[f]), np.asarray(b[f]))
        for f in fields
    )


BSS_FIELDS = ("srv_rx", "cli_rx", "tx_data", "drops")


def _bss_prog(sim_end_us=250_000, n_sta=3):
    return toy_bss_program(n_sta=n_sta, sim_end_us=sim_end_us)


def _bss_onoff(prog, seed=3):
    tp = TrafficProgram.onoff(
        prog.n, 120.0, horizon_us=prog.sim_end_us,
        on=(1.5, 0.05, 0.4), off_mean_s=0.1, start_us=prog.start_us,
        tr_seed=seed,
    )
    return tp.with_cbr_rows(
        np.arange(prog.n) == 0, int(prog.interval_us[0]),
        int(prog.start_us[0]),
    )


class TestBss:
    def test_cbr_program_bit_equal_to_traffic_none(self):
        from tpudes.parallel.replicated import run_replicated_bss

        prog = _bss_prog()
        base = run_replicated_bss(prog, 4, KEY)
        tp = TrafficProgram.cbr(prog.start_us, prog.interval_us)
        out = run_replicated_bss(
            dataclasses.replace(prog, traffic=tp), 4, KEY
        )
        assert _eq(base, out, BSS_FIELDS)

    def test_chunked_bucketed_checkpointed_bit_equal(self, tmp_path,
                                                     monkeypatch):
        from tpudes.parallel.replicated import run_replicated_bss

        prog = _bss_prog()
        p = dataclasses.replace(prog, traffic=_bss_onoff(prog))
        ref = run_replicated_bss(p, 5, KEY)
        chunk = max(1, int(ref["steps"]) // 3 - 1)
        chunked = run_replicated_bss(p, 5, KEY, chunk_steps=chunk)
        assert _eq(ref, chunked, BSS_FIELDS)
        monkeypatch.setenv("TPUDES_BUCKETING", "0")
        unbucketed = run_replicated_bss(p, 5, KEY)
        monkeypatch.delenv("TPUDES_BUCKETING")
        assert _eq(ref, unbucketed, BSS_FIELDS)
        # checkpoint/resume: first run persists segment carries, the
        # resumed run must be bit-equal to single-shot
        ck = tmp_path / "bss.ckpt"
        run_replicated_bss(p, 5, KEY, chunk_steps=chunk, checkpoint=ck)
        resumed = run_replicated_bss(
            p, 5, KEY, chunk_steps=chunk, checkpoint=ck
        )
        assert _eq(ref, resumed, BSS_FIELDS)

    def test_mixed_workload_sweep_one_launch_demux_bit_equal(self):
        from tpudes.obs.device import CompileTelemetry
        from tpudes.parallel.replicated import run_replicated_bss
        from tpudes.parallel.runtime import RUNTIME

        prog = _bss_prog()
        pts = toy_traffic_points(
            prog.n, prog.sim_end_us, start_us=prog.start_us,
            beacon=(int(prog.interval_us[0]), int(prog.start_us[0])),
        )
        assert len(pts) == 8
        per = [
            run_replicated_bss(
                dataclasses.replace(prog, traffic=tp), 3, KEY
            )
            for tp in pts
        ]
        base = dataclasses.replace(prog, traffic=pts[0])
        run_replicated_bss(base, 3, KEY, traffic_sweep=pts)  # warm
        l0 = RUNTIME.launches("bss")
        c0 = CompileTelemetry.compiles("bss")
        swept = run_replicated_bss(base, 3, KEY, traffic_sweep=pts)
        assert RUNTIME.launches("bss") - l0 == 1
        assert CompileTelemetry.compiles("bss") - c0 == 0
        for a, b in zip(per, swept):
            assert _eq(a, b, BSS_FIELDS)

    def test_workload_params_are_traced_not_cache_keyed(self):
        from tpudes.obs.device import CompileTelemetry
        from tpudes.parallel.replicated import run_replicated_bss

        prog = _bss_prog()
        p1 = dataclasses.replace(prog, traffic=_bss_onoff(prog, seed=1))
        p2 = dataclasses.replace(prog, traffic=_bss_onoff(prog, seed=2))
        run_replicated_bss(p1, 3, KEY)
        c0 = CompileTelemetry.compiles("bss")
        out = run_replicated_bss(p2, 3, KEY)
        assert CompileTelemetry.compiles("bss") - c0 == 0
        assert out["all_done"]

    def test_sweep_rejects_mismatched_shapes_and_double_axis(self):
        from tpudes.parallel.replicated import run_replicated_bss

        prog = _bss_prog()
        pts = toy_traffic_points(prog.n, prog.sim_end_us,
                                 start_us=prog.start_us)
        base = dataclasses.replace(prog, traffic=pts[0])
        bad = dataclasses.replace(pts[1], n_cycle=1)
        with pytest.raises(ValueError):
            run_replicated_bss(
                base, 3, KEY, traffic_sweep=[pts[0], bad]
            )
        with pytest.raises(ValueError):
            run_replicated_bss(
                base, 3, KEY, traffic_sweep=pts,
                sim_end_us=[prog.sim_end_us] * 8,
            )


LTE_FIELDS = ("rx_bits", "new_tbs", "retx", "drops", "ok")


def _lte_traffic(n_ue, n_ttis, seed=2):
    tp = TrafficProgram.onoff(
        n_ue, 50.0, horizon_us=n_ttis * 1000, on=(1.5, 0.01, 0.05),
        off_mean_s=0.02, tr_seed=seed,
    )
    return dataclasses.replace(
        tp, size_pareto=np.asarray([1.4, 800.0, 12000.0], np.float32)
    )


class TestLteSm:
    def test_saturating_fill_bit_equal_to_full_buffer(self):
        from tpudes.parallel.lte_sm import run_lte_sm

        prog = toy_lte_program(n_enb=2, n_ue=4, n_ttis=100)
        full = run_lte_sm(prog, KEY, replicas=2)
        sat = dataclasses.replace(
            TrafficProgram.cbr(
                np.zeros(4, np.int32), np.full(4, 1, np.int64)
            ),
            size_pareto=np.asarray([0.0, 20000.0, 20000.0], np.float32),
        )
        out = run_lte_sm(
            dataclasses.replace(prog, traffic=sat), KEY, replicas=2
        )
        assert _eq(full, out, LTE_FIELDS)

    def test_finite_backlog_bounds_and_chunk_sweep_bit_equal(self):
        from tpudes.parallel.lte_sm import run_lte_sm

        prog = toy_lte_program(n_enb=2, n_ue=4, n_ttis=120)
        p = dataclasses.replace(
            prog, traffic=_lte_traffic(4, prog.n_ttis)
        )
        full = run_lte_sm(prog, KEY, replicas=2)
        ref = run_lte_sm(p, KEY, replicas=2)
        # an app-limited cell cannot beat the saturated one, and the
        # workload goodput accounting closes: drained + backlog stays
        # within the realized offered fill (size quanta are drawn per
        # TTI, so compare against a generous multiple of the mean)
        assert (
            np.asarray(ref["rx_bits"]).sum()
            <= np.asarray(full["rx_bits"]).sum()
        )
        assert (np.asarray(ref["goodput_bits"]) >= 0).all()
        assert (np.asarray(ref["backlog_bits"]) >= 0).all()
        assert ref["offered_bits"].shape == (4,)
        chunked = run_lte_sm(p, KEY, replicas=2, chunk_ttis=50)
        assert _eq(ref, chunked, LTE_FIELDS + (
            "backlog_bits", "goodput_bits"))
        sw = run_lte_sm(p, KEY, replicas=2, schedulers=["pf", "rr"])
        assert _eq(ref, sw[0], LTE_FIELDS + ("goodput_bits",))

    def test_size_params_are_traced_not_baked(self):
        # regression (ISSUE-14 review): size_pareto must reach the
        # compiled backlog fill as the tr_size OPERAND — a size flip
        # changes the offered load WITHOUT a recompile (the cache key
        # carries shapes only, so a baked constant would silently
        # serve stale sizes)
        from tpudes.obs.device import CompileTelemetry
        from tpudes.parallel.lte_sm import run_lte_sm

        prog = toy_lte_program(n_enb=2, n_ue=4, n_ttis=100)
        small = dataclasses.replace(
            TrafficProgram.onoff(
                4, 20.0, horizon_us=100_000, on=(1.5, 0.01, 0.05),
                off_mean_s=0.02, tr_seed=3,
            ),
            size_pareto=np.asarray([0.0, 400.0, 400.0], np.float32),
        )
        big = dataclasses.replace(
            small,
            size_pareto=np.asarray([0.0, 9000.0, 9000.0], np.float32),
        )
        r_small = run_lte_sm(
            dataclasses.replace(prog, traffic=small), KEY, replicas=2
        )
        c0 = CompileTelemetry.compiles("lte_sm")
        r_big = run_lte_sm(
            dataclasses.replace(prog, traffic=big), KEY, replicas=2
        )
        assert CompileTelemetry.compiles("lte_sm") - c0 == 0
        assert (
            np.asarray(r_big["goodput_bits"]).sum()
            > np.asarray(r_small["goodput_bits"]).sum()
        )

    def test_traffic_plus_mobility_rejected_loudly(self):
        from tpudes.ops.mobility import MobilityProgram
        from tpudes.parallel.lte_sm import (
            UnliftableLteScenarioError,
            run_lte_sm,
        )

        prog = toy_lte_program(n_enb=2, n_ue=3, n_ttis=40)
        mob = MobilityProgram.static(np.zeros((3, 3), np.float32))
        p = dataclasses.replace(
            prog,
            traffic=_lte_traffic(3, 40),
            mobility=mob,
            enb_pos=np.zeros((2, 3), np.float32),
            pathloss=("log_distance", 3.0, 1.0, 46.7),
        )
        with pytest.raises(UnliftableLteScenarioError):
            run_lte_sm(p, KEY, replicas=2)


TCP_FIELDS = ("delivered", "drops", "cwnd_final")


class TestDumbbell:
    def test_unlimited_offer_bit_equal_to_bulk(self):
        from tpudes.parallel.tcp_dumbbell import run_tcp_dumbbell

        prog = toy_dumbbell_program(n_flows=2, n_slots=250)
        bulk = run_tcp_dumbbell(prog, KEY, replicas=2)
        tp = TrafficProgram.cbr(
            np.zeros(2, np.int32), np.full(2, 1, np.int64)
        )
        out = run_tcp_dumbbell(
            dataclasses.replace(prog, traffic=tp), KEY, replicas=2
        )
        assert _eq(bulk, out, TCP_FIELDS)

    def test_app_limited_flows_and_chunk_variant_sweep(self):
        from tpudes.parallel.tcp_dumbbell import run_tcp_dumbbell
        from tpudes.traffic.host import offered_packets

        prog = toy_dumbbell_program(n_flows=2, n_slots=300)
        tp = TrafficProgram.onoff(
            2, 60.0, horizon_us=300_000, on=(1.5, 0.02, 0.08),
            off_mean_s=0.05, tr_seed=1,
        )
        p = dataclasses.replace(prog, traffic=tp)
        ref = run_tcp_dumbbell(p, KEY, replicas=2)
        # the app-limit gate: no flow delivers more than the workload
        # offered by the end of the horizon
        cap = np.floor(offered_packets(tp, prog.n_slots * 1000))
        assert (np.asarray(ref["delivered"]) <= cap[None, :]).all()
        chunked = run_tcp_dumbbell(
            p, KEY, replicas=2, chunk_slots=97
        )
        assert _eq(ref, chunked, TCP_FIELDS)
        sw = run_tcp_dumbbell(
            p, KEY, replicas=2,
            variants=[
                ["TcpNewReno", "TcpCubic"], ["TcpVegas", "TcpVegas"],
            ],
        )
        pt = run_tcp_dumbbell(
            dataclasses.replace(
                p,
                variant_idx=np.asarray([0, 1], np.int32),
                ecn=np.zeros(2, bool),
            ),
            KEY, replicas=2,
        )
        assert _eq(pt, sw[0], TCP_FIELDS)


class TestDumbbellTrafficSweep:
    """ISSUE-15: the dumbbell engine gains the BSS-style config-axis
    workload sweep (``traffic_sweep=``)."""

    def test_mixed_workload_sweep_one_launch_demux_bit_equal(self):
        from tpudes.obs.device import CompileTelemetry
        from tpudes.parallel.runtime import RUNTIME
        from tpudes.parallel.tcp_dumbbell import run_tcp_dumbbell

        prog = toy_dumbbell_program(n_flows=3, n_slots=120)
        pts = toy_traffic_points(3, 120_000)
        assert len(pts) == 8
        per = [
            run_tcp_dumbbell(
                dataclasses.replace(prog, traffic=tp), KEY, replicas=3
            )
            for tp in pts
        ]
        base = dataclasses.replace(prog, traffic=pts[0])
        run_tcp_dumbbell(base, KEY, replicas=3, traffic_sweep=pts)  # warm
        l0 = RUNTIME.launches("dumbbell")
        c0 = CompileTelemetry.compiles("dumbbell")
        swept = run_tcp_dumbbell(
            base, KEY, replicas=3, traffic_sweep=pts
        )
        assert RUNTIME.launches("dumbbell") - l0 == 1
        assert CompileTelemetry.compiles("dumbbell") - c0 == 0
        for a, b in zip(per, swept):
            assert _eq(a, b, TCP_FIELDS)

    def test_sweep_rejects_mismatched_shapes_and_double_axis(self):
        from tpudes.parallel.tcp_dumbbell import run_tcp_dumbbell

        prog = toy_dumbbell_program(n_flows=2, n_slots=60)
        pts = toy_traffic_points(2, 60_000)
        base = dataclasses.replace(prog, traffic=pts[0])
        bad = dataclasses.replace(pts[1], n_cycle=1)
        with pytest.raises(ValueError, match="shape key"):
            run_tcp_dumbbell(
                base, KEY, replicas=2, traffic_sweep=[pts[0], bad]
            )
        with pytest.raises(ValueError, match="one config axis"):
            run_tcp_dumbbell(
                base, KEY, replicas=2, traffic_sweep=pts,
                variants=[[0, 1]] * 8,
            )
        # prog.traffic unset: the sweep has no shape class to compile
        with pytest.raises(ValueError, match="prog.traffic"):
            run_tcp_dumbbell(
                prog, KEY, replicas=2, traffic_sweep=pts
            )


class TestAsFlows:
    def test_cbr_multiplier_is_exact_identity(self):
        from tpudes.parallel.as_flows import run_as_flows

        prog = toy_as_program(n_nodes=16, n_flows=2, spf_rounds=8)
        base = run_as_flows(prog, KEY, replicas=2)
        tp = TrafficProgram.cbr(
            np.zeros(2, np.int32), np.full(2, 1000, np.int64)
        )
        out = run_as_flows(
            dataclasses.replace(prog, traffic=tp), KEY, replicas=2
        )
        assert _eq(
            base, out,
            ("goodput_bps", "delay_s", "delivered_frac", "max_util"),
        )

    def test_workload_scales_offered_load_and_rate_sweep_rides(self):
        from tpudes.parallel.as_flows import run_as_flows
        from tpudes.traffic.host import offered_packets

        prog = toy_as_program(n_nodes=16, n_flows=2, spf_rounds=8)
        tp = TrafficProgram.onoff(
            2, 100.0, horizon_us=int(prog.sim_s * 1e6),
            on=(1.5, 0.05, 0.3), off_mean_s=0.1, tr_seed=4,
        )
        p = dataclasses.replace(prog, traffic=tp)
        base = run_as_flows(prog, KEY, replicas=2)
        out = run_as_flows(p, KEY, replicas=2)
        mult = offered_packets(tp, int(prog.sim_s * 1e6)) / (
            tp.rate_pps.astype(np.float64) * prog.sim_s
        )
        want = np.asarray(base["goodput_bps"], np.float64) * mult[None, :]
        np.testing.assert_allclose(
            np.asarray(out["goodput_bps"], np.float64), want, rtol=2e-3
        )
        sw = run_as_flows(p, KEY, replicas=2, rate_scale=[1.0, 0.5])
        assert _eq(
            out, sw[0],
            ("goodput_bps", "delay_s", "delivered_frac", "max_util"),
        )


class TestServingKeys:
    def test_workloads_separate_coalesce_groups(self):
        from tpudes.parallel.lte_sm import lte_sm_study
        from tpudes.parallel.replicated import bss_study
        from tpudes.parallel.tcp_dumbbell import tcp_study

        bss = _bss_prog()
        a = bss_study(
            dataclasses.replace(bss, traffic=_bss_onoff(bss, 1)),
            KEY, 4,
        )
        b = bss_study(
            dataclasses.replace(bss, traffic=_bss_onoff(bss, 2)),
            KEY, 4,
        )
        assert a.coalesce_key != b.coalesce_key
        lte = toy_lte_program(n_enb=2, n_ue=4, n_ttis=80)
        la = lte_sm_study(
            dataclasses.replace(lte, traffic=_lte_traffic(4, 80, 1)),
            KEY, replicas=2,
        )
        lb = lte_sm_study(
            dataclasses.replace(lte, traffic=_lte_traffic(4, 80, 2)),
            KEY, replicas=2,
        )
        assert la.coalesce_key != lb.coalesce_key
        tcp = toy_dumbbell_program(n_flows=2, n_slots=100)
        tp = TrafficProgram.cbr(
            np.zeros(2, np.int32), np.full(2, 5000, np.int64)
        )
        ta = tcp_study(dataclasses.replace(tcp, traffic=tp), KEY, 2)
        tb = tcp_study(tcp, KEY, 2)
        assert ta.coalesce_key != tb.coalesce_key
