"""Object model: TypeId, attributes, aggregation, trace sources,
GlobalValue, Config paths, CommandLine (reference parity:
src/core/test/ attribute/config test suites; SURVEY.md 4)."""

import pytest

from tpudes.core.command_line import CommandLine
from tpudes.core.config import Config, Names
from tpudes.core.global_value import GlobalValue
from tpudes.core.object import Object, ObjectFactory, TypeId
from tpudes.core.trace import TracedValue


class Gadget(Object):
    tid = (
        TypeId("test::Gadget")
        .AddConstructor(lambda **kw: Gadget(**kw))
        .AddAttribute("Power", "tx power", 10.0)
        .AddAttribute("Name", "a name", "gadget")
        .AddTraceSource("Fired", "fired when poked")
    )

    def poke(self, x):
        self.fired(x)


class SuperGadget(Gadget):
    tid = (
        TypeId("test::SuperGadget")
        .SetParent(Gadget.tid)
        .AddConstructor(lambda **kw: SuperGadget(**kw))
        .AddAttribute("Boost", "extra gain", 3.0)
    )


class Holder(Object):
    tid = TypeId("test::Holder").AddAttribute("Gadgets", "child list", None)

    def __init__(self, gadgets):
        super().__init__()
        self.gadgets = gadgets


def test_attribute_defaults_and_set():
    g = Gadget()
    assert g.power == 10.0
    assert g.GetAttribute("Power") == 10.0
    g.SetAttribute("Power", 20.0)
    assert g.power == 20.0


def test_construct_overrides():
    g = Gadget(Power=33.0, Name="bob")
    assert g.power == 33.0 and g.name == "bob"


def test_inherited_attributes():
    s = SuperGadget()
    assert s.power == 10.0 and s.boost == 3.0
    s.SetAttribute("Power", 1.0)  # parent attribute reachable from child
    assert s.power == 1.0


def test_unknown_attribute_raises():
    with pytest.raises(KeyError):
        Gadget().SetAttribute("Nope", 1)
    assert not Gadget().SetAttributeFailSafe("Nope", 1)


def test_trace_source_connect():
    g = Gadget()
    got = []
    assert g.TraceConnectWithoutContext("Fired", got.append)
    g.poke(42)
    assert got == [42]


def test_trace_with_context():
    g = Gadget()
    got = []
    g.TraceConnect("Fired", "/my/path", lambda ctx, v: got.append((ctx, v)))
    g.poke(7)
    assert got == [("/my/path", 7)]


def test_aggregation():
    a, b = Gadget(), SuperGadget()
    a.AggregateObject(b)
    assert a.GetObject(SuperGadget) is b
    assert b.GetObject(Gadget) in (a, b)  # first match in ring
    assert a.GetObject(TypeId.LookupByName("test::SuperGadget")) is b


def test_object_factory():
    f = ObjectFactory("test::Gadget", Power=5.0)
    f.Set("Name", "fab")
    g = f.Create()
    assert g.power == 5.0 and g.name == "fab"


def test_set_default():
    Config.SetDefault("test::Gadget::Power", 99.0)
    try:
        assert Gadget().power == 99.0
        # subclasses inherit the overridden default
        assert SuperGadget().power == 99.0
    finally:
        from tpudes.core.object import _DEFAULT_OVERRIDES

        _DEFAULT_OVERRIDES.clear()


def test_config_paths_and_wildcards():
    holders = [Holder([Gadget(), Gadget()]), Holder([Gadget()])]
    Config.RegisterRootNamespaceObject("HolderList", lambda: holders)
    Config.Set("/HolderList/0/Gadgets/1/Power", 55.0)
    assert holders[0].gadgets[1].power == 55.0
    assert holders[0].gadgets[0].power == 10.0
    Config.Set("/HolderList/*/Gadgets/*/Power", 77.0)
    assert all(g.power == 77.0 for h in holders for g in h.gadgets)
    got = []
    Config.Connect("/HolderList/*/Gadgets/*/Fired", lambda ctx, v: got.append(v))
    holders[1].gadgets[0].poke(1)
    assert got == [1]


def test_names_registry():
    g = Gadget()
    Names.Add("ap", g)
    assert Names.Find("ap") is g
    assert Names.FindName(g) == "ap"


def test_traced_value():
    tv = TracedValue(5)
    got = []
    tv.ConnectWithoutContext(lambda old, new: got.append((old, new)))
    tv.Set(6)
    tv.Set(6)  # no change, no fire
    tv.Set(7)
    assert got == [(5, 6), (6, 7)]


def test_command_line_custom_and_global():
    cmd = CommandLine()
    cmd.AddValue("nCsma", "number of CSMA nodes", 3)
    cmd.Parse(["--nCsma=10", "--RngRun=5"])
    assert cmd.GetValue("nCsma") == 10
    assert GlobalValue.GetValue("RngRun") == 5


def test_command_line_attribute_default():
    cmd = CommandLine()
    cmd.Parse(["--test::Gadget::Power=42"])
    try:
        assert Gadget().power == 42.0 or Gadget().power == "42"
    finally:
        from tpudes.core.object import _DEFAULT_OVERRIDES

        _DEFAULT_OVERRIDES.clear()


def test_command_line_unknown_raises():
    with pytest.raises(ValueError):
        CommandLine().Parse(["--nonsense=1"])


def test_global_value_env(monkeypatch):
    monkeypatch.setenv("NS_GLOBAL_VALUE", "RngRun=9;ChecksumEnabled=true")
    GlobalValue.ApplyEnvironment()
    assert GlobalValue.GetValue("RngRun") in (9, "9")
