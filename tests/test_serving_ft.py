"""ISSUE 13 gates: serving-fleet fault tolerance + SLO classes.

- **Scheduler hardening**: an exception escaping dispatch/demux fails
  only that batch's handles — submits after a poisoned batch still
  complete (the loop never silently dies).
- **Typed member loss**: EOF / WireFormatError / timeout on a routed
  member surface as MemberLostError with the member id, never a raw
  pipe/pickle exception.
- **Requeue-on-death**: a batch that loses its member is requeued onto
  the survivors (or the local engine) with the member excluded, and
  the recovered results are BIT-equal to failure-free runs.
- **Retry budget + backoff**: transient faults retry boundedly; past
  the budget the handle raises RetryBudgetError (cause chained).
- **SLO classes**: gold preempts coalesce-pending standard work; the
  per-class attainment telemetry rides the serving schema gate.
"""

import dataclasses
import multiprocessing as mp
import threading
import time

import jax
import numpy as np
import pytest

import tpudes.chaos as chaos
from tpudes.chaos import ChaosEvent, ChaosInjected, ChaosSchedule
from tpudes.obs.device import ChunkStream, CompileTelemetry
from tpudes.obs.serving import ServingTelemetry, validate_serving_metrics
from tpudes.parallel.runtime import RUNTIME
from tpudes.serving import (
    MemberLostError,
    ProcessRouter,
    RetryBudgetError,
    StudyServer,
    serve_studies,
)

KEY = jax.random.PRNGKey(11)


@pytest.fixture(autouse=True)
def _fresh_runtime():
    RUNTIME.clear()
    CompileTelemetry.reset()
    ChunkStream.reset()
    ServingTelemetry.reset()
    chaos.reset()
    yield
    chaos.reset()
    RUNTIME.clear()
    ServingTelemetry.reset()


def _bss_prog(sim_end_us=40_000):
    from tpudes.parallel.programs import toy_bss_program

    return toy_bss_program(n_sta=4, sim_end_us=sim_end_us)


def _lte_prog(n_ttis=60):
    from tpudes.parallel.programs import toy_lte_program

    return toy_lte_program(n_enb=2, n_ue=4, n_ttis=n_ttis)


def _assert_equal(a: dict, b: dict):
    for k in b:
        np.testing.assert_array_equal(
            np.asarray(a[k]), np.asarray(b[k]), err_msg=f"field {k!r}"
        )


# --- scheduler hardening (satellite: a poisoned batch never kills the
# --- loop) ----------------------------------------------------------------


def test_submit_after_poisoned_demux_still_completes(monkeypatch):
    """A raise escaping the demux bookkeeping (NOT the launch itself)
    must fail only that batch; the scheduler thread keeps dispatching."""
    from tpudes.parallel.lte_sm import run_lte_sm

    prog = _lte_prog()
    solo = run_lte_sm(prog, KEY, replicas=3)  # pre-compile
    real = ServingTelemetry.record_launch_done.__func__
    boom = {"armed": True}

    def poisoned(cls, engine, wall_s):
        if boom.pop("armed", None):
            raise RuntimeError("telemetry bug (planted)")
        return real(cls, engine, wall_s)

    monkeypatch.setattr(
        ServingTelemetry, "record_launch_done", classmethod(poisoned)
    )
    with StudyServer(max_wait_s=0.01) as server:
        h1 = server.submit_study("lte_sm", prog, KEY, replicas=3)
        with pytest.raises(RuntimeError, match="planted"):
            h1.result(timeout=30)
        # the loop survived: a fresh submit completes normally
        h2 = server.submit_study("lte_sm", prog, KEY, replicas=3)
        _assert_equal(h2.result(timeout=30), solo)


def test_poisoned_dispatch_fails_batch_not_loop(monkeypatch):
    """A raise escaping _dispatch itself (after the internal launch
    try) is caught by the loop's per-batch hardening."""
    from tpudes.parallel.lte_sm import run_lte_sm

    prog = _lte_prog()
    solo = run_lte_sm(prog, KEY, replicas=3)
    real = ServingTelemetry.record_dispatch.__func__
    boom = {"armed": True}

    def poisoned(cls, *a, **kw):
        if boom.pop("armed", None):
            raise RuntimeError("dispatch bookkeeping bug (planted)")
        return real(cls, *a, **kw)

    monkeypatch.setattr(
        ServingTelemetry, "record_dispatch", classmethod(poisoned)
    )
    with StudyServer(max_wait_s=0.01) as server:
        h1 = server.submit_study("lte_sm", prog, KEY, replicas=3)
        with pytest.raises(RuntimeError, match="planted"):
            h1.result(timeout=30)
        h2 = server.submit_study("lte_sm", prog, KEY, replicas=3)
        _assert_equal(h2.result(timeout=30), solo)


# --- typed member loss (satellite: MemberLostError, never raw pipe) -------


def test_routed_future_translates_closed_conn():
    from tpudes.serving.distributed import _RoutedFuture

    a, b = mp.Pipe(duplex=True)
    b.close()
    fut = _RoutedFuture(None, 0, [(1, a, 2)], timeout_s=1.0)
    with pytest.raises(MemberLostError) as ei:
        fut.result()
    assert ei.value.members == (1,)
    assert "EOFError" in str(ei.value) or "OSError" in str(ei.value)
    # memoized: the same typed error on re-read, not a fresh recv
    with pytest.raises(MemberLostError):
        fut.result()


def test_routed_future_translates_wire_garbage():
    from tpudes.serving.distributed import _RoutedFuture

    a, b = mp.Pipe(duplex=True)
    b.send_bytes(b"\xffgarbage-that-is-not-a-frame")
    fut = _RoutedFuture(None, 0, [(2, a, 1)], timeout_s=1.0)
    with pytest.raises(MemberLostError) as ei:
        fut.result()
    assert ei.value.members == (2,)
    assert "WireFormatError" in str(ei.value)


def test_routed_future_timeout_is_member_loss():
    from tpudes.serving.distributed import _RoutedFuture

    a, _b = mp.Pipe(duplex=True)  # peer never replies (hung member)
    fut = _RoutedFuture(None, 0, [(3, a, 1)], timeout_s=0.0)
    with pytest.raises(MemberLostError) as ei:
        fut.result()
    assert ei.value.members == (3,)
    assert "TimeoutError" in str(ei.value)


# --- requeue-on-death: recovered results bit-equal ------------------------


def test_member_death_mid_batch_requeues_bit_equal():
    """The member takes its routed frame and dies before replying; the
    whole batch requeues (member excluded) and completes locally with
    results bit-equal to failure-free solo launches."""
    from tpudes.parallel.replicated import run_replicated_bss

    a, b = mp.Pipe(duplex=True)

    def member():
        b.recv_bytes()  # accept the study frame...
        b.close()       # ...and die mid-batch

    t = threading.Thread(target=member)
    t.start()
    router = ProcessRouter({1: a}, member_timeout_s=5.0)
    prog = _bss_prog()
    ends = (40_000, 44_000)
    with StudyServer(
        start=False, router=router, retry_backoff_s=0.0
    ) as server:
        handles = [
            server.submit_study(
                "bss", dataclasses.replace(prog, sim_end_us=e), KEY, 2
            )
            for e in ends
        ]
        server.pump()
        t.join()
        for h, e in zip(handles, ends):
            solo = run_replicated_bss(
                dataclasses.replace(prog, sim_end_us=e), 2, KEY
            )
            res = h.result(timeout=5)
            for k in solo:
                np.testing.assert_array_equal(
                    np.asarray(res[k]), np.asarray(solo[k]), err_msg=k
                )
        m = server.metrics()
    assert m["failures"]["requeued_studies"] == 2
    assert m["failures"]["members_lost"] == 1
    assert router._dead == {1}, "lost member must be excluded"


def test_wire_corruption_requeues_and_excludes():
    """Chaos corrupts the member's reply frame at the router: the
    stream is untrusted, the member excluded, the batch requeued —
    results still bit-equal."""
    from tpudes.parallel.replicated import run_replicated_bss

    a, b = mp.Pipe(duplex=True)
    stop = threading.Thread(
        target=serve_studies, args=(b,), kwargs=dict(member_id=1)
    )
    stop.start()
    chaos.arm(ChaosSchedule([
        ChaosEvent("wire_corrupt", "router_recv", nth=1, member=1),
    ]))
    router = ProcessRouter({1: a}, member_timeout_s=10.0)
    prog = _bss_prog()
    ends = (40_000, 44_000)
    with StudyServer(
        start=False, router=router, retry_backoff_s=0.0
    ) as server:
        handles = [
            server.submit_study(
                "bss", dataclasses.replace(prog, sim_end_us=e), KEY, 2
            )
            for e in ends
        ]
        server.pump()
        for h, e in zip(handles, ends):
            solo = run_replicated_bss(
                dataclasses.replace(prog, sim_end_us=e), 2, KEY
            )
            res = h.result(timeout=10)
            for k in solo:
                np.testing.assert_array_equal(
                    np.asarray(res[k]), np.asarray(solo[k]), err_msg=k
                )
        m = server.metrics()
    stop.join(timeout=10)
    assert m["failures"]["members_lost"] == 1
    assert m["failures"]["requeued_studies"] == 2
    assert m["failures"]["injected_wire_corrupt"] == 1
    assert router._dead == {1}


# --- retry budget + backoff ------------------------------------------------


def test_retry_budget_exhaustion_raises_typed_error():
    chaos.arm(ChaosSchedule([
        ChaosEvent("launch_error", "local_launch", nth=n)
        for n in (1, 2, 3)
    ]))
    prog = _lte_prog()
    with StudyServer(
        start=False, retry_budget=2, retry_backoff_s=0.0
    ) as server:
        h = server.submit_study("lte_sm", prog, KEY, replicas=3)
        server.pump()
        with pytest.raises(RetryBudgetError) as ei:
            h.result(timeout=5)
        assert isinstance(ei.value.__cause__, ChaosInjected)
        m = server.metrics()
        assert m["failures"]["retry_budget_exhausted"] == 1
        assert m["failures"]["injected_launch_error"] == 3
        chaos.disarm()
        # the server is fine afterwards: a fresh study completes
        h2 = server.submit_study("lte_sm", prog, KEY, replicas=3)
        server.pump()
        assert h2.result(timeout=5)["rx_bits"].shape == (3, 4)


def test_transient_fault_recovers_within_budget():
    from tpudes.parallel.lte_sm import run_lte_sm

    chaos.arm(ChaosSchedule([
        ChaosEvent("launch_error", "local_launch", nth=1),
    ]))
    prog = _lte_prog()
    solo = run_lte_sm(prog, KEY, replicas=3)
    with StudyServer(
        start=False, retry_budget=3, retry_backoff_s=0.0
    ) as server:
        h = server.submit_study("lte_sm", prog, KEY, replicas=3)
        server.pump()
        _assert_equal(h.result(timeout=5), solo)
        m = server.metrics()
    assert m["failures"]["requeued_studies"] == 1
    assert m["failures"]["injected_failures"] == 1


def test_backoff_delays_background_redispatch():
    """The background scheduler honors the retry backoff: the retried
    study completes, but not before the backoff elapsed."""
    from tpudes.parallel.lte_sm import run_lte_sm

    prog = _lte_prog()
    run_lte_sm(prog, KEY, replicas=3)  # pre-compile
    chaos.arm(ChaosSchedule([
        ChaosEvent("launch_error", "local_launch", nth=1),
    ]))
    backoff = 0.25
    with StudyServer(
        max_wait_s=0.005, retry_budget=3, retry_backoff_s=backoff
    ) as server:
        t0 = time.monotonic()
        h = server.submit_study("lte_sm", prog, KEY, replicas=3)
        h.result(timeout=30)
        waited = time.monotonic() - t0
    assert waited >= backoff * 0.8, (
        f"retried after {waited:.3f}s < backoff {backoff}s"
    )


# --- SLO classes -----------------------------------------------------------


def test_unknown_slo_class_rejected():
    with StudyServer(start=False) as server:
        with pytest.raises(ValueError, match="SLO class"):
            server.submit_study(
                "lte_sm", _lte_prog(), KEY, 3, slo="platinum"
            )


def test_gold_preempts_coalesce_pending_standard():
    """Two standard studies sit waiting out the batching deadline; a
    gold arrival dispatches FIRST even though it arrived last."""
    other_key = jax.random.PRNGKey(12)
    prog = _lte_prog()
    with StudyServer(start=False, max_wait_s=60.0) as server:
        h_std = [
            server.submit_study(
                "lte_sm", dataclasses.replace(prog, scheduler=s), KEY, 3
            )
            for s in ("pf", "rr")
        ]
        h_gold = server.submit_study(
            "lte_sm", prog, other_key, 3, slo="gold"
        )
        server.pump(force=False)  # only DUE work dispatches
        assert h_gold.done(), "gold preempts the batching deadline"
        assert not any(h.done() for h in h_std), (
            "standard studies still wait for their deadline"
        )
        server.pump(force=True)
        assert all(h.done() for h in h_std)
        m = server.metrics()
    assert m["slo"]["gold"]["studies"] == 1
    assert m["slo"]["standard"]["studies"] == 2
    assert 0.0 <= m["slo"]["gold"]["attainment"] <= 1.0


def test_gold_head_rides_its_own_batch():
    """Review fix: with more compatible requests than max_batch, the
    arrival-order slice must not cut the gold head out of the batch
    its preempt flag made due."""
    prog = _lte_prog()
    with StudyServer(start=False, max_wait_s=60.0, max_batch=4) as server:
        for s in ("pf", "rr", "fdmt", "tdmt"):
            server.submit_study(
                "lte_sm", dataclasses.replace(prog, scheduler=s), KEY, 3
            )
        h_gold = server.submit_study(
            "lte_sm", dataclasses.replace(prog, scheduler="tta"), KEY, 3,
            slo="gold",
        )
        server.pump(force=False)  # only the gold-headed batch is due
        assert h_gold.done(), (
            "the gold study must ride the batch it preempted for"
        )
        assert h_gold.batch_size == 4
        server.pump(force=True)


def test_slo_fields_ride_the_schema_gate(tmp_path):
    import json

    from tpudes.obs.__main__ import main as obs_main

    prog = _lte_prog()
    with StudyServer(start=False) as server:
        server.submit_study("lte_sm", prog, KEY, 3, slo="gold")
        server.submit_study(
            "lte_sm", dataclasses.replace(prog, scheduler="rr"), KEY, 3,
            slo="batch",
        )
        server.pump()
        m = server.metrics()
    assert validate_serving_metrics(m) == []
    assert set(m["slo"]) == {"batch", "gold"}
    assert m["slo"]["gold"]["attained"] <= m["slo"]["gold"]["studies"]
    for k in ("requeued_studies", "members_lost", "injected_failures",
              "checkpoint_saves", "checkpoint_restores"):
        assert m["failures"][k] == 0
    path = tmp_path / "serving-ft.json"
    path.write_text(json.dumps(m))
    assert obs_main(["--serving", str(path)]) == 0


def test_validator_rejects_missing_failure_and_slo_fields():
    good = ServingTelemetry.snapshot()
    for drop in ("failures", "slo"):
        bad = {k: v for k, v in good.items() if k != drop}
        assert validate_serving_metrics(bad) != [], f"missing {drop}"
    bad = dict(good)
    bad["slo"] = {"gold": {"studies": 1, "attained": 2,
                           "attainment": 2.0,
                           "latency_s": {"p50": 0, "p99": 0, "n": 0}}}
    problems = validate_serving_metrics(bad)
    assert any("attained > studies" in p for p in problems)
    assert any("attainment" in p for p in problems)
