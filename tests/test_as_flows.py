"""Config-#5 tests: BRITE-style generator, device SPF, flow engine.

Strategy mirrors upstream's global-routing and BRITE integration tests:
generator structure, SPF-vs-oracle distance parity, end-to-end delivery
parity against the packet-level scalar DES, overload direction, and the
lift seam.
"""

import heapq

import jax
import numpy as np
import pytest

from tpudes.core import Seconds, Simulator
from tpudes.helper.topology import BriteTopologyHelper
from tpudes.parallel.as_flows import (
    AsFlowsProgram,
    UnliftableAsError,
    device_spf,
    lower_as_flows,
    run_as_flows,
)
from tpudes.scenarios import build_as_network


# ---------------------------------------------------------------- generator
def test_ba_generator_structure():
    g = BriteTopologyHelper(model="BA", n=500, m=2, seed=9).Generate()
    assert g.is_connected()
    assert g.m == 2 * (500 - 3) + 3  # m per new node + seed clique
    deg = np.bincount(g.edges.ravel(), minlength=g.n)
    # preferential attachment: heavy tail, hubs far above the mean
    assert deg.max() >= 8 * deg.mean()
    assert deg.min() >= 2


def test_waxman_generator_locality():
    h = BriteTopologyHelper(model="Waxman", n=400, alpha=0.3, beta=0.06, seed=9)
    g = h.Generate()
    assert g.is_connected()
    # locality: a Waxman edge is much shorter than a random node pair
    rng = np.random.default_rng(0)
    pairs = rng.integers(0, g.n, size=(2000, 2))
    rand_d = np.sqrt(
        ((g.pos[pairs[:, 0]] - g.pos[pairs[:, 1]]) ** 2).sum(-1)
    ).mean()
    edge_d = np.sqrt(
        ((g.pos[g.edges[:, 0]] - g.pos[g.edges[:, 1]]) ** 2).sum(-1)
    ).mean()
    assert edge_d < 0.5 * rand_d


def test_generator_is_seed_deterministic():
    a = BriteTopologyHelper(model="BA", n=300, m=2, seed=5).Generate()
    b = BriteTopologyHelper(model="BA", n=300, m=2, seed=5).Generate()
    c = BriteTopologyHelper(model="BA", n=300, m=2, seed=6).Generate()
    np.testing.assert_array_equal(a.edges, b.edges)
    assert not np.array_equal(a.edges, c.edges)


def test_generator_rides_the_seeded_stream_api():
    """Promoted RNG002 regression: the topology draws are keyed by the
    global (RngSeed, RngRun) pair — selecting a different RngRun
    re-randomizes the graph (a bare np.random.default_rng(seed) could
    never see it), while the same (seed, run) reproduces it exactly."""
    from tpudes.core.rng import RngSeedManager

    run0 = RngSeedManager.GetRun()
    try:
        a = BriteTopologyHelper(model="BA", n=200, m=2, seed=5).Generate()
        RngSeedManager.SetRun(run0 + 7)
        b = BriteTopologyHelper(model="BA", n=200, m=2, seed=5).Generate()
        RngSeedManager.SetRun(run0)
        c = BriteTopologyHelper(model="BA", n=200, m=2, seed=5).Generate()
    finally:
        RngSeedManager.SetRun(run0)
    assert not np.array_equal(a.edges, b.edges)
    np.testing.assert_array_equal(a.edges, c.edges)
    np.testing.assert_array_equal(a.pos, c.pos)


# ---------------------------------------------------------------- device SPF
def _dijkstra(n, edges, w, dst):
    """float64 host oracle (hop metric when w=1)."""
    adj = [[] for _ in range(n)]
    for (u, v), wt in zip(edges, w):
        adj[u].append((v, wt))
        adj[v].append((u, wt))
    dist = np.full(n, np.inf)
    dist[dst] = 0.0
    pq = [(0.0, dst)]
    while pq:
        d, u = heapq.heappop(pq)
        if d > dist[u]:
            continue
        for v, wt in adj[u]:
            nd = d + wt
            if nd < dist[v]:
                dist[v] = nd
                heapq.heappush(pq, (nd, v))
    return dist


def test_device_spf_matches_dijkstra_oracle():
    g = BriteTopologyHelper(model="BA", n=200, m=2, seed=11).Generate()
    dsts = np.array([0, 17, 133], np.int32)
    prog = AsFlowsProgram(
        n=g.n, edges=g.edges, delay_s=g.delay_s, rate_bps=g.rate_bps,
        src=np.zeros(3, np.int32), dst=dsts,
        flow_bps=np.full(3, 1e5), pkt_bytes=512, sim_s=1.0,
    )
    ddst, dist, nh_edge, nh_node = device_spf(prog)
    dist = np.asarray(dist)
    for row, d in enumerate(np.unique(dsts)):
        oracle = _dijkstra(g.n, g.edges, np.ones(g.m), int(d))
        np.testing.assert_allclose(dist[row], oracle, rtol=1e-5)


def test_path_walk_reaches_destination_in_dist_hops():
    g = BriteTopologyHelper(model="BA", n=300, m=2, seed=2).Generate()
    rng = np.random.default_rng(1)
    F = 16
    src = rng.integers(0, g.n, F).astype(np.int32)
    dst = (src + rng.integers(1, g.n, F)).astype(np.int32) % g.n
    prog = AsFlowsProgram(
        n=g.n, edges=g.edges, delay_s=g.delay_s, rate_bps=g.rate_bps,
        src=src, dst=dst, flow_bps=np.full(F, 1e5), pkt_bytes=512,
        sim_s=1.0,
    )
    out = run_as_flows(prog, jax.random.PRNGKey(0), replicas=2)
    hops = np.asarray(out["hops"])
    assert not np.asarray(out["unreachable"]).any()
    for f in range(F):
        oracle = _dijkstra(g.n, g.edges, np.ones(g.m), int(dst[f]))
        assert hops[f] == int(oracle[src[f]]), f"flow {f} not shortest"


# ------------------------------------------------------------ flow outcomes
def test_sparse_traffic_parity_with_scalar_des():
    """Sparse regime: the fluid engine and the packet DES must agree on
    delivery (all packets arrive) and goodput within jitter."""
    build_as_network(80, 6, 2.0, seed=4)
    prog = lower_as_flows(2.0)
    _, servers = None, None  # objects live in the world already
    from tpudes.network.node import NodeList  # noqa: F401

    Simulator.Stop(Seconds(2.0))
    Simulator.Run()
    # host: every CBR packet delivered (no congestion on 10-100 Mbps links)
    from tpudes.models.applications import UdpServer

    host_rx = []
    for i in range(NodeList.GetNNodes()):
        node = NodeList.GetNode(i)
        for a in range(node.GetNApplications()):
            app = node.GetApplication(a)
            if isinstance(app, UdpServer):
                host_rx.append(app.received)
    expected = int((2.0 - 0.05) / (512 * 8 / 400e3))
    # a few packets are still in flight at Stop (multi-hop path delay)
    assert all(abs(rx - expected) <= 5 for rx in host_rx), host_rx

    out = run_as_flows(prog, jax.random.PRNGKey(0), replicas=16)
    frac = np.asarray(out["delivered_frac"])
    assert (frac > 0.999).all(), "sparse flows must be loss-free"
    g = np.asarray(out["goodput_bps"]).mean(axis=0)
    # replica jitter is zero-mean around the nominal 400 kbps
    assert g.mean() == pytest.approx(400e3, rel=0.15)


def test_overloaded_link_sheds_proportionally():
    """3-node line, two flows through the middle link at 2x capacity →
    fluid delivery ≈ 0.5 each."""
    edges = np.array([[0, 1], [1, 2]], np.int32)
    prog = AsFlowsProgram(
        n=3, edges=edges,
        delay_s=np.array([1e-3, 1e-3]),
        rate_bps=np.array([10e6, 10e6]),
        src=np.array([0, 0], np.int32), dst=np.array([2, 2], np.int32),
        flow_bps=np.array([10e6, 10e6]),
        pkt_bytes=512, sim_s=1.0, rate_jitter=0.0,
    )
    out = run_as_flows(prog, jax.random.PRNGKey(0), replicas=4)
    frac = np.asarray(out["delivered_frac"])
    np.testing.assert_allclose(frac, 0.5, rtol=0.01)
    assert np.asarray(out["max_util"]).max() == pytest.approx(2.0, rel=0.01)


def test_exact_max_hops_path_still_arrives():
    """A shortest path of exactly max_hops hops is reachable (r4 review:
    the arrival test off-by-one zeroed such flows)."""
    n = 6  # line graph: 5 hops end-to-end
    edges = np.stack(
        [np.arange(n - 1), np.arange(1, n)], axis=1
    ).astype(np.int32)
    prog = AsFlowsProgram(
        n=n, edges=edges, delay_s=np.full(n - 1, 1e-3),
        rate_bps=np.full(n - 1, 10e6),
        src=np.array([0], np.int32), dst=np.array([n - 1], np.int32),
        flow_bps=np.array([1e5]), pkt_bytes=512, sim_s=1.0,
        max_hops=5, spf_rounds=8, rate_jitter=0.0,
    )
    out = run_as_flows(prog, jax.random.PRNGKey(0), replicas=2)
    assert not np.asarray(out["unreachable"]).any()
    assert int(np.asarray(out["hops"])[0]) == 5
    np.testing.assert_allclose(
        np.asarray(out["delivered_frac"]), 1.0, rtol=1e-5
    )


def test_unmodeled_cross_traffic_is_rejected():
    """Apps the flow engine cannot represent must fail the lowering,
    not silently vanish from the link loads (r4 review)."""
    from tpudes.core import Seconds
    from tpudes.helper.applications import UdpEchoClientHelper
    from tpudes.network.address import Ipv4Address
    from tpudes.network.node import NodeList

    build_as_network(60, 4, 2.0, seed=8)
    echo = UdpEchoClientHelper(Ipv4Address("10.0.0.1"), 9)
    echo.Install(NodeList.GetNode(3)).Start(Seconds(0.1))
    with pytest.raises(UnliftableAsError, match="unmodeled"):
        lower_as_flows(2.0)


def test_flows_riding_other_technologies_are_rejected():
    """A UDP flow whose path crosses a non-p2p technology (here: LTE
    bearers behind the EPC) must NOT lift as the p2p backhaul graph
    (r4: the generic backstop silently swallowed an LTE scenario)."""
    from tpudes.helper.applications import UdpClientHelper, UdpServerHelper
    from tpudes.helper.containers import NodeContainer
    from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
    from tpudes.helper.point_to_point import PointToPointHelper
    from tpudes.core import Seconds

    # two p2p islands: remote--gw, and ue alone with an address the
    # client can name but no p2p path to reach it
    a = NodeContainer()
    a.Create(2)
    b = NodeContainer()
    b.Create(2)
    InternetStackHelper().Install(a)
    InternetStackHelper().Install(b)
    p2p = PointToPointHelper()
    p2p.SetDeviceAttribute("DataRate", "10Mbps")
    p2p.SetChannelAttribute("Delay", "1ms")
    Ipv4AddressHelper("10.1.0.0", "255.255.255.0").Assign(
        p2p.Install(a.Get(0), a.Get(1))
    )
    ifc_b = Ipv4AddressHelper("10.2.0.0", "255.255.255.0").Assign(
        p2p.Install(b.Get(0), b.Get(1))
    )
    server = UdpServerHelper(9)
    server.Install(b.Get(1)).Start(Seconds(0.0))
    client = UdpClientHelper(ifc_b.GetAddress(1), 9)
    client.SetAttribute("Interval", Seconds(0.01))
    client.Install(a.Get(0)).Start(Seconds(0.1))
    with pytest.raises(UnliftableAsError, match="not connected"):
        lower_as_flows(1.0)


def test_lowering_rejects_empty_and_lift_discovers():
    from tpudes.parallel.lift import lift

    with pytest.raises(UnliftableAsError):
        lower_as_flows(1.0)
    build_as_network(60, 4, 2.0, seed=8)
    kind, prog, commit = lift(2.0)
    assert kind == "as_flows"
    assert len(prog.src) == 4
    commit()


def test_mesh_sharded_run():
    from tpudes.parallel.mesh import replica_mesh

    g = BriteTopologyHelper(model="BA", n=100, m=2, seed=1).Generate()
    prog = AsFlowsProgram(
        n=g.n, edges=g.edges, delay_s=g.delay_s, rate_bps=g.rate_bps,
        src=np.array([1, 2], np.int32), dst=np.array([50, 60], np.int32),
        flow_bps=np.full(2, 1e5), pkt_bytes=512, sim_s=1.0,
    )
    out = run_as_flows(
        prog, jax.random.PRNGKey(0), replicas=16, mesh=replica_mesh(8)
    )
    assert np.asarray(out["goodput_bps"]).shape == (16, 2)
    assert not np.asarray(out["unreachable"]).any()


def test_topology_axis_sharding_matches_single_device():
    """SURVEY.md §5.7: the (D, N) SPF tables shard their destination
    rows over the mesh (with_sharding_constraint in device_spf) and the
    study result is identical to the replicated single-device run."""
    import jax as _jax

    if len(_jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    from tpudes.parallel.mesh import replica_mesh

    g = BriteTopologyHelper(model="BA", n=200, m=2, seed=3).Generate()
    n_dst = 16  # divisible by the 8-device mesh
    prog = AsFlowsProgram(
        n=g.n, edges=g.edges, delay_s=g.delay_s, rate_bps=g.rate_bps,
        src=np.arange(1, 1 + n_dst, dtype=np.int32),
        dst=np.arange(100, 100 + n_dst, dtype=np.int32),
        flow_bps=np.full(n_dst, 1e5), pkt_bytes=512, sim_s=1.0,
    )
    mesh = replica_mesh(8)
    sharded = run_as_flows(prog, jax.random.PRNGKey(2), replicas=16, mesh=mesh)
    single = run_as_flows(prog, jax.random.PRNGKey(2), replicas=16, mesh=None)
    np.testing.assert_allclose(
        np.asarray(sharded["goodput_bps"]), np.asarray(single["goodput_bps"]),
        rtol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(sharded["delay_s"]), np.asarray(single["delay_s"]),
        rtol=1e-5,
    )


def test_lift_warns_on_nondivisible_replica_count():
    """lift.py used to silently drop the mesh when replicas % devices
    != 0 (VERDICT r4 weak #5) — now it warns loudly."""
    import warnings

    import jax as _jax

    if len(_jax.devices()) < 2:
        pytest.skip("needs multiple devices")
    from tpudes.parallel.lift import run_lifted

    g = BriteTopologyHelper(model="BA", n=60, m=2, seed=1).Generate()
    prog = AsFlowsProgram(
        n=g.n, edges=g.edges, delay_s=g.delay_s, rate_bps=g.rate_bps,
        src=np.array([1], np.int32), dst=np.array([30], np.int32),
        flow_bps=np.full(1, 1e5), pkt_bytes=512, sim_s=1.0,
    )
    n_dev = len(_jax.devices())
    odd = n_dev + 1  # never divisible by (or sharing a factor > 1 with
                     # n_dev only when n_dev+1 ... gcd(n+1, n) == 1)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        out = run_lifted("as_flows", prog, replicas=odd)
    assert np.asarray(out["goodput_bps"]).shape[0] == odd
    assert any("not divisible" in str(w.message) for w in caught), [
        str(w.message) for w in caught
    ]


def test_flow_endpoints_ride_the_seeded_stream_api():
    """The endpoint draw uses the MRG32k3a stream API keyed by ``seed``
    (the promoted RNG002 baseline finding): the flow set is a pure
    function of the builder arguments, immune to stdlib random state."""
    import random as stdlib_random

    from tpudes.core.world import reset_world

    def endpoints(seed):
        reset_world()
        _, servers = build_as_network(40, 6, 1.0, seed=seed)
        out = [
            (srv.GetNode().GetId(), srv.port) for srv in servers
        ]
        reset_world()
        return out

    stdlib_random.seed(123)
    a = endpoints(seed=4)
    stdlib_random.seed(999)
    assert endpoints(seed=4) == a  # stdlib state is irrelevant
    assert endpoints(seed=5) != a  # but the seed argument is not
