"""Simulator engine semantics: ordering, contexts, cancel/remove, stop,
destroy, ScheduleWithContext — mirroring upstream simulator test suite
behaviors (src/core/test/; SURVEY.md 4)."""


from tpudes.core.global_value import GlobalValue
from tpudes.core.nstime import MilliSeconds, Seconds
from tpudes.core.simulator import RealtimeSimulatorImpl, Simulator


def test_event_ordering_and_now():
    log = []
    Simulator.Schedule(Seconds(2), lambda: log.append(("b", Simulator.Now().GetSeconds())))
    Simulator.Schedule(Seconds(1), lambda: log.append(("a", Simulator.Now().GetSeconds())))
    Simulator.Schedule(Seconds(3), lambda: log.append(("c", Simulator.Now().GetSeconds())))
    Simulator.Run()
    assert log == [("a", 1.0), ("b", 2.0), ("c", 3.0)]


def test_same_time_fifo_order():
    log = []
    for i in range(10):
        Simulator.Schedule(Seconds(1), log.append, i)
    Simulator.Run()
    assert log == list(range(10))


def test_schedule_now_and_nested():
    log = []

    def outer():
        log.append("outer")
        Simulator.ScheduleNow(lambda: log.append("nested-now"))
        Simulator.Schedule(Seconds(1), lambda: log.append("nested-later"))

    Simulator.Schedule(Seconds(5), outer)
    Simulator.Run()
    assert log == ["outer", "nested-now", "nested-later"]
    assert Simulator.Now() == Seconds(6)


def test_cancel_and_remove():
    log = []
    keep = Simulator.Schedule(Seconds(1), lambda: log.append("keep"))
    cancel = Simulator.Schedule(Seconds(2), lambda: log.append("cancel"))
    remove = Simulator.Schedule(Seconds(3), lambda: log.append("remove"))
    cancel.Cancel()
    Simulator.Remove(remove)
    assert keep.IsPending()
    assert cancel.IsCancelled()
    Simulator.Run()
    assert log == ["keep"]
    assert keep.IsExpired()


def test_stop_at_time():
    log = []
    for s in range(1, 10):
        Simulator.Schedule(Seconds(s), log.append, s)
    Simulator.Stop(Seconds(4.5))
    Simulator.Run()
    assert log == [1, 2, 3, 4]
    assert abs(Simulator.Now().GetSeconds() - 4.5) < 1e-9


def test_stop_now_inside_event():
    log = []

    def stopper():
        log.append("stop")
        Simulator.Stop()

    Simulator.Schedule(Seconds(1), stopper)
    Simulator.Schedule(Seconds(2), lambda: log.append("never"))
    Simulator.Run()
    assert log == ["stop"]


def test_context_propagation():
    seen = []
    Simulator.ScheduleWithContext(7, Seconds(1), lambda: seen.append(Simulator.GetContext()))
    Simulator.ScheduleWithContext(9, Seconds(2), lambda: seen.append(Simulator.GetContext()))
    Simulator.Run()
    assert seen == [7, 9]


def test_schedule_destroy():
    log = []
    Simulator.Schedule(Seconds(1), lambda: log.append("run"))
    Simulator.ScheduleDestroy(lambda: log.append("destroy"))
    Simulator.Run()
    assert log == ["run"]
    Simulator.Destroy()
    assert log == ["run", "destroy"]


def test_event_count():
    for s in range(5):
        Simulator.Schedule(Seconds(s + 1), lambda: None)
    Simulator.Run()
    assert Simulator.GetEventCount() == 5


def test_engine_seam_selection():
    GlobalValue.Bind("SimulatorImplementationType", "tpudes::RealtimeSimulatorImpl")
    impl = Simulator.GetImpl()
    assert isinstance(impl, RealtimeSimulatorImpl)


def test_realtime_tracks_wallclock():
    import time as wall

    GlobalValue.Bind("SimulatorImplementationType", "tpudes::RealtimeSimulatorImpl")
    log = []
    Simulator.Schedule(MilliSeconds(50), lambda: log.append(wall.monotonic()))
    t0 = wall.monotonic()
    Simulator.Run()
    assert len(log) == 1
    elapsed = log[0] - t0
    assert 0.045 <= elapsed <= 0.5  # scheduled at +50ms wall time


def test_scheduler_type_global():
    GlobalValue.Bind("SchedulerType", "tpudes::CalendarScheduler")
    log = []
    Simulator.Schedule(Seconds(2), log.append, 2)
    Simulator.Schedule(Seconds(1), log.append, 1)
    Simulator.Run()
    assert log == [1, 2]


def test_run_twice_after_destroy():
    log = []
    Simulator.Schedule(Seconds(1), log.append, "first")
    Simulator.Run()
    Simulator.Destroy()
    Simulator.Schedule(Seconds(1), log.append, "second")
    Simulator.Run()
    assert log == ["first", "second"]
    assert Simulator.Now() == Seconds(1)  # fresh engine restarted at 0
