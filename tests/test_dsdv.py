"""DSDV tests — upstream src/dsdv/test strategy: table convergence on
an adhoc chain, multihop forwarding beyond radio range, sequence-number
freshness, expiry of dead routes."""


from tpudes.core import Seconds, Simulator
from tpudes.helper.containers import NodeContainer
from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
from tpudes.models.internet.dsdv import DsdvHelper, DsdvHeader, DsdvRoutingProtocol
from tpudes.models.internet.ipv4 import Ipv4L3Protocol
from tpudes.models.mobility import ListPositionAllocator, MobilityHelper, Vector
from tpudes.network.address import Ipv4Address


def _adhoc_chain(n=3, spacing=80.0, period=1.0):
    """n adhoc WiFi nodes on a line; at 80 m hops each node only hears
    its immediate neighbors (default log-distance physics)."""
    from tpudes.models.wifi import (
        WifiHelper,
        WifiMacHelper,
        YansWifiChannelHelper,
        YansWifiPhyHelper,
    )

    nodes = NodeContainer()
    nodes.Create(n)
    alloc = ListPositionAllocator()
    for i in range(n):
        alloc.Add(Vector(i * spacing, 0.0, 0.0))
    mob = MobilityHelper()
    mob.SetPositionAllocator(alloc)
    mob.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    mob.Install(nodes)

    channel = YansWifiChannelHelper.Default().Create()
    phy = YansWifiPhyHelper()
    phy.SetChannel(channel)
    wifi = WifiHelper()
    wifi.SetRemoteStationManager(
        "tpudes::ConstantRateWifiManager", DataMode="OfdmRate6Mbps"
    )
    mac = WifiMacHelper()
    mac.SetType("tpudes::AdhocWifiMac")
    devices = wifi.Install(phy, mac, [nodes.Get(i) for i in range(n)])

    stack = InternetStackHelper()
    stack.SetRoutingHelper(DsdvHelper(PeriodicUpdateInterval=Seconds(period)))
    stack.Install(nodes)
    ifc = Ipv4AddressHelper("10.1.1.0", "255.255.255.0").Assign(devices)
    return nodes, devices, ifc


def test_tables_converge_to_all_destinations():
    nodes, devices, ifc = _adhoc_chain(3)
    Simulator.Stop(Seconds(5.0))
    Simulator.Run()
    for i in range(3):
        dsdv = nodes.Get(i).GetObject(Ipv4L3Protocol).GetRoutingProtocol()
        assert isinstance(dsdv, DsdvRoutingProtocol)
        # own address + the two others
        assert dsdv.GetNRoutes() == 3, f"node {i}: {dsdv.GetNRoutes()}"
    # the ends route to each other via the middle node, 2 hops
    end = nodes.Get(0).GetObject(Ipv4L3Protocol).GetRoutingProtocol()
    far = Ipv4Address(str(ifc.GetAddress(2)))
    row = end._table[far.addr]
    assert row[2] == 2, "far end must be 2 hops"
    assert str(row[0]) == str(ifc.GetAddress(1)), "via the middle node"


def test_multihop_ping_beyond_radio_range():
    from tpudes.models.internet.icmp import V4Ping

    nodes, devices, ifc = _adhoc_chain(3)
    ping = V4Ping(
        Remote=str(ifc.GetAddress(2)), Interval=Seconds(0.25), Count=8
    )
    nodes.Get(0).AddApplication(ping)
    ping.SetStartTime(Seconds(3.0))  # after convergence
    Simulator.Stop(Seconds(6.0))
    Simulator.Run()
    assert ping.received >= 6, f"{ping.received}/8 multihop pings"
    # two WiFi hops each way; well above a single-hop RTT
    assert min(ping.rtts) > 0.0005


def test_fresher_sequence_wins_and_stale_is_ignored():
    nodes, devices, ifc = _adhoc_chain(2, spacing=50.0)
    Simulator.Stop(Seconds(3.0))
    Simulator.Run()
    dsdv = nodes.Get(0).GetObject(Ipv4L3Protocol).GetRoutingProtocol()
    peer = Ipv4Address(str(ifc.GetAddress(1)))
    row = dsdv._table[peer.addr]
    seq_now = row[3]
    # replay a STALE update claiming a 9-hop path: must be ignored
    from tpudes.models.internet.ipv4 import Ipv4Header

    stale = DsdvHeader([(peer, 9, seq_now - 2)])
    import types

    pkt_hdr = Ipv4Header(source=peer, destination=Ipv4Address.GetBroadcast())
    from tpudes.network.packet import Packet

    p = Packet(0)
    p.AddHeader(stale)
    p.RemoveHeader(DsdvHeader)  # simulate wire: re-add for Receive
    p.AddHeader(stale)
    dsdv.Receive(p, pkt_hdr, dsdv.ipv4.GetInterface(1))
    assert dsdv._table[peer.addr][2] == row[2], "stale seq must not win"


def test_dead_route_expires():
    nodes, devices, ifc = _adhoc_chain(2, spacing=50.0, period=0.5)
    Simulator.Stop(Seconds(2.0))
    Simulator.Run()
    dsdv = nodes.Get(0).GetObject(Ipv4L3Protocol).GetRoutingProtocol()
    peer = Ipv4Address(str(ifc.GetAddress(1)))
    assert peer.addr in dsdv._table
    # silence the neighbor (radio off) and run past the hold time
    devices.Get(1).GetPhy().tx_power_start = -200.0
    devices.Get(1).GetPhy().tx_power_end = -200.0
    Simulator.Stop(Seconds(3.0))
    Simulator.Run()
    dsdv._expire()
    assert peer.addr not in dsdv._table, "dead route must age out"