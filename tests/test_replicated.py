"""Replica-axis engine vs the sequential DES (SURVEY.md §4: statistical
— not bitwise — parity; §7 step 7 "prototype early").

The scalar engine is the per-event oracle: the same BSS config is run
(a) K times sequentially with distinct RngRun, (b) once with R replicas
through the vectorized event-stepped program lowered from the SAME
object graph.  Delivery-count distributions must agree.
"""

import math

import jax
import numpy as np
import pytest

from tpudes.core import Seconds, Simulator
from tpudes.core.rng import RngSeedManager
from tpudes.helper.applications import UdpEchoClientHelper, UdpEchoServerHelper
from tpudes.helper.containers import NetDeviceContainer, NodeContainer
from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
from tpudes.models.mobility import ListPositionAllocator, MobilityHelper, Vector
from tpudes.models.wifi import (
    WifiHelper,
    WifiMacHelper,
    YansWifiChannelHelper,
    YansWifiPhyHelper,
)
from tpudes.parallel.replicated import lower_bss, run_replicated_bss

N_STAS = 5
SIM_TIME = 1.8
RADIUS = 32.0  # lossy at 54 Mbps under the corrected NIST 64-QAM BER
               # (snr/21): per-attempt PSR well below 1, replicas diverge


def _positions():
    pos = [(0.0, 0.0, 0.0)]
    for i in range(N_STAS):
        a = 2 * math.pi * i / N_STAS
        pos.append((RADIUS * math.cos(a), RADIUS * math.sin(a), 0.0))
    return pos


def _reset_world():
    from tpudes.core.world import reset_world

    reset_world()


def _build_bss():
    """The wifi-bss.py topology with deterministic positions.  Returns
    (sta_devices, ap_device, clients, server_rx_counter)."""
    nodes = NodeContainer()
    nodes.Create(N_STAS + 1)

    mobility = MobilityHelper()
    alloc = ListPositionAllocator()
    for x, y, z in _positions():
        alloc.Add(Vector(x, y, z))
    mobility.SetPositionAllocator(alloc)
    mobility.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    mobility.Install(nodes)

    channel = YansWifiChannelHelper.Default().Create()
    phy = YansWifiPhyHelper()
    phy.SetChannel(channel)
    wifi = WifiHelper()
    wifi.SetRemoteStationManager(
        "tpudes::ConstantRateWifiManager", DataMode="OfdmRate54Mbps"
    )

    ap_mac = WifiMacHelper()
    ap_mac.SetType("tpudes::ApWifiMac")
    ap_devices = wifi.Install(phy, ap_mac, [nodes.Get(0)])
    sta_mac = WifiMacHelper()
    sta_mac.SetType("tpudes::StaWifiMac")
    sta_devices = wifi.Install(
        phy, sta_mac, [nodes.Get(i) for i in range(1, N_STAS + 1)]
    )

    stack = InternetStackHelper()
    stack.Install(nodes)
    address = Ipv4AddressHelper()
    address.SetBase("10.1.3.0", "255.255.255.0")
    devices = NetDeviceContainer()
    devices.Add(ap_devices.Get(0))
    for i in range(N_STAS):
        devices.Add(sta_devices.Get(i))
    interfaces = address.Assign(devices)

    server = UdpEchoServerHelper(9)
    server_apps = server.Install(nodes.Get(0))
    server_apps.Start(Seconds(0.4))
    server_apps.Stop(Seconds(SIM_TIME))
    rx = [0]
    server_apps.Get(0).TraceConnectWithoutContext(
        "Rx", lambda pkt, *a: rx.__setitem__(0, rx[0] + 1)
    )

    clients = []
    for i in range(N_STAS):
        helper = UdpEchoClientHelper(interfaces.GetAddress(0), 9)
        helper.SetAttribute("MaxPackets", 1_000_000)
        helper.SetAttribute("Interval", Seconds(0.1))
        helper.SetAttribute("PacketSize", 512)
        apps = helper.Install(nodes.Get(1 + i))
        apps.Start(Seconds(1.0 + 0.001 * i))
        apps.Stop(Seconds(SIM_TIME))
        clients.append(apps.Get(0))
    return sta_devices, ap_devices.Get(0), clients, rx


def _des_delivery_counts(runs):
    counts = []
    for run in range(1, runs + 1):
        _reset_world()
        RngSeedManager.SetRun(run)
        _, _, _, rx = _build_bss()
        Simulator.Stop(Seconds(SIM_TIME))
        Simulator.Run()
        counts.append(rx[0])
    _reset_world()
    return np.array(counts, dtype=np.float64)


def _lowered_program():
    _reset_world()
    sta_devices, ap_device, clients, _ = _build_bss()
    prog = lower_bss(
        [sta_devices.Get(i) for i in range(N_STAS)], ap_device, clients, SIM_TIME
    )
    _reset_world()
    return prog


def test_lowering_reads_object_graph():
    prog = _lowered_program()
    assert prog.n == N_STAS + 1
    np.testing.assert_allclose(prog.positions, np.array(_positions()), atol=1e-5)
    # 54 Mbps ConstantRate → mode 7; payload 512 → PSDU 512+64
    assert prog.data_mode_idx == 7
    assert prog.data_bytes == 512 + 64
    # clients: start 1.0 s + (i-1) ms, interval 100 ms, stop at SIM_TIME
    assert prog.start_us[1] == 1_000_000
    assert prog.start_us[2] == 1_001_000
    assert prog.interval_us[1] == 100_000
    assert prog.stop_us[1] == int(SIM_TIME * 1e6)
    # AP slot carries the beacon schedule
    assert prog.interval_us[0] == 102_400


def test_statistical_parity_with_sequential_engine():
    des = _des_delivery_counts(10)
    prog = _lowered_program()
    out = run_replicated_bss(prog, 256, jax.random.PRNGKey(42))
    assert out["all_done"]
    rep = np.asarray(out["srv_rx"], dtype=np.float64)

    # per-STA offered load: 8 sends each (1.0→1.8 s, 0.1 s interval)
    offered = N_STAS * 8
    assert 0 < rep.mean() <= offered
    assert 0 < des.mean() <= offered

    # distribution-level agreement: means within 3× the combined spread
    # of the two estimators (plus 1 frame of timing-model slack)
    sem = math.sqrt(
        des.var(ddof=1) / len(des) + rep.var(ddof=1) / len(rep)
    )
    assert abs(des.mean() - rep.mean()) <= 3.0 * sem + 1.5, (
        f"DES mean {des.mean():.2f} vs replicated mean {rep.mean():.2f} "
        f"(sem {sem:.2f}; des {des}, rep mean/std {rep.mean():.2f}/{rep.std():.2f})"
    )


def test_same_key_is_deterministic():
    prog = _lowered_program()
    a = run_replicated_bss(prog, 32, jax.random.PRNGKey(7))
    b = run_replicated_bss(prog, 32, jax.random.PRNGKey(7))
    np.testing.assert_array_equal(np.asarray(a["srv_rx"]), np.asarray(b["srv_rx"]))
    c = run_replicated_bss(prog, 32, jax.random.PRNGKey(8))
    assert not np.array_equal(np.asarray(a["srv_rx"]), np.asarray(c["srv_rx"]))


def test_mesh_sharded_matches_single_device():
    from jax.sharding import Mesh

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    mesh = Mesh(np.array(devs[:8]), ("replica",))
    prog = _lowered_program()
    plain = run_replicated_bss(prog, 64, jax.random.PRNGKey(3))
    sharded = run_replicated_bss(prog, 64, jax.random.PRNGKey(3), mesh=mesh)
    assert sharded["all_done"]
    np.testing.assert_array_equal(
        np.asarray(plain["srv_rx"]), np.asarray(sharded["srv_rx"])
    )
    np.testing.assert_array_equal(
        np.asarray(plain["cli_rx"]), np.asarray(sharded["cli_rx"])
    )


def test_echo_replies_bounded_by_requests():
    prog = _lowered_program()
    out = run_replicated_bss(prog, 64, jax.random.PRNGKey(5))
    cli = np.asarray(out["cli_rx"]).sum(axis=1)
    srv = np.asarray(out["srv_rx"])
    assert (cli <= srv).all()


class TestShortHorizonGuard:
    """lower_bss skips association/ARP/ADDBA warm-up; a horizon within
    ~5x of that budget must warn loudly (0.2 s), a comfortable one must
    stay silent (1.6 s)."""

    def _lower_at(self, sim_end_s):
        _reset_world()
        sta_devices, ap_device, clients, _ = _build_bss()
        prog = lower_bss(
            [sta_devices.Get(i) for i in range(N_STAS)],
            ap_device, clients, sim_end_s,
        )
        _reset_world()
        return prog

    def test_short_horizon_warns(self):
        with pytest.warns(UserWarning, match="warm-up"):
            self._lower_at(0.2)

    def test_comfortable_horizon_is_silent(self):
        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("error")
            prog = self._lower_at(1.6)
        assert prog.sim_end_us == 1_600_000
