"""TCP tests — mirrors upstream's src/internet/test/tcp-* strategy:
whole-topology system tests asserting delivered bytes, retransmission
under forced loss, cwnd evolution per variant."""

import pytest

from tpudes.core import Seconds, Simulator
from tpudes.helper.applications import BulkSendHelper, PacketSinkHelper
from tpudes.helper.containers import NodeContainer
from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
from tpudes.helper.point_to_point import PointToPointHelper
from tpudes.models.internet.tcp import TcpL4Protocol, TcpSocketBase
from tpudes.models.internet.tcp_congestion import TCP_VARIANTS
from tpudes.network.address import InetSocketAddress, Ipv4Address
from tpudes.network.error_model import ReceiveListErrorModel
from tpudes.network.packet import Packet


def _p2p_pair(rate="5Mbps", delay="2ms"):
    nodes = NodeContainer()
    nodes.Create(2)
    p2p = PointToPointHelper()
    p2p.SetDeviceAttribute("DataRate", rate)
    p2p.SetChannelAttribute("Delay", delay)
    devices = p2p.Install(nodes)
    stack = InternetStackHelper()
    stack.Install(nodes)
    address = Ipv4AddressHelper()
    address.SetBase("10.1.1.0", "255.255.255.0")
    interfaces = address.Assign(devices)
    return nodes, devices, interfaces


def test_handshake_and_small_transfer():
    nodes, devices, interfaces = _p2p_pair()
    tcp1 = nodes.Get(1).GetObject(TcpL4Protocol)
    server = tcp1.CreateSocket()
    server.Bind(InetSocketAddress(Ipv4Address.GetAny(), 8080))
    server.Listen()
    received = []
    server.SetRecvCallback(lambda s: received.append(s.Recv().GetSize()))

    tcp0 = nodes.Get(0).GetObject(TcpL4Protocol)
    client = tcp0.CreateSocket()
    connected = []
    client.SetConnectCallback(lambda s: connected.append(True), lambda s: None)

    def go():
        client.Connect(InetSocketAddress(interfaces.GetAddress(1), 8080))
        client.Send(Packet(1000))

    Simulator.Schedule(Seconds(0.1), go)
    Simulator.Stop(Seconds(3))
    Simulator.Run()
    assert connected == [True]
    assert sum(received) == 1000
    assert client._state == TcpSocketBase.ESTABLISHED


def test_bulk_transfer_delivers_all_bytes():
    nodes, devices, interfaces = _p2p_pair()
    sink_helper = PacketSinkHelper(
        "tpudes::TcpSocketFactory", InetSocketAddress(Ipv4Address.GetAny(), 9000)
    )
    sink_apps = sink_helper.Install(nodes.Get(1))
    sink_apps.Start(Seconds(0.0))
    sink_apps.Stop(Seconds(20.0))

    bulk = BulkSendHelper(
        "tpudes::TcpSocketFactory", InetSocketAddress(interfaces.GetAddress(1), 9000)
    )
    bulk.SetAttribute("MaxBytes", 200_000)
    apps = bulk.Install(nodes.Get(0))
    apps.Start(Seconds(0.5))
    apps.Stop(Seconds(20.0))

    Simulator.Stop(Seconds(20))
    Simulator.Run()
    sink = sink_apps.Get(0)
    assert sink.GetTotalRx() == 200_000
    # 5 Mbps: 200kB = 1.6Mbit ≥ 0.32 s of airtime — sanity: finished
    assert apps.Get(0).total_bytes == 200_000


def test_retransmission_recovers_forced_losses():
    nodes, devices, interfaces = _p2p_pair()
    # drop the 4th, 9th packets arriving at the sink's device
    em = ReceiveListErrorModel()
    em.SetList([3, 8])
    devices.Get(1).SetReceiveErrorModel(em)

    sink_helper = PacketSinkHelper(
        "tpudes::TcpSocketFactory", InetSocketAddress(Ipv4Address.GetAny(), 9000)
    )
    sink_apps = sink_helper.Install(nodes.Get(1))
    sink_apps.Start(Seconds(0.0))
    sink_apps.Stop(Seconds(30.0))
    bulk = BulkSendHelper(
        "tpudes::TcpSocketFactory", InetSocketAddress(interfaces.GetAddress(1), 9000)
    )
    bulk.SetAttribute("MaxBytes", 60_000)
    apps = bulk.Install(nodes.Get(0))
    apps.Start(Seconds(0.5))
    apps.Stop(Seconds(30.0))

    Simulator.Stop(Seconds(30))
    Simulator.Run()
    assert sink_apps.Get(0).GetTotalRx() == 60_000  # losses fully recovered


def test_cwnd_grows_then_halves_on_fast_retransmit():
    nodes, devices, interfaces = _p2p_pair(rate="10Mbps", delay="5ms")
    em = ReceiveListErrorModel()
    em.SetList([40])  # one mid-stream loss → 3 dupacks → recovery
    devices.Get(1).SetReceiveErrorModel(em)

    sink_helper = PacketSinkHelper(
        "tpudes::TcpSocketFactory", InetSocketAddress(Ipv4Address.GetAny(), 9000)
    )
    sink_apps = sink_helper.Install(nodes.Get(1))
    sink_apps.Start(Seconds(0.0))
    sink_apps.Stop(Seconds(30.0))
    bulk = BulkSendHelper(
        "tpudes::TcpSocketFactory", InetSocketAddress(interfaces.GetAddress(1), 9000)
    )
    bulk.SetAttribute("MaxBytes", 400_000)
    apps = bulk.Install(nodes.Get(0))
    apps.Start(Seconds(0.1))
    apps.Stop(Seconds(30.0))

    cwnd_trace = []
    retx = []

    def attach():
        sock = apps.Get(0)._socket
        sock.TraceConnectWithoutContext("CongestionWindow", lambda old, new: cwnd_trace.append((old, new)))
        sock.TraceConnectWithoutContext("Retransmit", lambda seq: retx.append(seq))

    # attach as soon as the socket exists (app starts at 0.1s); the loss
    # of packet #40 triggers fast retransmit ~0.14s, so attaching later
    # would miss the Retransmit/CongestionWindow events entirely
    Simulator.Schedule(Seconds(0.101), attach)
    Simulator.Stop(Seconds(30))
    Simulator.Run()
    assert sink_apps.Get(0).GetTotalRx() == 400_000
    assert len(retx) >= 1  # fast retransmit happened
    # at least one decrease event (recovery), and growth before it
    decreases = [(o, n) for o, n in cwnd_trace if n < o]
    assert decreases, f"no cwnd decrease observed in {cwnd_trace[:20]}"


@pytest.mark.parametrize("variant", sorted(TCP_VARIANTS))
def test_all_variants_complete_transfer(variant):
    nodes, devices, interfaces = _p2p_pair()
    tcp0 = nodes.Get(0).GetObject(TcpL4Protocol)
    tcp0.socket_type = variant  # the SocketType knob

    sink_helper = PacketSinkHelper(
        "tpudes::TcpSocketFactory", InetSocketAddress(Ipv4Address.GetAny(), 9000)
    )
    sink_apps = sink_helper.Install(nodes.Get(1))
    sink_apps.Start(Seconds(0.0))
    sink_apps.Stop(Seconds(25.0))
    bulk = BulkSendHelper(
        "tpudes::TcpSocketFactory", InetSocketAddress(interfaces.GetAddress(1), 9000)
    )
    bulk.SetAttribute("MaxBytes", 100_000)
    apps = bulk.Install(nodes.Get(0))
    apps.Start(Seconds(0.5))
    apps.Stop(Seconds(25.0))
    Simulator.Stop(Seconds(25))
    Simulator.Run()
    assert sink_apps.Get(0).GetTotalRx() == 100_000
    assert type(apps.Get(0)._socket.GetCongestionControl()).__name__ == variant


def test_fin_teardown_reaches_closed():
    nodes, devices, interfaces = _p2p_pair()
    tcp1 = nodes.Get(1).GetObject(TcpL4Protocol)
    server = tcp1.CreateSocket()
    server.Bind(InetSocketAddress(Ipv4Address.GetAny(), 8080))
    server.Listen()
    forked = []
    server.SetAcceptCallback(lambda s, a: True, lambda s, a: forked.append(s))
    # echo-close: server closes its side when the peer's FIN arrives
    server.SetCloseCallbacks(lambda s: s.Close(), lambda s: None)

    tcp0 = nodes.Get(0).GetObject(TcpL4Protocol)
    client = tcp0.CreateSocket()

    def go():
        client.Connect(InetSocketAddress(interfaces.GetAddress(1), 8080))
        client.Send(Packet(500))
        Simulator.Schedule(Seconds(1.0), client.Close)

    Simulator.Schedule(Seconds(0.1), go)
    Simulator.Stop(Seconds(10))
    Simulator.Run()
    assert forked, "no connection accepted"
    srv_sock = forked[0]
    # client side went FIN_WAIT → (TIME_WAIT or CLOSED); server reached
    # LAST_ACK→CLOSED after closing in response
    assert client._state in (TcpSocketBase.TIME_WAIT, TcpSocketBase.CLOSED)
    assert srv_sock._state in (TcpSocketBase.CLOSED, TcpSocketBase.LAST_ACK)


def test_time_wait_timer_held_and_cancelled_on_teardown():
    """Promoted EVT001 finding: _enter_time_wait dropped its 2*MSL
    EventId, so a socket torn down mid-TIME_WAIT could not cancel the
    timer — 240 s later _time_wait_done fired on the dead socket and
    re-notified its close callbacks.  The EventId is now held and
    _cleanup cancels it."""
    from tpudes.models.internet.tcp import MSL_S

    nodes, devices, interfaces = _p2p_pair()
    tcp1 = nodes.Get(1).GetObject(TcpL4Protocol)
    server = tcp1.CreateSocket()
    server.Bind(InetSocketAddress(Ipv4Address.GetAny(), 8080))
    server.Listen()
    server.SetAcceptCallback(lambda s, a: True, lambda s, a: None)
    server.SetCloseCallbacks(lambda s: s.Close(), lambda s: None)

    tcp0 = nodes.Get(0).GetObject(TcpL4Protocol)
    client = tcp0.CreateSocket()
    closes = []
    client.SetCloseCallbacks(lambda s: closes.append(Simulator.Now()), lambda s: None)

    def go():
        client.Connect(InetSocketAddress(interfaces.GetAddress(1), 8080))
        client.Send(Packet(500))
        Simulator.Schedule(Seconds(1.0), client.Close)

    probe = {}

    def teardown_mid_time_wait():
        probe["state"] = client._state
        probe["held"] = client._time_wait_event is not None
        client._cleanup()  # app/protocol teardown before 2*MSL elapses
        probe["cancelled"] = client._time_wait_event is None

    Simulator.Schedule(Seconds(0.1), go)
    Simulator.Schedule(Seconds(5.0), teardown_mid_time_wait)
    Simulator.Stop(Seconds(2 * MSL_S + 10.0))
    Simulator.Run()
    assert probe["state"] == TcpSocketBase.TIME_WAIT
    assert probe["held"], "the 2*MSL EventId must be HELD, not dropped"
    assert probe["cancelled"]
    # the cancelled timer must NOT have fired on the torn-down socket:
    # no post-teardown close notification, state untouched by
    # _time_wait_done
    assert not closes, f"TIME_WAIT timer fired after teardown: {closes}"
    assert client._state == TcpSocketBase.TIME_WAIT


def test_htcp_throughput_ratio_guards_beta_adaptation():
    """Promoted REG001 finding: ThroughputRatio now guards H-TCP's
    adaptive backoff — beta follows RTTmin/RTTmax across stable epochs
    and falls back to the 0.5 default when the epoch throughput swings
    by more than the ratio (the RTT spread is stale then)."""
    from tpudes.models.internet.tcp_congestion import TcpHtcp, TcpSocketState

    ops = TcpHtcp()
    tcb = TcpSocketState(segment_size=1000)
    betas = []
    # two stable epochs: identical ack pattern → throughput unchanged
    for _ in range(2):
        ops.PktsAcked(tcb, 100, 0.06)
        ops.PktsAcked(tcb, 100, 0.10)
        ops.GetSsThresh(tcb, tcb.cwnd)
        betas.append(ops._beta)
    # starved epoch: throughput collapses past the 20% guard
    ops.PktsAcked(tcb, 5, 0.06)
    ops.GetSsThresh(tcb, tcb.cwnd)
    betas.append(ops._beta)

    assert betas[0] == pytest.approx(0.6)  # RTTmin/RTTmax = 0.06/0.10
    assert betas[1] == pytest.approx(0.6)  # stable: still adaptive
    assert betas[2] == pytest.approx(0.5)  # unstable: default backoff
