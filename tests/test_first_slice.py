"""Minimum end-to-end slice (SURVEY.md 7 step 3): first.cc equivalent,
with the event-trace golden oracle later engines must reproduce.

The expected timings are *upstream ns-3's own printed values* for
first.cc (2.00369s / 2.00737s): 1054 bytes (1024 payload + 8 UDP + 20
IPv4 + 2 PPP) at 5 Mbps = 1.6864 ms serialization + 2 ms propagation.
"""


from tpudes.core.nstime import MilliSeconds, Seconds
from tpudes.core.simulator import Simulator
from tpudes.helper import (
    InternetStackHelper,
    Ipv4AddressHelper,
    NodeContainer,
    PointToPointHelper,
    UdpEchoClientHelper,
    UdpEchoServerHelper,
)
from tpudes.network.address import InetSocketAddress, Ipv4Address


def build_first(packets=1, data_rate="5Mbps", delay="2ms"):
    nodes = NodeContainer()
    nodes.Create(2)
    p2p = PointToPointHelper()
    p2p.SetDeviceAttribute("DataRate", data_rate)
    p2p.SetChannelAttribute("Delay", delay)
    devices = p2p.Install(nodes)
    stack = InternetStackHelper()
    stack.Install(nodes)
    address = Ipv4AddressHelper()
    address.SetBase("10.1.1.0", "255.255.255.0")
    interfaces = address.Assign(devices)

    server_apps = UdpEchoServerHelper(9).Install(nodes.Get(1))
    server_apps.Start(Seconds(1.0))
    server_apps.Stop(Seconds(10.0))
    client_helper = UdpEchoClientHelper(interfaces.GetAddress(1), 9)
    client_helper.SetAttribute("MaxPackets", packets)
    client_helper.SetAttribute("Interval", Seconds(1.0))
    client_helper.SetAttribute("PacketSize", 1024)
    client_apps = client_helper.Install(nodes.Get(0))
    client_apps.Start(Seconds(2.0))
    client_apps.Stop(Seconds(10.0))
    return nodes, devices, interfaces, server_apps.Get(0), client_apps.Get(0)


def test_first_golden_trace():
    nodes, devices, interfaces, server, client = build_first()
    trace = []
    client.TraceConnectWithoutContext("Tx", lambda p: trace.append(("ctx", Simulator.Now().ticks, p.GetSize())))
    server.TraceConnectWithoutContext("Rx", lambda p: trace.append(("srx", Simulator.Now().ticks, p.GetSize())))
    client.TraceConnectWithoutContext("Rx", lambda p: trace.append(("crx", Simulator.Now().ticks, p.GetSize())))
    Simulator.Run()
    # golden: tx at 2s; server rx at 2s + 1.6864ms + 2ms; client rx after
    # the symmetric return trip — ns-3 first.cc's exact printed times
    assert trace == [
        ("ctx", 2_000_000_000, 1024),
        ("srx", 2_003_686_400, 1024),
        ("crx", 2_007_372_800, 1024),
    ]


def test_first_addresses():
    nodes, devices, interfaces, server, client = build_first()
    assert str(interfaces.GetAddress(0)) == "10.1.1.1"
    assert str(interfaces.GetAddress(1)) == "10.1.1.2"


def test_echo_multiple_packets():
    nodes, devices, interfaces, server, client = build_first(packets=5)
    Simulator.Run()
    assert client.sent == 5
    assert server.received == 5
    assert client.received == 5


def test_queueing_delay_back_to_back():
    """Two packets sent at once: the second's rx is one serialization
    time after the first's (tx queue drains serially)."""
    nodes, devices, interfaces, server, client = build_first()
    from tpudes.network.packet import Packet
    from tpudes.network.socket import SocketFactory

    rx_times = []
    server.TraceConnectWithoutContext("Rx", lambda p: rx_times.append(Simulator.Now().ticks))

    def burst():
        sock = SocketFactory.CreateSocket(nodes.Get(0), "tpudes::UdpSocketFactory")
        sock.Bind()
        dst = InetSocketAddress(interfaces.GetAddress(1), 9)
        sock.SendTo(Packet(1024), 0, dst)
        sock.SendTo(Packet(1024), 0, dst)

    Simulator.Schedule(Seconds(5), burst)
    Simulator.Run()
    assert len(rx_times) >= 2
    ser_time = rx_times[-1] - rx_times[-2]
    assert ser_time == 1_686_400  # exactly one 1054-byte serialization @5Mbps


def test_three_node_forwarding():
    """n0 -- n1 -- n2 with static routes through n1: exercises TTL
    decrement and UnicastForward."""
    nodes = NodeContainer()
    nodes.Create(3)
    p2p = PointToPointHelper()
    p2p.SetDeviceAttribute("DataRate", "5Mbps")
    p2p.SetChannelAttribute("Delay", "1ms")
    d01 = p2p.Install(nodes.Get(0), nodes.Get(1))
    d12 = p2p.Install(nodes.Get(1), nodes.Get(2))
    InternetStackHelper().Install(nodes)
    addr = Ipv4AddressHelper()
    addr.SetBase("10.1.1.0", "255.255.255.0")
    i01 = addr.Assign(d01)
    addr.SetBase("10.1.2.0", "255.255.255.0")
    i12 = addr.Assign(d12)

    from tpudes.models.internet.ipv4 import Ipv4L3Protocol

    # default routes via n1 on the edge nodes
    ip0 = nodes.Get(0).GetObject(Ipv4L3Protocol)
    ip2 = nodes.Get(2).GetObject(Ipv4L3Protocol)
    ip0.GetRoutingProtocol().SetDefaultRoute(i01.GetAddress(1), 1)
    ip2.GetRoutingProtocol().SetDefaultRoute(i12.GetAddress(0), 1)

    server_apps = UdpEchoServerHelper(9).Install(nodes.Get(2))
    server_apps.Start(Seconds(0.5))
    client_helper = UdpEchoClientHelper(i12.GetAddress(1), 9)
    client_helper.SetAttribute("MaxPackets", 2)
    client_apps = client_helper.Install(nodes.Get(0))
    client_apps.Start(Seconds(1.0))

    forwards = []
    ip1 = nodes.Get(1).GetObject(Ipv4L3Protocol)
    ip1.TraceConnectWithoutContext("UnicastForward", lambda h, p, i: forwards.append(h.ttl))

    Simulator.Stop(Seconds(20))
    Simulator.Run()
    server = server_apps.Get(0)
    client = client_apps.Get(0)
    assert server.received == 2
    assert client.received == 2
    assert len(forwards) == 4  # 2 requests + 2 replies through n1
    assert all(ttl == 63 for ttl in forwards)


def test_interface_down_drops():
    nodes, devices, interfaces, server, client = build_first()
    from tpudes.models.internet.ipv4 import Ipv4L3Protocol

    drops = []
    ip0 = nodes.Get(0).GetObject(Ipv4L3Protocol)
    ip0.TraceConnectWithoutContext("Drop", lambda h, p, r: drops.append(r))
    Simulator.Schedule(MilliSeconds(1500), ip0.SetDown, 1)
    Simulator.Run()
    assert server.received == 0
    assert drops and drops[0] == Ipv4L3Protocol.DROP_INTERFACE_DOWN


def test_loopback_delivery():
    nodes, devices, interfaces, server, client = build_first()
    from tpudes.network.packet import Packet
    from tpudes.network.socket import SocketFactory

    got = []

    def setup():
        recv = SocketFactory.CreateSocket(nodes.Get(0), "tpudes::UdpSocketFactory")
        recv.Bind(InetSocketAddress(Ipv4Address.GetAny(), 777))
        recv.SetRecvCallback(lambda s: got.append(s.Recv().GetSize()))
        send = SocketFactory.CreateSocket(nodes.Get(0), "tpudes::UdpSocketFactory")
        send.Bind()
        send.SendTo(Packet(64), 0, InetSocketAddress("127.0.0.1", 777))

    Simulator.Schedule(Seconds(3), setup)
    Simulator.Run()
    assert got == [64]
