"""lr-wpan (802.15.4) + 6LoWPAN — upstream src/lr-wpan/test and
src/sixlowpan/test strategy: acked data within radio range, CSMA/CA
deference, then IPv6 riding the adaptation layer with IPHC compression
and RFC 4944 fragmentation."""

from tpudes.core import Seconds, Simulator
from tpudes.helper.containers import NodeContainer
from tpudes.helper.internet import InternetStackHelper, Ipv6AddressHelper
from tpudes.models.lr_wpan import LrWpanHelper
from tpudes.models.mobility import ListPositionAllocator, MobilityHelper, Vector
from tpudes.models.sixlowpan import (
    SixLowPanFrag,
    SixLowPanHelper,
    SixLowPanIphc,
)
from tpudes.network.packet import Packet


def _reset():
    from tpudes.core.world import reset_world

    reset_world()


def _pan(n=2, spacing=20.0):
    nodes = NodeContainer()
    nodes.Create(n)
    alloc = ListPositionAllocator()
    for i in range(n):
        alloc.Add(Vector(i * spacing, 0.0, 0.0))
    mob = MobilityHelper()
    mob.SetPositionAllocator(alloc)
    mob.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    mob.Install(nodes)
    helper = LrWpanHelper()
    devices = helper.Install(nodes)
    return nodes, devices


# --- lr-wpan MAC/PHY -------------------------------------------------------

def test_acked_unicast_within_range():
    _reset()
    nodes, devices = _pan(2, spacing=20.0)
    got = []
    nodes.Get(1).RegisterProtocolHandler(
        lambda dev, pkt, proto, sender: got.append(pkt.GetSize()),
        0x86DD, devices.Get(1),
    )
    drops = []
    devices.Get(0).TraceConnectWithoutContext(
        "MacTxDrop", lambda pkt: drops.append(1)
    )
    Simulator.Schedule(
        Seconds(0.1),
        devices.Get(0).Send, Packet(50), devices.Get(1).GetAddress(), 0x86DD,
    )
    Simulator.Stop(Seconds(1.0))
    Simulator.Run()
    assert got == [50]
    assert not drops
    _reset()


def test_out_of_range_unicast_retries_then_drops():
    _reset()
    nodes, devices = _pan(2, spacing=100_000.0)  # far below sensitivity
    drops = []
    devices.Get(0).TraceConnectWithoutContext(
        "MacTxDrop", lambda pkt: drops.append(Simulator.Now().GetSeconds())
    )
    Simulator.Schedule(
        Seconds(0.1),
        devices.Get(0).Send, Packet(20), devices.Get(1).GetAddress(), 0x86DD,
    )
    Simulator.Stop(Seconds(2.0))
    Simulator.Run()
    # 1 + macMaxFrameRetries transmissions, then the drop
    assert len(drops) == 1
    _reset()


def test_csma_ca_defers_while_medium_busy():
    """A long broadcast from node 0 keeps node 1's CCA busy: node 1's
    own frame backs off at least once before transmitting."""
    _reset()
    nodes, devices = _pan(3, spacing=10.0)
    backoffs = []
    devices.Get(1).TraceConnectWithoutContext(
        "MacTxBackoff", lambda pkt: backoffs.append(1)
    )
    got = []
    nodes.Get(2).RegisterProtocolHandler(
        lambda dev, pkt, proto, sender: got.append(pkt.GetSize()),
        0x86DD, devices.Get(2),
    )
    # node 0: a max-size broadcast (~4.3 ms airtime); node 1 tries to
    # send right in the middle of it
    Simulator.Schedule(
        Seconds(0.100), devices.Get(0).Send, Packet(100), None, 0x86DD
    )
    Simulator.Schedule(
        Seconds(0.1012),
        devices.Get(1).Send, Packet(30), devices.Get(2).GetAddress(), 0x86DD,
    )
    Simulator.Stop(Seconds(1.0))
    Simulator.Run()
    assert 30 in got          # it did get through eventually
    assert backoffs, "CCA never found the medium busy"
    _reset()


def test_mtu_is_the_15_4_budget():
    _reset()
    nodes, devices = _pan(2)
    assert devices.Get(0).GetMtu() == 110  # 127 - 15 MAC - 2 FCS
    _reset()


# --- 6LoWPAN over lr-wpan --------------------------------------------------

def _six_pan(n=2, spacing=20.0):
    nodes, inner = _pan(n, spacing)
    InternetStackHelper().Install(nodes)
    six = SixLowPanHelper().Install(inner)
    a = Ipv6AddressHelper()
    a.SetBase("2001:db8:15:4::", 64)
    ifcs = a.Assign(six)
    return nodes, inner, six, ifcs


def test_ping6_over_sixlowpan_with_nd():
    from tpudes.models.internet.icmpv6 import Ping6

    _reset()
    nodes, inner, six, ifcs = _six_pan(2)
    ping = Ping6(Remote=str(ifcs.GetAddress(1, 1)), Interval=0.25, Size=16)
    nodes.Get(0).AddApplication(ping)
    ping.SetStartTime(Seconds(0.5))
    ping.SetStopTime(Seconds(2.0))
    Simulator.Stop(Seconds(3.0))
    Simulator.Run()
    assert len(ping.rtts) >= 5, ping.rtts
    # 250 kb/s serialization dominates: RTTs in the low milliseconds
    assert all(0.001 < r < 0.05 for r in ping.rtts), ping.rtts
    _reset()


def test_iphc_compression_shrinks_the_wire_frame():
    """A 16-byte echo over 6LoWPAN must ride a frame whose size
    reflects the 7-byte compressed header, not the 40-byte IPv6 one."""
    _reset()
    nodes, inner, six, ifcs = _six_pan(2)
    sizes = []
    inner.Get(0).TraceConnectWithoutContext(
        "PhyTxBegin", lambda pkt: sizes.append(
            (pkt.GetSize(), pkt.FindHeader(SixLowPanIphc) is not None)
        )
    )
    from tpudes.models.internet.icmpv6 import Icmpv6L4Protocol

    # ping the EUI-64 LINK-LOCAL address: both interface identifiers
    # are MAC-derived, so IPHC elides them (the helper-assigned global
    # ::1/::2 IIDs are not derivable and ride the uncompressed escape)
    Simulator.Schedule(
        Seconds(0.5),
        nodes.Get(0).GetObject(Icmpv6L4Protocol).SendEcho,
        ifcs.GetAddress(1, 0), 0x42, 1, 16,
    )
    Simulator.Stop(Seconds(1.0))
    Simulator.Run()
    data = [s for s, has in sizes if has]
    assert data, sizes
    # 16 payload + 8 icmpv6 + 7 IPHC + 15 MAC = 46 (vs 79 uncompressed)
    assert min(data) <= 50, sizes
    _reset()


def test_large_datagram_fragments_and_reassembles():
    from tpudes.helper.applications import (
        UdpEchoClientHelper,
        UdpEchoServerHelper,
    )

    _reset()
    nodes, inner, six, ifcs = _six_pan(2)
    frames = []
    inner.Get(0).TraceConnectWithoutContext(
        "PhyTxBegin", lambda pkt: frames.append(pkt)
    )
    server = UdpEchoServerHelper(9)
    sapps = server.Install(nodes.Get(1))
    sapps.Start(Seconds(0.1))
    client = UdpEchoClientHelper(ifcs.GetAddress(1, 1), 9)
    client.SetAttribute("MaxPackets", 1)
    client.SetAttribute("PacketSize", 600)
    capps = client.Install(nodes.Get(0))
    capps.Start(Seconds(0.5))
    Simulator.Stop(Seconds(3.0))
    Simulator.Run()
    assert sapps.Get(0).received == 1
    assert capps.Get(0).received == 1
    frag_frames = [
        p for p in frames if p.FindHeader(SixLowPanFrag) is not None
    ]
    # 600 B UDP payload + 8 UDP + 7 IPHC ≈ 615 adapted bytes over
    # ~102-byte fragments → 7 frames, every one within the PHY budget
    assert len(frag_frames) >= 6, len(frag_frames)
    assert all(p.GetSize() <= 127 for p in frames)
    _reset()


# --- ADVICE.md round-5 regressions ----------------------------------------

def test_triple_overlap_collision_does_not_poison_next_clean_frame():
    """ADVICE.md medium (lr_wpan collision bookkeeping): with >=3
    overlapping receptions the old single _rx_overlaps counter kept a
    positive residue after the pile-up drained, falsely dropping the
    NEXT clean frame.  Per-reception corrupted flags drop exactly the
    overlapped frames and nothing after."""
    from tpudes.models.lr_wpan import LrWpanMacHeader

    _reset()
    nodes, devices = _pan(2, spacing=20.0)
    rx = devices.Get(1)
    got = []
    nodes.Get(1).RegisterProtocolHandler(
        lambda dev, pkt, proto, sender: got.append(pkt.GetSize()),
        0x86DD, rx,
    )
    drops = []
    rx.TraceConnectWithoutContext(
        "PhyRxDrop", lambda pkt, reason: drops.append(reason)
    )

    def bcast(seq):
        p = Packet(50)
        p.AddHeader(LrWpanMacHeader(
            LrWpanMacHeader.DATA, seq,
            dst=rx.GetBroadcast(), src=devices.Get(0).GetAddress(),
        ))
        return p

    # A<-B<-C pile-up at the PHY, then a clean frame well afterwards
    for seq, t in ((1, 0.100), (2, 0.105), (3, 0.106)):
        Simulator.Schedule(Seconds(t), rx.phy_start_rx, bcast(seq), -40.0, 0.010)
    Simulator.Schedule(Seconds(0.2), rx.phy_start_rx, bcast(4), -40.0, 0.010)
    Simulator.Stop(Seconds(0.5))
    Simulator.Run()
    assert drops.count("collision") == 3, drops
    assert got == [50], got
    _reset()


def test_stranded_sixlowpan_fragment_expires_with_drop_trace():
    """ADVICE.md low (6LoWPAN reassembly leak): a buffer whose
    fragments never complete must expire (mirroring
    Ipv4L3Protocol._expire_fragments), firing the Drop trace and
    freeing the (src, tag) key before the 16-bit tag wraps."""
    from tpudes.models.sixlowpan import SIXLOWPAN_PROT

    _reset()
    nodes, devices = _pan(2)
    six = SixLowPanHelper().Install(devices)
    wrap = six.Get(1)
    drops = []
    wrap.TraceConnectWithoutContext("Drop", lambda reason: drops.append(reason))
    delivered = []
    nodes.Get(1).RegisterProtocolHandler(
        lambda dev, pkt, proto, sender: delivered.append(pkt),
        0x86DD, wrap,
    )
    # first fragment of a 200-byte datagram; the rest never arrive
    frag = Packet(40)
    frag.AddHeader(SixLowPanFrag(size=200, tag=7, offset=0, first=True))
    Simulator.Schedule(
        Seconds(0.1), wrap._receive_from_inner,
        devices.Get(1), frag, SIXLOWPAN_PROT, devices.Get(0).GetAddress(),
    )
    Simulator.Stop(Seconds(wrap.REASSEMBLY_EXPIRATION_S + 1.0))
    Simulator.Run()
    assert drops == ["reassembly-timeout"], drops
    assert wrap._frags == {}, wrap._frags
    assert delivered == []
    _reset()
