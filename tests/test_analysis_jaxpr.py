"""Trace-aware analysis (tpudes.analysis.jaxpr): planted-defect
fixtures for JXL001–JXL005 in both directions, the wired no-gather
acceptance pair, cache-key hygiene on the real engines, and the
dead-key fix regressions.

Fixture manifests run through the exact production rule code
(lint_manifest), so a rule that stops firing on its planted defect
fails here before it silently stops gating the engines.
"""

import dataclasses

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tpudes.analysis.jaxpr import (  # noqa: E402
    FlipSpec,
    ScaleAxis,
    TraceEntry,
    TraceManifest,
    TraceVariant,
    lint_manifest,
)

SYNTH = "tpudes/parallel/synthetic.py"


def _manifest(entries_fn, flips=None, **kw):
    return TraceManifest(
        engine="synth",
        path=SYNTH,
        variants=lambda: [TraceVariant("base", entries_fn)],
        flips=flips,
        **kw,
    )


def _codes(findings):
    return [f.code for f in findings]


# --- JXL001 forbidden primitives -------------------------------------------


def test_jxl001_gather_fires_only_under_no_gather_contract():
    x = jnp.arange(8, dtype=jnp.float32)
    idx = jnp.asarray([3, 1], jnp.int32)

    def kernel(v):
        return jnp.take(v, idx)

    entries = lambda: [TraceEntry("step", kernel, (x,))]  # noqa: E731
    armed = lint_manifest(_manifest(entries, no_gather=True))
    assert any(
        f.code == "JXL001" and "gather" in f.message for f in armed
    ), armed
    # same trace without the contract: no finding
    assert "JXL001" not in _codes(lint_manifest(_manifest(entries)))


def test_jxl001_gather_ban_spares_init_entries():
    x = jnp.arange(8, dtype=jnp.float32)
    idx = jnp.asarray([3, 1], jnp.int32)
    entries = lambda: [  # noqa: E731
        TraceEntry("init", lambda: jnp.take(x, idx), (), kernel=False)
    ]
    assert "JXL001" not in _codes(
        lint_manifest(_manifest(entries, no_gather=True))
    )


def test_jxl001_callback_forbidden_everywhere():
    def kernel(v):
        return jax.pure_callback(
            lambda a: np.asarray(a), jax.ShapeDtypeStruct((3,), np.float32), v
        )

    entries = lambda: [  # noqa: E731
        TraceEntry("step", kernel, (jnp.zeros(3, jnp.float32),))
    ]
    found = lint_manifest(_manifest(entries))
    assert any(
        f.code == "JXL001" and "callback" in f.message for f in found
    ), found


def test_planted_gather_in_wired_step_fires_and_real_kernel_is_clean():
    """ISSUE acceptance: a jnp.take smuggled into the wired step body
    must produce the JXL001 finding, and today's kernels must not."""
    from tpudes.parallel import wired

    prog = wired._trace_prog()
    init_state, advance = wired.build_wired_advance(prog, wired._TRACE_R)
    carry = init_state(jax.random.PRNGKey(0))
    P = int(carry["hop"].shape[1])
    no_ing = jnp.full((wired._TRACE_R, P), -1, jnp.int32)
    cols = jnp.arange(P, dtype=jnp.int32)

    def bad_advance(c, ih, ir, t_grant):
        c, metrics = advance(c, ih, ir, t_grant)
        # the smuggled dynamic lookup: per-packet delivery slots read
        # back through a gather instead of the one-hot algebra
        c = dict(c, deliver=jnp.take(c["deliver"], cols, axis=1))
        return c, metrics

    planted = _manifest(
        lambda: [
            TraceEntry(
                "advance", bad_advance,
                (carry, no_ing, no_ing, jnp.int32(8)),
            )
        ],
        no_gather=True,
    )
    found = lint_manifest(planted)
    assert any(
        f.code == "JXL001" and "gather" in f.message for f in found
    ), found

    # the real manifest stays gather-free (its only expected findings
    # are the baselined JXL005 egress-buffer entries)
    real = [
        f for f in lint_manifest(wired.trace_manifest())
        if f.code == "JXL001"
    ]
    assert real == []


# --- JXL002 dtype discipline ------------------------------------------------


def test_jxl002_unpinned_f64_fires_and_pinned_is_clean():
    def leaky(x):
        return jnp.zeros(3) + x  # unpinned: f64 under ambient x64

    def pinned(x):
        return jnp.zeros(3, jnp.float32) + x

    x = jnp.ones(3, jnp.float32)
    found = lint_manifest(
        _manifest(lambda: [TraceEntry("step", leaky, (x,))])
    )
    assert any(
        f.code == "JXL002" and "float64" in f.message for f in found
    ), found
    assert "JXL002" not in _codes(
        lint_manifest(_manifest(lambda: [TraceEntry("step", pinned, (x,))]))
    )


def test_jxl002_bf16_accumulator_fires_and_f32_accumulator_is_clean():
    x = jnp.ones((4, 4), jnp.float32)

    def bad(v):
        lo = v.astype(jnp.bfloat16)
        return lo @ lo  # dot_general accumulating at bf16

    def good(v):
        lo = v.astype(jnp.bfloat16)
        return jnp.einsum(
            "ij,jk->ik", lo, lo, preferred_element_type=jnp.float32
        )

    def run(fn, bf16):
        man = TraceManifest(
            engine="synth", path=SYNTH,
            variants=lambda: [
                TraceVariant(
                    "bf16", lambda: [TraceEntry("step", fn, (x,))],
                    bf16=bf16,
                )
            ],
        )
        return lint_manifest(man)

    found = run(bad, True)
    assert any(
        f.code == "JXL002" and "bfloat16" in f.message for f in found
    ), found
    assert "JXL002" not in _codes(run(good, True))
    # the accumulator check only arms on bf16-tagged variants
    assert "JXL002" not in _codes(run(bad, False))


def test_jxl002_x64_trace_failure_is_a_finding():
    def fragile(x):
        def body(c):
            # the loop carry widens: i32 in, sum-promoted i64 out
            return (c * jnp.ones((2,), jnp.int32)).sum()

        return jax.lax.while_loop(lambda c: c < x, body, jnp.int32(0))

    found = lint_manifest(
        _manifest(
            lambda: [TraceEntry("step", fragile, (jnp.int32(5),))]
        )
    )
    assert any(
        f.code == "JXL002" and "fails under ambient x64" in f.message
        for f in found
    ), found


# --- JXL003 baked-in constants ----------------------------------------------


def test_jxl003_large_const_fires_and_operand_form_is_clean():
    big = jnp.asarray(np.arange(4096, dtype=np.float32))  # 16 KiB

    def baked(x):
        return x + big

    def operand(x, table):
        return x + table

    x = jnp.ones(4096, jnp.float32)
    found = lint_manifest(
        _manifest(lambda: [TraceEntry("step", baked, (x,))])
    )
    assert any(
        f.code == "JXL003" and "baked constant" in f.message
        for f in found
    ), found
    assert "JXL003" not in _codes(
        lint_manifest(
            _manifest(lambda: [TraceEntry("step", operand, (x, big))])
        )
    )
    # raising the budget silences it (per-manifest knob)
    assert "JXL003" not in _codes(
        lint_manifest(
            _manifest(
                lambda: [TraceEntry("step", baked, (x,))],
                const_budget=1 << 20,
            )
        )
    )


# --- JXL004 cache-key hygiene ----------------------------------------------


def _affine(scale_val: float):
    scale = jnp.float32(scale_val)

    def fn(x):
        return x * scale

    return fn


def test_jxl004_dead_key_component_fires():
    x = jnp.ones(3, jnp.float32)
    entries = lambda v=1.0: [  # noqa: E731
        TraceEntry("step", _affine(v), (x,))
    ]
    man = _manifest(
        lambda: entries(),
        flips=lambda: {
            # key separates the flip, but the trace is identical
            "dead_field": FlipSpec(build=lambda: entries(), key_differs=True),
        },
    )
    found = lint_manifest(man)
    assert any(
        f.code == "JXL004" and "dead" in f.message for f in found
    ), found


def test_jxl004_live_component_and_honest_exclusion_are_clean():
    x = jnp.ones(3, jnp.float32)
    entries = lambda v: [TraceEntry("step", _affine(v), (x,))]  # noqa: E731
    man = _manifest(
        lambda: entries(1.0),
        flips=lambda: {
            "live_field": FlipSpec(
                build=lambda: entries(2.0), key_differs=True
            ),
            "excluded_field": FlipSpec(
                build=lambda: entries(1.0), key_differs=False
            ),
        },
    )
    assert "JXL004" not in _codes(lint_manifest(man))


def test_jxl004_missing_key_component_fires():
    x = jnp.ones(3, jnp.float32)
    entries = lambda v: [TraceEntry("step", _affine(v), (x,))]  # noqa: E731
    man = _manifest(
        lambda: entries(1.0),
        flips=lambda: {
            # flip changes the program but the key does not separate it
            "forgotten": FlipSpec(
                build=lambda: entries(2.0), key_differs=False
            ),
        },
    )
    found = lint_manifest(man)
    assert any(
        f.code == "JXL004" and "NOT a cache-key component" in f.message
        for f in found
    ), found


def test_jxl004_constant_burned_traced_operand_fires():
    x = jnp.ones(3, jnp.float32)
    burned_scale = jnp.float32(2.0)

    def burned(x, scale):
        return x * burned_scale  # ignores the declared operand

    def honest(x, scale):
        return x * scale

    def run(fn):
        return lint_manifest(
            _manifest(
                lambda: [
                    TraceEntry(
                        "step", fn, (x, jnp.float32(2.0)),
                        traced={"scale": 1},
                    )
                ]
            )
        )

    found = run(burned)
    assert any(
        f.code == "JXL004" and "'scale'" in f.message for f in found
    ), found
    assert "JXL004" not in _codes(run(honest))


# --- JXL005 donation audit ---------------------------------------------------


def test_jxl005_unused_donated_leaf_fires():
    def fn(carry, x):
        return dict(a=carry["a"] + x, b=jnp.zeros(3, jnp.float32))

    carry = dict(
        a=jnp.zeros(3, jnp.float32), b=jnp.ones(3, jnp.float32)
    )
    found = lint_manifest(
        _manifest(
            lambda: [
                TraceEntry(
                    "advance", fn, (carry, jnp.float32(1.0)),
                    donate=(0,), carry=(0,),
                )
            ]
        )
    )
    assert any(
        f.code == "JXL005" and "never consumed" in f.message
        for f in found
    ), found


def test_jxl005_undonated_carry_and_unaliasable_leaf_fire():
    def fn(carry, x):
        return carry + x

    args = (jnp.zeros(3, jnp.float32), jnp.float32(1.0))
    found = lint_manifest(
        _manifest(
            lambda: [TraceEntry("advance", fn, args, carry=(0,))]
        )
    )
    assert any(
        f.code == "JXL005" and "never donated" in f.message
        for f in found
    ), found

    def shrink(carry):
        return carry[:2]  # donated buffer has no same-shape output

    found = lint_manifest(
        _manifest(
            lambda: [
                TraceEntry(
                    "advance", shrink, (jnp.zeros(3, jnp.float32),),
                    donate=(0,),
                )
            ]
        )
    )
    assert any(
        f.code == "JXL005" and "cannot alias" in f.message
        for f in found
    ), found


def test_jxl005_proper_donated_carry_is_clean():
    def fn(carry, x):
        return carry + x

    assert "JXL005" not in _codes(
        lint_manifest(
            _manifest(
                lambda: [
                    TraceEntry(
                        "advance", fn,
                        (jnp.zeros(3, jnp.float32), jnp.float32(1.0)),
                        donate=(0,), carry=(0,),
                    )
                ]
            )
        )
    )


# --- real-surface checks -----------------------------------------------------


#: the baselined-by-design findings: four JXL005 egress buffers
#: (protocol-overwritten at every window start; dropping them from the
#: input carry would break the carry-in == carry-out chunk-handoff
#: shape) plus the two JXL007 superlinear wired step kernels (the
#: dense one-hot tables ROADMAP item 2 exists to replace)
_EXPECTED_REAL = {"JXL005", "JXL007"}


@pytest.mark.parametrize(
    "module",
    ["replicated", "lte_sm", "tcp_dumbbell", "as_flows", "wired",
     "hybrid"],
)
def test_real_manifest_lints_clean_modulo_baseline(module):
    import importlib

    mod = importlib.import_module(f"tpudes.parallel.{module}")
    found = lint_manifest(mod.trace_manifest())
    unexpected = [f for f in found if f.code not in _EXPECTED_REAL]
    assert unexpected == [], unexpected
    for f in found:
        if f.code == "JXL005":
            assert "eg_" in f.message, f  # only the known egress entries
        else:
            # only the wired engines carry the known quadratic axis
            assert module in ("wired", "hybrid"), f
            assert "scale axis 'n_nodes'" in f.message, f
    if module in ("wired", "hybrid"):
        # ISSUE acceptance: the dense one-hot step kernel must fire
        # JXL007 out of the box, pointing at the --cost report
        jxl7 = [f for f in found if f.code == "JXL007"]
        assert len(jxl7) == 1, found
        assert "exceeds budget" in jxl7[0].message
        assert "--jaxpr --cost" in jxl7[0].message


def test_wired_dead_key_fix_shares_one_runner():
    """Regression for the JXL004-found dead components: programs
    differing only in slot_s / link_owner must hit the SAME cached
    wired runner (they compile identical kernels)."""
    from tpudes.parallel.runtime import RUNTIME
    from tpudes.parallel.wired import run_wired, wired_chain

    prog = wired_chain(n_links=3, n_flows=2, n_slots=40)
    key = jax.random.PRNGKey(7)
    RUNTIME.clear("wired")
    base = run_wired(prog, key)
    misses = RUNTIME.misses
    twin = dataclasses.replace(
        prog, slot_s=0.5,
        link_owner=np.asarray([0, 1, 1], np.int32),
    )
    out = run_wired(twin, key)
    assert RUNTIME.misses == misses  # cache hit: no new runner
    np.testing.assert_array_equal(
        out["deliver_slot"], base["deliver_slot"]
    )


def test_dumbbell_red_knobs_out_of_fifo_key():
    """Regression: in fifo mode the RED parameters never reach the
    program — flipping them must reuse the cached runner."""
    from tpudes.parallel.runtime import RUNTIME
    from tpudes.parallel.tcp_dumbbell import (
        dumbbell_prog_key,
        run_tcp_dumbbell,
    )
    from tpudes.parallel.programs import toy_dumbbell_program

    prog = toy_dumbbell_program(n_flows=2, n_slots=30)
    twin = dataclasses.replace(prog, red_qw=0.5, red_max_p=0.9)
    assert dumbbell_prog_key(prog) == dumbbell_prog_key(twin)
    # ...while a RED-mode program still keys on them
    red = dataclasses.replace(prog, qdisc="red")
    red2 = dataclasses.replace(red, red_qw=0.5)
    assert dumbbell_prog_key(red) != dumbbell_prog_key(red2)

    key = jax.random.PRNGKey(3)
    RUNTIME.clear("dumbbell")
    base = run_tcp_dumbbell(prog, key, replicas=2)
    misses = RUNTIME.misses
    out = run_tcp_dumbbell(twin, key, replicas=2)
    assert RUNTIME.misses == misses
    np.testing.assert_array_equal(out["delivered"], base["delivered"])


# --- JXL006 grad hygiene (ISSUE-15) ----------------------------------------


def _surrogate_manifest(entries_fn):
    return TraceManifest(
        engine="synth",
        path=SYNTH,
        variants=lambda: [
            TraceVariant("base", entries_fn, surrogate=True)
        ],
    )


def test_jxl006_severed_gradient_fires_and_ste_is_clean():
    """A round() in the only path to the output kills the gradient —
    JXL006 fires; the straight-through annotation (tpudes.diff.ste)
    restores a soft path and is clean."""
    from tpudes.diff.surrogate import ste

    x = jnp.ones((3,), jnp.float32)

    def severed(x):
        return jnp.sum(jnp.round(x) * 2.0)

    def annotated(x):
        return jnp.sum(ste(jnp.round(x), x) * 2.0)

    found = lint_manifest(
        _surrogate_manifest(
            lambda: [TraceEntry("loss", severed, (x,), kernel=False,
                               grad_wrt=(0,))]
        )
    )
    assert "JXL006" in _codes(found)
    assert "straight-through" in found[0].message
    assert "JXL006" not in _codes(
        lint_manifest(
            _surrogate_manifest(
                lambda: [TraceEntry("loss", annotated, (x,),
                                    kernel=False, grad_wrt=(0,))]
            )
        )
    )


def test_jxl006_integer_cast_and_stop_gradient_sever():
    x = jnp.ones((2,), jnp.float32)

    def int_cast(x):
        return jnp.sum(x.astype(jnp.int32).astype(jnp.float32))

    def stopped(x):
        return jnp.sum(jax.lax.stop_gradient(x) * 3.0)

    for fn in (int_cast, stopped):
        found = lint_manifest(
            _surrogate_manifest(
                lambda fn=fn: [TraceEntry("loss", fn, (x,),
                                          kernel=False, grad_wrt=(0,))]
            )
        )
        assert "JXL006" in _codes(found), fn.__name__


def test_jxl006_scan_carry_feedback_path_is_live():
    """Regression for the fixed-point liveness: an operand whose only
    gradient route enters through a scan CARRY on iteration k>0 (the
    fluid cap→util→lfrac→lg chain) must count as live."""
    x = jnp.ones((3,), jnp.float32)

    def through_carry(x):
        def body(c, _):
            lf, acc = c
            # acc only sees x via the PREVIOUS iteration's lf
            return (lf + x, acc + jnp.sum(lf)), None

        (lf, acc), _ = jax.lax.scan(
            body, (jnp.zeros((3,), jnp.float32), jnp.float32(0.0)),
            None, length=3,
        )
        return acc

    assert "JXL006" not in _codes(
        lint_manifest(
            _surrogate_manifest(
                lambda: [TraceEntry("loss", through_carry, (x,),
                                    kernel=False, grad_wrt=(0,))]
            )
        )
    )


def test_jxl006_only_audits_surrogate_variants():
    """The same severed trace on a plain (non-surrogate) variant is
    out of scope — legacy engines quantize by design."""
    x = jnp.ones((3,), jnp.float32)

    def severed(x):
        return jnp.sum(jnp.round(x))

    assert "JXL006" not in _codes(
        lint_manifest(
            _manifest(
                lambda: [TraceEntry("loss", severed, (x,),
                                    kernel=False, grad_wrt=(0,))]
            )
        )
    )


def test_diff_manifest_is_clean_and_its_flips_hold():
    """The real diff-subsystem manifest: every exposed operand keeps a
    live gradient path (JXL006), the surrogate/loss flips are honest
    cache-key components (JXL004), the traces carry no stray f64
    (JXL002), its sparse sites are all audited (JXL008) and its scale
    axis stays linear (JXL007) — the ratchet stays ZERO."""
    from tpudes.diff import as_grad

    found = lint_manifest(as_grad.trace_manifest())
    assert found == [], [f.message for f in found]


# --- JXL007 scale growth (ISSUE-16 tentpole) --------------------------------


def _axis_manifest(build, **axkw):
    """A one-entry manifest whose entry declares one scale axis over
    ``build`` (value -> TraceEntry)."""

    def entries():
        return [
            dataclasses.replace(
                build(4), scale_axes=(ScaleAxis("n", build, **axkw),)
            )
        ]

    return _manifest(entries)


def _quad_entry(v):
    # the planted defect: an outer product materializes an O(n^2)
    # buffer while in/out stay O(n)
    return TraceEntry(
        "step", lambda x: jnp.outer(x, x).sum(),
        (jnp.ones(int(v), jnp.float32),),
    )


def _lin_entry(v):
    return TraceEntry(
        "step", lambda x: (x * 2.0).sum(),
        (jnp.ones(int(v), jnp.float32),),
    )


def test_jxl007_quadratic_axis_fires_and_linear_is_clean():
    found = lint_manifest(_axis_manifest(_quad_entry, points=(2, 8)))
    hits = [f for f in found if f.code == "JXL007"]
    assert len(hits) == 1, found
    assert "exceeds budget" in hits[0].message
    assert "widest buffer 2.00" in hits[0].message
    assert "JXL007" not in _codes(
        lint_manifest(_axis_manifest(_lin_entry, points=(2, 8)))
    )


def test_jxl007_declared_budget_silences_known_superlinear():
    # the bss n_sta pattern: O(n^2) pairwise geometry is the model's
    # contract — declaring mem_budget=2.0 makes the fit an assertion,
    # not a finding
    assert "JXL007" not in _codes(
        lint_manifest(
            _axis_manifest(_quad_entry, points=(2, 8), mem_budget=2.0)
        )
    )


def test_jxl007_dead_axis_declaration_fires():
    def dead(v):  # ignores v: the manifest claims a scaling it lacks
        return _lin_entry(4)

    found = lint_manifest(_axis_manifest(dead, points=(2, 8)))
    assert any(
        f.code == "JXL007" and "dead axis" in f.message for f in found
    ), found


def test_jxl007_single_point_axis_cannot_fit():
    found = lint_manifest(_axis_manifest(_lin_entry, points=(4,)))
    assert any(
        f.code == "JXL007" and "fewer than 2 points" in f.message
        for f in found
    ), found


# --- JXL008 sparse-site audit (ISSUE-16 tentpole) ---------------------------


def _take_entries():
    x = jnp.arange(8, dtype=jnp.float32)
    idx = jnp.asarray([3, 1], jnp.int32)
    return [TraceEntry("step", lambda v, i: v[i], (x, idx))]


def _synth_site(**over):
    from tpudes.analysis.jaxpr.sparse_registry import SparseSite

    kw = dict(
        site="synth.window", engine="synth", entry="*/step",
        primitive="gather", mode="promise_in_bounds",
        provenance=("operand",),
    )
    kw.update(over)
    return SparseSite(**kw)


def test_jxl008_unregistered_gather_fires():
    found = lint_manifest(_manifest(_take_entries))
    hits = [f for f in found if f.code == "JXL008"]
    assert len(hits) == 1, found
    assert "unaudited sparse site" in hits[0].message
    assert "sparse_registry" in hits[0].message


def test_jxl008_registered_contract_passes(monkeypatch):
    from tpudes.analysis.jaxpr import sparse_registry as SR

    monkeypatch.setattr(
        SR, "SPARSE_SITES", SR.SPARSE_SITES + (_synth_site(),)
    )
    assert "JXL008" not in _codes(lint_manifest(_manifest(_take_entries)))


@pytest.mark.parametrize(
    "over, fragment",
    [
        ({"mode": "clip"}, "mode"),
        ({"provenance": ("iota",)}, "provenance"),
    ],
)
def test_jxl008_contradicted_contract_fires(monkeypatch, over, fragment):
    """A registered site whose declared mode/provenance the jaxpr does
    not uphold is a finding, not a free pass — the contract is
    machine-checked, never trusted."""
    from tpudes.analysis.jaxpr import sparse_registry as SR

    monkeypatch.setattr(
        SR, "SPARSE_SITES", SR.SPARSE_SITES + (_synth_site(**over),)
    )
    found = lint_manifest(_manifest(_take_entries))
    hits = [f for f in found if f.code == "JXL008"]
    assert len(hits) == 1, found
    assert "contract contradicted" in hits[0].message
    assert fragment in hits[0].message


def test_jxl001_gather_ban_relaxed_by_verified_contract(monkeypatch):
    """The ISSUE-16 relaxation: under no_gather, a gather with a
    VERIFIED sparse_registry contract passes JXL001 (the audit
    replaces the blanket ban); an unregistered one still fires both."""
    from tpudes.analysis.jaxpr import sparse_registry as SR

    found = lint_manifest(_manifest(_take_entries, no_gather=True))
    assert "JXL001" in _codes(found) and "JXL008" in _codes(found)

    monkeypatch.setattr(
        SR, "SPARSE_SITES", SR.SPARSE_SITES + (_synth_site(),)
    )
    clean = lint_manifest(_manifest(_take_entries, no_gather=True))
    assert "JXL001" not in _codes(clean), clean
    assert "JXL008" not in _codes(clean), clean


def test_lte_serving_term_gather_is_audited():
    """ISSUE acceptance: the LTE serving-term gather is a REGISTERED
    allowlist entry whose contract (fill_or_drop mode, operand-rooted
    indices) the traced jaxpr upholds."""
    from tpudes.analysis.jaxpr import sparse_registry as SR
    from tpudes.analysis.jaxpr.trace import trace_entry
    from tpudes.parallel import lte_sm

    man = lte_sm.trace_manifest()
    variant = next(v for v in man.variants() if v.name == "traffic")
    entry = next(
        e for e in variant.build() if e.name == "traffic_advance"
    )
    records = SR.audit_entry(
        man.engine, f"{variant.name}/{entry.name}", trace_entry(entry)
    )
    assert records, "the serving-term gathers must be visible"
    assert all(r["ok"] for r in records), records
    sites = {r["site"] for r in records}
    assert "lte_sm.serving_term" in sites
    serving = [r for r in records if r["site"] == "lte_sm.serving_term"]
    assert all(r["mode"] == "fill_or_drop" for r in serving)
    assert all(r["kinds"] == ["operand"] for r in serving)


# --- cost model: peak-live / widest-buffer / FLOP accounting ----------------


def _cost():
    from tpudes.analysis.jaxpr import cost

    return cost


def test_buffer_accounting_pinned_on_tiny_jaxprs():
    cost = _cost()
    x = jnp.ones(4, jnp.float32)

    cj = jax.make_jaxpr(lambda v: (v * 2.0).sum())(x)
    assert cost.total_buffer_bytes(cj) == 36  # in 16 + mul 16 + sum 4
    assert cost.peak_live_bytes(cj) == 36  # nothing dies before the sum
    assert cost._jaxpr_flops(cj.jaxpr) == 8.0  # 4 mul + 4-element sum

    def chain(v):
        a = v * 2.0
        b = a + 1.0
        return b * 3.0

    cj = jax.make_jaxpr(chain)(x)
    assert cost.total_buffer_bytes(cj) == 64
    # liveness: `a` dies when `b` is born, so at most two 16 B
    # intermediates coexist on top of the held input
    assert cost.peak_live_bytes(cj) == 48


def test_widest_buffer_sees_the_quadratic_intermediate():
    cost = _cost()
    cj = jax.make_jaxpr(lambda v: jnp.outer(v, v))(
        jnp.ones(4, jnp.float32)
    )
    assert cost.widest_buffer_bytes(cj) == 64  # the 4x4 f32 table
    for n, widest in ((2, 16), (8, 256)):
        cj = jax.make_jaxpr(lambda v: jnp.outer(v, v).sum())(
            jnp.ones(n, jnp.float32)
        )
        assert cost.widest_buffer_bytes(cj) == widest  # exact n^2 * 4


def test_scan_body_costs_scale_with_length():
    cost = _cost()

    def fn(v):
        def body(c, _):
            return c * 2.0, c.sum()

        _, ys = jax.lax.scan(body, v, None, length=8)
        return ys

    cj = jax.make_jaxpr(fn)(jnp.ones(4, jnp.float32))
    assert cost.total_buffer_bytes(cj) == 84
    assert cost.peak_live_bytes(cj) == 84
    assert cost._jaxpr_flops(cj.jaxpr) == 64.0  # (4 mul + 4 sum) x 8


def test_fit_and_projection_are_exact_on_power_laws():
    cost = _cost()
    assert cost.fit_exponent([2, 4, 8], [4, 16, 64]) == pytest.approx(2.0)
    assert cost.fit_exponent([2, 8], [6, 24]) == pytest.approx(1.0)
    # projection anchors at the largest measured point
    assert cost.project_bytes([2, 4], [8, 32], 2.0, 8) == pytest.approx(128.0)


def test_peak_live_upper_bounds_xla_temp_allocation():
    """Cross-check against the HLO machinery the LTE kernel tests use:
    the abstract liveness walk assumes zero fusion, so it must never
    report LESS than what XLA actually allocates for temps."""
    cost = _cost()

    def fn(x):
        a = jnp.sin(x)
        b = a * x
        return b.sum()

    x = jnp.ones((256,), jnp.float32)
    compiled = jax.jit(fn).lower(x).compile()
    analysis = compiled.memory_analysis()
    if analysis is None:  # pragma: no cover - backend-dependent
        return
    cj = jax.make_jaxpr(fn)(x)
    assert cost.peak_live_bytes(cj) >= analysis.temp_size_in_bytes


def test_wired_scale_report_projects_the_csr_worklist():
    """ISSUE acceptance: the --cost report fits the wired dense
    one-hot step kernel at >= 2.0 in the joint (links, packets) axis
    and projects its bytes at 1e5/1e6 nodes — the ROADMAP item-2
    worklist."""
    from tpudes.analysis.jaxpr.cost import scale_report
    from tpudes.parallel import wired

    # restrict the manifest to the joint axis: the n_links/n_flows
    # marginals are already fitted (and held linear) by the lint in
    # test_real_manifest_lints_clean_modulo_baseline[wired]
    man = wired.trace_manifest()
    base = man.variants()[0]
    entries = [
        dataclasses.replace(
            e,
            scale_axes=tuple(
                a for a in e.scale_axes if a.name == "n_nodes"
            ),
        )
        for e in base.build()
    ]
    slim = dataclasses.replace(
        man, variants=lambda: [TraceVariant("base", lambda: entries)]
    )
    rep = scale_report(manifests=[(slim, 0)])
    assert rep["worklist"] == ["wired/advance:n_nodes"]
    (quad,) = [r for r in rep["entries"] if r["axis"] == "n_nodes"]
    assert quad["mem_exponent"] >= 1.99
    assert quad["over_budget"] and not quad["dead"]
    proj = quad["projected"]
    assert set(proj) == {"1e5_nodes", "1e6_nodes"}
    assert proj["1e6_nodes"]["bytes"] > proj["1e5_nodes"]["bytes"] > 0
    assert proj["1e6_nodes"]["human"].endswith("iB")
