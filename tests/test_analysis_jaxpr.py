"""Trace-aware analysis (tpudes.analysis.jaxpr): planted-defect
fixtures for JXL001–JXL005 in both directions, the wired no-gather
acceptance pair, cache-key hygiene on the real engines, and the
dead-key fix regressions.

Fixture manifests run through the exact production rule code
(lint_manifest), so a rule that stops firing on its planted defect
fails here before it silently stops gating the engines.
"""

import dataclasses

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tpudes.analysis.jaxpr import (  # noqa: E402
    FlipSpec,
    TraceEntry,
    TraceManifest,
    TraceVariant,
    lint_manifest,
)

SYNTH = "tpudes/parallel/synthetic.py"


def _manifest(entries_fn, flips=None, **kw):
    return TraceManifest(
        engine="synth",
        path=SYNTH,
        variants=lambda: [TraceVariant("base", entries_fn)],
        flips=flips,
        **kw,
    )


def _codes(findings):
    return [f.code for f in findings]


# --- JXL001 forbidden primitives -------------------------------------------


def test_jxl001_gather_fires_only_under_no_gather_contract():
    x = jnp.arange(8, dtype=jnp.float32)
    idx = jnp.asarray([3, 1], jnp.int32)

    def kernel(v):
        return jnp.take(v, idx)

    entries = lambda: [TraceEntry("step", kernel, (x,))]  # noqa: E731
    armed = lint_manifest(_manifest(entries, no_gather=True))
    assert any(
        f.code == "JXL001" and "gather" in f.message for f in armed
    ), armed
    # same trace without the contract: no finding
    assert "JXL001" not in _codes(lint_manifest(_manifest(entries)))


def test_jxl001_gather_ban_spares_init_entries():
    x = jnp.arange(8, dtype=jnp.float32)
    idx = jnp.asarray([3, 1], jnp.int32)
    entries = lambda: [  # noqa: E731
        TraceEntry("init", lambda: jnp.take(x, idx), (), kernel=False)
    ]
    assert "JXL001" not in _codes(
        lint_manifest(_manifest(entries, no_gather=True))
    )


def test_jxl001_callback_forbidden_everywhere():
    def kernel(v):
        return jax.pure_callback(
            lambda a: np.asarray(a), jax.ShapeDtypeStruct((3,), np.float32), v
        )

    entries = lambda: [  # noqa: E731
        TraceEntry("step", kernel, (jnp.zeros(3, jnp.float32),))
    ]
    found = lint_manifest(_manifest(entries))
    assert any(
        f.code == "JXL001" and "callback" in f.message for f in found
    ), found


def test_planted_gather_in_wired_step_fires_and_real_kernel_is_clean():
    """ISSUE acceptance: a jnp.take smuggled into the wired step body
    must produce the JXL001 finding, and today's kernels must not."""
    from tpudes.parallel import wired

    prog = wired._trace_prog()
    init_state, advance = wired.build_wired_advance(prog, wired._TRACE_R)
    carry = init_state(jax.random.PRNGKey(0))
    P = int(carry["hop"].shape[1])
    no_ing = jnp.full((wired._TRACE_R, P), -1, jnp.int32)
    cols = jnp.arange(P, dtype=jnp.int32)

    def bad_advance(c, ih, ir, t_grant):
        c, metrics = advance(c, ih, ir, t_grant)
        # the smuggled dynamic lookup: per-packet delivery slots read
        # back through a gather instead of the one-hot algebra
        c = dict(c, deliver=jnp.take(c["deliver"], cols, axis=1))
        return c, metrics

    planted = _manifest(
        lambda: [
            TraceEntry(
                "advance", bad_advance,
                (carry, no_ing, no_ing, jnp.int32(8)),
            )
        ],
        no_gather=True,
    )
    found = lint_manifest(planted)
    assert any(
        f.code == "JXL001" and "gather" in f.message for f in found
    ), found

    # the real manifest stays gather-free (its only expected findings
    # are the baselined JXL005 egress-buffer entries)
    real = [
        f for f in lint_manifest(wired.trace_manifest())
        if f.code == "JXL001"
    ]
    assert real == []


# --- JXL002 dtype discipline ------------------------------------------------


def test_jxl002_unpinned_f64_fires_and_pinned_is_clean():
    def leaky(x):
        return jnp.zeros(3) + x  # unpinned: f64 under ambient x64

    def pinned(x):
        return jnp.zeros(3, jnp.float32) + x

    x = jnp.ones(3, jnp.float32)
    found = lint_manifest(
        _manifest(lambda: [TraceEntry("step", leaky, (x,))])
    )
    assert any(
        f.code == "JXL002" and "float64" in f.message for f in found
    ), found
    assert "JXL002" not in _codes(
        lint_manifest(_manifest(lambda: [TraceEntry("step", pinned, (x,))]))
    )


def test_jxl002_bf16_accumulator_fires_and_f32_accumulator_is_clean():
    x = jnp.ones((4, 4), jnp.float32)

    def bad(v):
        lo = v.astype(jnp.bfloat16)
        return lo @ lo  # dot_general accumulating at bf16

    def good(v):
        lo = v.astype(jnp.bfloat16)
        return jnp.einsum(
            "ij,jk->ik", lo, lo, preferred_element_type=jnp.float32
        )

    def run(fn, bf16):
        man = TraceManifest(
            engine="synth", path=SYNTH,
            variants=lambda: [
                TraceVariant(
                    "bf16", lambda: [TraceEntry("step", fn, (x,))],
                    bf16=bf16,
                )
            ],
        )
        return lint_manifest(man)

    found = run(bad, True)
    assert any(
        f.code == "JXL002" and "bfloat16" in f.message for f in found
    ), found
    assert "JXL002" not in _codes(run(good, True))
    # the accumulator check only arms on bf16-tagged variants
    assert "JXL002" not in _codes(run(bad, False))


def test_jxl002_x64_trace_failure_is_a_finding():
    def fragile(x):
        def body(c):
            # the loop carry widens: i32 in, sum-promoted i64 out
            return (c * jnp.ones((2,), jnp.int32)).sum()

        return jax.lax.while_loop(lambda c: c < x, body, jnp.int32(0))

    found = lint_manifest(
        _manifest(
            lambda: [TraceEntry("step", fragile, (jnp.int32(5),))]
        )
    )
    assert any(
        f.code == "JXL002" and "fails under ambient x64" in f.message
        for f in found
    ), found


# --- JXL003 baked-in constants ----------------------------------------------


def test_jxl003_large_const_fires_and_operand_form_is_clean():
    big = jnp.asarray(np.arange(4096, dtype=np.float32))  # 16 KiB

    def baked(x):
        return x + big

    def operand(x, table):
        return x + table

    x = jnp.ones(4096, jnp.float32)
    found = lint_manifest(
        _manifest(lambda: [TraceEntry("step", baked, (x,))])
    )
    assert any(
        f.code == "JXL003" and "baked constant" in f.message
        for f in found
    ), found
    assert "JXL003" not in _codes(
        lint_manifest(
            _manifest(lambda: [TraceEntry("step", operand, (x, big))])
        )
    )
    # raising the budget silences it (per-manifest knob)
    assert "JXL003" not in _codes(
        lint_manifest(
            _manifest(
                lambda: [TraceEntry("step", baked, (x,))],
                const_budget=1 << 20,
            )
        )
    )


# --- JXL004 cache-key hygiene ----------------------------------------------


def _affine(scale_val: float):
    scale = jnp.float32(scale_val)

    def fn(x):
        return x * scale

    return fn


def test_jxl004_dead_key_component_fires():
    x = jnp.ones(3, jnp.float32)
    entries = lambda v=1.0: [  # noqa: E731
        TraceEntry("step", _affine(v), (x,))
    ]
    man = _manifest(
        lambda: entries(),
        flips=lambda: {
            # key separates the flip, but the trace is identical
            "dead_field": FlipSpec(build=lambda: entries(), key_differs=True),
        },
    )
    found = lint_manifest(man)
    assert any(
        f.code == "JXL004" and "dead" in f.message for f in found
    ), found


def test_jxl004_live_component_and_honest_exclusion_are_clean():
    x = jnp.ones(3, jnp.float32)
    entries = lambda v: [TraceEntry("step", _affine(v), (x,))]  # noqa: E731
    man = _manifest(
        lambda: entries(1.0),
        flips=lambda: {
            "live_field": FlipSpec(
                build=lambda: entries(2.0), key_differs=True
            ),
            "excluded_field": FlipSpec(
                build=lambda: entries(1.0), key_differs=False
            ),
        },
    )
    assert "JXL004" not in _codes(lint_manifest(man))


def test_jxl004_missing_key_component_fires():
    x = jnp.ones(3, jnp.float32)
    entries = lambda v: [TraceEntry("step", _affine(v), (x,))]  # noqa: E731
    man = _manifest(
        lambda: entries(1.0),
        flips=lambda: {
            # flip changes the program but the key does not separate it
            "forgotten": FlipSpec(
                build=lambda: entries(2.0), key_differs=False
            ),
        },
    )
    found = lint_manifest(man)
    assert any(
        f.code == "JXL004" and "NOT a cache-key component" in f.message
        for f in found
    ), found


def test_jxl004_constant_burned_traced_operand_fires():
    x = jnp.ones(3, jnp.float32)
    burned_scale = jnp.float32(2.0)

    def burned(x, scale):
        return x * burned_scale  # ignores the declared operand

    def honest(x, scale):
        return x * scale

    def run(fn):
        return lint_manifest(
            _manifest(
                lambda: [
                    TraceEntry(
                        "step", fn, (x, jnp.float32(2.0)),
                        traced={"scale": 1},
                    )
                ]
            )
        )

    found = run(burned)
    assert any(
        f.code == "JXL004" and "'scale'" in f.message for f in found
    ), found
    assert "JXL004" not in _codes(run(honest))


# --- JXL005 donation audit ---------------------------------------------------


def test_jxl005_unused_donated_leaf_fires():
    def fn(carry, x):
        return dict(a=carry["a"] + x, b=jnp.zeros(3, jnp.float32))

    carry = dict(
        a=jnp.zeros(3, jnp.float32), b=jnp.ones(3, jnp.float32)
    )
    found = lint_manifest(
        _manifest(
            lambda: [
                TraceEntry(
                    "advance", fn, (carry, jnp.float32(1.0)),
                    donate=(0,), carry=(0,),
                )
            ]
        )
    )
    assert any(
        f.code == "JXL005" and "never consumed" in f.message
        for f in found
    ), found


def test_jxl005_undonated_carry_and_unaliasable_leaf_fire():
    def fn(carry, x):
        return carry + x

    args = (jnp.zeros(3, jnp.float32), jnp.float32(1.0))
    found = lint_manifest(
        _manifest(
            lambda: [TraceEntry("advance", fn, args, carry=(0,))]
        )
    )
    assert any(
        f.code == "JXL005" and "never donated" in f.message
        for f in found
    ), found

    def shrink(carry):
        return carry[:2]  # donated buffer has no same-shape output

    found = lint_manifest(
        _manifest(
            lambda: [
                TraceEntry(
                    "advance", shrink, (jnp.zeros(3, jnp.float32),),
                    donate=(0,),
                )
            ]
        )
    )
    assert any(
        f.code == "JXL005" and "cannot alias" in f.message
        for f in found
    ), found


def test_jxl005_proper_donated_carry_is_clean():
    def fn(carry, x):
        return carry + x

    assert "JXL005" not in _codes(
        lint_manifest(
            _manifest(
                lambda: [
                    TraceEntry(
                        "advance", fn,
                        (jnp.zeros(3, jnp.float32), jnp.float32(1.0)),
                        donate=(0,), carry=(0,),
                    )
                ]
            )
        )
    )


# --- real-surface checks -----------------------------------------------------


#: the four baselined-by-design findings (egress buffers are protocol-
#: overwritten at every window start; dropping them from the input
#: carry would break the carry-in == carry-out chunk-handoff shape)
_EXPECTED_REAL = {"JXL005"}


@pytest.mark.parametrize(
    "module",
    ["replicated", "lte_sm", "tcp_dumbbell", "as_flows", "wired",
     "hybrid"],
)
def test_real_manifest_lints_clean_modulo_baseline(module):
    import importlib

    mod = importlib.import_module(f"tpudes.parallel.{module}")
    found = lint_manifest(mod.trace_manifest())
    unexpected = [f for f in found if f.code not in _EXPECTED_REAL]
    assert unexpected == [], unexpected
    for f in found:
        assert "eg_" in f.message, f  # only the known egress entries


def test_wired_dead_key_fix_shares_one_runner():
    """Regression for the JXL004-found dead components: programs
    differing only in slot_s / link_owner must hit the SAME cached
    wired runner (they compile identical kernels)."""
    from tpudes.parallel.runtime import RUNTIME
    from tpudes.parallel.wired import run_wired, wired_chain

    prog = wired_chain(n_links=3, n_flows=2, n_slots=40)
    key = jax.random.PRNGKey(7)
    RUNTIME.clear("wired")
    base = run_wired(prog, key)
    misses = RUNTIME.misses
    twin = dataclasses.replace(
        prog, slot_s=0.5,
        link_owner=np.asarray([0, 1, 1], np.int32),
    )
    out = run_wired(twin, key)
    assert RUNTIME.misses == misses  # cache hit: no new runner
    np.testing.assert_array_equal(
        out["deliver_slot"], base["deliver_slot"]
    )


def test_dumbbell_red_knobs_out_of_fifo_key():
    """Regression: in fifo mode the RED parameters never reach the
    program — flipping them must reuse the cached runner."""
    from tpudes.parallel.runtime import RUNTIME
    from tpudes.parallel.tcp_dumbbell import (
        dumbbell_prog_key,
        run_tcp_dumbbell,
    )
    from tpudes.parallel.programs import toy_dumbbell_program

    prog = toy_dumbbell_program(n_flows=2, n_slots=30)
    twin = dataclasses.replace(prog, red_qw=0.5, red_max_p=0.9)
    assert dumbbell_prog_key(prog) == dumbbell_prog_key(twin)
    # ...while a RED-mode program still keys on them
    red = dataclasses.replace(prog, qdisc="red")
    red2 = dataclasses.replace(red, red_qw=0.5)
    assert dumbbell_prog_key(red) != dumbbell_prog_key(red2)

    key = jax.random.PRNGKey(3)
    RUNTIME.clear("dumbbell")
    base = run_tcp_dumbbell(prog, key, replicas=2)
    misses = RUNTIME.misses
    out = run_tcp_dumbbell(twin, key, replicas=2)
    assert RUNTIME.misses == misses
    np.testing.assert_array_equal(out["delivered"], base["delivered"])


# --- JXL006 grad hygiene (ISSUE-15) ----------------------------------------


def _surrogate_manifest(entries_fn):
    return TraceManifest(
        engine="synth",
        path=SYNTH,
        variants=lambda: [
            TraceVariant("base", entries_fn, surrogate=True)
        ],
    )


def test_jxl006_severed_gradient_fires_and_ste_is_clean():
    """A round() in the only path to the output kills the gradient —
    JXL006 fires; the straight-through annotation (tpudes.diff.ste)
    restores a soft path and is clean."""
    from tpudes.diff.surrogate import ste

    x = jnp.ones((3,), jnp.float32)

    def severed(x):
        return jnp.sum(jnp.round(x) * 2.0)

    def annotated(x):
        return jnp.sum(ste(jnp.round(x), x) * 2.0)

    found = lint_manifest(
        _surrogate_manifest(
            lambda: [TraceEntry("loss", severed, (x,), kernel=False,
                               grad_wrt=(0,))]
        )
    )
    assert "JXL006" in _codes(found)
    assert "straight-through" in found[0].message
    assert "JXL006" not in _codes(
        lint_manifest(
            _surrogate_manifest(
                lambda: [TraceEntry("loss", annotated, (x,),
                                    kernel=False, grad_wrt=(0,))]
            )
        )
    )


def test_jxl006_integer_cast_and_stop_gradient_sever():
    x = jnp.ones((2,), jnp.float32)

    def int_cast(x):
        return jnp.sum(x.astype(jnp.int32).astype(jnp.float32))

    def stopped(x):
        return jnp.sum(jax.lax.stop_gradient(x) * 3.0)

    for fn in (int_cast, stopped):
        found = lint_manifest(
            _surrogate_manifest(
                lambda fn=fn: [TraceEntry("loss", fn, (x,),
                                          kernel=False, grad_wrt=(0,))]
            )
        )
        assert "JXL006" in _codes(found), fn.__name__


def test_jxl006_scan_carry_feedback_path_is_live():
    """Regression for the fixed-point liveness: an operand whose only
    gradient route enters through a scan CARRY on iteration k>0 (the
    fluid cap→util→lfrac→lg chain) must count as live."""
    x = jnp.ones((3,), jnp.float32)

    def through_carry(x):
        def body(c, _):
            lf, acc = c
            # acc only sees x via the PREVIOUS iteration's lf
            return (lf + x, acc + jnp.sum(lf)), None

        (lf, acc), _ = jax.lax.scan(
            body, (jnp.zeros((3,), jnp.float32), jnp.float32(0.0)),
            None, length=3,
        )
        return acc

    assert "JXL006" not in _codes(
        lint_manifest(
            _surrogate_manifest(
                lambda: [TraceEntry("loss", through_carry, (x,),
                                    kernel=False, grad_wrt=(0,))]
            )
        )
    )


def test_jxl006_only_audits_surrogate_variants():
    """The same severed trace on a plain (non-surrogate) variant is
    out of scope — legacy engines quantize by design."""
    x = jnp.ones((3,), jnp.float32)

    def severed(x):
        return jnp.sum(jnp.round(x))

    assert "JXL006" not in _codes(
        lint_manifest(
            _manifest(
                lambda: [TraceEntry("loss", severed, (x,),
                                    kernel=False, grad_wrt=(0,))]
            )
        )
    )


def test_diff_manifest_is_clean_and_its_flips_hold():
    """The real diff-subsystem manifest: every exposed operand keeps a
    live gradient path (JXL006), the surrogate/loss flips are honest
    cache-key components (JXL004), and the traces carry no stray f64
    (JXL002) — the ratchet stays ZERO."""
    from tpudes.diff import as_grad

    found = lint_manifest(as_grad.trace_manifest())
    assert found == [], [f.message for f in found]
