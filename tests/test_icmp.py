"""ICMP + V4Ping tests — upstream src/internet/test/ipv4-icmp strategy:
echo round trip with analytic RTT, TTL-exceeded from a mid-path router,
unreachable generation."""

import pytest

from tpudes.core import Seconds, Simulator
from tpudes.helper.containers import NodeContainer
from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
from tpudes.helper.point_to_point import PointToPointHelper
from tpudes.models.internet.global_routing import Ipv4GlobalRoutingHelper
from tpudes.models.internet.icmp import IcmpL4Protocol, Icmpv4Header, V4Ping
from tpudes.network.address import Ipv4Address


def _chain(n=3, rate="10Mbps", delay="2ms"):
    nodes = NodeContainer()
    nodes.Create(n)
    stack = InternetStackHelper()
    stack.SetRoutingHelper(Ipv4GlobalRoutingHelper())
    stack.Install(nodes)
    p2p = PointToPointHelper()
    p2p.SetDeviceAttribute("DataRate", rate)
    p2p.SetChannelAttribute("Delay", delay)
    addr = Ipv4AddressHelper("10.1.0.0", "255.255.255.0")
    last = None
    for i in range(n - 1):
        devs = p2p.Install(nodes.Get(i), nodes.Get(i + 1))
        last = addr.Assign(devs)
        addr.NewNetwork()
    Ipv4GlobalRoutingHelper.PopulateRoutingTables()
    return nodes, last


def test_ping_round_trip_rtt_is_analytic():
    nodes, last = _chain(3)
    ping = V4Ping(
        Remote=str(last.GetAddress(1)), Interval=Seconds(0.1), Count=4
    )
    nodes.Get(0).AddApplication(ping)
    ping.SetStartTime(Seconds(0.1))
    Simulator.Stop(Seconds(1.0))
    Simulator.Run()
    assert ping.sent == 4 and ping.received == 4
    # 2 hops × 2 ms × 2 directions + serialization (84B @ 10 Mbps ×4)
    for rtt in ping.rtts:
        assert rtt == pytest.approx(0.008, rel=0.1)


def test_ttl_exceeded_comes_back_from_midpath_router():
    nodes, last = _chain(4)
    errors = []
    icmp0 = nodes.Get(0).GetObject(IcmpL4Protocol)
    icmp0.register_error_listener(
        lambda t, c, inner, src: errors.append((t, c, str(src)))
    )
    # craft a 1-TTL packet toward the far end
    from tpudes.models.internet.ipv4 import Ipv4L3Protocol
    from tpudes.network.packet import Packet

    ipv4 = nodes.Get(0).GetObject(Ipv4L3Protocol)
    ipv4.default_ttl = 1
    icmp0.SendEcho(Ipv4Address(str(last.GetAddress(1))), 99, 0)
    Simulator.Stop(Seconds(0.5))
    Simulator.Run()
    assert errors, "TTL-exceeded must return to the sender"
    t, c, src = errors[0]
    assert t == Icmpv4Header.TIME_EXCEEDED
    # the first router (node 1) generated it
    assert src.startswith("10.1.0.")


def test_unreachable_destination_generates_icmp_error():
    nodes, last = _chain(3)
    errors = []
    icmp0 = nodes.Get(0).GetObject(IcmpL4Protocol)
    icmp0.register_error_listener(
        lambda t, c, inner, src: errors.append((t, c))
    )
    # static-route a bogus prefix into the chain so the middle router
    # has no route for it
    from tpudes.models.internet.global_routing import GlobalRouteManager

    mgr = GlobalRouteManager.Get()
    mgr.addr_to_node[Ipv4Address("10.99.0.1").addr] = 2  # resolvable at n0
    icmp0.SendEcho(Ipv4Address("10.99.0.1"), 77, 0)
    Simulator.Stop(Seconds(0.5))
    Simulator.Run()
    assert (Icmpv4Header.DEST_UNREACH, Icmpv4Header.NET_UNREACHABLE) in errors


def test_ping_counts_stop_at_count():
    nodes, last = _chain(2)
    ping = V4Ping(
        Remote=str(last.GetAddress(1)), Interval=Seconds(0.05), Count=3
    )
    nodes.Get(0).AddApplication(ping)
    ping.SetStartTime(Seconds(0.0))
    Simulator.Stop(Seconds(1.0))
    Simulator.Run()
    assert ping.sent == 3 and ping.received == 3